"""mgr modules: balancer (pg_upmap_items optimizer) + pg_autoscaler.

Mirrors the decision logic of src/pybind/mgr/balancer (upmap mode via
OSDMap::calc_pg_upmaps) and src/pybind/mgr/pg_autoscaler/module.py
(:270-330)."""
import numpy as np
import pytest

from ceph_tpu.mgr import (autoscale_recommendations, calc_pg_upmaps,
                          calc_weight_set, nearest_power_of_two,
                          osd_deviation)
from ceph_tpu.osdmap import apply_incremental

from test_osdmap import build_cluster


class TestCrushCompatBalancer:
    """The weight-set mode (reference: balancer module do_crush_compat
    writing CrushWrapper choose_args) — possible now that the bulk mapper
    honors choose_args (VERDICT r3 #9)."""

    def test_weight_set_reduces_deviation(self):
        m = build_cluster(seed=7)
        m.pools[1].pg_num = 128
        m.pools[1].pgp_num = 128
        counts0, targets0, _ = osd_deviation(m, [1])
        before = float(np.sqrt(((counts0 - targets0) ** 2).mean()))
        ws = calc_weight_set(m, max_iterations=12, pools=[1])
        assert ws is not None, "crush-compat found no improvement"
        m.crush.choose_args[-1] = ws
        counts1, targets1, _ = osd_deviation(m, [1])
        after = float(np.sqrt(((counts1 - targets1) ** 2).mean()))
        assert after < before, f"rms deviation {before} -> {after}"

    def test_weight_set_keeps_placements_valid(self):
        from ceph_tpu.osdmap import PG
        m = build_cluster(seed=8)
        m.pools[2].pg_num = 64
        m.pools[2].pgp_num = 64
        ws = calc_weight_set(m, max_iterations=8, pools=[2])
        if ws is None:
            pytest.skip("already balanced")
        m.crush.choose_args[-1] = ws
        for ps in range(64):
            up, _, acting, _ = m.pg_to_up_acting_osds(PG(2, ps))
            real = [o for o in acting if o != 0x7FFFFFFF]
            assert len(real) == len(set(real)), f"pg {ps}: duplicate osd"

    def test_bulk_and_scalar_agree_under_weight_set(self):
        """The installed compat weight-set flows through BOTH mapping
        paths identically (the bulk mapper no longer falls back to the
        scalar interpreter for choose_args maps)."""
        from ceph_tpu.osdmap import PG
        from ceph_tpu.osdmap.bulk import BulkPGMapper
        m = build_cluster(seed=13)
        ws = calc_weight_set(m, max_iterations=6)
        if ws is None:
            pytest.skip("already balanced")
        m.crush.choose_args[-1] = ws
        pm = BulkPGMapper(m).map_pool(1)
        for ps in range(m.pools[1].pg_num):
            up, _, _, _ = m.pg_to_up_acting_osds(PG(1, ps))
            want = list(up) + [0x7FFFFFFF] * (pm.up.shape[1] - len(up))
            assert list(pm.up[ps]) == want[:pm.up.shape[1]], f"ps={ps}"


class TestBalancer:
    def test_balancing_reduces_deviation(self):
        m = build_cluster(seed=7)
        m.pools[1].pg_num = 128          # more PGs: more room to balance
        m.pools[1].pgp_num = 128
        counts0, targets0, _ = osd_deviation(m, [1])
        before = float(np.abs(counts0 - targets0).max())
        inc = calc_pg_upmaps(m, max_iterations=48, max_deviation=1.0,
                             pools=[1])
        assert inc.new_pg_upmap_items, "balancer proposed nothing"
        m2 = apply_incremental(m, inc)
        counts1, targets1, _ = osd_deviation(m2, [1])
        after = float(np.abs(counts1 - targets1).max())
        assert after < before, f"deviation {before} -> {after}"

    def test_upmaps_keep_placements_valid(self):
        m = build_cluster(seed=8)
        m.pools[2].pg_num = 64
        m.pools[2].pgp_num = 64
        inc = calc_pg_upmaps(m, max_iterations=24, pools=[2])
        m2 = apply_incremental(m, inc)
        for ps in range(64):
            from ceph_tpu.osdmap import PG
            up, _, acting, _ = m2.pg_to_up_acting_osds(PG(2, ps))
            real = [o for o in acting if o != 0x7FFFFFFF]
            assert len(real) == len(set(real)), f"pg {ps}: duplicate osd"

    def test_already_balanced_proposes_nothing(self):
        m = build_cluster(seed=9)
        inc = calc_pg_upmaps(m, max_deviation=10_000.0)
        assert not inc.new_pg_upmap_items


    def test_existing_items_rewritten_not_dropped(self):
        """A pre-existing (f -> over) item must be REWRITTEN to (f, under),
        not dropped (review regression: dropping resurrects the raw osd
        and the appended item becomes a no-op)."""
        from ceph_tpu.osdmap import PG
        m = build_cluster(seed=12)
        m.pools[1].pg_num = 64
        m.pools[1].pgp_num = 64
        # seed an existing upmap: move raw[0] of pg 1.1 somewhere else
        raw, _ = m.pg_to_raw_osds(PG(1, 1))
        other = next(o for o in range(m.max_osd) if o not in raw)
        m.pg_upmap_items[PG(1, 1)] = [(raw[0], other)]
        up0, _ = m.pg_to_raw_up(PG(1, 1))
        assert other in up0
        # force the balancer to move `other` off this pg
        inc = calc_pg_upmaps(m, max_iterations=48, max_deviation=0.0,
                             pools=[1])
        m2 = apply_incremental(m, inc)
        for pg, items in inc.new_pg_upmap_items.items():
            up, _ = m2.pg_to_raw_up(pg)
            real = [o for o in up if o != 0x7FFFFFFF]
            assert len(real) == len(set(real))
            for f, t in items:
                assert t in real or f not in real, (
                    f"{pg}: item ({f},{t}) is a no-op")

    def test_moves_verified_against_full_chain(self):
        """Every proposed item, applied, must actually change the up set
        it claims to change."""
        from ceph_tpu.osdmap import PG
        m = build_cluster(seed=13)
        m.pools[1].pg_num = 128
        m.pools[1].pgp_num = 128
        inc = calc_pg_upmaps(m, max_iterations=32, pools=[1])
        m2 = apply_incremental(m, inc)
        for pg, items in inc.new_pg_upmap_items.items():
            up, _ = m2.pg_to_raw_up(pg)
            real = [o for o in up if o != 0x7FFFFFFF]
            for f, t in items:
                assert f not in real, f"{pg}: {f} still mapped"


class TestAutoscaler:
    def test_nearest_power_of_two(self):
        assert nearest_power_of_two(1) == 1
        assert nearest_power_of_two(3) == 4       # 3 is nearer 4 than 2
        assert nearest_power_of_two(5) == 4
        assert nearest_power_of_two(6.1) == 8
        assert nearest_power_of_two(1500) == 1024

    def test_recommendations_shape_and_adjustment(self):
        m = build_cluster()
        cap = 100 << 30
        # pool 1 (replicated size 3, pg_num 64) nearly empty -> shrink
        # pool 2 (EC 4+2, pg_num 48) holding ~60% of capacity -> grow
        recs = {r["pool_id"]: r for r in autoscale_recommendations(
            m, {1: 1 << 20, 2: 40 << 30}, cap,
            options={2: {"k": 4}})}
        assert recs[2]["raw_used_rate"] == pytest.approx(6 / 4)
        assert recs[1]["pg_num_final"] >= 4
        assert recs[1]["would_adjust"]            # 64 -> tiny
        assert recs[2]["pg_num_final"] > 48       # grow
        ideal = recs[2]["pg_num_ideal"]
        # allowance math: 27 osds * 100 pgs * ratio / rate
        ratio = 40 * 1.5 / 100
        assert ideal == int(27 * 100 * ratio / 1.5)

    def test_target_size_ratio_dominates_usage(self):
        m = build_cluster()
        recs = autoscale_recommendations(
            m, {1: 0, 2: 0}, 100 << 30,
            options={1: {"target_size_ratio": 0.5}})
        r1 = next(r for r in recs if r["pool_id"] == 1)
        assert r1["final_ratio"] == 0.5
        assert r1["pg_num_final"] > 64
