"""Shared-store OSD topology (r4 VERDICT missing #3): each OSD daemon owns
ONE ObjectStore hosting every PG shard on that OSD as collections, and one
bus endpoint on ONE cluster-wide bus (reference: src/osd/OSD.cc:3971
load_pgs over a single ObjectStore; one messenger per OSD)."""
import numpy as np
import pytest

from ceph_tpu.backend.collection import Collection, collection_names
from ceph_tpu.cluster import MiniCluster
from ceph_tpu.common import Context
from ceph_tpu.osd.osd_ops import ObjectOperation


def _data(n, seed):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def test_all_pg_shards_share_one_store_per_osd():
    c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512)
    c.create_ec_pool("a", {"k": "2", "m": "1", "device": "numpy"}, pg_num=8)
    c.create_replicated_pool("b", size=3, pg_num=8)
    per_osd_bases = {}
    n_colls = 0
    for pool in c.pools.values():
        for g in pool["pgs"].values():
            for shard, h in g.bus.handlers.items():
                st = h.store if hasattr(h, "store") else None
                if st is None:
                    continue
                assert isinstance(st, Collection)
                n_colls += 1
                base = per_osd_bases.setdefault(shard, st.base)
                assert st.base is base, \
                    f"osd {shard} has more than one backing store"
                assert st.base is c.osds[shard].store
    assert n_colls >= 30       # 16 PGs x 3 shards spread over 6 OSDs
    c.shutdown()


def test_one_cluster_bus_one_endpoint_per_osd():
    from ceph_tpu.backend.messages import OSDEndpoint, PGChannel
    c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512)
    c.create_ec_pool("a", {"k": "2", "m": "1", "device": "numpy"}, pg_num=8)
    assert all(isinstance(ep, OSDEndpoint)
               for ep in c.bus.handlers.values())
    g = next(iter(c.pools[1]["pgs"].values()))
    assert isinstance(g.bus, PGChannel)
    # every PG channel shares the one cluster bus
    assert all(g2.bus.bus is c.bus
               for p in c.pools.values() for g2 in p["pgs"].values())
    c.shutdown()


@pytest.mark.parametrize("pool_type", ["ec", "rep"])
def test_kill_osd_hosting_many_pgs_then_revive(pool_type):
    """Kill ONE OSD serving many PGs (primary for several), write through
    the degradation, revive, and verify everything — the cross-PG blast
    radius of a real OSD death on the shared bus."""
    cct = Context(overrides={"mon_osd_down_out_interval": 10_000})
    c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512, cct=cct)
    if pool_type == "ec":
        pid = c.create_ec_pool("p", {"k": "2", "m": "1", "device": "numpy"},
                               pg_num=16)
    else:
        pid = c.create_replicated_pool("p", size=3, pg_num=16)
    oids = [f"o{i}" for i in range(24)]
    model = {}
    for i, oid in enumerate(oids):
        model[oid] = _data(900 + 31 * i, i)
        c.operate(pid, oid, ObjectOperation().write_full(model[oid])
                  .setxattr("tag", oid.encode()))
    # the busiest OSD hosts shards of many PGs
    victim = max(range(6), key=lambda o: sum(
        o in g.acting for g in c.pools[pid]["pgs"].values()))
    hosted = sum(victim in g.acting
                 for g in c.pools[pid]["pgs"].values())
    assert hosted >= 8
    c.bus.mark_down(victim)
    from ceph_tpu.cluster import BlockedWriteError
    for i, oid in enumerate(oids):            # overwrite while degraded
        new = _data(700 + 13 * i, 100 + i)
        try:
            c.operate(pid, oid, ObjectOperation().write_full(new)
                      .setxattr("tag", oid.encode()))
            model[oid] = new
        except BlockedWriteError:
            c.bus.mark_up(victim)
            c.bus.deliver_all()
            model[oid] = new
            c.bus.mark_down(victim)
    c.bus.mark_up(victim)
    c.bus.deliver_all()
    for oid in oids:
        r = c.operate(pid, oid, ObjectOperation().read(0, 0)
                      .getxattr("tag"))
        assert r.outdata(0)[:len(model[oid])] == model[oid], oid
        assert r.outdata(1) == oid.encode()
    assert c.scrub_pool(pid) == {}
    c.shutdown()


def test_durable_restart_recovers_every_pg_from_one_store(tmp_path):
    """The VERDICT's done-criterion: a durable cluster whose OSD stores
    each hold MANY PG collections reopens from ONE FileStore per OSD and
    every PG serves its data."""
    c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                    data_dir=tmp_path)
    pid = c.create_ec_pool("p", {"k": "2", "m": "1", "device": "numpy"},
                           pg_num=16)
    rid = c.create_replicated_pool("r", size=3, pg_num=8)
    model = {}
    for i in range(24):
        oid = f"d{i}"
        model[oid] = _data(800 + 17 * i, i)
        c.put(pid, oid, model[oid])
        c.put(rid, f"r{oid}", model[oid])
    c.shutdown()
    # ONE store dir per OSD, holding many PG collections
    for o in range(6):
        assert (tmp_path / f"osd.{o}" / "store").exists()
        assert not list((tmp_path / f"osd.{o}").glob("pg.*"))
    c2 = MiniCluster.load(tmp_path)
    # collection discovery sees every hosted PG in the one store
    colls = collection_names(c2.osds[0].store)
    assert sum(1 for cn in colls if cn.startswith("pg.")) >= 6
    for oid, want in model.items():
        assert c2.get(pid, oid, len(want)) == want
        assert c2.get(rid, f"r{oid}", len(want)) == want
    assert c2.scrub_pool(pid) == {}
    c2.shutdown()


def test_remapped_pg_stays_on_shared_bus_and_old_collection_dies():
    """Backfill to a new acting set must keep the PG on the cluster bus
    (regression: the replacement group silently got a private bus, so
    OSD-wide deaths stopped applying to remapped PGs) and must destroy
    the outgoing incarnation's collection (regression: stale pg logs
    leaked in the shared store and haunted later incarnations)."""
    from ceph_tpu.backend.messages import PGChannel
    cct = Context(overrides={"mon_osd_down_out_interval": 60})
    c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512, cct=cct)
    pid = c.create_ec_pool("p", {"k": "2", "m": "1", "device": "numpy"},
                           pg_num=8)
    mon = c.attach_monitor()
    model = {}
    for i in range(12):
        model[f"o{i}"] = _data(700 + i, i)
        c.put(pid, f"o{i}", model[f"o{i}"])
    # fail a non-primary OSD through the mon: auto-out remaps PGs off it
    primaries = {g.backend.whoami for g in c.pools[pid]["pgs"].values()}
    victim = next(o for o in range(8) if o not in primaries)
    pre_acting = {ps: list(g.acting)
                  for ps, g in c.pools[pid]["pgs"].items()}
    for r in [o for o in range(8) if o != victim][:4]:
        mon.prepare_failure(victim, r, 0.0, 25.0)
    mon.propose_pending(25.0)
    mon.tick(5000.0)                      # auto-out -> remap + backfill
    assert mon.osdmap.is_out(victim)
    remapped = [ps for ps, g in c.pools[pid]["pgs"].items()
                if list(g.acting) != pre_acting[ps]]
    assert remapped, "weight-out produced no remaps"
    for ps in remapped:
        g = c.pools[pid]["pgs"][ps]
        assert isinstance(g.bus, PGChannel) and g.bus.bus is c.bus
        assert victim not in g.acting
        # the outgoing incarnation left no objects behind on the victim
        leftovers = [cn for cn in collection_names(c.osds[victim].store)
                     if cn == f"pg.{pid}.{ps}"]
        assert not leftovers, leftovers
    # OSD-wide death still reaches remapped PGs
    some = c.pools[pid]["pgs"][remapped[0]]
    peer = some.acting[1]
    c.bus.mark_down(peer)
    assert peer in some.bus.down
    c.bus.mark_up(peer)
    c.bus.deliver_all()
    for oid, want in model.items():
        assert c.get(pid, oid, len(want)) == want, oid
    c.shutdown()


def test_collection_namespace_isolation():
    """Same oid in two pools lands in different collections of the same
    per-OSD store without collision."""
    from ceph_tpu.backend.memstore import GObject, MemStore, Transaction
    base = MemStore()
    c1 = Collection(base, "pg.1.0")
    c2 = Collection(base, "pg.2.0")
    c1.queue_transaction(Transaction().write(GObject("x", 0), 0, b"one"))
    c2.queue_transaction(Transaction().write(GObject("x", 0), 0, b"two"))
    assert c1.read(GObject("x", 0)) == b"one"
    assert c2.read(GObject("x", 0)) == b"two"
    assert [g.oid for g in c1.list_objects()] == ["x"]
    assert collection_names(base) == {"pg.1.0", "pg.2.0"}
    # the objects view strips prefixes and supports membership/deletion
    assert GObject("x", 0) in c1.objects
    del c1.objects[GObject("x", 0)]
    assert not c1.exists(GObject("x", 0))
    assert c2.read(GObject("x", 0)) == b"two"
