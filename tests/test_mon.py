"""Monitor failure path + heartbeats: grace aging, distinct-subtree
reporters, min-up-ratio refusal, nodown, auto-out, boot.

Mirrors the reference semantics at src/mon/OSDMonitor.cc (prepare_failure
:2874, check_failure :2764-2850, can_mark_down :2671) and src/osd/OSD.cc
heartbeats (:4547-4996)."""
import pytest

from ceph_tpu.common import Context
from ceph_tpu.mon import HeartbeatAgent, Monitor, VirtualClock
from ceph_tpu.mon.heartbeat import build_heartbeat_mesh
from ceph_tpu.osdmap import PG

from test_osdmap import build_cluster

GRACE = 20          # osd_heartbeat_grace default


def make_mon(**conf):
    cct = Context(overrides=conf or None)
    m = build_cluster()                   # 3 racks x 3 hosts x 3 osds
    return Monitor(m, cct=cct), cct


class TestMonitorFailurePath:
    def test_two_subtree_reporters_after_grace_marks_down(self):
        mon, _ = make_mon()
        t0 = 100.0
        # reporters 3 and 6 are on different hosts
        assert not mon.prepare_failure(0, 3, failed_since=t0, now=t0 + 1)
        assert not mon.prepare_failure(0, 6, failed_since=t0, now=t0 + 1)
        # too early: within grace
        assert mon.propose_pending(t0 + 1) is None
        # after grace, a re-check succeeds
        assert mon.prepare_failure(0, 6, failed_since=t0, now=t0 + GRACE)
        new = mon.propose_pending(t0 + GRACE)
        assert new is not None and new.is_down(0)
        assert new.epoch == 2

    def test_same_host_reporters_insufficient(self):
        """Reporters from ONE host don't satisfy min_down_reporters=2
        distinct subtrees (OSDMonitor.cc:2772-2820)."""
        mon, _ = make_mon()
        t0 = 0.0
        # osds 1 and 2 share osd.0's host (first host holds osds 0,1,2)
        mon.prepare_failure(0, 1, failed_since=t0, now=t0 + GRACE + 1)
        assert not mon.prepare_failure(0, 2, failed_since=t0,
                                       now=t0 + GRACE + 1)
        assert mon.propose_pending(t0 + GRACE + 1) is None
        # a reporter from another host tips it
        assert mon.prepare_failure(0, 8, failed_since=t0, now=t0 + GRACE + 1)

    def test_cancel_report_retracts(self):
        mon, _ = make_mon()
        mon.prepare_failure(0, 3, failed_since=0.0, now=1.0)
        mon.cancel_failure(0, 3)
        assert 0 not in mon.failure_info
        mon.tick(GRACE + 5)
        assert mon.osdmap.is_up(0)

    def test_min_up_ratio_refuses_mass_downs(self):
        mon, _ = make_mon(mon_osd_min_up_ratio=0.75)
        n = mon.osdmap.max_osd               # 27
        t0 = 0.0
        now = GRACE + 1.0
        marked = 0
        for target in range(n):
            mon.prepare_failure(target, (target + 3) % n, t0, now)
            mon.prepare_failure(target, (target + 9) % n, t0, now)
            mon.propose_pending(now)
        up = sum(1 for o in range(n) if mon.osdmap.is_up(o))
        # the reference checks the ratio BEFORE each mark, so the floor can
        # dip at most one mark below it (OSDMonitor.cc:2683-2693)
        assert up / n >= 0.75 - 1.0 / n - 1e-9
        assert up < n                        # but marks did happen

    def test_nodown_flag(self):
        mon, _ = make_mon()
        mon.nodown.add(0)
        mon.prepare_failure(0, 3, failed_since=0.0, now=GRACE + 1)
        mon.prepare_failure(0, 6, failed_since=0.0, now=GRACE + 1)
        mon.tick(GRACE + 2)
        assert mon.osdmap.is_up(0)

    def test_auto_out_after_interval(self):
        mon, _ = make_mon(mon_osd_down_out_interval=600)
        mon.prepare_failure(0, 3, 0.0, GRACE + 1)
        mon.prepare_failure(0, 6, 0.0, GRACE + 1)
        mon.propose_pending(GRACE + 1)
        assert mon.osdmap.is_down(0) and mon.osdmap.is_in(0)
        mon.tick(GRACE + 1 + 599)
        assert mon.osdmap.is_in(0)
        mon.tick(GRACE + 1 + 601)
        assert mon.osdmap.is_out(0)          # weight 0 -> CRUSH remaps

    def test_boot_marks_up_and_clears_reports(self):
        mon, _ = make_mon()
        mon.prepare_failure(0, 3, 0.0, GRACE + 1)
        mon.prepare_failure(0, 6, 0.0, GRACE + 1)
        mon.propose_pending(GRACE + 1)
        assert mon.osdmap.is_down(0)
        mon.osd_boot(0)
        new = mon.propose_pending(GRACE + 2)
        assert new.is_up(0)
        assert new.epoch == 3

    def test_subscribers_see_commits(self):
        mon, _ = make_mon()
        seen = []
        mon.subscribers.append(lambda m, inc: seen.append(m.epoch))
        mon.prepare_failure(0, 3, 0.0, GRACE + 1)
        mon.prepare_failure(0, 6, 0.0, GRACE + 1)
        mon.propose_pending(GRACE + 1)
        assert seen == [2]


class TestHeartbeats:
    def test_silent_osd_detected_and_marked_down(self):
        mon, _ = make_mon()
        clock = VirtualClock()
        agents = build_heartbeat_mesh(mon, clock, mon.osdmap.max_osd)
        for _ in range(3):                   # establish baselines
            clock.advance(6)
            for a in agents.values():
                a.tick()
        victim = 5
        agents[victim] = None
        mon_net = next(iter(agents.values())).network
        mon_net[victim] = None               # dead: drops pings
        for _ in range(6):                   # ride out the grace
            clock.advance(6)
            for a in agents.values():
                if a is not None:
                    a.tick()
            mon.tick(clock.now())
        assert mon.osdmap.is_down(victim)
        for o in range(mon.osdmap.max_osd):
            if o != victim:
                assert mon.osdmap.is_up(o), f"osd.{o} wrongly down"

    def test_recovered_peer_cancels_reports(self):
        mon, _ = make_mon(mon_osd_min_down_reporters=26)  # never commits
        clock = VirtualClock()
        agents = build_heartbeat_mesh(mon, clock, mon.osdmap.max_osd)
        clock.advance(6)
        for a in agents.values():
            a.tick()
        net = agents[0].network
        net[5] = None                        # silence osd.5
        for _ in range(5):
            clock.advance(6)
            for o, a in agents.items():
                if net.get(o) is not None:
                    a.tick()
        assert 5 in mon.failure_info
        net[5] = agents[5]                   # revive
        for _ in range(2):
            clock.advance(6)
            for o, a in agents.items():
                if net.get(o) is not None:
                    a.tick()
        assert 5 not in mon.failure_info     # reports canceled


class TestMapDrivenRemap:
    def test_down_then_out_remaps_pgs(self):
        """The end-to-end control loop: failure -> down (holes) -> out
        (CRUSH refills), driving the data path's acting sets."""
        mon, _ = make_mon(mon_osd_down_out_interval=60)
        pgid = PG(2, 0)
        acting0 = mon.osdmap.pg_to_up_acting_osds(pgid)[2]
        victim = acting0[0]
        reporters = [o for o in range(mon.osdmap.max_osd)
                     if o not in (victim,)][:6]
        for r in reporters:
            mon.prepare_failure(victim, r, 0.0, GRACE + 1)
        mon.propose_pending(GRACE + 1)
        acting_down = mon.osdmap.pg_to_up_acting_osds(pgid)[2]
        assert acting_down[0] == 0x7FFFFFFF  # EC positional hole
        mon.tick(GRACE + 100)                # past down_out_interval
        acting_out = mon.osdmap.pg_to_up_acting_osds(pgid)[2]
        assert victim not in acting_out
        assert 0x7FFFFFFF not in acting_out  # CRUSH refilled the slot


class TestClusterControlLoop:
    def test_heartbeat_failure_drives_data_path(self):
        """Full loop: heartbeats detect a silent OSD -> monitor commits the
        down-mark -> PG buses route around it -> degraded reads succeed ->
        boot revives -> repair restores the shard."""
        import numpy as np
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.mon.heartbeat import build_heartbeat_mesh
        from ceph_tpu.backend.ec_backend import RecoveryState

        cluster = MiniCluster(n_osds=12, chunk_size=128)
        pid = cluster.create_ec_pool(
            "loop", {"plugin": "jax_rs", "k": "4", "m": "2",
                     "device": "numpy", "technique": "reed_sol_van"},
            pg_num=4)
        mon = cluster.attach_monitor()
        data = np.arange(4 * 128, dtype=np.uint8).tobytes() * 2
        for i in range(8):
            cluster.put(pid, f"o{i}", data)

        clock = VirtualClock()
        agents = build_heartbeat_mesh(mon, clock, 12)
        for _ in range(2):
            clock.advance(6)
            for a in agents.values():
                a.tick()
        # pick a non-primary victim and silence it
        primaries = {g.backend.whoami
                     for g in cluster.pools[pid]["pgs"].values()}
        victim = next(o for o in range(12) if o not in primaries)
        net = agents[0].network
        net[victim] = None
        for _ in range(6):
            clock.advance(6)
            for o, a in agents.items():
                if net.get(o) is not None:
                    a.tick()
            mon.tick(clock.now())
        assert mon.osdmap.is_down(victim)
        # data path saw the mark: PG buses route around the victim
        for g in cluster.pools[pid]["pgs"].values():
            if victim in g.acting:
                assert victim in g.bus.down
        for i in range(8):
            assert cluster.get(pid, f"o{i}", len(data)) == data
        # write while the victim is down (it goes stale), then boot + repair
        cluster.put(pid, "o0", data[::-1])
        net[victim] = agents[victim]
        mon.osd_boot(victim)
        mon.propose_pending(clock.now())
        assert mon.osdmap.is_up(victim)
        for g in cluster.pools[pid]["pgs"].values():
            if victim not in g.acting:
                continue
            for oid in [f"o{i}" for i in range(8)]:
                if cluster.pg_group(pid, oid) is not g:
                    continue
                report = g.backend.be_deep_scrub(oid)
                missing = {c for c, ok in report.items() if not ok}
                if missing:
                    rop = g.backend.recover_object(oid, missing)
                    g.bus.deliver_all()
                    assert rop.state == RecoveryState.COMPLETE
        want0 = data[::-1]
        assert cluster.get(pid, "o0", len(want0)) == want0

    def test_auto_out_triggers_backfill(self):
        """down -> auto-out -> CRUSH remap -> backfill: data lands on the
        new acting sets and reads survive with the old OSD gone for good."""
        import numpy as np
        from ceph_tpu.cluster import MiniCluster

        cct = Context(overrides={"mon_osd_down_out_interval": 60})
        cluster = MiniCluster(n_osds=12, chunk_size=128, cct=cct)
        pid = cluster.create_ec_pool(
            "bf", {"plugin": "jax_rs", "k": "4", "m": "2",
                   "device": "numpy", "technique": "reed_sol_van"},
            pg_num=4)
        mon = cluster.attach_monitor()
        data = {f"b{i}": np.random.default_rng(i).integers(
                    0, 256, size=1024, dtype=np.uint8).tobytes()
                for i in range(12)}
        for oid, v in data.items():
            cluster.put(pid, oid, v)

        primaries = {g.backend.whoami
                     for g in cluster.pools[pid]["pgs"].values()}
        victim = next(o for o in range(12) if o not in primaries)
        reporters = [o for o in range(12) if o != victim][:4]
        for r in reporters:
            mon.prepare_failure(victim, r, 0.0, GRACE + 1)
        mon.propose_pending(GRACE + 1)
        assert mon.osdmap.is_down(victim)
        mon.tick(GRACE + 1000)               # way past down_out_interval
        assert mon.osdmap.is_out(victim)
        # every PG was re-placed without the victim and holds the data
        for g in cluster.pools[pid]["pgs"].values():
            assert victim not in g.acting
        for oid, want in data.items():
            assert cluster.get(pid, oid, len(want)) == want
