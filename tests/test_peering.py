"""Peering statechart: GetInfo → GetLog → GetMissing → Activating → Active.

Mirrors the reference's PeeringState machine observables
(src/osd/PeeringState.{h,cc}): transition order, authoritative-log
election, acting-set negotiation (clean vs repair vs backfill peers),
replica activation epochs, mid-peering failures.
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osd.peering import PState
from ceph_tpu.osd.osd_ops import ObjectOperation


@pytest.fixture
def cluster():
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
    pid = c.create_ec_pool("p", {"k": "2", "m": "1", "device": "numpy"},
                           pg_num=4)
    yield c, pid
    c.shutdown()


def _transitions(g, epoch=None):
    return [s for e, s in g.peering.history if epoch is None or e == epoch]


def test_full_transition_sequence(cluster):
    c, pid = cluster
    c.put(pid, "obj", b"x" * 2000)
    g = c.pg_group(pid, "obj")
    g.peering.advance_map(epoch=5)
    g.bus.deliver_all()
    assert g.peering.state is PState.ACTIVE
    assert _transitions(g, 5) == [
        PState.GET_INFO.value, PState.GET_LOG.value,
        PState.GET_MISSING.value, PState.ACTIVATING.value,
        PState.ACTIVE.value]
    assert g.peering.last_epoch_started == 5
    # clean peers all joined the negotiated acting set
    assert sorted(g.peering.acting_set) == sorted(g.acting)
    assert not g.peering.repair_targets
    assert not g.peering.backfill_targets


def test_replicas_stamp_activation_epoch(cluster):
    c, pid = cluster
    c.put(pid, "obj", b"y" * 1000)
    g = c.pg_group(pid, "obj")
    g.peering.advance_map(epoch=7)
    g.bus.deliver_all()
    for osd in g.acting:
        if osd == g.backend.whoami:
            continue
        shard = g.bus.handlers[osd]
        assert shard.peered_epoch == 7


def test_stale_peer_negotiated_into_repair():
    # k=2,m=2 (min_size 3 of 4): one shard can die and the PG stays active
    c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
    pid = c.create_ec_pool("p", {"k": "2", "m": "2", "device": "numpy"},
                           pg_num=4)
    c.put(pid, "obj", b"a" * 1500)
    g = c.pg_group(pid, "obj")
    victim = next(o for o in g.acting if o != g.backend.whoami)
    g.bus.mark_down(victim)
    oid2 = next(f"obj2-{s}" for s in "xyzwvut"
                if c.object_pg(pid, f"obj2-{s}") == c.object_pg(pid, "obj"))
    c.put(pid, oid2, b"b" * 1500)         # same PG: victim misses this write
    g.bus.mark_up(victim)
    g.peering.advance_map(epoch=9)
    g.bus.deliver_all()
    assert g.peering.state is PState.ACTIVE
    # the stale shard was negotiated as a repair target and caught up
    assert victim in g.peering.repair_targets | g.peering.backfill_targets
    shard = g.bus.handlers[victim]
    assert shard.pg_log.head == g.backend.pg_log.head
    c.shutdown()


def test_primary_adopts_authority_from_peer(cluster):
    """A primary whose log is behind a peer's must adopt the peer's log in
    GetLog (find_best_info elects the peer)."""
    c, pid = cluster
    c.put(pid, "obj", b"c" * 1000)
    g = c.pg_group(pid, "obj")
    # fabricate staleness: rewind the primary's authority + local logs
    peer_head = g.backend.pg_log.head
    assert peer_head > 0
    from ceph_tpu.osd.pg_log import PGLog
    g.backend.pg_log = PGLog()            # primary lost its in-RAM log
    g.peering.advance_map(epoch=11)
    g.bus.deliver_all()
    assert g.peering.state is PState.ACTIVE
    assert g.backend.pg_log.head == peer_head     # adopted from the peer


def test_peer_death_mid_peering(cluster):
    c, pid = cluster
    c.put(pid, "obj", b"d" * 1000)
    g = c.pg_group(pid, "obj")
    victim = next(o for o in g.acting if o != g.backend.whoami)
    # advance without delivering: GetInfo is in flight
    g.peering.advance_map(epoch=13)
    assert g.peering.state is PState.GET_INFO
    g.bus.mark_down(victim)               # dies before answering
    g.bus.deliver_all()
    assert g.peering.state is PState.ACTIVE
    assert victim not in g.peering.acting_set


def test_monitor_down_up_drives_statechart(cluster):
    c, pid = cluster
    mon = c.attach_monitor()
    c.put(pid, "obj", b"e" * 1200)
    g = c.pg_group(pid, "obj")
    victim = next(o for o in g.acting if o != g.backend.whoami)
    # one reporter from each of the two OTHER hosts (distinct subtrees)
    other_hosts = sorted({o // 3 for o in range(9)} - {victim // 3})
    reporters = [h * 3 for h in other_hosts][:2]
    t0 = 100.0
    grace = 25.0
    for rep in reporters:
        mon.prepare_failure(victim, rep, failed_since=t0, now=t0 + 1)
    mon.prepare_failure(victim, reporters[0], failed_since=t0,
                        now=t0 + grace)
    assert mon.propose_pending(t0 + grace) is not None   # down committed
    assert g.peering.state is PState.ACTIVE
    runs = len([e for e, s in g.peering.history
                if s == PState.GET_INFO.value])
    mon.osd_boot(victim)
    assert mon.propose_pending(t0 + grace + 1) is not None   # up committed
    assert g.peering.state is PState.ACTIVE
    assert len([e for e, s in g.peering.history
                if s == PState.GET_INFO.value]) > runs
    # the PG still serves after the churn
    r = c.operate(pid, "obj", ObjectOperation().read(0, 0))
    assert r.outdata(0)[:4] == b"eeee"


def test_parked_write_redrives_after_peering(cluster):
    """Below min_size the PG parks writes; peering back to Active with the
    revived shard re-drives them (the reference's waiting_for_peered)."""
    from ceph_tpu.cluster import BlockedWriteError
    c, pid = cluster
    c.put(pid, "obj", b"f" * 900)
    g = c.pg_group(pid, "obj")
    peers = [o for o in g.acting if o != g.backend.whoami]
    for o in peers:
        g.bus.mark_down(o)                # k=2,m=1: below min_size
    with pytest.raises(BlockedWriteError):
        c.put(pid, "obj", b"g" * 900)
    for o in peers:
        g.bus.mark_up(o)
    g.peering.advance_map(epoch=17)
    g.bus.deliver_all()
    assert g.peering.state is PState.ACTIVE
    assert c.get(pid, "obj", 900) == b"g" * 900   # parked write committed


def test_replicas_record_activation_head(cluster):
    c, pid = cluster
    c.put(pid, "hobj", b"h" * 800)
    g = c.pg_group(pid, "hobj")
    g.peering.advance_map(epoch=21)
    g.bus.deliver_all()
    for osd in g.acting:
        if osd != g.backend.whoami:
            shard = g.bus.handlers[osd]
            assert shard.peered_head == g.backend.pg_log.head


def test_primary_death_skips_statechart(cluster):
    """A down-mark for the PG's own primary must NOT re-run its peering
    (replies to a dead shard drop — it would wedge in GetInfo)."""
    c, pid = cluster
    mon = c.attach_monitor()
    c.put(pid, "pobj", b"p" * 800)
    g = c.pg_group(pid, "pobj")
    primary = g.backend.whoami
    runs = len(g.peering.history)
    other_hosts = sorted({o // 3 for o in range(9)} - {primary // 3})
    reporters = [h * 3 for h in other_hosts][:2]
    for rep in reporters:
        mon.prepare_failure(primary, rep, failed_since=10.0, now=11.0)
    mon.prepare_failure(primary, reporters[0], failed_since=10.0, now=40.0)
    assert mon.propose_pending(40.0) is not None
    assert len(g.peering.history) == runs          # no GetInfo wedge
    assert g.peering.state is not PState.GET_INFO


def test_parked_write_survives_backfill_bookkeeping():
    """An op-vector write acked AFTER parking must still be known to the
    backfill object list (regression: bookkeeping ran at dispatch time,
    before the parked write hit the store)."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
    pid = c.create_ec_pool("pk", {"k": "2", "m": "1", "device": "numpy"},
                           pg_num=4)
    g = c.pg_group(pid, "parked")
    peers = [o for o in g.acting if o != g.backend.whoami]
    for o in peers:
        g.bus.mark_down(o)
    done = []
    res = c.osd_submit(pid, c.object_pg(pid, "parked"), g.backend.whoami,
                       c.osdmap.epoch, "parked", None,
                       ops=ObjectOperation().write_full(b"parked!").ops,
                       on_done=done.append)
    assert res is None and not done           # accepted, parked
    assert "parked" not in c.objects.get(pid, set())
    for o in peers:
        g.bus.mark_up(o)
    g.bus.deliver_all()
    assert done and done[0].result == 0       # committed on revival
    assert "parked" in c.objects[pid]         # bookkeeping at completion
    c.shutdown()


def test_batched_incremental_with_dead_primary_no_wedge():
    """One incremental marking the primary AND a replica down must not
    run the dead primary's statechart (regression: the guard was
    per-state-entry, so the replica's flip still advanced it)."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
    pid = c.create_ec_pool("bp", {"k": "2", "m": "1", "device": "numpy"},
                           pg_num=4)
    mon = c.attach_monitor()
    c.put(pid, "obj", b"q" * 800)
    g = c.pg_group(pid, "obj")
    primary = g.backend.whoami
    replica = next(o for o in g.acting if o != primary)
    runs = len(g.peering.history)
    # report BOTH down so one propose commits a batched incremental
    for victim in (primary, replica):
        hosts = sorted({o // 3 for o in range(9)} - {victim // 3})
        reps = [h * 3 for h in hosts if h * 3 not in (primary, replica)][:2]
        for rep in reps:
            mon.prepare_failure(victim, rep, failed_since=5.0, now=6.0)
        mon.prepare_failure(victim, reps[0], failed_since=5.0, now=35.0)
    new = mon.propose_pending(35.0)
    assert new is not None
    assert new.is_down(primary) and new.is_down(replica)
    assert len(g.peering.history) == runs, "dead primary's statechart ran"
    assert g.peering.state is not PState.GET_INFO
    c.shutdown()
