"""dmClock QoS scheduling: reservations, weights, limits, op classes.

Mirrors the behavior of the reference's mClock queues (reference:
src/osd/mClockOpClassQueue.{h,cc} over src/dmclock/ — the mClock paper's
reservation/weight/limit semantics): reservations are hard floors,
weights divide the surplus proportionally, limits are hard caps, and
strict-priority ops bypass QoS.
"""
import pytest

from ceph_tpu.osd.mclock import (BG_RECOVERY, BG_SCRUB, CLIENT_OP,
                                 ClientInfo, MClockOpClassQueue, MClockQueue)


def run_schedule(q, duration: float, tick: float = 0.001):
    """Serve as fast as the queue allows over [0, duration); returns
    {client: count} using each item's embedded client label."""
    served = {}
    now = 0.0
    while now < duration:
        item = q.dequeue(now)
        if item is None:
            nxt = q.next_eligible_time(now)
            if nxt is None or nxt >= duration:
                break
            now = max(nxt, now + tick)
            continue
        served[item[0]] = served.get(item[0], 0) + 1
    return served


class TestReservation:
    def test_reservation_is_a_hard_floor(self):
        """A (res 100/s) and B (no res, huge weight): A still gets its
        100 ops in the first second even though B's weight dwarfs it."""
        infos = {"A": ClientInfo(reservation=100.0, weight=1.0),
                 "B": ClientInfo(reservation=0.0, weight=1000.0)}
        q = MClockQueue(lambda c: infos[c])
        for i in range(200):
            q.enqueue("A", ("A", i), now=0.0)
            q.enqueue("B", ("B", i), now=0.0)
        # serve exactly 150 ops during the first simulated second, paced
        # uniformly (the constraint phase should claim A's 100)
        served = {"A": 0, "B": 0}
        for slot in range(150):
            now = slot / 150.0
            item = q.dequeue(now)
            assert item is not None
            served[item[0]] += 1
        assert served["A"] >= 100, served

    def test_idle_client_tags_reset_to_now(self):
        infos = {"A": ClientInfo(reservation=10.0)}
        q = MClockQueue(lambda c: infos[c])
        q.enqueue("A", ("A", 0), now=0.0)
        assert q.dequeue(0.0) is not None
        # long idle: the next request must be eligible immediately, not
        # at last_tag + 1/r in the distant past/future
        q.enqueue("A", ("A", 1), now=100.0)
        assert q.dequeue(100.0) is not None


class TestWeights:
    def test_surplus_split_by_weight(self):
        infos = {"A": ClientInfo(weight=2.0), "B": ClientInfo(weight=1.0)}
        q = MClockQueue(lambda c: infos[c])
        for i in range(300):
            q.enqueue("A", ("A", i), now=0.0)
            q.enqueue("B", ("B", i), now=0.0)
        served = {"A": 0, "B": 0}
        for _ in range(150):
            item = q.dequeue(now=1000.0)     # no limits: time irrelevant
            served[item[0]] += 1
        assert served["A"] == 2 * served["B"], served

    def test_weight_phase_credits_reservation(self):
        """Paper §III-B: ops granted by weight must not consume the
        client's reservation budget."""
        infos = {"A": ClientInfo(reservation=10.0, weight=100.0)}
        q = MClockQueue(lambda c: infos[c])
        for i in range(20):
            q.enqueue("A", ("A", i), now=0.0)
        # serve 10 by weight at t=0 (reservation tags 0.1, 0.2, ... are
        # not yet eligible except the first)
        for _ in range(10):
            assert q.dequeue(0.0) is not None
        # after the credits, the head's R tag should be ~1/r * 1, not
        # 1/r * 11: at t=0.11 it must be reservation-eligible
        before = q.served_reservation
        assert q.dequeue(0.11) is not None
        assert q.served_reservation == before + 1


class TestLimits:
    def test_limit_is_a_hard_cap(self):
        infos = {"A": ClientInfo(weight=1.0, limit=5.0)}
        q = MClockQueue(lambda c: infos[c])
        for i in range(100):
            q.enqueue("A", ("A", i), now=0.0)
        served = run_schedule(q, duration=2.0)
        assert served.get("A", 0) <= 11        # 5/s over 2s (+head)

    def test_over_limit_queue_idles_not_busy_loops(self):
        infos = {"A": ClientInfo(weight=1.0, limit=1.0)}
        q = MClockQueue(lambda c: infos[c])
        q.enqueue("A", ("A", 0), now=0.0)
        q.enqueue("A", ("A", 1), now=0.0)
        assert q.dequeue(0.0) is not None
        assert q.dequeue(0.5) is None          # L tag = 1.0
        nxt = q.next_eligible_time(0.5)
        assert nxt == pytest.approx(1.0)
        assert q.dequeue(1.0) is not None


class TestStrictPriority:
    def test_strict_bypasses_qos(self):
        infos = {"A": ClientInfo(weight=1.0, limit=0.001)}
        q = MClockQueue(lambda c: infos[c])
        q.enqueue("A", ("A", 0), now=0.0)
        q.enqueue_strict(200, ("peering", 0))
        q.enqueue_strict(100, ("boot", 0))
        assert q.dequeue(0.0)[0] == "peering"  # highest priority first
        assert q.dequeue(0.0)[0] == "boot"

    def test_empty(self):
        q = MClockQueue(lambda c: ClientInfo())
        assert q.empty()
        q.enqueue_strict(1, "x")
        assert not q.empty()
        q.dequeue(0.0)
        assert q.empty()


class TestOpClassQueue:
    def test_background_classes_cannot_starve_clients(self):
        """The reference's whole point: scrub/recovery limited so client
        ops dominate under contention (mClockOpClassSupport defaults)."""
        q = MClockOpClassQueue()
        for i in range(500):
            q.enqueue(CLIENT_OP, (CLIENT_OP, i), now=0.0)
            q.enqueue(BG_RECOVERY, (BG_RECOVERY, i), now=0.0)
            q.enqueue(BG_SCRUB, (BG_SCRUB, i), now=0.0)
        served = {}
        for slot in range(300):
            item = q.dequeue(now=slot / 300.0)
            if item is None:
                continue
            served[item[0]] = served.get(item[0], 0) + 1
        assert served[CLIENT_OP] > 250, served
        assert served.get(BG_SCRUB, 0) <= 1, served

    def test_recovery_reservation_guarantees_progress(self):
        """Recovery keeps a small reservation: even under full client
        load it is never starved completely."""
        q = MClockOpClassQueue()
        for i in range(1000):
            q.enqueue(CLIENT_OP, (CLIENT_OP, i), now=0.0)
        for i in range(20):
            q.enqueue(BG_RECOVERY, (BG_RECOVERY, i), now=0.0)
        served = {}
        for slot in range(600):
            item = q.dequeue(now=slot * 0.01)  # 6 simulated seconds
            if item:
                served[item[0]] = served.get(item[0], 0) + 1
        assert served.get(BG_RECOVERY, 0) >= 5, served
        assert served[CLIENT_OP] > 500, served
