"""Device kernels vs the numpy oracle: bitslice (MXU) and lookup (VPU) paths,
plus the RSCodec facade with its erasure-signature decode cache."""
import itertools

import numpy as np
import pytest

from ceph_tpu.gf import rs_vandermonde_isa, cauchy1
from ceph_tpu.gf import ref
from ceph_tpu.ops import gf_apply, xor_reduce, RSCodec


@pytest.mark.parametrize("variant", ["bitslice", "lookup"])
@pytest.mark.parametrize("shape", [(1, 2, 128), (4, 8, 1024), (3, 10, 333)])
def test_gf_apply_matches_numpy(variant, shape):
    r, k, n = shape
    rng = np.random.default_rng(42)
    mat = rng.integers(0, 256, size=(r, k), dtype=np.uint8)
    data = rng.integers(0, 256, size=(k, n), dtype=np.uint8)
    want = ref.apply_matrix(mat, data)
    got = np.asarray(gf_apply(mat, data, variant=variant))
    np.testing.assert_array_equal(got, want)


def test_xor_reduce():
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(5, 64), dtype=np.uint8)
    want = data[0] ^ data[1] ^ data[2] ^ data[3] ^ data[4]
    np.testing.assert_array_equal(np.asarray(xor_reduce(data))[0], want)


@pytest.mark.parametrize("technique", ["reed_sol_van", "vandermonde", "cauchy"])
@pytest.mark.parametrize("device", ["numpy", "jax"])
def test_codec_roundtrip(technique, device):
    k, m, n = 4, 2, 256
    codec = RSCodec(k, m, technique=technique, device=device)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, size=(k, n), dtype=np.uint8)
    par = codec.encode(data)
    assert par.shape == (m, n)
    full = {i: data[i] for i in range(k)} | {k + i: par[i] for i in range(m)}
    for lost in itertools.combinations(range(k + m), m):
        chunks = {i: v for i, v in full.items() if i not in lost}
        rec = codec.decode(chunks, list(lost))
        for e in lost:
            np.testing.assert_array_equal(rec[e], full[e])


def test_codec_batched_encode_matches_loop():
    codec = RSCodec(8, 4, technique="cauchy", device="jax")
    rng = np.random.default_rng(9)
    batch = rng.integers(0, 256, size=(6, 8, 128), dtype=np.uint8)
    got = codec.encode(batch)
    assert got.shape == (6, 4, 128)
    for b in range(6):
        np.testing.assert_array_equal(got[b], codec.encode(batch[b]))


def test_codec_decode_batch_shared_signature():
    codec = RSCodec(4, 2, technique="cauchy", device="jax")
    rng = np.random.default_rng(11)
    batch = rng.integers(0, 256, size=(3, 4, 64), dtype=np.uint8)
    par = codec.encode(batch)                      # [3, 2, 64]
    erasures = [1, 4]
    src = [0, 2, 3, 5]
    full = np.concatenate([batch, par], axis=1)    # [3, 6, 64]
    stack = full[:, src, :]
    rec = codec.decode_batch(stack, src, erasures)
    np.testing.assert_array_equal(rec[:, 0, :], full[:, 1, :])
    np.testing.assert_array_equal(rec[:, 1, :], full[:, 4, :])


def test_decode_cache_hits():
    codec = RSCodec(4, 2)
    D1, s1 = codec.decode_matrix([0, 1])
    D2, s2 = codec.decode_matrix([0, 1])
    assert D1 is D2 and s1 is s2


def test_bad_params_rejected():
    with pytest.raises(ValueError):
        RSCodec(1, 1)
    with pytest.raises(ValueError):
        RSCodec(2, 0)
    with pytest.raises(ValueError):
        RSCodec(4, 2, technique="nope")


def test_decode_batch_unsorted_src():
    # regression: caller-supplied src order must not corrupt decode output
    codec = RSCodec(4, 2, technique="cauchy", device="numpy")
    rng = np.random.default_rng(13)
    batch = rng.integers(0, 256, size=(2, 4, 32), dtype=np.uint8)
    par = codec.encode(batch)
    full = np.concatenate([batch, par], axis=1)
    src = [2, 0, 3, 5]
    rec = codec.decode_batch(full[:, src, :], src, [1, 4])
    np.testing.assert_array_equal(rec[:, 0, :], full[:, 1, :])
    np.testing.assert_array_equal(rec[:, 1, :], full[:, 4, :])


def test_isa_vandermonde_envelope_enforced():
    with pytest.raises(ValueError):
        RSCodec(22, 4, technique="vandermonde")
    with pytest.raises(ValueError):
        RSCodec(33, 2, technique="vandermonde")
    RSCodec(21, 4, technique="vandermonde")  # boundary is allowed
