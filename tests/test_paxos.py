"""Multi-monitor Paxos: elections, quorum commits, leader-death recovery.

Mirrors the reference's monitor consensus (reference: src/mon/Paxos.cc
collect/begin/accept/commit phases; src/mon/Elector.cc lowest-rank-wins
elections): map commits require a majority quorum, survive any single
monitor death — including the leader dying BETWEEN begin and commit — and
laggard monitors catch up through the collect phase.
"""
import numpy as np
import pytest

from ceph_tpu.crush import CRUSH_BUCKET_STRAW2, CrushMap
from ceph_tpu.mon import MonCluster
from ceph_tpu.mon.paxos import Accept, Begin, Commit
from ceph_tpu.osdmap import Incremental, OSDMap, OSD_UP


def make_map(n_osds=9) -> OSDMap:
    cmap = CrushMap()
    cmap.set_type_name(1, "host")
    cmap.set_type_name(2, "root")
    hosts = []
    for h0 in range(0, n_osds, 3):
        items = list(range(h0, h0 + 3))
        hosts.append(cmap.add_bucket(CRUSH_BUCKET_STRAW2, 1, items,
                                     [0x10000] * 3))
    root = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 2, hosts,
                           [3 * 0x10000] * len(hosts))
    cmap.set_item_name(root, "default")
    cmap.finalize()
    m = OSDMap(crush=cmap)
    for o in range(n_osds):
        m.create_osd(o)
    return m


def down_inc(osd: int) -> Incremental:
    inc = Incremental()
    inc.new_state[osd] = OSD_UP
    return inc


@pytest.fixture()
def mc():
    return MonCluster(make_map(), n_mons=3)


class TestElection:
    def test_lowest_rank_wins(self, mc):
        ld = mc.leader()
        assert ld is not None and ld.rank == 0
        assert mc.quorum_ranks() == {0, 1, 2}

    def test_leader_death_elects_next_rank(self, mc):
        mc.kill(0)
        ld = mc.elect()
        assert ld is not None and ld.rank == 1
        assert mc.quorum_ranks() == {1, 2}

    def test_no_quorum_without_majority(self, mc):
        mc.kill(1)
        mc.kill(2)
        assert mc.elect() is None         # 1 of 3 cannot form a quorum

    def test_revived_leader_retakes_lead(self, mc):
        mc.kill(0)
        assert mc.elect().rank == 1
        mc.revive(0)
        assert mc.leader().rank == 0
        assert mc.quorum_ranks() == {0, 1, 2}


class TestQuorumCommit:
    def test_commit_reaches_every_monitor(self, mc):
        ld = mc.leader()
        ld.submit(0.0, down_inc(3))
        mc.bus.deliver_all()
        for m in mc.mons:
            assert m.last_committed == 1
            assert not m.service.osdmap.is_up(3), f"mon.{m.rank} stale"

    def test_commits_survive_any_single_mon_death(self, mc):
        for victim in range(3):
            cluster = MonCluster(make_map(), n_mons=3)
            cluster.kill(victim)
            ld = cluster.elect()
            assert ld is not None
            ld.submit(0.0, down_inc(4))
            cluster.bus.deliver_all()
            for m in cluster.mons:
                if m.rank == victim:
                    continue
                assert not m.service.osdmap.is_up(4), \
                    f"mon.{m.rank} missed the commit (victim={victim})"

    def test_sequential_commits_ordered(self, mc):
        ld = mc.leader()
        for osd in (3, 4, 5):
            ld.submit(0.0, down_inc(osd))
        mc.bus.deliver_all()
        for m in mc.mons:
            assert m.last_committed == 3
            assert all(not m.service.osdmap.is_up(o) for o in (3, 4, 5))

    def test_peon_forwards_to_leader(self, mc):
        peon = mc.mons[2]
        peon.submit(0.0, down_inc(6))     # MForward analog
        mc.bus.deliver_all()
        for m in mc.mons:
            assert not m.service.osdmap.is_up(6)

    def test_no_commit_without_quorum(self, mc):
        mc.kill(1)
        mc.kill(2)
        assert mc.elect() is None
        # the service's pending change is refused by paxos (no quorum) and
        # RETAINED — not parked as a stale value, not lost
        svc = mc.mons[0].service
        svc.pending.new_state[3] = OSD_UP
        svc.propose_pending(0.0)
        mc.bus.deliver_all()
        assert mc.mons[0].last_committed == 0
        assert svc.osdmap.is_up(3)
        assert svc.pending.new_state.get(3) == OSD_UP, \
            "pending change lost while quorum-less"
        # majority returns: the retained change proposes and commits
        mc.revive(1)
        svc.propose_pending(1.0)
        mc.bus.deliver_all()
        assert mc.mons[0].last_committed == 1
        assert not svc.osdmap.is_up(3)
        assert not mc.mons[1].service.osdmap.is_up(3)

    def test_duplicated_forward_commits_once(self, mc):
        """A duplicated MForward (connection reset + resend) must not
        commit twice — with XOR incremental semantics a double commit
        would flip the OSD back up."""
        from ceph_tpu.backend.messages import FaultConfig
        mc.bus.inject_faults(FaultConfig(seed=5, dup_prob=1.0))
        mc.mons[2].submit(0.0, down_inc(5))
        mc.bus.deliver_all()
        assert all(m.last_committed == 1 for m in mc.mons)
        assert not mc.osdmap.is_up(5)


class TestLeaderDeathMidProposal:
    def test_value_accepted_by_peons_survives_leader_death(self, mc):
        """THE two-phase scenario: the leader sends begin, peons accept
        and persist the uncommitted value, the leader dies before sending
        commit.  The new leader's collect phase must find the uncommitted
        value and re-propose it (Paxos.cc handle_last recovery)."""
        ld = mc.leader()
        ld.submit(0.0, down_inc(7))
        # deliver ONLY the peons' queues: they process Begin and queue
        # their Accepts back to the leader...
        while mc.bus.deliver_one(1) or mc.bus.deliver_one(2):
            pass
        for r in (1, 2):
            assert mc.mons[r].uncommitted is not None, "peon missed begin"
        assert all(m.last_committed == 0 for m in mc.mons), \
            "nothing committed yet: the accepts are still in flight"
        # ...but the leader dies with the Accepts undelivered.  kill()
        # re-elects; the new leader's collect finds the uncommitted value
        mc.kill(0)
        new_ld = mc.leader()
        assert new_ld.rank == 1
        for m in mc.mons[1:]:
            assert m.last_committed == 1, "uncommitted value was lost"
            assert not m.service.osdmap.is_up(7)

    def test_value_only_at_leader_dies_with_it(self, mc):
        """Converse: the leader dies before ANY peon saw begin — the value
        was never acked and legitimately vanishes."""
        ld = mc.leader()
        ld.submit(0.0, down_inc(8))       # begins queued, not delivered
        # the leader dies before its begins hit the wire: they are lost
        # with it (a queued message on a dead host's NIC)
        mc.bus.down.add(0)                # died...
        mc.bus.queues[1].clear()          # ...with the begins unsent
        mc.bus.queues[2].clear()
        new_ld = mc.elect()
        assert new_ld.rank == 1
        assert all(m.last_committed == 0 for m in mc.mons[1:])
        assert mc.osdmap.is_up(8)

    def test_leader_death_after_partial_commit_broadcast(self, mc):
        """The leader committed and told one peon but died before telling
        the other: collect must catch the laggard up."""
        ld = mc.leader()
        ld.submit(0.0, down_inc(3))
        while mc.bus.deliver_one(1) or mc.bus.deliver_one(2):
            pass                          # peons accept
        while mc.bus.deliver_one(0):
            pass                          # leader commits, queues Commit
        # deliver the commit to peon 1 only, then the leader dies
        while mc.bus.deliver_one(1):
            pass
        mc.bus.queues[2].clear()          # peon 2 never hears the commit
        assert mc.mons[1].last_committed == 1
        assert mc.mons[2].last_committed == 0
        mc.kill(0)                        # mon1 leads; collect shares state
        assert mc.mons[2].last_committed == 1
        assert not mc.mons[2].service.osdmap.is_up(3)


class TestLaggardCatchUp:
    def test_revived_monitor_learns_missed_commits(self, mc):
        mc.kill(2)
        mc.elect()
        ld = mc.leader()
        for osd in (3, 4):
            ld.submit(0.0, down_inc(osd))
        mc.bus.deliver_all()
        assert mc.mons[2].last_committed == 0
        mc.revive(2)                      # collect ships the missed commits
        assert mc.mons[2].last_committed == 2
        assert all(not mc.mons[2].service.osdmap.is_up(o) for o in (3, 4))
        # and the revived mon participates in new commits
        mc.leader().submit(0.0, down_inc(5))
        mc.bus.deliver_all()
        assert mc.mons[2].last_committed == 3


class TestMiniClusterIntegration:
    def test_attach_quorum_monitor_drives_data_path(self):
        """attach_monitor(n_mons=3): a failure report committed through
        the Paxos quorum routes the data path around the dead OSD, and
        surviving a monitor death changes nothing for the data path."""
        from ceph_tpu.cluster import MiniCluster
        cluster = MiniCluster(n_osds=12, chunk_size=256)
        pid = cluster.create_ec_pool(
            "q", {"plugin": "jax_rs", "k": "4", "m": "2",
                  "device": "numpy"}, pg_num=4)
        data = np.random.default_rng(0).integers(
            0, 256, 4096, dtype=np.uint8).tobytes()
        cluster.put(pid, "obj", data)
        mon = cluster.attach_monitor(n_mons=3)
        assert mon.leader() is not None
        mon.kill(2)                       # a monitor dies: quorum holds
        mon.elect()
        g = cluster.pg_group(pid, "obj")
        victim = g.acting[1]
        grace = cluster.cct.conf.get("osd_heartbeat_grace")
        mon.prepare_failure(victim, (victim + 1) % 12, 0.0, grace + 1)
        mon.prepare_failure(victim, (victim + 4) % 12, 0.0, grace + 1)
        new = mon.propose_pending(grace + 1)
        assert new is not None and not new.is_up(victim)
        assert victim in g.bus.down       # subscriber routed the data path
        assert cluster.get(pid, "obj", len(data)) == data


class TestServiceIntegration:
    def test_failure_reports_commit_through_quorum(self, mc):
        """The OSDMonitor failure path rides Paxos: reports -> grace ->
        propose -> quorum commit -> every mon's map shows the OSD down,
        subscribers fire exactly once."""
        grace = mc.cct.conf.get("osd_heartbeat_grace")
        events = []
        mc.subscribers.append(lambda new_map, inc: events.append(inc))
        mc.prepare_failure(0, 3, failed_since=0.0, now=grace + 1)
        mc.prepare_failure(0, 6, failed_since=0.0, now=grace + 1)
        new = mc.propose_pending(grace + 1)
        assert new is not None and not new.is_up(0)
        assert len(events) == 1
        for m in mc.mons:
            assert not m.service.osdmap.is_up(0)

    def test_failure_path_survives_leader_loss(self, mc):
        grace = mc.cct.conf.get("osd_heartbeat_grace")
        mc.kill(0)
        mc.elect()
        mc.prepare_failure(2, 4, failed_since=0.0, now=grace + 1)
        mc.prepare_failure(2, 7, failed_since=0.0, now=grace + 1)
        new = mc.propose_pending(grace + 1)
        assert new is not None and not new.is_up(2)
        for m in mc.mons[1:]:
            assert not m.service.osdmap.is_up(2)
