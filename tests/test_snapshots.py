"""Pool snapshots: COW clones, snap reads, rollback, list_snaps, trim.

Mirrors the reference's snapshot semantics (src/osd/PrimaryLogPG.cc
make_writable COW + find_object_context snap resolution + _rollback_to;
pg_pool_t snap bookkeeping; the SnapTrimmer running as background work):
writes after a pool snap clone the head at first touch, reads at a snap
id resolve to the covering clone, rollback restores a snapped state,
and removing a snap trims its clones under the BG_SNAPTRIM QoS class.
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osd.osd_ops import ObjectOperation
from ceph_tpu.osd.primary_log_pg import EROFS, clone_oid


@pytest.fixture(params=["ec", "rep"])
def cluster(request):
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
    if request.param == "ec":
        pid = c.create_ec_pool("p", {"k": "2", "m": "1", "device": "numpy"},
                               pg_num=4)
    else:
        pid = c.create_replicated_pool("p", size=3, pg_num=4)
    yield c, pid
    c.shutdown()


def _data(n, seed):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def test_snapshot_isolation(cluster):
    c, pid = cluster
    v1 = _data(3000, 1)
    c.operate(pid, "obj", ObjectOperation().write_full(v1)
              .setxattr("gen", b"1"))
    s1 = c.create_pool_snap(pid, "before")
    v2 = _data(2000, 2)
    c.operate(pid, "obj", ObjectOperation().write_full(v2)
              .setxattr("gen", b"2"))
    # head sees v2; the snap sees v1 (data AND attrs)
    assert c.operate(pid, "obj", ObjectOperation()
                     .read(0, 0)).outdata(0)[:2000] == v2
    r = c.operate(pid, "obj", ObjectOperation().read(0, 0).getxattr("gen"),
                  snapid=s1)
    assert r.outdata(0)[:3000] == v1
    assert r.outdata(1) == b"1"


def test_multiple_snap_levels(cluster):
    c, pid = cluster
    versions = {}
    snaps = {}
    for i in range(3):
        versions[i] = _data(1000 + 200 * i, 10 + i)
        c.operate(pid, "ml", ObjectOperation().write_full(versions[i]))
        snaps[i] = c.create_pool_snap(pid, f"s{i}")
    final = _data(500, 99)
    c.operate(pid, "ml", ObjectOperation().write_full(final))
    for i in range(3):
        r = c.operate(pid, "ml", ObjectOperation().read(0, 0),
                      snapid=snaps[i])
        assert r.outdata(0)[:len(versions[i])] == versions[i], i
    assert c.operate(pid, "ml", ObjectOperation()
                     .read(0, 0)).outdata(0)[:500] == final


def test_no_cow_without_intervening_snap(cluster):
    """Two writes under the SAME snap seq clone only once."""
    c, pid = cluster
    c.operate(pid, "once", ObjectOperation().write_full(b"a" * 600))
    c.create_pool_snap(pid, "s")
    c.operate(pid, "once", ObjectOperation().write_full(b"b" * 600))
    c.operate(pid, "once", ObjectOperation().write_full(b"c" * 600))
    r = c.operate(pid, "once", ObjectOperation().list_snaps())
    assert len(r.outdata(0)["clones"]) == 1


def test_list_snaps(cluster):
    c, pid = cluster
    c.operate(pid, "ls", ObjectOperation().write_full(b"x" * 700))
    s1 = c.create_pool_snap(pid, "a")
    c.operate(pid, "ls", ObjectOperation().write_full(b"y" * 300))
    r = c.operate(pid, "ls", ObjectOperation().list_snaps())
    out = r.outdata(0)
    assert [cl["snapid"] for cl in out["clones"]] == [s1]
    assert out["clones"][0]["size"] == 700      # v1's logical size
    assert out["seq"] >= s1


def test_rollback(cluster):
    c, pid = cluster
    v1 = _data(2500, 3)
    c.operate(pid, "rb", ObjectOperation().write_full(v1)
              .setxattr("tag", b"old"))
    s1 = c.create_pool_snap(pid, "keep")
    c.operate(pid, "rb", ObjectOperation().write_full(b"clobbered")
              .setxattr("tag", b"new"))
    c.operate(pid, "rb", ObjectOperation().rollback(s1))
    r = c.operate(pid, "rb", ObjectOperation().read(0, 0).getxattr("tag")
                  .list_snaps())
    assert r.outdata(0)[:2500] == v1
    assert r.outdata(1) == b"old"               # attrs restored too
    # the head still knows its clones after rollback
    assert [cl["snapid"] for cl in r.outdata(2)["clones"]] == [s1]


def test_rollback_recreates_deleted_head(cluster):
    c, pid = cluster
    v1 = _data(1200, 4)
    c.operate(pid, "undel", ObjectOperation().write_full(v1))
    s1 = c.create_pool_snap(pid, "pre")
    c.operate(pid, "undel", ObjectOperation().remove())
    # head gone, snap still readable (clone discovered without the head)
    r = c.operate(pid, "undel", ObjectOperation().read(0, 0), snapid=s1)
    assert r.outdata(0)[:1200] == v1
    c.operate(pid, "undel", ObjectOperation().rollback(s1))
    assert c.operate(pid, "undel", ObjectOperation()
                     .read(0, 0)).outdata(0)[:1200] == v1


def test_writes_at_snap_rejected(cluster):
    c, pid = cluster
    c.operate(pid, "ro", ObjectOperation().write_full(b"w" * 600))
    s1 = c.create_pool_snap(pid, "rosnap")
    with pytest.raises(IOError) as ei:
        c.operate(pid, "ro", ObjectOperation().write_full(b"nope"),
                  snapid=s1)
    assert ei.value.errno == EROFS


def test_rollback_combined_with_write_rejected(cluster):
    c, pid = cluster
    c.operate(pid, "comb", ObjectOperation().write_full(b"z" * 600))
    s1 = c.create_pool_snap(pid, "c")
    c.operate(pid, "comb", ObjectOperation().write_full(b"zz" * 300))
    with pytest.raises(IOError):
        c.operate(pid, "comb", ObjectOperation().rollback(s1)
                  .write(0, b"no"))


def test_snap_trim_removes_clones(cluster):
    c, pid = cluster
    from ceph_tpu.backend.memstore import GObject
    c.operate(pid, "tr", ObjectOperation().write_full(b"t" * 900))
    s1 = c.create_pool_snap(pid, "doomed")
    c.operate(pid, "tr", ObjectOperation().write_full(b"u" * 900))
    g = c.pg_group(pid, "tr")
    cl = clone_oid("tr", s1)
    assert g.backend.local_shard.store.exists(
        GObject(cl, g.backend.whoami))
    c.remove_pool_snap(pid, "doomed")
    assert not g.backend.local_shard.store.exists(
        GObject(cl, g.backend.whoami))
    # the head's snapset no longer lists the trimmed clone
    r = c.operate(pid, "tr", ObjectOperation().list_snaps())
    assert r.outdata(0)["clones"] == []
    # head data untouched by the trim
    assert c.operate(pid, "tr", ObjectOperation()
                     .read(0, 0)).outdata(0)[:900] == b"u" * 900


def test_snap_read_degraded(cluster):
    """Snap reads reconstruct like any other read when a shard is down."""
    c, pid = cluster
    v1 = _data(4000, 5)
    c.operate(pid, "deg", ObjectOperation().write_full(v1))
    s1 = c.create_pool_snap(pid, "dsnap")
    c.operate(pid, "deg", ObjectOperation().write_full(b"new" * 100))
    g = c.pg_group(pid, "deg")
    victim = next(o for o in g.acting if o != g.backend.whoami)
    g.bus.mark_down(victim)
    try:
        r = c.operate(pid, "deg", ObjectOperation().read(0, 0), snapid=s1)
        assert r.outdata(0)[:4000] == v1
    finally:
        g.bus.mark_up(victim)


def test_snapshots_survive_restart(tmp_path):
    """Durable mode: snaps, clones, and snapsets reload with the stores."""
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512,
                    data_dir=tmp_path)
    pid = c.create_ec_pool("p", {"k": "2", "m": "1", "device": "numpy"},
                           pg_num=4)
    v1 = _data(1500, 6)
    c.operate(pid, "dur", ObjectOperation().write_full(v1))
    s1 = c.create_pool_snap(pid, "persist")
    c.operate(pid, "dur", ObjectOperation().write_full(b"head" * 100))
    c.shutdown()
    c2 = MiniCluster.load(tmp_path)
    pool = c2.pools[pid]["pool"]
    assert pool.snaps == {s1: "persist"}
    r = c2.operate(pid, "dur", ObjectOperation().read(0, 0), snapid=s1)
    assert r.outdata(0)[:1500] == v1
    assert c2.operate(pid, "dur", ObjectOperation()
                      .read(0, 0)).outdata(0)[:400] == b"head" * 100
    c2.shutdown()


def test_shared_clone_survives_newer_snap_removal(cluster):
    """A clone covering several snaps must survive removal of the newest
    one while an older snap still depends on it (regression: trim
    deleted any clone tagged with the removed id)."""
    c, pid = cluster
    v1 = _data(1100, 20)
    c.operate(pid, "sh", ObjectOperation().write_full(v1))
    s1 = c.create_pool_snap(pid, "old")
    s2 = c.create_pool_snap(pid, "new")
    # first write AFTER both snaps: ONE clone (tagged s2) covers s1 + s2
    c.operate(pid, "sh", ObjectOperation().write_full(b"head" * 100))
    c.remove_pool_snap(pid, "new")
    # snap s1 still resolves to the shared clone and reads v1
    r = c.operate(pid, "sh", ObjectOperation().read(0, 0), snapid=s1)
    assert r.outdata(0)[:1100] == v1
    # removing the LAST dependent snap finally trims it
    c.remove_pool_snap(pid, "old")
    assert c.operate(pid, "sh", ObjectOperation()
                     .list_snaps()).outdata(0)["clones"] == []


def test_rollback_after_newer_snap_keeps_fresh_clone(cluster):
    """rollback under a newer snap context COWs the pre-rollback head
    first; the fresh clone must stay recorded (regression: the rollback
    handler clobbered the snapset staged by make_writable)."""
    c, pid = cluster
    v1, v2 = _data(900, 21), _data(900, 22)
    c.operate(pid, "rc", ObjectOperation().write_full(v1))
    s1 = c.create_pool_snap(pid, "s1")
    c.operate(pid, "rc", ObjectOperation().write_full(v2))
    s2 = c.create_pool_snap(pid, "s2")
    c.operate(pid, "rc", ObjectOperation().rollback(s1))
    # head restored to v1; snap s2 still reads v2 via the fresh clone
    assert c.operate(pid, "rc", ObjectOperation()
                     .read(0, 0)).outdata(0)[:900] == v1
    r = c.operate(pid, "rc", ObjectOperation().read(0, 0), snapid=s2)
    assert r.outdata(0)[:900] == v2
    snaps = c.operate(pid, "rc", ObjectOperation().list_snaps()).outdata(0)
    assert [cl["snapid"] for cl in snaps["clones"]] == [s1, s2]


def test_read_at_precreation_snap_is_enoent(cluster):
    """An object created AFTER a snap must not exist at that snap
    (regression: the first write's content was backdated)."""
    c, pid = cluster
    s1 = c.create_pool_snap(pid, "early")
    c.operate(pid, "late", ObjectOperation().write_full(b"v1" * 200))
    c.operate(pid, "late", ObjectOperation().write_full(b"v2" * 200))
    with pytest.raises(IOError) as ei:
        c.operate(pid, "late", ObjectOperation().read(0, 0), snapid=s1)
    assert ei.value.errno == -2


def test_precreation_snap_enoent_even_with_later_clone(cluster):
    """A clone created by a post-creation overwrite must NOT cover snaps
    that predate the object's creation (regression: _resolve_snap checked
    clones before the snapset.seq guard, so any later clone resurrected
    pre-creation reads; reference SnapSet tracks per-clone clone_snaps)."""
    c, pid = cluster
    s1 = c.create_pool_snap(pid, "pre")         # snap BEFORE creation
    c.operate(pid, "lateclone", ObjectOperation().write_full(b"v1" * 300))
    c.create_pool_snap(pid, "post")             # snap AFTER creation
    c.operate(pid, "lateclone",                 # overwrite -> COW clone
              ObjectOperation().write_full(b"v2" * 300))
    with pytest.raises(IOError) as ei:
        c.operate(pid, "lateclone", ObjectOperation().read(0, 0), snapid=s1)
    assert ei.value.errno == -2


def test_precreation_snap_enoent_survives_head_deletion(cluster):
    """The per-clone lower bound must survive head deletion: clone
    rediscovery (the snapdir analog) harvests each clone's own recorded
    pre-COW seq (regression: rediscovery rebuilt lbs={} and the clone
    resurrected pre-creation reads)."""
    c, pid = cluster
    s1 = c.create_pool_snap(pid, "pre")
    c.operate(pid, "delhead", ObjectOperation().write_full(b"v1" * 300))
    c.create_pool_snap(pid, "post")
    c.operate(pid, "delhead", ObjectOperation().write_full(b"v2" * 300))
    c.operate(pid, "delhead", ObjectOperation().remove())
    with pytest.raises(IOError) as ei:
        c.operate(pid, "delhead", ObjectOperation().read(0, 0), snapid=s1)
    assert ei.value.errno == -2


def test_rollback_to_precreation_snap_deletes_head(cluster):
    """OP_ROLLBACK to a snap that predates creation removes the head even
    when a later clone exists (same lower-bound flaw as the read path)."""
    c, pid = cluster
    s1 = c.create_pool_snap(pid, "pre")
    c.operate(pid, "rbpre", ObjectOperation().write_full(b"v1" * 300))
    c.create_pool_snap(pid, "post")
    c.operate(pid, "rbpre", ObjectOperation().write_full(b"v2" * 300))
    c.operate(pid, "rbpre", ObjectOperation().rollback(s1))
    with pytest.raises(IOError) as ei:
        c.operate(pid, "rbpre", ObjectOperation().read(0, 0))
    assert ei.value.errno == -2


def test_legacy_put_respects_cow(cluster):
    """The whole-object put() API honors snapshots too (regression:
    it bypassed the op engine entirely)."""
    c, pid = cluster
    v1 = _data(1000, 23)
    c.put(pid, "lp", v1)
    s1 = c.create_pool_snap(pid, "lps")
    c.put(pid, "lp", _data(1000, 24))
    r = c.operate(pid, "lp", ObjectOperation().read(0, 0), snapid=s1)
    assert r.outdata(0)[:1000] == v1


def test_put_snap_path_surfaces_op_engine_error(cluster, monkeypatch):
    """put() through the snapshot op-engine path must raise on an error
    reply, not silently report the write as committed (regression: the
    completion callback ignored reply.result)."""
    import ceph_tpu.osd.primary_log_pg as plp
    c, pid = cluster
    c.create_pool_snap(pid, "s")          # snap_seq > 0: op-engine path
    orig = plp.PrimaryLogPG._do_one

    def failing(self, ctx, op, oi, readdata):
        if ctx.m.oid == "errput":
            raise plp.OpError(plp.EINVAL)
        return orig(self, ctx, op, oi, readdata)
    monkeypatch.setattr(plp.PrimaryLogPG, "_do_one", failing)
    with pytest.raises(IOError) as ei:
        c.put(pid, "errput", b"x" * 100)
    assert getattr(ei.value, "errno", None) == plp.EINVAL


def test_backfill_preserves_clones():
    """Snapshot clones move with their heads on remap (regression:
    backfill only moved bookkept head objects)."""
    from ceph_tpu.common import Context
    cct = Context(overrides={"mon_osd_down_out_interval": 60})
    c = MiniCluster(n_osds=12, osds_per_host=3, chunk_size=256, cct=cct)
    pid = c.create_ec_pool("bf", {"k": "2", "m": "1", "device": "numpy"},
                           pg_num=4)
    mon = c.attach_monitor()
    v1 = _data(800, 25)
    c.operate(pid, "snapped", ObjectOperation().write_full(v1))
    s1 = c.create_pool_snap(pid, "keep")
    c.operate(pid, "snapped", ObjectOperation().write_full(b"x" * 800))
    g = c.pg_group(pid, "snapped")
    victim = next(o for o in range(12)
                  if o not in {gg.backend.whoami
                               for gg in c.pools[pid]["pgs"].values()})
    reporters = [o for o in range(12) if o != victim][:4]
    for r in reporters:
        mon.prepare_failure(victim, r, 0.0, 25.0)
    mon.propose_pending(25.0)
    mon.tick(5000.0)                     # auto-out -> remap + backfill
    assert mon.osdmap.is_out(victim)
    r = c.operate(pid, "snapped", ObjectOperation().read(0, 0), snapid=s1)
    assert r.outdata(0)[:800] == v1      # clone survived the move
    c.shutdown()


def test_cow_survives_shard_death_via_log_repair():
    """A COW committed while a shard was down must reach that shard on
    revival through LOG repair — clones have their own log entries
    (regression: repair replayed only the head and the revived shard
    lost the clone forever; found by the soak campaign)."""
    from ceph_tpu.backend.memstore import GObject
    from ceph_tpu.backend.pg_backend import shard_store
    from ceph_tpu.osd.primary_log_pg import clone_oid
    c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
    pid = c.create_ec_pool("p", {"k": "2", "m": "2", "device": "numpy"},
                           pg_num=4)
    v1 = _data(1400, 40)
    c.operate(pid, "cowd", ObjectOperation().write_full(v1))
    g = c.pg_group(pid, "cowd")
    victim = next(o for o in g.acting if o != g.backend.whoami)
    g.bus.mark_down(victim)
    s1 = c.create_pool_snap(pid, "s")
    c.operate(pid, "cowd", ObjectOperation().write_full(b"n" * 1000))
    g.bus.mark_up(victim)
    g.bus.deliver_all()
    cl = clone_oid("cowd", s1)
    assert shard_store(g.bus, victim).exists(GObject(cl, victim)), \
        "revived shard missing the clone"
    # the snap reads clean even with OTHER shards down (needs the
    # revived shard's clone chunk)
    others = [o for o in g.acting if o not in (victim, g.backend.whoami)]
    g.bus.mark_down(others[0])
    try:
        r = c.operate(pid, "cowd", ObjectOperation().read(0, 0), snapid=s1)
        assert r.outdata(0)[:1400] == v1
    finally:
        g.bus.mark_up(others[0])
    c.remove_pool_snap(pid, "s")
    c.shutdown()


def test_cow_of_damaged_head_marks_clone_damaged():
    """The seed-113 chain, shrunk to its 5 essential beats: a shard
    misses writes; another shard rots silently; revival rebuilds the
    missed chunk from sources including the rot (detect-only -> head
    DAMAGED); the next write COWs that laundered state into a snapshot
    clone and wholesale-exonerates the head.  The clone must inherit
    the damage flag, or the snapshot serves corruption forever while
    every trace of the problem is erased."""
    import numpy as np
    from ceph_tpu.backend.memstore import GObject
    from ceph_tpu.backend.pg_backend import shard_store
    from ceph_tpu.osd.primary_log_pg import clone_oid
    c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
    pid = c.create_ec_pool("p", {"k": "2", "m": "2", "device": "numpy"},
                           pg_num=4)
    g = c.pg_group(pid, "obj")
    absent = g.acting[3]
    g.bus.mark_down(absent)                      # misses the writes
    c.operate(pid, "obj", ObjectOperation().write_full(b"a" * 1700))
    v = np.random.default_rng(0).integers(0, 256, 1187,
                                          np.uint8).tobytes()
    c.operate(pid, "obj", ObjectOperation().write_full(v))  # clears hashes
    s1 = c.create_pool_snap(pid, "s1")
    rot = g.acting[1]
    shard_store(g.bus, rot).objects[GObject("obj", rot)].data[0] ^= 0xFF
    g.bus.mark_up(absent)                        # rebuild from rotten set
    g.bus.deliver_all()
    assert "obj" in g.backend.inconsistent_objects   # detect-only damage
    c.operate(pid, "obj", ObjectOperation().write_full(b"fresh" * 300))
    # the wholesale write exonerates the HEAD...
    assert "obj" not in g.backend.inconsistent_objects
    # ...but the clone inherited the damage and stays pinned
    cl = clone_oid("obj", s1)
    assert cl in g.backend.inconsistent_objects
    rep = c.scrub_pool(pid)
    assert any(cl in b for b in rep.values())
    assert "OBJECT_DAMAGED" in c.health()["checks"]
    # operator retires the broken snapshot: snaptrim deletes the clone
    # AND its damage flag -> clean
    c.remove_pool_snap(pid, "s1")
    assert cl not in g.backend.inconsistent_objects
    assert c.scrub_pool(pid) == {}
    c.shutdown()


def test_rollback_carries_damage_both_directions():
    """Rollback replaces the head with the source's state INCLUDING its
    damage flag: restoring from a damaged clone flags the head (the COW
    laundering fix's mirror), restoring from a clean clone exonerates a
    damaged head (the operator's natural remediation)."""
    from ceph_tpu.osd.primary_log_pg import clone_oid
    c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
    pid = c.create_ec_pool("p", {"k": "2", "m": "2", "device": "numpy"},
                           pg_num=4)
    # clean clone, damaged head -> rollback exonerates
    c.operate(pid, "a", ObjectOperation().write_full(b"clean" * 200))
    s1 = c.create_pool_snap(pid, "s1")
    c.operate(pid, "a", ObjectOperation().write_full(b"x" * 900))
    g = c.pg_group(pid, "a")
    g.backend.inconsistent_objects.add("a")       # damage strikes the head
    c.operate(pid, "a", ObjectOperation().rollback(s1))
    assert "a" not in g.backend.inconsistent_objects
    assert c.operate(pid, "a", ObjectOperation()
                     .read(0, 0)).outdata(0)[:5] == b"clean"
    # damaged clone, clean head -> rollback flags the head
    g2 = c.pg_group(pid, "b")
    c.operate(pid, "b", ObjectOperation().write_full(b"v1" * 300))
    c.operate(pid, "b", ObjectOperation().write_full(b"v2" * 300))
    cl = clone_oid("b", c.create_pool_snap(pid, "s2"))
    c.operate(pid, "b", ObjectOperation().write_full(b"v3" * 300))
    g2.backend.inconsistent_objects.add(cl)       # the clone is damaged
    c.operate(pid, "b", ObjectOperation().rollback(
        next(s for s, n in c.pools[pid]["pool"].snaps.items()
             if n == "s2")))
    assert "b" in g2.backend.inconsistent_objects
    c.shutdown()
