"""PrimaryLogPG op engine: the do_osd_ops opcode switch.

Mirrors the reference's op-execution semantics
(src/osd/PrimaryLogPG.cc:5577 do_osd_ops; librados ObjectOperation):
atomic op vectors, errno-shaped failures, xattr/omap surfaces, object
classes — driven through MiniCluster.operate on both pool types.
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osd.osd_ops import (
    CMPXATTR_EQ, CMPXATTR_GT, ObjectOperation,
)
from ceph_tpu.osd.primary_log_pg import (
    ECANCELED, EEXIST, ENODATA, ENOENT, EOPNOTSUPP, MAX_ERRNO,
)


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=12, osds_per_host=3, chunk_size=512)
    ec = c.create_ec_pool("ecpool", {"k": "4", "m": "2", "device": "numpy"},
                          pg_num=4)
    rep = c.create_replicated_pool("reppool", size=3, pg_num=4)
    yield c, ec, rep
    c.shutdown()


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


@pytest.mark.parametrize("pool", ["ec", "rep"])
def test_write_read_roundtrip(cluster, pool):
    c, ec, rep = cluster
    pid = ec if pool == "ec" else rep
    payload = _data(5000, 1)
    c.operate(pid, f"rt-{pool}", ObjectOperation().write(0, payload))
    r = c.operate(pid, f"rt-{pool}", ObjectOperation().read(0, len(payload)))
    assert r.outdata(0) == payload
    # read with length 0 = read to end
    r = c.operate(pid, f"rt-{pool}", ObjectOperation().read(0, 0))
    assert r.outdata(0)[:5000] == payload


@pytest.mark.parametrize("pool", ["ec", "rep"])
def test_append_and_stat(cluster, pool):
    c, ec, rep = cluster
    pid = ec if pool == "ec" else rep
    oid = f"app-{pool}"
    c.operate(pid, oid, ObjectOperation().write_full(b"abc"))
    c.operate(pid, oid, ObjectOperation().append(b"defg"))
    r = c.operate(pid, oid, ObjectOperation().stat())
    size, mtime = r.outdata(0)
    assert size == 7
    r = c.operate(pid, oid, ObjectOperation().read(0, 7))
    assert r.outdata(0) == b"abcdefg"


def test_writefull_replaces(cluster):
    c, ec, _ = cluster
    c.operate(ec, "wf", ObjectOperation().write(0, _data(4000, 2)))
    c.operate(ec, "wf", ObjectOperation().write_full(b"short"))
    r = c.operate(ec, "wf", ObjectOperation().stat())
    assert r.outdata(0)[0] == 5
    assert c.operate(ec, "wf",
                     ObjectOperation().read(0, 0)).outdata(0)[:5] == b"short"


def test_zero_and_truncate(cluster):
    c, ec, _ = cluster
    c.operate(ec, "zt", ObjectOperation().write_full(b"x" * 100))
    c.operate(ec, "zt", ObjectOperation().zero(10, 20))
    r = c.operate(ec, "zt", ObjectOperation().read(0, 100))
    assert r.outdata(0)[:10] == b"x" * 10
    assert r.outdata(0)[10:30] == b"\0" * 20
    assert r.outdata(0)[30:100] == b"x" * 70
    # zero never extends
    c.operate(ec, "zt", ObjectOperation().zero(90, 1000))
    assert c.operate(ec, "zt", ObjectOperation().stat()).outdata(0)[0] == 100
    c.operate(ec, "zt", ObjectOperation().truncate(25))
    assert c.operate(ec, "zt", ObjectOperation().stat()).outdata(0)[0] == 25


def test_write_then_truncate_one_vector(cluster):
    c, _, rep = cluster
    c.operate(rep, "wt", ObjectOperation().write(0, b"A" * 100).truncate(10))
    r = c.operate(rep, "wt", ObjectOperation().read(0, 0))
    assert r.outdata(0) == b"A" * 10


def test_create_exclusive(cluster):
    c, ec, _ = cluster
    c.operate(ec, "cx", ObjectOperation().create(exclusive=True))
    with pytest.raises(IOError) as ei:
        c.operate(ec, "cx", ObjectOperation().create(exclusive=True))
    assert ei.value.errno == EEXIST
    c.operate(ec, "cx", ObjectOperation().create())      # non-excl ok


def test_delete(cluster):
    c, ec, _ = cluster
    c.operate(ec, "del", ObjectOperation().write_full(b"doomed"))
    c.operate(ec, "del", ObjectOperation().remove())
    with pytest.raises(IOError) as ei:
        c.operate(ec, "del", ObjectOperation().stat())
    assert ei.value.errno == ENOENT


@pytest.mark.parametrize("pool", ["ec", "rep"])
def test_xattrs(cluster, pool):
    c, ec, rep = cluster
    pid = ec if pool == "ec" else rep
    oid = f"xa-{pool}"
    c.operate(pid, oid, ObjectOperation()
              .write_full(b"body").setxattr("color", b"blue")
              .setxattr("n", b"3"))
    r = c.operate(pid, oid, ObjectOperation().getxattr("color"))
    assert r.outdata(0) == b"blue"
    r = c.operate(pid, oid, ObjectOperation().getxattrs())
    assert r.outdata(0) == {"color": b"blue", "n": b"3"}
    c.operate(pid, oid, ObjectOperation().rmxattr("color"))
    with pytest.raises(IOError) as ei:
        c.operate(pid, oid, ObjectOperation().getxattr("color"))
    assert ei.value.errno == ENODATA


def test_cmpxattr_guard(cluster):
    c, ec, _ = cluster
    c.operate(ec, "guard", ObjectOperation()
              .write_full(b"v1").setxattr("ver", b"1"))
    # passing guard: xattr==1 allows the write
    c.operate(ec, "guard", ObjectOperation()
              .cmpxattr("ver", CMPXATTR_EQ, b"1")
              .write_full(b"v2").setxattr("ver", b"2"))
    # failing guard aborts the WHOLE vector atomically
    with pytest.raises(IOError) as ei:
        c.operate(ec, "guard", ObjectOperation()
                  .cmpxattr("ver", CMPXATTR_EQ, b"1")
                  .write_full(b"v3"))
    assert ei.value.errno == ECANCELED
    assert c.operate(ec, "guard",
                     ObjectOperation().read(0, 0)).outdata(0)[:2] == b"v2"
    # u64 mode compares numerically
    c.operate(ec, "guard", ObjectOperation().setxattr("count", 7))
    c.operate(ec, "guard", ObjectOperation().cmpxattr(
        "count", CMPXATTR_GT, 5))


def test_cmpext(cluster):
    c, ec, _ = cluster
    c.operate(ec, "ce", ObjectOperation().write_full(b"hello world"))
    c.operate(ec, "ce", ObjectOperation().cmpext(0, b"hello"))
    with pytest.raises(IOError) as ei:
        c.operate(ec, "ce", ObjectOperation().cmpext(0, b"hellx"))
    # mismatch offset encoded the reference way: -(MAX_ERRNO + offset)
    assert ei.value.errno == -(MAX_ERRNO + 4)


def test_omap_replicated(cluster):
    c, _, rep = cluster
    oid = "om"
    c.operate(rep, oid, ObjectOperation()
              .omap_set({"b": b"2", "a": b"1", "ab": b"12"})
              .omap_set_header(b"HDR"))
    r = c.operate(rep, oid, ObjectOperation().omap_get_keys())
    assert r.outdata(0) == ["a", "ab", "b"]
    r = c.operate(rep, oid, ObjectOperation().omap_get_vals(
        start_after="a", filter_prefix="a"))
    assert r.outdata(0) == {"ab": b"12"}
    r = c.operate(rep, oid, ObjectOperation().omap_get_vals_by_keys(
        ["a", "zz"]))
    assert r.outdata(0) == {"a": b"1"}
    assert c.operate(rep, oid, ObjectOperation()
                     .omap_get_header()).outdata(0) == b"HDR"
    c.operate(rep, oid, ObjectOperation().omap_rm_keys(["a"]))
    assert c.operate(rep, oid, ObjectOperation()
                     .omap_get_keys()).outdata(0) == ["ab", "b"]
    # omap_cmp guard
    c.operate(rep, oid, ObjectOperation().omap_cmp(
        {"b": (b"2", CMPXATTR_EQ)}))
    with pytest.raises(IOError) as ei:
        c.operate(rep, oid, ObjectOperation()
                  .omap_cmp({"b": (b"9", CMPXATTR_EQ)})
                  .omap_set({"never": b"x"}))
    assert ei.value.errno == ECANCELED
    c.operate(rep, oid, ObjectOperation().omap_clear())
    assert c.operate(rep, oid, ObjectOperation()
                     .omap_get_keys()).outdata(0) == []
    assert c.operate(rep, oid, ObjectOperation()
                     .omap_get_header()).outdata(0) == b""


def test_omap_rejected_on_ec(cluster):
    c, ec, _ = cluster
    with pytest.raises(IOError) as ei:
        c.operate(ec, "omec", ObjectOperation().omap_set({"k": b"v"}))
    assert ei.value.errno == EOPNOTSUPP


def test_atomic_vector(cluster):
    c, ec, _ = cluster
    # second op fails -> first op's write must NOT be applied
    with pytest.raises(IOError):
        c.operate(ec, "atom", ObjectOperation()
                  .write_full(b"data").create(exclusive=False)
                  .getxattr("missing"))
    with pytest.raises(IOError) as ei:
        c.operate(ec, "atom", ObjectOperation().stat())
    assert ei.value.errno == ENOENT


def test_read_missing_object(cluster):
    c, ec, _ = cluster
    with pytest.raises(IOError) as ei:
        c.operate(ec, "ghost", ObjectOperation().read(0, 100))
    assert ei.value.errno == ENOENT


def test_degraded_read_through_engine(cluster):
    c, ec, _ = cluster
    payload = _data(6000, 3)
    g = c.operate(ec, "deg", ObjectOperation().write(0, payload))
    pg = c.pg_group(ec, "deg")
    victim = pg.acting[1]
    pg.bus.mark_down(victim)
    try:
        r = c.operate(ec, "deg", ObjectOperation().read(0, len(payload)))
        assert r.outdata(0) == payload       # reconstructed
    finally:
        pg.bus.mark_up(victim)


def test_cls_hello(cluster):
    c, ec, _ = cluster
    r = c.operate(ec, "obj-cls", ObjectOperation()
                  .call("hello", "say_hello", b"tpu"))
    assert r.outdata(0) == b"Hello, tpu!"
    c.operate(ec, "obj-cls", ObjectOperation()
              .call("hello", "record_hello", b"ceph"))
    r = c.operate(ec, "obj-cls", ObjectOperation().read(0, 0))
    assert r.outdata(0)[:12] == b"Hello, ceph!"
    assert c.operate(ec, "obj-cls", ObjectOperation()
                     .getxattr("recorded")).outdata(0) == b"1"
    with pytest.raises(IOError) as ei:
        c.operate(ec, "obj-cls", ObjectOperation().call("nope", "x"))
    assert ei.value.errno == EOPNOTSUPP


def test_mixed_read_write_vector_rules(cluster):
    c, ec, rep = cluster
    # EC: data read + write in one vector -> EINVAL
    c.operate(ec, "mix", ObjectOperation().write_full(b"0123456789"))
    with pytest.raises(IOError):
        c.operate(ec, "mix", ObjectOperation()
                  .read(0, 4).write(0, b"zz"))
    # replicated: allowed
    c.operate(rep, "mix", ObjectOperation().write_full(b"0123456789"))
    r = c.operate(rep, "mix", ObjectOperation().read(0, 4).write(4, b"ZZ"))
    assert r.outdata(0) == b"0123"
    assert c.operate(rep, "mix", ObjectOperation()
                     .read(0, 0)).outdata(0) == b"0123ZZ6789"
    # metadata reads inside a write vector work on EC too
    r = c.operate(ec, "mix", ObjectOperation()
                  .stat().write(10, b"more"))
    assert r.outdata(0)[0] == 10


def test_sparse_read(cluster):
    c, _, rep = cluster
    c.operate(rep, "sp", ObjectOperation().write_full(b"sparse-data"))
    r = c.operate(rep, "sp", ObjectOperation().sparse_read(2, 4))
    assert r.outdata(0) == {2: b"arse"}


def test_legacy_put_object_visible_to_engine(cluster):
    c, ec, _ = cluster
    payload = _data(3000, 4)
    c.put(ec, "legacy", payload)
    r = c.operate(ec, "legacy", ObjectOperation().stat())
    assert r.outdata(0)[0] >= 3000      # stripe-padded size, >= payload
    r = c.operate(ec, "legacy", ObjectOperation().read(0, 3000))
    assert r.outdata(0) == payload


def test_delete_recreate_keeps_new_attrs(cluster):
    """A remove+write+setxattr vector must land the new attrs on EC pools
    too (regression: the EC backend dropped attr_updates whenever
    delete_first was set)."""
    c, ec, _ = cluster
    c.operate(ec, "dr", ObjectOperation().write_full(b"old")
              .setxattr("gen", b"1"))
    c.operate(ec, "dr", ObjectOperation().remove().write(0, b"new")
              .setxattr("gen", b"2"))
    assert c.operate(ec, "dr", ObjectOperation()
                     .getxattr("gen")).outdata(0) == b"2"
    assert c.operate(ec, "dr", ObjectOperation()
                     .read(0, 0)).outdata(0)[:3] == b"new"


def test_empty_xattr_name_rejected(cluster):
    c, ec, _ = cluster
    c.operate(ec, "ean", ObjectOperation().create())
    for bad in (ObjectOperation().setxattr("", b"x"),
                ObjectOperation().getxattr(""),
                ObjectOperation().rmxattr("")):
        with pytest.raises(IOError) as ei:
            c.operate(ec, "ean", bad)
        assert ei.value.errno == -22


def test_user_xattr_named_version_survives(cluster):
    """'version' as a user xattr must not collide with the replicated
    backend's internal version attr (regression: both mapped to
    '_version')."""
    c, _, rep = cluster
    c.operate(rep, "vx", ObjectOperation().write_full(b"d")
              .setxattr("version", b"user-value"))
    c.operate(rep, "vx", ObjectOperation().append(b"2"))   # bumps internal
    assert c.operate(rep, "vx", ObjectOperation()
                     .getxattr("version")).outdata(0) == b"user-value"
    assert c.operate(rep, "vx", ObjectOperation()
                     .getxattrs()).outdata(0) == {"version": b"user-value"}


def test_delete_clears_object_listing(cluster):
    c, ec, _ = cluster
    c.operate(ec, "gone", ObjectOperation().write_full(b"x"))
    assert "gone" in c.objects[ec]
    c.operate(ec, "gone", ObjectOperation().remove())
    assert "gone" not in c.objects[ec]


def test_operate_deliver_false_batches(cluster):
    c, ec, _ = cluster
    g = c.pg_group(ec, "batch0")
    assert c.operate(ec, "batch0",
                     ObjectOperation().write_full(b"b0"),
                     deliver=False) is None
    d = c.osds[g.backend.whoami]
    d.drain()
    c.deliver_all()
    r = c.operate(ec, "batch0", ObjectOperation().read(0, 0))
    assert r.outdata(0)[:2] == b"b0"


def test_staged_delete_hides_attrs_in_vector(cluster):
    """After remove() in a vector, attr reads must see post-delete state
    (regression: they fell through to the committed store)."""
    c, ec, _ = cluster
    c.operate(ec, "sdel", ObjectOperation().write_full(b"x")
              .setxattr("a", b"1"))
    with pytest.raises(IOError) as ei:
        c.operate(ec, "sdel", ObjectOperation()
                  .remove().write(0, b"b").getxattr("a"))
    assert ei.value.errno == ENODATA
    # the failed vector aborted atomically: old object + attr intact
    assert c.operate(ec, "sdel", ObjectOperation()
                     .getxattr("a")).outdata(0) == b"1"
    assert c.operate(ec, "sdel", ObjectOperation()
                     .read(0, 0)).outdata(0)[:1] == b"x"
    # a cmpxattr guard after remove() must not pass against deleted attrs
    with pytest.raises(IOError) as ei:
        c.operate(ec, "sdel", ObjectOperation()
                  .remove().write(0, b"c")
                  .cmpxattr("a", CMPXATTR_EQ, b"1"))
    assert ei.value.errno in (ENODATA, ECANCELED)


def test_write_slot_taken_before_async_hop(cluster):
    """A second vector on the same object must queue the moment the first
    is accepted — even while the first is still mid-flight (regression:
    the slot was taken only after the async read hop)."""
    from ceph_tpu.osd.osd_ops import MOSDOp
    c, ec, _ = cluster
    c.operate(ec, "slot", ObjectOperation().write_full(b"v0"))
    g = c.pg_group(ec, "slot")
    replies = []
    m1 = MOSDOp(oid="slot", ops=ObjectOperation().write_full(b"v1").ops,
                epoch=g.epoch)
    m2 = MOSDOp(oid="slot", ops=ObjectOperation().write_full(b"v2").ops,
                epoch=g.epoch)
    g.engine.do_op(m1, lambda r: replies.append(("m1", r.result)))
    assert "slot" in g.engine._busy           # slot held immediately
    g.engine.do_op(m2, lambda r: replies.append(("m2", r.result)))
    assert len(g.engine._waiting.get("slot", ())) == 1   # m2 queued
    g.bus.deliver_all()
    assert [x[0] for x in replies] == ["m1", "m2"]       # ordered commits
    assert c.operate(ec, "slot", ObjectOperation()
                     .read(0, 0)).outdata(0)[:2] == b"v2"


class TestClsLock:
    """cls_lock: advisory object locks (src/cls/lock semantics)."""

    @staticmethod
    def _call(c, pid, oid, method, **req):
        import pickle
        from ceph_tpu.osd.osd_ops import ObjectOperation
        return c.operate(pid, oid, ObjectOperation().call(
            "lock", method, pickle.dumps(req) if req else b""))

    def test_exclusive_lock_lifecycle(self, cluster):
        c, ec, _ = cluster
        c.operate(ec, "lk", ObjectOperation().create())
        self._call(c, ec, "lk", "lock", name="l", cookie="A")
        # a second client is refused; the holder renews fine
        with pytest.raises(IOError) as ei:
            self._call(c, ec, "lk", "lock", name="l", cookie="B")
        assert ei.value.errno == -16              # EBUSY
        self._call(c, ec, "lk", "lock", name="l", cookie="A")
        info = self._call(c, ec, "lk", "get_info", name="l").outdata(0)
        assert info == {"type": "exclusive", "holders": ["A"]}
        self._call(c, ec, "lk", "unlock", name="l", cookie="A")
        self._call(c, ec, "lk", "lock", name="l", cookie="B")  # now free

    def test_shared_locks_and_break(self, cluster):
        c, ec, _ = cluster
        c.operate(ec, "sh", ObjectOperation().create())
        self._call(c, ec, "sh", "lock", name="s", cookie="A", type="shared")
        self._call(c, ec, "sh", "lock", name="s", cookie="B", type="shared")
        with pytest.raises(IOError):              # excl vs shared holders
            self._call(c, ec, "sh", "lock", name="s", cookie="C",
                       type="exclusive")
        info = self._call(c, ec, "sh", "get_info", name="s").outdata(0)
        assert info["holders"] == ["A", "B"]
        # A dies; another client breaks its lock
        self._call(c, ec, "sh", "break_lock", name="s", cookie="A")
        self._call(c, ec, "sh", "unlock", name="s", cookie="B")
        assert self._call(c, ec, "sh", "get_info").outdata(0) == {}

    def test_unlock_not_held(self, cluster):
        c, ec, _ = cluster
        c.operate(ec, "nh", ObjectOperation().create())
        with pytest.raises(IOError) as ei:
            self._call(c, ec, "nh", "unlock", name="x", cookie="Z")
        assert ei.value.errno == ENOENT

    def test_failed_vector_does_not_release_lock(self, cluster):
        """cls_lock mutations ride the transaction: an aborted vector
        must not release locks (regression: in-place xattr aliasing)."""
        import pickle
        c, ec, _ = cluster
        c.operate(ec, "lat", ObjectOperation().create())
        self._call(c, ec, "lat", "lock", name="l", cookie="A")
        with pytest.raises(IOError):
            c.operate(ec, "lat", ObjectOperation()
                      .call("lock", "unlock",
                            pickle.dumps({"name": "l", "cookie": "A"}))
                      .getxattr("missing"))
        info = self._call(c, ec, "lat", "get_info", name="l").outdata(0)
        assert info == {"type": "exclusive", "holders": ["A"]}

    def test_get_info_returns_copies(self, cluster):
        c, ec, _ = cluster
        c.operate(ec, "cp", ObjectOperation().create())
        self._call(c, ec, "cp", "lock", name="l", cookie="A")
        info = self._call(c, ec, "cp", "get_info", name="l").outdata(0)
        info["holders"].append("EVIL")      # must not corrupt the store
        again = self._call(c, ec, "cp", "get_info", name="l").outdata(0)
        assert again["holders"] == ["A"]

    def test_no_silent_type_upgrade(self, cluster):
        c, ec, _ = cluster
        c.operate(ec, "up", ObjectOperation().create())
        self._call(c, ec, "up", "lock", name="l", cookie="A", type="shared")
        with pytest.raises(IOError) as ei:    # upgrade attempt refused
            self._call(c, ec, "up", "lock", name="l", cookie="A",
                       type="exclusive")
        assert ei.value.errno == -16
        info = self._call(c, ec, "up", "get_info", name="l").outdata(0)
        assert info["type"] == "shared"
