"""TCP transport: a live cluster served over sockets with cephx auth and
HMAC-secured v2 frames (r4 VERDICT missing #4; reference:
src/msg/async/AsyncMessenger.h:74, ProtocolV2.cc, src/auth/cephx).
"""
import os
import pickle
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from ceph_tpu.net import ClusterServer, TcpRados


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()




def _spawn_server(tmp_path, env):
    """Start 'rados serve' and wait for its port + keyring, surfacing
    stderr on startup failure instead of hanging/IndexError."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "ceph_tpu.tools.rados_cli",
         "--data-dir", str(tmp_path), "serve", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env)
    import selectors
    sel = selectors.DefaultSelector()
    sel.register(proc.stdout, selectors.EVENT_READ)
    deadline = time.monotonic() + 60
    line = ""
    while time.monotonic() < deadline and not line:
        if sel.select(timeout=1.0):
            line = proc.stdout.readline()
        if proc.poll() is not None:
            raise AssertionError(
                f"serve died rc={proc.returncode}: {proc.stderr.read()}")
    assert "serving on" in line, f"no port line within 60s: {line!r}"
    port = int(line.rsplit(":", 1)[1])
    keyring = os.path.join(str(tmp_path), "client.admin.keyring")
    while not os.path.exists(keyring):
        assert time.monotonic() < deadline, "keyring never appeared"
        time.sleep(0.1)
    return proc, port, keyring


@pytest.fixture
def served(tmp_path):
    """An in-process served cluster (threaded server) + keyring path."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                    data_dir=tmp_path)
    server = ClusterServer(c)
    server.start()
    yield server, tmp_path / "client.admin.keyring"
    server.stop()
    c.shutdown()


class TestRpc:
    def test_put_get_roundtrip_secured(self, served):
        server, keyring = served
        r = TcpRados("127.0.0.1", server.port, keyring)
        r.mkpool("p", profile={"k": "2", "m": "1", "device": "numpy"})
        payload = _data(20000, 1)
        r.put("p", "obj", payload)
        assert r.get("p", "obj") == payload
        assert r.stat("p", "obj")[0] == len(payload)
        assert r.ls("p") == ["obj"]
        # frames after the handshake are HMAC mode (secret installed)
        assert r.ch.secret is not None
        r.setxattr("p", "obj", "k", b"v")
        assert r.getxattr("p", "obj", "k") == b"v"
        r.remove("p", "obj")
        with pytest.raises(IOError):
            r.get("p", "obj")
        r.close()

    def test_two_concurrent_clients(self, served):
        server, keyring = served
        a = TcpRados("127.0.0.1", server.port, keyring)
        b = TcpRados("127.0.0.1", server.port, keyring)
        a.mkpool("p", replicated=True, size=3)
        errs = []

        def worker(r, tag):
            try:
                for i in range(20):
                    r.put("p", f"{tag}{i}", _data(600 + i, i))
                for i in range(20):
                    assert r.get("p", f"{tag}{i}") == _data(600 + i, i)
            except Exception as e:            # noqa: BLE001
                errs.append(e)
        ta = threading.Thread(target=worker, args=(a, "a"))
        tb = threading.Thread(target=worker, args=(b, "b"))
        ta.start(), tb.start()
        ta.join(60), tb.join(60)
        assert not errs
        # each client sees the other's writes
        assert a.get("p", "b3") == _data(603, 3)
        assert b.get("p", "a7") == _data(607, 7)
        a.close(), b.close()

    def test_watch_notify_across_connections(self, served):
        """Client A watches; client B notifies; A's callback value rides
        the ack back to B — the cross-process watch/notify contract."""
        server, keyring = served
        a = TcpRados("127.0.0.1", server.port, keyring)
        b = TcpRados("127.0.0.1", server.port, keyring)
        a.mkpool("p", replicated=True, size=3)
        a.put("p", "watched", b"x")
        got = []

        def on_notify(notify_id, cookie, payload):
            got.append(bytes(payload))
            return b"seen:" + bytes(payload)
        a.watch("p", "watched", cookie=77, on_notify=on_notify)
        acks = b.notify("p", "watched", b"ping")
        assert got == [b"ping"]
        assert acks == {77: b"seen:ping"}
        a.unwatch("p", "watched", 77)
        assert b.notify("p", "watched", b"again") == {}
        a.close(), b.close()

    def test_wrong_key_rejected(self, served):
        server, keyring = served
        bad = keyring.parent / "bad.keyring"
        with open(keyring, "rb") as f:
            saved = pickle.load(f)
        saved["key"] = b"\x00" * 32
        with open(bad, "wb") as f:
            pickle.dump(saved, f)
        from ceph_tpu.auth.cephx import AuthError
        from ceph_tpu.backend.wire import WireError
        with pytest.raises((AuthError, WireError, ConnectionError,
                            IOError)):
            TcpRados("127.0.0.1", server.port, bad)


class TestTwoProcesses:
    def test_cli_server_process_and_concurrent_clients(self, tmp_path):
        """THE integration check: the cluster lives in another PROCESS
        (rados serve); this process runs two concurrent clients doing
        put/get + watch/notify over real sockets."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc, port, keyring = _spawn_server(tmp_path, env)
        try:
            a = TcpRados("127.0.0.1", port, keyring)
            b = TcpRados("127.0.0.1", port, keyring)
            a.mkpool("p", profile={"k": "2", "m": "1",
                                   "device": "numpy"})
            payload = _data(30000, 9)
            seen = []
            a.put("p", "obj", payload)
            a.watch("p", "obj", cookie=5,
                    on_notify=lambda nid, ck, pl: seen.append(bytes(pl))
                    or b"ok")
            assert b.get("p", "obj") == payload
            acks = b.notify("p", "obj", b"hello-from-b")
            assert seen == [b"hello-from-b"]
            assert acks == {5: b"ok"}
            # concurrent hammering from both clients
            errs = []

            def w(r, tag):
                try:
                    for i in range(10):
                        r.put("p", f"{tag}{i}", _data(800 + i, i))
                        assert r.get("p", f"{tag}{i}") == _data(800 + i, i)
                except Exception as e:        # noqa: BLE001
                    errs.append(e)
            ts = [threading.Thread(target=w, args=(a, "a")),
                  threading.Thread(target=w, args=(b, "b"))]
            [t.start() for t in ts]
            [t.join(60) for t in ts]
            assert not errs
            a.close(), b.close()
        finally:
            proc.terminate()
            proc.wait(timeout=30)

    def test_cli_connect_verbs(self, tmp_path):
        """rados --connect runs its verbs against the live server
        process: two processes sharing one cluster concurrently."""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc, port, keyring = _spawn_server(tmp_path, env)
        try:
            def cli(*argv, data=None):
                return subprocess.run(
                    [sys.executable, "-m", "ceph_tpu.tools.rados_cli",
                     "--connect", f"127.0.0.1:{port}",
                     "--keyring", keyring, *argv],
                    input=data, capture_output=True, env=env, timeout=120)
            r = cli("mkpool", "p", "replicated")
            assert r.returncode == 0, r.stderr
            r = cli("put", "p", "obj", "-", data=b"over-the-wire")
            assert r.returncode == 0, r.stderr
            r = cli("get", "p", "obj", "-")
            assert r.returncode == 0 and r.stdout == b"over-the-wire"
            r = cli("ls", "p")
            assert r.stdout.decode().split() == ["obj"]
            r = cli("df")
            assert b"pools" in r.stdout
        finally:
            proc.terminate()
            proc.wait(timeout=30)


class TestPreAuthHardening:
    def test_rpc_frames_rejected_before_auth(self, served):
        """A peer skipping cephx cannot reach the pickle decoder: RPC
        frames before authentication are refused at the codec (pre-auth
        unpickling of peer bytes would be remote code execution)."""
        import socket as socket_mod
        from ceph_tpu.backend.wire import BANNER, frame_encode
        server, _keyring = served
        sock = socket_mod.create_connection(("127.0.0.1", server.port))
        sock.recv(65536)                     # server banner
        evil = frame_encode(
            17, [b"RpcCall", pickle.dumps({"anything": 1})])
        sock.sendall(BANNER + evil)
        # the server drops the connection instead of unpickling
        sock.settimeout(10)
        assert sock.recv(65536) == b""
        sock.close()

    def test_keyring_has_no_rotating_secrets(self, served):
        """The client keyring carries ONLY the entity key; rotating
        service secrets stay server-side (a keyring holder must not be
        able to forge ticket blobs)."""
        _server, keyring = served
        with open(keyring, "rb") as f:
            saved = pickle.load(f)
        assert set(saved) == {"key"}


class TestCephCliRemote:
    def test_ceph_status_over_connect(self, tmp_path):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc, port, keyring = _spawn_server(tmp_path, env)
        try:
            r = subprocess.run(
                [sys.executable, "-m", "ceph_tpu.tools.ceph_cli",
                 "--connect", f"127.0.0.1:{port}", "--keyring", keyring,
                 "status"],
                capture_output=True, text=True, env=env, timeout=120)
            assert r.returncode == 0, r.stderr
            assert "health:" in r.stdout and "osds" in r.stdout
            r = subprocess.run(
                [sys.executable, "-m", "ceph_tpu.tools.ceph_cli",
                 "--connect", f"127.0.0.1:{port}", "--keyring", keyring,
                 "health"],
                capture_output=True, text=True, env=env, timeout=120)
            assert r.returncode == 0 and "HEALTH" in r.stdout
        finally:
            proc.terminate()
            proc.wait(timeout=30)
