"""Distributed tracing + device-time attribution (the ISSUE-6 tentpole).

Covers the acceptance surface:

- ONE client write on a MiniCluster produces ONE stitched multi-daemon
  trace: the primary's osd.op span and every remote shard's
  osd.ECSubWrite span share the client op's trace id, and the stitched
  Chrome export carries >= 3 daemon tracks;
- the trace context rides every hop: Objecter ops (client track),
  net.py RPC frames (TcpRados -> ClusterServer), ECSubRead/ECSubWrite
  payloads, and background work (recovery/scrub) gets its own owner
  class;
- per-class device-time accounting at the pipeline completion boundary
  sums to the pipeline busy time (within 5%) under a mixed
  serving+recovery load, is exported as
  ``ceph_tpu_device_time_seconds{class=...}``, and surfaces through the
  ``device top`` admin command;
- ``tools/trace_report.py --trace-id`` renders the cross-daemon tree.
"""
import json

import numpy as np
import pytest

from ceph_tpu.common import device_attribution
from ceph_tpu.common.tracer import TraceContext, default_tracer
from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osd.osd_ops import ObjectOperation


def _traced_events(doc):
    return [e for e in doc["traceEvents"] if e.get("ph") == "X"
            and e.get("args", {}).get("trace_id")]


def _tracks_of(doc, events):
    names = {m["pid"]: m["args"]["name"] for m in doc["traceEvents"]
             if m.get("ph") == "M" and m.get("name") == "process_name"}
    return {names.get(e["pid"]) for e in events}


class TestCrossDaemonStitching:
    def test_client_write_stitches_multi_daemon_trace(self):
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        tr = default_tracer()
        tr.reset()
        c.operate(pid, "obj", ObjectOperation().write_full(b"x" * 1700))
        doc = tr.dump(stitched=True)
        evs = _traced_events(doc)
        [root] = [e for e in evs if e["name"] == "osd.op"]
        tid = root["args"]["trace_id"]
        same = [e for e in evs if e["args"]["trace_id"] == tid]
        # the sub-writes crossed the daemon boundary under the SAME trace
        sub_writes = [e for e in same if e["name"] == "osd.ECSubWrite"]
        assert len(sub_writes) >= 3       # remote shards of a k2m2 PG
        # >= 3 daemons in one stitched Chrome trace (the acceptance bar)
        tracks = _tracks_of(doc, same)
        assert len([t for t in tracks
                    if t and t.startswith("osd.")]) >= 3, tracks
        # spans chain: every sub-write hangs under some span of the trace
        ids = {e["args"]["span_id"] for e in same}
        for e in sub_writes:
            assert e["args"]["parent_span_id"] in ids
        c.shutdown()

    def test_objecter_op_roots_the_trace_on_the_client_track(self):
        from ceph_tpu.client.objecter import Objecter
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        tr = default_tracer()
        tr.reset()
        Objecter(c).operate(pid, "oid1",
                            ObjectOperation().write_full(b"y" * 900))
        doc = tr.dump(stitched=True)
        evs = _traced_events(doc)
        client_ops = [e for e in evs if e["name"] == "client.op"]
        assert client_ops, "Objecter dispatch did not open client.op"
        tid = client_ops[0]["args"]["trace_id"]
        same = [e for e in evs if e["args"]["trace_id"] == tid]
        tracks = _tracks_of(doc, same)
        assert "client" in tracks
        # the op engine ran under the same trace on the primary's track
        assert any(e["name"] == "osd.op" for e in same)
        c.shutdown()

    def test_background_work_gets_its_owner_class(self):
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        c.operate(pid, "s1", ObjectOperation().write_full(b"z" * 1500))
        tr = default_tracer()
        tr.reset()
        c.scrub_pool(pid, repair=False)
        evs = _traced_events(tr.dump(stitched=False))
        scrubs = [e for e in evs if e["name"] == "osd.scrub"]
        assert scrubs and all(e["args"]["owner"] == "scrub"
                              for e in scrubs)
        c.shutdown()

    def test_trace_context_pickles_for_the_wire(self):
        import pickle
        ctx = TraceContext(7, 3, "recovery")
        again = pickle.loads(pickle.dumps(ctx))
        assert (again.trace_id, again.span_id, again.op_class) == \
            (7, 3, "recovery")


class TestNetTracePropagation:
    def test_rpc_trace_rides_the_frames(self, tmp_path):
        from ceph_tpu.net import ClusterServer, TcpRados
        c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                        data_dir=tmp_path)
        server = ClusterServer(c)
        server.start()
        tr = default_tracer()
        try:
            r = TcpRados("127.0.0.1", server.port,
                         tmp_path / "client.admin.keyring")
            r.mkpool("p", {"k": "2", "m": "2", "device": "numpy"})
            tr.reset()
            r.put("p", "obj", b"payload" * 100)
            # the server dispatched under the client's trace id: its
            # rpc.put span and the daemon fan-out below share one trace
            evs = _traced_events(tr.dump(stitched=False))
            rpcs = [e for e in evs if e["name"] == "rpc.put"]
            assert rpcs, "server did not adopt the RPC trace context"
            tid = rpcs[0]["args"]["trace_id"]
            same = [e for e in evs if e["args"]["trace_id"] == tid]
            assert any(e["name"] == "osd.op" for e in same)
            r.close()
        finally:
            server.stop()
            c.shutdown()


class TestDeviceAttribution:
    def _pipeline(self, depth=2, name="t.attr"):
        import jax.numpy as jnp
        from ceph_tpu.ops.pipeline import CodecPipeline
        pipe = CodecPipeline(depth=depth, name=name)

        def submit(owner=None, n=4096):
            data = np.random.default_rng(0).integers(
                0, 256, n, np.uint8)
            return pipe.submit(lambda: data,
                               lambda p: jnp.asarray(p).astype(jnp.int32)
                               .sum(),
                               lambda p, h: int(h), owner=owner)
        return pipe, submit

    def test_per_class_accounting_sums_to_busy_time(self):
        device_attribution.reset()
        pipe, submit = self._pipeline()
        try:
            for i in range(6):
                submit(owner="serving" if i % 2 else "recovery")
            pipe.flush()
        finally:
            pipe.close()
        snap = device_attribution.snapshot()
        assert set(snap["classes"]) == {"serving", "recovery"}
        total = sum(rec["device_s"] for rec in snap["classes"].values())
        assert snap["busy_s"] > 0
        # the acceptance invariant: per-class sum == busy time (5% slack)
        assert abs(total - snap["busy_s"]) <= 0.05 * snap["busy_s"]
        assert sum(rec["batches"] for rec in
                   snap["classes"].values()) == 6

    def test_mixed_serving_recovery_pipelines_share_the_ledger(self):
        """Two pipelines (a serving engine's and a recovery scheduler's)
        interleave on one device: the ledger's clamped accounting still
        satisfies the sum == busy invariant."""
        device_attribution.reset()
        p1, submit1 = self._pipeline(depth=3, name="t.serving")
        p2, submit2 = self._pipeline(depth=3, name="t.recovery")
        try:
            for _ in range(4):
                submit1(owner="serving")
                submit2(owner="recovery")
            p1.flush()
            p2.flush()
        finally:
            p1.close()
            p2.close()
        snap = device_attribution.snapshot()
        total = sum(rec["device_s"] for rec in snap["classes"].values())
        assert abs(total - snap["busy_s"]) <= 0.05 * max(snap["busy_s"],
                                                         1e-9)

    def test_owner_resolves_from_active_trace_context(self):
        device_attribution.reset()
        tr = default_tracer()
        pipe, submit = self._pipeline()
        try:
            with tr.activate(tr.new_trace("recovery")):
                fut = submit()          # no explicit owner
            pipe.flush()
            assert fut.owner == "recovery"
        finally:
            pipe.close()
        assert "recovery" in device_attribution.snapshot()["classes"]

    def test_prometheus_family_and_device_top(self):
        device_attribution.reset()
        pipe, submit = self._pipeline()
        try:
            submit(owner="serving")
            pipe.flush()
        finally:
            pipe.close()
        from ceph_tpu.mgr import prometheus
        text = prometheus.render()
        assert "# TYPE ceph_tpu_device_time_seconds counter" in text
        assert 'ceph_tpu_device_time_seconds{class="serving"}' in text
        assert 'ceph_tpu_device_time_seconds{class="_busy"}' in text
        # the admin command (registered by every Context)
        from ceph_tpu.common import default_context
        top = default_context().admin_socket.call("device top")
        assert top["busy_s"] > 0
        assert top["classes"][0]["class"] == "serving"
        assert top["classes"][0]["share_pct"] == 100.0

    def test_op_class_aliases_clamp_to_canonical(self):
        assert device_attribution.canonical_owner("bg_recovery") == \
            "recovery"
        assert device_attribution.canonical_owner("bg_snaptrim") == \
            "scrub"
        assert device_attribution.canonical_owner("backfill") == \
            "rebalance"
        assert device_attribution.canonical_owner(None) == "client"
        assert device_attribution.canonical_owner("martian") == "client"

    def test_traced_jit_folds_cost_analysis(self):
        device_attribution.reset()
        from ceph_tpu.ops.traced_jit import traced_jit

        @traced_jit(name="attr_cost_probe")
        def f(x):
            return x * 2 + 1
        f(np.arange(128, dtype=np.int32))
        execs = device_attribution.snapshot()["executables"]
        if "attr_cost_probe" not in execs:
            pytest.skip("cost_analysis unavailable on this backend")
        assert execs["attr_cost_probe"]["compiles"] == 1


class TestTraceReportTree:
    def test_trace_tree_renders_cross_daemon(self, tmp_path):
        import importlib.util
        from pathlib import Path
        path_py = Path(__file__).resolve().parent.parent / "tools" / \
            "trace_report.py"
        spec = importlib.util.spec_from_file_location("trace_report_t6",
                                                      path_py)
        trace_report = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(trace_report)
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        tr = default_tracer()
        tr.reset()
        c.operate(pid, "t1", ObjectOperation().write_full(b"q" * 1400))
        doc = tr.dump(stitched=True)
        path = tmp_path / "trace.json"
        path.write_text(json.dumps(doc))
        all_events = trace_report.load_doc(str(path))
        events = [e for e in all_events if e.get("ph") == "X"]
        [root] = [e for e in events if e["name"] == "osd.op"]
        tid = root["args"]["trace_id"]
        lines = trace_report.trace_tree(
            events, tid, trace_report._track_names(all_events))
        text = "\n".join(lines)
        assert "osd.op" in text and "osd.ECSubWrite" in text
        assert "@osd." in text
        listing = "\n".join(trace_report.list_traces(events))
        assert str(tid) in listing
        c.shutdown()
