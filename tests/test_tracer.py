"""Span tracer + traced_jit telemetry (common/tracer.py, ops/traced_jit.py).

Pins: span nesting on one tid, ring-buffer eviction, the Chrome
trace-event JSON schema (loads in chrome://tracing / Perfetto), traced_jit
compile accounting (one compilation per shape key, cache hits for repeats,
bypass under an enclosing jit), the slow-op threshold satellite, the
`trace dump`/`jit dump` admin commands after real EC backend traffic, and
the tools/trace_report.py self-time math.
"""
import importlib.util
import json
import pathlib

import numpy as np
import pytest

from ceph_tpu.common import Context
from ceph_tpu.common.optracker import OpTracker
from ceph_tpu.common.perf_counters import PerfCountersBuilder
from ceph_tpu.common.tracer import (Tracer, default_tracer, jit_dump,
                                    jit_perf_counters, trace_span)


class TestSpans:
    def test_nesting_same_thread(self):
        t = Tracer()
        with t.span("outer") as outer:
            assert t.depth() == 1
            assert t.current() is outer
            with t.span("inner") as inner:
                assert t.depth() == 2
        assert t.depth() == 0
        ev = {e["name"]: e for e in t.dump()["traceEvents"]}
        o, i = ev["outer"], ev["inner"]
        assert o["tid"] == i["tid"]
        # child contained in parent on the shared timeline
        assert i["ts"] >= o["ts"]
        assert i["ts"] + i["dur"] <= o["ts"] + o["dur"] + 1e-6
        assert inner.dur <= outer.dur

    def test_ring_buffer_eviction(self):
        t = Tracer(capacity=8)
        for n in range(20):
            with t.span(f"s{n}"):
                pass
        events = t.dump()["traceEvents"]
        assert len(events) == 8
        assert [e["name"] for e in events] == [f"s{n}" for n in range(12, 20)]

    def test_chrome_trace_event_schema(self):
        t = Tracer()
        with t.span("work", cat="test", items=3):
            pass
        t.instant("tick", note="hi")
        doc = t.dump()
        text = json.dumps(doc)                 # must be JSON-serializable
        doc = json.loads(text)
        assert doc["displayTimeUnit"] == "ms"
        kinds = {e["ph"] for e in doc["traceEvents"]}
        assert kinds == {"X", "i"}
        for e in doc["traceEvents"]:
            assert {"name", "ph", "ts", "pid", "tid"} <= set(e)
            assert e["ts"] >= 0
            if e["ph"] == "X":
                assert e["dur"] >= 0
        ev = {e["name"]: e for e in doc["traceEvents"]}
        assert ev["work"]["args"]["items"] == 3
        assert ev["tick"]["s"] == "t"

    def test_reset_and_histograms(self):
        t = Tracer()
        with t.span("h"):
            pass
        h = t.histograms()["h"]
        assert h["count"] == 1
        assert sum(h["counts"]) == 1
        assert h["sum"] >= 0
        assert len(h["counts"]) == len(h["buckets"]) + 1
        t.reset()
        assert t.dump()["traceEvents"] == []
        assert t.histograms() == {}


class TestTracedJit:
    def test_compile_per_shape_and_cache_hits(self):
        import jax.numpy as jnp
        from ceph_tpu.ops.traced_jit import traced_jit

        @traced_jit(name="tj_test_add")
        def add1(a):
            return a + jnp.uint8(1)

        x4 = np.zeros(4, dtype=np.uint8)
        for _ in range(3):
            np.testing.assert_array_equal(np.asarray(add1(x4)),
                                          np.ones(4, np.uint8))
        x8 = np.zeros(8, dtype=np.uint8)
        np.testing.assert_array_equal(np.asarray(add1(x8)),
                                      np.ones(8, np.uint8))
        entries = [e for e in jit_dump()["functions"]
                   if e["function"] == "tj_test_add"]
        assert len(entries) == 2               # one compilation per shape
        by_calls = sorted(e["calls"] for e in entries)
        assert by_calls == [1, 3]
        for e in entries:
            assert e["compiles"] == 1
            assert e["compile_s"] >= 0

    def test_bypass_under_enclosing_jit(self):
        import jax
        import jax.numpy as jnp
        from ceph_tpu.ops.traced_jit import traced_jit

        @traced_jit(name="tj_test_inner")
        def inner(a):
            return a * jnp.uint8(2)

        out = jax.jit(lambda a: inner(a) + jnp.uint8(1))(
            jnp.full((4,), 3, jnp.uint8))
        np.testing.assert_array_equal(np.asarray(out),
                                      np.full(4, 7, np.uint8))
        # the traced call inlined: no telemetry entry for it
        assert not [e for e in jit_dump()["functions"]
                    if e["function"] == "tj_test_inner"]

    def test_repeated_same_shape_encode_compiles_once(self):
        """The acceptance-criteria probe: repeated same-shape encodes show
        exactly ONE compilation for the kernel in the jit perf dump."""
        from ceph_tpu.ops import RSCodec

        codec = RSCodec(4, 2, technique="reed_sol_van", device="jax")
        rng = np.random.default_rng(0)
        data = rng.integers(0, 256, size=(4, 736), dtype=np.uint8)
        p1 = codec.encode(data)
        p2 = codec.encode(data)
        np.testing.assert_array_equal(p1, p2)
        entries = [e for e in jit_dump()["functions"]
                   if e["function"] == "gf_apply_bitslice"
                   and "(4, 736)" in e["key"]]
        assert len(entries) == 1               # exactly one compilation
        assert entries[0]["compiles"] == 1
        assert entries[0]["calls"] >= 2        # the repeat was a cache hit
        counters = jit_perf_counters().dump()
        assert counters["compilations"] >= 1
        assert counters["cache_hits"] >= 1
        assert counters["compile_time"]["avgcount"] >= 1


class TestSlowOps:
    def _perf(self):
        return (PerfCountersBuilder("slowtest")
                .add_u64_counter("slow_ops", "slow ops")
                .create_perf_counters())

    def test_threshold_marks_counts_and_dumps(self):
        perf = self._perf()
        tr = OpTracker(complaint_time=0.0, perf=perf)
        tr.create_request("write slowpoke").finish()
        assert perf.get("slow_ops") == 1
        hist = tr.dump_historic_ops()
        assert hist["ops"][0]["slow"] is True
        slow = tr.dump_historic_slow_ops()
        assert slow["num_ops"] == 1
        assert slow["ops"][0]["description"] == "write slowpoke"

    def test_fast_op_not_marked(self):
        perf = self._perf()
        tr = OpTracker(complaint_time=30.0, perf=perf)
        tr.create_request("write quick").finish()
        assert perf.get("slow_ops") == 0
        assert tr.dump_historic_ops()["ops"][0]["slow"] is False
        assert tr.dump_historic_slow_ops()["num_ops"] == 0

    def test_configured_via_options_with_live_update(self):
        cct = Context()
        tr = OpTracker(conf=cct.conf, perf=self._perf())
        assert tr.complaint_time == 30.0       # osd_op_complaint_time default
        cct.conf.set("osd_op_complaint_time", 0.25)
        assert tr.complaint_time == 0.25       # observer fired


class TestAdminSocketSurface:
    def test_trace_dump_contains_encode_decode_after_write_read(self):
        from ceph_tpu.backend import PGTransaction, make_cluster
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry

        default_tracer().reset()
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {"k": "2", "m": "1", "device": "numpy",
                           "technique": "reed_sol_van"})
        cct = Context()
        backend, bus = make_cluster(ec, chunk_size=128, cct=cct)
        data = np.arange(2 * 128, dtype=np.uint8).tobytes()
        backend.submit_transaction(PGTransaction().write("o", 0, data))
        bus.deliver_all()
        got = {}
        backend.objects_read_and_reconstruct(
            {"o": [(0, len(data))]},
            lambda result, errors: got.update(result))
        bus.deliver_all()
        assert got["o"][0][2] == data
        doc = json.loads(cct.admin_socket.call_json("trace dump"))
        names = {e["name"] for e in doc["traceEvents"]}
        assert "ec.encode" in names
        assert "ec.decode" in names
        assert "pg.generate_transactions" in names
        assert any(n.startswith("op.") for n in names)   # TrackedOp events
        # reset drops everything
        cct.admin_socket.call("trace reset")
        doc = json.loads(cct.admin_socket.call_json("trace dump"))
        assert doc["traceEvents"] == []

    def test_jit_dump_and_reset_commands(self):
        cct = Context()
        dump = cct.admin_socket.call("jit dump")
        assert set(dump) == {"functions", "num_keys", "counters"}
        assert dump["num_keys"] == len(dump["functions"])
        assert "success" in cct.admin_socket.call("jit reset")
        assert cct.admin_socket.call("jit dump")["num_keys"] == 0


class TestTraceReportTool:
    def _tool(self):
        path = pathlib.Path(__file__).resolve().parent.parent / \
            "tools" / "trace_report.py"
        spec = importlib.util.spec_from_file_location("trace_report", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_self_time_excludes_children(self, tmp_path):
        mod = self._tool()
        events = [
            {"name": "parent", "ph": "X", "ts": 0.0, "dur": 100.0,
             "pid": 1, "tid": 1},
            {"name": "child", "ph": "X", "ts": 10.0, "dur": 30.0,
             "pid": 1, "tid": 1},
            {"name": "child", "ph": "X", "ts": 50.0, "dur": 20.0,
             "pid": 1, "tid": 1},
            # another tid: independent track, no cross-charging
            {"name": "other", "ph": "X", "ts": 0.0, "dur": 5.0,
             "pid": 1, "tid": 2},
        ]
        f = tmp_path / "trace.json"
        f.write_text(json.dumps({"traceEvents": events}))
        agg = mod.self_times(mod.load_events(str(f)))
        assert agg["parent"]["total_us"] == 100.0
        assert agg["parent"]["self_us"] == 50.0       # minus both children
        assert agg["child"]["count"] == 2
        assert agg["child"]["self_us"] == 50.0
        assert agg["other"]["self_us"] == 5.0
        table = mod.render_table(agg)
        assert table.splitlines()[1].startswith(("parent", "child"))

    def test_cli_renders_a_real_dump(self, tmp_path, capsys):
        mod = self._tool()
        t = Tracer()
        with t.span("outer"):
            with t.span("inner"):
                pass
        f = tmp_path / "dump.json"
        f.write_text(json.dumps(t.dump()))
        assert mod.main([str(f)]) == 0
        out = capsys.readouterr().out
        assert "outer" in out and "inner" in out and "self ms" in out
        assert "p50 ms" in out and "p99 ms" in out

    def test_percentile_columns(self, tmp_path):
        """p50/p99 over each span name's per-occurrence durations (the
        serving-latency view): 100 spans of 1..100us -> p50=50, p99=99."""
        mod = self._tool()
        events = [{"name": "op", "ph": "X", "ts": float(i * 1000),
                   "dur": float(i + 1), "pid": 1, "tid": 1}
                  for i in range(100)]
        f = tmp_path / "trace.json"
        f.write_text(json.dumps({"traceEvents": events}))
        agg = mod.self_times(mod.load_events(str(f)))
        assert mod.percentile_us(agg["op"]["durs_us"], 50) == 50.0
        assert mod.percentile_us(agg["op"]["durs_us"], 99) == 99.0
        assert mod.percentile_us(agg["op"]["durs_us"], 100) == 100.0
        assert mod.percentile_us([], 50) == 0.0
        table = mod.render_table(agg)
        header, row = table.splitlines()[:2]
        assert "p50 ms" in header and "p99 ms" in header
        assert "0.050" in row and "0.099" in row
