"""Write-availability semantics: min_size gate + two-phase rollback.

Mirrors the reference's EC write durability contract (reference:
doc/dev/osd_internals/erasure_coding/ecbackend.rst:149-206 and the
dummy-transaction rollforward kick at src/osd/ECBackend.cc:2106-2120):

- a write is never acked with fewer than min_size current shards holding it;
- below min_size the PG goes inactive and client writes park, unacked;
- a write that partially applied before shards died ROLLS BACK on the
  survivors (log rewind + inverse transactions), so the old data remains
  the authoritative state;
- once the pipeline drains, the roll-forward point propagates and shards
  drop their rollback data;
- a revived shard is stale (no reads, no write fan-out) until a shard
  repair completes — the PeeringState acting-set semantics.
"""
import numpy as np
import pytest

from ceph_tpu.backend import ECBackend, MessageBus, PGTransaction, StripeInfo
from ceph_tpu.backend.ec_backend import OSDShard, RepairState
from ceph_tpu.backend.memstore import GObject
from ceph_tpu.plugins.registry import ErasureCodePluginRegistry

K, M = 4, 2
N = K + M
CHUNK = 64
STRIPE = K * CHUNK
MIN_SIZE = K + 1


def payload(n, seed=7):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


@pytest.fixture()
def cluster():
    ec = ErasureCodePluginRegistry.instance().factory(
        "jax_rs", "", {"k": str(K), "m": str(M), "device": "numpy",
                       "technique": "reed_sol_van"})
    bus = MessageBus()
    backend = ECBackend(ec, StripeInfo(K, CHUNK), bus,
                        acting=list(range(N)), whoami=0, min_size=MIN_SIZE)
    for s in range(1, N):
        OSDShard(s, bus)
    return backend, bus


def store_of(bus, backend, shard):
    h = bus.handlers[shard]
    return h.store if isinstance(h, OSDShard) else h.local_shard.store


def shard_obj(bus, backend, shard):
    h = bus.handlers[shard]
    return h if isinstance(h, OSDShard) else h.local_shard


def read_obj(backend, bus, oid, length):
    out = {}
    backend.objects_read_and_reconstruct(
        {oid: [(0, length)]},
        lambda result, errors: out.update(result=result, errors=errors))
    bus.deliver_all()
    if out.get("errors"):
        raise IOError(out["errors"])
    return out["result"][oid][0][2]


class TestMinSizeGate:
    def test_write_parks_below_min_size(self, cluster):
        backend, bus = cluster
        committed = []
        for s in (4, 5):
            bus.mark_down(s)          # current = 4 = k < min_size
        assert not backend.is_active()
        backend.submit_transaction(
            PGTransaction().write("obj", 0, payload(STRIPE)),
            on_commit=committed.append)
        bus.deliver_all()
        assert not committed, "write acked while PG inactive"
        assert len(backend.waiting_state) == 1
        # nothing was dispatched: no shard holds any data
        for s in range(N):
            assert not store_of(bus, backend, s).objects

    def test_parked_write_commits_after_revive_and_repair(self, cluster):
        backend, bus = cluster
        committed = []
        for s in (4, 5):
            bus.mark_down(s)
        backend.submit_transaction(
            PGTransaction().write("obj", 0, payload(STRIPE)),
            on_commit=committed.append)
        bus.deliver_all()
        assert not committed
        bus.mark_up(4)                # auto-repair -> current back to 5
        bus.deliver_all()
        assert committed, "parked write did not re-drive on revival"
        assert read_obj(backend, bus, "obj", STRIPE) == payload(STRIPE)

    def test_active_write_acks_normally(self, cluster):
        backend, bus = cluster
        committed = []
        bus.mark_down(5)              # current = 5 = min_size: still active
        backend.submit_transaction(
            PGTransaction().write("obj", 0, payload(STRIPE)),
            on_commit=committed.append)
        bus.deliver_all()
        assert committed


class TestRollback:
    def _commit_initial(self, backend, bus, data):
        done = []
        backend.submit_transaction(
            PGTransaction().write("obj", 0, data), on_commit=done.append)
        bus.deliver_all()
        assert done
        return done

    def test_partial_write_rolls_back_on_survivors(self, cluster):
        backend, bus = cluster
        data1 = payload(STRIPE, seed=1)
        data2 = payload(STRIPE, seed=2)
        self._commit_initial(backend, bus, data1)
        old_chunks = {s: store_of(bus, backend, s).read(GObject("obj", s))
                      for s in range(N)}

        committed = []
        backend.submit_transaction(
            PGTransaction().write("obj", 0, data2),
            on_commit=committed.append)
        # deliver the sub-writes to shards 1 and 2 only: they APPLY data2
        while bus.deliver_one(1) or bus.deliver_one(2):
            pass
        assert store_of(bus, backend, 1).read(GObject("obj", 1)) != \
            old_chunks[1]
        # shards 3 and 4 die with their sub-writes undelivered:
        # live acks can only reach 4 < min_size 5
        bus.mark_down(3)
        bus.mark_down(4)
        bus.deliver_all()
        assert not committed, "write acked below min_size"
        # survivors rolled back to data1's chunks
        for s in (0, 1, 2, 5):
            assert store_of(bus, backend, s).read(GObject("obj", s)) == \
                old_chunks[s], f"shard {s} kept rolled-back bytes"
        # the authoritative content is still data1
        assert read_obj(backend, bus, "obj", STRIPE) == data1
        # the op is parked, not lost
        assert len(backend.waiting_state) == 1

    def test_rolled_back_write_reexecutes_after_revival(self, cluster):
        backend, bus = cluster
        data1 = payload(STRIPE, seed=1)
        data2 = payload(STRIPE, seed=2)
        self._commit_initial(backend, bus, data1)
        committed = []
        backend.submit_transaction(
            PGTransaction().write("obj", 0, data2),
            on_commit=committed.append)
        while bus.deliver_one(1) or bus.deliver_one(2):
            pass
        bus.mark_down(3)
        bus.mark_down(4)
        bus.deliver_all()
        assert not committed
        bus.mark_up(3)                # repair -> active -> re-execute
        bus.deliver_all()
        assert committed, "rolled-back write did not re-execute"
        assert read_obj(backend, bus, "obj", STRIPE) == data2
        # version reuse is clean: log head advanced exactly once per write
        assert backend.pg_log.head == 2

    def test_rollback_restores_log_and_hinfo(self, cluster):
        backend, bus = cluster
        data1 = payload(STRIPE, seed=1)
        self._commit_initial(backend, bus, data1)
        head_before = backend.pg_log.head
        hinfo_version = backend._hinfo("obj").version
        committed = []
        backend.submit_transaction(
            PGTransaction().write("obj", 0, payload(STRIPE, seed=2)),
            on_commit=committed.append)
        while bus.deliver_one(1):
            pass
        bus.mark_down(3)
        bus.mark_down(4)
        bus.deliver_all()
        assert backend.pg_log.head == head_before
        assert backend._hinfo("obj").version == hinfo_version

    def test_roll_forward_drops_rollback_data(self, cluster):
        backend, bus = cluster
        self._commit_initial(backend, bus, payload(STRIPE))
        # commit + drain: the rollforward kick must reach every shard
        for s in range(N):
            assert not shard_obj(bus, backend, s).pending_rollbacks, \
                f"shard {s} still holds rollback data after drain"

    def test_deep_scrub_clean_after_rollback_cycle(self, cluster):
        backend, bus = cluster
        data1 = payload(STRIPE, seed=1)
        data2 = payload(STRIPE, seed=2)
        self._commit_initial(backend, bus, data1)
        committed = []
        backend.submit_transaction(
            PGTransaction().write("obj", 0, data2),
            on_commit=committed.append)
        while bus.deliver_one(1) or bus.deliver_one(2):
            pass
        bus.mark_down(3)
        bus.mark_down(4)
        bus.deliver_all()              # rollback
        bus.mark_up(3)
        bus.deliver_all()              # repair + re-execute
        bus.mark_up(4)
        bus.deliver_all()              # repair shard 4 (missed data2)
        assert committed
        report = backend.be_deep_scrub("obj")
        bad = {c for c, clean in report.items() if not clean}
        assert not bad, f"inconsistent chunks after rollback cycle: {bad}"


class TestStaleShards:
    def test_revived_shard_excluded_until_repaired(self, cluster):
        backend, bus = cluster
        data = payload(STRIPE)
        done = []
        backend.submit_transaction(PGTransaction().write("obj", 0, data),
                                   on_commit=done.append)
        bus.deliver_all()
        bus.mark_down(5)
        # a write lands while 5 is down
        backend.submit_transaction(
            PGTransaction().write("obj", 0, payload(STRIPE, seed=9)))
        bus.deliver_all()
        bus.mark_up(5)
        assert 5 in backend.stale
        assert 5 not in backend.current_shards()
        bus.deliver_all()              # auto-repair replays the missed write
        assert 5 not in backend.stale
        assert 5 in backend.current_shards()
        report = backend.be_deep_scrub("obj")
        assert all(report.values())

    def test_stale_shard_not_in_write_fanout(self, cluster):
        backend, bus = cluster
        bus.mark_down(5)
        bus.mark_up(5)                 # up but stale (repair still queued)
        committed = []
        backend.submit_transaction(
            PGTransaction().write("obj", 0, payload(STRIPE)),
            on_commit=committed.append)
        # dispatch happened at submit; shard 5 must not have a sub-write
        from ceph_tpu.backend.messages import ECSubWrite
        assert not any(isinstance(m, ECSubWrite) and m.log_entries
                       for m in bus.queues.get(5, ())), \
            "stale shard received new-write fan-out"
        bus.deliver_all()
        assert committed
