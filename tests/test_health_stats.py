"""Health engine + stats aggregation + flight recorder (ISSUE 3).

Reference analogs: the mon's named health-check registry
(src/mon/health_check.h, 'ceph health mute'), MgrStatMonitor/PGMap rate
digests (src/mon/PGMap.cc overall_client_io_rate_summary), and the
crash-dump discipline of keeping ring buffers so incident state is
captured at the moment of transition.
"""
import json

import numpy as np
import pytest

from ceph_tpu.common import Context, PerfCountersBuilder
from ceph_tpu.common.flight_recorder import FlightRecorder
from ceph_tpu.mgr.health import (CheckResult, HEALTH_ERR, HEALTH_OK,
                                 HEALTH_WARN, HealthCheckEngine,
                                 recompile_storm_check,
                                 throttle_saturated_check)
from ceph_tpu.mgr.stats import StatsAggregator


class TestHealthCheckEngine:
    def test_register_raise_clear(self):
        eng = HealthCheckEngine(name="t")
        state = {"bad": False}
        eng.register("MY_CHECK", lambda: "2 things bad"
                     if state["bad"] else None)
        try:
            ev = eng.evaluate()
            assert ev["status"] == HEALTH_OK and ev["checks"] == {}
            state["bad"] = True
            ev = eng.evaluate()
            assert ev["status"] == HEALTH_WARN
            assert ev["checks"]["MY_CHECK"]["summary"] == "2 things bad"
            assert ev["checks"]["MY_CHECK"]["severity"] == HEALTH_WARN
            state["bad"] = False
            assert eng.evaluate()["status"] == HEALTH_OK
        finally:
            eng.close()

    def test_severity_default_and_escalation(self):
        eng = HealthCheckEngine(name="t")
        sev = {"v": None}
        eng.register("ESC", lambda: CheckResult("bad", severity=sev["v"])
                     if sev["v"] or sev["v"] is False else None,
                     severity=HEALTH_WARN)
        eng.register("FATAL", lambda: "down", severity=HEALTH_ERR)
        try:
            ev = eng.evaluate()
            assert ev["status"] == HEALTH_ERR          # FATAL dominates
            assert ev["checks"]["FATAL"]["severity"] == HEALTH_ERR
            # a CheckResult severity override escalates past the default
            sev["v"] = HEALTH_ERR
            ev = eng.evaluate()
            assert ev["checks"]["ESC"]["severity"] == HEALTH_ERR
        finally:
            eng.close()

    def test_mute_excludes_from_status(self):
        eng = HealthCheckEngine(name="t")
        eng.register("NOISY", lambda: "flapping")
        try:
            assert eng.evaluate()["status"] == HEALTH_WARN
            eng.mute("NOISY")
            ev = eng.evaluate()
            assert ev["status"] == HEALTH_OK
            assert ev["checks"]["NOISY"]["muted"] is True
            assert ev["muted"] == ["NOISY"]
            eng.unmute("NOISY")
            assert eng.evaluate()["status"] == HEALTH_WARN
            # muting an unknown key is lenient (persisted mutes may
            # predate check registration)
            eng.mute("NOT_A_CHECK")
            assert "NOT_A_CHECK" in eng.muted
        finally:
            eng.close()

    def test_transitions_fire_once_per_raise(self):
        fired = []
        eng = HealthCheckEngine(
            name="t",
            on_transition=lambda k, info, ev: fired.append(
                (k, info["severity"])))
        state = {"sev": None}
        eng.register("T", lambda: CheckResult("bad", severity=state["sev"])
                     if state["sev"] else None)
        try:
            eng.evaluate()
            assert fired == []
            state["sev"] = HEALTH_WARN
            eng.evaluate()
            eng.evaluate()              # steady state: no re-fire
            assert fired == [("T", HEALTH_WARN)]
            state["sev"] = HEALTH_ERR   # escalation fires again
            eng.evaluate()
            assert fired[-1] == ("T", HEALTH_ERR)
            state["sev"] = None         # clear, then re-raise fires
            eng.evaluate()
            state["sev"] = HEALTH_WARN
            eng.evaluate()
            assert fired[-1] == ("T", HEALTH_WARN)
            assert len(fired) == 3
        finally:
            eng.close()

    def test_muted_check_does_not_fire_transitions(self):
        """A flapping MUTED check must not trip the flight-recorder
        hook (regression: each flap of a muted key evicted real
        incident bundles from the capacity-bounded ring)."""
        fired = []
        eng = HealthCheckEngine(
            name="t", on_transition=lambda k, i, e: fired.append(k))
        state = {"bad": False}
        eng.register("NOISY", lambda: "flap" if state["bad"] else None)
        try:
            eng.mute("NOISY")
            for _ in range(3):                  # flap down/up/down
                state["bad"] = True
                eng.evaluate()
                state["bad"] = False
                eng.evaluate()
            assert fired == []
            # unmuted raises DO fire
            eng.unmute("NOISY")
            state["bad"] = True
            eng.evaluate()
            assert fired == ["NOISY"]
        finally:
            eng.close()

    def test_broken_check_degrades_to_warn(self):
        eng = HealthCheckEngine(name="t")
        eng.register("BROKEN", lambda: 1 / 0)
        try:
            ev = eng.evaluate()
            assert ev["status"] == HEALTH_WARN
            assert "raised" in ev["checks"]["BROKEN"]["summary"]
        finally:
            eng.close()

    def test_severity_gauges_cover_all_registered(self):
        eng = HealthCheckEngine(name="t")
        eng.register("A_OK", lambda: None)
        eng.register("B_BAD", lambda: "x", severity=HEALTH_ERR)
        try:
            assert eng.severity_gauges() == {"A_OK": 0, "B_BAD": 2}
            # a MUTED check exports 0: prometheus alerting must fall
            # silent with the status line, or the pager defeats the mute
            eng.mute("B_BAD")
            assert eng.severity_gauges() == {"A_OK": 0, "B_BAD": 0}
        finally:
            eng.close()


class TestStatsAggregator:
    def _cct_with_counter(self, coll="ec_backend.test"):
        cct = Context()
        pc = (PerfCountersBuilder(coll)
              .add_u64_counter("write_bytes", "bytes written")
              .add_u64_counter("writes", "write ops")
              .create_perf_counters())
        cct.perf.add(pc)
        return cct, pc

    def test_rate_math_on_synthetic_stream(self):
        cct, pc = self._cct_with_counter()
        agg = StatsAggregator(cct=cct, name="t")
        try:
            agg.sample(now=0.0)
            pc.inc("write_bytes", 4096)
            pc.inc("writes", 2)
            agg.sample(now=2.0)
            assert agg.span() == 2.0
            assert agg.counter_delta("write_bytes") == 4096
            assert agg.rate("write_bytes") == pytest.approx(2048.0)
            assert agg.rate("writes") == pytest.approx(1.0)
            # prefix filter excludes non-matching collections
            assert agg.rate("write_bytes", ("replicated_",)) == 0.0
            d = agg.digest()
            assert d["client_io"]["wr_bytes_s"] == pytest.approx(2048.0)
            assert d["client_io"]["wr_op_s"] == pytest.approx(1.0)
        finally:
            agg.close()

    def test_window_rolls_and_bounds(self):
        cct, pc = self._cct_with_counter()
        agg = StatsAggregator(cct=cct, name="t", window=3)
        try:
            for t in range(10):
                pc.inc("write_bytes", 100)
                agg.sample(now=float(t))
            # only the last 3 samples survive: window spans t=7..9,
            # covering the 2 most recent 100-byte increments
            assert agg.span() == 2.0
            assert agg.counter_delta("write_bytes") == 200
            assert len(agg._samples) == 3
        finally:
            agg.close()

    def test_counter_reset_clamps_to_zero(self):
        cct, pc = self._cct_with_counter()
        agg = StatsAggregator(cct=cct, name="t")
        try:
            pc.inc("write_bytes", 1000)
            agg.sample(now=0.0)
            # re-registered collection: counters restart from zero
            cct.perf.remove("ec_backend.test")
            pc2 = (PerfCountersBuilder("ec_backend.test")
                   .add_u64_counter("write_bytes", "bytes written")
                   .create_perf_counters())
            cct.perf.add(pc2)
            pc2.inc("write_bytes", 10)
            agg.sample(now=1.0)
            assert agg.counter_delta("write_bytes") == 0.0
        finally:
            agg.close()

    def test_midwindow_collection_counts_fully(self):
        cct, _pc = self._cct_with_counter()
        agg = StatsAggregator(cct=cct, name="t")
        try:
            agg.sample(now=0.0)
            late = (PerfCountersBuilder("ec_backend.late")
                    .add_u64_counter("write_bytes", "bytes written")
                    .create_perf_counters())
            cct.perf.add(late)
            late.inc("write_bytes", 512)
            agg.sample(now=1.0)
            # the collection was BORN inside the window: its whole value
            # accrued within it
            assert agg.counter_delta("write_bytes") == 512
        finally:
            agg.close()

    def test_digest_flat_matches_digest(self):
        cct, _ = self._cct_with_counter()
        agg = StatsAggregator(cct=cct, name="t")
        try:
            flat = agg.digest_flat()
            assert set(flat) == {
                "client_wr_bytes_s", "client_rd_bytes_s", "client_wr_op_s",
                "client_rd_op_s", "recovery_bytes_s", "recovery_op_s",
                "recovery_queued_pgs", "recovery_active_pgs",
                "recovery_wire_per_byte",
                "serving_batch_s", "serving_op_s", "serving_bytes_s",
                "serving_wire_per_op", "serving_copies_per_byte",
                "wire_tx_bytes_s", "wire_tx_msgs_s",
                "jit_compiles", "jit_cache_hits"}
        finally:
            agg.close()

    def test_background_sampler_bounded(self):
        cct, pc = self._cct_with_counter()
        agg = StatsAggregator(cct=cct, name="t", window=5)
        try:
            agg.start(period=0.005)
            import time
            time.sleep(0.1)
            assert len(agg._samples) == 5        # deque bound holds
        finally:
            agg.close()

    def test_generic_checks_over_stats(self):
        """THROTTLE_SATURATED + RECOMPILE_STORM read only the perf/stats
        surfaces, so they work without a cluster."""
        cct = Context()
        thr = (PerfCountersBuilder("throttle.hot")
               .add_u64("val", "taken units").add_u64("max", "limit")
               .create_perf_counters())
        thr.set("max", 100)
        thr.set("val", 95)
        cct.perf.add(thr)
        res = throttle_saturated_check(cct)()
        assert res is not None and "throttle" in res.summary
        assert any("hot" in line for line in res.detail)
        thr.set("val", 10)
        assert throttle_saturated_check(cct)() is None

        jit = (PerfCountersBuilder("jit")
               .add_u64_counter("compilations", "compiles")
               .add_u64_counter("cache_hits", "hits")
               .create_perf_counters())
        cct2 = Context()
        cct2.perf.remove("jit")         # replace the shared collection
        cct2.perf.add(jit)
        agg = StatsAggregator(cct=cct2, name="t")
        try:
            agg.sample(now=0.0)
            jit.inc("compilations", 20)
            agg.sample(now=1.0)
            res = recompile_storm_check(cct2, agg)()
            assert res is not None and "20 jit compilations" in res.summary
            assert recompile_storm_check(cct2, agg, threshold=100)() is None
        finally:
            agg.close()

    def test_recompile_storm_is_time_normalized(self):
        """N compiles spread over a very LONG sparse window is warmup,
        not a storm (regression: the absolute count fired on
        rarely-polled clusters whatever the window duration)."""
        cct = Context()
        cct.perf.remove("jit")
        jit = (PerfCountersBuilder("jit")
               .add_u64_counter("compilations", "compiles")
               .add_u64_counter("cache_hits", "hits")
               .create_perf_counters())
        cct.perf.add(jit)
        agg = StatsAggregator(cct=cct, name="t")
        try:
            agg.sample(now=0.0)
            jit.inc("compilations", 8)
            agg.sample(now=86400.0)             # one sample per day
            assert recompile_storm_check(cct, agg, threshold=8)() is None
            # the same 8 compiles inside one minute IS a storm
            jit.inc("compilations", 8)
            agg2 = StatsAggregator(cct=cct, name="t2")
            try:
                agg2.sample(now=0.0)
                jit.inc("compilations", 8)
                agg2.sample(now=30.0)
                assert recompile_storm_check(cct, agg2,
                                             threshold=8)() is not None
            finally:
                agg2.close()
        finally:
            agg.close()


class TestFlightRecorder:
    def test_bundle_schema(self):
        cct = Context()
        fr = FlightRecorder(cct=cct)
        fr.add_source("custom", lambda: {"answer": 42})
        b = fr.dump(reason="unit-test")
        for key in ("version", "seq", "reason", "time", "trace", "jit",
                    "perf", "device", "custom"):
            assert key in b, f"bundle missing {key}"
        assert b["reason"] == "unit-test"
        assert b["custom"] == {"answer": 42}
        assert "traceEvents" in b["trace"]
        assert "jit" in b["perf"]               # the perf dump itself

    def test_failing_source_degrades(self):
        fr = FlightRecorder(cct=Context())
        fr.add_source("boom", lambda: 1 / 0)
        b = fr.dump()
        assert "error" in b["boom"]

    def test_disk_bundles_and_ring_bound(self, tmp_path):
        fr = FlightRecorder(cct=Context(), out_dir=tmp_path, capacity=2)
        for i in range(3):
            fr.dump(reason=f"r{i}")
        assert len(fr.bundles) == 2             # ring bound holds
        files = sorted(tmp_path.glob("flight-*.json"))
        assert len(files) == 3                  # disk keeps all three
        doc = json.loads(files[-1].read_text())
        assert doc["reason"] == "r2" and doc["version"] == 1
        assert [b["reason"] for b in fr.list_bundles()] == ["r1", "r2"]
        # a SECOND process (fresh seq counter) must not clobber the
        # first run's bundles: names carry timestamp+pid, not just seq
        fr2 = FlightRecorder(cct=Context(), out_dir=tmp_path, capacity=2)
        fr2.dump(reason="second-run")
        assert len(sorted(tmp_path.glob("flight-*.json"))) == 4
        # the on-disk ring is bounded too (a flapping check must not
        # fill the data dir): oldest files beyond the bound are pruned
        fr3 = FlightRecorder(cct=Context(), out_dir=tmp_path,
                             capacity=2, max_disk_bundles=3)
        fr3.dump(reason="prune-trigger")
        left = sorted(tmp_path.glob("flight-*.json"))
        assert len(left) == 3
        assert any("prune-trigger" in p.name for p in left)

    def test_same_reason_disk_cooldown(self, tmp_path):
        """A re-fired transition for the SAME reason within the cooldown
        keeps the in-memory bundle but skips the disk write (regression:
        a `watch ceph status` poll loop rotated the original incident's
        evidence out of the bounded disk ring); forced (operator) dumps
        always write."""
        fr = FlightRecorder(cct=Context(), out_dir=tmp_path,
                            min_repeat_interval_s=300.0)
        b1 = fr.dump(reason="health-X-HEALTH_ERR")
        assert "path" in b1
        b2 = fr.dump(reason="health-X-HEALTH_ERR")
        assert "path" not in b2 and "path_skipped" in b2
        assert len(fr.bundles) == 2             # memory ring unaffected
        assert len(list(tmp_path.glob("flight-*.json"))) == 1
        b3 = fr.dump(reason="health-X-HEALTH_ERR", force=True)
        assert "path" in b3
        # a DIFFERENT reason is a different incident: writes immediately
        b4 = fr.dump(reason="health-Y-HEALTH_WARN")
        assert "path" in b4

    def test_admin_command_takeover(self):
        cct = Context()
        fr = FlightRecorder(cct=cct)
        fr.register_admin()
        try:
            b = cct.admin_socket.call("flight dump")
            assert b["reason"] == "admin"
        finally:
            fr.close()
        with pytest.raises(KeyError):
            cct.admin_socket.call("flight dump")


class TestClusterIntegration:
    @pytest.fixture
    def cluster(self):
        from ceph_tpu.cluster import MiniCluster
        # k=2 m=2: min_size 3 of size 4, so ONE lost shard degrades
        # (WARN) and a second — past m — drops below min_size (ERR)
        c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "2", "device": "numpy"},
                               pg_num=4)
        yield c, pid
        c.shutdown()

    def test_client_io_rates_under_load(self, cluster):
        c, pid = cluster
        c.status()                              # open the rate window
        rng = np.random.default_rng(0)
        for i in range(12):
            c.put(pid, f"o{i}",
                  rng.integers(0, 256, 1500, np.uint8).tobytes())
            c.get(pid, f"o{i}", 1500)
        st = c.status()
        io = st["pgmap"]["io_rates"]["client_io"]
        assert io["wr_bytes_s"] > 0 and io["wr_op_s"] > 0
        assert io["rd_bytes_s"] > 0 and io["rd_op_s"] > 0
        from ceph_tpu.tools.ceph_cli import _fmt_status
        text = _fmt_status(st, c.health())
        assert "io:" in text and "client:" in text and " wr," in text

    def test_osd_loss_past_m_flips_err_and_records_flight(self, cluster):
        c, pid = cluster
        c.put(pid, "victim", b"x" * 1500)
        g = c.pools[pid]["pgs"][0]
        peers = [o for o in g.acting if o != g.backend.whoami]
        g.bus.mark_down(peers[0])               # 3/4 shards: degraded
        h = c.health()
        assert h["status"] == "HEALTH_WARN"
        assert "PG_DEGRADED" in h["checks"]
        g.bus.mark_down(peers[1])               # past m: below min_size
        h = c.health()
        assert h["status"] == "HEALTH_ERR"
        assert "PG_AVAILABILITY" in h["checks"]
        # the transition snapshotted a flight bundle with the full state
        reasons = [b["reason"] for b in c.flight.bundles]
        assert any("PG_AVAILABILITY" in r and "HEALTH_ERR" in r
                   for r in reasons)
        b = c.flight.bundles[-1]
        assert "traceEvents" in b["trace"]
        assert b["health"]["status"] == "HEALTH_ERR"
        assert "client_io" in b["stats"]
        assert any(k.startswith("ec_backend.") for k in b["perf"])
        g.bus.mark_up(peers[0])
        g.bus.mark_up(peers[1])
        g.bus.deliver_all()
        assert c.health()["status"] == "HEALTH_OK"

    def test_recovery_rate_surfaces(self, cluster):
        from ceph_tpu.backend.memstore import GObject
        from ceph_tpu.backend.pg_backend import shard_store
        c, pid = cluster
        c.put(pid, "r", b"y" * 1500)
        c.status()
        g = c.pg_group(pid, "r")
        victim_chunk = 1
        shard = g.acting[victim_chunk]
        del shard_store(g.bus, shard).objects[GObject("r", shard)]
        g.backend.recover_object("r", {victim_chunk})
        g.bus.deliver_all()
        st = c.status()
        rec = st["pgmap"]["io_rates"]["recovery"]
        assert rec["bytes_s"] > 0 and rec["op_s"] > 0

    def test_health_mute_persists_across_reload(self, tmp_path):
        from ceph_tpu.cluster import MiniCluster
        c = MiniCluster(n_osds=6, osds_per_host=3, chunk_size=512,
                        data_dir=tmp_path)
        pid = c.create_ec_pool("p", {"k": "2", "m": "1",
                                     "device": "numpy"}, pg_num=2)
        c.put(pid, "x", b"data" * 100)
        c.health_engine.mute("SLOW_OPS")
        c._save_meta()
        c.shutdown()
        c2 = MiniCluster.load(tmp_path)
        try:
            assert "SLOW_OPS" in c2.health_engine.muted
        finally:
            c2.shutdown()

    def test_ceph_cli_mute_and_status(self, tmp_path, capsys):
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.tools.ceph_cli import main as cli_main
        c = MiniCluster(n_osds=6, osds_per_host=3, chunk_size=512,
                        data_dir=tmp_path)
        pid = c.create_ec_pool("p", {"k": "2", "m": "1",
                                     "device": "numpy"}, pg_num=2)
        c.put(pid, "x", b"data" * 100)
        c.shutdown()
        d = str(tmp_path)
        assert cli_main(["--data-dir", d, "health", "mute", "OSD_DOWN"]) == 0
        assert "muted OSD_DOWN" in capsys.readouterr().out
        assert cli_main(["--data-dir", d, "-s"]) == 0
        out = capsys.readouterr().out
        assert "muted: OSD_DOWN" in out
        assert "io:" in out and "client:" in out
        assert cli_main(["--data-dir", d, "top"]) == 0
        out = capsys.readouterr().out
        assert "client io:" in out and "health:" in out
        assert cli_main(["--data-dir", d, "flight", "dump"]) == 0
        out = capsys.readouterr().out
        assert "captured flight bundle" in out
        [bundle_file] = (tmp_path / "flight").glob("flight-*.json")
        doc = json.loads(bundle_file.read_text())
        # a MANUAL dump on a process that never ran health() still
        # carries a real health evaluation (read-only fallback)
        assert doc["health"]["status"] in ("HEALTH_OK", "HEALTH_WARN",
                                           "HEALTH_ERR")
        assert cli_main(["--data-dir", d, "health", "unmute",
                         "OSD_DOWN"]) == 0
        capsys.readouterr()
        assert cli_main(["--data-dir", d, "health", "detail"]) == 0
        out = capsys.readouterr().out
        assert "muted" not in out.splitlines()[0]


class TestTraceReportJson:
    def test_json_output(self, tmp_path, capsys):
        # import by path: tools/ is not a package
        import importlib.util
        from pathlib import Path
        spec = importlib.util.spec_from_file_location(
            "trace_report_mod",
            Path(__file__).resolve().parent.parent / "tools" /
            "trace_report.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        trace = {"traceEvents": [
            {"name": "outer", "ph": "X", "ts": 0.0, "dur": 100.0,
             "pid": 1, "tid": 1},
            {"name": "inner", "ph": "X", "ts": 10.0, "dur": 40.0,
             "pid": 1, "tid": 1},
        ]}
        p = tmp_path / "t.json"
        p.write_text(json.dumps(trace))
        assert mod.main([str(p), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["num_spans"] == 2
        by_name = {s["name"]: s for s in doc["spans"]}
        assert by_name["outer"]["self_ms"] == pytest.approx(0.06)
        assert by_name["outer"]["total_ms"] == pytest.approx(0.1)
        assert by_name["inner"]["p99_ms"] == pytest.approx(0.04)
        # empty trace: --json still emits a parsable document but KEEPS
        # the failure exit code (CI must not green on an empty capture)
        p2 = tmp_path / "empty.json"
        p2.write_text(json.dumps({"traceEvents": []}))
        assert mod.main([str(p2), "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["num_spans"] == 0 and "error" in doc
