"""Objecter: client-side targeting, epoch stamps, resend on map change.

Mirrors the reference's client op lifecycle (reference: src/osdc/
Objecter.cc op_submit :2257, _calc_target :2786, resend-on-map-change
_scan_requests): a client holding a stale OSDMap gets its op rejected by
the OSD side and transparently resends to the new acting set after
refreshing its map — no manual re-routing by the caller.
"""
import numpy as np
import pytest

from ceph_tpu.client import Objecter
from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osdmap import PG

PROFILE = {"plugin": "jax_rs", "k": "4", "m": "2", "device": "numpy",
           "technique": "reed_sol_van"}


def payload(n, seed=7):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


@pytest.fixture()
def cluster():
    return MiniCluster(n_osds=12, chunk_size=256)


def trigger_remap(cluster, pid, oid):
    """Down + auto-out the primary-adjacent shard of oid's PG so CRUSH
    remaps it and the cluster backfills to a new acting set.  Returns the
    (old_acting, new_acting) pair."""
    mon = cluster.attach_monitor()
    g = cluster.pg_group(pid, oid)
    old_acting = list(g.acting)
    victim = old_acting[1]
    grace = cluster.cct.conf.get("osd_heartbeat_grace")
    reporters = [o for o in range(12) if o != victim][:4]
    for r in reporters:
        mon.prepare_failure(victim, r, 0.0, grace + 1)
    mon.propose_pending(grace + 1)
    out_after = cluster.cct.conf.get("mon_osd_down_out_interval")
    mon.tick(grace + out_after + 10)          # auto-out -> remap+backfill
    new_g = cluster.pg_group(pid, oid)
    assert list(new_g.acting) != old_acting, "remap did not happen"
    return old_acting, list(new_g.acting)


class TestBasics:
    def test_write_read_roundtrip(self, cluster):
        pid = cluster.create_ec_pool("p", PROFILE, pg_num=8)
        oc = Objecter(cluster)
        data = payload(2048)
        acked = []
        oc.write(pid, "obj", data, on_complete=acked.append)
        assert acked == [2048]
        assert oc.read(pid, "obj", 2048) == data
        assert oc.resends == 0 and oc.stale_rejects == 0

    def test_client_targets_match_cluster_placement(self, cluster):
        pid = cluster.create_ec_pool("p", PROFILE, pg_num=8)
        oc = Objecter(cluster)
        for i in range(16):
            oid = f"o{i}"
            ps, primary, acting = oc._calc_target(pid, oid)
            g = cluster.pg_group(pid, oid)
            assert g.pgid.ps == ps
            assert list(acting) == list(g.acting)
            assert primary == g.backend.whoami

    def test_replicated_pool_too(self, cluster):
        pid = cluster.create_replicated_pool("rep", size=3, pg_num=8)
        oc = Objecter(cluster)
        data = payload(512)
        oc.write(pid, "obj", data)
        assert oc.read(pid, "obj", 512) == data


class TestStaleClientResend:
    def test_write_during_remap_lands_on_new_acting_set(self, cluster):
        """THE VERDICT scenario: the client's map predates a backfill
        remap; its write must land on the new acting set without manual
        re-routing — stale reject -> map refresh -> resend."""
        pid = cluster.create_ec_pool("p", PROFILE, pg_num=8)
        oc = Objecter(cluster)                 # snapshots the current map
        oc.write(pid, "obj", payload(1024, seed=1))
        stale_epoch = oc.osdmap.epoch

        old_acting, new_acting = trigger_remap(cluster, pid, "obj")
        assert oc.osdmap.epoch == stale_epoch  # client did NOT see the maps

        data2 = payload(1024, seed=2)
        acked = []
        oc.write(pid, "obj", data2, on_complete=acked.append)
        assert acked == [1024], "stale-client write never completed"
        assert oc.stale_rejects >= 1
        assert oc.osdmap.epoch > stale_epoch   # refreshed by the reject
        # the write really landed on the NEW group
        new_g = cluster.pg_group(pid, "obj")
        assert list(new_g.acting) == new_acting
        assert oc.read(pid, "obj", 1024) == data2
        assert cluster.get(pid, "obj", 1024) == data2

    def test_read_with_stale_map_resends(self, cluster):
        pid = cluster.create_ec_pool("p", PROFILE, pg_num=8)
        oc = Objecter(cluster)
        data = payload(1024, seed=3)
        oc.write(pid, "obj", data)
        trigger_remap(cluster, pid, "obj")
        assert oc.read(pid, "obj", 1024) == data
        assert oc.stale_rejects >= 1

    def test_subscribed_client_never_goes_stale(self, cluster):
        """An Objecter attached to the monitor adopts each committed map
        as it lands, so post-remap ops hit the right target first try."""
        pid = cluster.create_ec_pool("p", PROFILE, pg_num=8)
        mon = cluster.attach_monitor()
        oc = Objecter(cluster)
        oc.attach(mon)
        oc.write(pid, "obj", payload(1024, seed=1))
        g = cluster.pg_group(pid, "obj")
        victim = g.acting[1]
        grace = cluster.cct.conf.get("osd_heartbeat_grace")
        for r in [o for o in range(12) if o != victim][:4]:
            mon.prepare_failure(victim, r, 0.0, grace + 1)
        mon.propose_pending(grace + 1)
        out_after = cluster.cct.conf.get("mon_osd_down_out_interval")
        mon.tick(grace + out_after + 10)
        assert oc.osdmap.epoch == cluster.osdmap.epoch
        data2 = payload(1024, seed=4)
        oc.write(pid, "obj", data2)
        assert oc.stale_rejects == 0           # first try hit the target
        assert oc.read(pid, "obj", 1024) == data2

    def test_epoch_gate_rejects_only_remapped_pgs(self, cluster):
        """Epoch bumps that do not change a PG's interval must not force
        resends (the same_interval_since semantics): a client one epoch
        behind still talks to untouched PGs directly."""
        pid = cluster.create_ec_pool("p", PROFILE, pg_num=8)
        oc = Objecter(cluster)
        mon = cluster.attach_monitor()
        # bump the cluster epoch withOUT remapping anything: mark an OSD
        # that serves no PG of this object down... simplest: nodown-less
        # down+up of some osd not in this PG's acting set
        g = cluster.pg_group(pid, "obj")
        outsider = next(o for o in range(12) if o not in g.acting)
        grace = cluster.cct.conf.get("osd_heartbeat_grace")
        for r in [o for o in range(12) if o != outsider][:4]:
            mon.prepare_failure(outsider, r, 0.0, grace + 1)
        mon.propose_pending(grace + 1)         # epoch bump, no remap
        assert cluster.osdmap.epoch > oc.osdmap.epoch
        oc.write(pid, "obj", payload(256, seed=5))
        assert oc.stale_rejects == 0, \
            "stale client rejected at an untouched PG"


class TestOperateVectors:
    """IoCtx::operate through the Objecter: op vectors with the full
    epoch/resend lifecycle (librados_cxx.cc:1482 -> op_submit)."""

    def test_operate_roundtrip(self, cluster):
        from ceph_tpu.osd.osd_ops import ObjectOperation
        pid = cluster.create_ec_pool("op", PROFILE, pg_num=8)
        client = Objecter(cluster)
        data = payload(3000)
        replies = []
        client.operate(pid, "vec", ObjectOperation()
                       .write_full(data).setxattr("tag", b"t1"),
                       on_complete=replies.append)
        assert replies and replies[0].result == 0
        client.operate(pid, "vec", ObjectOperation().read(0, 0).stat()
                       .getxattr("tag"), on_complete=replies.append)
        r = replies[1]
        assert r.outdata(0)[:3000] == data
        assert r.outdata(2) == b"t1"

    def test_operate_resends_after_remap(self, cluster):
        from ceph_tpu.osd.osd_ops import ObjectOperation
        pid = cluster.create_ec_pool("op2", PROFILE, pg_num=8)
        client = Objecter(cluster)
        data = payload(2000, seed=9)
        replies = []
        client.operate(pid, "vec2", ObjectOperation().write_full(data),
                       on_complete=replies.append)
        assert replies[0].result == 0
        old_acting, new_acting = trigger_remap(cluster, pid, "vec2")
        assert old_acting != new_acting
        # the client's map is stale: the OSD bounces, the objecter
        # refreshes + resends, and the vector lands on the NEW primary
        out = []
        client.operate(pid, "vec2", ObjectOperation().read(0, len(data)),
                       on_complete=out.append)
        assert out and out[0].outdata(0) == data
        assert client.stale_rejects >= 1

    def test_backfill_preserves_xattrs_and_omap(self, cluster):
        """Object metadata must move with the data on remap (attrs on EC,
        attrs+omap on replicated)."""
        from ceph_tpu.osd.osd_ops import ObjectOperation
        pid = cluster.create_replicated_pool("op3", size=3, pg_num=8)
        client = Objecter(cluster)
        out = []
        client.operate(pid, "meta", ObjectOperation()
                       .write_full(b"body").setxattr("color", b"red")
                       .omap_set({"k1": b"v1"}).omap_set_header(b"H"),
                       on_complete=out.append)
        assert out[0].result == 0
        trigger_remap(cluster, pid, "meta")
        r = []
        client.operate(pid, "meta", ObjectOperation()
                       .getxattr("color").omap_get_vals().omap_get_header(),
                       on_complete=r.append)
        assert r[0].result == 0
        assert r[0].outdata(0) == b"red"
        assert r[0].outdata(1) == {"k1": b"v1"}
        assert r[0].outdata(2) == b"H"
