"""EC backend tests: stripe algebra, write plan, pipeline, recovery, scrub.

Mirrors the reference's OSD-level EC tests (reference:
src/test/osd/TestECBackend.cc, test_ec_transaction.cc, test_extent_cache.cc)
plus the standalone put/get/degraded flows of
qa/standalone/erasure-code/test-erasure-code.sh.
"""
import numpy as np
import pytest

from ceph_tpu.backend import (ECBackend, ExtentSet, GObject, HashInfo,
                              MemStore, MessageBus, PGTransaction, StripeInfo,
                              Transaction, crc32c, get_write_plan,
                              make_cluster)
from ceph_tpu.backend import ecutil
from ceph_tpu.backend.ec_backend import RecoveryState
from ceph_tpu.backend.extent_cache import ExtentCache
from ceph_tpu.plugins.registry import ErasureCodePluginRegistry

K, M = 4, 2
CHUNK = 128
STRIPE = K * CHUNK


@pytest.fixture(scope="module")
def ec_impl():
    return ErasureCodePluginRegistry.instance().factory(
        "jax_rs", "", {"k": str(K), "m": str(M), "device": "numpy",
                       "technique": "reed_sol_van"})


@pytest.fixture()
def cluster(ec_impl):
    return make_cluster(ec_impl, chunk_size=CHUNK)


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


# -- extent set --------------------------------------------------------------

class TestExtentSet:
    def test_union_insert_coalesce(self):
        es = ExtentSet()
        es.union_insert(0, 10)
        es.union_insert(20, 10)
        es.union_insert(10, 10)       # bridges the gap
        assert list(es) == [(0, 30)]

    def test_overlap_merge(self):
        es = ExtentSet([(0, 10), (5, 20)])
        assert list(es) == [(0, 25)]

    def test_erase_splits(self):
        es = ExtentSet([(0, 30)])
        es.erase(10, 10)
        assert list(es) == [(0, 10), (20, 10)]

    def test_contains_and_intersects(self):
        es = ExtentSet([(10, 10)])
        assert es.contains(10, 10)
        assert es.contains(15, 5)
        assert not es.contains(15, 6)
        assert es.intersects(0, 11)
        assert not es.intersects(0, 10)

    def test_intersection(self):
        a = ExtentSet([(0, 10), (20, 10)])
        b = ExtentSet([(5, 20)])
        assert list(a.intersection(b)) == [(5, 5), (20, 5)]


# -- stripe algebra (ECUtil.h:27-80 semantics) ------------------------------

class TestStripeInfo:
    def test_offsets(self):
        s = StripeInfo(K, CHUNK)
        assert s.stripe_width == STRIPE
        assert s.logical_to_prev_stripe_offset(STRIPE + 1) == STRIPE
        assert s.logical_to_next_stripe_offset(STRIPE + 1) == 2 * STRIPE
        assert s.logical_to_next_stripe_offset(STRIPE) == STRIPE
        assert s.logical_to_prev_chunk_offset(2 * STRIPE + 5) == 2 * CHUNK
        assert s.logical_to_next_chunk_offset(2 * STRIPE + 5) == 3 * CHUNK
        assert s.aligned_logical_offset_to_chunk_offset(3 * STRIPE) == 3 * CHUNK
        assert s.aligned_chunk_offset_to_logical_offset(3 * CHUNK) == 3 * STRIPE

    def test_stripe_bounds(self):
        s = StripeInfo(K, CHUNK)
        off, length = s.offset_len_to_stripe_bounds(STRIPE + 5, STRIPE)
        assert off == STRIPE and length == 2 * STRIPE


# -- crc32c / HashInfo ------------------------------------------------------

class TestHashes:
    def test_crc32c_vector(self):
        # iSCSI CRC32C check value: crc("123456789") = 0xE3069283
        assert crc32c(0xFFFFFFFF, b"123456789") ^ 0xFFFFFFFF == 0xE3069283

    def test_crc32c_chaining(self):
        whole = crc32c(0xFFFFFFFF, b"hello world")
        part = crc32c(crc32c(0xFFFFFFFF, b"hello "), b"world")
        assert whole == part

    def test_hashinfo_append(self):
        h = HashInfo(3)
        bufs = {i: np.full(16, i, dtype=np.uint8) for i in range(3)}
        h.append(0, bufs)
        assert h.total_chunk_size == 16
        again = HashInfo(3)
        again.append(0, bufs)
        assert again.cumulative_shard_hashes == h.cumulative_shard_hashes
        h.append(16, bufs)
        assert h.total_chunk_size == 32
        assert h.cumulative_shard_hashes != again.cumulative_shard_hashes


# -- memstore ---------------------------------------------------------------

class TestMemStore:
    def test_write_read_truncate(self):
        st = MemStore()
        o = GObject("a", 0)
        st.queue_transaction(Transaction().write(o, 0, b"hello"))
        st.queue_transaction(Transaction().write(o, 10, b"world"))
        assert st.read(o) == b"hello\0\0\0\0\0world"
        st.queue_transaction(Transaction().truncate(o, 5))
        assert st.read(o) == b"hello"
        st.queue_transaction(Transaction().remove(o))
        assert not st.exists(o)

    def test_xattr_and_clone(self):
        st = MemStore()
        a, b = GObject("a", 0), GObject("b", 0)
        st.queue_transaction(
            Transaction().write(a, 0, b"data").setattr(a, "k", {"x": 1}))
        st.queue_transaction(Transaction().clone(a, b))
        assert st.read(b) == b"data"
        assert st.getattr(b, "k") == {"x": 1}


# -- write planning (ECTransaction.h:40-183 semantics) ----------------------

class TestWritePlan:
    def setup_method(self):
        self.sinfo = StripeInfo(K, CHUNK)
        self.hinfos = {}

    def _hinfo(self, oid, size=0):
        h = self.hinfos.setdefault(oid, HashInfo(K + M))
        if size:
            h.set_projected_total_logical_size(self.sinfo, size)
        return h

    def test_aligned_append_reads_nothing(self):
        t = PGTransaction().write("o", 0, b"x" * STRIPE)
        plan = get_write_plan(self.sinfo, t, self._hinfo)
        assert "o" not in plan.to_read
        assert list(plan.will_write["o"]) == [(0, STRIPE)]

    def test_partial_overwrite_reads_head_stripe(self):
        self._hinfo("o", 2 * STRIPE)
        t = PGTransaction().write("o", 10, b"y" * 20)
        plan = get_write_plan(self.sinfo, t, self._hinfo)
        assert list(plan.to_read["o"]) == [(0, STRIPE)]
        assert list(plan.will_write["o"]) == [(0, STRIPE)]

    def test_spanning_write_reads_head_and_tail(self):
        self._hinfo("o", 4 * STRIPE)
        t = PGTransaction().write("o", STRIPE - 10, b"z" * (2 * STRIPE + 20))
        plan = get_write_plan(self.sinfo, t, self._hinfo)
        assert list(plan.to_read["o"]) == [(0, STRIPE), (3 * STRIPE, STRIPE)]
        assert list(plan.will_write["o"]) == [(0, 4 * STRIPE)]

    def test_append_past_eof_reads_nothing(self):
        self._hinfo("o", STRIPE)
        t = PGTransaction().write("o", STRIPE, b"w" * STRIPE)
        plan = get_write_plan(self.sinfo, t, self._hinfo)
        assert "o" not in plan.to_read

    def test_unaligned_truncate_rewrites_last_stripe(self):
        self._hinfo("o", 2 * STRIPE)
        t = PGTransaction().truncate_to("o", STRIPE + 7)
        plan = get_write_plan(self.sinfo, t, self._hinfo)
        assert list(plan.to_read["o"]) == [(STRIPE, STRIPE)]
        assert list(plan.will_write["o"]) == [(STRIPE, STRIPE)]
        assert self.hinfos["o"].get_projected_total_logical_size(
            self.sinfo) == 2 * STRIPE


# -- extent cache -----------------------------------------------------------

class TestExtentCache:
    def test_claim_read_release(self):
        c = ExtentCache()
        c.claim("o", 1, 0, b"a" * STRIPE)
        assert c.read("o", 0, STRIPE) == b"a" * STRIPE
        assert c.read("o", 10, 20) == b"a" * 20
        assert c.read("o", 0, STRIPE + 1) is None
        c.release("o", 1)
        assert c.read("o", 0, STRIPE) is None

    def test_overlapping_ops_keep_pins(self):
        c = ExtentCache()
        c.claim("o", 1, 0, b"a" * 100)
        c.claim("o", 2, 50, b"b" * 100)
        c.release("o", 1)
        assert c.read("o", 50, 100) == b"b" * 100
        assert c.read("o", 0, 10) is None
        c.release("o", 2)
        assert c.read("o", 50, 1) is None


# -- batched ecutil encode/decode -------------------------------------------

class TestBatchedCodec:
    def test_encode_matches_per_stripe(self, ec_impl):
        """One batched call == the reference's per-stripe loop, bit for bit."""
        sinfo = StripeInfo(K, CHUNK)
        data = payload(5 * STRIPE)
        batched = ecutil.encode(sinfo, ec_impl, data)
        for s in range(5):
            stripe = data[s * STRIPE:(s + 1) * STRIPE]
            per = ec_impl.encode(set(range(K + M)), stripe)
            for chunk in range(K + M):
                np.testing.assert_array_equal(
                    batched[chunk][s * CHUNK:(s + 1) * CHUNK], per[chunk])

    def test_decode_roundtrip_with_erasures(self, ec_impl):
        sinfo = StripeInfo(K, CHUNK)
        data = payload(8 * STRIPE, seed=3)
        enc = ecutil.encode(sinfo, ec_impl, data)
        # drop two shards, decode from the rest
        avail = {i: v for i, v in enc.items() if i not in (1, 4)}
        assert ecutil.decode(sinfo, ec_impl, avail) == data


# -- full pipeline ----------------------------------------------------------

def _write(backend, bus, oid, off, data):
    done = []
    backend.submit_transaction(
        PGTransaction().write(oid, off, data),
        on_commit=lambda tid: done.append(tid))
    bus.deliver_all()
    assert done, "write did not commit"


def _read(backend, bus, oid, off, length, fast_read=False):
    out = {}
    backend.objects_read_and_reconstruct(
        {oid: [(off, length)]},
        lambda result, errors: out.update(result=result, errors=errors),
        fast_read=fast_read)
    bus.deliver_all()
    return out


class TestPipeline:
    def test_write_then_read(self, cluster):
        backend, bus = cluster
        data = payload(3 * STRIPE)
        _write(backend, bus, "obj", 0, data)
        out = _read(backend, bus, "obj", 0, len(data))
        assert not out["errors"]
        assert out["result"]["obj"][0][2] == data

    def test_shards_hold_chunks(self, cluster, ec_impl):
        backend, bus = cluster
        data = payload(2 * STRIPE, seed=1)
        _write(backend, bus, "obj", 0, data)
        sinfo = backend.sinfo
        want = ecutil.encode(sinfo, ec_impl, data)
        for chunk in range(K + M):
            handler = bus.handlers[chunk]
            store = handler.store if chunk else handler.local_shard.store
            got = store.read(GObject("obj", chunk))
            assert got == want[chunk].tobytes()

    def test_unaligned_read(self, cluster):
        backend, bus = cluster
        data = payload(4 * STRIPE, seed=2)
        _write(backend, bus, "obj", 0, data)
        out = _read(backend, bus, "obj", 100, 3 * STRIPE)
        assert out["result"]["obj"][0][2] == data[100:100 + 3 * STRIPE]

    def test_read_trims_to_object_size(self, cluster):
        backend, bus = cluster
        data = payload(STRIPE)
        _write(backend, bus, "obj", 0, data)
        out = _read(backend, bus, "obj", 0, 10 * STRIPE)
        assert out["result"]["obj"][0][2] == data

    def test_rmw_partial_overwrite(self, cluster):
        backend, bus = cluster
        data = bytearray(payload(2 * STRIPE, seed=4))
        _write(backend, bus, "obj", 0, bytes(data))
        patch = payload(40, seed=5)
        _write(backend, bus, "obj", 100, patch)
        data[100:140] = patch
        out = _read(backend, bus, "obj", 0, len(data))
        assert out["result"]["obj"][0][2] == bytes(data)

    def test_append_grows_object(self, cluster):
        backend, bus = cluster
        a, b = payload(STRIPE, seed=6), payload(2 * STRIPE, seed=7)
        _write(backend, bus, "obj", 0, a)
        _write(backend, bus, "obj", STRIPE, b)
        assert backend.object_size("obj") == 3 * STRIPE
        out = _read(backend, bus, "obj", 0, 3 * STRIPE)
        assert out["result"]["obj"][0][2] == a + b

    def test_pipelined_overlapping_writes_use_cache(self, cluster):
        """Two overlapping RMW writes submitted back-to-back: the second must
        read the first's stripes from the extent cache, not the shards."""
        backend, bus = cluster
        base = payload(STRIPE, seed=8)
        _write(backend, bus, "obj", 0, base)
        done = []
        p1, p2 = payload(10, seed=9), payload(10, seed=10)
        backend.submit_transaction(PGTransaction().write("obj", 0, p1),
                                   on_commit=done.append)
        # before any delivery, the second op must see the first's bytes
        backend.submit_transaction(PGTransaction().write("obj", 20, p2),
                                   on_commit=done.append)
        bus.deliver_all()
        assert len(done) == 2
        want = bytearray(base)
        want[0:10] = p1
        want[20:30] = p2
        out = _read(backend, bus, "obj", 0, STRIPE)
        assert out["result"]["obj"][0][2] == bytes(want)

    def test_delete(self, cluster):
        backend, bus = cluster
        _write(backend, bus, "obj", 0, payload(STRIPE))
        done = []
        backend.submit_transaction(PGTransaction().delete("obj"),
                                   on_commit=done.append)
        bus.deliver_all()
        assert done
        for chunk in range(1, K + M):
            assert not bus.handlers[chunk].store.exists(GObject("obj", chunk))


class TestDegradedAndRecovery:
    def test_degraded_read_reconstructs(self, cluster):
        backend, bus = cluster
        data = payload(4 * STRIPE, seed=11)
        _write(backend, bus, "obj", 0, data)
        bus.mark_down(1)
        bus.mark_down(3)
        out = _read(backend, bus, "obj", 0, len(data))
        assert not out["errors"]
        assert out["result"]["obj"][0][2] == data

    def test_too_many_failures_is_io_error(self, cluster):
        backend, bus = cluster
        data = payload(STRIPE)
        _write(backend, bus, "obj", 0, data)
        for s in (1, 2, 3):
            bus.mark_down(s)
        assert not backend.is_recoverable("obj", {1, 2, 3})
        with pytest.raises(IOError):
            backend.ec_impl.minimum_to_decode({1}, {0, 4, 5})

    def test_shard_error_triggers_retry(self, cluster):
        """A missing shard object (EIO analog) widens the read to parity
        shards instead of failing (ECBackend.cc:1627-1671)."""
        backend, bus = cluster
        data = payload(2 * STRIPE, seed=12)
        _write(backend, bus, "obj", 0, data)
        # corrupt shard 2: drop its chunk object entirely
        bus.handlers[2].store.queue_transaction(
            Transaction().remove(GObject("obj", 2)))
        out = _read(backend, bus, "obj", 0, len(data))
        assert not out["errors"]
        assert out["result"]["obj"][0][2] == data

    def test_fast_read(self, cluster):
        backend, bus = cluster
        data = payload(STRIPE, seed=13)
        _write(backend, bus, "obj", 0, data)
        out = _read(backend, bus, "obj", 0, STRIPE, fast_read=True)
        assert out["result"]["obj"][0][2] == data

    def test_recovery_restores_lost_shard(self, cluster, ec_impl):
        backend, bus = cluster
        data = payload(3 * STRIPE, seed=14)
        _write(backend, bus, "obj", 0, data)
        lost = GObject("obj", 4)
        bus.handlers[4].store.queue_transaction(Transaction().remove(lost))
        states = []
        rop = backend.recover_object(
            "obj", {4}, on_complete=lambda r: states.append(r.state))
        bus.deliver_all()
        assert rop.state == RecoveryState.COMPLETE
        assert states == [RecoveryState.COMPLETE]
        want = ecutil.encode(backend.sinfo, ec_impl, data)
        assert bus.handlers[4].store.read(lost) == want[4].tobytes()

    def test_recovery_after_missed_write(self, cluster, ec_impl):
        """Shard down during the write, revived, then repaired — the
        write-around + recover flow the Thrasher exercises (SURVEY.md §4.4)."""
        backend, bus = cluster
        bus.mark_down(5)
        data = payload(2 * STRIPE, seed=15)
        _write(backend, bus, "obj", 0, data)
        bus.mark_up(5)
        rop = backend.recover_object("obj", {5})
        bus.deliver_all()
        assert rop.state == RecoveryState.COMPLETE
        want = ecutil.encode(backend.sinfo, ec_impl, data)
        assert bus.handlers[5].store.read(GObject("obj", 5)) == want[5].tobytes()


class TestClayCluster:
    """Sub-chunk-aware code through the full backend: clay's fractional
    repair reads must survive the ECSubRead slicing + recovery decode."""

    @pytest.fixture()
    def clay_cluster(self):
        ec = ErasureCodePluginRegistry.instance().factory(
            "clay", "", {"k": str(K), "m": str(M),
                         "scalar_mds": "jax_rs", "device": "numpy"})
        return make_cluster(ec, chunk_size=CHUNK), ec

    def test_slice_subchunks(self):
        from ceph_tpu.backend.ec_backend import _slice_subchunks
        data = bytes(range(8))
        assert _slice_subchunks(data, [(0, 1)], 8) == b"\x00"
        assert _slice_subchunks(data, [(0, 4)], 8) == bytes(range(4))
        assert _slice_subchunks(data, [(1, 2), (5, 1)], 8) == b"\x01\x02\x05"

    def test_write_read_roundtrip(self, clay_cluster):
        (backend, bus), ec = clay_cluster
        data = payload(2 * STRIPE, seed=20)
        _write(backend, bus, "obj", 0, data)
        out = _read(backend, bus, "obj", 0, len(data))
        assert not out["errors"]
        assert out["result"]["obj"][0][2] == data

    def test_recovery_uses_fractional_reads(self, clay_cluster):
        (backend, bus), ec = clay_cluster
        data = payload(2 * STRIPE, seed=21)
        _write(backend, bus, "obj", 0, data)
        lost = GObject("obj", 1)
        want = bus.handlers[1].store.read(lost)
        bus.handlers[1].store.queue_transaction(Transaction().remove(lost))
        rop = backend.recover_object("obj", {1})
        bus.deliver_all()
        assert rop.state == RecoveryState.COMPLETE
        assert bus.handlers[1].store.read(lost) == want
        # the helpers really sent fractional buffers: d helpers, half chunk
        full = backend._hinfo("obj").get_total_chunk_size()
        sub_total = sum(c for _, c in ec.get_repair_subchunks(1))
        assert sub_total < ec.get_sub_chunk_count()

    def test_degraded_read_reconstructs(self, clay_cluster):
        (backend, bus), ec = clay_cluster
        data = payload(2 * STRIPE, seed=22)
        _write(backend, bus, "obj", 0, data)
        bus.mark_down(2)
        out = _read(backend, bus, "obj", 0, len(data))
        assert not out["errors"]
        assert out["result"]["obj"][0][2] == data


class TestScrub:
    def test_deep_scrub_clean(self, cluster):
        backend, bus = cluster
        _write(backend, bus, "obj", 0, payload(2 * STRIPE, seed=16))
        result = backend.be_deep_scrub("obj")
        assert result == {c: True for c in range(K + M)}

    def test_deep_scrub_detects_bitrot(self, cluster):
        backend, bus = cluster
        _write(backend, bus, "obj", 0, payload(2 * STRIPE, seed=17))
        store = bus.handlers[3].store
        obj = GObject("obj", 3)
        raw = bytearray(store.read(obj))
        raw[7] ^= 0xFF
        store.queue_transaction(Transaction().write(obj, 0, bytes(raw)))
        result = backend.be_deep_scrub("obj")
        assert result[3] is False
        assert all(result[c] for c in range(K + M) if c != 3)


class TestReviewRegressions:
    """Regressions for the pipeline-ordering, truncate, shard-death,
    recovery-cleanup, and memstore-atomicity bugs found in review."""

    def test_truncate_shrink_really_shrinks(self, cluster):
        backend, bus = cluster
        data = payload(2 * STRIPE, seed=20)
        _write(backend, bus, "obj", 0, data)
        done = []
        backend.submit_transaction(
            PGTransaction().truncate_to("obj", STRIPE),
            on_commit=done.append)
        bus.deliver_all()
        assert done
        assert backend.object_size("obj") == STRIPE
        out = _read(backend, bus, "obj", 0, 2 * STRIPE)
        assert out["result"]["obj"][0][2] == data[:STRIPE]
        # shard chunk objects shrank too
        for chunk in range(1, K + M):
            assert bus.handlers[chunk].store.stat(
                GObject("obj", chunk)) == CHUNK

    def test_truncate_unaligned_zero_fills_tail(self, cluster):
        backend, bus = cluster
        data = payload(2 * STRIPE, seed=21)
        _write(backend, bus, "obj", 0, data)
        cut = STRIPE + 10
        backend.submit_transaction(PGTransaction().truncate_to("obj", cut))
        bus.deliver_all()
        out = _read(backend, bus, "obj", 0, 2 * STRIPE)
        got = out["result"]["obj"][0][2]
        assert got[:cut] == data[:cut]
        assert got[cut:] == b"\0" * (2 * STRIPE - len(got[:cut]))

    def test_no_lost_update_through_stale_cache(self, cluster):
        """Op C must not assemble from op A's cached stripe while op B's
        overlapping overwrite is still in flight between them."""
        backend, bus = cluster
        base = payload(2 * STRIPE, seed=22)
        _write(backend, bus, "obj", 0, base)
        done = []
        pa, pb, pc = payload(10, seed=23), payload(STRIPE, seed=24), \
            payload(10, seed=25)
        # A: small patch in stripe 0 (RMW read of stripe 0)
        backend.submit_transaction(PGTransaction().write("obj", 0, pa),
                                   on_commit=done.append)
        # B: full overwrite of stripe 0 (no read needed)
        backend.submit_transaction(PGTransaction().write("obj", 0, pb),
                                   on_commit=done.append)
        # C: small patch at offset 20 (RMW read of stripe 0) — must see B
        backend.submit_transaction(PGTransaction().write("obj", 20, pc),
                                   on_commit=done.append)
        bus.deliver_all()
        assert len(done) == 3
        want = bytearray(base)
        want[:STRIPE] = pb
        want[20:30] = pc
        out = _read(backend, bus, "obj", 0, 2 * STRIPE)
        assert out["result"]["obj"][0][2] == bytes(want)

    def test_shard_death_during_rmw_read(self, cluster):
        backend, bus = cluster
        base = payload(2 * STRIPE, seed=26)
        _write(backend, bus, "obj", 0, base)
        done = []
        patch = payload(10, seed=27)
        backend.submit_transaction(PGTransaction().write("obj", 5, patch),
                                   on_commit=done.append)
        bus.mark_down(1)            # read request to shard 1 evaporates
        bus.deliver_all()
        assert done, "write hung after read-shard death"
        want = bytearray(base)
        want[5:15] = patch
        out = _read(backend, bus, "obj", 0, 2 * STRIPE)
        assert out["result"]["obj"][0][2] == bytes(want)

    def test_shard_death_during_client_read(self, cluster):
        backend, bus = cluster
        data = payload(2 * STRIPE, seed=28)
        _write(backend, bus, "obj", 0, data)
        out = {}
        backend.objects_read_and_reconstruct(
            {"obj": [(0, len(data))]},
            lambda result, errors: out.update(result=result, errors=errors))
        bus.mark_down(2)            # dies with the read outstanding
        bus.deliver_all()
        assert out, "read never completed after shard death"
        assert not out["errors"]
        assert out["result"]["obj"][0][2] == data

    def test_shard_death_during_recovery_read(self, cluster, ec_impl):
        backend, bus = cluster
        data = payload(2 * STRIPE, seed=29)
        _write(backend, bus, "obj", 0, data)
        lost = GObject("obj", 5)
        bus.handlers[5].store.queue_transaction(Transaction().remove(lost))
        rop = backend.recover_object("obj", {5})
        # a non-primary helper dies mid-recovery (killing the primary means
        # re-peering, which this single-primary harness doesn't model)
        helper = next(iter(rop._pending - {5, backend.whoami}))
        bus.mark_down(helper)
        bus.deliver_all()
        assert rop.state == RecoveryState.COMPLETE
        want = ecutil.encode(backend.sinfo, ec_impl, data)
        assert bus.handlers[5].store.read(lost) == want[5].tobytes()

    def test_recovery_state_dropped_after_complete(self, cluster):
        backend, bus = cluster
        _write(backend, bus, "obj", 0, payload(STRIPE, seed=30))
        bus.handlers[3].store.queue_transaction(
            Transaction().remove(GObject("obj", 3)))
        rop = backend.recover_object("obj", {3})
        bus.deliver_all()
        assert rop.state == RecoveryState.COMPLETE
        assert not backend.recovery_ops
        assert not backend._recovery_read_tids
        # a stale duplicate push reply is inert
        from ceph_tpu.backend.messages import PushReply
        backend.handle_push_reply(PushReply(3, "obj"))
        assert rop.state == RecoveryState.COMPLETE

    def test_memstore_stages_only_touched_objects(self):
        st = MemStore()
        a, b = GObject("a", 0), GObject("b", 0)
        st.queue_transaction(Transaction().write(a, 0, b"aaaa"))
        st.queue_transaction(Transaction().write(b, 0, b"bbbb"))
        # failing op mid-transaction leaves the store untouched
        t = Transaction().write(a, 0, b"xxxx")
        t.ops.append(("bogus", a))
        with pytest.raises(ValueError):
            st.queue_transaction(t)
        assert st.read(a) == b"aaaa"
        # remove + recreate in one transaction
        st.queue_transaction(
            Transaction().remove(a).write(a, 0, b"new"))
        assert st.read(a) == b"new"
        assert st.read(b) == b"bbbb"

    def test_unrecoverable_rmw_parks_then_redrives_on_mark_up(self, cluster):
        """Too many shard deaths stall the write (PG down); revival
        re-drives it instead of hanging forever."""
        backend, bus = cluster
        base = payload(2 * STRIPE, seed=31)
        _write(backend, bus, "obj", 0, base)
        done = []
        patch = payload(10, seed=32)
        backend.submit_transaction(PGTransaction().write("obj", 5, patch),
                                   on_commit=done.append)
        for s in (1, 2, 3):            # 3 of 6 dead: k=4 unreachable
            bus.mark_down(s)
        bus.deliver_all()
        assert not done                # parked, not crashed
        bus.mark_up(1)
        bus.mark_up(2)
        bus.deliver_all()
        assert done, "write not re-driven after shards returned"
        want = bytearray(base)
        want[5:15] = patch
        out = _read(backend, bus, "obj", 0, 2 * STRIPE)
        assert out["result"]["obj"][0][2] == bytes(want)

    def test_unrecoverable_recovery_parks_then_redrives(self, cluster, ec_impl):
        backend, bus = cluster
        data = payload(2 * STRIPE, seed=33)
        _write(backend, bus, "obj", 0, data)
        lost = GObject("obj", 5)
        bus.handlers[5].store.queue_transaction(Transaction().remove(lost))
        rop = backend.recover_object("obj", {5})
        helpers = [s for s in rop._pending if s != backend.whoami][:2]
        for s in helpers:
            bus.mark_down(s)           # second death -> unrecoverable
        bus.mark_down(4 if 4 not in helpers else 2)
        assert rop.state != RecoveryState.COMPLETE
        for s in helpers:
            bus.mark_up(s)
        bus.deliver_all()
        assert rop.state == RecoveryState.COMPLETE
        want = ecutil.encode(backend.sinfo, ec_impl, data)
        assert bus.handlers[5].store.read(lost) == want[5].tobytes()

    def test_push_target_death_fails_recovery(self, cluster):
        """A recovery whose push target dies must report FAILED, not
        COMPLETE (the shard is still degraded)."""
        backend, bus = cluster
        _write(backend, bus, "obj", 0, payload(STRIPE, seed=34))
        bus.handlers[5].store.queue_transaction(
            Transaction().remove(GObject("obj", 5)))
        states = []
        rop = backend.recover_object("obj", {5},
                                     on_complete=lambda r: states.append(r.state))
        # drain reads so the op reaches WRITING with the push in flight
        for s in list(rop._pending):
            while bus.deliver_one(s):
                pass
        while bus.deliver_one(backend.whoami):
            pass
        assert rop.state == RecoveryState.WRITING
        bus.mark_down(5)               # push target dies before acking
        bus.deliver_all()
        assert rop.state == RecoveryState.FAILED
        assert states == [RecoveryState.FAILED]
        assert not backend.recovery_ops

    def test_push_target_death_sticky_with_surviving_pushes(self, cluster):
        """One of two push targets dies while the other's push is still in
        flight: the surviving ack must NOT flip the op to COMPLETE — the
        dead target never got its chunk (reference _failed_push fails the
        op for any dead push target)."""
        backend, bus = cluster
        _write(backend, bus, "obj", 0, payload(STRIPE, seed=35))
        for shard in (4, 5):
            bus.handlers[shard].store.queue_transaction(
                Transaction().remove(GObject("obj", shard)))
        states = []
        rop = backend.recover_object(
            "obj", {4, 5}, on_complete=lambda r: states.append(r.state))
        for s in list(rop._pending):
            while bus.deliver_one(s):
                pass
        while bus.deliver_one(backend.whoami):
            pass
        assert rop.state == RecoveryState.WRITING
        assert rop.pending_pushes == {4, 5}
        bus.mark_down(5)               # one target dies, 4's push pending
        assert rop.state == RecoveryState.WRITING    # not finished yet
        bus.deliver_all()              # 4 receives its push and acks
        assert rop.state == RecoveryState.FAILED
        assert states == [RecoveryState.FAILED]
