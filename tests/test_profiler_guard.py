"""Guard: every ``jax.profiler`` use lives in common/profiler_capture.py.

Thin wrapper over the ``profiler-confinement`` rule in
:mod:`ceph_tpu.analysis.rules_guards` (ISSUE 15); semantics unchanged —
profiling is process-global and expensive, so the whole surface
(``import jax.profiler``, ``from jax import profiler``, attribute
access ``jax.profiler``, and direct ``start_trace``/``stop_trace``
calls) stays inside the one module built to bound it.
"""
import ceph_tpu.analysis as A
from ceph_tpu.analysis.rules_guards import PROFILER_ALLOWLIST


def test_profiler_use_confined_to_capture_module():
    offenders = [f.render() for f in A.run_rules(
        A.default_index(), ("profiler-confinement",))]
    assert not offenders, (
        "jax.profiler touches outside common/profiler_capture.py — "
        "route captures through ProfilerCapture's managed windows (or "
        "extend the allowlist with a justification):\n"
        + "\n".join(offenders))


def test_allowlist_entries_still_exist():
    idx = A.default_index()
    for rel in PROFILER_ALLOWLIST:
        assert idx.iter_modules((rel,)), f"stale allowlist entry: {rel}"


def test_guard_catches_a_violation():
    bad = ("import jax.profiler\n"
           "from jax import profiler\n"
           "from jax.profiler import start_trace\n"
           "def f():\n"
           "    jax.profiler.start_trace('/tmp/x')\n"
           "    profiler.stop_trace()\n")
    kinds = {f.message for f in A.run_rule_on_sources(
        "profiler-confinement", {"bad.py": bad})}
    assert "import jax.profiler" in kinds
    assert "from jax import profiler" in kinds
    assert "from jax.profiler import ..." in kinds
    assert "jax.profiler" in kinds
    assert "start_trace(...)" in kinds
    assert "stop_trace(...)" in kinds
