"""Guard: every ``jax.profiler`` use lives in common/profiler_capture.py.

Profiling is process-global and expensive: a stray ``start_trace`` in a
hot path (or a helper that "just profiles this one section") would tax
every dispatch and fight the managed capture windows for the single
process-wide profiler session.  This guard keeps the whole surface —
``import jax.profiler``, ``from jax import profiler``, attribute access
``jax.profiler``, and direct ``start_trace``/``stop_trace`` calls —
inside the one module built to bound it (the ``test_no_host_sync.py``
AST pattern, so comments and docstrings may mention the names).
"""
import ast
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
# the whole production tree: package, tools, and the bench driver
SCAN = ("ceph_tpu", "tools", "bench.py")

# path -> why the profiler touch is legitimate there
ALLOWLIST = {
    "ceph_tpu/common/profiler_capture.py":
        "IS the capture-window manager (the only sanctioned owner of "
        "the process-global profiler session)",
}

_FORBIDDEN_CALLS = {"start_trace", "stop_trace"}


class _Visitor(ast.NodeVisitor):
    def __init__(self):
        self.offenders: list[tuple[int, str]] = []

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.name == "jax.profiler" or \
                    alias.name.startswith("jax.profiler."):
                self.offenders.append(
                    (node.lineno, f"import {alias.name}"))
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        mod = node.module or ""
        if mod == "jax.profiler" or mod.startswith("jax.profiler."):
            self.offenders.append(
                (node.lineno, f"from {mod} import ..."))
        elif mod == "jax" and any(a.name == "profiler"
                                  for a in node.names):
            self.offenders.append(
                (node.lineno, "from jax import profiler"))
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr == "profiler" and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "jax":
            self.offenders.append((node.lineno, "jax.profiler"))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = fn.attr if isinstance(fn, ast.Attribute) else \
            fn.id if isinstance(fn, ast.Name) else None
        if name in _FORBIDDEN_CALLS:
            self.offenders.append((node.lineno, f"{name}(...)"))
        self.generic_visit(node)


def _scan_paths():
    for entry in SCAN:
        p = ROOT / entry
        if p.is_file():
            yield p
        else:
            yield from sorted(p.rglob("*.py"))


def test_profiler_use_confined_to_capture_module():
    offenders = []
    for path in _scan_paths():
        rel = path.relative_to(ROOT).as_posix()
        if rel in ALLOWLIST:
            continue
        v = _Visitor()
        v.visit(ast.parse(path.read_text(), filename=rel))
        offenders.extend(f"{rel}:{lineno}: {what}"
                         for lineno, what in v.offenders)
    assert not offenders, (
        "jax.profiler touches outside common/profiler_capture.py — "
        "route captures through ProfilerCapture's managed windows (or "
        "extend the allowlist with a justification):\n"
        + "\n".join(offenders))


def test_allowlist_entries_still_exist():
    for rel in ALLOWLIST:
        assert (ROOT / rel).exists(), f"stale allowlist entry: {rel}"


def test_guard_catches_a_violation():
    bad = ("import jax.profiler\n"
           "from jax import profiler\n"
           "from jax.profiler import start_trace\n"
           "def f():\n"
           "    jax.profiler.start_trace('/tmp/x')\n"
           "    profiler.stop_trace()\n")
    v = _Visitor()
    v.visit(ast.parse(bad))
    kinds = {what for _ln, what in v.offenders}
    assert "import jax.profiler" in kinds
    assert "from jax import profiler" in kinds
    assert "from jax.profiler import ..." in kinds
    assert "jax.profiler" in kinds
    assert "start_trace(...)" in kinds
    assert "stop_trace(...)" in kinds
