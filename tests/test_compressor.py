"""Compressor plugin registry (SURVEY.md §2.4: src/compressor/ — same
registry pattern as the EC plugins)."""
import numpy as np
import pytest

from ceph_tpu.compressor import CompressorRegistry, create

# capability probe: zstd needs the optional `zstandard` package, which
# not every environment ships.  An ABSENT codec is an environmental
# fact, not a code regression — those tests SKIP with the reason, so
# tier-1 signal stays clean (the registry itself already models the
# absence as an unloadable plugin; test_unavailable_algorithms covers
# that path).
_ALWAYS = ("zlib", "lzma", "bz2")           # stdlib: unconditionally present


def _available(alg: str) -> bool:
    return alg in CompressorRegistry.instance().supported()


def payload(n=65536, seed=0):
    rng = np.random.default_rng(seed)
    # compressible: repeated structured blocks + noise tail
    block = rng.integers(0, 32, size=256, dtype=np.uint8).tobytes()
    return block * (n // 256) + rng.bytes(n % 256)


@pytest.mark.parametrize("alg", ["zlib", "zstd", "lzma", "bz2"])
def test_roundtrip_and_ratio(alg):
    if alg not in _ALWAYS and not _available(alg):
        pytest.skip(f"{alg} codec unavailable in this environment "
                    f"(optional library not installed)")
    c = create(alg)
    data = payload()
    comp = c.compress(data)
    assert c.decompress(comp) == data
    assert len(comp) < len(data)        # structured data must shrink


def test_unavailable_algorithms_fail_like_unloadable_plugins():
    reg = CompressorRegistry.instance()
    for alg in ("snappy", "lz4"):
        with pytest.raises(FileNotFoundError):
            reg.create(alg)
    with pytest.raises(ValueError):
        reg.create("nope")


def test_supported_list():
    supported = set(CompressorRegistry.instance().supported())
    assert supported >= set(_ALWAYS)
    if not _available("zstd"):
        pytest.skip("zstd codec unavailable in this environment "
                    "(optional library not installed); stdlib set verified")
    assert "zstd" in supported


def test_custom_registration():
    class Null:
        name = "null"
        def compress(self, b): return bytes(b)
        def decompress(self, b): return bytes(b)
    reg = CompressorRegistry()
    reg.register("null", Null)
    c = reg.create("null")
    assert c.decompress(c.compress(b"abc")) == b"abc"
