"""lrc plugin: kml shorthand generation, layered repair, local-read
minimums, validation (mirrors src/test/erasure-code/TestErasureCodeLrc.cc
strategy)."""
import json

import numpy as np
import pytest

from ceph_tpu.plugins import ErasureCodePluginRegistry


@pytest.fixture
def registry():
    return ErasureCodePluginRegistry()


def _payload(n=4000, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8).tobytes()


KML = {"k": "4", "m": "2", "l": "3", "device": "numpy"}

LAYERS = {
    "mapping": "__DD__DD",
    "layers": json.dumps([
        ["_cDD_cDD", {"plugin": "jax_rs", "device": "numpy"}],
        ["c_DD____", {"plugin": "jax_rs", "device": "numpy"}],
        ["____c_DD", {"plugin": "jax_rs", "device": "numpy"}],
    ]),
}


# -- kml shorthand ----------------------------------------------------------

def test_kml_generates_mapping_and_layers(registry):
    ec = registry.factory("lrc", "", dict(KML))
    # k+m=6, l=3 -> 2 groups, mapping DD__DD__ style with l+1 positions/group
    assert ec.get_chunk_count() == 8        # (l+1) * groups
    assert ec.get_data_chunk_count() == 4
    assert len(ec.layers) == 3              # 1 global + 2 local
    # generated params are not exposed (ErasureCodeLrc.cc:536-545)
    assert "mapping" not in ec.get_profile()
    assert "layers" not in ec.get_profile()


@pytest.mark.parametrize("profile,match", [
    ({"k": "4", "m": "2"}, "all of k, m, l"),
    ({"k": "4", "m": "2", "l": "4"}, "multiple of l"),
    ({"k": "4", "m": "2", "l": "3", "mapping": "x"}, "cannot be set"),
    ({"k": "4", "m": "2", "l": "2"}, "k must be a multiple"),
    ({"k": "4", "m": "4", "l": "0"}, "multiple of l"),
])
def test_kml_validation(registry, profile, match):
    with pytest.raises(ValueError, match=match):
        registry.factory("lrc", "", dict(profile))


# -- explicit layers --------------------------------------------------------

def test_layers_roundtrip(registry):
    ec = registry.factory("lrc", "", dict(LAYERS))
    assert ec.get_chunk_count() == 8
    assert ec.get_data_chunk_count() == 4
    data = _payload(5000)
    want = set(range(8))
    encoded = ec.encode(want, data)
    assert set(encoded) == want
    # no erasure: decode_concat returns the payload
    assert ec.decode_concat(encoded)[:len(data)] == data


def test_local_repair_single_failure(registry):
    ec = registry.factory("lrc", "", dict(LAYERS))
    data = _payload(3000, seed=1)
    encoded = ec.encode(set(range(8)), data)
    # single failure of a data chunk in the second local group
    available = {i: v for i, v in encoded.items() if i != 6}
    decoded = ec.decode({6}, available)
    np.testing.assert_array_equal(decoded[6], encoded[6])
    # minimum set should stay inside the local layer ____c_DD
    got = ec.minimum_to_decode({6}, set(available))
    assert set(got) <= {4, 7}


def test_global_repair_two_failures(registry):
    ec = registry.factory("lrc", "", dict(LAYERS))
    data = _payload(3000, seed=2)
    encoded = ec.encode(set(range(8)), data)
    # two failures in one local group exceed the local layer (m=1) but the
    # global layer (m=2... here 'c' x2 at 1 and 5) catches them
    available = {i: v for i, v in encoded.items() if i not in (6, 7)}
    decoded = ec.decode({6, 7}, available)
    np.testing.assert_array_equal(decoded[6], encoded[6])
    np.testing.assert_array_equal(decoded[7], encoded[7])


def test_cascading_repair(registry):
    # kml layout: local layers can free up the global layer step by step
    ec = registry.factory("lrc", "", dict(KML))
    data = _payload(4096, seed=3)
    n = ec.get_chunk_count()
    encoded = ec.encode(set(range(n)), data)
    import itertools
    # all single and double erasures that lrc can structurally repair
    repaired = 0
    for lost in itertools.chain(
            ((i,) for i in range(n)),
            itertools.combinations(range(n), 2)):
        available = {i: v for i, v in encoded.items() if i not in lost}
        try:
            decoded = ec.decode(set(lost), available)
        except IOError:
            continue
        for e in lost:
            np.testing.assert_array_equal(decoded[e], encoded[e],
                                          err_msg=f"lost={lost}")
        repaired += 1
    assert repaired >= n  # at least all single failures repair


def test_minimum_to_decode_cases(registry):
    ec = registry.factory("lrc", "", dict(LAYERS))
    n = ec.get_chunk_count()
    # case 1: all wanted available
    got = ec.minimum_to_decode({2, 3}, set(range(n)))
    assert set(got) == {2, 3}
    # case impossible: too many failures everywhere
    with pytest.raises(IOError):
        ec.minimum_to_decode({2}, {0, 4})


def test_layer_validation(registry):
    # bad: layer map length mismatch
    with pytest.raises(ValueError, match="characters long"):
        registry.factory("lrc", "", {
            "mapping": "DD__",
            "layers": json.dumps([["DDc", ""]]),
        })
    # bad: layers not an array
    with pytest.raises(ValueError):
        registry.factory("lrc", "", {"mapping": "DD_",
                                     "layers": json.dumps({"a": 1})})
    # bad: missing layers entirely
    with pytest.raises(ValueError, match="layers"):
        registry.factory("lrc", "", {"mapping": "DD_"})


def test_crush_rule_steps(registry):
    ec = registry.factory("lrc", "", dict(KML))
    assert ec.rule_steps == [("chooseleaf", "host", 0)]
    ec2 = registry.factory("lrc", "", {**KML, "crush-locality": "rack"})
    assert ec2.rule_steps[0] == ("choose", "rack", 2)
    assert ec2.rule_steps[1] == ("chooseleaf", "host", 4)
    # explicit crush-steps JSON
    ec3 = registry.factory("lrc", "", {
        **LAYERS,
        "crush-steps": json.dumps([["choose", "rack", 2],
                                   ["chooseleaf", "host", 4]])})
    assert ec3.rule_steps == [("choose", "rack", 2), ("chooseleaf", "host", 4)]


def test_create_rule_with_crush_map(registry):
    from ceph_tpu.crush.map import CrushMap, CRUSH_BUCKET_STRAW2
    cmap = CrushMap()
    cmap.set_type_name(1, "host")
    cmap.set_type_name(2, "root")
    hosts = []
    for h in range(4):
        hid = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 1, [h * 2, h * 2 + 1],
                              weights=[0x10000, 0x10000])
        hosts.append(hid)
    root = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 2, hosts,
                           weights=[0x20000] * 4)
    cmap.set_item_name(root, "default")
    cmap.finalize()
    ec = registry.factory("lrc", "", dict(KML))
    ruleno = ec.create_rule("lrcrule", cmap)
    assert cmap.rule_names["lrcrule"] == ruleno
    steps = cmap.rules[ruleno].steps
    assert steps[0][1] == root
