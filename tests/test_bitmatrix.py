"""Bitmatrix RAID-6 techniques: liberation / blaum_roth / liber8tion.

Mirrors the reference's per-technique roundtrip strategy
(src/test/erasure-code/TestErasureCodeJerasure.cc) plus property tests
that pin the constructions' validity as codes: since the jerasure
submodule is empty in the reference checkout, nothing external can pin
the exact bits, so the tests prove the MDS property directly — every
single and double erasure pattern must decode (gf/bitmatrix.py).
"""
import itertools

import numpy as np
import pytest

from ceph_tpu.gf import bitmatrix as bm
from ceph_tpu.plugins import ErasureCodePluginRegistry


@pytest.fixture
def registry():
    return ErasureCodePluginRegistry()


def _roundtrip_all_erasure_pairs(coding, k, w, ps=4, seed=0):
    """Encode random chunks, then decode every 1- and 2-erasure pattern."""
    rng = np.random.default_rng(seed)
    B = w * ps * 2                                 # two packet groups
    data = rng.integers(0, 256, (k, B), dtype=np.uint8)
    packets = bm.to_packets(data, w, ps)
    parity = bm.from_packets(bm.xor_apply_host(coding, packets), w, ps)
    chunks = np.concatenate([data, parity], axis=0)     # [k+2, B]
    n = k + 2
    patterns = [(e,) for e in range(n)] + list(itertools.combinations(range(n), 2))
    for erasures in patterns:
        avail = [i for i in range(n) if i not in erasures]
        D, src = bm.decode_bitmatrix(coding, k, w, list(erasures), avail)
        stack = chunks[src]
        rec = bm.from_packets(
            bm.xor_apply_host(D, bm.to_packets(stack, w, ps)), w, ps)
        for row, e in enumerate(sorted(erasures)):
            assert np.array_equal(rec[row], chunks[e]), (erasures, e)


# -- constructions ----------------------------------------------------------

@pytest.mark.parametrize("k,w", [(2, 3), (4, 5), (7, 7), (5, 11)])
def test_liberation_all_pairs_decode(k, w):
    _roundtrip_all_erasure_pairs(bm.liberation_bitmatrix(k, w), k, w)


@pytest.mark.parametrize("k,w", [(2, 4), (4, 6), (6, 6), (8, 10)])
def test_blaum_roth_all_pairs_decode(k, w):
    _roundtrip_all_erasure_pairs(bm.blaum_roth_bitmatrix(k, w), k, w)


@pytest.mark.parametrize("k", [2, 4, 8])
def test_liber8tion_all_pairs_decode(k):
    _roundtrip_all_erasure_pairs(bm.liber8tion_bitmatrix(k), k, 8)


def test_liberation_envelope():
    with pytest.raises(ValueError):
        bm.liberation_bitmatrix(4, 6)        # w not prime
    with pytest.raises(ValueError):
        bm.liberation_bitmatrix(4, 2)        # w too small
    with pytest.raises(ValueError):
        bm.liberation_bitmatrix(8, 7)        # k > w


def test_blaum_roth_envelope():
    with pytest.raises(ValueError):
        bm.blaum_roth_bitmatrix(4, 5)        # w+1 = 6 not prime
    bm.blaum_roth_bitmatrix(4, 7)            # w=7 tolerated (Firefly compat)
    with pytest.raises(ValueError):
        bm.blaum_roth_bitmatrix(8, 6)        # k > w


def test_liber8tion_envelope():
    with pytest.raises(ValueError):
        bm.liber8tion_bitmatrix(9)           # k > 8


def test_gf2_invert_roundtrip():
    rng = np.random.default_rng(3)
    for _ in range(5):
        while True:
            M = rng.integers(0, 2, (12, 12), dtype=np.uint8)
            try:
                Minv = bm.gf2_invert(M)
                break
            except np.linalg.LinAlgError:
                continue
        assert np.array_equal(
            (M.astype(int) @ Minv.astype(int)) % 2, np.eye(12, dtype=int))


def test_gf2_invert_singular():
    M = np.zeros((4, 4), dtype=np.uint8)
    M[0, 0] = M[1, 1] = M[2, 2] = 1          # rank 3
    with pytest.raises(np.linalg.LinAlgError):
        bm.gf2_invert(M)


def test_packet_layout_roundtrip():
    rng = np.random.default_rng(4)
    chunks = rng.integers(0, 256, (3, 5 * 4 * 6), dtype=np.uint8)  # w=5 ps=4
    assert np.array_equal(
        bm.from_packets(bm.to_packets(chunks, 5, 4), 5, 4), chunks)
    # packet row i of chunk c gathers packet i of each w*ps group
    p = bm.to_packets(chunks, 5, 4)
    assert np.array_equal(p[0][:4], chunks[0][:4])
    assert np.array_equal(p[1][:4], chunks[0][4:8])
    assert np.array_equal(p[0][4:8], chunks[0][20:24])


def test_device_xor_apply_matches_host():
    from ceph_tpu.ops.rs_kernels import xor_apply
    rng = np.random.default_rng(5)
    W = rng.integers(0, 2, (14, 35), dtype=np.uint8)
    packets = rng.integers(0, 256, (35, 512), dtype=np.uint8)
    assert np.array_equal(
        np.asarray(xor_apply(W, packets)), bm.xor_apply_host(W, packets))


# -- plugin surface ---------------------------------------------------------

@pytest.mark.parametrize("profile", [
    {"technique": "liberation", "k": "4", "w": "7", "packetsize": "8"},
    {"technique": "blaum_roth", "k": "4", "w": "6", "packetsize": "8"},
    {"technique": "liber8tion", "k": "6", "packetsize": "8"},
])
def test_plugin_roundtrip(registry, profile):
    ec = registry.factory("jerasure", "", {**profile, "device": "numpy"})
    assert ec.get_chunk_count() == int(profile["k"]) + 2
    data = np.random.default_rng(6).integers(
        0, 256, 50000, dtype=np.uint8).tobytes()
    n = ec.get_chunk_count()
    encoded = ec.encode(set(range(n)), data)
    # chunk sizing honours the group alignment
    w = int(profile.get("w", "8"))
    assert len(encoded[0]) % (w * 8) == 0
    # drop two chunks (one data, one parity), recover via decode_concat
    avail = {i: encoded[i] for i in range(n) if i not in (1, n - 1)}
    assert ec.decode_concat(avail)[:50000] == data
    # decode_chunks recovers the parity chunk too
    decoded = {i: (encoded[i].copy() if i in avail
                   else np.zeros_like(encoded[i])) for i in range(n)}
    ec.decode_chunks(set(range(n)), avail, decoded)
    assert np.array_equal(decoded[n - 1], encoded[n - 1])


def test_plugin_envelope_errors(registry):
    with pytest.raises(ValueError):          # m != 2
        registry.factory("jerasure", "", {"technique": "liberation",
                                          "k": "4", "m": "3"})
    with pytest.raises(ValueError):          # w not prime
        registry.factory("jerasure", "", {"technique": "liberation",
                                          "k": "4", "w": "6"})
    with pytest.raises(ValueError):          # packetsize % 4
        registry.factory("jerasure", "", {"technique": "liberation",
                                          "k": "4", "w": "7",
                                          "packetsize": "6"})
    # liber8tion ignores profile w/m overrides (forced to 8/2)
    ec = registry.factory("jerasure", "", {"technique": "liber8tion",
                                           "k": "4", "w": "16", "m": "5",
                                           "packetsize": "8",
                                           "device": "numpy"})
    assert ec.get_chunk_count() == 6


def test_plugin_default_packetsize(registry):
    ec = registry.factory("jerasure", "",
                          {"technique": "liberation", "k": "2",
                           "device": "numpy"})
    assert ec.get_profile()["technique"] == "liberation"
    assert ec.get_alignment() == 7 * 2048


def test_plugin_chunk_mapping(registry):
    ec = registry.factory("jerasure", "",
                          {"technique": "liber8tion", "k": "2",
                           "packetsize": "4", "mapping": "D_DC",
                           "device": "numpy"})
    data = np.random.default_rng(7).integers(
        0, 256, 3000, dtype=np.uint8).tobytes()
    encoded = ec.encode(set(range(4)), data)
    avail = {i: encoded[i] for i in (0, 2, 3)}   # physical position 1 lost
    assert ec.decode_concat(avail)[:3000] == data


def test_blaum_roth_w7_compat_hazard_pinned():
    """w=7 (the reference's Firefly legacy) is accepted but NOT MDS:
    single and data+parity erasures decode; every (data, data) pair is
    undecodable — pin both facts so the compat hole stays visible."""
    k, w, ps = 4, 7, 4
    coding = bm.blaum_roth_bitmatrix(k, w)
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, (k, w * ps * 2), dtype=np.uint8)
    parity = bm.from_packets(
        bm.xor_apply_host(coding, bm.to_packets(data, w, ps)), w, ps)
    chunks = np.concatenate([data, parity], axis=0)
    ok = [(0,), (3,), (k,), (0, k), (1, k + 1), (k, k + 1)]
    for erasures in ok:
        avail = [i for i in range(k + 2) if i not in erasures]
        D, src = bm.decode_bitmatrix(coding, k, w, list(erasures), avail)
        rec = bm.from_packets(
            bm.xor_apply_host(D, bm.to_packets(chunks[src], w, ps)), w, ps)
        for row, e in enumerate(sorted(erasures)):
            assert np.array_equal(rec[row], chunks[e])
    for d1 in range(k):
        for d2 in range(d1 + 1, k):
            with pytest.raises(np.linalg.LinAlgError):
                bm.decode_bitmatrix(coding, k, w, [d1, d2],
                                    [i for i in range(k + 2)
                                     if i not in (d1, d2)])


def test_blaum_roth_plugin_default_w_is_mds(registry):
    ec = registry.factory("jerasure", "", {"technique": "blaum_roth",
                                           "k": "4", "packetsize": "8",
                                           "device": "numpy"})
    assert ec.w == 6        # NOT the reference's non-MDS w=7 legacy


def test_pallas_xor_apply_matches_host():
    """The fused pallas bitmatrix kernel (interpret mode on CPU)
    bit-matches the host XOR apply on awkward shapes."""
    from ceph_tpu.ops.pallas_kernels import xor_apply_pallas
    rng = np.random.default_rng(17)
    for R, K in ((14, 28), (16, 48), (64, 128)):   # liberation/w16/w32
        W = rng.integers(0, 2, (R, K), dtype=np.uint8)
        packets = rng.integers(0, 256, (K, 700), dtype=np.uint8)
        got = np.asarray(xor_apply_pallas(W, packets, tile_n=256,
                                          interpret=True))
        assert np.array_equal(got, bm.xor_apply_host(W, packets)), (R, K)
