"""Guard: every message class on the wire registers wire accounting.

``common/wire_accounting.py`` charges every sent message's bytes to a
per-type counter and a per-op-class rollup; the byte count for the
non-framed in-process bus comes from the per-type sizer registry.  A
message class added to ``backend/messages.py`` or ``net.py`` WITHOUT a
registered sizer would still be counted (pickle fallback + an
``unsized_msgs`` bump) but with an estimate nobody reviewed — so this
guard walks both modules by AST (the ``test_counter_help.py`` pattern:
discipline as a test), collects every dataclass that can ride the
PGChannel/RPC wire, and fails unless each one appears in the live sizer
registry.  No unmetered message types.
"""
import ast
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent

# message-shaped dataclasses that never ride a channel: local config /
# transport-internal wrappers (the _-prefixed ones are excluded by name)
NOT_WIRE_MESSAGES = {"FaultConfig"}

MESSAGE_MODULES = ("ceph_tpu/backend/messages.py", "ceph_tpu/net.py",
                   "ceph_tpu/msg/proto.py")


def _dataclass_names(path: Path) -> set[str]:
    tree = ast.parse(path.read_text(), filename=str(path))
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        for dec in node.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if isinstance(target, ast.Name) and target.id == "dataclass" \
                    or isinstance(target, ast.Attribute) and \
                    target.attr == "dataclass":
                names.add(node.name)
    return names


def test_ast_finds_message_dataclasses():
    """The guard must be scanning something real (if the message modules
    move, update MESSAGE_MODULES rather than silently guarding air)."""
    total = set()
    for rel in MESSAGE_MODULES:
        total |= _dataclass_names(ROOT / rel)
    assert len(total) >= 20, f"only {len(total)} dataclasses found"


def test_every_wire_message_registers_a_sizer():
    # importing the modules runs their register_wire_sizes() blocks
    import ceph_tpu.backend.messages  # noqa: F401
    import ceph_tpu.msg.proto  # noqa: F401
    import ceph_tpu.net  # noqa: F401
    from ceph_tpu.common.wire_accounting import registered_wire_types
    registered = registered_wire_types()
    offenders = []
    for rel in MESSAGE_MODULES:
        for name in sorted(_dataclass_names(ROOT / rel)):
            if name.startswith("_") or name in NOT_WIRE_MESSAGES:
                continue
            if name not in registered:
                offenders.append(f"{rel}: {name}")
    assert not offenders, (
        "message classes without a wire-accounting sizer (register them "
        "in register_wire_sizes next to the definition):\n"
        + "\n".join(offenders))


def test_rpc_registry_fully_metered():
    """Every type in net.py's RPC registry — the set that can actually
    arrive on a socket, including the mux batch frames msg/proto.py
    joins to it — is individually metered."""
    import ceph_tpu.msg.proto  # noqa: F401 — joins net._TYPES
    import ceph_tpu.net as net
    from ceph_tpu.common.wire_accounting import registered_wire_types
    missing = sorted(set(net._TYPES) - registered_wire_types())
    assert not missing, f"unmetered RPC types: {missing}"
    assert {"RpcBatch", "RpcResultBatch"} <= set(net._TYPES)


def test_sizers_measure_payloads():
    """Spot-check that the registered sizers weigh the payload-bearing
    fields (a sizer returning a constant would defeat the wire-per-byte
    metrics this PR exists to produce)."""
    from ceph_tpu.backend.memstore import GObject, Transaction
    from ceph_tpu.backend.messages import (ECSubReadReply, ECSubWrite,
                                           PushOp)
    from ceph_tpu.common.wire_accounting import wire_size
    small = PushOp(from_shard=0, oid="o", data=b"x" * 100)
    big = PushOp(from_shard=0, oid="o", data=b"x" * 10_000)
    assert wire_size(big) - wire_size(small) == 9_900
    t = Transaction().write(GObject("o", 1), 0, b"y" * 4096)
    w = ECSubWrite(from_shard=0, tid=1, t=t)
    assert wire_size(w) >= 4096
    r = ECSubReadReply(from_shard=1, tid=1,
                       buffers_read={"o": [(0, b"z" * 2048)]})
    assert wire_size(r) >= 2048
    from ceph_tpu.backend.messages import ECPartialSum
    ps_small = ECPartialSum(from_shard=0, tid=1, coordinator=0,
                            oids=["o"], lengths=[512], versions=[1],
                            rows=[1], targets=[3],
                            hops=[(2, 1, (7,))], attrs={},
                            acc=[b"a" * 512])
    ps_big = ECPartialSum(from_shard=0, tid=1, coordinator=0,
                          oids=["o"], lengths=[512], versions=[1],
                          rows=[1], targets=[3],
                          hops=[(2, 1, (7,))], attrs={},
                          acc=[b"a" * 8_192])
    assert wire_size(ps_big) - wire_size(ps_small) == 8_192 - 512
