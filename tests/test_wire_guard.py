"""Guard: every message class on the wire registers wire accounting.

Thin wrapper over the ``wire-sizer`` rule in
:mod:`ceph_tpu.analysis.rules_guards` (ISSUE 15); semantics unchanged —
every dataclass in the message modules that can ride the PGChannel/RPC
wire must appear in the live sizer registry, or its bytes get charged
by an unreviewed pickle estimate.  The runtime registry and sizer
spot-checks below stay as direct tests: they exercise live behaviour
the AST rule cannot see.
"""
import ceph_tpu.analysis as A
from ceph_tpu.analysis.rules_guards import MESSAGE_MODULES, _dataclass_names


def test_ast_finds_message_dataclasses():
    """The rule must be scanning something real (if the message modules
    move, update MESSAGE_MODULES rather than silently guarding air)."""
    idx = A.default_index()
    total = set()
    for mod in idx.iter_modules(MESSAGE_MODULES):
        total |= _dataclass_names(mod)
    assert len(total) >= 20, f"only {len(total)} dataclasses found"


def test_every_wire_message_registers_a_sizer():
    offenders = [f.render() for f in A.run_rules(
        A.default_index(), ("wire-sizer",))]
    assert not offenders, (
        "message classes without a wire-accounting sizer (register them "
        "in register_wire_sizes next to the definition):\n"
        + "\n".join(offenders))


def test_rpc_registry_fully_metered():
    """Every type in net.py's RPC registry — the set that can actually
    arrive on a socket, including the mux batch frames msg/proto.py
    joins to it — is individually metered."""
    import ceph_tpu.msg.proto  # noqa: F401 — joins net._TYPES
    import ceph_tpu.net as net
    from ceph_tpu.common.wire_accounting import registered_wire_types
    missing = sorted(set(net._TYPES) - registered_wire_types())
    assert not missing, f"unmetered RPC types: {missing}"
    assert {"RpcBatch", "RpcResultBatch"} <= set(net._TYPES)


def test_sizers_measure_payloads():
    """Spot-check that the registered sizers weigh the payload-bearing
    fields (a sizer returning a constant would defeat the wire-per-byte
    metrics this PR exists to produce)."""
    from ceph_tpu.backend.memstore import GObject, Transaction
    from ceph_tpu.backend.messages import (ECSubReadReply, ECSubWrite,
                                           PushOp)
    from ceph_tpu.common.wire_accounting import wire_size
    small = PushOp(from_shard=0, oid="o", data=b"x" * 100)
    big = PushOp(from_shard=0, oid="o", data=b"x" * 10_000)
    assert wire_size(big) - wire_size(small) == 9_900
    t = Transaction().write(GObject("o", 1), 0, b"y" * 4096)
    w = ECSubWrite(from_shard=0, tid=1, t=t)
    assert wire_size(w) >= 4096
    r = ECSubReadReply(from_shard=1, tid=1,
                       buffers_read={"o": [(0, b"z" * 2048)]})
    assert wire_size(r) >= 2048
    from ceph_tpu.backend.messages import ECPartialSum
    ps_small = ECPartialSum(from_shard=0, tid=1, coordinator=0,
                            oids=["o"], lengths=[512], versions=[1],
                            rows=[1], targets=[3],
                            hops=[(2, 1, (7,))], attrs={},
                            acc=[b"a" * 512])
    ps_big = ECPartialSum(from_shard=0, tid=1, coordinator=0,
                          oids=["o"], lengths=[512], versions=[1],
                          rows=[1], targets=[3],
                          hops=[(2, 1, (7,))], attrs={},
                          acc=[b"a" * 8_192])
    assert wire_size(ps_big) - wire_size(ps_small) == 8_192 - 512
