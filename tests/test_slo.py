"""mgr/slo.py + the ISSUE-10 acceptance criteria: multi-window burn
math, SLO_BURN/SLO_EXHAUSTED raise-and-clear with clusterlog receipts,
the loaded-cluster attribution table (fractions sum to 1, a
deliberately slowed phase dominates), retry-phase attribution under
transport faults, flight-bundle capture, and tools/slo_report.py
reproducing the table from artifacts alone.
"""
import importlib.util
import json
import time
from pathlib import Path

import numpy as np
import pytest

from ceph_tpu.common import Context
from ceph_tpu.common.critpath import CritPathLedger
from ceph_tpu.common.tracer import default_tracer
from ceph_tpu.mgr.slo import (
    SLOTracker, render_status, slo_burn_check, slo_exhausted_check,
    slo_objectives,
)

ROOT = Path(__file__).resolve().parent.parent

K4M2 = {"k": "4", "m": "2", "device": "numpy",
        "technique": "reed_sol_van"}


def _tracker(ledger, clock=None, **overrides):
    overrides.setdefault("slo_client_p99_ms", 10.0)
    overrides.setdefault("slo_client_target", 0.9)
    overrides.setdefault("slo_min_ops", 4)
    overrides.setdefault("slo_fast_window", 60.0)
    overrides.setdefault("slo_slow_window", 600.0)
    cct = Context(overrides=overrides)
    kw = {"clock": clock} if clock is not None else {}
    return SLOTracker(ledger, cct=cct, name="t", **kw)


class TestObjectives:
    def test_parsed_from_config(self):
        conf = Context(overrides={"slo_client_p99_ms": 40.0,
                                  "slo_recovery_p99_ms": 500.0,
                                  "slo_recovery_target": 0.99}).conf
        obj = slo_objectives(conf)
        assert set(obj) == {"client", "recovery"}
        assert obj["client"]["p99_ms"] == 40.0
        assert obj["client"]["budget"] == pytest.approx(0.001)
        assert obj["recovery"]["budget"] == pytest.approx(0.01)

    def test_zero_means_no_objective(self):
        assert slo_objectives(Context().conf) == {}


class TestBurnMath:
    def _ingest(self, led, n_good, n_bad, t):
        for _ in range(n_good):
            led.ingest("client", 0.001, {"device": 0.001}, t=t)
        for _ in range(n_bad):
            led.ingest("client", 0.050, {"device": 0.050}, t=t)

    def test_multi_window_agreement(self):
        """A fast-window blip alone stays silent; a burn present in
        BOTH windows raises; exhaustion needs the slow window past the
        exhausted threshold."""
        led = CritPathLedger(name="bm")
        try:
            now = 1000.0
            tr = _tracker(led, clock=lambda: now,
                          slo_fast_window=10.0, slo_slow_window=100.0,
                          slo_burn_rate_threshold=2.0,
                          slo_exhausted_burn_rate=8.0)
            # old clean traffic fills the slow window; a fresh blip of
            # bad ops lands only in the fast window
            self._ingest(led, 40, 0, t=920.0)        # slow window only
            self._ingest(led, 2, 6, t=995.0)         # both windows
            st = tr.class_status("client", slo_objectives(tr.cct.conf)
                                 ["client"], now=now)
            assert st["fast"]["burn"] >= 2.0
            assert st["slow"]["burn"] < 2.0
            assert not st["burning"] and not st["exhausted"]
            # sustained burn: bad ops throughout the slow window too —
            # slow = 78 ops / 36 bad -> burn 4.6x: burning, not yet
            # exhausted (threshold 8x)
            self._ingest(led, 0, 30, t=950.0)
            st = tr.class_status("client", slo_objectives(tr.cct.conf)
                                 ["client"], now=now)
            assert st["burning"]
            assert st["budget_remaining"] < 1.0
            assert not st["exhausted"]
            # pile on until bad_frac crosses 0.8 -> burn >= 8x: gone
            self._ingest(led, 0, 200, t=940.0)
            st = tr.class_status("client", slo_objectives(tr.cct.conf)
                                 ["client"], now=now)
            assert st["exhausted"]
            assert st["budget_remaining"] == 0.0
            tr.close()
        finally:
            led.close()

    def test_min_ops_gate(self):
        led = CritPathLedger(name="mo")
        try:
            now = 100.0
            tr = _tracker(led, clock=lambda: now, slo_min_ops=8)
            self._ingest(led, 0, 4, t=99.0)          # 100% bad, 4 ops
            st = tr.status(now=now)["objectives"]["client"]
            assert st["fast"]["burn"] > 2.0
            assert not st["burning"], "below min_ops must not page"
            tr.close()
        finally:
            led.close()

    def test_health_checks_raise_and_rank(self):
        led = CritPathLedger(name="hc")
        try:
            now = 50.0
            tr = _tracker(led, clock=lambda: now,
                          slo_exhausted_burn_rate=5.0)
            self._ingest(led, 0, 16, t=49.0)         # total burn
            burn = slo_burn_check(tr)()
            exhausted = slo_exhausted_check(tr)()
            # a class past the exhausted threshold reports THERE, not
            # twice (burn_check skips exhausted classes)
            assert burn is None
            assert exhausted is not None
            assert exhausted.severity == "HEALTH_ERR"
            assert "client" in exhausted.detail[0]
            tr.close()
        finally:
            led.close()

    def test_flat_series_and_render(self):
        led = CritPathLedger(name="fs")
        try:
            tr = _tracker(led)
            led.ingest("client", 0.004,
                       {"batch_delay": 0.003, "device": 0.001})
            flat = tr.flat_series()
            assert flat["client_budget_remaining"] == 1.0
            assert flat["client_p99_ms"] == pytest.approx(4.0)
            text = render_status(tr.status())
            assert "client p99 = 4.0 ms" in text
            assert "75% batch_delay" in text
            assert "ok" in text
            tr.close()
        finally:
            led.close()


@pytest.mark.filterwarnings("ignore")
class TestClusterAcceptance:
    """The ISSUE-10 acceptance: `ceph slo status` on a loaded
    MiniCluster prints per-class attribution whose fractions sum to
    1.0 (±1%), and a deliberately slowed phase dominates."""

    def _loaded_cluster(self, **overrides):
        from ceph_tpu.cluster import MiniCluster
        default_tracer().reset()
        cct = Context(overrides=overrides)
        c = MiniCluster(n_osds=6, chunk_size=1024, cct=cct)
        pid = c.create_ec_pool("slo", dict(K4M2), pg_num=4)
        rng = np.random.default_rng(3)
        data = rng.integers(0, 256, 8192, np.uint8).tobytes()
        for i in range(10):
            c.put(pid, f"o{i}", data)
        return c, pid, data

    def test_attribution_sums_to_one_and_slowed_phase_dominates(self):
        from ceph_tpu.failure import FaultPlan, StoreFaults
        c, pid, data = self._loaded_cluster(slo_client_p99_ms=30000.0)
        try:
            c.critpath.refresh()
            # now slow EVERY store read by 5 ms: the sub-read hops are
            # where that time lands, so `wire` must come to dominate
            # the client attribution for the faulted reads
            default_tracer().reset()
            c.inject_faults(FaultPlan(
                seed=2, store=StoreFaults(slow_read_prob=1.0,
                                          slow_read_ms=5.0)))
            for i in range(10):
                assert c.get(pid, f"o{i}", len(data)) == data
            out = c.cct.admin_socket.call("slo status")
            summary = out["attribution"]["client"]
            assert sum(summary["phases"].values()) == pytest.approx(
                1.0, abs=0.01)
            dominant = max(summary["phases"],
                           key=summary["phases"].get)
            assert dominant == "wire", summary["phases"]
            assert summary["phases"]["wire"] > 0.5
            # the rendered table carries the attribution line
            text = render_status(out)
            assert "client p99 =" in text and "% wire" in text
        finally:
            c.shutdown()

    def test_batch_delay_injection_dominates_serving_class(self):
        """The other acceptance arm: a serving submission that waits
        out a fat coalescer deadline attributes to batch_delay."""
        from ceph_tpu.backend import StripeInfo
        from ceph_tpu.exec import ServingEngine
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        tr = default_tracer()
        tr.reset()
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {"plugin": "jax_rs", **K4M2})
        eng = ServingEngine(cct=Context(), ec_impl=ec,
                            sinfo=StripeInfo(4, 1024),
                            name="slot", batch_max_delay_ms=50.0,
                            batch_max_ops=64,
                            pipeline_depth=0).start()
        led = CritPathLedger(name="bd")
        try:
            with tr.activate(tr.new_trace("serving")):
                fut = eng.submit_encode(
                    np.zeros(4096, np.uint8))   # non-eager: pays the
            fut.result(30)                      # full deadline
            led.refresh(tr)
            s = led.class_summary("serving")
            assert s is not None, led.snapshot()
            assert sum(s["phases"].values()) == pytest.approx(1.0,
                                                              abs=0.01)
            assert s["phases"]["batch_delay"] > 0.5, s["phases"]
            # the wait really was the deadline, not noise
            assert s["p99_ms"] >= 40.0
        finally:
            led.close()
            eng.stop()

    def test_queue_phase_attributed_through_daemon_dispatch(self):
        """Ops routed through the OSD daemon queue carry osd.queue_wait
        in their trace (the `queue` phase source)."""
        from ceph_tpu.osd.osd_ops import ObjectOperation
        c, pid, data = self._loaded_cluster()
        try:
            default_tracer().reset()
            c.operate(pid, "qq", ObjectOperation().write(0, data))
            c.critpath.refresh()
            snap = c.critpath.snapshot()
            # queue wait was stamped (near-zero in the cooperative
            # model, but PRESENT as an attributed phase event)
            evs = default_tracer().dump()["traceEvents"]
            assert any(e["name"] == "osd.queue_wait" and
                       e.get("args", {}).get("trace_id")
                       for e in evs)
            assert "client" in snap["classes"]
        finally:
            c.shutdown()


class TestBurnLifecycle:
    """SLO_BURN raises on a sustained burn and CLEARS after heal, with
    the transitions in the clusterlog — the in-tree arm of the
    chaos_run campaign check (satellite 6)."""

    def test_raise_then_clear_with_clusterlog_receipts(self):
        from ceph_tpu.cluster import MiniCluster
        default_tracer().reset()
        cct = Context(overrides={
            "slo_client_p99_ms": 0.0001,       # impossible: all ops bad
            "slo_client_target": 0.9,
            "slo_fast_window": 0.2, "slo_slow_window": 0.4,
            "slo_min_ops": 4,
        })
        c = MiniCluster(n_osds=6, chunk_size=1024, cct=cct)
        try:
            pid = c.create_ec_pool("b", dict(K4M2), pg_num=4)
            data = bytes(range(256)) * 16
            for i in range(8):
                c.put(pid, f"o{i}", data)
            c.critpath.refresh()
            checks = c.health()["checks"]
            assert "SLO_BURN" in checks or "SLO_EXHAUSTED" in checks, \
                checks
            # heal: no new bad ops; the windows drain and the burn
            # clears (idle windows below min_ops never page)
            time.sleep(0.5)
            checks = c.health()["checks"]
            assert "SLO_BURN" not in checks
            assert "SLO_EXHAUSTED" not in checks
            lines = [e["message"] for e in c.clusterlog.dump()]
            assert any("SLO_" in ln and "raised" in ln for ln in lines)
            assert any("SLO_" in ln and "cleared" in ln
                       for ln in lines), lines
        finally:
            c.shutdown()


class TestRetryPhaseUnderFaults:
    def test_tcp_blackholes_attribute_retry_time(self, tmp_path):
        """Transport faults -> bounded RPC resends -> `retry` phase
        time > 0 in the client attribution (the chaos_run receipt)."""
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.failure import FaultPlan, TransportFaults
        from ceph_tpu.net import ClusterServer, TcpRados
        default_tracer().reset()
        cct = Context(overrides={
            "ms_rpc_timeout": 2.0, "ms_rpc_retry_attempts": 5,
            "ms_reconnect_backoff_base": 0.005,
            "ms_reconnect_backoff_cap": 0.02,
        })
        c = MiniCluster(n_osds=6, chunk_size=256, cct=cct,
                        data_dir=tmp_path)
        server = ClusterServer(c)
        client = None
        try:
            # seeded: this schedule yields resends on every run without
            # ever exhausting the 5-attempt budget (decision streams are
            # per-(plane, kind), so other kinds never shift it)
            inj = c.inject_faults(FaultPlan(
                seed=11, transport=TransportFaults(blackhole_prob=0.15,
                                                   reset_prob=0.1)))
            server.inject_faults(inj)
            server.start()
            client = TcpRados("127.0.0.1", server.port,
                              tmp_path / "client.admin.keyring",
                              cct=cct)
            client.mkpool("r", profile={"plugin": "jax_rs", **K4M2},
                          pg_num=4)
            payload = bytes(range(256)) * 4
            for i in range(12):
                client.put("r", f"o{i}", payload)
            assert client.resends > 0, \
                "fault schedule produced no resends; bump probabilities"
            c.critpath.refresh()
            snap = c.critpath.snapshot()
            retry_s = sum(acc.get("retry", 0.0)
                          for acc in snap["phase_seconds"].values())
            assert retry_s > 0, snap["phase_seconds"]
        finally:
            if client is not None:
                client.close()
            server.stop()
            c.shutdown()


class TestFlightAndArtifacts:
    def _slo_report(self):
        spec = importlib.util.spec_from_file_location(
            "slo_report_t", ROOT / "tools" / "slo_report.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_flight_bundle_answers_which_phase(self, tmp_path):
        """Satellite 2: a WARN/ERR flight bundle carries the SLO +
        critical-path snapshot, and slo_report renders the attribution
        from the bundle file alone."""
        from ceph_tpu.cluster import MiniCluster
        default_tracer().reset()
        cct = Context(overrides={"slo_client_p99_ms": 20.0,
                                 "slo_client_target": 0.9})
        c = MiniCluster(n_osds=6, chunk_size=1024, cct=cct,
                        data_dir=tmp_path / "d")
        try:
            c.critpath.ingest("client", 0.050,
                              {"batch_delay": 0.040, "wire": 0.010})
            bundle = c.flight.dump(reason="test")
            assert "slo" in bundle and "critpath" in bundle["slo"]
            attribution = bundle["slo"]["slo"]["attribution"]["client"]
            assert attribution["phases"]["batch_delay"] == \
                pytest.approx(0.8)
            # the standalone tool reproduces the table from the file
            mod = self._slo_report()
            with open(bundle["path"]) as f:
                report = mod.build_report(json.load(f))
            assert report["source"] == "flight"
            text = mod.render(report)
            assert "client p99 = 50.0 ms" in text
            assert "80% batch_delay" in text
        finally:
            c.shutdown()

    def test_slo_report_from_bench_line(self, tmp_path):
        """The acceptance pin: slo_report reproduces the attribution
        table from the bench artifact alone."""
        line = {"metric": "m", "value": 1.0, "slo": {
            "device": "cpu",
            "client": {"p99_ms": 41.0, "ops": 64,
                       "phases": {"batch_delay": 0.62, "device": 0.21,
                                  "wire": 0.09, "other": 0.08},
                       "objective_p99_ms": 100.0,
                       "budget_remaining": 0.97,
                       "burn_fast": 0.1, "burn_slow": 0.2}}}
        p = tmp_path / "bench.json"
        p.write_text(json.dumps(line))
        mod = self._slo_report()
        assert mod.main([str(p), "--json"]) == 0
        report = mod.build_report(line)
        text = mod.render(report)
        assert "client p99 = 41.0 ms (64 ops): 62% batch_delay, " \
               "21% device, 9% wire" in text
        assert "97%" in text

    def test_slo_report_from_trace_dump(self, tmp_path):
        tr = default_tracer()
        tr.reset()
        with tr.activate(tr.new_trace("client")):
            with tr.span("client.op"):
                with tr.span("codec.encode"):
                    time.sleep(0.002)
        p = tmp_path / "trace.json"
        p.write_text(json.dumps(tr.dump()))
        mod = self._slo_report()
        with open(p) as f:
            report = mod.build_report(json.load(f))
        assert report["source"] == "trace"
        assert report["classes"]["client"]["ops"] == 1
        assert report["classes"]["client"]["phases"]["device"] > 0.5

    def test_bench_block_shape_gates(self):
        """The bench `slo` block exposes exactly the paths
        tools/perf_gate.py digs (slo.client.p99_ms /
        slo.client.budget_remaining)."""
        led = CritPathLedger(name="bb")
        try:
            tr = _tracker(led, slo_client_p99_ms=100.0)
            for _ in range(8):
                led.ingest("client", 0.002, {"device": 0.002})
            block = tr.bench_block("cpu")
            assert block["device"] == "cpu"
            assert block["client"]["p99_ms"] == pytest.approx(2.0)
            assert block["client"]["budget_remaining"] == 1.0
            assert sum(block["client"]["phases"].values()) == \
                pytest.approx(1.0, abs=0.01)
            tr.close()
        finally:
            led.close()
