"""ReplicatedBackend behind the PGBackend abstraction.

Mirrors the reference's ReplicatedBackend semantics (reference:
src/osd/ReplicatedBackend.cc behind src/osd/PGBackend.h:628): full-copy
fan-out, min_size = size//2+1 acks, whole-object recovery pushes, replica
deep scrub against the primary's copy — plus the same availability /
rollback / stale-shard machinery the EC backend inherits from PGBackend,
exercised by a replicated thrash campaign with kills past min_size.
"""
import numpy as np
import pytest

from ceph_tpu.backend import GObject, PGTransaction, Transaction
from ceph_tpu.backend.pg_backend import OSDShard, RecoveryState, RepairState
from ceph_tpu.backend.replicated import (ReplicatedBackend, VERSION_KEY,
                                         make_replicated_cluster)
from ceph_tpu.cluster import MiniCluster

SIZE = 3


def payload(n, seed=7):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


def store_of(bus, backend, shard):
    h = bus.handlers[shard]
    return h.store if isinstance(h, OSDShard) else h.local_shard.store


def read_obj(backend, bus, oid, length):
    out = {}
    backend.objects_read_and_reconstruct(
        {oid: [(0, length)]},
        lambda result, errors: out.update(result=result, errors=errors))
    bus.deliver_all()
    if out.get("errors"):
        raise IOError(out["errors"])
    return out["result"][oid][0][2]


@pytest.fixture()
def cluster():
    return make_replicated_cluster(SIZE)


class TestReplicatedBasics:
    def test_write_replicates_to_all(self, cluster):
        backend, bus = cluster
        data = payload(1000)
        done = []
        backend.submit_transaction(PGTransaction().write("a", 0, data),
                                   on_commit=done.append)
        bus.deliver_all()
        assert done
        for s in range(SIZE):
            assert store_of(bus, backend, s).read(GObject("a", s)) == data

    def test_partial_overwrite(self, cluster):
        backend, bus = cluster
        backend.submit_transaction(PGTransaction().write("a", 0, b"x" * 100))
        bus.deliver_all()
        backend.submit_transaction(PGTransaction().write("a", 10, b"y" * 5))
        bus.deliver_all()
        want = b"x" * 10 + b"y" * 5 + b"x" * 85
        assert read_obj(backend, bus, "a", 100) == want
        for s in range(SIZE):
            assert store_of(bus, backend, s).read(GObject("a", s)) == want

    def test_delete_and_truncate(self, cluster):
        backend, bus = cluster
        backend.submit_transaction(PGTransaction().write("a", 0, b"z" * 64))
        backend.submit_transaction(PGTransaction().truncate_to("a", 10))
        backend.submit_transaction(PGTransaction().write("b", 0, b"w" * 8))
        backend.submit_transaction(PGTransaction().delete("b"))
        bus.deliver_all()
        assert read_obj(backend, bus, "a", 10) == b"z" * 10
        assert backend.object_size("a") == 10
        for s in range(SIZE):
            assert not store_of(bus, backend, s).exists(GObject("b", s))

    def test_version_xattr_tracks_log(self, cluster):
        backend, bus = cluster
        backend.submit_transaction(PGTransaction().write("a", 0, b"1"))
        backend.submit_transaction(PGTransaction().write("a", 0, b"2"))
        bus.deliver_all()
        for s in range(SIZE):
            v = store_of(bus, backend, s).getattr(GObject("a", s),
                                                  VERSION_KEY)
            assert v == backend.pg_log.last_version_of("a")

    def test_min_size_gate(self, cluster):
        backend, bus = cluster           # size 3 -> min_size 2
        committed = []
        bus.mark_down(1)
        bus.mark_down(2)                 # 1 current < 2
        assert not backend.is_active()
        backend.submit_transaction(PGTransaction().write("a", 0, b"x" * 16),
                                   on_commit=committed.append)
        bus.deliver_all()
        assert not committed
        bus.mark_up(1)                   # auto-repair -> active again
        bus.deliver_all()
        assert committed
        assert read_obj(backend, bus, "a", 16) == b"x" * 16

    def test_recovery_pushes_full_copy(self, cluster):
        backend, bus = cluster
        data = payload(500)
        backend.submit_transaction(PGTransaction().write("a", 0, data))
        bus.deliver_all()
        lost = GObject("a", 2)
        store_of(bus, backend, 2).queue_transaction(Transaction().remove(lost))
        rop = backend.recover_object("a", {2})
        bus.deliver_all()
        assert rop.state == RecoveryState.COMPLETE
        assert store_of(bus, backend, 2).read(lost) == data
        assert store_of(bus, backend, 2).getattr(lost, VERSION_KEY) == \
            backend.pg_log.last_version_of("a")

    def test_deep_scrub_detects_bitrot(self, cluster):
        backend, bus = cluster
        backend.submit_transaction(PGTransaction().write("a", 0, b"q" * 64))
        bus.deliver_all()
        assert all(backend.be_deep_scrub("a").values())
        bad = store_of(bus, backend, 1)
        bad.queue_transaction(Transaction().write(GObject("a", 1), 5, b"!"))
        report = backend.be_deep_scrub("a")
        assert report[1] is False and report[0] and report[2]

    def test_stale_replica_repairs_via_log(self, cluster):
        backend, bus = cluster
        backend.submit_transaction(PGTransaction().write("a", 0, b"1" * 32))
        bus.deliver_all()
        bus.mark_down(2)
        backend.submit_transaction(PGTransaction().write("a", 0, b"2" * 32))
        backend.submit_transaction(PGTransaction().write("b", 0, b"3" * 32))
        bus.deliver_all()
        bus.mark_up(2)                   # auto-repair replays the 2 writes
        bus.deliver_all()
        assert 2 not in backend.stale
        for oid in ("a", "b"):
            assert all(backend.be_deep_scrub(oid).values()), oid


class TestReplicatedCluster:
    def test_pool_via_crush(self):
        c = MiniCluster(n_osds=12, chunk_size=256)
        pid = c.create_replicated_pool("rep", size=3, pg_num=8)
        data = {f"o{i}": payload(777, seed=i) for i in range(20)}
        for oid, d in data.items():
            c.put(pid, oid, d)
        for oid, d in sorted(data.items()):
            assert c.get(pid, oid, len(d)) == d
        # every PG has 3 distinct OSDs from distinct hosts
        for g in c.pools[pid]["pgs"].values():
            assert len(set(g.acting)) == 3
            hosts = {o // 3 for o in g.acting}
            assert len(hosts) == 3

    def test_ec_and_replicated_pools_coexist(self):
        c = MiniCluster(n_osds=12, chunk_size=256)
        rp = c.create_replicated_pool("rep", size=3, pg_num=4)
        ep = c.create_ec_pool("ec", {"plugin": "jax_rs", "k": "4", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        c.put(rp, "same-name", payload(512, seed=1))
        c.put(ep, "same-name", payload(2048, seed=2))
        assert c.get(rp, "same-name", 512) == payload(512, seed=1)
        assert c.get(ep, "same-name", 2048) == payload(2048, seed=2)

    def test_replicated_pool_survives_restart(self, tmp_path):
        c1 = MiniCluster(n_osds=12, chunk_size=256, data_dir=tmp_path)
        pid = c1.create_replicated_pool("rep", size=3, pg_num=4)
        data = {f"o{i}": payload(400, seed=i) for i in range(8)}
        for oid, d in data.items():
            c1.put(pid, oid, d)
        c1.shutdown()
        c2 = MiniCluster.load(tmp_path)
        pid2 = c2.pool_ids["rep"]
        for oid, d in sorted(data.items()):
            assert c2.get(pid2, oid, len(d)) == d


class TestReplicatedThrash:
    """The replicated half of the thrash matrix (the reference runs the
    Thrasher over both pool types, qa/suites/rados/thrash*)."""

    def test_thrash_replicated(self):
        rng = np.random.default_rng(99)
        cluster = MiniCluster(n_osds=12, chunk_size=256)
        pid = cluster.create_replicated_pool("thrash", size=3, pg_num=8)
        model: dict[str, bytes] = {}
        down: set[int] = set()
        kills = 0

        def pgs_for(osd):
            return [g for g in cluster.pools[pid]["pgs"].values()
                    if osd in g.acting]

        primaries = {g.backend.whoami
                     for g in cluster.pools[pid]["pgs"].values()}
        for _ in range(150):
            action = rng.random()
            if action < 0.45:
                oid = f"obj{int(rng.integers(0, 30))}"
                data = rng.integers(0, 256, int(rng.integers(1, 5)) * 256,
                                    dtype=np.uint8).tobytes()

                def committed(tid, _oid=oid, _d=data):
                    old = model.get(_oid, b"")
                    model[_oid] = _d + old[len(_d):] \
                        if len(old) > len(_d) else _d
                cluster.put(pid, oid, data, wait=False, on_commit=committed)
            elif action < 0.80 and model:
                oid = sorted(model)[int(rng.integers(0, len(model)))]
                g = cluster.pg_group(pid, oid)
                if g.backend.whoami in g.backend.current_shards() or \
                        g.backend.current_shards():
                    got = cluster.get(pid, oid, len(model[oid]))
                    assert got == model[oid], f"{oid} diverged"
            elif action < 0.92 and len(down) < 2:
                candidates = [o for o in range(12)
                              if o not in down and o not in primaries]
                if candidates:
                    osd = int(rng.choice(candidates))
                    down.add(osd)
                    kills += 1
                    for g in pgs_for(osd):
                        g.bus.mark_down(osd)
            elif down:
                osd = int(rng.choice(sorted(down)))
                down.discard(osd)
                for g in pgs_for(osd):
                    g.bus.mark_up(osd)
                    g.bus.deliver_all()

        for osd in sorted(down):
            down.discard(osd)
            for g in pgs_for(osd):
                g.bus.mark_up(osd)
                g.bus.deliver_all()
        for _ in range(10):
            busy = False
            for g in cluster.pools[pid]["pgs"].values():
                g.bus.deliver_all()
                if g.backend.stale or g.backend.shard_repairs:
                    busy = True
            if not busy:
                break
        assert kills >= 3
        for oid, want in sorted(model.items()):
            assert cluster.get(pid, oid, len(want)) == want, \
                f"{oid} lost acked data"
            g = cluster.pg_group(pid, oid)
            report = g.backend.be_deep_scrub(oid)
            assert all(report.values()), f"{oid}: dirty replicas {report}"


class TestMajorityScrub:
    """Majority-vote deep scrub (regression: the primary's copy was
    blind authority — rot ON the primary flagged every healthy replica
    and repair would have pushed the rotten copy over them)."""

    def _cluster(self):
        from ceph_tpu.cluster import MiniCluster
        c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
        pid = c.create_replicated_pool("r", size=3, pg_num=4)
        return c, pid

    def test_primary_rot_located_and_repaired(self):
        import numpy as np
        from ceph_tpu.backend.memstore import GObject
        from ceph_tpu.backend.pg_backend import shard_store
        from ceph_tpu.osd.osd_ops import ObjectOperation
        c, pid = self._cluster()
        payload = np.random.default_rng(50).integers(
            0, 256, 2000, np.uint8).tobytes()
        c.operate(pid, "pr", ObjectOperation().write_full(payload))
        g = c.pg_group(pid, "pr")
        primary = g.backend.whoami
        st = shard_store(g.bus, primary)
        st.objects[GObject("pr", primary)].data[7] ^= 0xAA
        report = c.scrub_pool(pid, repair=True)
        [bad] = [b["pr"] for b in report.values() if "pr" in b]
        assert bad == [0], f"mislocated: {report}"     # the PRIMARY
        assert c.scrub_pool(pid) == {}
        assert c.operate(pid, "pr", ObjectOperation()
                         .read(0, 0)).outdata(0)[:2000] == payload
        c.shutdown()

    def test_replica_rot_still_located(self):
        from ceph_tpu.backend.memstore import GObject
        from ceph_tpu.backend.pg_backend import shard_store
        from ceph_tpu.osd.osd_ops import ObjectOperation
        c, pid = self._cluster()
        c.operate(pid, "rr", ObjectOperation().write_full(b"q" * 1500))
        g = c.pg_group(pid, "rr")
        replica = g.acting[2]
        shard_store(g.bus, replica).objects[
            GObject("rr", replica)].data[0] ^= 0x11
        report = c.scrub_pool(pid, repair=True)
        [bad] = [b["rr"] for b in report.values() if "rr" in b]
        assert bad == [2]
        assert c.scrub_pool(pid) == {}
        c.shutdown()

    def test_two_way_tie_flags_all(self):
        from ceph_tpu.backend.memstore import GObject
        from ceph_tpu.backend.pg_backend import shard_store
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.osd.osd_ops import ObjectOperation
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_replicated_pool("r2", size=2, pg_num=4)
        c.operate(pid, "tie", ObjectOperation().write_full(b"t" * 900))
        g = c.pg_group(pid, "tie")
        shard_store(g.bus, g.acting[1]).objects[
            GObject("tie", g.acting[1])].data[0] ^= 1
        report = c.scrub_pool(pid, repair=False)
        [bad] = [b["tie"] for b in report.values() if "tie" in b]
        assert bad == [0, 1]          # detected, honestly unlocatable
        c.shutdown()

    def test_omap_divergence_detected_and_repaired(self):
        """Scrub's vote covers omap, and recovery pushes omap+header with
        the data (regression: detection was data/version-only and the
        push would have dropped the omap)."""
        from ceph_tpu.backend.memstore import GObject
        from ceph_tpu.backend.pg_backend import shard_store
        from ceph_tpu.osd.osd_ops import ObjectOperation
        c, pid = self._cluster()
        c.operate(pid, "om", ObjectOperation().write_full(b"body")
                  .omap_set({"idx": b"7"}).omap_set_header(b"H"))
        g = c.pg_group(pid, "om")
        replica = g.acting[1]
        st = shard_store(g.bus, replica)
        st.objects[GObject("om", replica)].omap["idx"] = b"CORRUPT"
        report = c.scrub_pool(pid, repair=True)
        [bad] = [b["om"] for b in report.values() if "om" in b]
        assert bad == [1], report
        assert c.scrub_pool(pid) == {}
        # the repaired replica carries the correct omap AND header
        assert st.get_omap(GObject("om", replica)) == {"idx": b"7"}
        assert st.get_omap_header(GObject("om", replica)) == b"H"
        c.shutdown()
