"""OSD daemon shell: boot/superblock, epoch gate, mClock op dispatch.

Mirrors the reference daemon skeleton (src/osd/OSD.cc init :2719,
ms_fast_dispatch :6877, sharded queue :9490-9600) at the granularity this
framework models: cooperative drain, QoS classes, superblock reload.
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.mon.heartbeat import VirtualClock
from ceph_tpu.osd.mclock import BG_SCRUB, CLIENT_OP
from ceph_tpu.osd.osd_daemon import OSDDaemon
from ceph_tpu.osd.osd_ops import MOSDOp, ObjectOperation


@pytest.fixture
def cluster():
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
    pid = c.create_ec_pool("p", {"k": "4", "m": "2", "device": "numpy"},
                           pg_num=4)
    yield c, pid
    c.shutdown()


def test_ops_route_through_primary_daemon(cluster):
    c, pid = cluster
    c.operate(pid, "obj", ObjectOperation().write_full(b"hi"))
    g = c.pg_group(pid, "obj")
    d = c.osds[g.backend.whoami]
    assert g.pgid in d.pgs
    assert d.booted is False    # boot() never ran: registered live, no sb load
    assert d.pending() == 0     # operate() drained the shard queues


def test_epoch_gate_bounces_stale_ops(cluster):
    c, pid = cluster
    c.operate(pid, "obj2", ObjectOperation().write_full(b"x"))
    g = c.pg_group(pid, "obj2")
    d = c.osds[g.backend.whoami]
    stale = MOSDOp(oid="obj2", ops=ObjectOperation().stat().ops,
                   epoch=g.epoch - 1)
    res = d.ms_dispatch(g.pgid, stale, lambda r: None)
    assert res is not None and res[0] == "stale"
    # op for a PG this daemon does not host
    other = next(dd for o, dd in c.osds.items() if o != g.backend.whoami)
    res = other.ms_dispatch(g.pgid, MOSDOp(oid="obj2", ops=[], epoch=99),
                            lambda r: None)
    assert res is not None and res[0] == "stale"


def test_mclock_classes_client_ops_not_starved(cluster):
    """With a full queue of scrub work, client ops (weight 500) are
    served far ahead of scrub items (weight 1, limit 0.001)."""
    c, pid = cluster
    c.operate(pid, "qos", ObjectOperation().write_full(b"x"))
    g = c.pg_group(pid, "qos")
    d = OSDDaemon(whoami=g.backend.whoami, num_shards=1,
                  clock=VirtualClock())
    d.register_pg(g.pgid, g)
    order = []
    for i in range(20):
        d.queue_background(g.pgid, lambda i=i: order.append(("scrub", i)),
                           op_class=BG_SCRUB)
    for i in range(5):
        # stat replies synchronously at dispatch, so `order` records true
        # dequeue order (a write's reply waits for the commit callback)
        m = MOSDOp(oid="qos", ops=ObjectOperation().stat().ops,
                   epoch=g.epoch)
        d.ms_dispatch(g.pgid, m, lambda r, i=i: order.append(("client", i)))
    d.drain()
    g.bus.deliver_all()
    # all work ran
    assert sum(1 for k, _ in order if k == "client") == 5
    assert sum(1 for k, _ in order if k == "scrub") == 20
    # every client op beat the bulk of the scrub queue
    last_client = max(i for i, (k, _) in enumerate(order) if k == "client")
    scrubs_before = sum(1 for k, _ in order[:last_client] if k == "scrub")
    assert scrubs_before <= 4, order


def test_background_limit_defers_but_completes(cluster):
    c, pid = cluster
    g = c.pg_group(pid, "bg")
    clock = VirtualClock()
    d = OSDDaemon(whoami=g.backend.whoami, num_shards=1, clock=clock)
    d.register_pg(g.pgid, g)
    ran = []
    for i in range(10):
        d.queue_background(g.pgid, lambda i=i: ran.append(i),
                           op_class=BG_SCRUB)
    t0 = clock.now()
    assert d.drain() == 10
    assert ran == list(range(10))
    # the scrub limit (0.001/s) forced the clock forward between items
    assert clock.now() > t0


def test_superblock_boot(tmp_path):
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512,
                    data_dir=tmp_path)
    pid = c.create_ec_pool("p", {"k": "2", "m": "1", "device": "numpy"},
                           pg_num=4)
    payload = np.random.default_rng(0).integers(
        0, 256, 2000, np.uint8).tobytes()
    c.operate(pid, "persist", ObjectOperation().write(0, payload))
    hosted = {o: sorted(d.pgs, key=repr) for o, d in c.osds.items() if d.pgs}
    c.shutdown()

    # cluster-level reload reconstructs the same daemon->PG hosting
    c2 = MiniCluster.load(tmp_path)
    hosted2 = {o: sorted(d.pgs, key=repr)
               for o, d in c2.osds.items() if d.pgs}
    assert hosted2 == hosted
    r = c2.operate(pid, "persist", ObjectOperation().read(0, len(payload)))
    assert r.outdata(0) == payload
    # daemon-level boot: a fresh daemon shell reads its superblock and
    # reloads exactly the PGs it hosted (OSD::init)
    osd0 = next(iter(hosted))
    fresh = OSDDaemon(osd0, meta_store=c2.osds[osd0].meta_store)
    loaded = fresh.boot(pg_loader=lambda pgid: next(
        (g for p in c2.pools.values() for g in p["pgs"].values()
         if g.pgid == pgid), None))
    assert sorted(loaded, key=repr) == hosted[osd0]
    assert fresh.booted
    c2.shutdown()


def test_primary_change_rehomes_pg():
    """down -> auto-out -> CRUSH remap: PGs whose primary changed must be
    re-registered on the new primary's daemon and dropped from the old."""
    from ceph_tpu.common import Context
    cct = Context(overrides={"mon_osd_down_out_interval": 60})
    c = MiniCluster(n_osds=12, osds_per_host=3, chunk_size=128, cct=cct)
    pid = c.create_ec_pool("p", {"k": "2", "m": "1", "device": "numpy"},
                           pg_num=8)
    mon = c.attach_monitor()
    c.put(pid, "obj", b"data" * 100)
    victim = next(g.backend.whoami               # kill a PRIMARY
                  for g in c.pools[pid]["pgs"].values())
    moved = [g.pgid for g in c.pools[pid]["pgs"].values()
             if g.backend.whoami == victim]
    reporters = [o for o in range(12) if o // 3 != victim // 3][:4]
    for r in reporters:
        mon.prepare_failure(victim, r, 0.0, 25.0)
    mon.propose_pending(25.0)
    assert mon.osdmap.is_down(victim)
    mon.tick(2000.0)                             # auto-out -> backfill
    assert mon.osdmap.is_out(victim)
    # the moved PGs are gone from the dead primary's daemon...
    for pgid in moved:
        assert pgid not in c.osds[victim].pgs
    # ...and every PG is hosted exactly by its current primary's daemon
    for gg in c.pools[pid]["pgs"].values():
        assert gg.backend.whoami != victim
        d = c.osds[gg.backend.whoami]
        assert gg.pgid in d.pgs and d.pgs[gg.pgid] is gg
    c.shutdown()
