"""Async messenger (ISSUE 14): reactor, zero-copy parser, session
multiplexing, write-queue backpressure, shed ladder, sharded front end.

The bounded-thread contract — the whole point of replacing the
thread-per-connection transport — is pinned here: a served cluster plus
thousands of logical sessions costs a FIXED set of threads (reactor +
dispatch pool + one sender), never one per connection or per client.
"""
import random
import socket
import threading
import time

import numpy as np
import pytest

from ceph_tpu.backend.wire import (BANNER, FrameParser, TAG_MESSAGE,
                                   WireError, frame_encode)
from ceph_tpu.msg import (AsyncConnection, MuxClient, Reactor, ShedPolicy,
                          ShardedFrontend, StreamParser)
from ceph_tpu.msg.frontend import FrontendBusy
from ceph_tpu.msg.shed import DEFAULT_SHED_FRACTIONS, EBUSY
from ceph_tpu.osd.mclock import BG_SCRUB, CLIENT_OP


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


# -- reactor -----------------------------------------------------------------

class TestReactor:
    def test_call_soon_crosses_threads(self):
        r = Reactor(name="t-soon").start()
        try:
            hits = []
            ev = threading.Event()
            r.call_soon(lambda: (hits.append(threading.current_thread()),
                                 ev.set()))
            assert ev.wait(5.0)
            # the callback ran ON the loop thread, not the caller's
            assert hits[0].name == "reactor.t-soon"
        finally:
            r.stop()

    def test_call_later_ordering_and_cancel(self):
        r = Reactor(name="t-timer").start()
        try:
            order = []
            done = threading.Event()
            r.call_later(0.05, lambda: order.append("b"))
            r.call_later(0.01, lambda: order.append("a"))
            t = r.call_later(0.02, lambda: order.append("cancelled"))
            t.cancel()
            r.call_later(0.08, lambda: (order.append("c"), done.set()))
            assert done.wait(5.0)
            assert order == ["a", "b", "c"]
        finally:
            r.stop()

    def test_stop_joins_loop_thread(self):
        r = Reactor(name="t-stop").start()
        assert r.running
        r.stop()
        assert not r.running
        assert not any(t.name == "reactor.t-stop"
                       for t in threading.enumerate())


# -- zero-copy stream parser -------------------------------------------------

def _rand_chunks(blob: bytes, rng: random.Random):
    i = 0
    while i < len(blob):
        n = rng.randint(1, 97)
        yield blob[i:i + n]
        i += n


class TestStreamParser:
    SECRETS = (None, b"k" * 32)

    def _frames(self, secret, n=12, seed=3):
        rng = random.Random(seed)
        out = []
        for i in range(n):
            segs = [bytes([65 + i]) * rng.randint(0, 5000)
                    for _ in range(rng.randint(1, 4))]
            out.append((TAG_MESSAGE,
                        [bytes(s) for s in segs],
                        frame_encode(TAG_MESSAGE, segs, secret=secret)))
        return out

    @pytest.mark.parametrize("secret", SECRETS,
                             ids=["crc", "secure"])
    def test_equivalent_to_frameparser_any_chunking(self, secret):
        """Same frames out of the same bytes, regardless of where recv
        boundaries fall — including 1-byte feeds mid-preamble/mid-MAC —
        and the same real on-wire sizes FrameParser.track_sizes reports."""
        frames = self._frames(secret)
        blob = b"".join(f[2] for f in frames)
        ref = FrameParser(secret)
        ref.track_sizes = True
        ref_out = ref.feed(blob)
        for seed in (1, 2, 7):
            sp = StreamParser(secret)
            got = []
            for chunk in _rand_chunks(blob, random.Random(seed)):
                for tag, segs in sp.feed(chunk):
                    got.append((tag, [bytes(s) for s in segs]))
            assert got == [(t, list(s)) for t, s in ref_out]
            assert sp.frame_sizes == ref.frame_sizes
            assert sp.pending() == 0

    def test_banner_is_stream_state(self):
        f = frame_encode(TAG_MESSAGE, [b"hello"])
        sp = StreamParser(expect_banner=True)
        blob = BANNER + f
        assert sp.feed(blob[:5]) == []
        out = sp.feed(blob[5:])
        assert [bytes(s) for _, s in out for s in s] == [b"hello"]
        with pytest.raises(WireError, match="banner"):
            StreamParser(expect_banner=True).feed(b"X" * len(BANNER))

    def test_corruption_raises_wire_error(self):
        good = frame_encode(TAG_MESSAGE, [b"payload" * 100])
        flipped = bytearray(good)
        flipped[len(good) // 2] ^= 0xFF
        with pytest.raises(WireError):
            StreamParser(None).feed(bytes(flipped))
        sec = frame_encode(TAG_MESSAGE, [b"payload"], secret=b"s" * 32)
        bad_mac = bytearray(sec)
        bad_mac[-1] ^= 0xFF
        with pytest.raises(WireError, match="MAC"):
            StreamParser(b"s" * 32).feed(bytes(bad_mac))

    def test_mid_stream_secret_switch(self):
        """The post-auth handoff: crc frames, then set_secret, then
        HMAC frames — one parser, one buffer."""
        key = b"q" * 32
        sp = StreamParser(None)
        a = sp.feed(frame_encode(TAG_MESSAGE, [b"clear"]))
        sp.set_secret(key)
        b = sp.feed(frame_encode(TAG_MESSAGE, [b"sealed"], secret=key))
        assert bytes(a[0][1][0]) == b"clear"
        assert bytes(b[0][1][0]) == b"sealed"

    def test_compaction_survives_long_streams(self):
        """Many frames through one parser: the consumed head compacts
        (no unbounded buffer growth) and every frame still parses."""
        sp = StreamParser(None)
        seen = 0
        payload = b"z" * 40_000
        for _ in range(16):
            for _, segs in sp.feed(
                    frame_encode(TAG_MESSAGE, [payload])):
                assert bytes(segs[0]) == payload
                seen += 1
        assert seen == 16
        assert len(sp._buf) < 3 * (len(payload) + 64)


# -- write-queue backpressure ------------------------------------------------

class TestBackpressure:
    def test_send_bounded_by_throttle_then_connection_error(self):
        """A peer that never drains exhausts the byte budget: send()
        blocks for its timeout, then fails AND closes the link — never
        an unbounded outbound buffer.  (register=False keeps the
        reactor from flushing, so the queue genuinely stalls.)"""
        import ceph_tpu.net as net
        a, b = socket.socketpair()
        r = Reactor(name="t-bp").start()
        try:
            conn = AsyncConnection(a, r, name="bp", secret=b"k" * 32,
                                   write_queue_bytes=8192,
                                   register=False)
            conn.send(net.RpcCall(1, "noop", {"blob": b"x" * 3000}),
                      timeout=0.5)
            conn.send(net.RpcCall(2, "noop", {"blob": b"x" * 3000}),
                      timeout=0.5)
            with pytest.raises(ConnectionError, match="write queue full"):
                conn.send(net.RpcCall(3, "noop", {"blob": b"x" * 3000}),
                          timeout=0.3)
            assert conn.closed
        finally:
            r.stop()
            a.close(), b.close()

    def test_budget_released_as_peer_drains(self):
        import ceph_tpu.net as net
        a, b = socket.socketpair()
        r = Reactor(name="t-drain").start()
        try:
            conn = AsyncConnection(a, r, name="drain", secret=b"k" * 32,
                                   write_queue_bytes=64 * 1024)
            for i in range(20):
                conn.send(net.RpcCall(i, "noop", {"blob": b"y" * 2048}),
                          timeout=2.0)
            b.setblocking(False)
            deadline = time.monotonic() + 10.0
            received = 0
            while time.monotonic() < deadline and (
                    conn.wthrottle.count > 0 or received < 20 * 2048):
                try:
                    received += len(b.recv(65536))
                except BlockingIOError:
                    time.sleep(0.01)
            assert conn.wthrottle.count == 0, "budget not fully released"
            assert received >= 20 * 2048
        finally:
            r.stop()
            a.close(), b.close()


# -- shed ladder -------------------------------------------------------------

class TestShedPolicy:
    def test_background_sheds_before_client(self):
        p = ShedPolicy(100)
        # at depth 60: scrub (threshold 50) sheds, client (100) admits
        assert p.should_shed(BG_SCRUB, 60)
        assert not p.should_shed(CLIENT_OP, 60)
        assert p.should_shed(CLIENT_OP, 100)
        snap = p.snapshot()
        assert snap["shed"][BG_SCRUB] == 1
        assert snap["shed"][CLIENT_OP] == 1 and snap["admitted"] == 1

    def test_depth_counts_logical_ops(self):
        """A mux batch sheds/admits as a unit but is COUNTED per op —
        shed_rate means the same thing batched and unbatched."""
        p = ShedPolicy(10)
        assert not p.should_shed(CLIENT_OP, 0, n=7)
        assert p.should_shed(CLIENT_OP, 10, n=3)
        assert p.snapshot()["admitted"] == 7
        assert p.shed_total == 3
        assert p.shed_rate() == pytest.approx(0.3)

    def test_ladder_ordering_matches_qos(self):
        p = ShedPolicy(1000)
        ths = {c: p.threshold(c) for c in DEFAULT_SHED_FRACTIONS}
        ordered = sorted(ths, key=ths.get)
        assert ordered[0] == BG_SCRUB and ordered[-1] == CLIENT_OP


# -- sharded front end -------------------------------------------------------

class _StubEngine:
    """depths()/submit shapes of ServingEngine, queue depth scripted."""

    def __init__(self, depth=0):
        self._depth = depth
        self.encodes = []

    def depths(self):
        return {"_total": self._depth}

    def submit_encode(self, buf, op_class, **kw):
        self.encodes.append((bytes(buf), op_class))
        return f"fut-{len(self.encodes)}"

    def submit_decode(self, chunks, op_class, **kw):
        return "dfut"

    def pressure(self):
        return self._depth / 100.0

    def start(self):
        return self

    def stop(self):
        pass

    def flush(self, timeout=None):
        pass


class TestShardedFrontend:
    def test_routing_is_stable_and_respects_locate(self):
        fe = ShardedFrontend({0: _StubEngine(), 1: _StubEngine(),
                              2: _StubEngine()})
        assert fe.shard_for("obj-a") == fe.shard_for("obj-a")
        assert {fe.shard_for(f"o{i}") for i in range(64)} == {0, 1, 2}
        placed = ShardedFrontend({0: _StubEngine(), 1: _StubEngine()},
                                 locate=lambda name: 1)
        assert placed.shard_for("anything") == 1

    def test_striped_encode_fans_pieces_across_shards(self):
        shards = {i: _StubEngine() for i in range(4)}
        fe = ShardedFrontend(shards)
        data = _data(300_000, 5)
        out = fe.submit_striped_encode("soid", data, stripe_unit=65536,
                                       stripe_count=4)
        assert len(out) >= 2                  # the object really striped
        assert len({sid for _, sid, _ in out}) >= 2
        total = sum(len(buf) for eng in shards.values()
                    for buf, _ in eng.encodes)
        assert total == len(data)             # every byte routed, once

    def test_striped_pieces_carry_the_right_bytes(self):
        """One shard so submit order == route order: each piece buffer's
        extents hold exactly the logical bytes the striper maps there."""
        eng = _StubEngine()
        fe = ShardedFrontend({0: eng})
        data = _data(300_000, 6)
        out = fe.submit_striped_encode("soid", data, stripe_unit=65536,
                                       stripe_count=4)
        routes = fe.stripe_routes("soid", len(data), stripe_unit=65536,
                                  stripe_count=4)
        assert [p for p, _, _ in routes] == [p for p, _, _ in out]
        for (pname, _sid, extents), (buf, _cls) in zip(routes,
                                                       eng.encodes):
            for p_off, l_off, n in extents:
                assert buf[p_off:p_off + n] == data[l_off:l_off + n], \
                    pname

    def test_shed_ladder_refuses_background_first(self):
        eng = _StubEngine(depth=60)
        fe = ShardedFrontend({0: eng}, queue_limit=100)
        with pytest.raises(FrontendBusy) as ei:
            fe.submit_encode("o", b"x", op_class=BG_SCRUB)
        assert ei.value.errno == EBUSY and ei.value.op_class == BG_SCRUB
        sid, fut = fe.submit_encode("o", b"x", op_class=CLIENT_OP)
        assert fut == "fut-1"
        eng._depth = 100
        with pytest.raises(FrontendBusy):
            fe.submit_encode("o", b"x", op_class=CLIENT_OP)
        stats = fe.stats()
        assert stats["routed"][0] == 1
        assert stats["shed"][0]["shed_total"] == 2

    def test_pressures_surface_engine_occupancy(self):
        fe = ShardedFrontend({0: _StubEngine(depth=50),
                              1: _StubEngine(depth=0)})
        p = fe.pressures()
        assert p[0] == pytest.approx(0.5) and p[1] == 0.0


# -- the full async stack ----------------------------------------------------

@pytest.fixture
def served(tmp_path):
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.net import ClusterServer
    c = MiniCluster(n_osds=3, osds_per_host=3, chunk_size=512,
                    data_dir=tmp_path)
    server = ClusterServer(c)
    server.start()
    yield server, tmp_path / "client.admin.keyring"
    server.stop()
    c.shutdown()


class TestMuxStack:
    def test_many_sessions_bounded_threads(self, served):
        """500 logical sessions over 2 sockets: every call lands, and
        the thread census stays FIXED — reactor threads + the dispatch
        pool + one mux sender, no per-connection or per-client spawns
        (satellite 1: the net.py thread leak is structurally gone)."""
        server, keyring = served
        before = threading.active_count()
        mux = MuxClient("127.0.0.1", server.port, keyring, n_conns=2)
        try:
            mux.connect()
            s0 = mux.session()
            s0.call("mkpool", {"name": "p", "replicated": True,
                               "size": 3})
            sessions = [mux.session() for _ in range(500)]
            calls = [s.call_async("put", {"pool": "p",
                                          "oid": f"o{i % 32}",
                                          "data": _data(256, i)})
                     for i, s in enumerate(sessions)]
            for c in calls:
                c.event.wait(30.0)
                assert c.done and c.value() == 256
            # thread count is independent of session count: allow only
            # the fixed transport threads over the baseline
            grown = threading.active_count() - before
            assert grown <= 6, \
                f"thread census grew by {grown} for 500 sessions"
            st = mux.stats()
            assert st["sessions"] == 501
            assert st["connections"] <= 2
            assert st["batches_sent"] < st["calls_sent"]  # mux coalesced
        finally:
            mux.close()

    def test_reqid_dedup_is_per_session(self, served):
        """(session, rid) is the dedup key: the same rid in two sessions
        executes twice; a resent (session, rid) executes once and both
        replies carry the first execution's result."""
        import ceph_tpu.net as net
        from ceph_tpu.msg.proto import RpcBatch
        from ceph_tpu.msg.reactor import client_reactor
        server, keyring = served
        hits = []
        server._rpc_bump = lambda ch, tag: hits.append(tag) or len(hits)
        import pickle
        with open(keyring, "rb") as f:
            key = pickle.load(f)["key"]
        sock, skey = net.dial_and_handshake("127.0.0.1", server.port, key)
        got = []
        ev = threading.Event()

        def on_msg(conn, msg):
            got.extend(msg.results)
            if len(got) >= 3:
                ev.set()
        conn = AsyncConnection(sock, client_reactor(), secret=skey,
                               name="dedup", on_message=on_msg)
        try:
            conn.send(RpcBatch([
                net.RpcCall(7, "bump", {"tag": "a"}, session="S1"),
                net.RpcCall(7, "bump", {"tag": "b"}, session="S2"),
                net.RpcCall(7, "bump", {"tag": "a"}, session="S1"),
            ]))
            assert ev.wait(20.0)
            assert hits == ["a", "b"]         # dup never re-executed
            assert server.rpc_dedup_hits >= 1
            by_order = [r.value for r in got]
            assert by_order[0] == by_order[2]  # cached first result
            assert all(r.ok for r in got)
        finally:
            conn.close()

    def test_shed_by_class_under_tiny_queue(self, served):
        """Dispatch queue clamped to 1 with every worker HELD on a gated
        rpc: background traffic bounces with EBUSY while the server
        stays up and client ops still complete.  (Holding the workers
        makes the shed deterministic — on an idle host a fast drain can
        otherwise serve the whole flood without ever filling a queue of
        one.)"""
        server, keyring = served
        server._transport.shed = ShedPolicy(1)
        server._transport.dispatcher.shed = server._transport.shed
        gate = threading.Event()
        running = threading.Semaphore(0)

        def _rpc_block(ch):
            running.release()
            gate.wait(30.0)
            return "unblocked"

        server._rpc_block = _rpc_block
        mux = MuxClient("127.0.0.1", server.port, keyring, n_conns=1)
        try:
            s = mux.session()
            s.call("mkpool", {"name": "p", "replicated": True, "size": 3})
            # ONE parked blocker stalls the whole pool: rpc dispatch
            # serializes handlers on the cluster lock, so the other
            # workers pop an op each and wait on the lock, and the flood
            # piles into the depth-1 queue
            blocker = mux.session().call_async("block", {}, timeout=30.0)
            assert running.acquire(timeout=10.0)
            outcomes = {"ok": 0, "shed": 0}
            calls = [s.call_async("ping", {"payload": i},
                                  op_class=BG_SCRUB, timeout=10.0)
                     for i in range(200)]
            gate.set()
            for c in calls:
                c.event.wait(30.0)
                try:
                    c.value()
                    outcomes["ok"] += 1
                except IOError as e:
                    assert e.errno == EBUSY
                    outcomes["shed"] += 1
            blocker.event.wait(30.0)
            assert blocker.value() == "unblocked"
            assert outcomes["shed"] > 0, "tiny queue never shed"
            assert mux.stats()["sheds_seen"] == outcomes["shed"]
            snap = server._transport.shed.snapshot()
            assert snap["shed"].get(BG_SCRUB, 0) == outcomes["shed"]
            # the link survived shedding: a client op still round-trips
            assert s.call("ping", {"payload": "after"}) == "after"
        finally:
            mux.close()

    def test_wire_accounting_partition_invariant(self, served):
        """Satellite 6: on the async transport every tx/rx byte lands in
        exactly one dmClock class — sum(class_bytes) == tx+rx totals —
        including the new RpcBatch/RpcResultBatch frames."""
        server, keyring = served
        mux = MuxClient("127.0.0.1", server.port, keyring, n_conns=2)
        try:
            s = mux.session()
            s.call("mkpool", {"name": "p", "replicated": True, "size": 3})
            calls = [s.call_async("put", {"pool": "p", "oid": f"w{i}",
                                          "data": _data(2048, i)})
                     for i in range(32)]
            for c in calls:
                c.event.wait(30.0)
                assert c.done and c.value() == 2048
            totals = server.wire.totals()
            cls = server.wire.class_bytes()
            assert totals["tx_bytes"] > 0 and totals["rx_bytes"] > 0
            assert sum(cls.values()) == \
                totals["tx_bytes"] + totals["rx_bytes"]
            per = server.wire.per_type()
            assert per.get("RpcBatch", {}).get("rx_msgs", 0) > 0, \
                "mux batches never reached the server's accountant"
        finally:
            mux.close()

    def test_tcprados_interops_with_async_server(self, served):
        """The classic one-session client and the mux client share one
        server: same pools, same data, same watch/notify plumbing."""
        from ceph_tpu.net import TcpRados
        server, keyring = served
        r = TcpRados("127.0.0.1", server.port, keyring)
        mux = MuxClient("127.0.0.1", server.port, keyring)
        try:
            r.mkpool("p", replicated=True, size=3)
            r.put("p", "shared", b"from-tcprados")
            s = mux.session()
            assert s.call("get", {"pool": "p", "oid": "shared"}) == \
                b"from-tcprados"
            s.call("put", {"pool": "p", "oid": "back",
                           "data": b"from-mux"})
            assert r.get("p", "back") == b"from-mux"
        finally:
            mux.close()
            r.close()
