"""Vmapped CRUSH vs the exact host interpreter (which is itself validated
bit-for-bit against the reference C in test_crush_golden.py)."""
import json
import os

import numpy as np
import pytest

from ceph_tpu.crush import (CRUSH_BUCKET_STRAW2, CRUSH_ITEM_NONE,
                            CRUSH_RULE_CHOOSELEAF_FIRSTN,
                            CRUSH_RULE_CHOOSELEAF_INDEP,
                            CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP,
                            CRUSH_RULE_EMIT, CRUSH_RULE_TAKE, CrushMap,
                            crush_do_rule)
from ceph_tpu.crush.jax_mapper import BulkMapper

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "crush_golden.json")
with open(GOLDEN) as f:
    G = json.load(f)

NX = 48


def _interp_padded(cmap, ruleno, x, result_max, weights, numrep,
                   choose_args=None):
    got = crush_do_rule(cmap, ruleno, x, result_max, weights,
                        choose_args=choose_args)
    return got + [CRUSH_ITEM_NONE] * (numrep - len(got))


def _compare(cmap, ruleno, result_max, weights=None, choose_args=None):
    bm = BulkMapper(cmap)
    xs = np.arange(NX)
    out, placed = bm.map_rule(ruleno, xs, reweights=weights,
                              result_max=result_max,
                              choose_args=choose_args)
    numrep = out.shape[1]
    for x in range(NX):
        want = _interp_padded(cmap, ruleno, x, result_max,
                              list(weights) if weights is not None else None,
                              numrep, choose_args=choose_args)
        assert list(out[x]) == want[:numrep], (
            f"x={x}: jax={list(out[x])} interp={want}")


def _golden_straw2_cases():
    for g in G["groups"]:
        cmap = CrushMap.from_dict(g["map"])
        if any(b.alg != CRUSH_BUCKET_STRAW2 for b in cmap.buckets.values()):
            continue
        if cmap.tunables["choose_local_tries"]:
            continue  # legacy tunables -> host interpreter only
        for run in g["runs"]:
            if len(cmap.rules[run["ruleno"]].steps) != 3:
                continue  # multi-choose rules -> host interpreter only
            yield g["map"], run


CASES = list(_golden_straw2_cases())


@pytest.mark.parametrize("case", CASES, ids=[r["name"] for _, r in CASES])
def test_bulk_matches_reference_golden(case):
    """JAX bulk mapper must equal the reference C output on golden runs."""
    map_dict, run = case
    cmap = CrushMap.from_dict(map_dict)
    bm = BulkMapper(cmap)
    nx = len(run["results"])
    out, placed = bm.map_rule(run["ruleno"], np.arange(nx),
                              reweights=run["weights"],
                              result_max=run["result_max"])
    numrep = out.shape[1]
    for x in range(nx):
        want = run["results"][x]
        want = want + [CRUSH_ITEM_NONE] * (numrep - len(want))
        assert list(out[x]) == want[:numrep], (
            f"{run['name']} x={x}: jax={list(out[x])} want={want}")


def _three_level_map(seed=0):
    """racks -> hosts -> osds with uneven weights, some zero."""
    rng = np.random.default_rng(seed)
    cmap = CrushMap()
    osd = 0
    racks = []
    for r in range(3):
        hosts = []
        for h in range(3):
            n = int(rng.integers(2, 5))
            items = list(range(osd, osd + n))
            osd += n
            w = [int(rng.integers(0, 5)) * 0x8000 for _ in items]
            hosts.append(cmap.add_bucket(CRUSH_BUCKET_STRAW2, 1, items, w))
        hw = [max(sum(cmap.buckets[h].item_weights), 0) for h in hosts]
        racks.append(cmap.add_bucket(CRUSH_BUCKET_STRAW2, 2, hosts, hw))
    rw = [sum(cmap.buckets[r].item_weights) for r in racks]
    root = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 3, racks, rw)
    cmap.finalize()
    return cmap, root


@pytest.mark.parametrize("op,numrep,ttype", [
    (CRUSH_RULE_CHOOSELEAF_FIRSTN, 3, 2),   # 3 replicas across racks
    (CRUSH_RULE_CHOOSELEAF_INDEP, 4, 1),    # EC across hosts
    (CRUSH_RULE_CHOOSE_FIRSTN, 2, 1),       # pick 2 host buckets
    (CRUSH_RULE_CHOOSE_INDEP, 3, 0),        # devices directly
    (CRUSH_RULE_CHOOSELEAF_FIRSTN, 2, 0),   # chooseleaf over osd domain
])
def test_bulk_matches_interpreter_three_level(op, numrep, ttype):
    cmap, root = _three_level_map()
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0), (op, numrep, ttype),
                            (CRUSH_RULE_EMIT, 0, 0)])
    _compare(cmap, ruleno, result_max=numrep)


def test_bulk_with_reweights():
    cmap, root = _three_level_map(seed=3)
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                            (CRUSH_RULE_CHOOSELEAF_INDEP, 4, 1),
                            (CRUSH_RULE_EMIT, 0, 0)])
    n = cmap.max_devices
    rng = np.random.default_rng(7)
    weights = [int(w) for w in rng.choice(
        [0, 0x4000, 0x8000, 0xC000, 0x10000], size=n)]
    _compare(cmap, ruleno, result_max=4, weights=weights)


def test_bulk_numrep_zero_uses_result_max():
    cmap, root = _three_level_map(seed=5)
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                            (CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1),
                            (CRUSH_RULE_EMIT, 0, 0)])
    _compare(cmap, ruleno, result_max=5)


def _host_weight_sets(cmap, n_positions, seed):
    """Per-position weight-set overrides for every host bucket (the shape
    the mgr balancer's crush-compat mode writes, mapper.c:309-326)."""
    rng = np.random.default_rng(seed)
    args = {}
    for bid, b in cmap.buckets.items():
        if b.type != 1:
            continue
        wset = []
        for _ in range(n_positions):
            wset.append([int(w * rng.choice([0.5, 0.75, 1.0, 1.25]))
                         for w in b.item_weights])
        args[bid] = {"weight_set": wset}
    return args


@pytest.mark.parametrize("op,numrep,ttype", [
    (CRUSH_RULE_CHOOSELEAF_FIRSTN, 3, 2),   # replicated shape
    (CRUSH_RULE_CHOOSELEAF_INDEP, 4, 1),    # EC shape
    (CRUSH_RULE_CHOOSE_INDEP, 3, 0),        # devices directly
    (CRUSH_RULE_CHOOSE_FIRSTN, 2, 1),       # bucket targets
])
def test_bulk_choose_args_weight_sets(op, numrep, ttype):
    """choose_args weight-set overrides: the bulk mapper must bit-match
    the host interpreter when per-position weights replace the bucket
    weights (VERDICT r3 #9; mapper.c:309-326 semantics)."""
    cmap, root = _three_level_map(seed=21)
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0), (op, numrep, ttype),
                            (CRUSH_RULE_EMIT, 0, 0)])
    args = _host_weight_sets(cmap, n_positions=numrep, seed=31)
    _compare(cmap, ruleno, result_max=numrep, choose_args=args)


def test_bulk_choose_args_single_position_and_short_sets():
    """A weight_set shorter than numrep clamps to its last entry."""
    cmap, root = _three_level_map(seed=23)
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                            (CRUSH_RULE_CHOOSELEAF_FIRSTN, 4, 1),
                            (CRUSH_RULE_EMIT, 0, 0)])
    args = _host_weight_sets(cmap, n_positions=2, seed=37)   # < numrep
    _compare(cmap, ruleno, result_max=4, choose_args=args)


def test_bulk_choose_args_ids_override():
    """``ids`` overrides reseed the straw2 hash while returning the
    bucket's own items."""
    cmap, root = _three_level_map(seed=29)
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                            (CRUSH_RULE_CHOOSELEAF_INDEP, 4, 1),
                            (CRUSH_RULE_EMIT, 0, 0)])
    args = {}
    for bid, b in cmap.buckets.items():
        if b.type == 1:
            args[bid] = {"ids": [int(i) + 1000 for i in b.items]}
    _compare(cmap, ruleno, result_max=4, choose_args=args)


def test_bulk_choose_args_mixed_with_reweights():
    cmap, root = _three_level_map(seed=31)
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                            (CRUSH_RULE_CHOOSELEAF_INDEP, 4, 1),
                            (CRUSH_RULE_EMIT, 0, 0)])
    args = _host_weight_sets(cmap, n_positions=4, seed=43)
    n = cmap.max_devices
    rng = np.random.default_rng(47)
    weights = [int(w) for w in rng.choice(
        [0, 0x8000, 0x10000], size=n, p=[0.1, 0.3, 0.6])]
    _compare(cmap, ruleno, result_max=4, weights=weights, choose_args=args)


def test_compile_rejects_unsupported():
    from ceph_tpu.crush import CRUSH_BUCKET_LIST
    cmap = CrushMap()
    cmap.add_bucket(CRUSH_BUCKET_LIST, 1, [0, 1], [0x10000, 0x10000])
    cmap.finalize()
    with pytest.raises(ValueError, match="straw2"):
        BulkMapper(cmap)
    cmap2, root = _three_level_map()
    cmap2.tunables["choose_local_tries"] = 2
    with pytest.raises(ValueError, match="local retry"):
        BulkMapper(cmap2)


def _ceph_id_order_map():
    """Root gets id -1, children -2..: the Ceph-default id assignment
    (regression: depth must not rely on id ordering)."""
    cmap = CrushMap()
    # reserve -1 for root by building top-down with explicit ids
    cmap.add_bucket(CRUSH_BUCKET_STRAW2, 3, [-2, -3], [0x40000, 0x40000],
                    id=-1)
    cmap.add_bucket(CRUSH_BUCKET_STRAW2, 1, [0, 1], [0x20000, 0x20000], id=-2)
    cmap.add_bucket(CRUSH_BUCKET_STRAW2, 1, [2, 3], [0x20000, 0x20000], id=-3)
    cmap.finalize()
    return cmap


def test_bulk_root_id_minus_one():
    cmap = _ceph_id_order_map()
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, -1, 0),
                            (CRUSH_RULE_CHOOSELEAF_FIRSTN, 2, 1),
                            (CRUSH_RULE_EMIT, 0, 0)])
    out, placed = BulkMapper(cmap).map_rule(ruleno, np.arange(16),
                                            result_max=2)
    assert (out != CRUSH_ITEM_NONE).all(), "depth bug: all-NONE placements"
    _compare(cmap, ruleno, result_max=2)


def test_bulk_result_max_smaller_than_numrep():
    """The retry stride must keep the rule's numrep even when result_max
    clamps the output (regression for the out_size/numrep split)."""
    cmap, root = _three_level_map(seed=11)
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                            (CRUSH_RULE_CHOOSE_INDEP, 5, 0),
                            (CRUSH_RULE_EMIT, 0, 0)])
    _compare(cmap, ruleno, result_max=3)
    ruleno2 = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                             (CRUSH_RULE_CHOOSELEAF_FIRSTN, 4, 1),
                             (CRUSH_RULE_EMIT, 0, 0)])
    _compare(cmap, ruleno2, result_max=2)
