"""Generator-matrix properties: systematic form, MDS, decode-matrix algebra.

Mirrors the reference's per-plugin roundtrip strategy
(src/test/erasure-code/TestErasureCodeJerasure.cc:80-135 etc.)."""
import itertools

import numpy as np
import pytest

from ceph_tpu.gf import (rs_vandermonde_isa, rs_vandermonde_jerasure, cauchy1,
                         generator_matrix, gf_matmul, gf_invert, decode_matrix,
                         gf_mul, gf_pow)
from ceph_tpu.gf import ref


def _mds_check(parity, k, m):
    """Every way of losing <= m chunks must leave an invertible system."""
    gen = generator_matrix(parity)
    n = k + m
    for lost in itertools.combinations(range(n), m):
        rows = [i for i in range(n) if i not in lost][:k]
        sub = gen[rows, :]
        inv = gf_invert(sub)  # raises if singular
        assert (gf_matmul(inv, sub) == np.eye(k, dtype=np.uint8)).all()


@pytest.mark.parametrize("k,m", [(2, 1), (3, 2), (4, 2), (6, 3), (8, 4)])
def test_isa_vandermonde_mds(k, m):
    _mds_check(rs_vandermonde_isa(k, m), k, m)


@pytest.mark.parametrize("k,m", [(2, 1), (3, 2), (4, 2), (6, 3), (8, 4), (10, 4)])
def test_cauchy_mds(k, m):
    _mds_check(cauchy1(k, m), k, m)


@pytest.mark.parametrize("k,m", [(2, 1), (3, 2), (4, 2), (7, 3), (8, 4)])
def test_jerasure_vandermonde_mds(k, m):
    _mds_check(rs_vandermonde_jerasure(k, m), k, m)


def test_isa_vandermonde_values():
    # gf_gen_rs_matrix semantics: row r col j == 2^(r*j)
    a = rs_vandermonde_isa(4, 3)
    for r in range(3):
        for j in range(4):
            assert a[r, j] == gf_pow(gf_pow(2, r), j)
    assert (a[0] == 1).all()


def test_jerasure_vandermonde_structure():
    # systematic extended-Vandermonde, column-normalised the way jerasure
    # publishes it: the FIRST PARITY ROW is all ones (XOR — the reason
    # reed_sol_r6_op's P drive is an XOR), and the construction is
    # deterministic.
    for k, m in [(3, 2), (7, 3), (8, 4)]:
        a = rs_vandermonde_jerasure(k, m)
        assert (a[0, :] == 1).all()
        b = rs_vandermonde_jerasure(k, m)
        assert (a == b).all()


def test_decode_matrix_identity_when_parity_lost():
    # losing only parity chunks: decode matrix rows are parity rows themselves
    parity = cauchy1(4, 2)
    D, src = decode_matrix(parity, [4])
    assert src == [0, 1, 2, 3]
    assert (D == parity[0:1]).all()


@pytest.mark.parametrize("technique", [rs_vandermonde_isa, rs_vandermonde_jerasure, cauchy1])
def test_roundtrip_all_erasure_patterns(technique):
    k, m, n = 4, 2, 64
    parity = technique(k, m)
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, size=(k, n), dtype=np.uint8)
    par = ref.encode(parity, data)
    full = {i: data[i] for i in range(k)} | {k + i: par[i] for i in range(m)}
    for lost in itertools.combinations(range(k + m), m):
        chunks = {i: v for i, v in full.items() if i not in lost}
        rec = ref.decode(parity, chunks, list(lost))
        for e in lost:
            np.testing.assert_array_equal(rec[e], full[e], err_msg=f"lost={lost} e={e}")


def test_gf_invert_random():
    rng = np.random.default_rng(3)
    for _ in range(20):
        mat = rng.integers(0, 256, size=(5, 5), dtype=np.uint8)
        try:
            inv = gf_invert(mat)
        except np.linalg.LinAlgError:
            continue
        assert (gf_matmul(inv, mat) == np.eye(5, dtype=np.uint8)).all()
