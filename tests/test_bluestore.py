"""BlueStore-lite: extent allocation, checksums at rest, compression,
blob-sharing clones, restart survival (r4 VERDICT missing #2; reference:
src/os/bluestore/BlueStore.cc structure, src/os/ObjectStore.h contract)."""
import pickle

import numpy as np
import pytest

from ceph_tpu.backend.bluestore import (BlueStoreLite, ChecksumError,
                                        RunListAllocator)
from ceph_tpu.backend.memstore import GObject, MemStore, Transaction


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


@pytest.fixture
def bs(tmp_path):
    s = BlueStoreLite(tmp_path / "bs", min_alloc=512)
    yield s
    s.close()


class TestAllocator:
    def test_alloc_free_coalesce(self):
        a = RunListAllocator(512)
        o1, l1 = a.alloc(1000)          # 2 units
        o2, l2 = a.alloc(512)           # 1 unit
        assert (o1, l1) == (0, 1024) and (o2, l2) == (1024, 512)
        a.free(o1, l1)
        a.free(o2, l2)                  # coalesces into one run
        assert a.runs == [[0, 3]]
        o3, _ = a.alloc(1536)           # first-fit reuses the hole
        assert o3 == 0
        assert a.watermark == 3

    def test_rebuild_from_blobs(self):
        from ceph_tpu.backend.bluestore import Blob
        a = RunListAllocator(512)
        blobs = {1: Blob(poff=512, plen=400, alloc=512, raw_len=400,
                         csum=0, comp=None),
                 2: Blob(poff=2048, plen=512, alloc=512, raw_len=512,
                         csum=0, comp=None)}
        a.rebuild(blobs)
        assert a.runs == [[0, 1], [2, 2]]
        assert a.watermark == 5


class TestStoreContract:
    """MemStore-equivalence: every Transaction op produces identical
    observable state on both stores."""

    OPS = [
        lambda t, g: t.write(g, 0, _data(700, 1)),
        lambda t, g: t.write(g, 300, _data(600, 2)),   # overlapping rmw
        lambda t, g: t.zero(g, 100, 250),
        lambda t, g: t.truncate(g, 450),
        lambda t, g: t.truncate(g, 900),               # extend
        lambda t, g: t.write(g, 2000, _data(64, 3)),   # hole
        lambda t, g: t.setattr(g, "a", {"x": 1}),
        lambda t, g: t.omap_setkeys(g, {"k": b"v"}),
        lambda t, g: t.omap_setheader(g, b"hdr"),
    ]

    def test_matches_memstore(self, bs):
        mem = MemStore()
        g = GObject("o", 3)
        for op in self.OPS:
            for store in (bs, mem):
                t = Transaction()
                op(t, g)
                store.queue_transaction(t)
            assert bs.read(g) == mem.read(g)
            assert bs.stat(g) == mem.stat(g)
        assert bs.getattrs(g) == mem.getattrs(g)
        assert bs.get_omap(g) == mem.get_omap(g)
        assert bs.get_omap_header(g) == mem.get_omap_header(g)

    def test_random_rmw_fuzz_matches_memstore(self, bs):
        """rmw-heavy fuzz: random overlapping writes/zeros/truncates must
        track MemStore byte-for-byte (the extent-map surgery is the
        riskiest code here)."""
        rng = np.random.default_rng(42)
        mem = MemStore()
        g = GObject("fuzz", 0)
        for i in range(300):
            t1, t2 = Transaction(), Transaction()
            kind = rng.integers(0, 10)
            off = int(rng.integers(0, 5000))
            ln = int(rng.integers(1, 2000))
            if kind < 6:
                d = _data(ln, 1000 + i)
                t1.write(g, off, d)
                t2.write(g, off, d)
            elif kind < 8:
                t1.zero(g, off, ln)
                t2.zero(g, off, ln)
            else:
                t1.truncate(g, off)
                t2.truncate(g, off)
            bs.queue_transaction(t1)
            mem.queue_transaction(t2)
            if i % 37 == 0:
                assert bs.read(g) == mem.read(g), i
        assert bs.read(g) == mem.read(g)
        # every live blob is referenced by exactly its extent count
        refcount = {}
        for onode in bs.onodes.values():
            for e in onode.extents:
                refcount[e.blob] = refcount.get(e.blob, 0) + 1
        assert refcount == {bid: b.refs for bid, b in bs.blobs.items()}

    def test_remove_frees_space(self, bs):
        g = GObject("big", 0)
        bs.queue_transaction(Transaction().write(g, 0, _data(8192, 5)))
        used = bs.usage()["allocated_bytes"]
        assert used >= 8192
        bs.queue_transaction(Transaction().remove(g))
        assert bs.usage()["allocated_bytes"] == 0
        assert bs.usage()["free_bytes"] >= used
        # the freed space is REUSED, not appended after
        wm = bs.alloc.watermark
        bs.queue_transaction(Transaction().write(GObject("n", 0), 0,
                                                 _data(4096, 6)))
        assert bs.alloc.watermark == wm

    def test_clone_shares_blobs(self, bs):
        g, c = GObject("h", 0), GObject("h\x00snap\x001", 0)
        payload = _data(4096, 7)
        bs.queue_transaction(Transaction().write(g, 0, payload)
                             .setattr(g, "t", b"v"))
        before = bs.usage()["allocated_bytes"]
        bs.queue_transaction(Transaction().clone(g, c))
        # O(extent-map) clone: no new data allocation
        assert bs.usage()["allocated_bytes"] == before
        assert bs.read(c) == payload
        assert bs.getattr(c, "t") == b"v"
        # COW: overwriting the head leaves the clone intact
        bs.queue_transaction(Transaction().write(g, 0, _data(4096, 8)))
        assert bs.read(c) == payload
        # dropping the head keeps the shared blob alive for the clone
        bs.queue_transaction(Transaction().remove(g))
        assert bs.read(c) == payload


class TestChecksums:
    def test_bitrot_at_rest_detected(self, bs):
        g = GObject("x", 0)
        bs.queue_transaction(Transaction().write(g, 0, _data(2048, 9)))
        blob = next(iter(bs.blobs.values()))
        # flip one byte of the stored data behind the store's back
        bs._block.seek(blob.poff + 100)
        orig = bs._block.read(1)
        bs._block.seek(blob.poff + 100)
        bs._block.write(bytes([orig[0] ^ 0xFF]))
        with pytest.raises(ChecksumError):
            bs.read(g)
        # repair (rewrite) clears the error
        bs.queue_transaction(Transaction().write(g, 0, _data(2048, 9)))
        assert bs.read(g) == _data(2048, 9)


class TestCompression:
    def test_compressible_data_saves_units(self, tmp_path):
        s = BlueStoreLite(tmp_path / "c", min_alloc=512,
                          compression="zlib")
        g = GObject("z", 0)
        payload = b"A" * 65536                   # wildly compressible
        s.queue_transaction(Transaction().write(g, 0, payload))
        u = s.usage()
        assert u["compressed_blobs"] == 1
        assert u["allocated_bytes"] < len(payload) // 4
        assert s.read(g) == payload
        # partial reads decompress and slice exactly
        assert s.read(g, 1000, 500) == payload[1000:1500]
        s.close()
        # survives restart (comp metadata persisted)
        s2 = BlueStoreLite(tmp_path / "c", min_alloc=512,
                           compression="zlib")
        assert s2.read(g) == payload
        s2.close()

    def test_incompressible_data_stays_raw(self, tmp_path):
        s = BlueStoreLite(tmp_path / "r", min_alloc=512,
                          compression="zlib")
        g = GObject("rnd", 0)
        payload = _data(8192, 11)               # random: incompressible
        s.queue_transaction(Transaction().write(g, 0, payload))
        assert s.usage()["compressed_blobs"] == 0
        assert s.read(g) == payload
        s.close()


class TestDurability:
    def test_restart_survival(self, tmp_path):
        s = BlueStoreLite(tmp_path / "d", min_alloc=512)
        g1, g2 = GObject("a", 0), GObject("b", 1)
        s.queue_transaction(Transaction().write(g1, 0, _data(3000, 12))
                            .setattr(g1, "k", b"v")
                            .omap_setkeys(g1, {"o": b"m"}))
        s.queue_transaction(Transaction().write(g2, 100, _data(700, 13)))
        s.close()                               # checkpoint path
        s2 = BlueStoreLite(tmp_path / "d", min_alloc=512)
        assert s2.read(g1) == _data(3000, 12)
        assert s2.getattr(g1, "k") == b"v"
        assert s2.get_omap(g1) == {"o": b"m"}
        assert s2.read(g2, 100, 700) == _data(700, 13)
        assert s2.stat(g2) == 800
        s2.close()

    def test_wal_replay_without_checkpoint(self, tmp_path):
        s = BlueStoreLite(tmp_path / "w", min_alloc=512)
        g = GObject("a", 0)
        s.queue_transaction(Transaction().write(g, 0, _data(1500, 14)))
        s.queue_transaction(Transaction().write(g, 500, _data(400, 15)))
        want = s.read(g)
        s._wal.flush()
        s._block.flush()
        # crash: no close/checkpoint
        s2 = BlueStoreLite(tmp_path / "w", min_alloc=512)
        assert s2.read(g) == want
        # allocator rebuilt: new writes do not clobber live blobs
        s2.queue_transaction(Transaction().write(GObject("n", 0), 0,
                                                 _data(2048, 16)))
        assert s2.read(g) == want
        s2.close()

    def test_torn_wal_tail_discarded(self, tmp_path):
        s = BlueStoreLite(tmp_path / "t", min_alloc=512)
        g = GObject("a", 0)
        s.queue_transaction(Transaction().write(g, 0, b"committed"))
        s._wal.flush()
        s._block.flush()
        # simulate a crash mid-append: garbage half-record at the tail
        with open(s.path / "kv.log", "ab") as f:
            f.write(b"\x99" * 7)
        s2 = BlueStoreLite(tmp_path / "t", min_alloc=512)
        assert s2.read(g) == b"committed"
        # the store keeps working (tail truncated)
        s2.queue_transaction(Transaction().write(g, 0, b"next"))
        s2.close()
        s3 = BlueStoreLite(tmp_path / "t", min_alloc=512)
        assert s3.read(g, 0, 4) == b"next"
        s3.close()

    def test_metadata_checkpoint_excludes_data(self, tmp_path):
        """The checkpoint is metadata-only: its size must not scale with
        data volume (the r4 FileStore whole-store-pickle weakness)."""
        s = BlueStoreLite(tmp_path / "m", min_alloc=4096)
        for i in range(8):
            s.queue_transaction(Transaction().write(
                GObject(f"o{i}", 0), 0, _data(1 << 18, i)))   # 2 MiB total
        s.close()
        snap_size = (tmp_path / "m" / "kv.snap").stat().st_size
        block_size = (tmp_path / "m" / "block").stat().st_size
        assert block_size >= 1 << 21
        assert snap_size < 64 * 1024


class TestScrubWithChecksumsAtRest:
    def test_scrub_flags_rotten_blob(self, tmp_path):
        """Bitrot injected into a replica's blob AT REST: the store's own
        crc32c locates it during deep scrub — no majority vote needed —
        and repair restores the copy."""
        from ceph_tpu.cluster import MiniCluster
        c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                        data_dir=tmp_path, store_backend="bluestore")
        pid = c.create_replicated_pool("p", size=3, pg_num=4)
        payload = _data(3000, 77)
        c.put(pid, "rotten", payload)
        g = c.pg_group(pid, "rotten")
        peer = next(s for s in g.acting if s != g.backend.whoami)
        _rot_shard_copy(c, pid, "rotten", peer)
        rep = c.scrub_pool(pid)
        assert any("rotten" in o for bad in rep.values() for o in bad)
        # scrub's repair rewrote the copy: clean now, reads fine
        assert c.scrub_pool(pid) == {}
        assert c.get(pid, "rotten", len(payload)) == payload
        c.shutdown()


def _rot_shard_copy(c, pid, oid, shard):
    """Flip one at-rest byte of ``oid``'s copy on ``shard`` behind the
    store's back (the blob-level bitrot injection)."""
    bs = c.osds[shard].store
    target = next(go for go in bs.onodes
                  if go.oid.endswith(oid) and go.shard == shard)
    blob = bs.blobs[bs.onodes[target].extents[0].blob]
    bs._block.seek(blob.poff)
    b0 = bs._block.read(1)
    bs._block.seek(blob.poff)
    bs._block.write(bytes([b0[0] ^ 0xFF]))
    bs._block.flush()


class TestRottenSourceRecovery:

    def test_ec_rmw_read_retries_past_rotten_chunk(self, tmp_path):
        """A partial-stripe overwrite whose RMW read hits a rotten source
        chunk must widen to a parity chunk, not hand the decode k-1
        chunks (regression: reply errors were silently discarded)."""
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.osd.osd_ops import ObjectOperation
        c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                        data_dir=tmp_path, store_backend="bluestore")
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        payload = _data(2048, 21)
        c.operate(pid, "rmw", ObjectOperation().write_full(payload))
        g = c.pg_group(pid, "rmw")
        data_shard = g.acting[1]              # a non-primary data chunk
        _rot_shard_copy(c, pid, "rmw", data_shard)
        # partial overwrite: RMW reads the stripe, hits the rot, widens
        patch = _data(100, 22)
        c.operate(pid, "rmw", ObjectOperation().write(300, patch))
        want = bytearray(payload)
        want[300:400] = patch
        r = c.operate(pid, "rmw", ObjectOperation().read(0, 0))
        assert r.outdata(0)[:len(want)] == bytes(want)
        c.shutdown()

    def test_ec_recovery_rebuilds_rotten_source_too(self, tmp_path):
        """Recovery reading a rotten source must drop it, rebuild from
        clean chunks, and repair the rotten shard as well (regression:
        the -5 reply failed the whole recovery op forever)."""
        from ceph_tpu.backend.pg_backend import RecoveryState
        from ceph_tpu.cluster import MiniCluster
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512,
                        data_dir=tmp_path, store_backend="bluestore")
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        payload = _data(2048, 23)
        c.put(pid, "rec", payload)
        g = c.pg_group(pid, "rec")
        rotten = g.acting[2]
        _rot_shard_copy(c, pid, "rec", rotten)
        missing_chunk = 3                     # rebuild the last chunk
        rop = g.backend.recover_object("rec", {missing_chunk})
        g.bus.deliver_all()
        assert rop.state == RecoveryState.COMPLETE
        # the rotten chunk was detected and repaired alongside
        assert 2 in rop.missing_shards
        assert c.get(pid, "rec", len(payload)) == payload
        assert c.scrub_pool(pid) == {}
        c.shutdown()


class TestClusterIntegration:
    def test_minicluster_on_bluestore(self, tmp_path):
        """A durable cluster on BlueStore-lite: EC pool IO, rmw-heavy
        churn, restart, deep scrub with checksums at rest."""
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.osd.osd_ops import ObjectOperation
        c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                        data_dir=tmp_path, store_backend="bluestore")
        pid = c.create_ec_pool("p", {"k": "2", "m": "1",
                                     "device": "numpy"}, pg_num=8)
        rng = np.random.default_rng(0)
        model = {}
        for i in range(10):
            model[f"o{i}"] = _data(1500 + 37 * i, 50 + i)
            c.operate(pid, f"o{i}", ObjectOperation()
                      .write_full(model[f"o{i}"]).setxattr("t", b"x"))
        for step in range(60):                   # rmw churn
            oid = f"o{int(rng.integers(0, 10))}"
            off = int(rng.integers(0, 1000))
            d = _data(int(rng.integers(50, 600)), 500 + step)
            c.operate(pid, oid, ObjectOperation().write(off, d))
            cur = bytearray(model[oid])
            if len(cur) < off + len(d):
                cur.extend(b"\0" * (off + len(d) - len(cur)))
            cur[off:off + len(d)] = d
            model[oid] = bytes(cur)
        c.shutdown()
        c2 = MiniCluster.load(tmp_path)
        for oid, want in model.items():
            r = c2.operate(pid, oid, ObjectOperation().read(0, 0))
            assert r.outdata(0)[:len(want)] == want, oid
        assert c2.scrub_pool(pid) == {}
        c2.shutdown()


class TestBlueStoreComposition:
    def test_snaps_kills_rot_restart_campaign(self, tmp_path):
        """Everything at once on the bluestore backend: snapshots with
        COW clones, an OSD death and revival mid-writes, at-rest bitrot
        located by the store's checksums and repaired by scrub, then a
        full restart recovering every PG from the per-OSD block files."""
        from ceph_tpu.cluster import BlockedWriteError, MiniCluster
        from ceph_tpu.common import Context
        from ceph_tpu.osd.osd_ops import ObjectOperation
        cct = Context(overrides={"mon_osd_down_out_interval": 10_000})
        c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                        data_dir=tmp_path, store_backend="bluestore",
                        cct=cct)
        pid = c.create_replicated_pool("p", size=3, pg_num=8)
        model, snaps = {}, {}
        for i in range(12):
            model[f"o{i}"] = _data(900 + 31 * i, i)
            c.operate(pid, f"o{i}", ObjectOperation()
                      .write_full(model[f"o{i}"]))
        sid = c.create_pool_snap(pid, "s1")
        snaps[sid] = dict(model)
        # kill an OSD, write through the degradation
        victim = c.pg_group(pid, "o0").acting[1]
        c.bus.mark_down(victim)
        for i in range(12):
            new = _data(700 + 13 * i, 100 + i)
            try:
                c.operate(pid, f"o{i}",
                          ObjectOperation().write_full(new))
                model[f"o{i}"] = new
            except BlockedWriteError:
                c.bus.mark_up(victim)
                c.bus.deliver_all()
                model[f"o{i}"] = new
                c.bus.mark_down(victim)
        c.bus.mark_up(victim)
        c.bus.deliver_all()
        # at-rest rot on a non-primary copy of one object
        g = c.pg_group(pid, "o3")
        peer = next(s for s in g.acting if s != g.backend.whoami)
        _rot_shard_copy(c, pid, "o3", peer)
        rep = c.scrub_pool(pid)
        assert any("o3" in o for bad in rep.values() for o in bad)
        assert c.scrub_pool(pid) == {}          # repaired
        # snapshot isolation held through all of it
        r = c.operate(pid, "o5", ObjectOperation().read(0, 0), snapid=sid)
        assert r.outdata(0)[:len(snaps[sid]["o5"])] == snaps[sid]["o5"]
        c.shutdown()
        # restart: everything recovers from the per-OSD block files
        c2 = MiniCluster.load(tmp_path)
        for oid, want in model.items():
            r = c2.operate(pid, oid, ObjectOperation().read(0, 0))
            assert r.outdata(0)[:len(want)] == want, oid
        r = c2.operate(pid, "o5", ObjectOperation().read(0, 0),
                       snapid=sid)
        assert r.outdata(0)[:len(snaps[sid]["o5"])] == snaps[sid]["o5"]
        assert c2.scrub_pool(pid) == {}
        c2.shutdown()
