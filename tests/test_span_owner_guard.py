"""Guard: spans in the serving/recovery layers must carry an owner class.

Sibling of ``test_no_bare_time.py``: device-time attribution
(common/device_attribution) only works if the work crossing the chip is
TAGGED.  ``ceph_tpu/exec/`` and ``ceph_tpu/recovery/`` are the layers
that dispatch on behalf of someone else (serving batches, repair waves),
so every span opened there must say WHOSE work it is — an ``owner=``
keyword with a canonical owner class — or the attribution ledger and the
``device top`` command silently misfile the time as client work.
"""
import ast
from pathlib import Path

from ceph_tpu.common.device_attribution import OWNER_CLASSES

ROOT = Path(__file__).resolve().parent.parent
SCAN_DIRS = ("ceph_tpu/exec", "ceph_tpu/recovery")

_SPAN_CALLS = {"trace_span", "span"}     # trace_span(...) / tracer.span(...)


def _span_call_name(call: ast.Call) -> str | None:
    fn = call.func
    if isinstance(fn, ast.Name) and fn.id in _SPAN_CALLS:
        return fn.id
    if isinstance(fn, ast.Attribute) and fn.attr in _SPAN_CALLS:
        return fn.attr
    return None


def test_spans_in_exec_and_recovery_carry_owner_class():
    offenders = []
    for sub in SCAN_DIRS:
        for path in sorted((ROOT / sub).rglob("*.py")):
            rel = path.relative_to(ROOT).as_posix()
            tree = ast.parse(path.read_text())
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call) or \
                        _span_call_name(node) is None:
                    continue
                owner = next((kw.value for kw in node.keywords
                              if kw.arg == "owner"), None)
                if owner is None:
                    offenders.append(
                        f"{rel}:{node.lineno}: span without owner= "
                        f"(attribution would misfile this as client "
                        f"work)")
                elif isinstance(owner, ast.Constant) and \
                        owner.value not in OWNER_CLASSES:
                    offenders.append(
                        f"{rel}:{node.lineno}: owner={owner.value!r} is "
                        f"not a canonical owner class {OWNER_CLASSES}")
    assert not offenders, (
        "spans in exec/ and recovery/ must carry an owner class so "
        "device-time attribution can file them:\n" + "\n".join(offenders))


def test_scan_dirs_still_exist():
    for sub in SCAN_DIRS:
        assert (ROOT / sub).is_dir(), f"stale scan dir: {sub}"
