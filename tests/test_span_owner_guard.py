"""Guard: spans in the serving/recovery layers must carry an owner class.

Thin wrapper over the ``span-owner`` rule in
:mod:`ceph_tpu.analysis.rules_guards` (ISSUE 15); semantics unchanged:
every span opened in ``exec/`` or ``recovery/`` needs an ``owner=``
from the canonical OWNER_CLASSES or device-time attribution misfiles
the work as client time.
"""
import ceph_tpu.analysis as A


def test_spans_in_exec_and_recovery_carry_owner_class():
    offenders = [f.render() for f in A.run_rules(
        A.default_index(), ("span-owner",))]
    assert not offenders, (
        "spans in exec/ and recovery/ must carry an owner class so "
        "device-time attribution can file them:\n"
        + "\n".join(offenders))


def test_scan_dirs_still_exist():
    idx = A.default_index()
    for sub in ("ceph_tpu/exec", "ceph_tpu/recovery"):
        assert idx.iter_modules((sub,)), f"stale scan dir: {sub}"


def test_guard_catches_missing_and_bogus_owner():
    bad = ("def f(tr):\n"
           "    with tr.span('x'):\n"
           "        pass\n"
           "    with tr.span('y', owner='not-a-class'):\n"
           "        pass\n"
           "    with tr.span('z', owner='scrub'):\n"
           "        pass\n")
    found = A.run_rule_on_sources("span-owner", {"bad.py": bad})
    assert len(found) == 2
    assert any("without owner=" in f.message for f in found)
    assert any("not-a-class" in f.message for f in found)
