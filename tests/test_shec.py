"""shec plugin: matrix shape, roundtrips with erasures, minimum_to_decode
locality, parameter validation (mirrors src/test/erasure-code/
TestErasureCodeShec*.cc strategy)."""
import numpy as np
import pytest

from ceph_tpu.plugins import ErasureCodePluginRegistry
from ceph_tpu.plugins.plugin_shec import (MULTIPLE, SINGLE,
                                          shec_coding_matrix)


@pytest.fixture
def registry():
    return ErasureCodePluginRegistry()


def _payload(n=4000, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8).tobytes()


# -- coding matrix ----------------------------------------------------------

def test_matrix_shape_and_shingles():
    mat = shec_coding_matrix(4, 3, 2, MULTIPLE)
    assert mat.shape == (3, 4)
    # shingled: at least one zero (each parity covers a window, not all of k)
    assert (mat == 0).any()
    # every parity row covers something
    assert (mat != 0).any(axis=1).all()
    # every data chunk is covered by at least one parity
    assert (mat != 0).any(axis=0).all()


def test_matrix_single_vs_multiple_differ():
    a = shec_coding_matrix(6, 4, 2, MULTIPLE)
    b = shec_coding_matrix(6, 4, 2, SINGLE)
    assert a.shape == b.shape == (4, 6)
    assert not np.array_equal(a, b)


def test_c_equals_m_is_full_rs():
    # c == m means no shingling: full Vandermonde coverage
    mat = shec_coding_matrix(4, 2, 2)
    assert (mat != 0).all()


# -- roundtrip --------------------------------------------------------------

@pytest.mark.parametrize("k,m,c", [(4, 3, 2), (6, 3, 2), (8, 4, 3), (4, 2, 2)])
def test_roundtrip_single_erasures(registry, k, m, c):
    ec = registry.factory("shec", "", {"k": str(k), "m": str(m), "c": str(c),
                                       "device": "numpy"})
    data = _payload(5000, seed=k * 100 + m)
    want = set(range(k + m))
    encoded = ec.encode(want, data)
    for lost in range(k + m):
        available = {i: v for i, v in encoded.items() if i != lost}
        decoded = ec.decode({lost}, available)
        np.testing.assert_array_equal(decoded[lost], encoded[lost],
                                      err_msg=f"lost={lost}")
    assert ec.decode_concat({i: encoded[i] for i in range(k + m) if i != 1}
                            )[:len(data)] == data


def test_roundtrip_c_erasures(registry):
    # any c=2 failures must be recoverable (the durability guarantee)
    ec = registry.factory("shec", "", {"k": "4", "m": "3", "c": "2",
                                       "device": "numpy"})
    data = _payload(3000, seed=7)
    encoded = ec.encode(set(range(7)), data)
    import itertools
    for lost in itertools.combinations(range(7), 2):
        available = {i: v for i, v in encoded.items() if i not in lost}
        decoded = ec.decode(set(lost), available)
        for e in lost:
            np.testing.assert_array_equal(decoded[e], encoded[e],
                                          err_msg=f"lost={lost}")


# -- minimum_to_decode (locality) -------------------------------------------

def test_minimum_to_decode_local_repair(registry):
    ec = registry.factory("shec", "", {"k": "4", "m": "3", "c": "2",
                                       "device": "numpy"})
    n = 7
    # single data failure should read fewer than k+1 chunks on average
    sizes = []
    for lost in range(4):
        available = set(range(n)) - {lost}
        got = ec.minimum_to_decode({lost}, available)
        assert lost not in got
        sizes.append(len(got))
    # shec's point: average repair cost below plain RS (which always reads k)
    assert sum(sizes) / len(sizes) <= 4

    # want available chunks only: no repair needed
    got = ec.minimum_to_decode({0, 1}, set(range(n)))
    assert set(got) <= {0, 1}


def test_minimum_to_decode_impossible(registry):
    ec = registry.factory("shec", "", {"k": "4", "m": "3", "c": "2",
                                       "device": "numpy"})
    # losing 4 chunks (> m) cannot be repaired
    with pytest.raises(IOError):
        ec.minimum_to_decode({0, 1, 2, 3}, {4, 5, 6})


def test_minimum_to_decode_range_check(registry):
    ec = registry.factory("shec", "", {"device": "numpy"})
    with pytest.raises(ValueError):
        ec.minimum_to_decode({99}, {0, 1})


# -- parameter validation (ErasureCodeShec.cc:276-344) ----------------------

@pytest.mark.parametrize("profile", [
    {"k": "0", "m": "3", "c": "2"},
    {"k": "4", "m": "0", "c": "2"},
    {"k": "4", "m": "3", "c": "0"},
    {"k": "4", "m": "2", "c": "3"},      # c > m
    {"k": "13", "m": "3", "c": "2"},     # k > 12
    {"k": "12", "m": "9", "c": "2"},     # k+m > 20
    {"k": "3", "m": "4", "c": "2"},      # m > k
    {"k": "4", "m": "3"},                # partial k/m/c
    {"k": "4", "m": "3", "c": "2", "w": "16"},
    {"k": "4", "m": "3", "c": "2", "technique": "bogus"},
])
def test_invalid_profiles(registry, profile):
    with pytest.raises(ValueError):
        registry.factory("shec", "", dict(profile))


def test_defaults(registry):
    ec = registry.factory("shec", "", {"device": "numpy"})
    assert ec.k == 4 and ec.m == 3 and ec.c == 2
    assert ec.get_chunk_count() == 7
    assert ec.get_profile()["technique"] == "multiple"


def test_single_technique_roundtrip(registry):
    ec = registry.factory("shec", "", {"k": "4", "m": "3", "c": "2",
                                       "technique": "single",
                                       "device": "numpy"})
    data = _payload(2000, seed=3)
    encoded = ec.encode(set(range(7)), data)
    available = {i: v for i, v in encoded.items() if i not in (0, 4)}
    decoded = ec.decode({0, 4}, available)
    np.testing.assert_array_equal(decoded[0], encoded[0])
    np.testing.assert_array_equal(decoded[4], encoded[4])
