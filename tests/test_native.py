"""Native C++ runtime: registry dlopen contract, RS codec parity with the
Python/JAX field math, broken-plugin failure paths, batch queue.

Mirrors the reference's registry tests (reference:
src/test/erasure-code/TestErasureCodePlugin.cc exercising the deliberately
broken ErasureCodePlugin{FailToInitialize,FailToRegister,MissingEntryPoint,
MissingVersion}.cc) and per-plugin encode/decode roundtrips
(TestErasureCodeIsa.cc / TestErasureCodeJerasure.cc:80-135)."""
import numpy as np
import pytest

from ceph_tpu.gf import matrix as gfm
from ceph_tpu.native import BatchQueue, NativeRegistry, build


@pytest.fixture(scope="module")
def registry():
    build()
    return NativeRegistry.instance()


@pytest.fixture(scope="module")
def rs(registry):
    return registry.factory("cpp_rs", {"k": 4, "m": 2,
                                       "technique": "reed_sol_van"})


def payload(k, chunk, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=(k, chunk), dtype=np.uint8)


class TestRegistry:
    def test_load_and_count(self, registry):
        registry.load("cpp_rs")
        assert registry.count() >= 1
        registry.load("cpp_rs")          # idempotent

    def test_factory_unknown_plugin(self, registry):
        with pytest.raises(IOError):
            registry.factory("does_not_exist", {})

    def test_wrong_version_rejected(self, registry):
        with pytest.raises(IOError) as ei:
            registry.load("badver")
        assert "version" in str(ei.value)

    def test_fail_to_initialize(self, registry):
        with pytest.raises(IOError):
            registry.load("failinit")

    def test_fail_to_register(self, registry):
        with pytest.raises(IOError) as ei:
            registry.load("noreg")
        assert "register" in str(ei.value)

    def test_missing_entry_point(self, registry):
        with pytest.raises(IOError) as ei:
            registry.load("noentry")
        assert "__erasure_code_init" in str(ei.value)

    def test_bad_profile_rejected(self, registry):
        with pytest.raises(ValueError):
            registry.factory("cpp_rs", {"k": 300, "m": 2})
        with pytest.raises(ValueError):
            registry.factory("cpp_rs", {"k": 4, "m": 2,
                                        "technique": "nope"})

    def test_preload(self, registry):
        registry.preload("cpp_rs")


class TestNativeRS:
    @pytest.mark.parametrize("technique,pyfn", [
        ("reed_sol_van", gfm.rs_vandermonde_jerasure),
        ("cauchy", gfm.cauchy1),
        ("vandermonde_isa", gfm.rs_vandermonde_isa),
    ])
    def test_encode_matches_python_field_math(self, registry, technique,
                                              pyfn):
        """The native codec and the Python/JAX path share one field: the
        parity bytes must be identical."""
        k, m, chunk = 5, 3, 512
        codec = registry.factory("cpp_rs", {"k": k, "m": m,
                                            "technique": technique})
        data = payload(k, chunk, seed=1)
        got = codec.encode(data)
        want = gfm.gf_matmul(pyfn(k, m), data)
        assert np.array_equal(got, want)

    def test_roundtrip_all_single_erasures(self, rs):
        k, chunk = 4, 256
        data = payload(k, chunk, seed=2)
        parity = rs.encode(data)
        full = {i: data[i] for i in range(k)}
        full.update({k + i: parity[i] for i in range(parity.shape[0])})
        for lost in range(6):
            avail = {i: v for i, v in full.items() if i != lost}
            rec = rs.decode(avail, [lost], chunk)
            assert np.array_equal(rec[lost], full[lost]), f"chunk {lost}"

    def test_roundtrip_double_erasures(self, rs):
        k, chunk = 4, 256
        data = payload(k, chunk, seed=3)
        parity = rs.encode(data)
        full = {i: data[i] for i in range(k)}
        full.update({k + i: parity[i] for i in range(2)})
        for a in range(6):
            for b in range(a + 1, 6):
                avail = {i: v for i, v in full.items() if i not in (a, b)}
                rec = rs.decode(avail, [a, b], chunk)
                assert np.array_equal(rec[a], full[a])
                assert np.array_equal(rec[b], full[b])

    def test_too_many_erasures(self, rs):
        k, chunk = 4, 64
        data = payload(k, chunk)
        parity = rs.encode(data)
        avail = {0: data[0], 1: data[1], 4: parity[0]}
        with pytest.raises(IOError):
            rs.decode(avail, [2, 3, 5], chunk)

    def test_minimum_to_decode(self, rs):
        got = rs.minimum_to_decode([0], [1, 2, 3, 4, 5])
        assert len(got) == 4
        assert set(got) <= {1, 2, 3, 4, 5}
        with pytest.raises(IOError):
            rs.minimum_to_decode([0, 1, 2], [3, 4])

    def test_chunk_size_alignment(self, rs):
        # ceil(object/k) aligned up to 32 (SIMD_ALIGN, ErasureCode.cc:42)
        assert rs.get_chunk_size(4096) == 1024
        assert rs.get_chunk_size(4097) == 1056
        assert rs.get_chunk_size(1) == 32

    def test_defaults_are_reed_sol_van_7_3(self, registry):
        codec = registry.factory("cpp_rs", {})
        assert codec.k == 7 and codec.n == 10


class TestBatchQueue:
    def test_batched_dispatch_correct_and_coalesced(self, registry):
        """Many submits -> few batches; every stripe's parity must match the
        synchronous native codec."""
        k, m, chunk = 4, 2, 128
        codec = registry.factory("cpp_rs", {"k": k, "m": m,
                                            "technique": "cauchy"})
        pmat = gfm.cauchy1(k, m)

        def batched_encode(data, n_stripes, chunk_size):
            # data [n, k, chunk] -> parity [n, m, chunk] (numpy stand-in for
            # the JAX device dispatch)
            flat = data.transpose(1, 0, 2).reshape(k, -1)
            par = gfm.gf_matmul(pmat, flat)
            return par.reshape(m, n_stripes, chunk_size).transpose(1, 0, 2)

        q = BatchQueue(k, m, chunk, batched_encode, max_batch=64)
        stripes = [payload(k, chunk, seed=i) for i in range(100)]
        parities = [q.submit(s) for s in stripes]
        q.flush()
        assert q.stripes == 100
        assert q.batches <= 100     # coalescing happened (often far fewer)
        for s, p in zip(stripes, parities):
            assert np.array_equal(p, codec.encode(s))
        q.close()

    def test_callback_error_propagates(self, registry):
        def boom(data, n, c):
            raise RuntimeError("sidecar died")
        q = BatchQueue(2, 1, 64, boom, max_batch=8)
        q.submit(payload(2, 64))
        with pytest.raises(RuntimeError, match="sidecar died"):
            q.flush()
        q.close()


class TestPythonPluginBridge:
    """cpp_rs through the Python plugin registry: same interface, same
    bytes as the jax_rs plugin (they share one field)."""

    def test_roundtrip_via_python_interface(self):
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        reg = ErasureCodePluginRegistry.instance()
        ec = reg.factory("cpp_rs", "", {"k": "4", "m": "2",
                                        "technique": "reed_sol_van"})
        data = bytes(payload(1, 4096, seed=7)[0].tobytes())
        encoded = ec.encode(set(range(6)), data)
        assert len(encoded) == 6
        # drop two chunks, decode, compare
        chunks = {i: v for i, v in encoded.items() if i not in (1, 4)}
        decoded = ec.decode({0, 1, 2, 3}, chunks, chunk_size=encoded[0].nbytes)
        got = b"".join(decoded[i].tobytes() for i in range(4))[:len(data)]
        assert got == data

    def test_matches_jax_rs_bytes(self):
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        reg = ErasureCodePluginRegistry.instance()
        prof = {"k": "4", "m": "2", "technique": "cauchy"}
        cpp = reg.factory("cpp_rs", "", dict(prof))
        jax_rs = reg.factory("jax_rs", "", dict(prof, device="numpy"))
        data = bytes(payload(1, 8192, seed=8)[0].tobytes())
        a = cpp.encode(set(range(6)), data)
        b = jax_rs.encode(set(range(6)), data)
        for i in range(6):
            assert np.array_equal(a[i], b[i]), f"chunk {i} differs"

    def test_mapping_profile_matches_jax_rs(self):
        """The mapping= profile key must produce the same chunk layout in
        both plugins (review regression)."""
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        reg = ErasureCodePluginRegistry.instance()
        prof = {"k": "2", "m": "1", "technique": "cauchy",
                "mapping": "_DD"}
        cpp = reg.factory("cpp_rs", "", dict(prof))
        jx = reg.factory("jax_rs", "", dict(prof, device="numpy"))
        data = bytes(payload(1, 1024, seed=9)[0].tobytes())
        a = cpp.encode(set(range(3)), data)
        b = jx.encode(set(range(3)), data)
        for i in range(3):
            assert np.array_equal(a[i], b[i]), f"chunk {i} differs"

    def test_concurrent_decodes_thread_safe(self):
        """Concurrent decodes through the shared LRU (review regression:
        the cached entry must be copied out under the lock)."""
        import threading
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        reg = ErasureCodePluginRegistry.instance()
        ec = reg.factory("cpp_rs", "", {"k": "4", "m": "2",
                                        "technique": "cauchy"})
        data = bytes(payload(1, 4096, seed=10)[0].tobytes())
        encoded = ec.encode(set(range(6)), data)
        csz = encoded[0].nbytes
        errors = []

        def worker(drop):
            try:
                for _ in range(50):
                    chunks = {i: v for i, v in encoded.items()
                              if i not in drop}
                    dec = ec.decode(set(range(4)), chunks, chunk_size=csz)
                    got = b"".join(dec[i].tobytes()
                                   for i in range(4))[:len(data)]
                    assert got == data
            except BaseException as e:      # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=worker, args=({a, b},))
                   for a in range(3) for b in range(3, 6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:1]

    def test_mapped_decode_roundtrip_cpp_rs(self):
        """Decode must invert the physical->logical mapping (review/corpus
        regression: encode remapped but decode did not)."""
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        reg = ErasureCodePluginRegistry.instance()
        ec = reg.factory("cpp_rs", "", {"k": "4", "m": "2",
                                        "technique": "reed_sol_van",
                                        "mapping": "_DDD_D"})
        data = bytes(payload(1, 8192, seed=12)[0].tobytes())
        enc = ec.encode(set(range(6)), data)
        for lost in ((0,), (1,), (0, 1), (1, 5), (0, 4)):
            avail = {i: v for i, v in enc.items() if i not in lost}
            got = ec.decode_concat(avail)[:len(data)]
            assert bytes(got) == data, f"erasure {lost}"
