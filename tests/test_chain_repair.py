"""Chained streaming repair (recovery/chain.py + the ECPartialSum hop
path): bitwise equivalence against centralized repair across geometries,
forced fallbacks (clay, mid-chain death, rotten sources), cost-aware
planning, and the scale-accumulate primitive."""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.common import Context
from ceph_tpu.recovery import chain as chainmod

CHUNK = 512


def _cluster(k, m, enable=True, profile=None, conf=None):
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=CHUNK,
                    cct=Context())
    c.cct.conf.set("osd_recovery_chain_enable", enable)
    for key, value in (conf or {}).items():
        c.cct.conf.set(key, value)
    c.enable_recovery_scheduler()
    prof = {"k": str(k), "m": str(m), "device": "numpy",
            "technique": "reed_sol_van"}
    prof.update(profile or {})
    pid = c.create_ec_pool("p", prof, pg_num=1)
    g = next(iter(c.pools[pid]["pgs"].values()))
    return c, pid, g


def _write_degrade_revive(c, pid, g, k, n_objects, victims=1, seed=3):
    """Write, kill ``victims`` shards, overwrite everything they miss,
    revive, drain.  Returns the expected object contents."""
    rng = np.random.default_rng(seed)
    obj_bytes = 3 * CHUNK * k
    data = {f"o{i}": rng.integers(0, 256, obj_bytes, np.uint8).tobytes()
            for i in range(n_objects)}
    for oid, d in data.items():
        c.put(pid, oid, d)
    vs = [g.acting[i + 1] for i in range(victims)]
    for v in vs:
        g.bus.mark_down(v)
    for oid in list(data):
        data[oid] = rng.integers(0, 256, obj_bytes, np.uint8).tobytes()
        c.put(pid, oid, data[oid])
    for v in vs:
        g.bus.mark_up(v)
    c.deliver_all()
    return data


def _perf(g):
    return {x: g.backend.perf.get(x) for x in
            ("recoveries", "recovery_failures", "chain_repairs",
             "chain_objects", "chain_fallbacks")}


def _shard_state(g, oids):
    """Every shard's stored bytes + hinfo digest dict, for bitwise
    comparison between repair arms."""
    from ceph_tpu.backend.ecutil import HINFO_KEY
    from ceph_tpu.backend.memstore import GObject
    from ceph_tpu.backend.pg_backend import shard_store
    out = {}
    for oid in sorted(oids):
        for s in g.acting:
            st = shard_store(g.backend.bus, s)
            obj = GObject(oid, s)
            out[(oid, s)] = (st.read(obj, 0, None),
                             st.getattr(obj, HINFO_KEY))
    return out


def _run_arm(k, m, enable, n_objects=8, victims=1, profile=None):
    c, pid, g = _cluster(k, m, enable=enable, profile=profile)
    try:
        data = _write_degrade_revive(c, pid, g, k, n_objects,
                                     victims=victims)
        assert not g.backend.stale
        perf = _perf(g)
        for oid, want in data.items():
            assert c.get(pid, oid, len(want)) == want
        assert c.scrub_pool(pid, repair=False) == {}
        state = _shard_state(g, data)
    finally:
        c.shutdown()
    return perf, state


class TestChainBitwiseEquivalence:
    @pytest.mark.parametrize("k,m,victims", [(2, 2, 1), (4, 2, 1),
                                             (4, 3, 2), (6, 3, 1)])
    def test_chain_matches_centralized(self, k, m, victims):
        """Chain repair must land byte-identical shard contents AND
        hinfo digests vs the centralized wave, across geometries and
        single/double erasure."""
        chain_perf, chain_state = _run_arm(k, m, True, victims=victims)
        cent_perf, cent_state = _run_arm(k, m, False, victims=victims)
        assert chain_perf["chain_objects"] == 8
        assert chain_perf["chain_fallbacks"] == 0
        assert chain_perf["recovery_failures"] == 0
        assert cent_perf["chain_objects"] == 0
        assert chain_state == cent_state

    def test_clay_forces_centralized_fallback(self):
        """Sub-chunked codes have no whole-chunk linear repair form:
        chains must never plan (the gate is upstream of the planner),
        and repair still completes through the verified path."""
        perf, _state = _run_arm(4, 2, True,
                                profile={"plugin": "clay", "k": "4",
                                         "m": "2", "d": "5",
                                         "scalar_mds": "jax_rs"})
        assert perf["chain_objects"] == 0
        assert perf["chain_repairs"] == 0
        assert perf["recovery_failures"] == 0
        assert perf["recoveries"] >= 8


class TestChainFallbacks:
    def test_mid_chain_death_blackholes_then_centralized(self):
        """Kill a hop the moment it receives a partial sum: the
        in-flight accumulator is black-holed, the coordinator's down
        listener pops the chain, and every unfinished object re-drives
        through the verified per-object path — zero acked-write loss,
        fault stamped in the campaign log.  (The primary's own hop is
        exempt: killing the coordinator is a peering event, not a
        mid-chain leg failure.)"""
        from ceph_tpu.failure import FaultInjector, FaultPlan
        c, pid, g = _cluster(4, 2, enable=True)
        inj = FaultInjector(FaultPlan(seed=11))
        try:
            killed = []

            def dying_hop(msg, _shard=None):
                # the OSD dies mid-leg: no forward, no abort — only the
                # bus down event tells the coordinator anything
                killed.append(_shard.shard)
                inj.record("chain", "hop_blackhole", target=_shard.shard)
                g.bus.mark_down(_shard.shard)

            for s in g.acting[1:]:
                h = g.bus.handlers.get(s)
                shard_obj = getattr(h, "local_shard", h)
                orig = shard_obj._partial_sum_hop

                def hook(msg, _o=orig, _s=shard_obj):
                    if not killed:
                        dying_hop(msg, _shard=_s)
                    else:
                        _o(msg)
                shard_obj._partial_sum_hop = hook
            data = _write_degrade_revive(c, pid, g, 4, n_objects=8)
            assert len(killed) == 1
            g.bus.mark_up(killed[0])
            c.deliver_all()
            assert not g.backend.stale
            perf = _perf(g)
            assert perf["chain_fallbacks"] >= 1
            assert perf["recovery_failures"] == 0
            for oid, want in data.items():          # zero acked loss
                assert c.get(pid, oid, len(want)) == want
            assert c.scrub_pool(pid, repair=False) == {}
            assert inj.summary()["planes"]["chain"]["hop_blackhole"] == 1
        finally:
            c.shutdown()

    def test_rotten_hop_chunk_aborts_to_verified_path(self):
        """Corrupt the first hop's stored chunk of one object without
        touching its hinfo: the hop's crc-vs-plan-hinfo check must abort
        the chain (never launder rot into the rebuilt chunk), and the
        centralized fallback routes around the rotten source.  Objects
        are CREATED while the victim is down — fresh appends carry chunk
        hashes; RMW overwrites invalidate them (and with no hash there
        is nothing for either repair path to check against)."""
        from ceph_tpu.backend.memstore import GObject, Transaction
        from ceph_tpu.backend.pg_backend import shard_store
        c, pid, g = _cluster(4, 2, enable=True)
        try:
            rng = np.random.default_rng(5)
            obj_bytes = 3 * CHUNK * 4
            victim = g.acting[1]
            g.bus.mark_down(victim)
            data = {f"o{i}": rng.integers(0, 256, obj_bytes,
                                          np.uint8).tobytes()
                    for i in range(6)}
            for oid, d in data.items():
                c.put(pid, oid, d)
            # first hop of the plan the coordinator will cut: replicate
            # its ranking with the same helpers it uses
            be = g.backend
            sig = {g.acting.index(victim)}
            avail = {ch for ch, s in enumerate(g.acting)
                     if s != victim and ch not in sig}
            costs = chainmod.source_costs(avail, [victim], g.acting,
                                          be.osd_locations)
            srcs = be.ec_impl.minimum_to_decode_with_cost(sig, costs)
            coeffs, _rows = be.ec_impl.partial_sum_coefficients(
                sig, sorted(srcs))
            hop0 = chainmod.order_hops(coeffs, [victim], g.acting,
                                       be.osd_locations)[0]
            s = g.acting[hop0]
            st = shard_store(g.bus, s)
            obj = GObject("o0", s)
            rot = bytes(b ^ 0xFF for b in st.read(obj, 0, None))
            st.queue_transaction(Transaction().write(obj, 0, rot))
            g.bus.mark_up(victim)
            c.deliver_all()
            assert not g.backend.stale
            perf = _perf(g)
            assert perf["chain_fallbacks"] >= 1
            assert perf["recovery_failures"] == 0
            for oid, want in data.items():
                assert c.get(pid, oid, len(want)) == want
            # the fallback already routed around (and healed) the rot:
            # a verifying scrub must come back clean
            assert c.scrub_pool(pid, repair=False) == {}
        finally:
            c.shutdown()


class TestPlanner:
    def test_crush_distance_buckets(self):
        loc = {0: 0, 1: 0, 2: 1}
        assert chainmod.crush_distance(0, 0, loc) == chainmod.SAME_OSD
        assert chainmod.crush_distance(0, 1, loc) == chainmod.SAME_HOST
        assert chainmod.crush_distance(0, 2, loc) == chainmod.CROSS_HOST
        # topology unknown: every remote OSD equidistant
        assert chainmod.crush_distance(0, 2, None) == chainmod.SAME_HOST

    def test_order_hops_puts_nearest_survivor_last(self):
        # acting: chunk -> osd; targets on host 0; source chunk 2 shares
        # the target's host, chunks 0/1 are cross-host
        acting = [3, 4, 1, 5]
        loc = {1: 0, 3: 1, 4: 2, 5: 0}
        order = chainmod.order_hops([0, 1, 2], targets=[5],
                                    acting=acting, locations=loc)
        assert order[-1] == 2                  # same-host leg runs last
        assert order == [0, 1, 2]              # ties break on chunk id

    def test_cost_aware_selection_prefers_cheap_sources(self):
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {"k": "4", "m": "2", "device": "numpy"})
        # chunk 0 erased; chunk 5 is expensive (cross-host), the rest
        # cheap — the minimum must take the 4 cheapest survivors
        costs = {1: 1, 2: 1, 3: 1, 4: 1, 5: 3}
        assert ec.minimum_to_decode_with_cost({0}, costs) == {1, 2, 3, 4}
        # when everything wanted survives, cost is irrelevant
        assert ec.minimum_to_decode_with_cost({1}, costs) == {1}

    def test_coefficients_reconstruct_erasures(self):
        """XOR over sources of coeff*chunk must equal the erased chunks
        — the exact identity every hop chain relies on."""
        from ceph_tpu.gf import ref as gfref
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {"k": "4", "m": "2", "device": "numpy"})
        rng = np.random.default_rng(7)
        raw = rng.integers(0, 256, 4 * CHUNK, np.uint8).tobytes()
        enc = ec.encode(set(range(6)), raw)
        for erased in ({1}, {0, 5}, {2, 3}):
            sources = sorted(set(range(6)) - erased)[:4]
            coeffs, rows = ec.partial_sum_coefficients(erased, sources)
            assert set(coeffs) == set(sources)
            assert set(rows) == erased
            acc = [np.zeros(len(enc[0]), np.uint8) for _ in rows]
            for src, cs in coeffs.items():
                for r, coeff in enumerate(cs):
                    term = gfref.apply_matrix_fast(
                        np.array([[coeff]], np.uint8),
                        np.asarray(enc[src], np.uint8).reshape(1, -1))
                    acc[r] ^= term[0]
            for r, e in enumerate(rows):
                assert bytes(acc[r]) == bytes(enc[e]), f"row {e}"

    def test_partial_sum_accumulate_host_path(self):
        from ceph_tpu.backend import ecutil
        rng = np.random.default_rng(1)
        stream = rng.integers(0, 256, 1024, np.uint8).tobytes()
        prev = [rng.integers(0, 256, 1024, np.uint8).tobytes()
                for _ in range(2)]
        out = ecutil.partial_sum_accumulate([3, 7], stream, prev)
        from ceph_tpu.gf import ref as gfref
        want = gfref.apply_matrix_fast(
            np.array([[3], [7]], np.uint8),
            np.frombuffer(stream, np.uint8).reshape(1, -1))
        for r in range(2):
            ref = bytes(want[r] ^ np.frombuffer(prev[r], np.uint8))
            assert out[r] == ref
        first = ecutil.partial_sum_accumulate([3, 7], stream, None)
        assert [bytes(w) for w in want] == list(first)


class TestChainWire:
    def test_hops_account_to_recovery_class_and_partition_holds(self):
        """Every chain leg is charged ONCE, to the recovery op class,
        and the class partition invariant survives the new types."""
        c, pid, g = _cluster(4, 2, enable=True)
        try:
            before_cls = c.wire.class_bytes()["recovery"]
            _write_degrade_revive(c, pid, g, 4, n_objects=6)
            per_type = c.wire.per_type()
            assert per_type["ECPartialSum"]["tx_bytes"] > 0
            assert per_type["ECPartialSumApply"]["tx_bytes"] > 0
            assert per_type["ECPartialSumApplied"]["tx_msgs"] >= 6
            chain_bytes = sum(per_type[t]["tx_bytes"] for t in
                              ("ECPartialSum", "ECPartialSumApply",
                               "ECPartialSumApplied"))
            assert (c.wire.class_bytes()["recovery"] - before_cls
                    >= chain_bytes)
            totals = c.wire.totals()
            assert sum(c.wire.class_bytes().values()) == \
                totals["tx_bytes"] + totals["rx_bytes"]
        finally:
            c.shutdown()


def test_chain_module_is_queue_guard_scanned():
    """Satellite guard coverage: the unbounded-queue AST scan must walk
    recovery/chain.py (it rglobs ceph_tpu/recovery)."""
    import pathlib
    import test_no_unbounded_queue as guard
    scanned = {p.name for p in guard._scan_files()} \
        if hasattr(guard, "_scan_files") else None
    if scanned is None:
        root = pathlib.Path(guard.__file__).resolve().parent.parent
        assert (root / "ceph_tpu" / "recovery" / "chain.py").exists()
    else:
        assert "chain.py" in scanned
