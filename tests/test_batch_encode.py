"""Cross-op/cross-PG encode coalescing: one device dispatch per batch.

The TPU-first thesis from SURVEY §3.2: the reference encodes per stripe
per op (reference: src/osd/ECUtil.cc:136-148 — the ★ hot loop); this
framework batches all stripes of an op, and ``put_many`` +
``ecutil.encode_many`` lift that to ALL OBJECTS ACROSS PGs in one
``encode_chunks`` call (→ one jitted device dispatch), with the backends
adopting the precomputed chunks only when the write plan matches exactly.
"""
import numpy as np
import pytest

from ceph_tpu.backend import StripeInfo, ecutil
from ceph_tpu.cluster import MiniCluster
from ceph_tpu.plugins.registry import ErasureCodePluginRegistry

PROFILE = {"plugin": "jax_rs", "k": "4", "m": "2", "device": "numpy",
           "technique": "reed_sol_van"}
CHUNK = 256
STRIPE = 4 * CHUNK


def payload(n, seed):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


def counting(ec):
    """Wrap encode_chunks with a call counter."""
    calls = {"n": 0}
    orig = ec.encode_chunks

    def wrapped(want, chunks):
        calls["n"] += 1
        return orig(want, chunks)
    ec.encode_chunks = wrapped
    return calls, orig


class TestEncodeMany:
    def test_matches_per_buffer_encode(self):
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", dict(PROFILE))
        sinfo = StripeInfo(4, CHUNK)
        bufs = [payload(STRIPE * s, seed=s) for s in (1, 3, 2, 5)]
        batched = ecutil.encode_many(sinfo, ec, bufs)
        for buf, got in zip(bufs, batched):
            want = ecutil.encode(sinfo, ec, buf)
            assert set(got) == set(want)
            for c in want:
                assert np.array_equal(got[c], want[c]), f"chunk {c}"

    def test_single_dispatch_for_many_buffers(self):
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", dict(PROFILE))
        sinfo = StripeInfo(4, CHUNK)
        calls, orig = counting(ec)
        ecutil.encode_many(sinfo, ec,
                           [payload(STRIPE * 2, seed=i) for i in range(16)])
        assert calls["n"] == 1, "encode_many did not coalesce"

    # -- edge cases (the serving coalescer leans on every one of these) --

    def test_empty_batch_is_a_noop(self):
        """A drained-to-zero batch (flush racing the coalescer) must not
        touch the device at all."""
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", dict(PROFILE))
        sinfo = StripeInfo(4, CHUNK)
        calls, _ = counting(ec)
        assert ecutil.encode_many(sinfo, ec, []) == []
        assert calls["n"] == 0

    def test_single_op_batch_matches_encode(self):
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", dict(PROFILE))
        sinfo = StripeInfo(4, CHUNK)
        buf = payload(STRIPE * 3, seed=11)
        [got] = ecutil.encode_many(sinfo, ec, [buf])
        want = ecutil.encode(sinfo, ec, buf)
        for c in want:
            assert np.array_equal(got[c], want[c]), f"chunk {c}"

    def test_mixed_stripe_counts_split_back_exactly(self):
        """Buffers of 1/2/5/16 stripes in ONE call: each op's chunk
        slices must carry exactly its own stripes (the split-offset
        bookkeeping is the coalescer's correctness backbone)."""
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", dict(PROFILE))
        sinfo = StripeInfo(4, CHUNK)
        bufs = [payload(STRIPE * s, seed=s) for s in (1, 2, 5, 16)]
        batched = ecutil.encode_many(sinfo, ec, bufs)
        for buf, got, stripes in zip(bufs, batched, (1, 2, 5, 16)):
            want = ecutil.encode(sinfo, ec, buf)
            for c in want:
                assert len(got[c]) == stripes * CHUNK
                assert np.array_equal(got[c], want[c]), f"chunk {c}"

    def test_non_stripe_aligned_tail_rejected(self):
        """encode_many's contract is stripe-aligned buffers: a ragged
        tail must fail loudly here — padding is the SUBMITTER's job (the
        serving engine pads to stripe width before admission)."""
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", dict(PROFILE))
        sinfo = StripeInfo(4, CHUNK)
        with pytest.raises(AssertionError, match="stripe aligned"):
            ecutil.encode_many(sinfo, ec, [payload(STRIPE + 100, seed=2)])

    def test_non_chunk_aligned_tail_padded_by_engine(self):
        """The serving path accepts the ragged tail and zero-pads it to
        the stripe boundary — byte-identical to encoding the padded
        buffer directly."""
        from ceph_tpu.exec import ServingEngine
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", dict(PROFILE))
        sinfo = StripeInfo(4, CHUNK)
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="edge.pad")
        ragged = payload(STRIPE + CHUNK // 2, seed=3)   # half-chunk tail
        fut = eng.submit_encode(ragged)
        eng.flush()
        got = fut.result(1)
        want = ecutil.encode(
            sinfo, ec, ragged + b"\0" * (STRIPE - CHUNK // 2))
        for c in want:
            assert np.array_equal(got[c], want[c]), f"chunk {c}"


class TestDecodeMany:
    def test_decode_many_matches_per_op_decode(self):
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", dict(PROFILE))
        sinfo = StripeInfo(4, CHUNK)
        bufs = [payload(STRIPE * s, seed=s) for s in (1, 3, 2)]
        encoded = [ecutil.encode(sinfo, ec, b) for b in bufs]
        # two survivor signatures -> two decode dispatches, three ops
        picks = [(0, 1, 4, 5), (0, 1, 4, 5), (1, 2, 3, 4)]
        got = ecutil.decode_many(
            sinfo, ec, [{c: e[c] for c in p}
                        for e, p in zip(encoded, picks)])
        assert got == bufs

    def test_decode_many_empty(self):
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", dict(PROFILE))
        assert ecutil.decode_many(StripeInfo(4, CHUNK), ec, []) == []

    def test_decode_many_pad_buckets_exact(self):
        """Zero padding to a size bucket must slice off bit-exactly."""
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", dict(PROFILE))
        sinfo = StripeInfo(4, CHUNK)
        bufs = [payload(STRIPE * s, seed=40 + s) for s in (1, 2)]  # 3 total
        encoded = [ecutil.encode(sinfo, ec, b) for b in bufs]
        got = ecutil.decode_many(
            sinfo, ec, [{c: e[c] for c in (0, 1, 2, 3)} for e in encoded],
            pad_chunks=lambda n: 1 << (n - 1).bit_length())   # 3 -> 4
        assert got == bufs


class TestPutMany:
    def test_put_many_one_dispatch_across_pgs(self):
        cluster = MiniCluster(n_osds=12, chunk_size=CHUNK)
        pid = cluster.create_ec_pool("batch", PROFILE, pg_num=8)
        ec = cluster.pools[pid]["ec"]
        objects = {f"o{i}": payload(STRIPE * (1 + i % 3), seed=i)
                   for i in range(24)}
        # the 24 objects span several PGs
        pgs = {id(cluster.pg_group(pid, oid)) for oid in objects}
        assert len(pgs) > 2
        calls, _ = counting(ec)
        cluster.put_many(pid, objects)
        assert calls["n"] == 1, \
            f"{calls['n']} encode dispatches for one batch"
        for oid, want in sorted(objects.items()):
            assert cluster.get(pid, oid, len(want)) == want, oid
            g = cluster.pg_group(pid, oid)
            assert all(g.backend.be_deep_scrub(oid).values()), oid

    def test_put_many_matches_put(self):
        """Bit-identical on-disk state vs the per-object path."""
        a = MiniCluster(n_osds=12, chunk_size=CHUNK)
        b = MiniCluster(n_osds=12, chunk_size=CHUNK)
        pa = a.create_ec_pool("p", PROFILE, pg_num=4)
        pb = b.create_ec_pool("p", PROFILE, pg_num=4)
        objects = {f"x{i}": payload(STRIPE * 2, seed=40 + i)
                   for i in range(8)}
        a.put_many(pa, objects)
        for oid, data in objects.items():
            b.put(pb, oid, data)
        for oid in objects:
            ga, gb = a.pg_group(pa, oid), b.pg_group(pb, oid)
            for chunk, shard in enumerate(ga.acting):
                from ceph_tpu.backend import GObject
                from ceph_tpu.backend.ec_backend import OSDShard
                ha = ga.bus.handlers[shard]
                sa = ha.store if isinstance(ha, OSDShard) \
                    else ha.local_shard.store
                shard_b = gb.acting[chunk]
                hb = gb.bus.handlers[shard_b]
                sb = hb.store if isinstance(hb, OSDShard) \
                    else hb.local_shard.store
                assert sa.read(GObject(oid, shard)) == \
                    sb.read(GObject(oid, shard_b)), f"{oid} chunk {chunk}"

    def test_rmw_overwrite_falls_back_to_live_encode(self):
        """A precomputed write whose plan turns into an RMW (existing
        longer object -> same extent, but stale precomputed bytes would
        differ) must re-encode live, never corrupt."""
        cluster = MiniCluster(n_osds=12, chunk_size=CHUNK)
        pid = cluster.create_ec_pool("p", PROFILE, pg_num=4)
        long = payload(STRIPE * 4, seed=1)
        cluster.put(pid, "obj", long)
        short = payload(STRIPE, seed=2)
        cluster.put_many(pid, {"obj": short})
        want = short + long[len(short):]
        assert cluster.get(pid, "obj", len(long)) == want
        g = cluster.pg_group(pid, "obj")
        assert all(g.backend.be_deep_scrub("obj").values())

    def test_put_many_replicated_pool(self):
        cluster = MiniCluster(n_osds=12, chunk_size=CHUNK)
        pid = cluster.create_replicated_pool("rep", size=3, pg_num=4)
        objects = {f"r{i}": payload(500, seed=i) for i in range(6)}
        cluster.put_many(pid, objects)
        for oid, want in objects.items():
            assert cluster.get(pid, oid, len(want)) == want
