"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Two layers of defence, both needed in this environment:
- env vars must be set before jax import;
- the axon sitecustomize (PYTHONPATH=/root/.axon_site) overrides platform
  selection via jax.config (jax_platforms="axon,cpu"), which would make the
  first backend init dial the TPU tunnel even for CPU-only tests — so the
  config must be forced back to cpu after import, too.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if jax.config.jax_platforms != "cpu":
    jax.config.update("jax_platforms", "cpu")

# CRUSH bulk kernels need exact int64 straw2 draws
jax.config.update("jax_enable_x64", True)
