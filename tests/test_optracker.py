"""common/optracker.py direct coverage (ISSUE 8 satellite): historic-op
event timelines, slow-flag promotion, and bounded-history eviction —
previously exercised only indirectly through the backend dumps."""
from __future__ import annotations

import time

from ceph_tpu.common.optracker import OpTracker
from ceph_tpu.common.options import ConfigProxy
from ceph_tpu.common.perf_counters import PerfCountersBuilder


class TestEventTimeline:
    def test_events_ordered_and_complete(self):
        tracker = OpTracker()
        op = tracker.create_request("osd_op(client.1 write)")
        op.mark_event("queued")
        op.mark_event("reached_pg")
        op.mark_event("commit_sent")
        op.finish()
        dump = tracker.dump_historic_ops()
        assert dump["num_ops"] == 1
        events = [e["event"] for e in dump["ops"][0]["type_data"]["events"]]
        # the tracker brackets the caller's marks: initiated first,
        # done last, caller events in call order between them
        assert events == ["initiated", "queued", "reached_pg",
                          "commit_sent", "done"]
        times = [e["time"] for e in dump["ops"][0]["type_data"]["events"]]
        assert times == sorted(times)
        assert dump["ops"][0]["duration"] >= 0

    def test_inflight_moves_to_history_on_finish(self):
        tracker = OpTracker()
        op = tracker.create_request("op")
        assert tracker.dump_ops_in_flight()["num_ops"] == 1
        assert tracker.dump_historic_ops()["num_ops"] == 0
        op.finish()
        assert tracker.dump_ops_in_flight()["num_ops"] == 0
        assert tracker.dump_historic_ops()["num_ops"] == 1

    def test_context_manager_finishes_and_double_finish_is_idempotent(self):
        tracker = OpTracker()
        with tracker.create_request("ctx op") as op:
            op.mark_event("working")
        assert tracker.dump_historic_ops()["num_ops"] == 1
        op.finish()                      # second finish must not re-file
        assert tracker.dump_historic_ops()["num_ops"] == 1
        events = [e["event"] for e in tracker.dump_historic_ops()
                  ["ops"][0]["type_data"]["events"]]
        assert events.count("done") == 1

    def test_age_histogram_buckets(self):
        tracker = OpTracker()
        op = tracker.create_request("aging")
        op.initiated_at = time.time() - 15.0     # lands in the <60s bucket
        tracker.create_request("fresh")
        hist = tracker.get_age_histogram()
        assert hist == {"<60s": 1, "<1s": 1}


class TestSlowFlagPromotion:
    def _perf(self):
        return (PerfCountersBuilder("optracker_test")
                .add_u64_counter("slow_ops", "ops over the complaint time")
                .create_perf_counters())

    def test_slow_op_flagged_counted_and_kept(self):
        perf = self._perf()
        tracker = OpTracker(complaint_time=0.0, perf=perf)
        op = tracker.create_request("slow write")
        op.finish()                              # 0.0 threshold: always slow
        assert op.slow
        assert perf.get("slow_ops") == 1
        slow = tracker.dump_historic_slow_ops()
        assert slow["num_ops"] == 1 and slow["ops"][0]["slow"]
        # the regular history carries the flag too
        assert tracker.dump_historic_ops()["ops"][0]["slow"]

    def test_fast_op_not_promoted(self):
        perf = self._perf()
        tracker = OpTracker(complaint_time=30.0, perf=perf)
        tracker.create_request("fast").finish()
        assert perf.get("slow_ops") == 0
        assert tracker.dump_historic_slow_ops()["num_ops"] == 0
        assert not tracker.dump_historic_ops()["ops"][0]["slow"]

    def test_complaint_time_live_updates_via_conf_observer(self):
        conf = ConfigProxy({"osd_op_complaint_time": 30.0})
        tracker = OpTracker(conf=conf)
        assert tracker.complaint_time == 30.0
        tracker.create_request("before").finish()
        conf.set("osd_op_complaint_time", 0.0)
        assert tracker.complaint_time == 0.0
        tracker.create_request("after").finish()
        slow = [o["description"] for o in
                tracker.dump_historic_slow_ops()["ops"]]
        assert slow == ["after"]

    def test_missing_slow_ops_counter_is_tolerated(self):
        perf = (PerfCountersBuilder("no_slow_key")
                .add_u64_counter("other", "unrelated")
                .create_perf_counters())
        tracker = OpTracker(complaint_time=0.0, perf=perf)
        tracker.create_request("slow anyway").finish()   # must not raise
        assert tracker.dump_historic_slow_ops()["num_ops"] == 1


class TestBoundedHistory:
    def test_history_evicts_oldest_past_capacity(self):
        tracker = OpTracker(history_size=3)
        for i in range(5):
            tracker.create_request(f"op{i}").finish()
        dump = tracker.dump_historic_ops()
        assert dump["num_ops"] == 3
        assert [o["description"] for o in dump["ops"]] == \
            ["op2", "op3", "op4"]

    def test_slow_ring_bounded_independently(self):
        tracker = OpTracker(history_size=2, complaint_time=0.0)
        for i in range(4):
            tracker.create_request(f"s{i}").finish()
        slow = tracker.dump_historic_slow_ops()
        assert slow["num_ops"] == 2
        assert [o["description"] for o in slow["ops"]] == ["s2", "s3"]

    def test_eviction_leaves_inflight_registry_clean(self):
        tracker = OpTracker(history_size=1)
        ops = [tracker.create_request(f"o{i}") for i in range(3)]
        for op in ops:
            op.finish()
        assert tracker.dump_ops_in_flight() == {"ops": [], "num_ops": 0}
        assert tracker.dump_historic_ops()["num_ops"] == 1
