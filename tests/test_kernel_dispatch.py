"""Which-kernel-executed guards.

Round-4 postmortem (BASELINE.md "dispatch-detection postscript"):
`_runs_on_tpu` once mapped the ConcretizationTypeError a Tracer raises
from `.devices()` to "not TPU", so every JITTED caller — including the
bench chain — silently took the XLA bitslice fallback instead of the
pallas kernel, and the bench quietly measured the wrong kernel.  These
tests pin the dispatch contract so that failure mode cannot recur:

- under jit trace on a TPU-default backend, `_runs_on_tpu` is True;
- a jitted caller at bench-like shapes actually INVOKES the pallas
  kernel (recorded via monkeypatch, executed in interpret mode on CPU);
- the sharded multichip step routes through the SAME production
  selector (`gf_apply_stripes`) as the single-chip bench;
- on a real TPU, the lowered HLO of the bench apply contains the pallas
  custom call (skipped elsewhere).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ceph_tpu.gf import cauchy1, ref
from ceph_tpu.ops import pallas_kernels, rs_kernels

K, M, S, N = 8, 4, 8, 1024      # bench-like: n >= 1024 engages pallas


class _FakeTpuDevice:
    platform = "tpu"

    def __repr__(self):
        return "FakeTpuDevice"


@pytest.fixture
def fake_tpu(monkeypatch):
    """Make the runtime LOOK like a TPU host without real hardware: the
    default-device probe reports tpu, and the pallas kernel runs in
    interpret mode so it executes on CPU."""
    monkeypatch.setattr(jax, "devices",
                        lambda *a, **k: [_FakeTpuDevice()])
    orig = pallas_kernels.gf_apply_stripes_pallas
    calls: list = []

    def recording(mat, data, stripes, **kw):
        calls.append(stripes)
        kw["interpret"] = True
        return orig(mat, data, stripes, **kw)
    monkeypatch.setattr(pallas_kernels, "gf_apply_stripes_pallas",
                        recording)
    return calls


def test_runs_on_tpu_true_under_trace(fake_tpu):
    """A Tracer has no committed device; the probe MUST fall through to
    the runtime default platform, not report 'not TPU'."""
    seen = []

    def f(x):
        seen.append(rs_kernels._runs_on_tpu(x))
        return x + 1
    jax.jit(f)(jnp.zeros((4, 4), jnp.uint8))
    assert seen == [True]


def test_jitted_caller_invokes_pallas(fake_tpu):
    """The bench's jitted apply at bench shapes must reach the pallas
    kernel — and its output must bit-match the XLA fallback."""
    rng = np.random.default_rng(7)
    mat = cauchy1(K, M)
    data = rng.integers(0, 256, size=(S * K, N), dtype=np.uint8)

    out = jax.jit(
        lambda Mt, D: rs_kernels.gf_apply_stripes(Mt, D, S))(
            jnp.asarray(mat), jnp.asarray(data))
    assert fake_tpu == [S], "jitted caller did not reach the pallas kernel"
    want = np.concatenate([ref.encode(mat, data[s * K:(s + 1) * K])
                           for s in range(S)], axis=0)
    np.testing.assert_array_equal(np.asarray(out), want)


def test_cpu_committed_array_takes_xla_fallback(fake_tpu):
    """Eager callers with CPU-COMMITTED concrete arrays must stay on the
    XLA path even on a TPU host (the Mosaic kernel cannot lower on CPU;
    the committed device wins — _runs_on_tpu's documented contract)."""
    rng = np.random.default_rng(8)
    mat = cauchy1(K, M)
    data = rng.integers(0, 256, size=(S * K, N), dtype=np.uint8)
    out = rs_kernels.gf_apply_stripes(mat, data, S)   # asarray commits CPU
    assert fake_tpu == [], "CPU-committed data must not hit the TPU kernel"
    want = np.concatenate([ref.encode(mat, data[s * K:(s + 1) * K])
                           for s in range(S)], axis=0)
    np.testing.assert_array_equal(np.asarray(out), want)


def test_sharded_step_routes_through_production_selector(monkeypatch):
    """The multichip encode must call gf_apply_stripes (the SAME selector
    the bench uses: pallas on TPU, bitslice elsewhere) — not a private
    kernel of its own (round-4 weakness #2)."""
    from ceph_tpu.parallel.mesh import make_mesh, sharded_encode_step

    calls: list = []
    orig = rs_kernels.gf_apply_stripes

    def recording(mat, data, stripes, *a, **kw):
        calls.append(stripes)
        return orig(mat, data, stripes, *a, **kw)
    monkeypatch.setattr(rs_kernels, "gf_apply_stripes", recording)

    mesh = make_mesh(8)
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]
    pm = cauchy1(K, M)
    step = sharded_encode_step(mesh, pm)
    rng = np.random.default_rng(9)
    data = rng.integers(0, 256, size=(2 * dp, K, 128 * sp), dtype=np.uint8)
    parity, _, _ = step(data)
    assert calls, "sharded_encode_step bypassed gf_apply_stripes"
    for b in range(data.shape[0]):
        np.testing.assert_array_equal(np.asarray(parity[b]),
                                      ref.encode(pm, data[b]))


@pytest.mark.skipif(jax.default_backend() != "tpu",
                    reason="real-TPU lowering check")
def test_bench_apply_lowers_to_pallas_on_tpu():
    """On real hardware the jitted bench apply must contain the Mosaic
    custom call — the direct form of the dispatch guard."""
    rng = np.random.default_rng(10)
    mat = jnp.asarray(cauchy1(K, M))
    data = jnp.asarray(rng.integers(0, 256, size=(S * K, 128 * 1024),
                                    dtype=np.uint8))
    txt = jax.jit(
        lambda Mt, D: rs_kernels.gf_apply_stripes(Mt, D, S)).lower(
            mat, data).as_text()
    assert ("tpu_custom_call" in txt) or ("pallas" in txt)
