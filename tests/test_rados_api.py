"""librados facade + rados CLI.

Mirrors the reference's client API surface (librados_cxx.cc /
rados.pyx method shapes; src/tools/rados verbs): a reference user's
code patterns must work unchanged in spirit.
"""
import numpy as np
import pytest

from ceph_tpu.client.rados import ObjectNotFound, Rados
from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osd.osd_ops import CMPXATTR_EQ, ObjectOperation
from ceph_tpu.tools.rados_cli import main as rados_main


@pytest.fixture
def io():
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
    c.create_ec_pool("data", {"k": "2", "m": "1", "device": "numpy"},
                     pg_num=4)
    yield Rados(c).open_ioctx("data")
    c.shutdown()


class TestIoCtx:
    def test_object_lifecycle(self, io):
        io.write_full("obj", b"hello world")
        assert io.read("obj")[:11] == b"hello world"
        io.append("obj", b"!")
        size, _ = io.stat("obj")
        assert size == 12
        io.write("obj", b"J", offset=0)
        assert io.read("obj")[:1] == b"J"
        assert "obj" in io.list_objects()
        io.remove_object("obj")
        with pytest.raises(ObjectNotFound):
            io.stat("obj")
        assert "obj" not in io.list_objects()

    def test_xattrs(self, io):
        io.write_full("x", b"body")
        io.set_xattr("x", "k", b"v")
        assert io.get_xattr("x", "k") == b"v"
        assert io.get_xattrs("x") == {"k": b"v"}
        io.rm_xattr("x", "k")
        with pytest.raises(IOError):
            io.get_xattr("x", "k")

    def test_operate_vector(self, io):
        io.write_full("g", b"v1")
        io.set_xattr("g", "ver", b"1")
        io.operate("g", ObjectOperation()
                   .cmpxattr("ver", CMPXATTR_EQ, b"1")
                   .write_full(b"v2").setxattr("ver", b"2"))
        assert io.read("g")[:2] == b"v2"

    def test_snapshots(self, io):
        io.write_full("s", b"v1" * 100)
        sid = io.snap_create("before")
        io.write_full("s", b"v2" * 100)
        assert io.snap_list() == {sid: "before"}
        io.set_read(sid)
        assert io.read("s")[:200] == b"v1" * 100
        io.set_read(None)
        assert io.read("s")[:200] == b"v2" * 100
        io.snap_rollback("s", "before")
        assert io.read("s")[:200] == b"v1" * 100
        io.snap_remove("before")
        assert io.snap_list() == {}

    def test_watch_notify(self, io):
        io.write_full("w", b"x")
        got = []
        cookie = io.watch("w", lambda n, ck, p: (got.append(p), b"ok")[1])
        acks = io.notify("w", b"ding")
        assert got == [b"ding"] and acks == {cookie: b"ok"}
        io.unwatch("w", cookie)
        io.notify("w", b"silent")
        assert got == [b"ding"]

    def test_list_objects_hides_clones(self, io):
        io.write_full("c", b"v1")
        io.snap_create("s")
        io.write_full("c", b"v2")        # creates a COW clone
        assert io.list_objects() == ["c"]


class TestRadosCli:
    def test_cli_roundtrip_across_invocations(self, tmp_path, capsys):
        d = str(tmp_path / "cluster")
        payload = np.random.default_rng(0).integers(
            0, 256, 3000, np.uint8).tobytes()
        src = tmp_path / "in.bin"
        src.write_bytes(payload)
        out = tmp_path / "out.bin"
        # each call is a separate process-lifetime: load -> op -> close
        assert rados_main(["--data-dir", d, "mkpool", "data",
                           "k=2", "m=1", "device=numpy"]) == 0
        assert rados_main(["--data-dir", d, "put", "data", "obj",
                           str(src)]) == 0
        assert rados_main(["--data-dir", d, "ls", "data"]) == 0
        assert capsys.readouterr().out.splitlines()[-1] == "obj"
        assert rados_main(["--data-dir", d, "mksnap", "data", "s1"]) == 0
        assert rados_main(["--data-dir", d, "setxattr", "data", "obj",
                           "color", "teal"]) == 0
        assert rados_main(["--data-dir", d, "getxattr", "data", "obj",
                           "color"]) == 0
        assert capsys.readouterr().out.strip().endswith("teal")
        assert rados_main(["--data-dir", d, "get", "data", "obj",
                           str(out)]) == 0
        assert out.read_bytes() == payload
        assert rados_main(["--data-dir", d, "lssnap", "data"]) == 0
        assert "s1" in capsys.readouterr().out
        assert rados_main(["--data-dir", d, "df"]) == 0
        assert "osds up" in capsys.readouterr().out

    def test_cli_snapshot_rollback_across_invocations(self, tmp_path,
                                                      capsys):
        d = str(tmp_path / "c2")
        a, b = tmp_path / "a", tmp_path / "b"
        a.write_bytes(b"version-one")
        b.write_bytes(b"version-two")
        rados_main(["--data-dir", d, "mkpool", "p", "k=2", "m=1",
                    "device=numpy"])
        rados_main(["--data-dir", d, "put", "p", "doc", str(a)])
        rados_main(["--data-dir", d, "mksnap", "p", "golden"])
        rados_main(["--data-dir", d, "put", "p", "doc", str(b)])
        assert rados_main(["--data-dir", d, "rollback", "p", "doc",
                           "golden"]) == 0
        out = tmp_path / "restored"
        rados_main(["--data-dir", d, "get", "p", "doc", str(out)])
        assert out.read_bytes() == b"version-one"

    def test_cli_missing_object_errors(self, tmp_path, capsys):
        d = str(tmp_path / "c3")
        rados_main(["--data-dir", d, "mkpool", "p", "k=2", "m=1",
                    "device=numpy"])
        assert rados_main(["--data-dir", d, "stat", "p", "ghost"]) == 2


class TestReviewRegressions:
    def test_set_read_does_not_block_writes(self, io):
        """snap_set_read affects READS only: writes under set_read go to
        the head (regression: they bounced EROFS)."""
        io.write_full("sr", b"v1")
        sid = io.snap_create("s")
        io.set_read(sid)
        io.write_full("sr", b"v2")        # must NOT raise
        assert io.read("sr") == b"v1"     # read still at the snap
        io.set_read(None)
        assert io.read("sr") == b"v2"
        io.snap_remove("s")

    def test_cookies_unique_across_handles(self, io):
        io.write_full("ck", b"x")
        io2 = io.rados.open_ioctx("data")
        fn = lambda n, ck, p: b"a"        # noqa: E731 — same callback
        c1 = io.watch("ck", fn)
        c2 = io2.watch("ck", fn)
        assert c1 != c2                   # two registrations, two cookies
        acks = io.notify("ck")
        assert set(acks) == {c1, c2}

    def test_operate_routes_through_objecter(self, io):
        before = io.rados.objecter.next_tid
        io.write_full("tid", b"x")
        assert io.rados.objecter.next_tid > before


class TestHealthAndAio:
    def test_health_transitions(self, io):
        r = io.rados
        c = r.cluster
        assert r.health() == {"status": "HEALTH_OK", "checks": {}}
        io.write_full("h", b"x")
        g = c.pg_group(io.pool_id, "h")
        peers = [o for o in g.acting if o != g.backend.whoami]
        g.bus.mark_down(peers[0])
        h = r.health()          # 2/3 shards, min_size 3: inactive -> ERR
        assert h["status"] == "HEALTH_ERR"
        assert "PG_AVAILABILITY" in h["checks"]
        g.bus.mark_up(peers[0])
        g.bus.deliver_all()
        assert r.health()["status"] == "HEALTH_OK"

    def test_aio_operate(self, io):
        comps = [io.aio_operate(f"a{i}", ObjectOperation()
                                .write_full(f"v{i}".encode()))
                 for i in range(4)]
        fired = []
        comps[0].set_complete_callback(lambda c: fired.append(c.result))
        assert not any(c.is_complete for c in comps)    # still queued
        for c in comps:
            assert c.wait_for_complete() == 0
        assert fired == [0]
        for i in range(4):
            assert io.read(f"a{i}") == f"v{i}".encode()

    def test_aio_parked_completes_on_revival(self, io):
        from ceph_tpu.cluster import BlockedWriteError
        io.write_full("ap", b"v1")
        c = io.rados.cluster
        g = c.pg_group(io.pool_id, "ap")
        peers = [o for o in g.acting if o != g.backend.whoami]
        for o in peers:
            g.bus.mark_down(o)
        comp = io.aio_operate("ap", ObjectOperation().write_full(b"v2"))
        with pytest.raises(BlockedWriteError):
            comp.wait_for_complete()          # parked != success
        assert not comp.is_complete
        with pytest.raises(ValueError):
            comp.result                       # no fake success code
        for o in peers:
            g.bus.mark_up(o)
        g.bus.deliver_all()
        assert comp.is_complete and comp.result == 0
        assert io.read("ap") == b"v2"

    def test_aio_honors_set_read(self, io):
        io.write_full("as", b"v1")
        sid = io.snap_create("s")
        io.write_full("as", b"v2")
        io.set_read(sid)
        comp = io.aio_operate("as", ObjectOperation().read(0, 0))
        comp.wait_for_complete()
        assert comp.reply.outdata(0) == b"v1"     # snap, not head
        io.set_read(None)
        io.snap_remove("s")

    def test_aio_leaves_no_resendable_ghost(self, io):
        """A queued aio op must leave inflight immediately: a map change
        in the submit-to-wait window would resend and double-apply a
        non-idempotent vector (regression)."""
        comp = io.aio_operate("ag", ObjectOperation().write_full(b"v"))
        assert not io.rados.objecter.inflight
        assert comp.wait_for_complete() == 0
        assert io.read("ag") == b"v"


class TestCephCli:
    def test_ceph_cli_verbs(self, tmp_path, capsys):
        from ceph_tpu.tools.ceph_cli import main as ceph_main
        d = str(tmp_path / "cl")
        rados_main(["--data-dir", d, "mkpool", "data", "k=2", "m=1",
                    "device=numpy"])
        src = tmp_path / "f"
        src.write_bytes(b"x" * 2000)
        rados_main(["--data-dir", d, "put", "data", "obj", str(src)])
        capsys.readouterr()

        assert ceph_main(["--data-dir", d, "status"]) == 0
        out = capsys.readouterr().out
        assert "health: HEALTH_OK" in out and "8 pgs" in out

        assert ceph_main(["--data-dir", d, "health"]) == 0
        assert capsys.readouterr().out.strip() == "HEALTH_OK"

        assert ceph_main(["--data-dir", d, "osd", "tree"]) == 0
        out = capsys.readouterr().out
        assert "root default" in out and "osd.0" in out and "host" in out
        assert out.count("up") >= 9

        assert ceph_main(["--data-dir", d, "pg", "dump"]) == 0
        out = capsys.readouterr().out
        assert "active+clean" in out and "1.0" in out

        assert ceph_main(["--data-dir", d, "osd", "df"]) == 0
        out = capsys.readouterr().out
        assert "osd.0" in out

        assert ceph_main(["--data-dir", d, "df"]) == 0
        assert "pool data" in capsys.readouterr().out

        assert ceph_main(["--data-dir", d, "bogus"]) == 2

    def test_ceph_cli_no_cluster(self, tmp_path, capsys):
        from ceph_tpu.tools.ceph_cli import main as ceph_main
        assert ceph_main(["--data-dir", str(tmp_path / "none"),
                          "status"]) == 2

    def test_ceph_cli_s_alias_and_reweight_column(self, tmp_path, capsys):
        from ceph_tpu.tools.ceph_cli import main as ceph_main
        d = str(tmp_path / "al")
        rados_main(["--data-dir", d, "mkpool", "p", "k=2", "m=1",
                    "device=numpy"])
        capsys.readouterr()
        assert ceph_main(["--data-dir", d, "-s"]) == 0
        assert "health:" in capsys.readouterr().out
        assert ceph_main(["--data-dir", d, "osd", "tree"]) == 0
        out = capsys.readouterr().out
        assert "REWEIGHT" in out
        # leaf CRUSH weights sum to their host bucket's weight
        lines = [line for line in out.splitlines() if "osd." in line]
        w = float(lines[0].split()[1])
        assert abs(w - 1.0) < 1e-6

    def test_ceph_cli_counts_user_objects_only(self, tmp_path, capsys):
        """osd df excludes _pgmeta_; df excludes snapshot clones
        (regressions: both inflated the counts)."""
        from ceph_tpu.tools.ceph_cli import main as ceph_main
        d = str(tmp_path / "cnt")
        rados_main(["--data-dir", d, "mkpool", "p", "k=2", "m=1",
                    "device=numpy"])
        src = tmp_path / "f"
        src.write_bytes(b"z" * 1500)
        rados_main(["--data-dir", d, "put", "p", "obj", str(src)])
        rados_main(["--data-dir", d, "mksnap", "p", "s"])
        rados_main(["--data-dir", d, "put", "p", "obj", str(src)])  # COW
        capsys.readouterr()
        assert ceph_main(["--data-dir", d, "df"]) == 0
        assert "objects 1" in capsys.readouterr().out
        assert ceph_main(["--data-dir", d, "osd", "df"]) == 0
        out = capsys.readouterr().out
        # 1 object + 1 clone over k+m=3 shards = 6 shard objects total
        total = sum(int(line.split()[-3]) for line in out.splitlines())
        assert total == 6, out
