"""Guard: hot paths time through the tracer/perf API, not ad-hoc clocks.

Thin wrapper over the ``bare-clock`` rule in
:mod:`ceph_tpu.analysis.rules_guards` (ISSUE 15); semantics unchanged —
timing added to ``ceph_tpu/ops/`` or ``ceph_tpu/backend/`` must go
through ``trace_span``, ``PerfCounters.time``/``tinc`` or
``traced_jit`` so it lands in the observability surfaces, and a bare
``time.time()`` / ``perf_counter()`` site is allowed only on the
explicit allowlist (the timing wrappers themselves).
"""
import ceph_tpu.analysis as A
from ceph_tpu.analysis.rules_guards import CLOCK_ALLOWLIST


def test_no_bare_timing_in_hot_paths():
    offenders = [f.render() for f in A.run_rules(
        A.default_index(), ("bare-clock",))]
    assert not offenders, (
        "bare timing calls in hot paths — route them through "
        "trace_span/PerfCounters/traced_jit (or extend the allowlist "
        "with a justification):\n" + "\n".join(offenders))


def test_allowlist_entries_still_exist():
    idx = A.default_index()
    for rel in CLOCK_ALLOWLIST:
        assert idx.iter_modules((rel,)), f"stale allowlist entry: {rel}"


def test_guard_catches_a_bare_clock():
    bad = ("import time\n"
           "from time import perf_counter\n"
           "def f():\n"
           "    t0 = time.time()\n"
           "    t1 = perf_counter()\n"
           "    return t1 - t0\n")
    found = A.run_rule_on_sources("bare-clock", {"bad.py": bad})
    assert len(found) == 2
