"""Guard: hot paths time through the tracer/perf API, not ad-hoc clocks.

``ceph_tpu/ops/`` and ``ceph_tpu/backend/`` are the encode/decode hot
paths; timing added there must go through ``trace_span``,
``PerfCounters.time``/``tinc`` or ``traced_jit`` so it lands in the
observability surfaces (`trace dump`, `perf dump`, prometheus) instead of
rotting as a local print.  A bare ``time.time()`` / ``perf_counter()``
call site is allowed only on the explicit allowlist below (the timing
wrappers themselves).
"""
import re
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
SCAN_DIRS = ("ceph_tpu/ops", "ceph_tpu/backend")

# path -> why the bare clock is legitimate there
ALLOWLIST = {
    "ceph_tpu/ops/traced_jit.py":
        "IS the timing wrapper (AOT fallback books compile wall time)",
}

_BARE_TIME = re.compile(r"time\.time\(\)|perf_counter\(\)")


def test_no_bare_timing_in_hot_paths():
    offenders = []
    for sub in SCAN_DIRS:
        for path in sorted((ROOT / sub).rglob("*.py")):
            rel = path.relative_to(ROOT).as_posix()
            if rel in ALLOWLIST:
                continue
            for lineno, line in enumerate(
                    path.read_text().splitlines(), start=1):
                if _BARE_TIME.search(line):
                    offenders.append(f"{rel}:{lineno}: {line.strip()}")
    assert not offenders, (
        "bare timing calls in hot paths — route them through "
        "trace_span/PerfCounters/traced_jit (or extend the allowlist "
        "with a justification):\n" + "\n".join(offenders))


def test_allowlist_entries_still_exist():
    for rel in ALLOWLIST:
        assert (ROOT / rel).exists(), f"stale allowlist entry: {rel}"
