"""Thrasher: randomized OSD kill/revive under live EC I/O with a
model-based consistency check.

Mirrors the reference's thrash-erasure-code suites (reference:
qa/suites/rados/thrash-erasure-code*/ driven by the Thrasher in
qa/tasks/ceph_manager.py:103 — kill_osd :196 / revive_osd :380 while
ceph_test_rados (src/test/osd/RadosModel.cc) validates every read against
a model of expected object contents).  Here the model is a plain dict;
kills are bounded to m concurrent so every PG stays available (the suites
bound thrashing with min_in the same way); revived shards are repaired via
log-based shard repair (PG log catch-up) before the next kill.
"""
import numpy as np
import pytest

from ceph_tpu.backend.ec_backend import RepairState
from ceph_tpu.cluster import MiniCluster

K, M = 4, 2
CHUNK = 128
ROUNDS = 120


@pytest.fixture(scope="module")
def thrashed():
    """Run the whole thrash campaign once; individual tests assert on the
    final state."""
    rng = np.random.default_rng(1234)
    cluster = MiniCluster(n_osds=12, chunk_size=CHUNK)
    pid = cluster.create_ec_pool(
        "thrash", {"plugin": "jax_rs", "k": str(K), "m": str(M),
                   "device": "numpy", "technique": "reed_sol_van"},
        pg_num=8)
    model: dict[str, bytes] = {}
    down: set[int] = set()
    log = []

    def pg_buses_for(osd):
        for g in cluster.pools[pid]["pgs"].values():
            if osd in g.acting:
                yield g

    def kill(osd):
        down.add(osd)
        for g in pg_buses_for(osd):
            g.bus.mark_down(osd)
        log.append(f"kill osd.{osd}")

    def revive(osd):
        down.discard(osd)
        for g in pg_buses_for(osd):
            g.bus.mark_up(osd)
        # repair via the PG log: replay exactly the writes the shard
        # missed (O(missed), not O(all objects) — PGLog.cc semantics)
        for g in pg_buses_for(osd):
            rop = g.backend.start_shard_repair(osd)
            g.bus.deliver_all()
            assert rop.state == RepairState.COMPLETE, (
                f"log repair of osd.{osd} in {g.pgid}: {rop.state}")
        log.append(f"revive osd.{osd}")

    def do_write():
        i = int(rng.integers(0, 40))
        oid = f"obj{i}"
        size = int(rng.integers(1, 5)) * CHUNK * K
        data = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()
        cluster.put(pid, oid, data)
        old = model.get(oid, b"")
        if len(old) > len(data):        # overwrite keeps the longer tail
            data = data + old[len(data):]
        model[oid] = data

    def do_read():
        if not model:
            return
        oid = sorted(model)[int(rng.integers(0, len(model)))]
        want = model[oid]
        got = cluster.get(pid, oid, len(want))
        assert got == want, f"{oid} diverged from model mid-thrash"

    for _ in range(ROUNDS):
        action = rng.random()
        if action < 0.45:
            do_write()
        elif action < 0.80:
            do_read()
        elif action < 0.90 and len(down) < M:
            # never kill a primary: the per-PG group has no re-peering /
            # primary takeover (the reference Thrasher relies on peering
            # electing a new primary, which this harness doesn't model)
            primaries = {g.backend.whoami
                         for g in cluster.pools[pid]["pgs"].values()}
            candidates = [o for o in range(12)
                          if o not in down and o not in primaries]
            if candidates:
                kill(int(rng.choice(candidates)))
        elif down:
            revive(int(rng.choice(sorted(down))))

    for osd in sorted(down):
        revive(osd)
    return cluster, pid, model, log


class TestThrash:
    def test_campaign_exercised_failures(self, thrashed):
        _, _, model, log = thrashed
        assert sum(1 for e in log if e.startswith("kill")) >= 3
        assert len(model) >= 10

    def test_all_objects_match_model(self, thrashed):
        cluster, pid, model, _ = thrashed
        for oid, want in sorted(model.items()):
            got = cluster.get(pid, oid, len(want))
            assert got == want, f"{oid} lost data after thrashing"

    def test_deep_scrub_clean_everywhere(self, thrashed):
        cluster, pid, model, _ = thrashed
        for oid in sorted(model):
            g = cluster.pg_group(pid, oid)
            report = g.backend.be_deep_scrub(oid)
            bad = {c for c, clean in report.items() if not clean}
            assert not bad, f"{oid}: inconsistent chunks {bad} after repair"

    def test_degraded_reads_still_consistent(self, thrashed):
        """One more failure after the campaign: every object must still
        read back through reconstruction."""
        cluster, pid, model, _ = thrashed
        victim_groups = {}
        for oid, want in sorted(model.items())[:8]:
            g = cluster.pg_group(pid, oid)
            if id(g) not in victim_groups:
                # non-primary data shard (killing the primary means
                # re-peering, which the single-primary group doesn't model)
                victim = g.acting[1]
                victim_groups[id(g)] = victim
                g.bus.mark_down(victim)
            got = cluster.get(pid, oid, len(want))
            assert got == want
