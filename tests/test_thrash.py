"""Thrasher: randomized OSD kill/revive under live EC I/O with a
model-based consistency check — kills past m, into min_size territory.

Mirrors the reference's thrash-erasure-code suites (reference:
qa/suites/rados/thrash-erasure-code*/ driven by the Thrasher in
qa/tasks/ceph_manager.py:103 — kill_osd :196 / revive_osd :380 while
ceph_test_rados (src/test/osd/RadosModel.cc) validates every read against
a model of expected object contents).  Unlike the r3 harness, kills are
NOT bounded to m: up to k+m-1 shards of a PG may be down at once, driving
PGs below min_size.  The model then asserts the reference's availability
contract instead of availability itself:

- a write acked (on_commit fired) is NEVER lost, no matter what dies next;
- a write below min_size parks unacked — the model only advances when the
  commit callback fires, whenever that is;
- reads succeed and match the model whenever >= k current shards exist,
  and fail cleanly (EIO) otherwise;
- after full revival + repair, every acked byte reads back and deep scrub
  is clean everywhere.
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster

K, M = 4, 2
CHUNK = 128
ROUNDS = 200
MAX_DOWN = K + M - 1     # past m: PGs may lose up to 5 of 6 shards


def _run_campaign(cluster, pid, rng, rounds):
    """The thrash campaign body, shared by the inline-recovery fixture
    and the recovery-scheduler soak variant: randomized kills/revives
    past m under live writes/reads, model-checked, then full revival and
    convergence.  Returns (model, log)."""
    model: dict[str, bytes] = {}
    down: set[int] = set()
    log = []

    def pg_groups_for(osd):
        for g in cluster.pools[pid]["pgs"].values():
            if osd in g.acting:
                yield g

    def kill(osd):
        down.add(osd)
        for g in pg_groups_for(osd):
            g.bus.mark_down(osd)
        log.append(f"kill osd.{osd}")

    def revive(osd):
        down.discard(osd)
        # mark_up auto-starts a shard repair (peering); repairs that cannot
        # proceed yet (< k current shards) park and finish on later revives
        for g in pg_groups_for(osd):
            g.bus.mark_up(osd)
            g.bus.deliver_all()
        log.append(f"revive osd.{osd}")

    def do_write():
        i = int(rng.integers(0, 40))
        oid = f"obj{i}"
        size = int(rng.integers(1, 5)) * CHUNK * K
        data = rng.integers(0, 256, size=size, dtype=np.uint8).tobytes()

        # the model advances ONLY when the write is durable on min_size
        # shards — exactly the reference's ack contract.  The callback may
        # fire inside this put, or many rounds later on a revive.
        def committed(tid, _oid=oid, _data=data):
            old = model.get(_oid, b"")
            merged = _data + old[len(_data):] if len(old) > len(_data) \
                else _data
            model[_oid] = merged
            log.append(f"commit {_oid}")
        cluster.put(pid, oid, data, wait=False, on_commit=committed)

    def do_read():
        if not model:
            return
        oid = sorted(model)[int(rng.integers(0, len(model)))]
        want = model[oid]
        g = cluster.pg_group(pid, oid)
        if len(g.backend.current_shards()) >= K:
            got = cluster.get(pid, oid, len(want))
            assert got == want, f"{oid} diverged from model mid-thrash"
        else:
            # below k current shards the read must fail cleanly, not
            # return wrong bytes (inactive-PG behavior)
            with pytest.raises(IOError):
                cluster.get(pid, oid, len(want))
            log.append(f"eio {oid} (expected: <k current)")

    def kill_candidates():
        primaries = {g.backend.whoami
                     for g in cluster.pools[pid]["pgs"].values()}
        return [o for o in range(12)
                if o not in down and o not in primaries]

    def do_partial_write_then_kill():
        """Kill a shard MID-WRITE: sub-writes partially delivered when the
        victim dies.  If live acks can't reach min_size the survivors must
        roll the write back (the ecbackend.rst two-phase contract)."""
        if len(down) >= MAX_DOWN:
            return
        i = int(rng.integers(0, 40))
        oid = f"obj{i}"
        data = rng.integers(0, 256, size=CHUNK * K,
                            dtype=np.uint8).tobytes()

        def committed(tid, _oid=oid, _data=data):
            old = model.get(_oid, b"")
            merged = _data + old[len(_data):] if len(old) > len(_data) \
                else _data
            model[_oid] = merged
            log.append(f"commit {_oid}")
        g = cluster.put(pid, oid, data, deliver=False, on_commit=committed)
        live = [s for s in g.acting if s not in down]
        for s in live[:int(rng.integers(0, len(live) + 1))]:
            while g.bus.deliver_one(s):
                pass
        victims = [s for s in live if s != g.backend.whoami
                   and s in kill_candidates()]
        if victims:
            kill(int(rng.choice(victims)))
            log.append(f"  (mid-write of {oid})")
        cluster.deliver_all()

    for _ in range(rounds):
        action = rng.random()
        if action < 0.40:
            do_write()
        elif action < 0.48:
            do_partial_write_then_kill()
        elif action < 0.80:
            do_read()
        elif action < 0.92 and len(down) < MAX_DOWN:
            # never kill a primary: the per-PG group has no re-peering /
            # primary takeover (the reference Thrasher relies on peering
            # electing a new primary, which this harness doesn't model)
            primaries = {g.backend.whoami
                         for g in cluster.pools[pid]["pgs"].values()}
            candidates = [o for o in range(12)
                          if o not in down and o not in primaries]
            if candidates:
                kill(int(rng.choice(candidates)))
        elif down:
            revive(int(rng.choice(sorted(down))))

    # full revival: every shard comes back; the backend auto-repairs (and
    # auto-retries failed repairs on every cluster event), parked writes
    # drain, and the cluster converges
    for osd in sorted(down):
        revive(osd)
    for _ in range(20):
        busy = False
        for g in cluster.pools[pid]["pgs"].values():
            g.bus.deliver_all()
            if g.backend.stale or g.backend.shard_repairs:
                busy = True
        if cluster.recovery is not None and cluster.recovery.jobs:
            busy = True
        if not busy:
            break
    return model, log


def _build_cluster(seed_offset=0):
    # fresh Context per campaign: the scheduled variant's conf knobs
    # must not leak into other tests via the process-global default
    from ceph_tpu.common import Context
    cluster = MiniCluster(n_osds=12, chunk_size=CHUNK, cct=Context())
    pid = cluster.create_ec_pool(
        "thrash", {"plugin": "jax_rs", "k": str(K), "m": str(M),
                   "device": "numpy", "technique": "reed_sol_van"},
        pg_num=8)
    # messenger-level fault injection rides along with the kills: every
    # message may be duplicated and cross-sender delivery order at each
    # destination is randomized (per-sender FIFO preserved, like TCP)
    from ceph_tpu.backend.messages import FaultConfig
    for i, g in enumerate(cluster.pools[pid]["pgs"].values()):
        g.bus.inject_faults(FaultConfig(seed=i * 7 + 1 + seed_offset,
                                        reorder=True, dup_prob=0.1))
    return cluster, pid


@pytest.fixture(scope="module")
def thrashed():
    """Run the whole thrash campaign once; individual tests assert on the
    final state."""
    rng = np.random.default_rng(20260729)
    cluster, pid = _build_cluster()
    model, log = _run_campaign(cluster, pid, rng, ROUNDS)
    return cluster, pid, model, log


class TestThrash:
    def test_campaign_exercised_failures_past_m(self, thrashed):
        cluster, pid, model, log = thrashed
        assert sum(1 for e in log if e.startswith("kill")) >= 5
        assert len(model) >= 10
        # the campaign must actually have driven PGs below availability:
        # at least one clean EIO or late commit proves the gate engaged
        assert any(e.startswith("eio") for e in log) or \
            sum(1 for e in log if e.startswith("commit")) > len(model)
        # and at least one mid-write kill forced a rollback
        rollbacks = sum(
            g.backend.perf.get("write_rollbacks")
            for g in cluster.pools[pid]["pgs"].values())
        assert rollbacks >= 1, "campaign never exercised write rollback"

    def test_everything_repaired(self, thrashed):
        cluster, pid, _, _ = thrashed
        for g in cluster.pools[pid]["pgs"].values():
            assert not g.backend.stale, \
                f"{g.pgid}: shards {g.backend.stale} never repaired"
            assert not g.backend.waiting_state, \
                f"{g.pgid}: writes still parked after full revival"
            assert g.backend.is_active()

    def test_all_objects_match_model(self, thrashed):
        cluster, pid, model, _ = thrashed
        for oid, want in sorted(model.items()):
            got = cluster.get(pid, oid, len(want))
            assert got == want, f"{oid} lost acked data after thrashing"

    def test_deep_scrub_clean_everywhere(self, thrashed):
        cluster, pid, model, _ = thrashed
        for oid in sorted(model):
            g = cluster.pg_group(pid, oid)
            report = g.backend.be_deep_scrub(oid)
            bad = {c for c, clean in report.items() if not clean}
            assert not bad, f"{oid}: inconsistent chunks {bad} after repair"

    def test_degraded_reads_still_consistent(self, thrashed):
        """One more failure after the campaign: every object must still
        read back through reconstruction."""
        cluster, pid, model, _ = thrashed
        victim_groups = {}
        for oid, want in sorted(model.items())[:8]:
            g = cluster.pg_group(pid, oid)
            if id(g) not in victim_groups:
                # non-primary data shard (killing the primary means
                # re-peering, which the single-primary group doesn't model)
                victim = g.acting[1]
                victim_groups[id(g)] = victim
                g.bus.mark_down(victim)
            got = cluster.get(pid, oid, len(want))
            assert got == want


@pytest.fixture(scope="module")
def thrashed_scheduled():
    """The same campaign under the RECOVERY SCHEDULER with tight caps:
    every repair is reservation-gated (osd_max_backfills=1), waves carry
    ONE object (osd_recovery_max_active=1), and a byte-rate cap +
    recovery sleep pace them — the acked-write/read invariants must hold
    exactly as in the ungated run, and the cluster must still converge."""
    rng = np.random.default_rng(20260804)
    cluster, pid = _build_cluster(seed_offset=1000)
    cluster.cct.conf.set("osd_recovery_max_active", 1)
    cluster.cct.conf.set("osd_recovery_max_bytes_per_sec", 64 * 1024)
    cluster.cct.conf.set("osd_recovery_sleep", 0.001)
    cluster.enable_recovery_scheduler()
    model, log = _run_campaign(cluster, pid, rng, 120)
    return cluster, pid, model, log


@pytest.fixture(scope="module")
def thrashed_scheduled_fused():
    """The campaign again at the DEFAULT wave size (osd_recovery_max_active=3,
    no byte cap): waves carry multiple objects, so the batch-fused
    decode path (_RecoveryWave / decode_shards_many) — not the
    per-object escape hatch — is what the thrash exercises."""
    rng = np.random.default_rng(20260805)
    cluster, pid = _build_cluster(seed_offset=2000)
    cluster.enable_recovery_scheduler()
    model, log = _run_campaign(cluster, pid, rng, 120)
    return cluster, pid, model, log


class TestThrashScheduledFused:
    def test_converged_with_fused_waves(self, thrashed_scheduled_fused):
        cluster, pid, model, log = thrashed_scheduled_fused
        assert sum(1 for e in log if e.startswith("kill")) >= 3
        for g in cluster.pools[pid]["pgs"].values():
            assert not g.backend.stale
            assert g.backend.is_active()
        assert cluster.recovery.jobs == {}
        assert cluster.recovery.summary()["reservations"]["granted"] == 0
        sched = cluster.recovery
        # fusion actually happened: more objects than waves overall
        assert sched.perf.get("wave_objects") > sched.perf.get("waves")

    def test_acked_writes_survive(self, thrashed_scheduled_fused):
        cluster, pid, model, _ = thrashed_scheduled_fused
        for oid, want in sorted(model.items()):
            assert cluster.get(pid, oid, len(want)) == want


@pytest.fixture(scope="module")
def thrashed_clay():
    """The recovery soak over a CLAY pool: sub-chunk FRACTIONAL repair
    reads (get_repair_subchunks < full chunk) under randomized churn,
    reservation-gated by the scheduler.  The seed's fractional-read
    regression (zero-padded helper reads full-decoding garbage) was only
    caught by a unit test; this arm makes the whole repair path —
    fractional reads through ECSubRead slicing, per-object fallback
    inside scheduler waves, log catch-up after revival — hold the
    acked-write/scrub-clean invariants under fire, so it cannot silently
    regress again (ROADMAP item 1's leftover)."""
    from ceph_tpu.common import Context
    rng = np.random.default_rng(20260806)
    cluster = MiniCluster(n_osds=12, chunk_size=CHUNK, cct=Context())
    pid = cluster.create_ec_pool(
        "thrash", {"plugin": "clay", "k": str(K), "m": str(M),
                   "scalar_mds": "jax_rs", "device": "numpy"},
        pg_num=8)
    from ceph_tpu.backend.messages import FaultConfig
    for i, g in enumerate(cluster.pools[pid]["pgs"].values()):
        g.bus.inject_faults(FaultConfig(seed=i * 7 + 3001,
                                        reorder=True, dup_prob=0.1))
    cluster.enable_recovery_scheduler()
    model, log = _run_campaign(cluster, pid, rng, 120)
    return cluster, pid, model, log


class TestThrashClay:
    def test_fractional_code_actually_engaged(self, thrashed_clay):
        cluster, pid, model, log = thrashed_clay
        ec = cluster.pools[pid]["ec"]
        # the pool really is sub-chunked and its repair plan fractional
        assert ec.get_sub_chunk_count() > 1
        assert sum(c for _, c in ec.get_repair_subchunks(1)) < \
            ec.get_sub_chunk_count()
        # and the campaign really repaired through it
        assert sum(1 for e in log if e.startswith("kill")) >= 3
        recoveries = sum(
            g.backend.perf.get("recoveries")
            + g.backend.perf.get("log_repair_objects")
            + g.backend.perf.get("backfill_objects")
            for g in cluster.pools[pid]["pgs"].values())
        assert recoveries >= 1, "clay soak never exercised repair"

    def test_converged_and_model_intact(self, thrashed_clay):
        cluster, pid, model, _ = thrashed_clay
        assert len(model) >= 8
        for g in cluster.pools[pid]["pgs"].values():
            assert not g.backend.stale, \
                f"{g.pgid}: shards {g.backend.stale} never repaired"
            assert not g.backend.waiting_state
            assert g.backend.is_active()
        assert cluster.recovery.jobs == {}
        for oid, want in sorted(model.items()):
            got = cluster.get(pid, oid, len(want))
            assert got == want, f"{oid} lost acked data under clay repair"

    def test_deep_scrub_clean_after_clay_soak(self, thrashed_clay):
        cluster, pid, model, _ = thrashed_clay
        for oid in sorted(model):
            g = cluster.pg_group(pid, oid)
            report = g.backend.be_deep_scrub(oid)
            bad = {c for c, clean in report.items() if not clean}
            assert not bad, f"{oid}: inconsistent chunks {bad}"


class TestThrashScheduled:
    def test_campaign_ran_and_converged(self, thrashed_scheduled):
        cluster, pid, model, log = thrashed_scheduled
        assert sum(1 for e in log if e.startswith("kill")) >= 3
        assert len(model) >= 8
        for g in cluster.pools[pid]["pgs"].values():
            assert not g.backend.stale, \
                f"{g.pgid}: shards {g.backend.stale} never repaired"
            assert not g.backend.waiting_state
            assert g.backend.is_active()
        # scheduler drained: no jobs held, no reservations leaked
        assert cluster.recovery.jobs == {}
        assert cluster.recovery.summary()["reservations"]["granted"] == 0

    def test_repairs_were_reservation_gated(self, thrashed_scheduled):
        cluster, _pid, _model, _log = thrashed_scheduled
        sched = cluster.recovery
        assert sched.perf.get("jobs_completed") >= 1
        bound = cluster.cct.conf.get("osd_max_backfills")
        for table in (sched._local, sched._remote):
            for r in table.values():
                assert r.stats.peak_in_flight <= bound

    def test_acked_writes_survive_and_scrub_clean(self, thrashed_scheduled):
        cluster, pid, model, _ = thrashed_scheduled
        for oid, want in sorted(model.items()):
            got = cluster.get(pid, oid, len(want))
            assert got == want, f"{oid} lost acked data under gated repair"
        for oid in sorted(model):
            g = cluster.pg_group(pid, oid)
            report = g.backend.be_deep_scrub(oid)
            bad = {c for c, clean in report.items() if not clean}
            assert not bad, f"{oid}: inconsistent chunks {bad}"
