"""Guard: every queue constructed in ``ceph_tpu/exec/`` and
``ceph_tpu/recovery/`` is bounded.

The serving subsystem exists to put BOUNDS between demand and the device
(ISSUE 2's backpressure contract: once a throttle limit is hit,
submission blocks or fails fast and queue depth/bytes stay bounded), and
the recovery subsystem exists to put bounds between damage and repair
bandwidth (ISSUE 4: reservations, wave sizes, byte-rate caps).  An
unbounded ``deque()``/``Queue()`` smuggled into either silently voids
that contract under overload — this guard fails the build instead
(mirrors the ``tests/test_no_bare_time.py`` pattern: discipline as a
test).  The recovery package's lists are bounded by construction (one
reservation per distinct PG); the guard keeps stdlib queue types out.

Checked constructors (by AST, so multiline calls and aliases through
``collections.deque``/``queue.Queue`` are caught):

- ``deque`` must pass ``maxlen=`` (positionally or by keyword), non-None;
- ``queue.Queue``/``LifoQueue``/``PriorityQueue`` must pass a nonzero
  ``maxsize``;
- ``queue.SimpleQueue`` is banned outright (it cannot be bounded).

Unbounded queues remain legitimate ELSEWHERE (e.g. the mClock queues in
``osd/mclock.py``, whose bound is the daemon/engine throttle in front of
them) — the scan is scoped to ``ceph_tpu/exec/`` where construction
implies ownership of the bound.
"""
import ast
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
SCAN_DIRS = (ROOT / "ceph_tpu" / "exec",
             ROOT / "ceph_tpu" / "recovery")

_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue"}


def _callee_name(node: ast.Call) -> str | None:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


def _has_bound(node: ast.Call, kw_name: str, pos_index: int) -> bool:
    for kw in node.keywords:
        if kw.arg == kw_name:
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value in (None, 0))
    if len(node.args) > pos_index:
        arg = node.args[pos_index]
        return not (isinstance(arg, ast.Constant)
                    and arg.value in (None, 0))
    return False


def _scan(path: Path) -> list[str]:
    tree = ast.parse(path.read_text(), filename=str(path))
    offenders = []
    rel = path.relative_to(ROOT).as_posix()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node)
        if name == "SimpleQueue":
            offenders.append(f"{rel}:{node.lineno}: SimpleQueue cannot "
                             f"be bounded — use Queue(maxsize=...)")
        elif name == "deque" and not _has_bound(node, "maxlen", 1):
            offenders.append(f"{rel}:{node.lineno}: deque without an "
                             f"explicit maxlen bound")
        elif name in _QUEUE_CTORS and not _has_bound(node, "maxsize", 0):
            offenders.append(f"{rel}:{node.lineno}: {name} without an "
                             f"explicit nonzero maxsize bound")
    return offenders


def test_scanned_packages_exist():
    for scan_dir in SCAN_DIRS:
        files = sorted(scan_dir.rglob("*.py"))
        assert files, (f"{scan_dir.name}/ vanished — update or remove "
                       f"this guard")


def test_every_queue_in_scanned_packages_is_bounded():
    offenders = []
    for scan_dir in SCAN_DIRS:
        for path in sorted(scan_dir.rglob("*.py")):
            offenders.extend(_scan(path))
    assert not offenders, (
        "unbounded queues in a bounded subsystem — pass an explicit "
        "bound (the backpressure contract):\n" + "\n".join(offenders))


def test_guard_rejects_unbounded(tmp_path):
    """The guard itself must catch the three shapes it documents."""
    bad = tmp_path / "bad.py"
    bad.write_text("from collections import deque\nimport queue\n"
                   "a = deque()\n"
                   "b = queue.Queue()\n"
                   "c = queue.SimpleQueue()\n"
                   "ok = deque(maxlen=8)\n"
                   "ok2 = queue.Queue(maxsize=8)\n")
    found = _scan_path_outside_root(bad)
    assert len(found) == 3


def _scan_path_outside_root(path: Path) -> list[str]:
    tree = ast.parse(path.read_text(), filename=str(path))
    offenders = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _callee_name(node)
        if name == "SimpleQueue":
            offenders.append(f"{path.name}:{node.lineno}")
        elif name == "deque" and not _has_bound(node, "maxlen", 1):
            offenders.append(f"{path.name}:{node.lineno}")
        elif name in _QUEUE_CTORS and not _has_bound(node, "maxsize", 0):
            offenders.append(f"{path.name}:{node.lineno}")
    return offenders
