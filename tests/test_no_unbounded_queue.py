"""Guard: every queue constructed in ``ceph_tpu/exec/`` and
``ceph_tpu/recovery/`` is bounded.

Thin wrapper over the ``unbounded-queue`` rule in
:mod:`ceph_tpu.analysis.rules_guards` (ISSUE 15); semantics unchanged:
``deque`` needs ``maxlen``, ``Queue``/``LifoQueue``/``PriorityQueue``
need a nonzero ``maxsize``, ``SimpleQueue`` is banned outright.
"""
from pathlib import Path

import ceph_tpu.analysis as A

ROOT = Path(__file__).resolve().parent.parent


def test_scanned_packages_exist():
    idx = A.default_index()
    for sub in ("ceph_tpu/exec", "ceph_tpu/recovery"):
        assert idx.iter_modules((sub,)), (
            f"{sub}/ vanished — update or remove this guard")


def test_every_queue_in_scanned_packages_is_bounded():
    offenders = [f.render() for f in A.run_rules(
        A.default_index(), ("unbounded-queue",))]
    assert not offenders, (
        "unbounded queues in a bounded subsystem — pass an explicit "
        "bound (the backpressure contract):\n" + "\n".join(offenders))


def test_guard_rejects_unbounded():
    """The rule catches the three shapes it documents."""
    bad = ("from collections import deque\nimport queue\n"
           "a = deque()\n"
           "b = queue.Queue()\n"
           "c = queue.SimpleQueue()\n"
           "ok = deque(maxlen=8)\n"
           "ok2 = queue.Queue(maxsize=8)\n")
    found = A.run_rule_on_sources("unbounded-queue", {"bad.py": bad})
    assert len(found) == 3
