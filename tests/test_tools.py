"""osdmaptool/crushtool-equivalent CLI tools (SURVEY.md §2.3: the offline
pure-function cluster evaluators, src/tools/osdmaptool.cc:491-610 and
src/crush/CrushTester.cc:600-700)."""
import io
import json

import numpy as np
import pytest

from ceph_tpu.crush import (CRUSH_ITEM_NONE, CRUSH_RULE_CHOOSELEAF_FIRSTN,
                            CRUSH_RULE_EMIT, CRUSH_RULE_TAKE, crush_do_rule)
from ceph_tpu.osdmap import OSDMap, PG
from ceph_tpu.tools import test_map_pgs as map_pgs_report
from ceph_tpu.tools import test_rule as rule_report
from ceph_tpu.tools.crushtool import main as crushtool_main
from ceph_tpu.tools.osdmaptool import main as osdmaptool_main

from test_osdmap import build_cluster


class TestMapPGs:
    def test_counts_add_up(self):
        m = build_cluster()
        buf = io.StringIO()
        stats = map_pgs_report(m, out=buf)
        text = buf.getvalue()
        assert "pool 1 pg_num 64" in text
        assert "pool 2 pg_num 48" in text
        assert "#osd\tcount\tfirst\tprimary" in text
        # every acting entry counted once: 64*3 + 48*6
        assert stats["total"] == 64 * 3 + 48 * 6
        assert sum(stats["primary"]) == 64 + 48
        assert stats["in"] == m.max_osd
        assert stats["size_hist"] == {3: 64, 6: 48}

    def test_counts_match_scalar_chain(self):
        m = build_cluster(seed=9)
        stats = map_pgs_report(m, pool=1)
        want = [0] * m.max_osd
        for ps in range(m.pools[1].pg_num):
            _, _, acting, _ = m.pg_to_up_acting_osds(PG(1, ps))
            for o in acting:
                if o != CRUSH_ITEM_NONE:
                    want[o] += 1
        assert stats["count"] == want

    def test_out_osds_excluded_from_table(self):
        m = build_cluster()
        m.osd_weight[0] = 0
        buf = io.StringIO()
        stats = map_pgs_report(m, out=buf)
        assert stats["in"] == m.max_osd - 1
        assert "osd.0\t" not in buf.getvalue()

    def test_dump_format(self):
        m = build_cluster()
        buf = io.StringIO()
        map_pgs_report(m, pool=1, dump=True, out=buf)
        lines = [ln for ln in buf.getvalue().splitlines()
                 if "\t" in ln and not ln.startswith("#") and
                 not ln.startswith("osd.")]
        assert len(lines) == 64
        pgid, osds, primary = lines[0].split("\t")
        assert pgid == "1.0"
        assert json.loads(osds)  # list literal
        assert int(primary) >= 0

    def test_cli_roundtrip(self, tmp_path, capsys):
        m = build_cluster()
        path = tmp_path / "map.json"
        path.write_text(json.dumps(m.to_dict()))
        rc = osdmaptool_main([str(path), "--test-map-pgs", "--print",
                              "--test-map-pg", "1.7"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "epoch 1" in out
        assert "pool 1 'rbd' replicated size 3" in out
        assert " parsed '1.7' -> 1.7" in out
        assert " avg " in out and " stddev " in out


class TestCrushTester:
    def test_per_device_matches_interpreter(self):
        m = build_cluster(seed=3)
        ruleno = m.pools[1].crush_rule
        res = rule_report(m.crush, ruleno, num_rep=3, min_x=0, max_x=127)
        want = [0] * m.crush.max_devices
        for x in range(128):
            for o in crush_do_rule(m.crush, ruleno, x, 3):
                if o != CRUSH_ITEM_NONE:
                    want[o] += 1
        assert res["per_device"] == want
        assert res["bad_mappings"] == 0
        assert res["sizes"] == {3: 128}

    def test_bad_mappings_detected(self):
        """Asking for more replicas than failure domains yields short/holey
        results that must be flagged."""
        m = build_cluster(n_racks=2, hosts_per_rack=2)
        cmap = m.crush
        root = max(b.type for b in cmap.buckets.values())
        root_id = next(b.id for b in cmap.buckets.values() if b.type == 3)
        ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root_id, 0),
                                (CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 2),
                                (CRUSH_RULE_EMIT, 0, 0)])
        res = rule_report(cmap, ruleno, num_rep=3, min_x=0, max_x=63)
        assert res["bad_mappings"] == 64      # only 2 racks exist

    def test_utilization_expectation_weighted(self):
        m = build_cluster(seed=5)
        ruleno = m.pools[1].crush_rule
        res = rule_report(m.crush, ruleno, num_rep=3, min_x=0, max_x=255)
        exp = res["expected"]
        assert exp, "no expectation computed"
        total_expected = sum(exp.values())
        assert total_expected == pytest.approx(3 * 256, rel=1e-6)
        # zero reweight zeroes the expectation
        w = [0x10000] * m.crush.max_devices
        w[0] = 0
        res2 = rule_report(m.crush, ruleno, num_rep=3, min_x=0, max_x=63,
                         weights=w)
        assert res2["expected"][0] == 0

    def test_cli(self, tmp_path, capsys):
        m = build_cluster()
        path = tmp_path / "crush.json"
        path.write_text(json.dumps(m.crush.to_dict()))
        rc = crushtool_main(["-i", str(path), "--test",
                             "--rule", str(m.pools[1].crush_rule),
                             "--num-rep", "3", "--max-x", "63",
                             "--show-statistics", "--show-utilization"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "num_rep 3 result size == 3:\t64/64" in out
        assert "stored" in out and "expected" in out
