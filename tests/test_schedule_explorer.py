"""Schedule exploration: delivery-order race hunting (the TSAN analog).

The reference validates concurrency with TSAN/lockdep/valgrind builds
(CMakeLists.txt:585-607); this framework's nondeterminism is delivery
order, so the explorer drives scenarios through many interleavings and
asserts the EC pipeline's invariants hold in every one — and proves it
can catch a planted race by replaying its trace.
"""
import numpy as np
import pytest

from ceph_tpu.backend.ec_backend import ECBackend
from ceph_tpu.backend.ecutil import StripeInfo
from ceph_tpu.backend.transaction import PGTransaction
from ceph_tpu.plugins.plugin_xor import ErasureCodeXor
from ceph_tpu.utils.schedule_explorer import (
    explore_dfs, explore_random, replay,
)

K, M, CHUNK = 2, 1, 256
STRIPE = K * CHUNK


def _codec():
    ec = ErasureCodeXor()
    ec.init({"k": str(K), "m": str(M), "plugin": "xor"})
    return ec


def _payload(seed):
    return np.random.default_rng(seed).integers(
        0, 256, STRIPE, dtype=np.uint8).tobytes()


def _mk_backend(bus):
    from ceph_tpu.backend.pg_backend import OSDShard
    backend = ECBackend(_codec(), StripeInfo(K, CHUNK), bus,
                        acting=[0, 1, 2], whoami=0)
    for s in (1, 2):
        OSDShard(s, bus)
    return backend


def _read(backend, bus, oid):
    out = {}
    backend.objects_read_and_reconstruct(
        {oid: [(0, STRIPE)]},
        lambda result, errors: out.update(result=result, errors=errors))
    bus.run_to_quiescence()
    if out.get("errors"):
        raise IOError(out["errors"])
    return bytes(out["result"][oid][0][2])


def scenario_concurrent_writes(bus):
    """Two in-flight writes to one object + a concurrent write to
    another: in EVERY delivery order, acked writes are durable, the
    pipeline commits them in submission order, and all shards scrub
    clean."""
    backend = _mk_backend(bus)
    a, b, c = _payload(1), _payload(2), _payload(3)
    commits = []
    backend.submit_transaction(PGTransaction().write("obj", 0, a),
                               on_commit=lambda t: commits.append("a"))
    backend.submit_transaction(PGTransaction().write("obj", 0, b),
                               on_commit=lambda t: commits.append("b"))
    backend.submit_transaction(PGTransaction().write("other", 0, c),
                               on_commit=lambda t: commits.append("c"))
    bus.run_to_quiescence()
    assert "a" in commits and "b" in commits and "c" in commits
    assert commits.index("a") < commits.index("b"), "pipeline order broken"
    assert _read(backend, bus, "obj") == b, "last write must win"
    assert _read(backend, bus, "other") == c
    scrub = {oid: backend.be_deep_scrub(oid) for oid in ("obj", "other")}
    for oid, per_shard in scrub.items():
        assert all(per_shard.values()), f"scrub inconsistency on {oid}"


def scenario_write_vs_recovery(bus):
    """A shard dies mid-write and revives: whatever the interleaving of
    sub-writes, repair reads and pushes, the acked write survives and
    the revived shard converges to the authority log."""
    backend = _mk_backend(bus)
    first, second = _payload(4), _payload(5)
    backend.submit_transaction(PGTransaction().write("obj", 0, first))
    bus.run_to_quiescence()
    bus.mark_down(2)
    committed = []
    backend.submit_transaction(PGTransaction().write("obj", 0, second),
                               on_commit=committed.append)
    bus.run_to_quiescence()
    assert committed, "write acked while 2/3 shards up (min_size k)"
    bus.mark_up(2)                      # auto-repair kicks
    bus.run_to_quiescence()
    assert _read(backend, bus, "obj") == second
    shard2 = bus.handlers[2]
    assert shard2.pg_log.head == backend.pg_log.head, "revived shard stale"


def test_concurrent_writes_random_schedules():
    res = explore_random(scenario_concurrent_writes, schedules=40)
    assert res.ok, f"trace {res.failure_trace}: {res.failure}"
    assert res.schedules_run == 40
    assert len(res.traces_seen) > 1, "exploration degenerated to one order"


def test_concurrent_writes_dfs():
    res = explore_dfs(scenario_concurrent_writes, max_runs=120)
    assert res.ok, f"trace {res.failure_trace}: {res.failure}"
    assert res.schedules_run == 120          # tree is larger than the bound
    assert len(res.traces_seen) == 120       # every schedule distinct


def test_write_vs_recovery_schedules():
    res = explore_random(scenario_write_vs_recovery, schedules=30)
    assert res.ok, f"trace {res.failure_trace}: {res.failure}"


def test_explorer_catches_planted_race():
    """Sanity: the tool finds a real ordering bug and its trace replays.
    The planted 'service' acks as soon as ANY reply arrives (quorum 1 of
    2) and claims the FIRST reply's payload is the quorum value — true
    only for schedules that deliver replica 1 first."""
    from ceph_tpu.backend.messages import PGLogInfo, PGLogQuery

    class Replica:
        def __init__(self, bus, shard, value):
            self.bus, self.shard, self.value = bus, shard, value
            bus.register(shard, self)

        def handle_message(self, m):
            if isinstance(m, PGLogQuery):
                self.bus.send(m.from_shard,
                              PGLogInfo(self.shard, self.value, 0))

    class BuggyQuorum:
        def __init__(self, bus):
            self.bus = bus
            self.first = None
            bus.register(0, self)
            bus.send(1, PGLogQuery(0))
            bus.send(2, PGLogQuery(0))

        def handle_message(self, m):
            if self.first is None:
                self.first = m.last_update     # BUG: first reply "wins"

    def scenario(bus):
        svc = BuggyQuorum(bus)
        Replica(bus, 1, value=10)
        Replica(bus, 2, value=20)
        bus.run_to_quiescence()
        assert svc.first == 10, "quorum raced: adopted the wrong reply"

    res = explore_dfs(scenario, max_runs=50)
    assert not res.ok, "explorer missed the planted race"
    with pytest.raises(AssertionError, match="quorum raced"):
        replay(scenario, res.failure_trace)


def scenario_peering_vs_writes(bus):
    """The peering statechart restarted mid-write-storm: in EVERY
    delivery interleaving of GetInfo replies, activation acks, sub-ops
    and repair traffic, the PG ends Active with all acked writes
    readable and the statechart history well-formed."""
    from ceph_tpu.osd.peering import PeeringCoordinator, PState
    backend = _mk_backend(bus)
    coord = PeeringCoordinator(backend)
    a, b = _payload(8), _payload(9)
    commits = []
    backend.submit_transaction(PGTransaction().write("obj", 0, a),
                               on_commit=lambda t: commits.append("a"))
    coord.advance_map(epoch=3)      # peer while the write is in flight
    backend.submit_transaction(PGTransaction().write("obj", 0, b),
                               on_commit=lambda t: commits.append("b"))
    bus.run_to_quiescence()
    assert coord.state is PState.ACTIVE, coord.state
    assert commits == ["a", "b"], commits
    assert _read(backend, bus, "obj") == b
    # the history never skips states within one epoch
    seq = [s for e, s in coord.history if e == 3]
    assert seq[0] == PState.GET_INFO.value
    assert seq[-1] == PState.ACTIVE.value


def test_peering_vs_writes_schedules():
    res = explore_random(scenario_peering_vs_writes, schedules=30)
    assert res.ok, f"trace {res.failure_trace}: {res.failure}"
    res = explore_dfs(scenario_peering_vs_writes, max_runs=60)
    assert res.ok, f"trace {res.failure_trace}: {res.failure}"
