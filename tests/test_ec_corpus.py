"""Erasure-code encoding non-regression: replay the committed corpus.

Mirror of the reference's corpus replay (reference:
src/test/erasure-code/ceph_erasure_code_non_regression.cc +
qa/workunits/erasure-code/encode-decode-non-regression.sh:19-40 — encoding
stability across versions is a hard compatibility requirement, SURVEY.md
§4.2): every (plugin, profile) must reproduce the exact chunk bytes
recorded in tests/golden/ec_corpus.json, and must decode the original
payload back from any m-subset erasure of those chunks.
"""
import hashlib
import json
import os

import numpy as np
import pytest

from ceph_tpu.plugins.registry import ErasureCodePluginRegistry

CORPUS = os.path.join(os.path.dirname(__file__), "golden", "ec_corpus.json")
with open(CORPUS) as f:
    C = json.load(f)


def payload() -> bytes:
    rng = np.random.default_rng(C["payload_seed"])
    return rng.integers(0, 256, size=C["payload_size"],
                        dtype=np.uint8).tobytes()


def make_impl(entry):
    prof = dict(entry["profile"])
    if entry["plugin"] in ("jax_rs", "clay"):
        prof.setdefault("device", "numpy")
    return ErasureCodePluginRegistry.instance().factory(
        entry["plugin"], "", prof)


@pytest.mark.parametrize("name", sorted(C["entries"]),
                         ids=lambda n: n.replace("/", ":"))
def test_encoding_bit_stable(name):
    entry = C["entries"][name]
    ec = make_impl(entry)
    data = payload()
    encoded = ec.encode(set(range(ec.get_chunk_count())), data)
    assert len(encoded) == len(entry["chunk_sha256"])
    for i_s, want in entry["chunk_sha256"].items():
        chunk = np.ascontiguousarray(encoded[int(i_s)])
        assert chunk.nbytes == entry["chunk_size"]
        got = hashlib.sha256(chunk.tobytes()).hexdigest()
        assert got == want, (
            f"{name} chunk {i_s} changed: encoding is no longer "
            f"bit-compatible with the committed corpus")


@pytest.mark.parametrize("name", sorted(C["entries"]),
                         ids=lambda n: n.replace("/", ":"))
def test_decode_from_corpus_erasures(name):
    entry = C["entries"][name]
    ec = make_impl(entry)
    data = payload()
    n = ec.get_chunk_count()
    k = ec.get_data_chunk_count()
    encoded = ec.encode(set(range(n)), data)
    m = n - k
    # lose the first m chunks (a maximal erasure for MDS codes; shec/lrc
    # validate their own recoverable subsets via minimum_to_decode)
    erased = list(range(m))
    avail = {i: v for i, v in encoded.items() if i not in erased}
    want = {ec.chunk_index(i) for i in range(k)}
    try:
        ec.minimum_to_decode(want, set(avail))
    except IOError:
        pytest.skip(f"{name}: erasure pattern not recoverable (non-MDS)")
    # decode_concat assembles data in logical order through chunk_index,
    # exactly like the reference read path (ErasureCode.cc:345-361)
    got = ec.decode_concat(avail)[:len(data)]
    assert bytes(got) == data
