"""Common runtime services (SURVEY.md §5): typed config + observers, perf
counters, ring log, admin socket, op tracker, and their wiring into the EC
backend.  Mirrors the reference's config/perf behaviors
(src/common/options.cc schema typing, src/common/config.cc observers,
src/common/perf_counters.h avg dumps, src/log/Log.cc recent-ring dump)."""
import io
import json

import numpy as np
import pytest

from ceph_tpu.common import (AdminSocket, ConfigProxy, Context, Log, Option,
                             OpTracker, PerfCountersBuilder,
                             PerfCountersCollection, parse_size, SCHEMA,
                             TYPE_BOOL, TYPE_SIZE, TYPE_UINT)


class TestOptions:
    def test_typed_defaults(self):
        conf = ConfigProxy()
        assert conf.get("osd_pool_default_size") == 3
        assert conf.get("osd_recovery_max_chunk") == 8 << 20
        assert isinstance(conf.get("osd_erasure_code_plugins"), str)

    def test_size_parsing(self):
        assert parse_size("4K") == 4096
        assert parse_size("1m") == 1 << 20
        assert parse_size("2G") == 2 << 30
        assert parse_size(512) == 512
        conf = ConfigProxy()
        conf.set("osd_recovery_max_chunk", "16M")
        assert conf.get("osd_recovery_max_chunk") == 16 << 20

    def test_bounds_and_unknown_rejected(self):
        conf = ConfigProxy()
        with pytest.raises(ValueError):
            conf.set("osd_heartbeat_interval", 0)       # min=1
        with pytest.raises(ValueError):
            conf.set("debug_osd", 99)                   # max=20
        with pytest.raises(KeyError):
            conf.set("no_such_option", 1)
        with pytest.raises(ValueError):
            conf.set("osd_pool_default_size", -1)       # uint

    def test_startup_flag_blocks_runtime_update(self):
        conf = ConfigProxy()
        with pytest.raises(ValueError):
            conf.set("erasure_code_dir", "/elsewhere")
        conf2 = ConfigProxy({"erasure_code_dir": "/plugins"})  # startup ok
        assert conf2.get("erasure_code_dir") == "/plugins"

    def test_observers_fire_on_set(self):
        conf = ConfigProxy()
        seen = []
        conf.add_observer("osd_recovery_max_active",
                          lambda k, v: seen.append((k, v)))
        conf.set("osd_recovery_max_active", 7)
        assert seen == [("osd_recovery_max_active", 7)]

    def test_diff_shows_only_overrides(self):
        conf = ConfigProxy()
        conf.set("debug_ec", 10)
        assert conf.diff() == {"debug_ec": 10}
        assert len(conf.show_config()) == len(SCHEMA)


class TestPerfCounters:
    def build(self):
        return (PerfCountersBuilder("osd")
                .add_u64_counter("ops", "client operations")
                .add_u64("queue_depth")
                .add_time_avg("op_latency")
                .add_u64_avg("batch_size")
                .add_histogram("sizes", [128, 1024, 65536])
                .create_perf_counters())

    def test_counter_and_gauge(self):
        pc = self.build()
        pc.inc("ops")
        pc.inc("ops", 4)
        pc.set("queue_depth", 17)
        d = pc.dump()
        assert d["ops"] == 5 and d["queue_depth"] == 17

    def test_time_avg_dump_shape(self):
        pc = self.build()
        pc.tinc("op_latency", 0.5)
        pc.tinc("op_latency", 1.5)
        d = pc.dump()["op_latency"]
        assert d == {"avgcount": 2, "sum": 2.0, "avgtime": 1.0}

    def test_timer_context(self):
        pc = self.build()
        with pc.time("op_latency"):
            pass
        assert pc.dump()["op_latency"]["avgcount"] == 1

    def test_histogram_buckets(self):
        pc = self.build()
        for v in (64, 512, 4096, 1 << 20):
            pc.hinc("sizes", v)
        b = pc.dump()["sizes"]["buckets"]
        assert b["128"] == 1 and b["1024"] == 1 and b["65536"] == 1
        assert b["inf"] == 1

    def test_collection_dump(self):
        coll = PerfCountersCollection()
        coll.add(self.build())
        out = coll.perf_dump()
        assert "osd" in out and "ops" in out["osd"]


class TestLog:
    def test_gather_levels_gate(self):
        conf = ConfigProxy()
        log = Log(conf)
        log.dout("osd", 1, "kept")
        log.dout("osd", 5, "dropped (debug_osd default 1)")
        assert [e.message for e in log.recent()] == ["kept"]
        conf.set("debug_osd", 10)
        log.dout("osd", 5, "now kept")
        assert len(log.recent()) == 2

    def test_ring_bounded_and_dump(self):
        log = Log(max_recent=3)
        for i in range(10):
            log.dout("ec", 1, f"msg{i}")
        buf = io.StringIO()
        lines = log.dump_recent(file=buf)
        assert len(lines) == 3
        assert "msg9" in lines[-1]
        assert "begin dump of recent" in buf.getvalue()


class TestAdminSocket:
    def test_register_call_json(self):
        sock = AdminSocket()
        sock.register("status", lambda **kw: {"ok": True}, "health")
        assert sock.call("status") == {"ok": True}
        assert json.loads(sock.call_json("status")) == {"ok": True}
        assert "status" in sock.call("help")
        with pytest.raises(ValueError):
            sock.register("status", lambda **kw: None)
        with pytest.raises(KeyError):
            sock.call("nope")


class TestOpTracker:
    def test_lifecycle_and_dumps(self):
        tr = OpTracker()
        op = tr.create_request("write obj1")
        op.mark_event("queued")
        assert tr.dump_ops_in_flight()["num_ops"] == 1
        op.finish()
        assert tr.dump_ops_in_flight()["num_ops"] == 0
        hist = tr.dump_historic_ops()
        assert hist["num_ops"] == 1
        events = [e["event"] for e in hist["ops"][0]["type_data"]["events"]]
        assert events == ["initiated", "queued", "done"]

    def test_context_manager(self):
        tr = OpTracker()
        with tr.create_request("read obj2") as op:
            op.mark_event("dispatched")
        assert tr.dump_ops_in_flight()["num_ops"] == 0

    def test_history_bounded(self):
        tr = OpTracker(history_size=2)
        for i in range(5):
            tr.create_request(f"op{i}").finish()
        assert tr.dump_historic_ops()["num_ops"] == 2


class TestContextAndBackendWiring:
    def test_context_admin_commands(self):
        cct = Context()
        assert "perf dump" in cct.admin_socket.call("help")
        cct.conf.set("debug_ec", 5)
        assert cct.admin_socket.call("config diff") == {"debug_ec": 5}
        cct.admin_socket.call("config set", name="debug_ec", value="7")
        assert cct.conf.get("debug_ec") == 7

    def test_backend_counters_and_optracker(self):
        from ceph_tpu.backend import PGTransaction, make_cluster
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {"k": "4", "m": "2", "device": "numpy",
                           "technique": "reed_sol_van"})
        cct = Context()
        backend, bus = make_cluster(ec, chunk_size=128, cct=cct)
        data = np.arange(4 * 128, dtype=np.uint8).tobytes()
        backend.submit_transaction(PGTransaction().write("o", 0, data))
        bus.deliver_all()
        out = {}
        backend.objects_read_and_reconstruct(
            {"o": [(0, len(data))]},
            lambda result, errors: out.update(result))
        bus.deliver_all()
        dump = cct.perf.perf_dump()["ec_backend.0"]
        assert dump["writes"] == 1
        assert dump["write_bytes"] == len(data)
        assert dump["stripe_bytes_encoded"] == len(data)
        assert dump["reads"] == 1
        assert dump["read_bytes"] == len(data)
        assert dump["encode_time"]["avgcount"] == 1
        # small RMW write: client bytes counted, stripe bytes padded
        backend.submit_transaction(PGTransaction().write("o", 3, b"xy"))
        bus.deliver_all()
        dump = cct.perf.perf_dump()["ec_backend.0"]
        assert dump["write_bytes"] == len(data) + 2
        assert dump["stripe_bytes_encoded"] == \
            len(data) + backend.sinfo.stripe_width
        assert dump["pipeline_depth"] == 0
        # read of a missing object is an error, not a completed read
        out2 = {}
        backend.objects_read_and_reconstruct(
            {"nope": [(0, 16)]},
            lambda result, errors: out2.update(errors=errors))
        bus.deliver_all()
        dump = cct.perf.perf_dump()["ec_backend.0"]
        assert dump["reads"] == 1 and dump["read_errors"] == 1
        hist = backend.op_tracker.dump_historic_ops()
        assert hist["num_ops"] == 2            # full-stripe write + RMW patch
        events = [e["event"]
                  for e in hist["ops"][0]["type_data"]["events"]]
        assert events == ["initiated", "queued_for_pg", "encoded",
                          "commit_sent", "done"]


def test_backend_shutdown_unhooks_context_and_bus():
    """shutdown() must remove every registration the constructor added
    (review regression: leaked closures pinned dead backends)."""
    from ceph_tpu.backend import make_cluster
    from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
    ec = ErasureCodePluginRegistry.instance().factory(
        "jax_rs", "", {"k": "4", "m": "2", "device": "numpy",
                       "technique": "reed_sol_van"})
    cct = Context()
    backend, bus = make_cluster(ec, chunk_size=128, cct=cct)
    assert "dump_ops_in_flight.0" in cct.admin_socket.call("help")
    assert backend.on_shard_down in bus.down_listeners
    backend.shutdown()
    assert "dump_ops_in_flight.0" not in cct.admin_socket.call("help")
    assert "ec_backend.0" not in cct.perf.perf_dump()
    assert backend.on_shard_down not in bus.down_listeners
    assert backend.on_shard_up not in bus.up_listeners


def test_log_timestamp_no_rounding_carry():
    from ceph_tpu.common.log import Entry
    e = Entry(stamp=1000000.9999996, subsys="osd", level=1, message="x")
    # truncation: fraction stays within the same second
    assert ".999999" in e.format()
