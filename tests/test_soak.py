"""Soak: one seeded campaign composing EVERYTHING against a model.

The reference's thrash-erasure-code suites run workloads against a
model-based checker while the Thrasher churns the cluster
(qa/suites/rados/thrash-erasure-code*, src/test/osd/RadosModel.cc).
This campaign goes wider than test_thrash.py: op vectors with xattrs,
pool snapshots (reads at snaps checked against historical model
states), shard kills/revivals, monitor-driven auto-out REMAPS
(backfill), scheduled scrub with injected bitrot, and wire-mode buses
with reorder/dup faults — all interleaved by one seeded RNG, with the
model asserting after every step that acked state is exactly
observable state.

The sweep found (and the fixes closed) real bugs: scrub blindness to
post-overwrite bitrot, clones lost to log repair, recovery laundering
rot into parity, and — via an action-trace shrinker on seed 113 — a
COW of a damage-flagged head copying laundered corruption into a
snapshot clone while the head's wholesale-overwrite exoneration erased
every trace (clones now inherit the damage flag; see
test_snapshots.test_cow_of_damaged_head_marks_clone_damaged for the
13-action chain reduced to its 5 essential beats).
"""
import random

import numpy as np
import pytest

import ceph_tpu.cluster as cluster_mod
from ceph_tpu.backend.memstore import GObject
from ceph_tpu.backend.messages import FaultConfig, MessageBus
from ceph_tpu.cluster import BlockedWriteError, MiniCluster
from ceph_tpu.common import Context
from ceph_tpu.osd.osd_ops import ObjectOperation

K, M = 2, 2
N_OSDS = 12
STEPS = 300


@pytest.mark.parametrize("pool_type", ["ec", "rep"])
@pytest.mark.parametrize("seed", [1, 7, 106, 110, 113, 114, 20260730])
def test_soak_campaign(seed, pool_type):
    rng = random.Random(seed)
    drng = np.random.default_rng(seed)

    def bus_factory():
        bus = MessageBus(wire=True)
        bus.inject_faults(FaultConfig(seed=seed, reorder=True,
                                      dup_prob=0.1))
        return bus
    orig_bus = cluster_mod.MessageBus
    cluster_mod.MessageBus = bus_factory
    try:
        cct = Context(overrides={"mon_osd_down_out_interval": 10_000})
        c = MiniCluster(n_osds=N_OSDS, osds_per_host=3, chunk_size=512,
                        cct=cct)
        if pool_type == "ec":
            pid = c.create_ec_pool("soak", {"k": str(K), "m": str(M),
                                            "device": "numpy"}, pg_num=8)
        else:
            pid = c.create_replicated_pool("soak", size=3, pg_num=8)
        mon = c.attach_monitor()

        oids = [f"obj{i}" for i in range(10)]
        model: dict[str, bytes] = {}
        attrs: dict[str, bytes] = {}
        snaps: dict[int, dict[str, bytes]] = {}   # snapid -> model copy
        snap_no = 0
        # oids with injected bitrot not yet scrub-repaired: reads may
        # legitimately see the rot (the reference doesn't verify
        # checksums on read — only deep scrub catches silent corruption)
        dirty_rot: set[str] = set()
        # (snapid, oid) whose CLONE captured pre-repair rot: a write on a
        # dirty head COWs the rotten state into the snapshot, which reads
        # rotten until scrub repairs the clone — correct semantics, so
        # the model skips those reads until a scrub
        tainted_snaps: set[tuple] = set()
        # (snapid, oid) whose snap view PERMANENTLY diverged from the
        # model: a delete COWs to the newest snap only, so older snaps
        # resolve through a covering clone that may hold later state
        # (the interval clone-covering rule vs exact per-snap history —
        # the documented divergence).  Scrub cannot heal these, so the
        # settle phase must keep skipping them (rot taints, by contrast,
        # clear once repaired/restored).
        diverged_snaps: set[tuple] = set()

        def alive_peers(g):
            return [o for o in g.acting if o not in g.bus.down]

        def check(oid):
            if oid not in model or oid in dirty_rot:
                return
            try:
                got = c.operate(pid, oid, ObjectOperation().read(0, 0)
                                .getxattr("tag"))
            except IOError:
                # unreadable is LEGITIMATE only while the PG is degraded
                # (fewer than k chunks reachable); with everything up a
                # read failure is a real bug
                assert c.pg_group(pid, oid).bus.down, \
                    f"read of {oid} failed on a healthy PG"
                return
            assert got.outdata(0)[:len(model[oid])] == model[oid], oid
            assert got.outdata(1) == attrs[oid]

        for step in range(STEPS):
            action = rng.choices(
                ["write", "read", "snap", "snapread", "kill", "revive",
                 "scrub", "rot", "delete", "omap"],
                weights=[30, 20, 5, 10, 10, 12, 5, 3, 5, 5])[0]
            oid = rng.choice(oids)
            try:
                if action == "write":
                    data = drng.integers(0, 256, rng.randrange(200, 3000),
                                         np.uint8).tobytes()
                    tag = f"s{step}".encode()
                    c.operate(pid, oid, ObjectOperation()
                              .write_full(data).setxattr("tag", tag))
                    if oid in dirty_rot:
                        # the COW (if a newer snap exists) captured the
                        # rotten pre-write state into the clones
                        for sid in snaps:
                            tainted_snaps.add((sid, oid))
                    model[oid] = data
                    attrs[oid] = tag
                    dirty_rot.discard(oid)     # overwritten wholesale
                elif action == "read":
                    check(oid)
                elif action == "snap" and snap_no < 6:
                    snap_no += 1
                    sid = c.create_pool_snap(pid, f"s{snap_no}")
                    snaps[sid] = dict(model)
                elif action == "snapread" and snaps:
                    sid = rng.choice(sorted(snaps))
                    old = snaps[sid]
                    if oid in old and (sid, oid) not in tainted_snaps \
                            and (sid, oid) not in diverged_snaps \
                            and oid not in dirty_rot:
                        # (a dirty head serves snap reads until a COW or
                        # scrub — same visibility rule as plain reads)
                        try:
                            r = c.operate(pid, oid,
                                          ObjectOperation().read(0, 0),
                                          snapid=sid)
                        except IOError:
                            assert c.pg_group(pid, oid).bus.down, \
                                f"snap read of {oid} failed healthy"
                            continue
                        assert r.outdata(0)[:len(old[oid])] == old[oid], \
                            (oid, sid)
                elif action == "kill":
                    g = c.pg_group(pid, oid)
                    peers = [o for o in alive_peers(g)
                             if o != g.backend.whoami]
                    if peers:
                        g.bus.mark_down(rng.choice(peers))
                elif action == "revive":
                    for g in c.pools[pid]["pgs"].values():
                        for o in list(g.bus.down):
                            g.bus.mark_up(o)
                        g.bus.deliver_all()
                elif action == "scrub":
                    # scrub only what is fully up (degraded PGs defer)
                    if not any(g.bus.down
                               for g in c.pools[pid]["pgs"].values()):
                        rep = c.scrub_pool(pid)
                        # DAMAGED objects (inconsistent recovery with too
                        # few spare equations to localise) stay reported
                        # and dirty until an operator-grade overwrite
                        still = {o.split("\x00")[0] for b in rep.values()
                                 for o in b}
                        dirty_rot &= still
                        tainted_snaps = {(sid2, o2) for sid2, o2
                                         in tainted_snaps if o2 in still}
                elif action == "rot" and model:
                    # silent bitrot on a random up non-primary shard.
                    # ONE rot per object between scrubs: multi-chunk rot
                    # is detectable but honestly unlocatable (m parity
                    # equations localise single corruption only), so a
                    # second hit would need operator restore, not scrub
                    candidates = sorted(set(model) - dirty_rot)
                    if not candidates:
                        continue
                    victim_oid = rng.choice(candidates)
                    g = c.pg_group(pid, victim_oid)
                    peers = [o for o in alive_peers(g)
                             if o != g.backend.whoami]
                    if peers:
                        shard = rng.choice(peers)
                        from ceph_tpu.backend.pg_backend import shard_store
                        st = shard_store(g.bus, shard)
                        obj = GObject(victim_oid, shard)
                        if st.exists(obj):
                            st.objects[obj].data[0] ^= 0xFF
                            dirty_rot.add(victim_oid)
                elif action == "delete" and oid in model:
                    c.operate(pid, oid, ObjectOperation().remove())
                    del model[oid]
                    del attrs[oid]
                    # delete COWs to the NEWEST snap only: older snaps'
                    # views resolve through the covering clone, which may
                    # now hold later state than their model copy — the
                    # simplified clone-covering rule diverges from exact
                    # per-snap history here, so the model stops asserting
                    # those reads (documented divergence)
                    for sid in snaps:
                        tainted_snaps.add((sid, oid))
                elif action == "omap" and pool_type == "rep":
                    c.operate(pid, oid, ObjectOperation().omap_set(
                        {f"k{step}": f"v{step}".encode()}))
                    r = c.operate(pid, oid, ObjectOperation()
                                  .omap_get_vals_by_keys([f"k{step}"]))
                    assert r.outdata(0) == {f"k{step}": f"v{step}".encode()}
            except BlockedWriteError:
                # inactive PG: revive everything so the parked op commits,
                # then the model write IS durable
                for g in c.pools[pid]["pgs"].values():
                    for o in list(g.bus.down):
                        g.bus.mark_up(o)
                    g.bus.deliver_all()
                if action == "write":
                    model[oid] = data
                    attrs[oid] = tag
                elif action == "delete":
                    model.pop(oid, None)
                    attrs.pop(oid, None)

        # settle: revive all, repair, then RESTORE any damaged objects
        # from the model (the operator's 'restore from backup' for
        # unlocatable inconsistency), scrub clean, verify EVERY object
        for g in c.pools[pid]["pgs"].values():
            for o in list(g.bus.down):
                g.bus.mark_up(o)
            g.bus.deliver_all()
        rep = c.scrub_pool(pid)
        damaged_heads = {o.split("\x00")[0] for b in rep.values()
                         for o in b}
        for oid2 in sorted(damaged_heads & set(model)):
            c.operate(pid, oid2, ObjectOperation()
                      .write_full(model[oid2]).setxattr("tag", attrs[oid2]))
        # damaged CLONES have no head to rewrite: the operator deletes the
        # broken snapshot copy (accepting loss of that historical view)
        from ceph_tpu.backend.transaction import PGTransaction
        for b in rep.values():
            for oid2 in b:
                if "\x00" in oid2:
                    g2 = c.pg_group(pid, oid2.split("\x00")[0])
                    g2.backend.submit_transaction(
                        PGTransaction().delete(oid2))
                    g2.bus.deliver_all()
                    g2.backend.inconsistent_objects.discard(oid2)
        # snapshots of damaged objects were laundered/restored: their
        # historical checks are void
        for sid2 in list(snaps):
            for oid2 in damaged_heads:
                snaps[sid2].pop(oid2, None)
        dirty_rot.clear()
        tainted_snaps.clear()
        c.scrub_pool(pid)
        assert c.scrub_pool(pid) == {}, "scrub not clean after settle"
        for oid in sorted(model):
            check(oid)
        # snapshots still read their historical state after all the churn
        # (pairs that PERMANENTLY diverged through delete-COW stay out)
        for sid, old in snaps.items():
            for oid, want in old.items():
                if oid not in model and oid not in old:
                    continue
                if (sid, oid) in diverged_snaps:
                    continue
                try:
                    r = c.operate(pid, oid, ObjectOperation().read(0, 0),
                                  snapid=sid)
                    assert r.outdata(0)[:len(want)] == want, (oid, sid)
                except IOError:
                    pass   # head deleted post-snap without COW-able state
        assert c.health()["status"] == "HEALTH_OK"
        c.shutdown()
    finally:
        cluster_mod.MessageBus = orig_bus
