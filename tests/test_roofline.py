"""Device-efficiency observability (ISSUE 8): the roofline ledger,
profiler capture windows, HBM watermarks, and the bench preflight.

Covers the acceptance spine: the per-executable ledger joins
``cost_analysis()`` FLOPs/bytes with measured dispatch seconds into
nonzero achieved-B/s and a bound classification for the k=8,m=4 encode
executable; auto-capture produces exactly one bounded profiler artifact
on an injected WARN transition; the bench preflight aborts with a named
error on platform mismatch.
"""
from __future__ import annotations

import importlib.util
import json
from pathlib import Path

import numpy as np
import pytest

from ceph_tpu.common import Context, roofline
from ceph_tpu.common.profiler_capture import ProfilerCapture

_REPO = Path(__file__).resolve().parent.parent


def _load_tool(name: str):
    path = _REPO / "tools" / f"{name}.py"
    spec = importlib.util.spec_from_file_location(f"{name}_t", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_ledger():
    roofline.reset()
    yield
    roofline.reset()


class FakeProfiler:
    """jax.profiler stand-in: the AST guard keeps the real one out of
    tests; ProfilerCapture's dependency injection keeps them fast."""

    def __init__(self, fail_start=False):
        self.calls: list[tuple] = []
        self.fail_start = fail_start

    def start_trace(self, path):
        if self.fail_start:
            raise RuntimeError("profiler backend down")
        self.calls.append(("start", path))

    def stop_trace(self):
        self.calls.append(("stop",))


class TestPeaks:
    def test_registry_matches_device_kind(self):
        p = roofline.lookup_peaks(device_kind="TPU v5e", platform="tpu")
        assert p["hbm_bytes_s"] == 819e9
        assert p["source"] == "registry:v5e"
        assert p["ridge_flops_per_byte"] == pytest.approx(197e12 / 819e9)

    def test_unknown_tpu_defaults_to_baseline_hardware(self):
        p = roofline.lookup_peaks(device_kind="TPU v99", platform="tpu")
        assert p["source"] == "default-tpu(v5e)"
        assert p["hbm_bytes_s"] == 819e9

    def test_cpu_falls_back_to_nominal(self):
        p = roofline.lookup_peaks(device_kind="cpu", platform="cpu")
        assert p["source"].startswith("nominal-cpu")
        assert p["flops"] > 0 and p["hbm_bytes_s"] > 0

    def test_config_overrides_win(self):
        cct = Context()
        cct.conf.set("device_peak_flops", 1e12)
        cct.conf.set("device_peak_hbm_bytes_per_sec", int(2e11))
        p = roofline.lookup_peaks(cct, device_kind="cpu", platform="cpu")
        assert p["flops"] == 1e12 and p["hbm_bytes_s"] == 2e11
        assert p["source"] == "config"
        assert p["ridge_flops_per_byte"] == pytest.approx(5.0)


class TestLedger:
    def test_join_and_classification(self):
        key = (((4, 8), "uint8"), ((8, 1024), "uint8"))
        # memory-bound synthetic: AI 0.5 well under any ridge
        roofline.record_compile("enc", key, flops_per_call=512.0,
                                bytes_per_call=1024.0)
        roofline.record_call("enc", key, 0.001, synced=True)
        roofline.record_call("enc", key, 0.001)
        snap = roofline.snapshot()
        eid = "enc[4x8:uint8,8x1024:uint8]"
        rec = snap["executables"][eid]
        assert rec["calls"] == 2 and rec["synced_calls"] == 1
        assert rec["flops"] == 1024.0 and rec["bytes"] == 2048.0
        assert rec["achieved_bytes_s"] == pytest.approx(2048.0 / 0.002)
        assert rec["arithmetic_intensity"] == pytest.approx(0.5)
        assert rec["bound"] == "memory"
        peak_b = snap["peaks"]["hbm_bytes_s"]
        assert rec["pct_of_peak"] == pytest.approx(
            100.0 * (2048.0 / 0.002) / peak_b, rel=0.05)

    def test_compute_bound_uses_flops_peak(self):
        key = (((8, 8), "uint8"),)
        # AI 1e6: over any ridge point
        roofline.record_compile("mm", key, flops_per_call=1e9,
                                bytes_per_call=1e3)
        roofline.record_call("mm", key, 0.01, synced=True)
        snap = roofline.snapshot()
        rec = snap["executables"]["mm[8x8:uint8]"]
        assert rec["bound"] == "compute"
        assert rec["pct_of_peak"] == pytest.approx(
            100.0 * (1e9 / 0.01) / snap["peaks"]["flops"], rel=1e-3)

    def test_input_bytes_fallback_when_cost_model_is_empty(self):
        key = (((2, 2), "uint8"),)
        roofline.record_compile("nf", key, 0.0, 0.0, input_bytes=4096)
        roofline.record_call("nf", key, 0.001)
        rec = roofline.snapshot()["executables"]["nf[2x2:uint8]"]
        assert rec["modeled_source"] == "input_shapes"
        assert rec["bytes"] == 4096.0
        assert rec["achieved_bytes_s"] > 0

    def test_async_undercount_extrapolates_from_synced_samples(self):
        """An async backend returns from dispatch before the device
        finishes: the unsynced wall samples under-count and would show
        an impossible >100% of peak.  The estimator detects the gap via
        the synced samples (first dispatches) and extrapolates their
        per-call mean instead."""
        key = (((4, 8), "uint8"),)
        roofline.record_compile("async_enc", key, flops_per_call=1e6,
                                bytes_per_call=1e6)
        roofline.record_call("async_enc", key, 0.010, synced=True)
        for _ in range(9):
            roofline.record_call("async_enc", key, 0.0001)  # early return
        rec = roofline.snapshot()["executables"]["async_enc[4x8:uint8]"]
        assert rec["estimator"] == "synced-extrapolated"
        assert rec["est_seconds"] == pytest.approx(0.010 * 10)
        assert rec["achieved_bytes_s"] == pytest.approx(1e7 / 0.1,
                                                        rel=0.01)
        # a sample set whose synced mean matches stays on the raw clock
        roofline.record_compile("sync_enc", key, 1e6, 1e6)
        roofline.record_call("sync_enc", key, 0.010, synced=True)
        roofline.record_call("sync_enc", key, 0.009)
        rec = roofline.snapshot()["executables"]["sync_enc[4x8:uint8]"]
        assert rec["estimator"] == "measured"
        assert rec["est_seconds"] == pytest.approx(0.019)

    def test_call_without_compile_record_is_dropped(self):
        roofline.record_call("ghost", ("k",), 0.001)
        assert roofline.snapshot()["executables"] == {}

    def test_reset_and_totals(self):
        key = (((2, 2), "uint8"),)
        roofline.record_compile("a", key, 10.0, 100.0)
        roofline.record_call("a", key, 0.001)
        snap = roofline.snapshot()
        assert snap["totals"]["calls"] == 1
        assert snap["totals"]["achieved_bytes_s"] > 0
        roofline.reset()
        assert roofline.snapshot()["totals"]["calls"] == 0

    def test_flat_series_shape(self):
        key = (((2, 2), "uint8"),)
        roofline.record_compile("a", key, 10.0, 100.0)
        roofline.record_call("a", key, 0.001)
        s = roofline.flat_series()
        assert set(s) == {"achieved_flops_s", "achieved_bytes_s",
                          "pct_of_peak", "executables", "device_busy_s"}
        assert s["executables"] == 1.0


class TestTracedJitFeedsLedger:
    """The real join on jax-cpu: the k=8,m=4 encode executable lands in
    the ledger with nonzero achieved-B/s and a bound classification
    (the ISSUE-8 acceptance row, minus the full bench run)."""

    def test_encode_executable_measured(self):
        from ceph_tpu.ops.codec import RSCodec
        codec = RSCodec(8, 4, technique="reed_sol_van", device="jax")
        data = np.random.default_rng(0).integers(
            0, 256, (8, 4096), np.uint8)
        for _ in range(3):
            codec.encode(data)
        snap = roofline.snapshot()
        enc = [rec for eid, rec in snap["executables"].items()
               if "4x8" in eid]             # the [m=4, k=8] parity matrix
        assert enc, f"no k=8,m=4 encode executable: "\
                    f"{list(snap['executables'])}"
        rec = enc[0]
        assert rec["calls"] >= 3
        assert rec["achieved_bytes_s"] > 0
        assert rec["bound"] in ("memory", "compute")
        # a fresh compile sync-times its first dispatch; when an earlier
        # test already compiled this shape, the re-seeded record is all
        # cache hits — either way the clock in use is named
        assert rec["estimator"] in ("measured", "synced-extrapolated")
        assert rec["seconds"] > 0

    def test_admin_command_and_render(self):
        from ceph_tpu.common import default_context
        from ceph_tpu.ops.codec import RSCodec
        codec = RSCodec(4, 2, device="jax")
        data = np.random.default_rng(1).integers(
            0, 256, (4, 2048), np.uint8)
        codec.encode(data)
        top = default_context().admin_socket.call("device roofline")
        assert top["executables"] and "peaks" in top
        text = roofline.render_table(top)
        assert "BOUND" in text and "gf_apply" in text

    def test_prometheus_family(self):
        from ceph_tpu.mgr.prometheus import render
        from ceph_tpu.ops.codec import RSCodec
        codec = RSCodec(4, 2, device="jax")
        data = np.random.default_rng(2).integers(
            0, 256, (4, 2048), np.uint8)
        codec.encode(data)
        text = render(Context())
        lines = text.splitlines()
        assert lines.count(
            "# TYPE ceph_tpu_device_efficiency gauge") == 1
        eff = [line for line in lines
               if line.startswith("ceph_tpu_device_efficiency{")]
        assert any('stat="achieved_bytes_s"' in line for line in eff)
        assert any('stat="pct_of_peak"' in line for line in eff)
        assert any('stat="memory_bound"' in line for line in eff)
        assert all('executable="' in line for line in eff)
        # the aggregate rides the ordinary collection walk
        assert any("ceph_tpu_pct_of_peak_x100{" in line
                   for line in lines)


    def test_prometheus_family_honours_peak_overrides(self):
        """The per-executable family must use the SAME (config-
        overridable) peaks as the aggregate gauges in one scrape —
        render shares one refresh(cct) snapshot across both."""
        from ceph_tpu.mgr.prometheus import render
        key = (((4, 8), "uint8"),)
        roofline.record_compile("ov", key, flops_per_call=10.0,
                                bytes_per_call=1e6)      # memory-bound
        roofline.record_call("ov", key, 0.001, synced=True)  # 1e9 B/s
        cct = Context()
        cct.conf.set("device_peak_hbm_bytes_per_sec", int(2e9))
        text = render(cct)
        line = next(l for l in text.splitlines()
                    if 'executable="ov_4x8_uint8_"' in l
                    and 'stat="pct_of_peak"' in l)
        assert line.endswith(" 50.0")     # 1e9 / 2e9 of the OVERRIDE
        # and the aggregate collection gauge agrees
        assert "ceph_tpu_pct_of_peak_x100{" \
               'collection="device_efficiency"} 5000' in text


class TestRooflineReportTool:
    def test_renders_bench_artifact(self, tmp_path, capsys):
        from ceph_tpu.ops.codec import RSCodec
        codec = RSCodec(8, 4, device="jax")
        data = np.random.default_rng(3).integers(
            0, 256, (8, 4096), np.uint8)
        for _ in range(2):
            codec.encode(data)
        block = roofline.bench_block("cpu")
        art = tmp_path / "art.json"
        art.write_text(json.dumps(
            {"metric": "m", "value": 1.0, "efficiency": block}))
        tool = _load_tool("roofline_report")
        assert tool.main([str(art)]) == 0
        out = capsys.readouterr().out
        row = next(line for line in out.splitlines() if "4x8" in line)
        # nonzero achieved GB/s + a bound classification on the row
        assert row.split()[-1] in ("memory", "compute")
        assert float(row.split()[-4]) > 0            # GB/S column
        assert tool.main([str(art), "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["executables"]

    def test_renders_flight_bundle_and_snapshot(self, tmp_path, capsys):
        key = (((4, 8), "uint8"),)
        roofline.record_compile("enc", key, 100.0, 1000.0)
        roofline.record_call("enc", key, 0.001)
        tool = _load_tool("roofline_report")
        bundle = tmp_path / "flight.json"
        bundle.write_text(json.dumps(
            {"seq": 1, "efficiency": roofline.snapshot()}))
        assert tool.main([str(bundle)]) == 0
        assert "enc[4x8:uint8]" in capsys.readouterr().out
        raw = tmp_path / "snap.json"
        raw.write_text(json.dumps(roofline.snapshot()))
        assert tool.main([str(raw)]) == 0

    def test_rejects_artifact_without_efficiency(self, tmp_path):
        art = tmp_path / "bare.json"
        art.write_text(json.dumps({"metric": "m", "value": 1.0}))
        tool = _load_tool("roofline_report")
        assert tool.main([str(art)]) == 2


class TestProfilerCapture:
    def test_window_start_stop_writes_bounded_artifacts(self, tmp_path):
        fp = FakeProfiler()
        pc = ProfilerCapture(cct=Context(), out_dir=tmp_path,
                             max_captures=2, profiler=fp)
        for i in range(3):
            assert "error" not in pc.start(f"w{i}")
            res = pc.stop()
            assert res["duration_s"] >= 0
            meta = json.loads(
                (Path(res["path"]) / "capture.json").read_text())
            assert meta["reason"] == f"w{i}"
        # bounded: only the newest two survive
        assert len(pc.captures()) == 2
        assert fp.calls.count(("stop",)) == 3

    def test_double_start_and_bare_stop_refused(self, tmp_path):
        pc = ProfilerCapture(cct=Context(), out_dir=tmp_path,
                             profiler=FakeProfiler())
        assert "error" in pc.stop()
        assert "error" not in pc.start("a")
        assert "error" in pc.start("b")        # process-global window
        pc.stop()

    def test_no_out_dir_disables(self):
        pc = ProfilerCapture(cct=Context(), out_dir=None,
                             profiler=FakeProfiler())
        assert "error" in pc.start("x")
        assert pc.auto_capture("WARN") is None

    def test_auto_capture_one_shot_rate_limited(self, tmp_path):
        pc = ProfilerCapture(cct=Context(), out_dir=tmp_path,
                             cooldown_s=300.0, auto_window_s=0.0,
                             profiler=FakeProfiler())
        first = pc.auto_capture("SLOW_OPS")
        assert first is not None and "stopped" in first
        # exactly one artifact; the second transition is inside the
        # cooldown and must not capture
        assert pc.auto_capture("SLOW_OPS") is None
        assert len(pc.captures()) == 1
        assert pc.auto_captures == 1 and pc.auto_skipped == 1

    def test_timed_auto_window_stops_itself(self, tmp_path):
        import time as _time
        fp = FakeProfiler()
        pc = ProfilerCapture(cct=Context(), out_dir=tmp_path,
                             auto_window_s=0.05, profiler=fp)
        info = pc.auto_capture("SLOW_OPS")
        assert info is not None and "stopped" not in info   # still open
        deadline = _time.time() + 2.0
        while pc.status()["active"] is not None and _time.time() < deadline:
            _time.sleep(0.01)
        assert pc.status()["active"] is None
        assert fp.calls.count(("stop",)) == 1
        assert len(pc.captures()) == 1

    def test_manual_stop_cancels_pending_auto_timer(self, tmp_path):
        """A stale auto-stop timer must not fire into a LATER window the
        operator opened (the auto window was already closed by hand)."""
        import time as _time
        fp = FakeProfiler()
        pc = ProfilerCapture(cct=Context(), out_dir=tmp_path,
                             auto_window_s=0.05, cooldown_s=0.0,
                             profiler=fp)
        assert pc.auto_capture("X") is not None
        pc.stop()                                  # close the auto window
        assert "error" not in pc.start("operator")
        _time.sleep(0.15)                          # past the auto window
        assert pc.status()["active"] is not None, \
            "stale auto timer killed the operator's window"
        pc.stop()

    def test_auto_capture_survives_profiler_failure(self, tmp_path):
        pc = ProfilerCapture(cct=Context(), out_dir=tmp_path,
                             profiler=FakeProfiler(fail_start=True))
        assert pc.auto_capture("X") is None
        assert pc.captures() == []
        # the global window latch must be released after the failure
        pc2 = ProfilerCapture(cct=Context(), out_dir=tmp_path,
                              profiler=FakeProfiler())
        assert "error" not in pc2.start("ok")
        pc2.stop()

    def test_admin_commands(self, tmp_path):
        cct = Context()
        pc = ProfilerCapture(cct=cct, out_dir=tmp_path,
                             profiler=FakeProfiler())
        pc.register_admin()
        try:
            assert "error" not in cct.admin_socket.call(
                "device profile start")
            st = cct.admin_socket.call("device profile status")
            assert st["active"] is not None
            res = cct.admin_socket.call("device profile stop")
            assert "path" in res
        finally:
            pc.close()
        assert cct.admin_socket.get("device profile start") is None


class TestClusterIntegration:
    def test_injected_warn_produces_exactly_one_capture(self, tmp_path):
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.mgr.health import CheckResult
        c = MiniCluster(n_osds=4, osds_per_host=2, chunk_size=1024,
                        cct=Context(), data_dir=tmp_path)
        try:
            c.profiler._profiler = FakeProfiler()
            c.health_engine.register("TEST_WARN",
                                     lambda: CheckResult("injected"))
            c.health()
            assert len(c.profiler.captures()) == 1
            # a second, different transition within the cooldown: the
            # flight recorder still dumps, the profiler does not churn
            c.health_engine.register("TEST_WARN2",
                                     lambda: CheckResult("injected2"))
            c.health()
            assert len(c.profiler.captures()) == 1
            # the capture landed under <data_dir>/profiles
            assert (tmp_path / "profiles").is_dir()
        finally:
            c.shutdown()

    def test_efficiency_rides_ts_ring_and_flight_bundle(self, tmp_path):
        from ceph_tpu.cluster import MiniCluster
        key = (((4, 8), "uint8"),)
        roofline.record_compile("enc", key, 100.0, 1000.0)
        roofline.record_call("enc", key, 0.001)
        c = MiniCluster(n_osds=4, osds_per_host=2, chunk_size=1024,
                        cct=Context(), data_dir=tmp_path)
        try:
            c.ts.record(force=True)
            assert "efficiency.achieved_bytes_s" in c.ts.series_names()
            b = c.flight.dump(reason="test", force=True)
            assert b["efficiency"]["executables"]
            assert "HBM_PRESSURE" in c.health_engine.registered()
        finally:
            c.shutdown()


class TestHbmWatermarks:
    def test_hbm_pressure_check_fires_on_high_water(self):
        from ceph_tpu.mgr.health import hbm_pressure_check
        cct = Context()
        marks = {}
        check = hbm_pressure_check(cct, sampler=lambda: marks)
        assert check() is None                    # no devices: silent
        marks["tpu:0"] = {"bytes_in_use": 10, "peak_bytes_in_use": 95,
                          "bytes_limit": 100, "high_water_bytes": 95}
        res = check()
        assert res is not None and res.count == 1
        assert "95/100" in res.detail[0]
        marks["tpu:0"]["high_water_bytes"] = 10   # below the ratio
        assert check() is None

    def test_watermarks_guarded_on_cpu(self):
        """jax-cpu lacks memory_stats: the sampler returns partial (or
        empty) data and refresh() still succeeds — the satellite-2
        contract that telemetry never raises on a bare platform."""
        from ceph_tpu.common import device_telemetry
        marks = device_telemetry.hbm_watermarks()
        assert isinstance(marks, dict)
        for rec in marks.values():
            assert {"bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                    "high_water_bytes"} <= set(rec)
        snap = device_telemetry.refresh(Context())
        assert "watermarks" in snap

    def test_high_water_retained_across_samples(self, monkeypatch):
        from ceph_tpu.common import device_telemetry

        class _Dev:
            platform, id = "faketpu", 0

            def __init__(self):
                self.stats = {"bytes_in_use": 90, "peak_bytes_in_use": 90,
                              "bytes_limit": 100}

            def memory_stats(self):
                return self.stats

        dev = _Dev()
        monkeypatch.setattr(device_telemetry, "memory_stats",
                            lambda initialize=False:
                            {"faketpu:0": dict(dev.stats)})
        device_telemetry._hbm_high_water.pop("faketpu:0", None)
        m1 = device_telemetry.hbm_watermarks()
        assert m1["faketpu:0"]["high_water_bytes"] == 90
        # the backend's own peak resets; the session mark must not
        dev.stats.update(bytes_in_use=5, peak_bytes_in_use=5)
        m2 = device_telemetry.hbm_watermarks()
        assert m2["faketpu:0"]["high_water_bytes"] == 90
        assert m2["faketpu:0"]["high_water_ratio"] == pytest.approx(0.9)
        device_telemetry._hbm_high_water.pop("faketpu:0", None)


class TestBenchPreflight:
    """Satellite 1: the r05 silent-CPU-fallback mode dies at the source."""

    def _bench(self):
        spec = importlib.util.spec_from_file_location(
            "bench_t", _REPO / "bench.py")
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_mismatch_raises_named_error(self, monkeypatch):
        bench = self._bench()
        monkeypatch.setenv("BENCH_EXPECT_PLATFORM", "tpu")
        with pytest.raises(bench.PlatformMismatchError):
            bench.preflight_platform("cpu")
        with pytest.raises(bench.PlatformMismatchError):
            bench.preflight_platform(None)
        bench.preflight_platform("tpu")            # match passes

    def test_jax_platforms_env_is_the_default_request(self, monkeypatch):
        bench = self._bench()
        monkeypatch.delenv("BENCH_EXPECT_PLATFORM", raising=False)
        monkeypatch.setenv("JAX_PLATFORMS", "tpu")
        assert bench.requested_platform() == "tpu"
        with pytest.raises(bench.PlatformMismatchError):
            bench.preflight_platform("cpu")
        # a comma list is jax's own fallback chain: no hard request
        monkeypatch.setenv("JAX_PLATFORMS", "tpu,cpu")
        assert bench.requested_platform() is None
        bench.preflight_platform("cpu")
        monkeypatch.delenv("JAX_PLATFORMS")
        assert bench.requested_platform() is None
