"""Externally-derived bit-compat vectors for the RS codec (VERDICT r3 #5).

The EC corpus (tests/test_ec_corpus.py) pins the codec against its own
earlier output; these tests pin it against sources INDEPENDENT of
ceph_tpu.gf:

1. the published exp/antilog sequence of GF(2^8) mod 0x11D — the standard
   table printed in Reed-Solomon tutorials (QR-code RS references) and
   implied by gf-complete's w=8 default and ISA-L's field choice;
2. hand-derivable scalar identities (shift-reduce longhand shown inline);
3. a from-scratch longhand field implementation LOCAL TO THIS FILE
   (peasant multiplication + brute-force inverse + its own Gauss-Jordan:
   no import from ceph_tpu.gf), used to re-derive every matrix family
   from its published construction:
   - gf_gen_rs_matrix (ISA-L): parity row r col j = (2^r)^j
     (reference: src/erasure-code/isa/ErasureCodeIsa.cc:384-387);
   - gf_gen_cauchy1_matrix (ISA-L): absolute row i, col j = inv(i ^ j);
   - reed_sol_vandermonde_coding_matrix (jerasure / Plank & Ding 2003):
     systematic extended Vandermonde;
4. frozen literal encode vectors computed from (3) alone: one per
   technique, hex-embedded, so a regression in EITHER implementation —
   tables, matrix build, or kernel — breaks the match.

One wrong constant in gf/tables.py or gf/matrix.py now fails here even if
the codec stays self-consistent.
"""
import numpy as np
import pytest

from ceph_tpu.gf import matrix as gfm
from ceph_tpu.gf import ref as gfref
from ceph_tpu.gf.tables import EXP_TABLE, GF_POLY, MUL_TABLE, gf_inv, gf_mul
from ceph_tpu.ops.codec import RSCodec

# -- 1. the published antilog sequence ---------------------------------------
# First 36 powers of the generator alpha=2 in GF(2^8)/0x11D, exactly as
# printed in published RS-code log/antilog tables.  Each step is
# "shift left; if bit 8 set, XOR 0x11D" — e.g. 128<<1=0x100 -> ^0x11D = 29.
PUBLISHED_EXP = [
    1, 2, 4, 8, 16, 32, 64, 128, 29, 58, 116, 232, 205, 135, 19, 38,
    76, 152, 45, 90, 180, 117, 234, 201, 143, 3, 6, 12, 24, 48, 96, 192,
    157, 39, 78, 156,
]


def test_exp_table_matches_published_sequence():
    assert list(EXP_TABLE[:36]) == PUBLISHED_EXP


def test_known_scalar_identities():
    # 0x8E<<1 = 0x11C; 0x11C ^ 0x11D = 1  =>  2 * 0x8E = 1, inv(2) = 0x8E
    assert gf_mul(2, 0x8E) == 1
    assert gf_inv(2) == 0x8E
    # 0x80<<1 = 0x100; ^0x11D = 0x1D  =>  2 * 0x80 = 0x1D
    assert gf_mul(2, 0x80) == 0x1D
    # Fermat: a^255 = 1 for every nonzero a (field order 256)
    for a in (1, 2, 3, 0x53, 0xCA, 0xFF):
        p = 1
        for _ in range(255):
            p = gf_mul(p, a)
        assert p == 1, f"{a}^255 != 1"


# -- 3. the independent longhand field ----------------------------------------

def longhand_mul(a: int, b: int) -> int:
    """Peasant multiplication with 0x11D reduction — no tables."""
    r = 0
    while b:
        if b & 1:
            r ^= a
        a <<= 1
        if a & 0x100:
            a ^= 0x11D
        b >>= 1
    return r


def longhand_inv(a: int) -> int:
    for x in range(1, 256):
        if longhand_mul(a, x) == 1:
            return x
    raise ZeroDivisionError(a)


def longhand_matmul(A, B):
    n, k = len(A), len(B[0])
    out = [[0] * k for _ in range(n)]
    for i in range(n):
        for j in range(k):
            acc = 0
            for t in range(len(B)):
                acc ^= longhand_mul(A[i][t], B[t][j])
            out[i][j] = acc
    return out


def longhand_invert(M):
    """Gauss-Jordan over the longhand field, independent of gfm.gf_invert."""
    n = len(M)
    aug = [list(row) + [1 if i == j else 0 for j in range(n)]
           for i, row in enumerate(M)]
    for col in range(n):
        piv = next(r for r in range(col, n) if aug[r][col])
        aug[col], aug[piv] = aug[piv], aug[col]
        inv_p = longhand_inv(aug[col][col])
        aug[col] = [longhand_mul(v, inv_p) for v in aug[col]]
        for r in range(n):
            if r != col and aug[r][col]:
                f = aug[r][col]
                aug[r] = [v ^ longhand_mul(f, w)
                          for v, w in zip(aug[r], aug[col])]
    return [row[n:] for row in aug]


def test_mul_table_vs_longhand():
    rng = np.random.default_rng(0x11D)
    pairs = rng.integers(0, 256, size=(2000, 2))
    for a, b in pairs:
        assert MUL_TABLE[a, b] == longhand_mul(int(a), int(b))
    for b in range(256):          # full rows for the generators
        assert MUL_TABLE[2, b] == longhand_mul(2, b)
        assert MUL_TABLE[3, b] == longhand_mul(3, b)


# -- published matrix constructions, re-derived longhand ----------------------

def longhand_rs_matrix_isa(k: int, m: int):
    """gf_gen_rs_matrix (ISA-L): parity row r = geometric row of gen=2^r."""
    parity = []
    gen = 1
    for _ in range(m):
        p, row = 1, []
        for _ in range(k):
            row.append(p)
            p = longhand_mul(p, gen)
        parity.append(row)
        gen = longhand_mul(gen, 2)
    return parity


def longhand_cauchy1(k: int, m: int):
    """gf_gen_cauchy1_matrix (ISA-L): absolute row i, col j = inv(i ^ j)."""
    return [[longhand_inv((k + i) ^ j) for j in range(k)] for i in range(m)]


def longhand_jerasure_vandermonde(k: int, m: int):
    """Plank & Ding 2003 systematic EXTENDED Vandermonde: natural rows
    V[i, j] = i^j plus the extension row e_{k-1} last; systematize
    (parity = V_bottom @ inv(V_top)); then divide every column by the
    first coding row's entry (and rescale data rows to restore the
    identity) so the first parity row is all ones — the construction
    jerasure's reed_sol_vandermonde_coding_matrix publishes."""
    rows = k + m
    V = []
    for i in range(rows - 1):
        row, p = [], 1
        for _ in range(k):
            row.append(p)
            p = longhand_mul(p, i)
        V.append(row)
    V.append([0] * (k - 1) + [1])        # extension row e_{k-1}
    top_inv = longhand_invert(V[:k])
    parity = longhand_matmul(V[k:], top_inv)
    for j in range(k):
        s = longhand_inv(parity[0][j])
        for r in range(m):
            parity[r][j] = longhand_mul(parity[r][j], s)
    # reed_sol.c's final step: scale coding rows 1..m-1 so the first
    # column of the parity block is all ones as well
    for r in range(1, m):
        s = longhand_inv(parity[r][0])
        parity[r] = [longhand_mul(v, s) for v in parity[r]]
    return parity


@pytest.mark.parametrize("k,m", [(4, 2), (8, 4), (10, 4), (3, 3)])
def test_vandermonde_isa_matches_published_construction(k, m):
    assert gfm.rs_vandermonde_isa(k, m).tolist() == \
        longhand_rs_matrix_isa(k, m)


@pytest.mark.parametrize("k,m", [(4, 2), (8, 4), (6, 3), (12, 4)])
def test_cauchy1_matches_published_construction(k, m):
    assert gfm.cauchy1(k, m).tolist() == longhand_cauchy1(k, m)


@pytest.mark.parametrize("k,m", [(4, 2), (7, 3), (8, 4)])
def test_jerasure_vandermonde_matches_published_construction(k, m):
    assert gfm.rs_vandermonde_jerasure(k, m).tolist() == \
        longhand_jerasure_vandermonde(k, m)


def test_jerasure_first_parity_row_is_xor():
    """Published jerasure behavior: the first coding row of
    reed_sol_vandermonde_coding_matrix is all ones (plain XOR parity),
    and after the final row scaling so is the first COLUMN."""
    for k, m in ((4, 2), (7, 3), (8, 4), (10, 4)):
        P = gfm.rs_vandermonde_jerasure(k, m)
        assert all(v == 1 for v in P[0])
        assert all(int(row[0]) == 1 for row in P)


# -- 4. frozen literal encode vectors -----------------------------------------
# Input: bytes 0..31 as k=4 chunks of 8 bytes.  Expected parity computed by
# the longhand field ONLY (verified at generation time), then frozen.

FIXED_INPUT = np.frombuffer(
    bytes.fromhex("5bb1f83a9c07d2e4416fc9258ad0137e"
                  "f462b89d03e7541cca2f6b90d8a3e517"),
    dtype=np.uint8).reshape(4, 8).copy()

FROZEN_PARITY = {
    # technique: hex of the [m=2, 8] parity block (generated by the
    # longhand implementation above and frozen; the test re-derives it)
    "vandermonde": "2493e212cd937091309fd2ca1770c2d0",
    "cauchy": "390122a8fa53494b5c962a6e77f9bf29",
    "reed_sol_van": "2493e212cd937091d02ba3f0b4641547",
}


def _longhand_parity(technique):
    build = {"vandermonde": longhand_rs_matrix_isa,
             "cauchy": longhand_cauchy1,
             "reed_sol_van": longhand_jerasure_vandermonde}[technique]
    P = build(4, 2)
    return bytes(bytearray(
        v for row in longhand_matmul(P, FIXED_INPUT.tolist()) for v in row))


@pytest.mark.parametrize("technique", ["vandermonde", "cauchy",
                                       "reed_sol_van"])
def test_codec_reproduces_frozen_vectors(technique):
    codec = RSCodec(4, 2, technique=technique, device="numpy")
    parity = codec.encode(FIXED_INPUT)
    got = parity.tobytes().hex()
    assert got == FROZEN_PARITY[technique], \
        f"{technique}: codec output diverged from the frozen vector"
    # and the frozen vector itself must match the longhand derivation —
    # proving it is externally pinned, not a copy of the codec's output
    assert _longhand_parity(technique).hex() == FROZEN_PARITY[technique]


def test_decode_roundtrip_against_longhand():
    """Erase two chunks; the codec's reconstruction must equal the
    longhand solve of the same linear system."""
    codec = RSCodec(4, 2, technique="cauchy", device="numpy")
    parity = codec.encode(FIXED_INPUT)
    rec = codec.decode({1: FIXED_INPUT[1], 2: FIXED_INPUT[2],
                        3: FIXED_INPUT[3], 4: parity[0]}, erasures=[0, 5])
    # longhand: data0 from rows {1,2,3,parity0} of the generator
    P = longhand_cauchy1(4, 2)
    G = [[1 if i == j else 0 for j in range(4)] for i in range(4)] + P
    sub = [G[i] for i in (1, 2, 3, 4)]
    inv = longhand_invert(sub)
    chunks = [FIXED_INPUT[1].tolist(), FIXED_INPUT[2].tolist(),
              FIXED_INPUT[3].tolist(), list(parity[0])]
    data0 = longhand_matmul([inv[0]], chunks)[0]
    assert list(rec[0]) == data0
    parity1 = longhand_matmul([P[1]], FIXED_INPUT.tolist())[0]
    assert list(rec[5]) == parity1
