"""Multi-chip sharded codec steps over the virtual 8-device CPU mesh
(the driver's dryrun_multichip validates the same paths; SURVEY.md §5
distributed communication backend -> pjit/shard_map collectives)."""
import numpy as np
import pytest

from ceph_tpu.gf import cauchy1, decode_matrix, ref
from ceph_tpu.parallel.mesh import (make_mesh, sharded_decode_step,
                                    sharded_encode_step)

K, M = 8, 4


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(8)


def test_mesh_shape(mesh):
    assert mesh.shape["dp"] * mesh.shape["sp"] == 8


def test_sharded_encode_matches_host(mesh):
    pm = cauchy1(K, M)
    step = sharded_encode_step(mesh, pm)
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(2 * dp, K, 128 * sp), dtype=np.uint8)
    parity, checksum, rotated = step(data)
    assert parity.shape == (2 * dp, M, 128 * sp)
    for b in range(data.shape[0]):
        want = ref.encode(pm, data[b])
        np.testing.assert_array_equal(np.asarray(parity[b]), want)
    # the dp-ring rotation moved batch blocks by one dp step
    blk = data.shape[0] // dp
    np.testing.assert_array_equal(
        np.asarray(rotated[blk:2 * blk]), np.asarray(parity[:blk]))


def test_sharded_decode_reconstructs(mesh):
    """Chunk-parallel reconstruction: survivors sharded over dp, partial
    GF products psum'd (XOR over bit-planes) into the rebuilt chunks."""
    pm = cauchy1(K, M)
    dp, sp = mesh.shape["dp"], mesh.shape["sp"]
    rng = np.random.default_rng(1)
    N = 256 * sp
    data = rng.integers(0, 256, size=(K, N), dtype=np.uint8)
    parity = ref.encode(pm, data)
    full = np.concatenate([data, parity], axis=0)

    erasures = [0, 9]
    D, src = decode_matrix(pm, erasures)
    step = sharded_decode_step(mesh)      # pads survivors internally
    rec = np.asarray(step(D, full[src]))
    np.testing.assert_array_equal(rec[0], full[0])
    np.testing.assert_array_equal(rec[1], full[9])


def test_decode_rejects_mismatched_shapes(mesh):
    step = sharded_decode_step(mesh)
    with pytest.raises(ValueError):
        step(np.zeros((2, 5), dtype=np.uint8),
             np.zeros((6, 128 * mesh.shape["sp"]), dtype=np.uint8))


def test_sharded_placement_step():
    """Distributed ParallelPGMapper: seeds shard over dp, the per-OSD
    histogram psums over the ring, outputs bit-match the scalar host
    interpreter."""
    import numpy as np
    from ceph_tpu.crush import (CRUSH_BUCKET_STRAW2,
                                CRUSH_RULE_CHOOSELEAF_FIRSTN,
                                CRUSH_RULE_EMIT, CRUSH_RULE_TAKE, CrushMap)
    from ceph_tpu.crush.jax_mapper import BulkMapper
    from ceph_tpu.crush.mapper import crush_do_rule
    from ceph_tpu.parallel.mesh import make_mesh, sharded_placement_step

    cmap = CrushMap()
    cmap.set_type_name(1, "host")
    hosts = [cmap.add_bucket(CRUSH_BUCKET_STRAW2, 1, [2 * h, 2 * h + 1],
                             [0x10000, 0x10000]) for h in range(4)]
    root = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 2, hosts, [0x20000] * 4)
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                            (CRUSH_RULE_CHOOSELEAF_FIRSTN, 3, 1),
                            (CRUSH_RULE_EMIT, 0, 0)])
    cmap.finalize()
    mesh = make_mesh(8)
    dp = mesh.shape["dp"]
    pstep = sharded_placement_step(mesh, BulkMapper(cmap), ruleno, 8)
    xs = np.arange(8 * dp, dtype=np.uint32)
    out, hist = map(np.asarray, pstep(xs))
    for x in range(0, len(xs), 7):
        np.testing.assert_array_equal(out[x],
                                      crush_do_rule(cmap, ruleno, x, 3))
    np.testing.assert_array_equal(
        hist, np.bincount(out[out >= 0].ravel(), minlength=8))


def test_sharded_placement_masks_holes():
    """Placement holes (CRUSH_ITEM_NONE) must not corrupt the histogram
    (regression: the positive sentinel passed the valid mask)."""
    import numpy as np
    from ceph_tpu.crush import (CRUSH_BUCKET_STRAW2,
                                CRUSH_RULE_CHOOSELEAF_INDEP,
                                CRUSH_RULE_EMIT, CRUSH_RULE_TAKE, CrushMap)
    from ceph_tpu.crush.jax_mapper import BulkMapper
    from ceph_tpu.parallel.mesh import make_mesh, sharded_placement_step

    # ask INDEP for 3 leaves from only 2 hosts: position 3 stays a hole
    cmap = CrushMap()
    cmap.set_type_name(1, "host")
    hosts = [cmap.add_bucket(CRUSH_BUCKET_STRAW2, 1, [2 * h, 2 * h + 1],
                             [0x10000, 0x10000]) for h in range(2)]
    root = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 2, hosts, [0x20000] * 2)
    ruleno = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                            (CRUSH_RULE_CHOOSELEAF_INDEP, 3, 1),
                            (CRUSH_RULE_EMIT, 0, 0)])
    cmap.finalize()
    mesh = make_mesh(8)
    pstep = sharded_placement_step(mesh, BulkMapper(cmap), ruleno, 4)
    xs = np.arange(8 * mesh.shape["dp"], dtype=np.uint32)
    out, hist = map(np.asarray, pstep(xs))
    assert (out == 0x7FFFFFFF).any()          # holes really occurred
    valid = out[(out >= 0) & (out != 0x7FFFFFFF)]
    np.testing.assert_array_equal(hist, np.bincount(valid, minlength=4))
