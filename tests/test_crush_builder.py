"""CRUSH map surgery: insert/remove/move/reweight operations.

Mirrors the builder API surface of the reference (reference:
src/crush/builder.c crush_bucket_add_item/remove_item/adjust_item_weight/
crush_reweight; src/crush/CrushWrapper.{h,cc} insert_item/remove_item/
move_bucket/adjust_item_weight/adjust_subtree_weight — the map-mutation
half the r3 VERDICT called out as missing from the builder).
Every operation must keep ancestor weights consistent and placements
valid through the real mapping chain.
"""
import numpy as np
import pytest

from ceph_tpu.crush import (CRUSH_BUCKET_STRAW2, CRUSH_RULE_CHOOSELEAF_INDEP,
                            CRUSH_RULE_EMIT, CRUSH_RULE_TAKE, CrushMap,
                            crush_do_rule)


def three_host_map():
    m = CrushMap()
    m.set_type_name(1, "host")
    m.set_type_name(2, "root")
    hosts = []
    for h in range(3):
        items = [h * 3, h * 3 + 1, h * 3 + 2]
        b = m.add_bucket(CRUSH_BUCKET_STRAW2, 1, items, [0x10000] * 3)
        m.set_item_name(b, f"host{h}")
        hosts.append(b)
    root = m.add_bucket(CRUSH_BUCKET_STRAW2, 2, hosts, [0x30000] * 3)
    m.set_item_name(root, "default")
    m.finalize()
    return m, hosts, root


def subtree_sum(m, bid):
    return sum(m.buckets[bid].item_weights)


class TestInsertRemove:
    def test_insert_device_propagates_weight(self):
        m, hosts, root = three_host_map()
        m.insert_item(9, 0x20000, hosts[0])
        assert m.buckets[hosts[0]].items[-1] == 9
        assert m.buckets[hosts[0]].weight == 0x50000
        # the root's entry for host0 followed
        idx = m.buckets[root].items.index(hosts[0])
        assert m.buckets[root].item_weights[idx] == 0x50000
        assert m.buckets[root].weight == 0xB0000
        assert m.max_devices == 10

    def test_remove_device_propagates_weight(self):
        m, hosts, root = three_host_map()
        m.remove_item(4)
        assert 4 not in m.buckets[hosts[1]].items
        assert m.buckets[hosts[1]].weight == 0x20000
        assert m.buckets[root].weight == 0x80000

    def test_remove_nonempty_bucket_refused(self):
        m, hosts, _ = three_host_map()
        with pytest.raises(ValueError, match="not empty"):
            m.remove_item(hosts[0])

    def test_remove_emptied_bucket(self):
        m, hosts, root = three_host_map()
        for d in (0, 1, 2):
            m.remove_item(d)
        m.remove_item(hosts[0])
        assert hosts[0] not in m.buckets
        assert hosts[0] not in m.buckets[root].items
        assert m.buckets[root].weight == 0x60000


class TestMoveBucket:
    def test_move_host_to_new_rack(self):
        m, hosts, root = three_host_map()
        m.set_type_name(3, "rack")
        rack = m.add_bucket(CRUSH_BUCKET_STRAW2, 3, [], [])
        m.set_item_name(rack, "rack0")
        m.insert_item(rack, 0, root)
        m.move_bucket(hosts[0], rack)
        assert hosts[0] in m.buckets[rack].items
        assert hosts[0] not in m.buckets[root].items
        assert m.buckets[rack].weight == 0x30000
        # total cluster weight unchanged
        assert m.buckets[root].weight == 0x90000

    def test_move_cycle_refused(self):
        m, hosts, root = three_host_map()
        with pytest.raises(ValueError, match="cycle"):
            m.move_bucket(root, hosts[0])


class TestReweight:
    def test_adjust_item_weight(self):
        m, hosts, root = three_host_map()
        m.adjust_item_weight(0, 0x8000)
        assert m.buckets[hosts[0]].item_weights[0] == 0x8000
        assert m.buckets[hosts[0]].weight == 0x28000
        assert m.buckets[root].weight == 0x88000

    def test_adjust_subtree_weight(self):
        m, hosts, root = three_host_map()
        changed = m.adjust_subtree_weight(root, 0x8000)
        assert changed == 9
        for h in hosts:
            assert m.buckets[h].item_weights == [0x8000] * 3
            assert m.buckets[h].weight == 0x18000
        assert m.buckets[root].weight == 0x48000

    def test_reweight_rebuilds_from_leaves(self):
        m, hosts, root = three_host_map()
        # corrupt the aggregates, then rebuild (crush_reweight)
        m.buckets[hosts[0]].weight = 0
        m.buckets[root].item_weights[0] = 0
        m.buckets[root].weight = 7
        m.reweight()
        assert m.buckets[hosts[0]].weight == 0x30000
        assert m.buckets[root].weight == 0x90000


class TestPlacementAfterSurgery:
    def test_placements_valid_after_mutations(self):
        m, hosts, root = three_host_map()
        ruleno = m.add_rule([(CRUSH_RULE_TAKE, root, 0),
                             (CRUSH_RULE_CHOOSELEAF_INDEP, 3, 1),
                             (CRUSH_RULE_EMIT, 0, 0)])
        m.insert_item(9, 0x10000, hosts[2])
        m.remove_item(1)
        m.adjust_item_weight(5, 0x4000)
        m.finalize()
        devices = {i for b in m.buckets.values() for i in b.items if i >= 0}
        for x in range(64):
            out = crush_do_rule(m, ruleno, x, 3)
            real = [o for o in out if o != 0x7FFFFFFF]
            assert all(o in devices for o in real), f"x={x}: {out}"
            assert 1 not in real, "removed device still placed"
        # a zero-weighted subtree never receives placements
        m.adjust_subtree_weight(hosts[0], 0)
        for x in range(64):
            out = crush_do_rule(m, ruleno, x, 3)
            assert all(o not in (0, 2) for o in out
                       if o != 0x7FFFFFFF), "zeroed subtree placed"

    def test_surgery_round_trips_through_text(self):
        from ceph_tpu.crush import compile_crushmap, decompile
        m, hosts, root = three_host_map()
        m.insert_item(9, 0x18000, hosts[0])
        m.adjust_item_weight(9, 0x8000)
        m.finalize()
        m2 = compile_crushmap(decompile(m))
        ruleno = m.add_rule([(CRUSH_RULE_TAKE, root, 0),
                             (CRUSH_RULE_CHOOSELEAF_INDEP, 3, 1),
                             (CRUSH_RULE_EMIT, 0, 0)])
        m2.add_rule([(CRUSH_RULE_TAKE, root, 0),
                     (CRUSH_RULE_CHOOSELEAF_INDEP, 3, 1),
                     (CRUSH_RULE_EMIT, 0, 0)])
        for x in range(32):
            assert crush_do_rule(m, ruleno, x, 3) == \
                crush_do_rule(m2, ruleno, x, 3)


class TestStrawV1Construction:
    """crush_calc_straw parity (builder.c:427): straw(v1) buckets BUILT
    here must carry the same straw lengths — and place identically — as
    the reference-built bucket in the golden dump (closes the r4
    'straw maps load-only' partial)."""

    def _golden_straw(self):
        import json
        import pathlib
        d = json.loads((pathlib.Path(__file__).parent / "golden" /
                        "crush_golden.json").read_text())
        for g in d["groups"]:
            for run in g.get("runs", []):
                if run["name"] == "alg_straw":
                    return g["map"], run
        raise AssertionError("alg_straw group missing from golden dump")

    def test_straws_match_reference_builder(self):
        from ceph_tpu.crush.map import CRUSH_BUCKET_STRAW
        gmap, _run = self._golden_straw()
        gb = next(b for b in gmap["buckets"]
                  if b["alg"] == CRUSH_BUCKET_STRAW)
        # crush_create() starts at straw_calc_version=0 (builder.c:1506)
        m = CrushMap(tunables=dict(gmap["tunables"],
                                   straw_calc_version=0))
        bid = m.add_bucket(CRUSH_BUCKET_STRAW, gb["type"],
                           list(gb["items"]), list(gb["item_weights"]))
        built = m.buckets[bid]
        assert built.straws == list(gb["straws"])
        assert built.weight == gb["weight"]
        # v1 agrees on all-distinct weights (the golden case)
        m1 = CrushMap(tunables=dict(gmap["tunables"],
                                    straw_calc_version=1))
        bid1 = m1.add_bucket(CRUSH_BUCKET_STRAW, gb["type"],
                             list(gb["items"]), list(gb["item_weights"]))
        assert m1.buckets[bid1].straws == built.straws

    def test_built_straw_map_places_like_golden(self):
        from ceph_tpu.crush.map import CRUSH_BUCKET_STRAW
        from ceph_tpu.crush import CRUSH_RULE_CHOOSE_FIRSTN
        gmap, run = self._golden_straw()
        gb = next(b for b in gmap["buckets"]
                  if b["alg"] == CRUSH_BUCKET_STRAW)
        m = CrushMap(tunables=dict(gmap["tunables"],
                                   straw_calc_version=0))
        root = m.add_bucket(CRUSH_BUCKET_STRAW, gb["type"],
                            list(gb["items"]), list(gb["item_weights"]))
        ruleno = m.add_rule([(CRUSH_RULE_TAKE, root, 0),
                             (CRUSH_RULE_CHOOSE_FIRSTN, 3, 0),
                             (CRUSH_RULE_EMIT, 0, 0)])
        m.finalize()
        for x, want in enumerate(run["results"]):   # x = 0..NX-1
            got = crush_do_rule(m, ruleno, x, run["result_max"],
                                weights=list(run["weights"]))
            assert got == want, (x, got, want)

    def test_straw_bucket_mutable(self):
        """Surgery recomputes straws (the old code refused to mutate)."""
        from ceph_tpu.crush.map import CRUSH_BUCKET_STRAW
        m = CrushMap(tunables={"straw_calc_version": 1})
        m.set_type_name(1, "host")
        bid = m.add_bucket(CRUSH_BUCKET_STRAW, 1, [0, 1, 2],
                           [0x10000, 0x20000, 0x30000])
        before = list(m.buckets[bid].straws)
        m.insert_item(3, 0x18000, bid)
        after = m.buckets[bid].straws
        assert len(after) == 4 and after != before
        # straws for a rebuilt identical set are reproducible
        m2 = CrushMap(tunables={"straw_calc_version": 1})
        m2.set_type_name(1, "host")
        b2 = m2.add_bucket(CRUSH_BUCKET_STRAW, 1, [0, 1, 2, 3],
                           [0x10000, 0x20000, 0x30000, 0x18000])
        assert m2.buckets[b2].straws == after

    def test_v0_dump_with_repeated_weights_round_trips_text(self):
        """A reference-style dump (straws computed at v0, tunable absent)
        with REPEATED weights must round-trip through text: decompile
        detects the version that reproduces the stored straws and pins
        it as a tunable (regression: recompile silently rebuilt straws
        at v1, diverging placements)."""
        from ceph_tpu.crush import compile_crushmap, decompile
        from ceph_tpu.crush.map import CRUSH_BUCKET_STRAW, calc_straw_lengths
        weights = [0x10000, 0x10000, 0x30000, 0x20000, 0x20000]
        assert calc_straw_lengths(weights, 0) != \
            calc_straw_lengths(weights, 1)     # the split really shows
        m0 = CrushMap(tunables={"straw_calc_version": 0})
        m0.set_type_name(1, "host")
        bid = m0.add_bucket(CRUSH_BUCKET_STRAW, 1, [0, 1, 2, 3, 4],
                            weights)
        m0.set_item_name(bid, "r")
        m0.finalize()
        # simulate a loaded reference dump: straws as data, no tunable
        d = m0.to_dict()
        d["tunables"].pop("straw_calc_version", None)
        loaded = CrushMap.from_dict(d)
        m2 = compile_crushmap(decompile(loaded))
        assert m2.buckets[bid].straws == m0.buckets[bid].straws

    def test_corrupt_straws_refuse_text(self):
        """Straws matching NO calc version must refuse decompile rather
        than silently re-derive different placements."""
        from ceph_tpu.crush import decompile
        from ceph_tpu.crush.map import CRUSH_BUCKET_STRAW
        m = CrushMap(tunables={"straw_calc_version": 1})
        m.set_type_name(1, "host")
        bid = m.add_bucket(CRUSH_BUCKET_STRAW, 1, [0, 1, 2],
                           [0x10000, 0x20000, 0x30000])
        m.set_item_name(bid, "r")
        m.buckets[bid].straws[1] ^= 0x5555     # corrupt
        d = m.to_dict()
        d["tunables"].pop("straw_calc_version")
        with pytest.raises(ValueError, match="straw_calc_version"):
            decompile(CrushMap.from_dict(d))
