"""Fixture: host syncs reachable under jit — one directly in the
jitted body, one through a helper call; plus a donated buffer read
after the dispatch."""
import functools

import jax


@jax.jit
def direct_sync(x):
    y = x + 1
    host = jax.device_get(y)
    return y, host


def _helper(y):
    return y.block_until_ready()


@jax.jit
def transitive_sync(x):
    return _helper(x * 2)


@functools.partial(jax.jit, donate_argnums=(0,))
def consume(buf):
    return buf * 2


def reuse_after_donation(buf):
    out = consume(buf)
    return out, buf
