"""Fixture: a seeded A->B / B->A lock-order cycle.

``Alpha.cross`` takes Alpha._lock then (via the beta attribute's
typed method) Beta._lock; ``Beta.cross`` takes Beta._lock then (via
the unique-name fallback on ``ping``) Alpha._lock.  The lock graph
has the 2-cycle the detector must find.
"""
import threading


class Beta:
    def __init__(self):
        self._lock = threading.Lock()
        self.alpha = None

    def poke(self):
        with self._lock:
            return 1

    def cross(self):
        with self._lock:
            self.alpha.ping()


class Alpha:
    def __init__(self):
        self._lock = threading.Lock()
        self.beta = Beta()

    def ping(self):
        with self._lock:
            return 2

    def cross(self):
        with self._lock:
            self.beta.poke()
