"""Clean twin of ``cross_thread_bad``: both writers take ``_lock``
around the mutation, so the contexts share a common lock."""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        for _ in range(100):
            with self._lock:
                self.count += 1

    def bump(self):
        with self._lock:
            self.count += 1
