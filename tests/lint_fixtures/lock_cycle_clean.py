"""Clean twin of ``lock_cycle_bad``: both cross-class paths take the
locks in the same order (Alpha._lock before Beta._lock), so the lock
graph is acyclic."""
import threading


class Beta:
    def __init__(self):
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            return 1


class Alpha:
    def __init__(self):
        self._lock = threading.Lock()
        self.beta = Beta()

    def cross(self):
        with self._lock:
            self.beta.poke()

    def also_cross(self):
        with self._lock:
            self.beta.poke()
