"""Clean twin of ``jit_sync_bad``: pure jitted bodies, the sync lives
outside the trace, and the donated name is rebound by the call."""
import functools

import jax


@jax.jit
def pure(x):
    return x + 1


def sync_outside(x):
    y = pure(x)
    return jax.device_get(y)


@functools.partial(jax.jit, donate_argnums=(0,))
def consume(buf):
    return buf * 2


def rebind_after_donation(buf):
    buf = consume(buf)
    return buf
