"""Fixture: a known cross-thread unlocked mutation.  ``Worker.count``
is written by the spawned worker thread (``_loop``) and by public
callers (``bump``), and neither write holds ``_lock``."""
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        for _ in range(100):
            self.count += 1

    def bump(self):
        self.count += 1
