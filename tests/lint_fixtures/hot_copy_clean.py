"""Fixture twin: id-sized copies and boundary functions stay silent."""


def route(name, shard):
    key = bytes(name)                      # object id, not payload-ish
    return key, shard


def client_handshake(payload_view):
    return bytes(payload_view)             # allowlisted boundary


def read_auth_frame(data):
    return data.tobytes()                  # allowlisted boundary
