"""Fixture: three payload-sized copy shapes on the data path."""
import pickle


def relay(view, payload):
    body = bytes(view)                     # constructor materialize
    raw = payload.tobytes()                # ndarray materialize
    head = pickle.dumps({"p": payload})    # pickler on the data path
    return body, raw, head
