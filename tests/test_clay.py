"""clay plugin: geometry, roundtrips, sub-chunk repair bandwidth, parameter
validation (mirrors src/test/erasure-code/TestErasureCodeClay.cc strategy)."""
import itertools

import numpy as np
import pytest

from ceph_tpu.plugins import ErasureCodePluginRegistry


@pytest.fixture
def registry():
    return ErasureCodePluginRegistry()


def _payload(n, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8).tobytes()


def _make(registry, k, m, d=None, **extra):
    profile = {"k": str(k), "m": str(m), "device": "numpy", **extra}
    if d is not None:
        profile["d"] = str(d)
    return registry.factory("clay", "", profile)


# -- geometry ---------------------------------------------------------------

def test_geometry_defaults(registry):
    ec = _make(registry, 4, 2)          # d defaults to k+m-1 = 5
    assert ec.d == 5 and ec.q == 2 and ec.nu == 0 and ec.t == 3
    assert ec.get_sub_chunk_count() == 8
    assert ec.get_chunk_count() == 6
    assert ec.get_data_chunk_count() == 4


def test_geometry_with_nu(registry):
    # k=3, m=2, d=4 -> q=2, k+m=5 odd -> nu=1, t=3, sub=8
    ec = _make(registry, 3, 2, d=4)
    assert ec.q == 2 and ec.nu == 1 and ec.t == 3
    assert ec.get_sub_chunk_count() == 8


def test_chunk_size_subchunk_aligned(registry):
    ec = _make(registry, 4, 2)
    cs = ec.get_chunk_size(1)
    assert cs % ec.get_sub_chunk_count() == 0
    cs2 = ec.get_chunk_size(100000)
    assert cs2 * 4 >= 100000 and cs2 % ec.get_sub_chunk_count() == 0


@pytest.mark.parametrize("profile", [
    {"k": "4", "m": "2", "d": "3"},      # d < k
    {"k": "4", "m": "2", "d": "6"},      # d > k+m-1
    {"k": "4", "m": "2", "scalar_mds": "bogus"},
    {"k": "4", "m": "2", "technique": "bogus"},
    {"k": "4", "m": "2", "scalar_mds": "isa", "technique": "liber8tion"},
])
def test_invalid_profiles(registry, profile):
    with pytest.raises(ValueError):
        registry.factory("clay", "", {**profile, "device": "numpy"})


# -- roundtrip --------------------------------------------------------------

@pytest.mark.parametrize("k,m,d", [(4, 2, 5), (2, 2, 3), (3, 2, 4),
                                   (4, 3, 6), (6, 3, 8)])
def test_encode_decode_all_single_erasures(registry, k, m, d):
    ec = _make(registry, k, m, d)
    data = _payload(ec.get_chunk_size(1) * k, seed=k * 10 + m)
    n = k + m
    encoded = ec.encode(set(range(n)), data)
    for lost in range(n):
        available = {i: v for i, v in encoded.items() if i != lost}
        decoded = ec.decode({lost}, available)
        np.testing.assert_array_equal(decoded[lost], encoded[lost],
                                      err_msg=f"lost={lost}")


@pytest.mark.parametrize("k,m", [(4, 2), (4, 3)])
def test_decode_all_m_erasures(registry, k, m):
    ec = _make(registry, k, m)
    data = _payload(ec.get_chunk_size(1) * k, seed=9)
    n = k + m
    encoded = ec.encode(set(range(n)), data)
    for lost in itertools.combinations(range(n), m):
        available = {i: v for i, v in encoded.items() if i not in lost}
        decoded = ec.decode(set(lost), available)
        for e in lost:
            np.testing.assert_array_equal(decoded[e], encoded[e],
                                          err_msg=f"lost={lost}")


def test_decode_concat_roundtrip(registry):
    ec = _make(registry, 4, 2)
    data = _payload(3000, seed=4)
    encoded = ec.encode(set(range(6)), data)
    available = {i: encoded[i] for i in (1, 2, 3, 5)}
    assert ec.decode_concat(available)[:len(data)] == data


# -- repair path (the MSR feature) ------------------------------------------

def test_minimum_to_repair_reads_fraction(registry):
    ec = _make(registry, 4, 2)          # q=2: helpers send 1/2 chunk
    lost = 1
    available = set(range(6)) - {lost}
    minimum = ec.minimum_to_decode({lost}, available)
    assert len(minimum) == ec.d == 5
    sub = ec.get_sub_chunk_count()
    for node, runs in minimum.items():
        read = sum(count for _, count in runs)
        assert read == sub // ec.q, f"node {node} reads {read}"


def test_minimum_to_decode_falls_back_to_full(registry):
    ec = _make(registry, 4, 2)
    # two losses -> not a repair; full chunks from k survivors
    got = ec.minimum_to_decode({0, 1}, {2, 3, 4, 5})
    sub = ec.get_sub_chunk_count()
    assert all(runs == [(0, sub)] for runs in got.values())


@pytest.mark.parametrize("k,m,d", [(4, 2, 5), (4, 3, 6), (3, 2, 4)])
def test_repair_with_subchunk_reads(registry, k, m, d):
    """Feed repair() only the sub-chunk runs minimum_to_decode asked for and
    check the reconstruction is exact (the regenerating property)."""
    ec = _make(registry, k, m, d)
    chunk_size = ec.get_chunk_size(1) * 4
    data = _payload(chunk_size * k, seed=13)
    n = k + m
    encoded = ec.encode(set(range(n)), data)
    sub = ec.get_sub_chunk_count()
    sc_size = chunk_size // sub
    for lost in range(n):
        available = set(range(n)) - {lost}
        minimum = ec.minimum_to_decode({lost}, available)
        assert len(minimum) == d
        helper_chunks = {}
        for node, runs in minimum.items():
            full = encoded[node].reshape(sub, sc_size)
            parts = [full[off:off + cnt] for off, cnt in runs]
            helper_chunks[node] = np.concatenate(parts).reshape(-1)
            assert helper_chunks[node].nbytes < chunk_size  # true saving
        decoded = ec.decode({lost}, helper_chunks, chunk_size=chunk_size)
        np.testing.assert_array_equal(decoded[lost], encoded[lost],
                                      err_msg=f"lost={lost}")


def test_repair_bandwidth_ratio(registry):
    # d=k+m-1 MSR: repair bandwidth = d/q vs k full chunks for plain RS
    ec = _make(registry, 4, 2)
    minimum = ec.minimum_to_decode({0}, {1, 2, 3, 4, 5})
    sub = ec.get_sub_chunk_count()
    total_sub = sum(sum(c for _, c in runs) for runs in minimum.values())
    rs_cost = 4 * sub               # k chunks, all sub-chunks
    assert total_sub < rs_cost      # 5 * 4 = 20 < 32


# -- scalar_mds variants ----------------------------------------------------

@pytest.mark.parametrize("scalar_mds,technique", [
    ("jerasure", "reed_sol_van"),
    ("isa", "cauchy"),
    ("jax_rs", "cauchy"),
    ("shec", "single"),
])
def test_scalar_mds_choices(registry, scalar_mds, technique):
    ec = _make(registry, 4, 2, scalar_mds=scalar_mds, technique=technique)
    data = _payload(ec.get_chunk_size(1) * 4, seed=5)
    encoded = ec.encode(set(range(6)), data)
    available = {i: encoded[i] for i in (0, 2, 3, 4)}
    decoded = ec.decode({1, 5}, available)
    np.testing.assert_array_equal(decoded[1], encoded[1])
    np.testing.assert_array_equal(decoded[5], encoded[5])


# -- cluster read paths (sub-chunk geometry vs chunk slicing) ---------------

class TestClayClusterReads:
    """A sub-chunked chunk is ONE codeword over its whole height: any
    read path that must DECODE (degraded, or mid-read source failure)
    has to fetch full chunks — a (c_off, c_len) slice is not a smaller
    codeword the way it is for per-byte-linear RS.  Both regressions
    here were found by the clay thrash soak."""

    def _cluster(self):
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.common import Context
        c = MiniCluster(n_osds=12, chunk_size=128, cct=Context())
        pid = c.create_ec_pool(
            "p", {"plugin": "clay", "k": "4", "m": "2",
                  "scalar_mds": "jax_rs", "device": "numpy"}, pg_num=1)
        g = c.pools[pid]["pgs"][0]
        data = _payload(3 * 512, seed=3)      # 3 stripes: height 384 > 128
        c.put(pid, "o", data)
        return c, pid, g, data

    def test_degraded_partial_read_decodes_whole_chunks(self):
        c, pid, g, data = self._cluster()
        try:
            g.bus.mark_down(g.acting[1])
            out = {}
            g.backend.objects_read_and_reconstruct(
                {"o": [(512, 512)]}, lambda r, e: out.update(r=r, e=e))
            g.bus.deliver_all()
            assert not out["e"]
            assert out["r"]["o"][0][2] == data[512:1024]
        finally:
            c.shutdown()

    def test_mid_read_source_failure_upgrades_to_whole_chunks(self):
        """A HEALTHY sliced read whose source errors mid-flight retries
        through parity: the retry must re-fetch every contributor at
        full height (sliced buffers + parity slices decode garbage)."""
        from ceph_tpu.backend.memstore import GObject
        from ceph_tpu.backend.pg_backend import shard_store
        c, pid, g, data = self._cluster()
        try:
            victim = g.acting[1]
            del shard_store(g.bus, victim).objects[GObject("o", victim)]
            out = {}
            g.backend.objects_read_and_reconstruct(
                {"o": [(512, 512)]}, lambda r, e: out.update(r=r, e=e))
            g.bus.deliver_all()
            assert not out["e"], out["e"]
            assert out["r"]["o"][0][2] == data[512:1024]
        finally:
            c.shutdown()
