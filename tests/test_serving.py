"""Serving engine: admission throttles, op coalescing, futures, QoS.

Reference analogs: src/common/Throttle.{h,cc} (FIFO bounded semaphore),
src/common/Finisher.{h,cc} (ordered completion thread), the mClock op
queues — fused here with inference-style dynamic batching through
``ecutil.encode_many``/``decode_many`` (ceph_tpu/exec/).
"""
import threading
import time

import numpy as np
import pytest

from ceph_tpu.backend import StripeInfo, ecutil
from ceph_tpu.common import Context
from ceph_tpu.exec import (BatchFuture, Finisher, ServingEngine, Throttle,
                           ThrottleFull, bucket_pad_stripes)
from ceph_tpu.osd.mclock import BG_SCRUB, CLIENT_OP
from ceph_tpu.plugins.registry import ErasureCodePluginRegistry

PROFILE = {"plugin": "jax_rs", "k": "4", "m": "2", "device": "numpy",
           "technique": "reed_sol_van"}
CHUNK = 256
STRIPE = 4 * CHUNK


def codec():
    ec = ErasureCodePluginRegistry.instance().factory(
        "jax_rs", "", dict(PROFILE))
    return ec, StripeInfo(4, CHUNK)


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


def counting(ec):
    calls = {"n": 0}
    orig = ec.encode_chunks

    def wrapped(want, chunks):
        calls["n"] += 1
        return orig(want, chunks)
    ec.encode_chunks = wrapped
    return calls


class TestThrottle:
    def test_get_put_counts(self):
        t = Throttle("t", 10)
        assert t.get(4) and t.count == 4
        assert t.get(6) and t.count == 10
        t.put(10)
        assert t.count == 0

    def test_get_or_fail_backpressure(self):
        t = Throttle("t", 4)
        assert t.get_or_fail(3)
        assert not t.get_or_fail(2)        # would overshoot
        assert t.get_or_fail(1)
        assert not t.get_or_fail(1)
        assert t.perf.get("get_or_fail_fail") == 2

    def test_blocking_get_waits_for_put(self):
        t = Throttle("t", 2)
        t.get(2)
        order = []

        def taker():
            t.get(1)
            order.append("took")
        th = threading.Thread(target=taker, daemon=True)
        th.start()
        time.sleep(0.05)
        assert order == [] and t.waiters() == 1      # blocked, bounded
        t.put(1)
        th.join(2)
        assert order == ["took"]

    def test_fifo_large_request_not_starved(self):
        """A queued large take must not be starved by later small ones
        (Throttle.cc queues per-waiter conds for exactly this)."""
        t = Throttle("t", 4)
        t.get(4)
        got = []

        def take(n, tag):
            t.get(n)
            got.append(tag)
        big = threading.Thread(target=take, args=(4, "big"), daemon=True)
        big.start()
        time.sleep(0.02)
        small = threading.Thread(target=take, args=(1, "small"),
                                 daemon=True)
        small.start()
        time.sleep(0.02)
        # small could sneak in without FIFO; with it, nothing moves yet
        t.put(4)                   # big (head) takes all four
        big.join(2)
        assert got == ["big"]
        t.put(4)
        small.join(2)
        assert got == ["big", "small"]

    def test_get_timeout(self):
        t = Throttle("t", 1)
        t.get(1)
        assert t.get(1, timeout=0.02) is False
        assert t.waiters() == 0            # timed-out waiter left cleanly

    def test_oversized_singleton_admitted_when_empty(self):
        t = Throttle("t", 4)
        assert t.get_or_fail(100)          # would deadlock otherwise
        assert not t.get_or_fail(1)
        t.put(100)
        assert t.get_or_fail(1)


class TestFinisher:
    def test_inline_drain_preserves_order(self):
        f = Finisher("t")
        out = []
        for i in range(5):
            f.queue(out.append, i)
        assert f.drain() == 5
        assert out == list(range(5))

    def test_threaded_stop_drains_everything(self):
        f = Finisher("t").start()
        out = []
        for i in range(100):
            f.queue(out.append, i)
        f.stop()
        assert out == list(range(100))

    def test_crashing_callback_does_not_kill_the_rest(self):
        f = Finisher("t")
        out = []
        f.queue(lambda: 1 / 0)
        f.queue(out.append, "ok")
        f.drain()
        assert out == ["ok"]


class TestCoalescing:
    def test_many_ops_one_dispatch_results_exact(self):
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.co")
        calls = counting(ec)
        bufs = [payload(STRIPE * (1 + i % 3), seed=i) for i in range(16)]
        futs = [eng.submit_encode(b) for b in bufs]
        eng.step()
        assert calls["n"] == 1, "concurrent submissions did not coalesce"
        for b, fut in zip(bufs, futs):
            want = ecutil.encode(sinfo, ec, b)
            got = fut.result(1)
            for c in want:
                assert np.array_equal(got[c], want[c]), f"chunk {c}"

    def test_batch_max_ops_splits_batches(self):
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.max",
                            batch_max_ops=4)
        calls = counting(ec)
        futs = [eng.submit_encode(payload(STRIPE, seed=i))
                for i in range(10)]
        eng.flush()
        assert calls["n"] == 3             # 4 + 4 + 2
        assert all(f.done() for f in futs)
        assert eng.perf.get("batches") == 3

    def test_decode_ops_coalesce_and_match(self):
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.dec")
        bufs = [payload(STRIPE * (1 + i % 2), seed=i) for i in range(8)]
        encoded = [ecutil.encode(sinfo, ec, b) for b in bufs]
        # same survivor signature for all -> one decode dispatch
        futs = [eng.submit_decode({c: e[c] for c in (0, 2, 3, 5)})
                for e in encoded]
        eng.flush()
        for b, fut in zip(bufs, futs):
            assert fut.result(1) == b

    def test_mixed_codecs_do_not_fuse(self):
        """Ops from pools with different codecs share the QUEUE but never
        a device dispatch."""
        ec1, sinfo1 = codec()
        ec2 = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {**PROFILE, "k": "2", "m": "1"})
        sinfo2 = StripeInfo(2, CHUNK)
        eng = ServingEngine(name="t.mix")
        c1, c2 = counting(ec1), counting(ec2)
        f1 = eng.submit_encode(payload(STRIPE), sinfo=sinfo1, ec_impl=ec1)
        f2 = eng.submit_encode(payload(2 * CHUNK, seed=1), sinfo=sinfo2,
                               ec_impl=ec2)
        eng.step()
        assert c1["n"] == 1 and c2["n"] == 1
        assert f1.result(1) is not None and f2.result(1) is not None

    def test_unaligned_op_padded_to_stripe(self):
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.pad")
        raw = payload(STRIPE + 100, seed=3)    # non-stripe-aligned tail
        fut = eng.submit_encode(raw)
        eng.flush()
        want = ecutil.encode(
            sinfo, ec, raw + b"\0" * (STRIPE - 100))
        got = fut.result(1)
        for c in want:
            assert np.array_equal(got[c], want[c])

    def test_size_buckets_are_powers_of_two(self):
        assert [bucket_pad_stripes(n) for n in (0, 1, 2, 3, 5, 64, 65)] \
            == [1, 1, 2, 4, 8, 64, 128]

    def test_group_error_fails_futures_not_engine(self):
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.err")

        def boom(want, chunks):
            raise RuntimeError("device fell over")
        orig = ec.encode_chunks
        ec.encode_chunks = boom
        try:
            fut = eng.submit_encode(payload(STRIPE))
            eng.flush()
            with pytest.raises(RuntimeError, match="fell over"):
                fut.result(1)
        finally:
            ec.encode_chunks = orig
        # the engine still serves (throttles were released)
        assert eng.op_throttle.count == 0
        fut2 = eng.submit_encode(payload(STRIPE))
        eng.flush()
        assert fut2.result(1)


class TestDeadline:
    def test_partial_batch_dispatches_at_deadline(self):
        """A lone op must not wait for batch_max_ops companions forever:
        the coalescer's deadline bounds its queue time."""
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.dl",
                            batch_max_ops=64,
                            batch_max_delay_ms=10.0).start()
        try:
            fut = eng.submit_encode(payload(STRIPE))
            got = fut.result(2.0)          # << 64 ops ever arrive
            assert got is not None
            assert fut.t_dispatch - fut.t_submit < 1.0
        finally:
            eng.stop()

    def test_sync_encode_cuts_through_deadline(self):
        """A BLOCKED sync caller (engine.encode) must not sit out the
        whole batching deadline when it is alone — eager submissions
        dispatch what has arrived (regression: serial cluster writes
        through a threaded engine paid ~deadline per op)."""
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.eager",
                            batch_max_ops=64,
                            batch_max_delay_ms=500.0).start()
        try:
            t0 = time.monotonic()
            for i in range(3):
                assert eng.encode(payload(STRIPE, seed=i), timeout=5.0)
            # 3 serial ops at a 500 ms deadline would take >= 1.5 s if
            # each waited it out; eager cut-through stays far under ONE
            assert time.monotonic() - t0 < 0.5
        finally:
            eng.stop()

    def test_full_batch_does_not_wait_for_deadline(self):
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.full",
                            batch_max_ops=4,
                            batch_max_delay_ms=10_000.0).start()
        try:
            futs = [eng.submit_encode(payload(STRIPE, seed=i))
                    for i in range(4)]
            for f in futs:
                f.result(5.0)              # deadline is 10s: batch-size
        finally:                           # trigger fired, not the clock
            eng.stop()


class TestBackpressure:
    def test_fail_fast_bounds_queue(self):
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.ff",
                            max_ops=4, fail_fast=True)
        for i in range(4):
            eng.submit_encode(payload(STRIPE, seed=i))
        with pytest.raises(ThrottleFull):
            eng.submit_encode(payload(STRIPE))
        d = eng.depths()
        assert d["_total"] == 4            # depth stays bounded
        assert eng.perf.get("ops_rejected") == 1
        assert eng.perf.get("queue_depth") == 4
        eng.flush()
        # completions released the throttle: admission works again
        assert eng.submit_encode(payload(STRIPE)) is not None
        eng.flush()

    def test_byte_throttle_bounds_queued_bytes(self):
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.bytes",
                            max_bytes=4 * STRIPE, fail_fast=True)
        eng.submit_encode(payload(3 * STRIPE))
        with pytest.raises(ThrottleFull):
            eng.submit_encode(payload(2 * STRIPE))
        assert eng.depths()["_bytes"] <= 4 * STRIPE
        eng.flush()

    def test_blocking_submitter_parks_until_capacity(self):
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.blk",
                            max_ops=2, fail_fast=False)
        eng.submit_encode(payload(STRIPE, seed=0))
        eng.submit_encode(payload(STRIPE, seed=1))
        submitted = []

        def third():
            f = eng.submit_encode(payload(STRIPE, seed=2))
            submitted.append(f)
        th = threading.Thread(target=third, daemon=True)
        th.start()
        time.sleep(0.05)
        assert not submitted               # blocked at the throttle
        assert eng.depths()["_total"] == 2  # queue depth stays bounded
        eng.step()                         # completes the two -> room
        th.join(2)
        assert submitted
        eng.flush()
        assert submitted[0].result(1)


class TestQoS:
    def test_client_ops_dequeue_ahead_of_scrub(self):
        """Admission is dmClock-ordered: with a backlog of both classes,
        the first batch carries every client op while the rate-limited
        scrub class (limit 0.001/s) gets AT MOST its one under-limit op
        — background work cannot crowd clients out of a batch."""
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.qos",
                            batch_max_ops=5)
        scrub = [eng.submit_encode(payload(STRIPE, seed=i),
                                   op_class=BG_SCRUB) for i in range(4)]
        client = [eng.submit_encode(payload(STRIPE, seed=10 + i),
                                    op_class=CLIENT_OP) for i in range(4)]
        eng.step()                         # ONE batch of 5, mClock order
        assert all(f.done() for f in client)
        assert sum(f.done() for f in scrub) <= 1
        eng.flush()
        assert all(f.done() for f in scrub)


class TestFutures:
    def test_add_done_callback_after_completion_runs_inline(self):
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.fut")
        fut = eng.submit_encode(payload(STRIPE))
        eng.flush()
        seen = []
        fut.add_done_callback(seen.append)
        assert seen == [fut]

    def test_result_timeout(self):
        ec, sinfo = codec()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="t.to")
        fut = eng.submit_encode(payload(STRIPE))
        with pytest.raises(TimeoutError):
            fut.result(0.01)               # engine never stepped
        eng.flush()
        assert fut.result(1)


class TestClusterIntegration:
    def test_serving_cluster_matches_plain_cluster(self):
        """Writes routed through the engine land bit-identical to the
        direct encode path, and reads decode through the engine too."""
        from ceph_tpu.cluster import MiniCluster
        a = MiniCluster(n_osds=12, chunk_size=CHUNK)
        b = MiniCluster(n_osds=12, chunk_size=CHUNK)
        pa = a.create_ec_pool("p", PROFILE, pg_num=4)
        pb = b.create_ec_pool("p", PROFILE, pg_num=4)
        eng = b.enable_serving()
        objs = {f"o{i}": payload(STRIPE * (1 + i % 3), seed=i)
                for i in range(8)}
        for oid, data in objs.items():
            a.put(pa, oid, data)
            b.put(pb, oid, data)
        assert eng.perf.get("ops_submitted") >= len(objs)
        for oid, data in objs.items():
            assert b.get(pb, oid, len(data)) == data, oid
            ga, gb = a.pg_group(pa, oid), b.pg_group(pb, oid)
            from ceph_tpu.backend import GObject
            for chunk, (sa, sb) in enumerate(zip(ga.acting, gb.acting)):
                from ceph_tpu.backend.pg_backend import shard_store
                assert shard_store(ga.bus, sa).read(GObject(oid, sa)) == \
                    shard_store(gb.bus, sb).read(GObject(oid, sb)), \
                    f"{oid} chunk {chunk}"
            assert all(gb.backend.be_deep_scrub(oid).values()), oid
        a.shutdown()
        b.shutdown()

    def test_scrub_and_recovery_survive_serving(self):
        from ceph_tpu.backend.memstore import GObject
        from ceph_tpu.backend.pg_backend import shard_store
        from ceph_tpu.cluster import MiniCluster
        c = MiniCluster(n_osds=12, chunk_size=CHUNK)
        pid = c.create_ec_pool("p", PROFILE, pg_num=4)
        c.enable_serving()
        data = payload(STRIPE * 2, seed=7)
        c.put(pid, "victim", data)
        g = c.pg_group(pid, "victim")
        rot = g.acting[1]
        st = shard_store(g.bus, rot)
        st.objects[GObject("victim", rot)].data[0] ^= 0xFF
        report = c.scrub_pool(pid, repair=True)
        assert any("victim" in bad for bad in report.values())
        assert c.scrub_pool(pid) == {}
        assert c.get(pid, "victim", len(data)) == data
        c.shutdown()


class TestDaemonThrottle:
    def test_ms_dispatch_throttled_past_bound(self):
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.osd.osd_ops import MOSDOp, ObjectOperation
        c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "1",
                                     "device": "numpy"}, pg_num=4)
        c.put(pid, "obj", payload(1024))
        g = c.pg_group(pid, "obj")
        d = c.osds[g.backend.whoami]
        d.op_throttle = Throttle("osd.q", 2)
        results = []
        for i in range(3):
            m = MOSDOp(oid="obj", ops=ObjectOperation().stat().ops,
                       epoch=g.epoch)
            results.append(d.ms_dispatch(g.pgid, m, lambda r: None))
        assert results[:2] == [None, None]
        assert results[2] == ("throttled", d.epoch)
        assert d.queue_stats["throttled_rejects"] == 1
        d.drain()                          # runs + releases the throttle
        g.bus.deliver_all()
        m = MOSDOp(oid="obj", ops=ObjectOperation().stat().ops,
                   epoch=g.epoch)
        assert d.ms_dispatch(g.pgid, m, lambda r: None) is None
        d.drain()
        c.shutdown()

    def test_osd_queue_throttle_ops_option_wires_daemons(self):
        from ceph_tpu.cluster import MiniCluster
        cct = Context(overrides={"osd_queue_throttle_ops": 3})
        c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512,
                        cct=cct)
        assert all(d.op_throttle is not None and d.op_throttle.max == 3
                   for d in c.osds.values())
        # normal I/O drains within the bound (ops release on dequeue)
        pid = c.create_ec_pool("p", {"k": "2", "m": "1",
                                     "device": "numpy"}, pg_num=4)
        data = payload(1024, seed=5)
        c.put(pid, "obj", data)
        assert c.get(pid, "obj", len(data)) == data
        c.shutdown()

    def test_cluster_drains_and_resends_on_throttled_bounce(self):
        """A throttled dispatch is a TRANSIENT: the cluster drains the
        daemon (freeing its queue slots) and resends, so a batch far
        larger than the bound still completes — no mislabeled 'stale'
        failure (regression: the bounce surfaced as a stale-map
        IOError with no retry)."""
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.osd.osd_ops import ObjectOperation
        cct = Context(overrides={"osd_queue_throttle_ops": 1})
        c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512,
                        cct=cct)
        pid = c.create_ec_pool("p", {"k": "2", "m": "1",
                                     "device": "numpy"}, pg_num=4)
        # deliver=False queues without draining: past op #1 every
        # dispatch to the same primary hits the full queue
        for i in range(6):
            c.operate(pid, "same-obj" if i else "same-obj",
                      ObjectOperation().write_full(payload(777, seed=i)),
                      deliver=False)
        c.deliver_all()
        assert c.get(pid, "same-obj", 777) == payload(777, seed=5)
        rejects = sum(d.queue_stats["throttled_rejects"]
                      for d in c.osds.values())
        assert rejects >= 1            # the bound actually bit
        c.shutdown()


class TestServingMetrics:
    def test_prometheus_carries_serving_and_mclock_metrics(self):
        from ceph_tpu.mgr.prometheus import render
        cct = Context()
        ec, sinfo = codec()
        eng = ServingEngine(cct=cct, ec_impl=ec, sinfo=sinfo,
                            name="promtest", max_ops=16, fail_fast=True)
        for i in range(3):
            eng.submit_encode(payload(STRIPE, seed=i))
        text = render(cct)                 # scrape WHILE queued: depth > 0
        assert 'ceph_tpu_queue_depth{collection="promtest"} 3' in text
        assert 'ceph_tpu_mclock_queue_depth{owner="serving.promtest",' \
               'shard="0",op_class="client_op"} 3' in text
        eng.flush()
        text = render(cct)
        assert 'ceph_tpu_queue_depth{collection="promtest"} 0' in text
        assert 'ceph_tpu_ops_coalesced{collection="promtest"} 3' in text
        # batch-size histogram with the full _bucket/_sum/_count set
        assert 'ceph_tpu_batch_size_bucket{collection="promtest",' \
               'le="+Inf"} 1' in text
        assert 'ceph_tpu_batch_size_sum{collection="promtest"}' in text
        # throttle counters registered under their own collections
        assert 'collection="throttle.promtest.ops"' in text

    def test_e2e_latency_histogram_counts_ops(self):
        cct = Context()
        ec, sinfo = codec()
        eng = ServingEngine(cct=cct, ec_impl=ec, sinfo=sinfo,
                            name="latm")
        for i in range(5):
            eng.submit_encode(payload(STRIPE, seed=i))
        eng.flush()
        dump = eng.perf.dump()
        assert dump["op_e2e_lat"]["count"] == 5
        assert dump["queue_wait_lat"]["count"] == 5
        assert dump["e2e_time"]["avgcount"] == 5
