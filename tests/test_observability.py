"""Wire & workload observability: accounting completeness, heat maps,
cluster log, and the embedded time-series ring.

The acceptance surface of the wire-observability PR:

- per-op-class wire bytes SUM to total connection bytes (accounting is
  complete — no message escapes classification);
- ``recovery.wire_bytes_per_byte_repaired`` reports ~k for centralized
  repair at k=8 (ROADMAP item 3's success metric, finally measurable);
- a synthetic hot-shard workload trips ``HOT_SHARD`` and shows the skew
  in ``ceph_tpu_osd_heat``;
- the cluster log ring is bounded and persists; ``ceph -w`` /
  ``ceph log last`` / ``daemonperf`` render it; the time-series ring
  evicts round-robin; and ``ts_report`` replays an episode from the
  flight-recorder bundle alone.
"""
import importlib.util
import json
import os
from pathlib import Path

import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.common import Context
from ceph_tpu.common.clusterlog import ClusterLog
from ceph_tpu.common.wire_accounting import (WIRE_CLASSES, WireAccounting,
                                             wire_size)
from ceph_tpu.mgr.timeseries import TimeSeriesRing

ROOT = Path(__file__).resolve().parent.parent


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"tool_{name}", ROOT / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _Ctx:
    """A minimal TraceContext stand-in for unit tests."""
    def __init__(self, op_class):
        self.op_class = op_class


class TestWireAccountingUnit:
    def test_classes_partition_totals(self):
        cct = Context()
        acct = WireAccounting(cct=cct, name="unit")
        try:
            acct.account_tx("A", 100, ctx=_Ctx("recovery"))
            acct.account_tx("B", 50, ctx=_Ctx("client"))
            acct.account_tx("B", 25, ctx=None)          # untraced -> other
            acct.account_rx("A", 10, ctx=_Ctx("scrub"))
            totals = acct.totals()
            assert totals["tx_bytes"] == 175 and totals["rx_bytes"] == 10
            assert totals["tx_msgs"] == 3 and totals["rx_msgs"] == 1
            cls = acct.class_bytes()
            assert sum(cls.values()) == 185
            assert cls["recovery"] == 100 and cls["other"] == 25
            per = acct.per_type()
            assert per["B"]["tx_bytes"] == 75
            assert per["A"]["rx_msgs"] == 1
        finally:
            acct.close()
        assert cct.perf.get("wire.unit") is None     # close() unhooks

    def test_queue_depth_peak_and_rpc_latency(self):
        acct = WireAccounting(cct=Context(), name="unit2")
        try:
            acct.note_queue_depth(3)
            acct.note_queue_depth(9)
            acct.note_queue_depth(1)
            assert acct.perf.get("send_queue_depth") == 1
            assert acct.perf.get("send_queue_peak") == 9
            acct.observe_rpc("put", 0.002)
            acct.observe_rpc("put", 0.004)
            acct.observe_rpc("get", 0.001)
            rpc = acct.rpc_methods()
            assert rpc["put"]["count"] == 2
            assert rpc["put"]["avg_ms"] == pytest.approx(3.0, abs=0.5)
            dump = acct.perf.dump()["rpc_latency_ms"]
            assert dump["count"] == 3
        finally:
            acct.close()

    def test_wire_size_fallback_is_still_counted(self):
        class Unregistered:
            pass
        acct = WireAccounting(cct=Context(), name="unit3")
        try:
            acct.account_msg(Unregistered())
            assert acct.perf.get("unsized_msgs") == 1
            assert acct.perf.get("tx_bytes") >= wire_size(Unregistered()) \
                or acct.perf.get("tx_bytes") > 0
        finally:
            acct.close()


class TestWireCompleteness:
    def test_mixed_serving_recovery_classes_sum_to_totals(self):
        """The acceptance invariant: under a mixed serving+recovery run
        every byte on the bus lands in exactly one op-class bucket."""
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512,
                        cct=Context())
        try:
            pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                         "device": "numpy"}, pg_num=4)
            c.enable_recovery_scheduler()
            rng = np.random.default_rng(0)
            objs = {f"o{i}": rng.integers(0, 256, 3000,
                                          np.uint8).tobytes()
                    for i in range(8)}
            for oid, d in objs.items():
                c.put(pid, oid, d)
            g = c.pools[pid]["pgs"][0]
            victim = g.acting[1]
            g.bus.mark_down(victim)
            for oid, d in objs.items():      # writes the victim misses
                c.put(pid, oid, b"\x07" + d[1:])
            g.bus.mark_up(victim)
            c.deliver_all()
            for oid, d in objs.items():      # serving reads
                assert c.get(pid, oid, 3000) == b"\x07" + d[1:]
            c.scrub_pool(pid, repair=False)  # scrub-class traffic too
            totals = c.wire.totals()
            cls_bytes = c.wire.class_bytes()
            assert totals["tx_bytes"] > 0
            assert sum(cls_bytes.values()) == \
                totals["tx_bytes"] + totals["rx_bytes"]
            cls_msgs = {k: c.wire.perf.get(f"class_msgs:{k}")
                        for k in WIRE_CLASSES}
            assert sum(cls_msgs.values()) == \
                totals["tx_msgs"] + totals["rx_msgs"]
            # the mixed run actually exercised several classes
            assert cls_bytes["client"] > 0
            assert cls_bytes["recovery"] > 0
            assert c.wire.perf.get("unsized_msgs") == 0
            assert c.wire.perf.get("send_queue_peak") >= 1
        finally:
            c.shutdown()


class TestRecoveryWireRatio:
    def test_centralized_repair_is_k_times_on_wire(self):
        """k=8 centralized repair hauls ~k survivor chunks to the
        primary per chunk repaired: wire-bytes-per-byte-repaired lands
        near k (log/header overhead rides on top) — the number the
        pipelined-repair work (ROADMAP item 3) must push toward ~1."""
        k = 8
        c = MiniCluster(n_osds=12, osds_per_host=1, chunk_size=512,
                        cct=Context())
        try:
            pid = c.create_ec_pool("p", {"k": str(k), "m": "2",
                                         "device": "numpy"}, pg_num=1)
            g = c.pools[pid]["pgs"][0]
            rng = np.random.default_rng(1)
            objs = {f"o{i}": rng.integers(0, 256, 16384,
                                          np.uint8).tobytes()
                    for i in range(6)}
            c.stats.sample(now=0.0)
            for oid, d in objs.items():
                c.put(pid, oid, d)
            victim = g.acting[1]
            g.bus.mark_down(victim)
            for oid, d in objs.items():
                c.put(pid, oid, b"\x01" + d[1:])
            wire0 = c.wire.perf.get("class_bytes:recovery")
            rep0 = g.backend.perf.get("recovery_bytes")
            g.bus.mark_up(victim)
            c.deliver_all()
            wire = c.wire.perf.get("class_bytes:recovery") - wire0
            repaired = g.backend.perf.get("recovery_bytes") - rep0
            assert repaired > 0
            ratio = wire / repaired
            assert 0.9 * k <= ratio <= 2.0 * k, \
                f"centralized repair wire ratio {ratio:.2f} not ~k={k}"
            # the digest reports the same metric over the stats window
            c.stats.sample(now=10.0)
            d = c.stats.digest()
            assert d["recovery"]["wire_bytes_per_byte_repaired"] == \
                pytest.approx(ratio, rel=0.25)
            assert d["serving"]["wire_bytes_per_op"] > 0
        finally:
            c.shutdown()


class TestHotShard:
    def _cluster(self):
        c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512,
                        cct=Context())
        pid = c.create_ec_pool("p", {"k": "2", "m": "1",
                                     "device": "numpy"}, pg_num=4)
        # deterministic window: drive the aggregator on a fake clock so
        # rates don't depend on wall time
        t = [0.0]
        c.stats.clock = lambda: t[0]
        return c, pid, t

    def test_hot_shard_trips_check_and_heat_gauges(self):
        c, pid, t = self._cluster()
        try:
            # oids that all land in ONE PG: the synthetic hot shard
            hot = [oid for oid in (f"h{i}" for i in range(200))
                   if c.object_pg(pid, oid) == 0][:4]
            assert len(hot) == 4
            c.stats.sample()
            for rep in range(15):
                for oid in hot:
                    c.put(pid, oid, bytes([rep]) * 1024)
            t[0] = 2.0
            c.stats.sample()                 # 60 ops / 2s = 30 op/s
            h = c.health()
            assert "HOT_SHARD" in h["checks"], h
            ev = c.health_engine.last_evaluation
            assert ev["checks"]["HOT_SHARD"]["detail"]
            hot_osd = c.pools[pid]["pgs"][0].backend.whoami
            heat = c.heat.osd_heat()
            assert heat[hot_osd]["op_s"] >= 16
            digest = c.heat.tail_digest()
            assert hot_osd in digest["hot_osds"]
            from ceph_tpu.mgr.prometheus import render
            lines = render(c.cct).splitlines()
            row = next(l for l in lines if l.startswith(
                f'ceph_tpu_osd_heat{{owner="c{c.cluster_id}",'
                f'osd="{hot_osd}",stat="op_s"}}'))
            assert float(row.rsplit(" ", 1)[1]) > 0
            pg_row = next(l for l in lines if l.startswith(
                f'ceph_tpu_pg_heat{{owner="c{c.cluster_id}",'
                f'pg="1.0",stat="op_s"}}'))
            assert float(pg_row.rsplit(" ", 1)[1]) > 0
        finally:
            c.shutdown()

    def test_balanced_load_does_not_fire(self):
        c, pid, t = self._cluster()
        try:
            c.stats.sample()
            rng = np.random.default_rng(3)
            for i in range(60):              # spread over all PGs
                c.put(pid, f"b{i}", rng.integers(0, 256, 800,
                                                 np.uint8).tobytes())
            t[0] = 2.0
            c.stats.sample()
            assert "HOT_SHARD" not in c.health()["checks"]
        finally:
            c.shutdown()

    def test_idle_and_subsecond_windows_never_fire(self):
        c, pid, t = self._cluster()
        try:
            c.stats.sample()
            t[0] = 0.5                       # sub-second window
            for oid in ("x", "y"):
                c.put(pid, oid, b"z" * 512)
            c.stats.sample()
            assert "HOT_SHARD" not in c.health()["checks"]
        finally:
            c.shutdown()


class TestClusterLog:
    def test_ring_bounded_and_severity_filter(self):
        log = ClusterLog(cct=Context(), capacity=5)
        for i in range(12):
            log.log("INF" if i % 2 else "WRN", f"event {i}")
        entries = log.last(100)
        assert len(entries) == 5                       # bounded
        assert entries[-1]["message"] == "event 11"
        assert entries[0]["message"] == "event 7"      # oldest evicted
        warns = log.last(100, severity="WRN")
        assert all(e["severity"] == "WRN" for e in warns)
        assert log.tail_since(entries[-2]["seq"]) == entries[-1:]
        with pytest.raises(ValueError):
            log.log("NOPE", "bad severity")

    def test_persistence_and_seq_survive_reopen(self, tmp_path):
        path = tmp_path / "clusterlog"
        log = ClusterLog(cct=Context(), path=path, capacity=10)
        log.info("first")
        log.warn("second")
        log.close()
        log2 = ClusterLog(cct=Context(), path=path, capacity=10)
        msgs = [e["message"] for e in log2.last(10)]
        assert msgs == ["first", "second"]
        e = log2.error("third")
        assert e["seq"] == 3                           # seq continues
        from ceph_tpu.common.clusterlog import read_log_file
        assert [x["message"] for x in read_log_file(path)] == \
            ["first", "second", "third"]

    def test_file_compaction_bounds_disk(self, tmp_path):
        path = tmp_path / "clusterlog"
        log = ClusterLog(cct=Context(), path=path, capacity=4)
        for i in range(50):
            log.info(f"e{i}")
        from ceph_tpu.common.clusterlog import COMPACT_FACTOR, \
            read_log_file
        on_disk = read_log_file(path)
        assert len(on_disk) <= 4 * COMPACT_FACTOR
        assert on_disk[-1]["message"] == "e49"         # newest survives


class TestTimeSeries:
    def _ring(self, **kw):
        t = [0.0]
        kw.setdefault("interval", 1.0)
        kw.setdefault("capacity", 4)
        kw.setdefault("coarse_every", 2)
        ring = TimeSeriesRing(cct=Context(), clock=lambda: t[0], **kw)
        return ring, t

    def test_round_robin_eviction(self):
        ring, t = self._ring()
        vals = [0.0]
        ring.add_source("s", lambda: {"v": vals[0]})
        for i in range(10):
            t[0] = float(i)
            vals[0] = float(i)
            assert ring.record() is not None
        assert len(ring.fine) == 4                     # bounded
        assert [p["s.v"] for p in ring.fine] == [6.0, 7.0, 8.0, 9.0]
        assert ring.points_recorded == 10
        # coarse: every 2 fine points folded to avg+max, also bounded
        assert len(ring.coarse) == 4
        last = ring.coarse[-1]
        assert last["s.v:avg"] == 8.5 and last["s.v:max"] == 9.0

    def test_interval_gating_and_force(self):
        ring, t = self._ring(interval=5.0)
        ring.add_source("s", lambda: {"v": 1.0})
        assert ring.record() is not None
        t[0] = 1.0
        assert ring.record() is None                   # inside interval
        assert ring.points_skipped == 1
        assert ring.record(force=True) is not None     # phase boundary
        t[0] = 6.0
        assert ring.record() is not None

    def test_broken_source_marks_error_not_crash(self):
        ring, t = self._ring()
        ring.add_source("bad", lambda: 1 / 0)
        ring.add_source("good", lambda: {"v": 2.0})
        p = ring.record()
        assert p["bad.error"] == 1.0 and p["good.v"] == 2.0

    def test_series_access_and_dump_shape(self):
        ring, t = self._ring()
        ring.add_source("s", lambda: {"v": t[0]})
        for i in range(3):
            t[0] = float(i)
            ring.record()
        assert ring.series_names() == ["s.v"]
        assert ring.series("s.v") == [(0.0, 0.0), (1.0, 1.0), (2.0, 2.0)]
        d = ring.dump()
        assert d["recorded"] == 3 and len(d["fine"]) == 3


@pytest.fixture
def durable_cluster(tmp_path):
    c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                    cct=Context(), data_dir=tmp_path / "d")
    pid = c.create_ec_pool("p", {"k": "2", "m": "1", "device": "numpy"},
                           pg_num=4)
    rng = np.random.default_rng(5)
    for i in range(6):
        c.put(pid, f"o{i}", rng.integers(0, 256, 1500,
                                         np.uint8).tobytes())
    yield c, pid, tmp_path / "d"
    c.shutdown()


class TestCLISurfaces:
    def test_log_last_and_watch_and_daemonperf(self, durable_cluster,
                                               capsys):
        c, pid, data_dir = durable_cluster
        g = c.pools[pid]["pgs"][0]
        victim = g.acting[1]
        g.bus.mark_down(victim)
        g.bus.mark_up(victim)
        c.deliver_all()
        c.shutdown()          # release stores for the CLI reopen
        from ceph_tpu.tools.ceph_cli import main as ceph_main
        assert ceph_main(["--data-dir", str(data_dir),
                          "log", "last", "50"]) == 0
        out = capsys.readouterr().out
        assert f"osd.{victim} down" in out
        assert f"osd.{victim} up" in out
        assert "pool 'p' created" in out
        # `ceph -w` follows the FILE without reopening the cluster
        assert ceph_main(["--data-dir", str(data_dir), "-w",
                          "--iterations", "1",
                          "--interval", "0.01"]) == 0
        out = capsys.readouterr().out
        assert "[osd]" in out or "[mon]" in out
        # daemonperf: per-daemon counter-rate columns
        assert ceph_main(["--data-dir", str(data_dir), "daemonperf",
                          "--iterations", "2", "--interval", "0"]) == 0
        out = capsys.readouterr().out
        assert "daemon" in out and "osd.0" in out and "wire_B/s" in out

    def test_watch_without_log_is_an_error(self, tmp_path, capsys):
        from ceph_tpu.tools.ceph_cli import main as ceph_main
        assert ceph_main(["--data-dir", str(tmp_path), "watch",
                          "--iterations", "1"]) == 2
        assert "no clusterlog" in capsys.readouterr().err


class TestFlightReplay:
    def test_ts_report_replays_episode_from_bundle_alone(
            self, durable_cluster, capsys):
        """The acceptance closer: degrade the cluster, let the health
        transition snapshot a flight bundle, then reconstruct what
        happened from the BUNDLE — time-series sparklines + the cluster
        log — with no live cluster and no external scraper."""
        c, pid, data_dir = durable_cluster
        c.status()                      # tick stats + timeseries
        c.ts.record(force=True)
        g = c.pools[pid]["pgs"][0]
        g.bus.mark_down(g.acting[1])    # degrade -> PG_DEGRADED WARN
        c.ts.record(force=True)
        h = c.health()                  # transition -> flight dump
        assert h["status"] != "HEALTH_OK"
        bundles = sorted((data_dir / "flight").glob("flight-*.json"))
        assert bundles, "health transition wrote no flight bundle"
        bundle = json.loads(bundles[-1].read_text())
        assert bundle["timeseries"]["fine"], "bundle carries no points"
        assert any("down" in e["message"]
                   for e in bundle["clusterlog"])
        ts_report = _load_tool("ts_report")
        assert ts_report.main([str(data_dir / "flight"), "--log"]) == 0
        out = capsys.readouterr().out
        assert "stats.client_wr_op_s" in out
        assert "down" in out            # the clusterlog replay
        assert ts_report.main([str(bundles[-1]), "--series",
                               "heat.tail", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert any(r["series"].startswith("heat.tail")
                   for r in doc["series"])

    def test_ts_report_rejects_garbage(self, tmp_path, capsys):
        ts_report = _load_tool("ts_report")
        p = tmp_path / "junk.json"
        p.write_text(json.dumps({"hello": 1}))
        assert ts_report.main([str(p)]) == 2
        assert "no usable timeseries" in capsys.readouterr().err


class TestNetWireAccounting:
    def test_tcp_rpc_frames_and_latency_accounted(self, tmp_path):
        from ceph_tpu.net import ClusterServer, TcpRados
        c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                        cct=Context(), data_dir=tmp_path / "d")
        server = ClusterServer(c)
        server.start()
        try:
            r = TcpRados("127.0.0.1", server.port,
                         tmp_path / "d" / "client.admin.keyring")
            r.mkpool("np", {"k": "2", "m": "1", "device": "numpy"},
                     pg_num=4)
            payload = os.urandom(2048)
            r.put("np", "obj", payload)
            assert r.get("np", "obj") == payload
            r.close()
            per = server.wire.per_type()
            assert per["RpcCall"]["rx_msgs"] >= 3      # mkpool/put/get
            assert per["RpcResult"]["tx_msgs"] >= 3
            assert per["RpcCall"]["rx_bytes"] >= 2048  # the put payload
            rpc = server.wire.rpc_methods()
            assert rpc["put"]["count"] == 1 and rpc["get"]["count"] == 1
            assert server.wire.perf.dump()["rpc_latency_ms"]["count"] \
                >= 3
            # RPC frames rode a traced client op: classed, not "other"
            assert server.wire.perf.get("class_bytes:client") > 0
        finally:
            server.stop()
            c.shutdown()
