"""Message-bus fault injection: duplicate and reordered delivery.

The role the reference's messenger fault injection plays under the
Thrasher (reference: qa/tasks/ceph_manager.py; ``ms inject socket
failures`` causes reconnect + resend, which the OSD dedups by reqid) —
here every duplicate-sensitive path is exercised deterministically:
sub-write dedup by at_version, idempotent ack/push-reply handling, state
guards on recovery/repair replies, and cross-sender reordering at the
primary.
"""
import numpy as np
import pytest

from ceph_tpu.backend import ECBackend, MessageBus, PGTransaction, StripeInfo
from ceph_tpu.backend.ec_backend import OSDShard, RecoveryState
from ceph_tpu.backend.memstore import GObject, Transaction
from ceph_tpu.backend.messages import ECSubWrite, FaultConfig
from ceph_tpu.cluster import MiniCluster
from ceph_tpu.plugins.registry import ErasureCodePluginRegistry

K, M = 4, 2
N = K + M
CHUNK = 64
STRIPE = K * CHUNK


def payload(n, seed=7):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


def make_backend(faults=None):
    ec = ErasureCodePluginRegistry.instance().factory(
        "jax_rs", "", {"k": str(K), "m": str(M), "device": "numpy",
                       "technique": "reed_sol_van"})
    bus = MessageBus()
    if faults:
        bus.inject_faults(faults)
    backend = ECBackend(ec, StripeInfo(K, CHUNK), bus,
                        acting=list(range(N)), whoami=0)
    for s in range(1, N):
        OSDShard(s, bus)
    return backend, bus


def read_obj(backend, bus, oid, length):
    out = {}
    backend.objects_read_and_reconstruct(
        {oid: [(0, length)]},
        lambda result, errors: out.update(result=result, errors=errors))
    bus.deliver_all()
    if out.get("errors"):
        raise IOError(out["errors"])
    return out["result"][oid][0][2]


class TestDuplicateDelivery:
    def test_dup_sub_write_applies_once(self):
        """A resent ECSubWrite must not re-apply: the at_version dedup
        (the reference's reqid dedup) re-acks without touching the log."""
        backend, bus = make_backend()
        data = payload(STRIPE)
        backend.submit_transaction(PGTransaction().write("o", 0, data))
        # capture shard 1's sub-write and deliver it twice by hand
        sw = next(m for m in bus.queues[1] if isinstance(m, ECSubWrite))
        bus.deliver_all()
        h1 = bus.handlers[1]
        head = h1.pg_log.head
        h1.handle_message(sw)              # the duplicate
        assert h1.pg_log.head == head, "dup sub-write advanced the log"
        assert len(h1.pending_rollbacks) <= 1
        bus.deliver_all()                  # the dup re-ack is harmless
        assert read_obj(backend, bus, "o", STRIPE) == data

    def test_everything_duplicated_campaign(self):
        """Run an entire write/read/recover workload with EVERY message
        having a 30% chance of duplicate delivery."""
        backend, bus = make_backend(FaultConfig(seed=3, dup_prob=0.3))
        model = {}
        for i in range(10):
            oid = f"o{i}"
            model[oid] = payload(2 * STRIPE, seed=i)
            done = []
            backend.submit_transaction(
                PGTransaction().write(oid, 0, model[oid]),
                on_commit=done.append)
            bus.deliver_all()
            assert done, f"write {oid} did not commit under dup injection"
        assert bus.duplicated > 0
        # lose a shard's object, recover it (dup push replies etc.)
        lost = GObject("o3", 4)
        bus.handlers[4].store.queue_transaction(Transaction().remove(lost))
        rop = backend.recover_object("o3", {4})
        bus.deliver_all()
        assert rop.state == RecoveryState.COMPLETE
        for oid, want in model.items():
            assert read_obj(backend, bus, oid, len(want)) == want
            assert all(backend.be_deep_scrub(oid).values()), oid

    def test_dup_during_failure_and_repair(self):
        backend, bus = make_backend(FaultConfig(seed=9, dup_prob=0.25))
        backend.submit_transaction(
            PGTransaction().write("a", 0, payload(STRIPE, seed=1)))
        bus.deliver_all()
        bus.mark_down(3)
        backend.submit_transaction(
            PGTransaction().write("a", 0, payload(STRIPE, seed=2)))
        backend.submit_transaction(
            PGTransaction().write("b", 0, payload(STRIPE, seed=3)))
        bus.deliver_all()
        bus.mark_up(3)                     # auto-repair under dup injection
        bus.deliver_all()
        assert 3 not in backend.stale
        assert read_obj(backend, bus, "a", STRIPE) == payload(STRIPE, seed=2)
        for oid in ("a", "b"):
            assert all(backend.be_deep_scrub(oid).values()), oid


class TestReordering:
    def test_reorder_preserves_per_sender_fifo(self):
        bus = MessageBus()
        bus.inject_faults(FaultConfig(seed=1, reorder=True))

        seen = []

        class Sink:
            def handle_message(self, m):
                seen.append(m)

        bus.register(0, Sink())

        from dataclasses import dataclass

        @dataclass
        class M:
            from_shard: int
            seq: int
        for s in (1, 2, 3):
            for i in range(5):
                bus.send(0, M(s, i))
        bus.deliver_all()
        assert len(seen) == 15
        for s in (1, 2, 3):
            seqs = [m.seq for m in seen if m.from_shard == s]
            assert seqs == sorted(seqs), f"sender {s} reordered internally"
        # and the interleaving is actually randomized
        assert [m.from_shard for m in seen] != [1] * 5 + [2] * 5 + [3] * 5

    def test_reordered_campaign_consistent(self):
        """Writes, degraded reads and recovery with replies delivered in
        randomized cross-sender order at the primary."""
        backend, bus = make_backend(FaultConfig(seed=17, reorder=True,
                                                dup_prob=0.15))
        model = {}
        for i in range(12):
            oid = f"r{i}"
            model[oid] = payload(int(np.random.default_rng(i).integers(1, 4))
                                 * STRIPE, seed=100 + i)
            backend.submit_transaction(
                PGTransaction().write(oid, 0, model[oid]))
        bus.deliver_all()
        bus.mark_down(2)
        for oid, want in model.items():    # degraded, reconstructing reads
            assert read_obj(backend, bus, oid, len(want)) == want
        bus.mark_up(2)
        bus.deliver_all()
        assert 2 not in backend.stale
        for oid in model:
            assert all(backend.be_deep_scrub(oid).values()), oid


class TestDropInjection:
    def test_drop_prob_discards_and_counts(self):
        """drop_prob=1 discards every send and counts it; drop_prob=0
        drops nothing."""
        bus = MessageBus()
        seen = []

        class Sink:
            def handle_message(self, m):
                seen.append(m)

        bus.register(0, Sink())
        bus.inject_faults(FaultConfig(seed=2, drop_prob=1.0))
        for i in range(5):
            bus.send(0, ("m", i))
        assert bus.dropped == 5 and not bus.queues[0]
        bus.inject_faults(FaultConfig(seed=2, drop_prob=0.0))
        for i in range(5):
            bus.send(0, ("m", i))
        bus.deliver_all()
        assert bus.dropped == 5 and len(seen) == 5

    def test_partial_drop_rate(self):
        bus = MessageBus()
        bus.register(0, type("S", (), {"handle_message":
                                       lambda self, m: None})())
        bus.inject_faults(FaultConfig(seed=4, drop_prob=0.5))
        for i in range(400):
            bus.send(0, i)
        assert 100 < bus.dropped < 300          # ~50% of 400
        assert len(bus.queues[0]) == 400 - bus.dropped

    def test_lost_read_request_survivable(self):
        """Pure drops (reset without resend) on CLIENT READS only: the
        primary routes around shards that never answer once they are
        marked down (the reference's analog: osd op timeout -> heartbeat
        failure -> map update)."""
        backend, bus = make_backend()
        data = payload(STRIPE)
        backend.submit_transaction(PGTransaction().write("o", 0, data))
        bus.deliver_all()
        # read request to shard 1 evaporates: simulate by clearing its
        # queue after issuing the read
        out = {}
        backend.objects_read_and_reconstruct(
            {"o": [(0, STRIPE)]},
            lambda result, errors: out.update(result=result, errors=errors))
        bus.queues[1].clear()
        bus.deliver_all()
        assert not out                     # stalled on the lost request
        bus.mark_down(1)                   # failure detection kicks in
        bus.deliver_all()
        assert out["result"]["o"][0][2] == data


class TestThrashWithFaults:
    def test_mini_thrash_under_full_injection(self):
        """A compact MiniCluster thrash with reorder + dup active on every
        PG bus simultaneously with kills."""
        rng = np.random.default_rng(7)
        cluster = MiniCluster(n_osds=12, chunk_size=CHUNK)
        pid = cluster.create_ec_pool(
            "faulty", {"plugin": "jax_rs", "k": str(K), "m": str(M),
                       "device": "numpy", "technique": "reed_sol_van"},
            pg_num=4)
        for i, g in enumerate(cluster.pools[pid]["pgs"].values()):
            g.bus.inject_faults(FaultConfig(seed=i, reorder=True,
                                            dup_prob=0.2))
        model = {}
        down = set()
        primaries = {g.backend.whoami
                     for g in cluster.pools[pid]["pgs"].values()}
        for step in range(80):
            r = rng.random()
            if r < 0.5:
                oid = f"x{int(rng.integers(0, 20))}"
                data = rng.integers(0, 256, STRIPE, np.uint8).tobytes()

                def committed(tid, _oid=oid, _d=data):
                    model[_oid] = _d
                cluster.put(pid, oid, data, wait=False, on_commit=committed)
            elif r < 0.8 and model:
                oid = sorted(model)[int(rng.integers(0, len(model)))]
                g = cluster.pg_group(pid, oid)
                if len(g.backend.current_shards()) >= K:
                    assert cluster.get(pid, oid, STRIPE) == model[oid]
            elif r < 0.9 and len(down) < M:
                cands = [o for o in range(12)
                         if o not in down and o not in primaries]
                if cands:
                    osd = int(rng.choice(cands))
                    down.add(osd)
                    for g in cluster.pools[pid]["pgs"].values():
                        if osd in g.acting:
                            g.bus.mark_down(osd)
            elif down:
                osd = int(rng.choice(sorted(down)))
                down.discard(osd)
                for g in cluster.pools[pid]["pgs"].values():
                    if osd in g.acting:
                        g.bus.mark_up(osd)
                        g.bus.deliver_all()
        for osd in sorted(down):
            for g in cluster.pools[pid]["pgs"].values():
                if osd in g.acting:
                    g.bus.mark_up(osd)
                    g.bus.deliver_all()
        for _ in range(10):
            if not any(g.backend.stale or g.backend.shard_repairs
                       for g in cluster.pools[pid]["pgs"].values()):
                break
            cluster.deliver_all()
        dupes = sum(g.bus.duplicated
                    for g in cluster.pools[pid]["pgs"].values())
        assert dupes > 0, "campaign never exercised duplicates"
        for oid, want in sorted(model.items()):
            assert cluster.get(pid, oid, len(want)) == want, oid
            g = cluster.pg_group(pid, oid)
            assert all(g.backend.be_deep_scrub(oid).values()), oid
