"""common/critpath.py: critical-path extraction over golden stitched
traces (the ISSUE-10 tentpole's correctness core), the span->phase
registry, the bounded ledger, and the unified nearest-rank percentile
(+ its AST guard: bench p99 and trace p99 can never drift apart again).
"""
import importlib.util
import time
from pathlib import Path

import pytest

from ceph_tpu.common import critpath
from ceph_tpu.common.critpath import (
    CritPathLedger, PHASES, decompose, group_traces, phase_for,
    render_attribution,
)
from ceph_tpu.common.percentile import nearest_rank, percentile
from ceph_tpu.common.tracer import Tracer

ROOT = Path(__file__).resolve().parent.parent


def ev(name, ts_ms, dur_ms, sid, parent=0, trace=1, **extra):
    args = {"trace_id": trace, "span_id": sid,
            "parent_span_id": parent}
    args.update(extra)
    return {"name": name, "ph": "X", "ts": ts_ms * 1000.0,
            "dur": dur_ms * 1000.0, "args": args}


class TestGoldenDecomposition:
    def test_known_per_phase_durations_attribute_exactly(self):
        """The fixture trace from the issue: queue 20, batch_delay 30,
        device 40 (two OVERLAPPING codec spans — union, never sum),
        wire 8, other 2 — summing to the 100 ms root exactly."""
        spans = [
            ev("client.op", 0, 100, 1, op_class="client"),
            ev("osd.queue_wait", 0, 20, 2, 1),
            ev("serving.batch_wait", 20, 30, 3, 1),
            ev("codec.encode", 50, 30, 4, 1),
            ev("codec.decode", 70, 20, 5, 1),   # overlaps encode 10 ms
            ev("osd.ECSubWrite", 90, 8, 6, 1),
        ]
        rec = decompose(spans)
        assert rec["op_class"] == "client"
        assert rec["total_s"] == pytest.approx(0.100)
        ph = rec["phases"]
        assert ph["queue"] == pytest.approx(0.020)
        assert ph["batch_delay"] == pytest.approx(0.030)
        # device overlap must not double-count: union [50,90] = 40 ms,
        # not 30+20 (the device_attribution clamping convention)
        assert ph["device"] == pytest.approx(0.040)
        assert ph["wire"] == pytest.approx(0.008)
        assert ph["other"] == pytest.approx(0.002)
        assert sum(ph.values()) == pytest.approx(rec["total_s"])

    def test_nested_children_charge_parents_self_time_down(self):
        spans = [
            ev("osd.op", 0, 50, 1, owner="client"),
            ev("ec.encode", 10, 30, 2, 1),
            ev("codec.encode", 15, 20, 3, 2),
        ]
        rec = decompose(spans)
        ph = rec["phases"]
        assert ph["other"] == pytest.approx(0.020)     # osd.op self
        assert ph["device"] == pytest.approx(0.030)    # ec + codec
        assert sum(ph.values()) == pytest.approx(rec["total_s"])

    def test_multiple_roots_union_not_sum(self):
        """Sibling roots (queue-wait event + daemon span, resent ops)
        contribute the UNION of their intervals; overlap clamps."""
        spans = [
            ev("osd.queue_wait", 0, 20, 1),
            ev("osd.op", 15, 35, 2, owner="client"),    # 5 ms overlap
        ]
        rec = decompose(spans)
        assert rec["total_s"] == pytest.approx(0.050)
        assert rec["phases"]["queue"] == pytest.approx(0.020)
        assert rec["phases"]["other"] == pytest.approx(0.030)

    def test_child_clipped_to_parent(self):
        """A child reaching past its parent's end (late async span)
        charges only the contained part — the invariant survives."""
        spans = [
            ev("client.op", 0, 40, 1, op_class="client"),
            ev("pipeline.complete", 30, 30, 2, 1),      # runs past root
        ]
        rec = decompose(spans)
        assert rec["total_s"] == pytest.approx(0.040)
        assert rec["phases"]["device"] == pytest.approx(0.010)
        assert rec["phases"]["other"] == pytest.approx(0.030)

    def test_explicit_phase_arg_wins_over_registry(self):
        spans = [ev("client.op", 0, 10, 1, phase="retry",
                    op_class="client")]
        rec = decompose(spans)
        assert rec["phases"]["retry"] == pytest.approx(0.010)

    def test_unknown_span_lands_in_other_and_is_counted(self):
        unmapped = {}
        rec = decompose([ev("mystery.span", 0, 5, 1)], unmapped=unmapped)
        assert rec["phases"]["other"] == pytest.approx(0.005)
        assert unmapped == {"mystery.span": 1}

    def test_empty_trace_is_none(self):
        assert decompose([]) is None


class TestPhaseRegistry:
    def test_bus_msgtype_prefix_is_wire_but_daemon_spans_are_not(self):
        assert phase_for("osd.ECSubWrite") == "wire"
        assert phase_for("osd.ECSubReadReply") == "wire"
        assert phase_for("rpc.put") == "wire"
        assert phase_for("osd.op") == "other"
        assert phase_for("osd.recovery") == "other"
        assert phase_for("osd.queue_wait") == "queue"

    def test_retry_family(self):
        for name in ("net.resend", "client.op_retry",
                     "pipeline.host_fallback", "client.backoff_resend"):
            assert phase_for(name) == "retry", name

    def test_declare_extends_registry(self):
        critpath.declare("my.new_span", "device")
        try:
            assert phase_for("my.new_span") == "device"
            assert critpath.is_declared("my.new_span")
        finally:
            del critpath.SPAN_PHASES["my.new_span"]
        with pytest.raises(ValueError):
            critpath.declare("bad", "not_a_phase")

    def test_every_registry_phase_is_canonical(self):
        assert set(critpath.SPAN_PHASES.values()) <= set(PHASES)


class TestLedger:
    def test_fold_dedup_and_summary(self):
        tr = Tracer()
        led = CritPathLedger(name="t", capacity=16)
        try:
            for i in range(3):
                with tr.activate(tr.new_trace("client")):
                    with tr.span("client.op"):
                        with tr.span("codec.encode"):
                            time.sleep(0.001)
            assert led.refresh(tr) == 3
            assert led.refresh(tr) == 0            # each trace folds ONCE
            s = led.class_summary("client")
            assert s["ops"] == 3
            assert sum(s["phases"].values()) == pytest.approx(1.0,
                                                              abs=0.01)
            assert s["phases"]["device"] > 0.5
            assert led.phase_seconds()["client"]["device"] > 0
        finally:
            led.close()

    def test_midflight_fold_amended_when_trace_grows(self):
        """A refresh that races an in-flight op (e.g. a prometheus
        scrape between the queue-wait event and the root span closing)
        folds the partial tree; the NEXT refresh after the root closes
        must amend the record in place — full wall time, no duplicate
        record, cumulative phase seconds corrected by delta."""
        tr = Tracer()
        led = CritPathLedger(name="amend")
        try:
            ctx = tr.new_trace("client")
            tr.complete("osd.queue_wait", time.time(), 0.002, ctx=ctx)
            assert led.refresh(tr) == 1          # truncated fold
            s = led.class_summary("client")
            assert s["ops"] == 1
            assert s["phase_ms"]["queue"] == pytest.approx(2.0, rel=0.2)
            # the op's root work completes afterwards
            with tr.activate(ctx):
                with tr.span("osd.op", owner="client"):
                    time.sleep(0.005)
            assert led.refresh(tr) == 1          # amended, not re-added
            s = led.class_summary("client")
            assert s["ops"] == 1, "amendment must not duplicate"
            assert s["phase_ms"]["other"] > 0    # osd.op self time now in
            assert led.phase_seconds()["client"]["other"] > 0
            assert led.refresh(tr) == 0          # settled: nothing new
        finally:
            led.close()

    def test_bounded_records(self):
        led = CritPathLedger(name="b", capacity=8)
        try:
            for i in range(50):
                led.ingest("client", 0.001 * (i + 1), {"device": 0.001})
            assert len(led.records("client")) == 8
            assert led.folded == 50
        finally:
            led.close()

    def test_background_class_attribution(self):
        tr = Tracer()
        led = CritPathLedger(name="bg")
        try:
            with tr.activate(tr.new_trace("bg_scrub")):
                with tr.span("osd.scrub", owner="scrub"):
                    time.sleep(0.001)
            led.refresh(tr)
            assert led.classes() == ["scrub"]
        finally:
            led.close()

    def test_render_attribution_shape(self):
        led = CritPathLedger(name="r")
        try:
            led.ingest("client", 0.040,
                       {"batch_delay": 0.025, "device": 0.010,
                        "wire": 0.005})
            lines = render_attribution(led.snapshot())
            assert len(lines) == 1
            assert lines[0].startswith("client p99 = 40.0 ms")
            assert "62% batch_delay" in lines[0] or \
                "63% batch_delay" in lines[0]
        finally:
            led.close()

    def test_group_traces_drops_untraced(self):
        events = [ev("a", 0, 1, 1, trace=7),
                  {"name": "b", "ph": "X", "ts": 0, "dur": 1}]
        grouped = group_traces(events)
        assert list(grouped) == [7]


class TestUnifiedPercentile:
    def test_nearest_rank_definition(self):
        s = [1.0, 2.0, 3.0, 4.0]
        assert nearest_rank(s, 50) == 2.0
        assert nearest_rank(s, 99) == 4.0
        assert nearest_rank(s, 100) == 4.0
        assert nearest_rank(s, 0) == 1.0
        assert nearest_rank([], 99) == 0.0
        assert percentile([3.0, 1.0, 2.0], 50) == 2.0

    def test_workload_and_trace_report_share_the_definition(self):
        """The two once-deliberately-duplicated copies now ARE the
        shared helper: identical answers on an awkward distribution."""
        from ceph_tpu.exec.workload import percentile as wl_pctl
        spec = importlib.util.spec_from_file_location(
            "trace_report_pctl", ROOT / "tools" / "trace_report.py")
        trace_report = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(trace_report)
        vals = [0.1, 5.0, 5.0, 7.5, 100.0, 0.2, 3.3]
        for q in (0, 1, 50, 95, 99, 100):
            assert wl_pctl(sorted(vals), q) == \
                trace_report.percentile_us(vals, q), q

    def test_ast_guard_no_local_percentile_redefinitions(self):
        """No file but common/percentile.py may define a function named
        percentile/percentile_us/nearest_rank — the drift that made
        ts_report's copy silently diverge to floor-index.  Thin wrapper
        over the ``percentile-redef`` rule (ISSUE 15)."""
        import ceph_tpu.analysis as A
        offenders = [f.render() for f in A.run_rules(
            A.default_index(), ("percentile-redef",))]
        assert not offenders, (
            "local percentile redefinitions (use "
            "ceph_tpu/common/percentile.py):\n" + "\n".join(offenders))
