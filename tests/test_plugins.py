"""Plugin layer: interface roundtrips, profile validation, registry load
paths (including the deliberately-broken plugins, mirroring
src/test/erasure-code/TestErasureCodePlugin*.cc and the per-plugin
TestErasureCode*.cc roundtrip strategy)."""
import os

import numpy as np
import pytest

from ceph_tpu.plugins import ErasureCodePluginRegistry
from ceph_tpu.plugins.plugin_jax_rs import ErasureCodeJaxRS

BROKEN_DIR = os.path.join(os.path.dirname(__file__), "broken_plugins")


@pytest.fixture
def registry():
    reg = ErasureCodePluginRegistry()  # fresh, not the singleton
    return reg


def _payload(n=4000, seed=0):
    return np.random.default_rng(seed).integers(0, 256, n, dtype=np.uint8).tobytes()


# -- registry ---------------------------------------------------------------

def test_registry_singleton():
    a = ErasureCodePluginRegistry.instance()
    b = ErasureCodePluginRegistry.instance()
    assert a is b


def test_factory_loads_and_instantiates(registry):
    ec = registry.factory("jax_rs", "", {"k": "4", "m": "2", "device": "numpy"})
    assert ec.get_chunk_count() == 6
    assert ec.get_data_chunk_count() == 4
    assert ec.get_profile()["plugin"] == "jax_rs"


def test_factory_unknown_plugin(registry):
    with pytest.raises(FileNotFoundError):
        registry.factory("no_such_plugin", "", {})


def test_factory_profile_plugin_mismatch(registry):
    with pytest.raises(ValueError):
        registry.factory("jax_rs", "", {"plugin": "other", "k": "4", "m": "2"})


def test_double_add_rejected(registry):
    registry.load("xor")
    with pytest.raises(ValueError):
        registry.load("xor").factory  # second load -> add collides
        registry.add("xor", object())


def test_preload(registry):
    registry.preload(["jax_rs", "xor"])
    assert registry.get("jax_rs") is not None
    assert registry.get("xor") is not None
    registry.preload(["jax_rs"])  # idempotent


@pytest.mark.parametrize("name,err,match", [
    ("missing_version", RuntimeError, "version"),
    ("missing_entry_point", RuntimeError, "init"),
    ("fail_to_initialize", RuntimeError, "ESRCH"),
    ("fail_to_register", RuntimeError, "register"),
    ("wrong_version", RuntimeError, "0.0.0"),
])
def test_broken_plugins(registry, name, err, match):
    with pytest.raises(err, match=match):
        registry.load(name, BROKEN_DIR)


def test_load_from_missing_directory(registry):
    with pytest.raises(FileNotFoundError):
        registry.load("whatever", "/nonexistent/dir")


# -- jax_rs plugin ----------------------------------------------------------

@pytest.mark.parametrize("technique", ["reed_sol_van", "vandermonde", "cauchy"])
def test_jax_rs_encode_decode_roundtrip(registry, technique):
    profile = {"k": "4", "m": "2", "technique": technique, "device": "numpy"}
    ec = registry.factory("jax_rs", "", profile)
    data = _payload()
    want = set(range(6))
    encoded = ec.encode(want, data)
    assert set(encoded) == want
    chunk_size = ec.get_chunk_size(len(data))
    assert chunk_size * 4 >= len(data)
    assert all(len(v) == chunk_size for v in encoded.values())
    # erase two chunks, decode, compare content (TestErasureCodeJerasure.cc:80-135)
    available = {i: encoded[i] for i in want if i not in (0, 1)}
    decoded = ec.decode({0, 1}, available)
    np.testing.assert_array_equal(decoded[0], encoded[0])
    np.testing.assert_array_equal(decoded[1], encoded[1])
    # full payload recovery
    assert ec.decode_concat(available)[:len(data)] == data


def test_jax_rs_defaults(registry):
    ec = registry.factory("jax_rs", "", {"device": "numpy"})
    assert ec.get_data_chunk_count() == 7   # jerasure defaults k=7 m=3
    assert ec.get_coding_chunk_count() == 3
    assert ec.get_profile()["k"] == "7"


def test_jax_rs_rejects_bad_profile(registry):
    for bad in ({"k": "1", "m": "1"}, {"k": "4", "m": "0"},
                {"k": "4", "m": "2", "w": "16"},
                {"k": "4", "m": "2", "technique": "liberation"},
                {"k": "4", "m": "2", "device": "gpu"}):
        with pytest.raises(ValueError):
            registry.factory("jax_rs", "", dict(bad))


def test_jax_rs_chunk_mapping(registry):
    profile = {"k": "2", "m": "1", "mapping": "D_D", "device": "numpy"}
    ec = registry.factory("jax_rs", "", profile)
    assert ec.get_chunk_mapping() == [0, 2, 1]
    data = _payload(1000)
    encoded = ec.encode(set(range(3)), data)
    # chunk 1 holds parity now; erasing it and decoding data still works
    available = {0: encoded[0], 2: encoded[2]}
    assert ec.decode_concat(available)[:1000] == data


def test_jax_rs_minimum_to_decode(registry):
    ec = registry.factory("jax_rs", "", {"k": "4", "m": "2", "device": "numpy"})
    # all wanted available: want itself
    assert set(ec.minimum_to_decode({0, 1}, {0, 1, 2, 3})) == {0, 1}
    # missing chunk: first k available
    got = ec.minimum_to_decode({0}, {1, 2, 3, 4, 5})
    assert set(got) == {1, 2, 3, 4}
    assert got[1] == [(0, 1)]
    with pytest.raises(IOError):
        ec.minimum_to_decode({0}, {1, 2, 3})
    assert ec.minimum_to_decode_with_cost({0}, {1: 1, 2: 1, 3: 1, 4: 9}) == {1, 2, 3, 4}


def test_jax_rs_padding_edge_cases(registry):
    ec = registry.factory("jax_rs", "", {"k": "4", "m": "2", "device": "numpy"})
    for n in (1, 127, 128, 129, 511, 512, 513, 4096):
        data = _payload(n, seed=n)
        encoded = ec.encode(set(range(6)), data)
        available = {i: encoded[i] for i in (2, 3, 4, 5)}
        assert ec.decode_concat(available)[:n] == data, f"n={n}"


# -- xor plugin -------------------------------------------------------------

def test_xor_roundtrip(registry):
    ec = registry.factory("xor", "", {"k": "3"})
    data = _payload(999)
    encoded = ec.encode(set(range(4)), data)
    for lost in range(4):
        available = {i: v for i, v in encoded.items() if i != lost}
        decoded = ec.decode({lost}, available)
        np.testing.assert_array_equal(decoded[lost], encoded[lost])
    with pytest.raises(IOError):
        ec.decode({0, 1}, {i: encoded[i] for i in (2, 3)})
    with pytest.raises(ValueError):
        registry.factory("xor", "", {"k": "2", "m": "2"})


# -- jerasure / isa compat plugins ------------------------------------------

def test_jerasure_compat(registry):
    ec = registry.factory("jerasure", "",
                          {"k": "4", "m": "2", "technique": "reed_sol_van",
                           "device": "numpy"})
    data = _payload()
    encoded = ec.encode(set(range(6)), data)
    available = {i: encoded[i] for i in (1, 2, 4, 5)}
    assert ec.decode_concat(available)[:len(data)] == data
    assert ec.get_profile()["technique"] == "reed_sol_van"
    # RAID6 technique forces m=2
    r6 = registry.factory("jerasure", "",
                          {"k": "4", "m": "3", "technique": "reed_sol_r6_op",
                           "device": "numpy"})
    assert r6.get_coding_chunk_count() == 2
    # bitmatrix techniques route to the packet-layout GF(2) codec
    # (full coverage in tests/test_bitmatrix.py)
    lib = registry.factory("jerasure", "", {"k": "4", "m": "2",
                                            "technique": "liber8tion",
                                            "packetsize": "8",
                                            "device": "numpy"})
    assert lib.get_profile()["technique"] == "liber8tion"


def test_isa_compat(registry):
    ec = registry.factory("isa", "", {"k": "8", "m": "4", "device": "numpy"})
    data = _payload(8192)
    encoded = ec.encode(set(range(12)), data)
    available = {i: encoded[i] for i in range(12) if i not in (0, 5, 9, 11)}
    assert ec.decode_concat(available)[:8192] == data
    # vandermonde envelope (ErasureCodeIsa.cc:323-364)
    with pytest.raises(ValueError):
        registry.factory("isa", "", {"k": "22", "m": "4"})
    # cauchy has no such limit
    registry.factory("isa", "", {"k": "22", "m": "4", "technique": "cauchy",
                                 "device": "numpy"})
