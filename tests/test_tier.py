"""The tier/ subsystem: promote/proxy/flush/evict over (cache, base)
pool bindings (reference: src/osd/PrimaryLogPG.cc maybe_handle_cache /
agent_work, src/osd/TierAgentState.h; the mon's `osd tier add` +
`cache-mode` surface is MiniCluster.create_tier).

Seed-level hit-set and xattr-dirty mechanics are covered by
test_tiering.py; this file pins the SERVICE invariants the ISSUE names:
promote→hit, evict→miss→re-promote, dirty flush ordering, writeback
durability across a kill -9 restart with zero acked-write loss, the
live-tunable hit_set_* pool params, and the TIER_* health checks."""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.common import Context
from ceph_tpu.osd.osd_ops import ObjectOperation


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def _mk(tmp_path=None, **conf):
    cct = Context(overrides=conf) if conf else None
    c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                    cct=cct, data_dir=tmp_path)
    base = c.create_ec_pool("base", {"k": "2", "m": "1",
                                     "device": "numpy"}, pg_num=4)
    cache = c.create_replicated_pool(
        "cache", size=3, pg_num=4,
        params={"hit_set_count": "2", "hit_set_period": "8"})
    return c, cache, base


@pytest.fixture
def tiered():
    c, cache, base = _mk(tier_promote_min_recency=1)
    svc = c.create_tier(cache, base)
    yield c, svc, cache, base
    c.shutdown()


class TestReadPath:
    def test_miss_proxies_then_promotes_then_hits(self, tiered):
        c, svc, cache, base = tiered
        payload = _data(3000, 1)
        c.operate(base, "obj", ObjectOperation().write_full(payload))
        # cold read: not resident -> miss, proxied from the EC base,
        # promoted (min_recency=1: the miss's own hit-set record counts)
        assert svc.read("obj") == payload
        ctr = svc.stats()["counters"]
        assert (ctr["miss"], ctr["proxy_read"], ctr["promote"]) == (1, 1, 1)
        assert "obj" in svc.resident()
        # re-read: a HIT, the base pool is never touched again
        assert svc.read("obj") == payload
        ctr = svc.stats()["counters"]
        assert ctr["hit"] == 1 and ctr["proxy_read"] == 1

    def test_single_cold_read_does_not_promote_at_recency_2(self):
        c, cache, base = _mk()          # default min_recency = 2
        svc = c.create_tier(cache, base)
        try:
            c.operate(base, "o", ObjectOperation().write_full(b"x" * 64))
            svc.read("o")               # recency 1: proxy only
            ctr = svc.stats()["counters"]
            assert ctr["promote"] == 0 and ctr["promote_skip"] == 1
            assert "o" not in svc.resident()
            # age the hit set into the archive ring, then re-read:
            # current + newest archive both contain it -> recency 2
            svc.agent.age()
            svc.read("o")
            assert svc.stats()["counters"]["promote"] == 1
            assert "o" in svc.resident()
        finally:
            c.shutdown()

    def test_absent_everywhere_raises_enoent(self, tiered):
        _c, svc, _cache, _base = tiered
        with pytest.raises(IOError):
            svc.read("never-written")

    def test_evict_then_miss_then_repromote(self, tiered):
        c, svc, cache, base = tiered
        payload = _data(900, 3)
        c.operate(base, "e", ObjectOperation().write_full(payload))
        assert svc.read("e") == payload             # promoted
        svc.evict("e")
        with pytest.raises(IOError):
            c.operate(cache, "e", ObjectOperation().stat())
        assert svc.read("e") == payload             # miss -> re-promote
        ctr = svc.stats()["counters"]
        assert ctr["miss"] == 2 and ctr["promote"] == 2
        c.operate(cache, "e", ObjectOperation().stat())


class TestWritePath:
    def test_writeback_absorbs_then_flush_orders_base_before_clean(
            self, tiered):
        c, svc, cache, base = tiered
        payload = _data(2500, 7)
        svc.write("w", payload)
        assert svc.is_dirty("w")
        with pytest.raises(IOError):          # not yet on the base
            c.operate(base, "w", ObjectOperation().stat())
        svc.flush("w")
        # ordering invariant: by the time the dirty mark is gone the
        # base MUST hold the bytes (flush commits base-first)
        assert not svc.is_dirty("w")
        r = c.operate(base, "w", ObjectOperation().read(0, 0))
        assert bytes(r.ops[0].outdata)[:len(payload)] == payload
        # and a re-flush of a clean object is idempotent (the crash
        # window between base write and mark clear re-runs flush)
        svc.flush("w")
        r = c.operate(base, "w", ObjectOperation().read(0, 0))
        assert bytes(r.ops[0].outdata)[:len(payload)] == payload

    def test_readonly_mode_refuses_writes(self):
        c, cache, base = _mk()
        svc = c.create_tier(cache, base, mode="readonly")
        try:
            with pytest.raises(IOError) as ei:
                svc.write("x", b"nope")
            assert ei.value.errno == -30          # EROFS
            # reads still proxy from the base
            c.operate(base, "x", ObjectOperation().write_full(b"ro"))
            assert svc.read("x")[:2] == b"ro"
        finally:
            c.shutdown()

    def test_proxy_mode_forwards_and_invalidates(self):
        c, cache, base = _mk(tier_promote_min_recency=1)
        svc = c.create_tier(cache, base, mode="proxy")
        try:
            c.operate(base, "p", ObjectOperation().write_full(b"v1" * 32))
            assert svc.read("p") == b"v1" * 32    # promoted copy resident
            svc.write("p", b"v2" * 32)            # forwarded to the base
            r = c.operate(base, "p", ObjectOperation().read(0, 0),
                          internal=True)
            assert bytes(r.ops[0].outdata)[:64] == b"v2" * 32
            # the stale cached copy was dropped, not served
            ctr = svc.stats()["counters"]
            assert ctr["proxy_write"] == 1 and ctr["invalidate"] == 1
            assert svc.read("p") == b"v2" * 32
        finally:
            c.shutdown()


class TestAgent:
    def test_flush_hysteresis_and_heat_ranked_evict(self, tiered):
        c, svc, cache, base = tiered
        conf = c.cct.conf
        conf.set("tier_target_max_objects", 5)
        conf.set("tier_dirty_ratio_high", 0.5)
        conf.set("tier_dirty_ratio_low", 0.25)
        for i in range(4):
            svc.write(f"d{i}", _data(300 + i, i))
        stats = svc.agent.tick()
        # 4/5 dirty > 0.5 high: flush down to <= 0.25 low (1 left), not 0
        assert stats["flushes"] == 3
        assert stats["dirty_ratio"] <= 0.25
        assert svc.agent.backlog_ticks == 0
        # now keep d0 hot each period while the rest age cold: agent
        # passes are the clock (hit sets are op-count-periodic), so
        # ticks with age=True rotate heat out of the count=2 ring
        conf.set("tier_full_ratio", 0.1)
        total = {"evictions": 0, "skipped_hot": 0}
        for _ in range(4):
            assert svc.read("d0") == _data(300, 0)
            stats = svc.agent.tick(age=True)
            total["evictions"] += stats["evictions"]
            total["skipped_hot"] += stats["skipped_hot"]
        assert total["evictions"] >= 3            # the cold ones left
        assert total["skipped_hot"] >= 1          # the hot one was spared
        assert svc.resident() == ["d0"]
        # evicted objects read back through the tier (base holds them)
        assert svc.read("d2") == _data(302, 2)

    def test_hard_full_overrides_hot_skip(self, tiered):
        c, svc, _cache, _base = tiered
        c.cct.conf.set("tier_target_max_objects", 1)
        c.cct.conf.set("tier_full_ratio", 0.5)
        svc.write("h0", b"a" * 64)
        svc.write("h1", b"b" * 64)
        svc.read("h0"), svc.read("h1")            # everything is hot
        stats = svc.agent.tick(max_ops=16)
        assert stats["evictions"] >= 1            # at hard capacity the
        assert len(svc.resident()) <= 1           # agent stops being polite


class TestWritebackDurability:
    def test_kill9_restart_loses_no_acked_write(self, tmp_path):
        """The writeback promise: an acked absorbed write IS durable.
        Every transaction's WAL record is flushed to the OS before the
        ack (backend/filestore.py _append_wal), so abandoning the
        process image wholesale — no shutdown, no checkpoint, the
        kill -9 analog — and rebooting from the directory must replay
        every acked write, still dirty, and flushable to the base."""
        c1, cache, base = _mk(tmp_path)
        svc1 = c1.create_tier(cache, base)
        payloads = {f"k{i}": _data(1200 + i, 40 + i) for i in range(6)}
        for oid, p in payloads.items():
            svc1.write(oid, p)                    # acked writebacks
        del svc1, c1                              # kill -9: no shutdown

        c2 = MiniCluster.load(tmp_path)
        cache2, base2 = c2.pool_ids["cache"], c2.pool_ids["base"]
        svc2 = c2.create_tier(cache2, base2)
        try:
            for oid, p in payloads.items():
                assert svc2.read(oid) == p, f"acked write {oid} lost"
                assert svc2.is_dirty(oid)         # dirty mark rode the WAL
            # and the replayed dirty set flushes through the EC base
            for oid in payloads:
                svc2.flush(oid)
            for oid, p in payloads.items():
                r = c2.operate(base2, oid, ObjectOperation().read(0, 0))
                assert bytes(r.ops[0].outdata)[:len(p)] == p
        finally:
            c2.shutdown()


class TestPoolSetLiveTune:
    def test_hit_set_params_rearm_live_and_persist(self, tmp_path):
        c, cache, _base = _mk(tmp_path)
        g = c.pg_group(cache, "o")
        assert g.engine.hit_set_params["period"] == 8
        c.pool_set(cache, "hit_set_period", 16)
        c.pool_set(cache, "hit_set_count", 4)
        c.pool_set(cache, "hit_set_target_size", 512)
        for gg in c.pools[cache]["pgs"].values():
            assert gg.engine.hit_set_params["period"] == 16
            assert gg.engine.hit_set_params["count"] == 4
        # accumulation continues under the new geometry
        for i in range(20):
            c.operate(cache, "o", ObjectOperation().write_full(b"x"))
        assert g.engine.object_temperature("o") >= 1
        c.shutdown()
        # the retune is a POOL property: it survives restart
        c2 = MiniCluster.load(tmp_path)
        g2 = c2.pg_group(c2.pool_ids["cache"], "o")
        assert g2.engine.hit_set_params["period"] == 16
        assert g2.engine.hit_set_params["count"] == 4
        c2.shutdown()

    def test_hit_set_count_zero_disarms(self):
        c, cache, _base = _mk()
        try:
            c.pool_set(cache, "hit_set_count", 0)
            for g in c.pools[cache]["pgs"].values():
                assert g.engine.hit_set_params is None
            c.operate(cache, "o", ObjectOperation().write_full(b"x"))
            assert c.pg_group(cache, "o").engine \
                .object_temperature("o") == 0
            c.pool_set(cache, "hit_set_count", 2)     # re-arm
            c.operate(cache, "o", ObjectOperation().write_full(b"x"))
            assert c.pg_group(cache, "o").engine \
                .object_temperature("o") >= 1
        finally:
            c.shutdown()

    def test_unknown_pool_raises(self):
        c, _cache, _base = _mk()
        try:
            with pytest.raises(KeyError):
                c.pool_set(999, "hit_set_count", 1)
        finally:
            c.shutdown()


class TestTierHealth:
    def test_tier_full_raises_and_clears(self, tiered):
        c, svc, _cache, _base = tiered
        c.cct.conf.set("tier_target_max_objects", 2)
        c.cct.conf.set("tier_full_ratio", 0.5)
        svc.write("f0", b"x" * 64)
        svc.write("f1", b"y" * 64)
        h = c.health()
        assert "TIER_FULL" in h["checks"]
        assert h["status"] != "HEALTH_OK"
        # one funded pass drains it: at hard capacity the agent evicts
        # hot objects too, and drives residency STRICTLY below the
        # watermark so the check cannot stay latched
        svc.agent.tick(max_ops=16)
        assert "TIER_FULL" not in c.health()["checks"]

    def test_flush_backlog_raises_and_clears(self, tiered):
        c, svc, _cache, _base = tiered
        c.cct.conf.set("tier_target_max_objects", 4)
        c.cct.conf.set("tier_dirty_ratio_high", 0.25)
        for i in range(3):
            svc.write(f"b{i}", b"z" * 32)
        # two zero-budget passes end over the high watermark: a STREAK
        svc.agent.tick(max_ops=0)
        assert "TIER_FLUSH_BACKLOG" not in c.health()["checks"]
        svc.agent.tick(max_ops=0)
        assert "TIER_FLUSH_BACKLOG" in c.health()["checks"]
        # a funded pass drains the dirty set and the check clears
        svc.agent.tick(max_ops=16)
        assert svc.agent.backlog_ticks == 0
        assert "TIER_FLUSH_BACKLOG" not in c.health()["checks"]


class TestFrontendAdmission:
    def test_overloaded_shard_sheds_tier_hits(self):
        from ceph_tpu.msg.frontend import FrontendBusy, ShardedFrontend

        class BusyEngine:
            def depths(self):
                return {"_total": 10_000}
        c, cache, base = _mk(tier_promote_min_recency=1)
        fe = ShardedFrontend({0: BusyEngine()}, queue_limit=4)
        svc = c.create_tier(cache, base, frontend=fe)
        try:
            svc.write("s", b"q" * 16)   # resident (writes skip admission)
            # the hit path is admission-gated: a saturated shard sheds
            # it with EBUSY instead of letting "free" reads bypass
            # overload control
            with pytest.raises(FrontendBusy):
                svc.read("s")
        finally:
            c.shutdown()


class TestAdminSurfaces:
    def test_tier_status_and_heat_top(self, tiered):
        c, svc, cache, base = tiered
        c.operate(base, "hot", ObjectOperation().write_full(b"h" * 32))
        for _ in range(3):
            svc.read("hot")
        st = c.cct.admin_socket.call("tier status")
        s = st[str(cache)]
        assert s["mode"] == "writeback" and s["base_pool"] == base
        assert s["counters"]["promote"] == 1
        assert 0.0 < s["hit_rate"] < 1.0
        top = c.cct.admin_socket.call("heat top", n=5)["top"]
        assert any(r["oid"] == "hot" and r["temperature"] >= 1
                   for r in top)
        assert len(top) <= 5

    def test_double_tier_binding_refused(self, tiered):
        c, _svc, cache, base = tiered
        with pytest.raises(ValueError):
            c.create_tier(cache, base)

    def test_prometheus_tier_families_render(self, tiered):
        c, svc, _cache, base = tiered
        c.operate(base, "m", ObjectOperation().write_full(b"m" * 16))
        svc.read("m")
        from ceph_tpu.mgr.prometheus import render
        text = render(c.cct)
        assert "ceph_tpu_tier_ops" in text
        assert 'op="promote"' in text
        assert "ceph_tpu_tier_state" in text
