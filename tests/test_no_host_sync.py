"""Guard: serving/recovery hot paths never sync with the device.

Thin wrapper over the ``no-host-sync`` rule in
:mod:`ceph_tpu.analysis.rules_guards` (ISSUE 15 moved the walker into
the shared engine); semantics unchanged — ``exec/`` and ``recovery/``
must not import jax or call ``device_get`` / ``block_until_ready`` /
``jnp.asarray``; the completion boundary lives in ops/pipeline.py.
"""
import ceph_tpu.analysis as A


def test_no_device_sync_in_serving_or_recovery():
    offenders = [f.render() for f in A.run_rules(
        A.default_index(), ("no-host-sync",))]
    assert not offenders, (
        "device-runtime touches in serving/recovery hot paths — route "
        "them through ops/pipeline.py's completion boundary:\n"
        + "\n".join(offenders))


def test_guard_catches_a_violation():
    """The rule itself must keep working: a synthetic offender trips on
    every shape it claims to enforce."""
    bad = ("import jax\n"
           "import jax.numpy as jnp\n"
           "from jax import block_until_ready\n"
           "def f(x):\n"
           "    y = jnp.asarray(x)\n"
           "    jax.device_get(y)\n"
           "    return y.block_until_ready()\n")
    kinds = {f.message for f in A.run_rule_on_sources(
        "no-host-sync", {"bad.py": bad})}
    assert "import jax" in kinds
    assert "import jax.numpy" in kinds
    assert "from jax import ..." in kinds
    assert "jnp.asarray(...)" in kinds
    assert "device_get(...)" in kinds
    assert "block_until_ready(...)" in kinds
