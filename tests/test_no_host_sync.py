"""Guard: serving/recovery hot paths never sync with the device.

The codec pipeline's whole point is that ``exec/`` and ``recovery/``
stay on the HOST side of the boundary: they pack batches and hand them
to ``ceph_tpu/ops/pipeline.py``, and the ``jax.device_get`` /
``block_until_ready`` happens only inside that module's completion
boundary.  A per-op ``device_get`` (or a decode-matrix ``jnp.asarray``
re-upload) in these layers silently re-serialises host packing against
device compute — the exact transfer stall ISSUE-5 removed.

AST-walked (the ``test_no_bare_time.py`` pattern, upgraded from regex so
comments/docstrings can mention the names):

- no ``import jax`` / ``import jax.numpy`` / ``from jax import ...`` —
  these layers have no business talking to the device runtime at all;
- no call to an attribute or name ``device_get``, ``block_until_ready``,
  or ``asarray`` on a ``jnp``/``jax.numpy`` alias (the upload-side sync).

``np``/host numpy stays allowed — packing IS their job.
"""
import ast
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
SCAN_DIRS = ("ceph_tpu/exec", "ceph_tpu/recovery")

# path -> why a device-runtime touch is legitimate there (none today: the
# completion boundary lives in ceph_tpu/ops/pipeline.py, outside the scan)
ALLOWLIST: dict[str, str] = {}

_FORBIDDEN_CALLS = {"device_get", "block_until_ready"}
_JAX_MODULES = ("jax",)


class _Visitor(ast.NodeVisitor):
    def __init__(self):
        self.offenders: list[tuple[int, str]] = []
        self._jnp_aliases: set[str] = set()

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            root = alias.name.split(".")[0]
            if root in _JAX_MODULES:
                self.offenders.append(
                    (node.lineno, f"import {alias.name}"))
            if alias.name in ("jax.numpy",):
                self._jnp_aliases.add(alias.asname or "jax")
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        root = (node.module or "").split(".")[0]
        if root in _JAX_MODULES:
            self.offenders.append(
                (node.lineno, f"from {node.module} import ..."))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        name = None
        if isinstance(fn, ast.Attribute):
            name = fn.attr
            if name == "asarray" and isinstance(fn.value, ast.Name) \
                    and fn.value.id in ("jnp", *self._jnp_aliases):
                self.offenders.append(
                    (node.lineno, f"{fn.value.id}.asarray(...)"))
        elif isinstance(fn, ast.Name):
            name = fn.id
        if name in _FORBIDDEN_CALLS:
            self.offenders.append((node.lineno, f"{name}(...)"))
        self.generic_visit(node)


def test_no_device_sync_in_serving_or_recovery():
    offenders = []
    for sub in SCAN_DIRS:
        for path in sorted((ROOT / sub).rglob("*.py")):
            rel = path.relative_to(ROOT).as_posix()
            if rel in ALLOWLIST:
                continue
            tree = ast.parse(path.read_text(), filename=rel)
            v = _Visitor()
            v.visit(tree)
            offenders.extend(f"{rel}:{lineno}: {what}"
                             for lineno, what in v.offenders)
    assert not offenders, (
        "device-runtime touches in serving/recovery hot paths — route "
        "them through ops/pipeline.py's completion boundary (or extend "
        "the allowlist with a justification):\n" + "\n".join(offenders))


def test_allowlist_entries_still_exist():
    for rel in ALLOWLIST:
        assert (ROOT / rel).exists(), f"stale allowlist entry: {rel}"


def test_guard_catches_a_violation(tmp_path):
    """The guard itself must keep working: a synthetic offender trips on
    every rule it claims to enforce."""
    bad = ("import jax\n"
           "import jax.numpy as jnp\n"
           "from jax import block_until_ready\n"
           "def f(x):\n"
           "    y = jnp.asarray(x)\n"
           "    jax.device_get(y)\n"
           "    return y.block_until_ready()\n")
    v = _Visitor()
    v.visit(ast.parse(bad))
    kinds = {what for _ln, what in v.offenders}
    assert "import jax" in kinds
    assert "from jax import ..." in kinds
    assert "jnp.asarray(...)" in kinds
    assert "device_get(...)" in kinds
    assert "block_until_ready(...)" in kinds
