"""Async stack under transport faults (ISSUE 14 satellite 3).

The ``failure/`` transport planes — truncated frames, connection
resets, black-holed requests, delays — driven against the reactor
transport and the mux client: the frame state machine must survive any
recv chunking, and the session layer must deliver ZERO acked-op loss
(every put whose ack arrived reads back) with clean reconnects, exactly
the contract tests/test_chaos.py pins for the threaded client.
"""
import threading

import numpy as np
import pytest

from ceph_tpu.backend.wire import TAG_MESSAGE, WireError, frame_encode
from ceph_tpu.common import Context
from ceph_tpu.failure import FaultInjector, FaultPlan, TransportFaults
from ceph_tpu.msg import MuxClient, StreamParser


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


# -- frame state machine vs hostile byte delivery ----------------------------

class TestParserUnderFaults:
    SECRET = b"f" * 32

    def _stream(self, n=8):
        return b"".join(
            frame_encode(TAG_MESSAGE, [bytes([i])] * 2 + [b"p" * (100 * i)],
                         secret=self.SECRET)
            for i in range(1, n + 1))

    def test_one_byte_at_a_time(self):
        """The pathological recv pattern: every frame reassembles."""
        blob = self._stream()
        sp = StreamParser(self.SECRET)
        tags = []
        for i in range(len(blob)):
            for tag, _segs in sp.feed(blob[i:i + 1]):
                tags.append(tag)
        assert tags == [TAG_MESSAGE] * 8
        assert sp.pending() == 0

    def test_reordered_partial_reads(self):
        """Chunk boundaries shuffled across frame boundaries (a frame's
        tail arriving fused with the next frame's head, in bursts):
        byte ORDER is TCP's to keep, boundary placement is not."""
        import random
        blob = self._stream()
        rng = random.Random(17)
        cuts = sorted(rng.sample(range(1, len(blob)), 40))
        pieces = [blob[a:b] for a, b in
                  zip([0] + cuts, cuts + [len(blob)])]
        # deliver in bursts of 1..4 pieces joined back-to-back
        sp = StreamParser(self.SECRET)
        got = 0
        i = 0
        while i < len(pieces):
            k = rng.randint(1, 4)
            got += len(sp.feed(b"".join(pieces[i:i + k])))
            i += k
        assert got == 8 and sp.pending() == 0

    def test_truncated_stream_yields_nothing_then_heals_on_reconnect(self):
        """A cut-off frame (mid-frame RST) parses to NOTHING — no
        partially-validated output — and a FRESH parser on the new
        connection replays the full frame cleanly."""
        frame = frame_encode(TAG_MESSAGE, [b"op-payload" * 50],
                             secret=self.SECRET)
        sp = StreamParser(self.SECRET)
        assert sp.feed(frame[:len(frame) // 2]) == []
        assert sp.pending() == len(frame) // 2
        # the transport closes on EOF; the resend rides a new parser
        sp2 = StreamParser(self.SECRET)
        out = sp2.feed(frame)
        assert len(out) == 1
        assert bytes(out[0][1][0]) == b"op-payload" * 50

    def test_garbage_after_truncation_is_detected(self):
        """Bytes resuming mid-frame after a truncation can't silently
        decode: the preamble crc refuses the misaligned stream."""
        frame = frame_encode(TAG_MESSAGE, [b"x" * 500],
                             secret=self.SECRET)
        sp = StreamParser(self.SECRET)
        sp.feed(frame[:60])
        with pytest.raises(WireError):
            # a fresh frame glued onto the cut — misaligned preamble
            sp.feed(frame)


# -- the mux stack over injected transport faults ----------------------------

def _served(tmp_path, plan, **overrides):
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.net import ClusterServer
    cct = Context(overrides={
        "ms_rpc_timeout": 6.0, "ms_rpc_retry_attempts": 8,
        "ms_reconnect_backoff_base": 0.01,
        "ms_reconnect_backoff_cap": 0.05, **overrides})
    c = MiniCluster(n_osds=3, osds_per_host=3, chunk_size=512,
                    cct=cct, data_dir=tmp_path)
    inj = c.inject_faults(plan)
    server = ClusterServer(c)
    server.inject_faults(inj)
    server.start()
    return c, server, inj, cct


class TestMuxTransportFaults:
    N_OPS = 24

    def _mux(self, server, tmp_path, cct):
        return MuxClient("127.0.0.1", server.port,
                         tmp_path / "client.admin.keyring", cct=cct,
                         n_conns=2)

    def _hammer(self, mux, tag):
        """Closed-loop puts across many sessions; returns the ACKED
        model {oid: data} (an unacked op may or may not have landed —
        only acked ones carry the zero-loss contract)."""
        sessions = [mux.session() for _ in range(8)]
        s0 = sessions[0]
        s0.call("mkpool", {"name": "p", "replicated": True, "size": 3},
                timeout=30.0)
        model = {}
        for i in range(self.N_OPS):
            oid = f"{tag}{i % 6}"
            data = _data(1536, seed=i)
            try:
                sessions[i % len(sessions)].call(
                    "put", {"pool": "p", "oid": oid, "data": data})
            except (ConnectionError, TimeoutError, IOError):
                continue                      # unacked: no contract
            model[oid] = data
        return model

    def _verify(self, mux, model):
        s = mux.session()
        for oid, want in sorted(model.items()):
            for attempt in range(6):
                try:
                    assert s.call("get", {"pool": "p", "oid": oid}) \
                        == want, oid
                    break
                except (ConnectionError, TimeoutError):
                    continue
            else:
                raise AssertionError(f"get {oid} never completed")

    def test_resets_zero_acked_loss_clean_reconnect(self, tmp_path):
        plan = FaultPlan(seed=5, transport=TransportFaults(
            reset_prob=0.10))
        c, server, inj, cct = _served(tmp_path, plan)
        mux = None
        try:
            mux = self._mux(server, tmp_path, cct)
            model = self._hammer(mux, "r")
            assert model, "no op was ever acked under resets"
            self._verify(mux, model)
            kinds = inj.summary()["planes"].get("transport", {})
            assert kinds.get("reset", 0) + kinds.get("recv_reset", 0) > 0
            assert mux.stats()["reconnects"] > 0, "no clean reconnect"
            assert mux.live_connections() >= 1
        finally:
            if mux is not None:
                mux.close()
            server.stop()
            c.shutdown()

    def test_blackholes_resend_and_dedup(self, tmp_path):
        """Swallowed requests heal by per-attempt resend; reqid dedup
        keeps the re-applied puts exactly-once on the server."""
        plan = FaultPlan(seed=9, transport=TransportFaults(
            blackhole_prob=0.10))
        c, server, inj, cct = _served(tmp_path, plan, ms_rpc_timeout=3.0)
        mux = None
        try:
            mux = self._mux(server, tmp_path, cct)
            model = self._hammer(mux, "b")
            assert model
            self._verify(mux, model)
            assert inj.summary()["planes"][
                "transport"].get("blackhole", 0) > 0
            assert mux.stats()["resends"] > 0
        finally:
            if mux is not None:
                mux.close()
            server.stop()
            c.shutdown()

    def test_truncated_replies_and_delays(self, tmp_path):
        """Cut frames + delays on the reply path: the client parser
        hits EOF mid-frame, reconnects, resends — nothing acked lost."""
        plan = FaultPlan(seed=4, transport=TransportFaults(
            truncate_prob=0.08, delay_prob=0.2, delay_ms=1.0))
        c, server, inj, cct = _served(tmp_path, plan)
        mux = None
        try:
            mux = self._mux(server, tmp_path, cct)
            model = self._hammer(mux, "t")
            assert model
            self._verify(mux, model)
            assert inj.summary()["planes"][
                "transport"].get("truncate", 0) > 0
        finally:
            if mux is not None:
                mux.close()
            server.stop()
            c.shutdown()

    def test_handshake_never_faulted(self, tmp_path):
        """reset_prob=1.0: post-auth frames always die, yet a fresh mux
        client can still dial and complete cephx — injection arms only
        after authentication, so reconnects always get back in."""
        plan = FaultPlan(seed=1, transport=TransportFaults(
            reset_prob=1.0))
        c, server, inj, cct = _served(tmp_path, plan)
        mux = None
        try:
            mux = self._mux(server, tmp_path, cct)
            mux.connect()
            assert mux.live_connections() >= 1
        finally:
            if mux is not None:
                mux.close()
            server.stop()
            c.shutdown()
