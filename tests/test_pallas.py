"""Pallas fused GF kernel: bit-exact vs the numpy field math (interpret
mode on CPU; the same kernel compiles for TPU where it is the auto-routed
encode path)."""
import numpy as np
import pytest

from ceph_tpu.gf import matrix as gfm
from ceph_tpu.ops import rs_kernels
from ceph_tpu.ops.pallas_kernels import (expand_bits_plane_major,
                                         gf_apply_pallas)


@pytest.mark.parametrize("r,k,n,tile", [
    (4, 8, 2048, 512),       # even tiles
    (2, 4, 3000, 512),       # ragged tail -> padding path
    (3, 5, 512, 1024),       # single partial tile
    (1, 2, 256, 256),        # minimal shapes
])
def test_pallas_matches_field_math(r, k, n, tile):
    rng = np.random.default_rng(r * 100 + k)
    mat = rng.integers(0, 256, size=(r, k), dtype=np.uint8)
    data = rng.integers(0, 256, size=(k, n), dtype=np.uint8)
    got = np.asarray(gf_apply_pallas(mat, data, tile_n=tile, interpret=True))
    assert np.array_equal(got, gfm.gf_matmul(mat, data))


def test_pallas_matches_xla_bitslice():
    rng = np.random.default_rng(7)
    mat = rng.integers(0, 256, size=(4, 8), dtype=np.uint8)
    data = rng.integers(0, 256, size=(8, 4096), dtype=np.uint8)
    a = np.asarray(gf_apply_pallas(mat, data, tile_n=1024, interpret=True))
    b = np.asarray(rs_kernels.gf_apply_bitslice(mat, data))
    assert np.array_equal(a, b)


def test_plane_major_expansion_consistent():
    """The plane-major bit matrix must express the same linear map as the
    interleaved one used by the XLA path."""
    rng = np.random.default_rng(9)
    mat = rng.integers(0, 256, size=(3, 4), dtype=np.uint8)
    B = np.asarray(expand_bits_plane_major(mat))
    r, k = mat.shape
    data = rng.integers(0, 256, size=(k, 64), dtype=np.uint8)
    # manual plane-major apply
    planes = np.concatenate([(data >> b) & 1 for b in range(8)], axis=0)
    acc = (B.astype(np.int64) @ planes.astype(np.int64)) & 1
    out = np.zeros((r, 64), dtype=np.uint8)
    for b in range(8):
        out |= (acc[b * r:(b + 1) * r] << b).astype(np.uint8)
    assert np.array_equal(out, gfm.gf_matmul(mat, data))


def test_auto_routing_off_tpu_stays_on_xla():
    """On the CPU test backend, auto must not pick pallas (it would need
    interpret mode)."""
    rng = np.random.default_rng(11)
    mat = rng.integers(0, 256, size=(2, 4), dtype=np.uint8)
    data = rng.integers(0, 256, size=(4, 2048), dtype=np.uint8)
    out = np.asarray(rs_kernels.gf_apply(mat, data, "auto"))
    assert np.array_equal(out, gfm.gf_matmul(mat, data))
