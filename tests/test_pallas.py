"""Pallas fused GF kernel: bit-exact vs the numpy field math (interpret
mode on CPU; the same kernel compiles for TPU where it is the auto-routed
encode path)."""
import numpy as np
import pytest

from ceph_tpu.gf import matrix as gfm
from ceph_tpu.ops import rs_kernels
from ceph_tpu.ops.pallas_kernels import (expand_bits_plane_major,
                                         gf_apply_pallas,
                                         gf_apply_stripes_pallas)


@pytest.mark.parametrize("r,k,S,n,groups,tile", [
    (4, 8, 8, 1024, 4, 512),     # even groups
    (4, 8, 6, 1024, 4, 512),     # stripe count not a group multiple
    (2, 4, 3, 700, 4, 256),      # ragged columns + groups > stripes
    (4, 8, 1, 512, 4, 512),      # single stripe
])
def test_stripes_kernel_matches_field_math(r, k, S, n, groups, tile):
    """Vertical layout: stripe s = rows [s*k, (s+1)*k); parity at
    [s*r, (s+1)*r).  Bit-exact vs per-stripe host math."""
    rng = np.random.default_rng(r * 1000 + S)
    mat = rng.integers(0, 256, size=(r, k), dtype=np.uint8)
    data = rng.integers(0, 256, size=(S * k, n), dtype=np.uint8)
    got = np.asarray(gf_apply_stripes_pallas(
        mat, data, S, groups=groups, tile_n=tile, interpret=True))
    assert got.shape == (S * r, n)
    for s in range(S):
        want = gfm.gf_matmul(mat, data[s * k:(s + 1) * k])
        assert np.array_equal(got[s * r:(s + 1) * r], want), f"stripe {s}"


def test_stripes_dispatch_fallback_matches():
    """rs_kernels.gf_apply_stripes off-TPU folds to the XLA path and must
    agree with the interpret-mode pallas kernel."""
    rng = np.random.default_rng(4)
    mat = rng.integers(0, 256, size=(4, 8), dtype=np.uint8)
    data = rng.integers(0, 256, size=(5 * 8, 512), dtype=np.uint8)
    a = np.asarray(rs_kernels.gf_apply_stripes(mat, data, 5))
    b = np.asarray(gf_apply_stripes_pallas(mat, data, 5, tile_n=256,
                                           interpret=True))
    assert np.array_equal(a, b)


@pytest.mark.parametrize("r,k,n,tile", [
    (4, 8, 2048, 512),       # even tiles
    (2, 4, 3000, 512),       # ragged tail -> padding path
    (3, 5, 512, 1024),       # single partial tile
    (1, 2, 256, 256),        # minimal shapes
])
def test_pallas_matches_field_math(r, k, n, tile):
    rng = np.random.default_rng(r * 100 + k)
    mat = rng.integers(0, 256, size=(r, k), dtype=np.uint8)
    data = rng.integers(0, 256, size=(k, n), dtype=np.uint8)
    got = np.asarray(gf_apply_pallas(mat, data, tile_n=tile, interpret=True))
    assert np.array_equal(got, gfm.gf_matmul(mat, data))


def test_pallas_matches_xla_bitslice():
    rng = np.random.default_rng(7)
    mat = rng.integers(0, 256, size=(4, 8), dtype=np.uint8)
    data = rng.integers(0, 256, size=(8, 4096), dtype=np.uint8)
    a = np.asarray(gf_apply_pallas(mat, data, tile_n=1024, interpret=True))
    b = np.asarray(rs_kernels.gf_apply_bitslice(mat, data))
    assert np.array_equal(a, b)


def test_plane_major_expansion_consistent():
    """The plane-major bit matrix must express the same linear map as the
    interleaved one used by the XLA path."""
    rng = np.random.default_rng(9)
    mat = rng.integers(0, 256, size=(3, 4), dtype=np.uint8)
    B = np.asarray(expand_bits_plane_major(mat))
    r, k = mat.shape
    data = rng.integers(0, 256, size=(k, 64), dtype=np.uint8)
    # manual plane-major apply
    planes = np.concatenate([(data >> b) & 1 for b in range(8)], axis=0)
    acc = (B.astype(np.int64) @ planes.astype(np.int64)) & 1
    out = np.zeros((r, 64), dtype=np.uint8)
    for b in range(8):
        out |= (acc[b * r:(b + 1) * r] << b).astype(np.uint8)
    assert np.array_equal(out, gfm.gf_matmul(mat, data))


def test_auto_routing_off_tpu_stays_on_xla():
    """On the CPU test backend, auto must not pick pallas (it would need
    interpret mode)."""
    rng = np.random.default_rng(11)
    mat = rng.integers(0, 256, size=(2, 4), dtype=np.uint8)
    data = rng.integers(0, 256, size=(4, 2048), dtype=np.uint8)
    out = np.asarray(rs_kernels.gf_apply(mat, data, "auto"))
    assert np.array_equal(out, gfm.gf_matmul(mat, data))
