"""Device-class shadow trees (CrushWrapper::device_class_clone /
populate_classes, reference: src/crush/CrushWrapper.cc:2648,
CrushWrapper.h:1342,1350): per-class clones of the hierarchy so rules can
say 'step take <root> class <c>' and place only on matching devices.
Closes the r4 VERDICT missing item #1.
"""
import numpy as np
import pytest

from ceph_tpu.crush import (CRUSH_BUCKET_STRAW2, CRUSH_RULE_CHOOSELEAF_INDEP,
                            CRUSH_RULE_EMIT, CRUSH_RULE_TAKE, CrushMap,
                            crush_do_rule)

CRUSH_ITEM_NONE = 0x7FFFFFFF


def mixed_map(n_hosts=4, per_host=2):
    """n_hosts hosts x per_host devices; even devices ssd, odd hdd."""
    m = CrushMap()
    m.set_type_name(1, "host")
    m.set_type_name(2, "root")
    hosts = []
    for h in range(n_hosts):
        items = [h * per_host + i for i in range(per_host)]
        for d in items:
            m.set_device_class(d, "ssd" if d % 2 == 0 else "hdd")
        b = m.add_bucket(CRUSH_BUCKET_STRAW2, 1, items,
                         [0x10000 * (1 + d % 3) for d in items])
        m.set_item_name(b, f"host{h}")
        hosts.append(b)
    root = m.add_bucket(
        CRUSH_BUCKET_STRAW2, 2, hosts,
        [m.buckets[b].weight for b in hosts])
    m.set_item_name(root, "default")
    m.finalize()
    return m, hosts, root


def leaves(m, bid):
    out = []
    stack = [bid]
    while stack:
        cur = stack.pop()
        if cur >= 0:
            out.append(cur)
        else:
            stack.extend(m.buckets[cur].items)
    return out


class TestClone:
    def test_clone_keeps_only_class_devices(self):
        m, hosts, root = mixed_map()
        sid = m.device_class_clone(root, "ssd")
        assert m.is_shadow(sid)
        assert m.item_names[sid] == "default~ssd"
        got = sorted(leaves(m, sid))
        assert got == [d for d in range(8) if d % 2 == 0]
        # per-host shadow buckets exist and are named
        for h, hb in enumerate(hosts):
            hs = m.class_bucket[hb]["ssd"]
            assert m.item_names[hs] == f"host{h}~ssd"
            assert m.buckets[hs].type == 1

    def test_clone_weights_are_class_sums(self):
        m, hosts, root = mixed_map()
        sid = m.device_class_clone(root, "hdd")
        for hb in hosts:
            hs = m.class_bucket[hb]["hdd"]
            b = m.buckets[hb]
            want = sum(w for i, w in zip(b.items, b.item_weights)
                       if m.device_classes.get(i) == "hdd")
            assert m.buckets[hs].weight == want
        assert m.buckets[sid].weight == sum(
            m.buckets[m.class_bucket[hb]["hdd"]].weight for hb in hosts)

    def test_clone_idempotent(self):
        m, _hosts, root = mixed_map()
        assert m.device_class_clone(root, "ssd") == \
            m.device_class_clone(root, "ssd")

    def test_populate_classes(self):
        m, hosts, root = mixed_map()
        made = m.populate_classes()
        assert made == 2                     # root x {ssd, hdd}
        assert m.populate_classes() == 0     # idempotent
        assert set(m.class_bucket[root]) == {"ssd", "hdd"}
        assert m.nonshadow_roots() == [root]

    def test_unknown_class_rejected(self):
        m, _hosts, _root = mixed_map()
        with pytest.raises(ValueError, match="not assigned"):
            m.take_with_class("default", "nvme")


class TestClassRules:
    def test_simple_rule_places_on_class_only(self):
        m, _hosts, _root = mixed_map()
        ruleno = m.add_simple_rule("ssd_rule", "default", "host",
                                   device_class="ssd", mode="indep",
                                   num_rep=3)
        ssd = {d for d in range(8) if d % 2 == 0}
        placed = set()
        for x in range(256):
            out = crush_do_rule(m, ruleno, x, 3)
            real = [o for o in out if o != CRUSH_ITEM_NONE]
            assert real and set(real) <= ssd, (x, out)
            placed |= set(real)
        assert placed == ssd                 # every ssd participates

    def test_choose_args_weight_sets_clone(self):
        m, hosts, root = mixed_map()
        # install a weight-set (balancer shape) on the ORIGINALS
        m.choose_args[-1] = {
            root: {"weight_set": [[m.buckets[h].weight for h in hosts]]},
            hosts[0]: {"weight_set": [[0x8000, 0x8000]]},
        }
        sid = m.device_class_clone(root, "ssd")
        h0s = m.class_bucket[hosts[0]]["ssd"]
        args = m.choose_args[-1]
        # the host clone kept its ssd position's weight
        assert args[h0s]["weight_set"] == [[0x8000]]
        # the root clone sums child clones per position
        row = args[sid]["weight_set"][0]
        assert row[0] == 0x8000              # host0~ssd via its weight set
        ruleno = m.add_simple_rule("s", "default", "host",
                                   device_class="ssd", mode="indep",
                                   num_rep=3)
        ssd = {d for d in range(8) if d % 2 == 0}
        for x in range(64):
            out = crush_do_rule(m, ruleno, x, 3,
                                choose_args=m.choose_args[-1])
            assert {o for o in out if o != CRUSH_ITEM_NONE} <= ssd

    def test_lrc_device_class_rule(self):
        from ceph_tpu.plugins import ErasureCodePluginRegistry
        m, _hosts, _root = mixed_map(n_hosts=6, per_host=2)
        lrc = ErasureCodePluginRegistry.instance().factory(
            "lrc", "", {"k": "2", "m": "1", "l": "3",
                        "crush-device-class": "ssd",
                        "crush-failure-domain": "host"})
        ruleno = lrc.create_rule("lrc_ssd", m)
        ssd = {d for d in range(12) if d % 2 == 0}
        for x in range(64):
            out = crush_do_rule(m, ruleno, x, 3)
            assert {o for o in out if o != CRUSH_ITEM_NONE} <= ssd


class TestRoundTrip:
    def test_text_round_trip_preserves_class_rule(self):
        from ceph_tpu.crush import compile_crushmap, decompile
        m, _hosts, root = mixed_map()
        ruleno = m.add_simple_rule("ssd_rule", "default", "host",
                                   device_class="ssd", mode="indep",
                                   num_rep=3)
        text = decompile(m)
        assert "step take default class ssd" in text
        assert "default~ssd" not in text      # shadows not dumped
        m2 = compile_crushmap(text)
        # shadow ids preserved via the 'id <sid> class <c>' lines
        assert m2.class_bucket[root]["ssd"] == m.class_bucket[root]["ssd"]
        for x in range(128):
            assert crush_do_rule(m, ruleno, x, 3) == \
                crush_do_rule(m2, ruleno, x, 3)

    def test_dict_round_trip(self):
        m, _hosts, root = mixed_map()
        m.add_simple_rule("ssd_rule", "default", "host",
                          device_class="ssd", mode="indep", num_rep=3)
        m2 = CrushMap.from_dict(m.to_dict())
        # item_names must carry shadows for is_shadow to survive
        sid = m.class_bucket[root]["ssd"]
        assert m2.class_bucket[root]["ssd"] == sid
        assert m2.is_shadow(sid)


class TestGolden:
    def test_clone_places_like_reference_built_shadow(self):
        """The cloned shadow tree must place bit-identically to the
        reference-C-built equivalent hierarchy (golden scenario
        'class_shadow_ssd': same devices/weights, ssd-only subtree built
        with the reference builder)."""
        import json
        import pathlib
        d = json.loads((pathlib.Path(__file__).parent / "golden" /
                        "crush_golden.json").read_text())
        run = next(r for g in d["groups"] for r in g.get("runs", [])
                   if r["name"] == "class_shadow_ssd")
        m, _hosts, _root = mixed_map()       # same geometry as golden_gen.c
        ruleno = m.add_simple_rule("ssd", "default", "host",
                                   device_class="ssd", mode="indep",
                                   num_rep=3)
        for x, want in enumerate(run["results"]):
            got = crush_do_rule(m, ruleno, x, run["result_max"],
                                weights=list(run["weights"]))
            assert got == want, (x, got, want)


class TestBulkMapper:
    def test_jax_bulk_matches_host_on_class_rule(self):
        from ceph_tpu.crush.jax_mapper import BulkMapper
        m, _hosts, _root = mixed_map()
        ruleno = m.add_simple_rule("ssd_rule", "default", "host",
                                   device_class="ssd", mode="indep",
                                   num_rep=3)
        bulk = BulkMapper(m)
        xs = np.arange(128, dtype=np.uint32)
        out, _placed = bulk.map_rule(ruleno, xs)
        out = np.asarray(out)
        for x in range(128):
            want = crush_do_rule(m, ruleno, x, 3)
            np.testing.assert_array_equal(out[x], want)


class TestCluster:
    def test_ec_pool_with_device_class(self):
        from ceph_tpu.cluster import MiniCluster
        c = MiniCluster(n_osds=12, osds_per_host=2, chunk_size=512)
        crush = c.osdmap.crush
        ssd = {d for d in range(12) if d % 2 == 0}
        for d in range(12):
            crush.set_device_class(d, "ssd" if d in ssd else "hdd")
        pid = c.create_ec_pool(
            "fast", {"k": "2", "m": "1", "device": "numpy",
                     "crush-device-class": "ssd"}, pg_num=8)
        for g in c.pools[pid]["pgs"].values():
            real = [o for o in g.acting if o != CRUSH_ITEM_NONE]
            assert real and set(real) <= ssd, g.acting
        # IO works end to end on the class-restricted pool
        c.put(pid, "obj", b"x" * 4096)
        assert c.get(pid, "obj", 4096) == b"x" * 4096
        c.shutdown()
