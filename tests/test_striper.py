"""RadosStriper: RAID-0 striping over RADOS objects.

Mirrors libradosstriper semantics (src/libradosstriper/
RadosStriperImpl.cc): stripe_unit round-robin placement, layout+size
xattrs on piece 0, reads honoring the WRITER's layout.
"""
import numpy as np
import pytest

from ceph_tpu.client.rados import ObjectNotFound, Rados
from ceph_tpu.client.striper import LAYOUT_ATTR, RadosStriper, piece_name
from ceph_tpu.cluster import MiniCluster


@pytest.fixture
def io():
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
    c.create_ec_pool("s", {"k": "2", "m": "1", "device": "numpy"}, pg_num=8)
    yield Rados(c).open_ioctx("s")
    c.shutdown()


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


def test_roundtrip_odd_size(io):
    st = RadosStriper(io, stripe_unit=1024, stripe_count=3,
                      object_size=4096)
    payload = _data(50_001, 1)            # deliberately unaligned
    n_pieces = st.write_full("big", payload)
    assert n_pieces > 3                   # spilled past one object set
    assert st.read("big") == payload
    assert st.stat("big") == len(payload)
    # partial reads at arbitrary offsets
    assert st.read("big", 5000, offset=12345) == payload[12345:17345]
    assert st.read("big", 10**9, offset=49_000) == payload[49_000:]


def test_stripe_placement(io):
    """Byte n lands in piece (n // su) % sc at row n // (su*sc) — the
    RAID-0 layout the reference documents."""
    su, sc = 512, 3
    st = RadosStriper(io, stripe_unit=su, stripe_count=sc,
                      object_size=2048)
    payload = _data(su * sc * 2, 2)       # two full stripe rows
    st.write_full("lay", payload)
    for col in range(sc):
        piece = io.read(piece_name("lay", col))
        assert piece[:su] == payload[col * su:(col + 1) * su]
        row1 = payload[(sc + col) * su:(sc + col + 1) * su]
        assert piece[su:2 * su] == row1


def test_layout_attr_and_cross_layout_read(io):
    st = RadosStriper(io, stripe_unit=1024, stripe_count=2,
                      object_size=2048)
    payload = _data(9000, 3)
    st.write_full("x", payload)
    lay = io.get_xattr(piece_name("x", 0), LAYOUT_ATTR)
    assert lay["size"] == 9000 and lay["su"] == 1024
    # a reader configured with a DIFFERENT default layout still
    # reassembles correctly (it honors the stored layout)
    other = RadosStriper(io, stripe_unit=4096, stripe_count=7,
                         object_size=8192)
    assert other.read("x") == payload


def test_remove_deletes_all_pieces(io):
    st = RadosStriper(io, stripe_unit=512, stripe_count=2,
                      object_size=1024)
    st.write_full("gone", _data(6000, 4))
    assert st.remove("gone") >= 3
    with pytest.raises((ObjectNotFound, IOError)):
        st.stat("gone")
    assert not [o for o in io.list_objects() if o.startswith("gone.")]


def test_striped_write_survives_degraded_read(io):
    st = RadosStriper(io, stripe_unit=1024, stripe_count=2,
                      object_size=2048)
    payload = _data(20_000, 5)
    st.write_full("deg", payload)
    c = io.rados.cluster
    g = c.pg_group(io.pool_id, piece_name("deg", 0))
    victim = next(o for o in g.acting if o != g.backend.whoami)
    g.bus.mark_down(victim)
    try:
        assert st.read("deg") == payload
    finally:
        g.bus.mark_up(victim)


def test_shrinking_rewrite_removes_stale_pieces(io):
    """write_full of a smaller payload must delete trailing pieces and
    remove() must not orphan anything (regression: both derived the
    piece set from the new layout only)."""
    st = RadosStriper(io, stripe_unit=512, stripe_count=2,
                      object_size=1024)
    st.write_full("shrink", _data(6000, 6))
    st.write_full("shrink", b"tiny")
    assert [o for o in io.list_objects() if o.startswith("shrink.")] == \
        [piece_name("shrink", 0)]
    assert st.read("shrink") == b"tiny"
    assert st.remove("shrink") == 1
    assert not [o for o in io.list_objects() if o.startswith("shrink.")]


def test_user_object_matching_piece_pattern_survives(io):
    """A user object whose name happens to match '<soid>.<16 hex>' must
    survive write_full's shrink sweep AND remove() (regression: the
    piece set came from a pool-wide name scan, silently deleting
    unrelated objects; the reference derives pieces from the layout
    xattr).  Also: a shrink must still clear its own stale pieces."""
    st = RadosStriper(io, stripe_unit=512, stripe_count=2,
                      object_size=1024)
    victim = "big.00000000000000ff"            # piece-shaped USER object
    io.write_full(victim, b"precious")
    st.write_full("big", _data(6000, 7))       # many pieces
    st.write_full("big", b"tiny")              # shrink sweep runs
    assert io.read(victim) == b"precious"      # user object untouched
    # the striper's own stale pieces ARE gone
    assert [o for o in io.list_objects()
            if o.startswith("big.") and o != victim] == \
        [piece_name("big", 0)]
    st.remove("big")
    assert io.read(victim) == b"precious"      # remove() untouched it too


def test_interrupted_write_full_reclaims_pieces(io, monkeypatch):
    """A write_full that dies between the piece writes and the layout
    commit must leave enough state (the staged 'pending' layout) for the
    NEXT write — or remove() — to reclaim every piece it stored."""
    st = RadosStriper(io, stripe_unit=512, stripe_count=2,
                      object_size=1024)
    st.write_full("part", _data(1200, 11))     # small initial object
    cluster = io.rados.cluster
    orig = cluster.put_many

    def dying(pool_id, objects, **kw):
        orig(pool_id, objects, **kw)           # pieces land...
        raise RuntimeError("simulated crash after piece write")
    monkeypatch.setattr(cluster, "put_many", dying)
    with pytest.raises(RuntimeError):
        st.write_full("part", _data(6000, 12))  # grows to pieces 0..5
    monkeypatch.setattr(cluster, "put_many", orig)
    # recovery: the next write sweeps the orphans of the interrupted one
    st.write_full("part", b"tiny")
    assert [o for o in io.list_objects() if o.startswith("part.")] == \
        [piece_name("part", 0)]
    assert st.read("part") == b"tiny"


def test_blocked_op_leaves_no_ghost_resend(io):
    """A write raising BlockedWriteError must leave the objecter's
    inflight list (regression: a map change could resend it and a
    non-idempotent op would double-apply)."""
    from ceph_tpu.cluster import BlockedWriteError
    io.write_full("gh", b"v1")
    c = io.rados.cluster
    g = c.pg_group(io.pool_id, "gh")
    peers = [o for o in g.acting if o != g.backend.whoami]
    for o in peers:
        g.bus.mark_down(o)
    with pytest.raises(BlockedWriteError):
        io.append("gh", b"X")
    assert not io.rados.objecter.inflight     # no ghost to resend
    for o in peers:
        g.bus.mark_up(o)
    g.bus.deliver_all()
    assert io.read("gh") == b"v1X"            # queued op still committed


def test_empty_object(io):
    """write_full(b'') keeps its layout piece: stat 0, read b''
    (regression: the stale-piece sweep deleted piece 0)."""
    st = RadosStriper(io, stripe_unit=512, stripe_count=2,
                      object_size=1024)
    assert st.write_full("empty", b"") == 1
    assert st.stat("empty") == 0
    assert st.read("empty") == b""
    assert st.remove("empty") == 1


def test_striper_composes_with_snapshots(io):
    """Striped objects under pool snapshots: every piece COWs, and a
    striped read at the snap reassembles the old version."""
    st = RadosStriper(io, stripe_unit=1024, stripe_count=2,
                      object_size=2048)
    v1 = _data(15000, 30)
    st.write_full("snappy", v1)
    sid = io.snap_create("before")
    st.write_full("snappy", _data(15000, 31))
    io.set_read(sid)
    assert st.read("snappy") == v1            # pieces resolve per-clone
    io.set_read(None)
    io.snap_remove("before")
