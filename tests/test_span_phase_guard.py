"""Guard: spans in the serving/recovery/pipeline layers must map to a
DECLARED critical-path phase.

Sibling of ``test_span_owner_guard.py``: the latency-objective layer
(common/critpath.py) decomposes every completed op's trace into the
canonical phase taxonomy, and an undeclared span silently files its
self-time under ``other`` — the attribution table then under-reports
exactly the new code path someone just added.  Every span opened (or
``tracer.complete()``-stamped) in ``ceph_tpu/exec/``,
``ceph_tpu/recovery/`` and ``ceph_tpu/ops/pipeline.py`` must either be
declared in the registry (``critpath.SPAN_PHASES`` / the prefix rules)
or carry an explicit constant ``phase=`` keyword.
"""
import ast
from pathlib import Path

from ceph_tpu.common.critpath import PHASES, is_declared

ROOT = Path(__file__).resolve().parent.parent
SCAN = ("ceph_tpu/exec", "ceph_tpu/recovery", "ceph_tpu/ops/pipeline.py")

_SPAN_CALLS = {"trace_span", "span", "complete"}


def _span_name(call: ast.Call) -> str | None:
    fn = call.func
    name = fn.id if isinstance(fn, ast.Name) else \
        fn.attr if isinstance(fn, ast.Attribute) else None
    if name not in _SPAN_CALLS or not call.args:
        return None
    first = call.args[0]
    return first.value if isinstance(first, ast.Constant) and \
        isinstance(first.value, str) else None


def _paths():
    for sub in SCAN:
        p = ROOT / sub
        yield from (sorted(p.rglob("*.py")) if p.is_dir() else [p])


def test_spans_in_serving_recovery_pipeline_declare_a_phase():
    offenders = []
    for path in _paths():
        rel = path.relative_to(ROOT).as_posix()
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _span_name(node)
            if name is None:
                continue
            phase_kw = next((kw.value for kw in node.keywords
                             if kw.arg == "phase"), None)
            if isinstance(phase_kw, ast.Constant) and \
                    phase_kw.value in PHASES:
                continue                      # explicit declaration
            if is_declared(name):
                continue
            offenders.append(
                f"{rel}:{node.lineno}: span {name!r} maps to no "
                f"declared critical-path phase — add it to "
                f"critpath.SPAN_PHASES or pass phase=<one of {PHASES}>")
    assert not offenders, (
        "undeclared span phases (attribution would file these under "
        "'other'):\n" + "\n".join(offenders))


def test_scan_targets_still_exist():
    for sub in SCAN:
        assert (ROOT / sub).exists(), f"stale scan target: {sub}"


def test_registry_covers_the_process_wide_span_inventory():
    """The spans the rest of the codebase emits on the client-op path
    must stay declared too — this is the list the decomposition's
    fixtures and docs are written against."""
    for name in ("client.op", "osd.op", "osd.queue_wait", "ec.encode",
                 "ec.decode", "codec.encode", "codec.decode",
                 "serving.batch_wait", "serving.admission",
                 "pipeline.complete", "pipeline.host_fallback",
                 "net.resend", "client.op_retry", "recovery.wave",
                 "osd.ECSubWrite", "rpc.put"):
        assert is_declared(name), name
