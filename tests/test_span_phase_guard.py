"""Guard: spans in the serving/recovery/pipeline layers must map to a
DECLARED critical-path phase.

Thin wrapper over the ``span-phase`` rule in
:mod:`ceph_tpu.analysis.rules_guards` (ISSUE 15); semantics unchanged —
an undeclared span silently files its self-time under ``other`` in the
latency decomposition, so every span opened (or
``tracer.complete()``-stamped) in ``exec/``, ``recovery/`` and
``ops/pipeline.py`` must be declared in ``critpath.SPAN_PHASES`` or
carry an explicit constant ``phase=``.
"""
import ceph_tpu.analysis as A
from ceph_tpu.common.critpath import is_declared


def test_spans_in_serving_recovery_pipeline_declare_a_phase():
    offenders = [f.render() for f in A.run_rules(
        A.default_index(), ("span-phase",))]
    assert not offenders, (
        "undeclared span phases (attribution would file these under "
        "'other'):\n" + "\n".join(offenders))


def test_scan_targets_still_exist():
    idx = A.default_index()
    for sub in ("ceph_tpu/exec", "ceph_tpu/recovery",
                "ceph_tpu/ops/pipeline.py"):
        assert idx.iter_modules((sub,)), f"stale scan target: {sub}"


def test_guard_catches_an_undeclared_span():
    bad = ("def f(tr):\n"
           "    with tr.span('totally.new.span'):\n"
           "        pass\n"
           "    with tr.span('ec.encode'):\n"       # declared: fine
           "        pass\n"
           "    with tr.span('x.y', phase='device'):\n"  # explicit: fine
           "        pass\n")
    found = A.run_rule_on_sources("span-phase", {"bad.py": bad})
    assert len(found) == 1
    assert "totally.new.span" in found[0].message


def test_registry_covers_the_process_wide_span_inventory():
    """The spans the rest of the codebase emits on the client-op path
    must stay declared too — this is the list the decomposition's
    fixtures and docs are written against."""
    for name in ("client.op", "osd.op", "osd.queue_wait", "ec.encode",
                 "ec.decode", "codec.encode", "codec.decode",
                 "serving.batch_wait", "serving.admission",
                 "pipeline.complete", "pipeline.host_fallback",
                 "net.resend", "client.op_retry", "recovery.wave",
                 "osd.ECSubWrite", "rpc.put"):
        assert is_declared(name), name
