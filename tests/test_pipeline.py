"""Device-resident codec pipeline: async dispatch, cached decode tables.

Covers the ISSUE-5 acceptance surface:

- encode -> corrupt -> decode round-trips entirely through the
  device-resident/async API at several depths, bitwise-identical to the
  synchronous path;
- out-of-order completion (forcing a later future first) and an injected
  device-side failure surfacing on the future, not the dispatcher;
- the signature-LRU's DEVICE decode-matrix cache: an LRU hit performs
  zero host->device table transfers (``decode_table_uploads`` pinned);
- the ``decode_batch`` permutation fast path (O(k) index map, no gather
  on identity-after-drop);
- the mesh-sharded serving batch path (``jax_rs_mesh_devices``) over the
  conftest's virtual 8-device mesh, bitwise-identical again.
"""
import numpy as np
import pytest

from ceph_tpu.backend import ecutil
from ceph_tpu.backend.ecutil import StripeInfo
from ceph_tpu.exec.engine import ServingEngine
from ceph_tpu.ops.codec import RSCodec
from ceph_tpu.ops.pipeline import CodecPipeline
from ceph_tpu.plugins.registry import ErasureCodePluginRegistry

K, M, CHUNK = 4, 2, 1024


@pytest.fixture
def ec():
    return ErasureCodePluginRegistry.instance().factory(
        "jax_rs", "", {"plugin": "jax_rs", "k": str(K), "m": str(M),
                       "technique": "reed_sol_van", "device": "jax"})


@pytest.fixture
def sinfo():
    return StripeInfo(K, CHUNK)


def _payloads(n, nbytes=4096, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 256, nbytes, np.uint8).tobytes()
            for _ in range(n)]


# -- round trips at several depths, bitwise vs the synchronous path ----------

@pytest.mark.parametrize("depth", [1, 2, 4, 8])
def test_engine_roundtrip_bitwise_identical_to_sync(ec, sinfo, depth):
    payloads = _payloads(10, seed=depth)
    sync = ServingEngine(ec_impl=ec, sinfo=sinfo,
                         name=f"sync{depth}", pipeline_depth=0)
    pipe = ServingEngine(ec_impl=ec, sinfo=sinfo,
                         name=f"pipe{depth}", pipeline_depth=depth)
    try:
        futs_s = [sync.submit_encode(p) for p in payloads]
        sync.flush()
        futs_p = [pipe.submit_encode(p) for p in payloads]
        pipe.flush()
        enc_s = [f.result(10) for f in futs_s]
        enc_p = [f.result(10) for f in futs_p]
        for a, b in zip(enc_s, enc_p):
            assert set(a) == set(b)
            for c in a:
                np.testing.assert_array_equal(np.asarray(a[c]),
                                              np.asarray(b[c]))
        # corrupt: drop a data chunk and a parity chunk, decode back
        degraded = [{c: v for c, v in e.items() if c not in (0, K + 1)}
                    for e in enc_p]
        dfuts = [pipe.submit_decode(d) for d in degraded]
        pipe.flush()
        assert [f.result(10) for f in dfuts] == [bytes(p) for p in payloads]
    finally:
        sync.stop()
        pipe.stop()


def test_threaded_engine_roundtrip(ec, sinfo):
    payloads = _payloads(16, seed=42)
    eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="thr",
                        pipeline_depth=4).start()
    try:
        futs = [eng.submit_encode(p) for p in payloads]
        encs = [f.result(30) for f in futs]
        outs = [eng.decode({c: v for c, v in e.items() if c != 1},
                           timeout=30) for e in encs]
        assert outs == [bytes(p) for p in payloads]
    finally:
        eng.stop()


# -- raw pipeline semantics --------------------------------------------------

def test_out_of_order_completion():
    pl = CodecPipeline(depth=8, name="ooo")
    try:
        codec = RSCodec(K, M, device="jax")
        rng = np.random.default_rng(3)
        blocks = [rng.integers(0, 256, (K, CHUNK), np.uint8)
                  for _ in range(3)]
        futs = [pl.submit(lambda b=b: b,
                          lambda packed: pl.dispatch_encode(codec, packed,
                                                            CHUNK),
                          lambda packed, parity: parity)
                for b in blocks]
        assert pl.in_flight == 3
        # force the LAST future first: it completes alone, the earlier
        # ones stay dispatched
        p2 = futs[2].result(10)
        assert futs[2].done() and not futs[0].done()
        assert pl.in_flight == 2
        p0 = futs[0].result(10)
        p1 = futs[1].result(10)
        assert pl.in_flight == 0
        for b, p in zip(blocks, (p0, p1, p2)):
            np.testing.assert_array_equal(p, np.asarray(codec.encode(b)))
    finally:
        pl.close()


def test_injected_failure_surfaces_on_future():
    pl = CodecPipeline(depth=4, name="fail")
    try:
        # dispatch-stage failure (bad kernel launch)
        def boom(_packed):
            raise RuntimeError("device exploded at dispatch")
        f1 = pl.submit(lambda: None, boom, lambda p, h: h)
        assert isinstance(f1.exception(1), RuntimeError)
        with pytest.raises(RuntimeError, match="at dispatch"):
            f1.result(1)
        # completion-boundary failure (device-side error surfaces at the
        # deferred sync, NOT on the dispatching thread)
        class _Wedged:
            def block_until_ready(self):
                raise ValueError("device-side failure at completion")
        f2 = pl.submit(lambda: None, lambda _p: _Wedged(),
                       lambda p, h: h)
        assert not f2.done()            # dispatch itself succeeded
        with pytest.raises(ValueError, match="at completion"):
            f2.result(1)
        assert pl.perf.get("errors") == 2
        # the pipeline stays usable after failures
        codec = RSCodec(K, M, device="jax")
        data = np.arange(K * CHUNK, dtype=np.uint8).reshape(K, CHUNK)
        f3 = pl.submit(lambda: data,
                       lambda d: pl.dispatch_encode(codec, d, CHUNK),
                       lambda p, h: h)
        np.testing.assert_array_equal(f3.result(10),
                                      np.asarray(codec.encode(data)))
    finally:
        pl.close()


def test_engine_surfaces_pipeline_failure_on_batch_future(ec, sinfo,
                                                          monkeypatch):
    """With BOTH the device dispatch and the host fallback failing the
    error surfaces on the batch future (ISSUE 9: a lone device failure
    is healed by the breaker's host fallback — see the sibling test)."""
    eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="efail",
                        pipeline_depth=4)
    try:
        monkeypatch.setattr(
            CodecPipeline, "dispatch_encode",
            lambda self, codec, data, chunk: (_ for _ in ()).throw(
                RuntimeError("injected")))
        monkeypatch.setattr(
            CodecPipeline, "host_encode",
            lambda self, codec, data, chunk: (_ for _ in ()).throw(
                RuntimeError("injected host too")))
        fut = eng.submit_encode(_payloads(1)[0])
        eng.flush()
        with pytest.raises(RuntimeError, match="injected"):
            fut.result(5)
        assert eng.perf.get("ops_failed") == 1
    finally:
        eng.stop()


def test_engine_heals_device_failure_via_host_fallback(ec, sinfo,
                                                       monkeypatch):
    """A device dispatch failure with the host codec available: the op
    SUCCEEDS (host-served), nothing fails, the fallback is counted."""
    eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="eheal",
                        pipeline_depth=4)
    try:
        monkeypatch.setattr(
            CodecPipeline, "dispatch_encode",
            lambda self, codec, data, chunk: (_ for _ in ()).throw(
                RuntimeError("device down")))
        buf = _payloads(1)[0]
        fut = eng.submit_encode(buf)
        eng.flush()
        chunks = fut.result(5)
        from ceph_tpu.backend import ecutil
        assert {k: bytes(v) for k, v in chunks.items()} == \
            {k: bytes(v) for k, v in
             ecutil.encode(sinfo, ec, bytes(buf)).items()}
        assert eng.perf.get("ops_failed") == 0
        assert eng.pipeline.perf.get("host_fallbacks") >= 1
    finally:
        eng.stop()


def test_depth_counters_and_backpressure():
    pl = CodecPipeline(depth=2, name="depth")
    try:
        codec = RSCodec(K, M, device="jax")
        rng = np.random.default_rng(5)
        futs = []
        for _ in range(5):
            d = rng.integers(0, 256, (K, CHUNK), np.uint8)
            futs.append(pl.submit(
                lambda d=d: d,
                lambda p: pl.dispatch_encode(codec, p, CHUNK),
                lambda p, h: h))
        # depth-limited: never more than `depth` in flight
        assert pl.in_flight <= 2
        assert pl.perf.get("submitted") == 5
        pl.flush()
        assert pl.in_flight == 0
        assert pl.perf.get("completed") == 5
        assert all(f.done() for f in futs)
    finally:
        pl.close()


# -- the LRU-hit transfer counter (no decode-matrix re-upload) ---------------

def test_lru_hit_uploads_no_decode_table():
    codec = RSCodec(K, M, device="jax")
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, (K, CHUNK), np.uint8)
    parity = np.asarray(codec.encode(data))
    chunks = {i: data[i] for i in range(1, K)}
    chunks.update({K + j: parity[j] for j in range(M)})
    rec1 = codec.decode(dict(chunks), [0])
    assert codec.decode_table_uploads == 1
    # LRU hit: same signature, ZERO new table transfers
    for _ in range(3):
        rec2 = codec.decode(dict(chunks), [0])
        np.testing.assert_array_equal(rec2[0], rec1[0])
    assert codec.decode_table_uploads == 1
    assert codec.parity_uploads == 1
    # a different signature uploads exactly one more
    chunks2 = {i: data[i] for i in (0, 2, 3)}
    chunks2.update({K + j: parity[j] for j in range(M)})
    codec.decode(chunks2, [1])
    assert codec.decode_table_uploads == 2
    np.testing.assert_array_equal(rec1[0], data[0])


def test_decode_batch_uses_cached_device_matrix():
    codec = RSCodec(K, M, device="jax")
    rng = np.random.default_rng(9)
    stacks = rng.integers(0, 256, (3, 8, K, CHUNK), np.uint8)
    src = [1, 2, 3, K]        # survivors: data 1..3 + first parity
    for i, stack in enumerate(stacks):
        codec.decode_batch(stack, src, [0])
        assert codec.decode_table_uploads == 1, \
            f"decode_batch re-uploaded the matrix on call {i}"


# -- decode_batch permutation fast path --------------------------------------

def test_decode_batch_permuted_and_identity_sources():
    codec = RSCodec(K, M, device="jax")
    ref = RSCodec(K, M, device="numpy")
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, (4, K, CHUNK), np.uint8)
    parity = np.stack([np.asarray(codec.encode(d)) for d in data])
    full = np.concatenate([data, parity], axis=1)       # [B, K+M, CHUNK]
    for src in ([1, 2, 3, K],            # identity (already sorted)
                [K, 3, 1, 2],            # permuted
                [1, 2, 3, K, K + 1]):    # extras beyond k: dropped
        stack = full[:, src, :]
        got = codec.decode_batch(stack, list(src), [0])
        want = ref.decode_batch(stack, list(src), [0])
        np.testing.assert_array_equal(got, want)
        np.testing.assert_array_equal(got[:, 0, :], data[:, 0, :])


def test_src_index_map_identity_and_gather():
    assert RSCodec._src_index_map([1, 2, 3], [1, 2, 3]) is None
    assert RSCodec._src_index_map([1, 2, 3, 9], [1, 2, 3]) is None
    assert RSCodec._src_index_map([3, 1, 2], [1, 2, 3]) == [1, 2, 0]


# -- device-resident decode variants (no host round-trip) --------------------

def test_decode_device_and_batch_device_match_host():
    import jax.numpy as jnp
    codec = RSCodec(K, M, device="jax")
    ref = RSCodec(K, M, device="numpy")
    rng = np.random.default_rng(13)
    data = rng.integers(0, 256, (2, K, CHUNK), np.uint8)
    parity = np.stack([np.asarray(codec.encode(d)) for d in data])
    full = np.concatenate([data, parity], axis=1)
    src = [1, 2, 3, K]
    out = codec.decode_batch_device(jnp.asarray(full[:, src, :]),
                                    src, [0])
    want = ref.decode_batch(full[:, src, :], src, [0])
    np.testing.assert_array_equal(np.asarray(out), want)
    # single-stack variant, survivors already in sorted-src order
    one = codec.decode_device(jnp.asarray(full[0][src]), [0],
                              available=src)
    np.testing.assert_array_equal(np.asarray(one)[0], data[0, 0])


# -- the mesh-sharded serving batch path -------------------------------------

def test_mesh_serving_batches_bitwise_identical(ec, sinfo):
    payloads = _payloads(8, seed=17)
    plain = ServingEngine(ec_impl=ec, sinfo=sinfo, name="m0",
                          pipeline_depth=4)
    meshed = ServingEngine(ec_impl=ec, sinfo=sinfo, name="m8",
                           pipeline_depth=4)
    meshed.pipeline.mesh_devices = 8       # conftest forces 8 cpu devices
    try:
        futs_a = [plain.submit_encode(p) for p in payloads]
        plain.flush()
        futs_b = [meshed.submit_encode(p) for p in payloads]
        meshed.flush()
        assert meshed.pipeline.perf.get("mesh_dispatches") > 0, \
            "mesh path did not engage"
        encs = []
        for fa, fb in zip(futs_a, futs_b):
            a, b = fa.result(10), fb.result(10)
            for c in a:
                np.testing.assert_array_equal(np.asarray(a[c]),
                                              np.asarray(b[c]))
            encs.append(b)
        degraded = [{c: v for c, v in e.items() if c != 0} for e in encs]
        before = meshed.pipeline.perf.get("mesh_dispatches")
        dfuts = [meshed.submit_decode(d) for d in degraded]
        meshed.flush()
        assert [f.result(10) for f in dfuts] == [bytes(p) for p in payloads]
        assert meshed.pipeline.perf.get("mesh_dispatches") > before
    finally:
        plain.stop()
        meshed.stop()


def test_mesh_option_ignored_when_too_few_devices(ec, sinfo):
    eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="m64",
                        pipeline_depth=4)
    eng.pipeline.mesh_devices = 64         # more than the virtual mesh has
    try:
        fut = eng.submit_encode(_payloads(1)[0])
        eng.flush()
        assert fut.result(10)              # falls back to single-chip
        assert eng.pipeline.perf.get("mesh_dispatches") == 0
    finally:
        eng.stop()


# -- recovery wave decode through the pipeline -------------------------------

def test_decode_shards_many_pipelined_matches_sync(ec, sinfo):
    bufs = _payloads(6, seed=19)
    encoded = ecutil.encode_many(sinfo, ec, bufs)
    # two distinct survivor signatures in one wave
    batches = []
    for i, chunks in enumerate(encoded):
        lost = 0 if i % 2 else 1
        batches.append(({c: v for c, v in chunks.items() if c != lost},
                        {lost}))
    sync = ecutil.decode_shards_many(sinfo, ec, batches)
    pl = CodecPipeline(depth=4, name="wave")
    try:
        piped = ecutil.decode_shards_many(sinfo, ec, batches, pipeline=pl)
    finally:
        pl.close()
    for a, b in zip(sync, piped):
        assert set(a) == set(b)
        for c in a:
            np.testing.assert_array_equal(np.asarray(a[c]),
                                          np.asarray(b[c]))
