"""cephx ticket protocol: handshake, tickets, authorizers, rotation.

Mirrors the reference's cephx flows (reference: src/auth/cephx/
CephxProtocol.{h,cc}): challenge-response authentication, service
tickets sealed under rotating secrets, authorizer verification with
mutual auth and replay defense, expiry and renewal.
"""
import pytest

from ceph_tpu.auth import (AuthError, CephxClient, CephxServiceHandler,
                           KeyServer)


@pytest.fixture()
def world():
    ks = KeyServer()
    key = ks.create_entity("client.admin")
    ks.rotate("osd")
    client = CephxClient("client.admin", key)
    osd = CephxServiceHandler("osd", ks)
    return ks, client, osd


class TestHandshake:
    def test_full_mutual_auth(self, world):
        ks, client, osd = world
        client.authenticate(ks, now=0.0)
        client.get_ticket(ks, "osd", now=0.0)
        authz = client.build_authorizer("osd", now=1.0)
        name, reply = osd.verify_authorizer(authz, now=1.0)
        assert name == "client.admin"
        client.verify_reply("osd", reply, authz.nonce)   # server proved too

    def test_wrong_entity_key_rejected(self, world):
        ks, _, _ = world
        impostor = CephxClient("client.admin", b"\x00" * 32)
        with pytest.raises(AuthError, match="bad authenticate"):
            impostor.authenticate(ks, now=0.0)

    def test_unknown_entity_rejected(self, world):
        ks, _, _ = world
        ghost = CephxClient("client.ghost", b"\x00" * 32)
        with pytest.raises(AuthError, match="unknown entity"):
            ghost.authenticate(ks, now=0.0)

    def test_ticket_requires_session(self, world):
        ks, client, _ = world
        with pytest.raises(AuthError, match="authenticate first"):
            client.get_ticket(ks, "osd", now=0.0)


class TestAuthorizers:
    def test_tampered_ticket_rejected(self, world):
        ks, client, osd = world
        client.authenticate(ks, now=0.0)
        client.get_ticket(ks, "osd", now=0.0)
        authz = client.build_authorizer("osd", now=0.0)
        authz.blob = authz.blob[:-1] + bytes([authz.blob[-1] ^ 1])
        with pytest.raises(AuthError, match="bad magic"):
            osd.verify_authorizer(authz, now=0.0)

    def test_replayed_authorizer_rejected(self, world):
        ks, client, osd = world
        client.authenticate(ks, now=0.0)
        client.get_ticket(ks, "osd", now=0.0)
        authz = client.build_authorizer("osd", now=0.0)
        osd.verify_authorizer(authz, now=0.0)
        with pytest.raises(AuthError, match="replay"):
            osd.verify_authorizer(authz, now=0.0)

    def test_wrong_service_rejected(self, world):
        ks, client, _ = world
        ks.rotate("mds")
        client.authenticate(ks, now=0.0)
        client.get_ticket(ks, "osd", now=0.0)
        mds = CephxServiceHandler("mds", ks)
        authz = client.build_authorizer("osd", now=0.0)
        with pytest.raises(AuthError, match="wrong service"):
            mds.verify_authorizer(authz, now=0.0)

    def test_expired_ticket_rejected_then_renewed(self, world):
        ks, client, osd = world
        client.authenticate(ks, now=0.0)
        client.get_ticket(ks, "osd", now=0.0)
        late = KeyServer.TICKET_VALIDITY + 1
        with pytest.raises(AuthError, match="expired"):
            client.build_authorizer("osd", now=late)
        # renewal: re-authenticate, new ticket works
        client.authenticate(ks, now=late)
        client.get_ticket(ks, "osd", now=late)
        authz = client.build_authorizer("osd", now=late + 1)
        name, _ = osd.verify_authorizer(authz, now=late + 1)
        assert name == "client.admin"


class TestRotation:
    def test_old_generation_valid_within_grace(self, world):
        """One rotation after ticket issue: the service still holds the
        previous generation and accepts the ticket (rotation grace)."""
        ks, client, osd = world
        client.authenticate(ks, now=0.0)
        client.get_ticket(ks, "osd", now=0.0)
        ks.rotate("osd")                        # one generation forward
        authz = client.build_authorizer("osd", now=1.0)
        name, _ = osd.verify_authorizer(authz, now=1.0)
        assert name == "client.admin"

    def test_two_rotations_invalidate_ticket(self, world):
        ks, client, osd = world
        client.authenticate(ks, now=0.0)
        client.get_ticket(ks, "osd", now=0.0)
        ks.rotate("osd")
        ks.rotate("osd")                        # grace window passed
        authz = client.build_authorizer("osd", now=1.0)
        with pytest.raises(AuthError, match="expired"):
            osd.verify_authorizer(authz, now=1.0)
        # refresh: new ticket under the current generation works
        client.get_ticket(ks, "osd", now=1.0)
        authz2 = client.build_authorizer("osd", now=1.0)
        assert osd.verify_authorizer(authz2, now=1.0)[0] == "client.admin"
