"""ISSUE 20: the zero-copy device-direct data path.

Bitwise equivalence of the sideband wire format against the legacy
pickle path (any chunking, 1-byte partial reads, reordered frame
bursts), memoryview-lifetime safety under the stream parser's
compaction and BufferError fallback, the fused encode+checksum kernel
against the host crc loop, and the copy ledger's end-to-end
copies-per-byte contrast over a real mux stack.
"""
import os
import random
import threading

import numpy as np
import pytest

import ceph_tpu.net as net
from ceph_tpu.backend import ecutil, wire
from ceph_tpu.common import copy_ledger
from ceph_tpu.msg import proto  # noqa: F401 — registers batch codecs
from ceph_tpu.msg.parser import StreamParser
from ceph_tpu.msg.staging import StagingPool

SECRET = bytes(range(32))


def _rng(seed: int) -> np.random.Generator:
    return np.random.default_rng(seed)


def _flatten(parts: list) -> bytes:
    return b"".join(bytes(p) if isinstance(p, memoryview) else p
                    for p in parts)


def _parse_one(blob: bytes, secret, staging=None):
    p = StreamParser(secret)
    frames = p.feed(blob)
    assert len(frames) == 1 and p.pending() == 0
    tag, segs = frames[0]
    return net._decode(tag, segs, authed=True, staging=staging)


# -- the frame splice: frame_encode_parts == frame_encode --------------------

class TestFramePartsEquality:
    @pytest.mark.parametrize("secret", [None, SECRET],
                             ids=["crc", "secure"])
    def test_scattered_segment_bitwise_equal(self, secret):
        """A scattered third segment (length table + spliced payload
        views) wires byte-for-byte identically to the joined frame, in
        BOTH integrity modes — the device->wire splice never changes
        what the peer verifies."""
        pieces = [b"\x07" * 12, memoryview(bytes(range(256)) * 17),
                  b"xy", memoryview(b"z" * 4096),
                  memoryview(b"s" * 8)]          # small view: coalesces
        segs_joined = [b"RpcBatch", b"header", _flatten(pieces)]
        segs_parts = [b"RpcBatch", b"header", pieces]
        joined = wire.frame_encode(wire.TAG_MESSAGE, segs_joined,
                                   secret=secret)
        parts = wire.frame_encode_parts(wire.TAG_MESSAGE, segs_parts,
                                        secret=secret)
        assert _flatten(parts) == joined
        # the bulk views really splice unjoined (no hidden join copy)
        spliced = [p for p in parts if isinstance(p, memoryview)]
        assert len(spliced) == 2
        assert spliced[0].obj is pieces[1].obj
        assert spliced[1].obj is pieces[3].obj


# -- the sideband codec: _encode_parts == _encode ----------------------------

class TestSidebandCodec:
    @pytest.mark.parametrize("n", [1024, 4096, 65536, 1 << 20])
    def test_rpc_call_roundtrips_match_legacy(self, n):
        payload = os.urandom(n)
        msg = net.RpcCall(3, "put", {"pool": "p", "data": payload},
                          session="S1")
        parts = net._encode_parts(msg, SECRET)
        assert parts is not None
        legacy = net._encode(msg, SECRET)
        got_sb = _parse_one(_flatten(parts), SECRET)
        got_legacy = _parse_one(legacy, SECRET)
        assert bytes(got_sb.args["data"]) == payload \
            == bytes(got_legacy.args["data"])
        assert got_sb.args["pool"] == "p" and got_sb.rid == 3
        assert got_sb.session == "S1"
        # extraction never mutates the original (retries resend it)
        assert msg.args["data"] is payload

    def test_result_batch_staged_landing(self):
        from ceph_tpu.msg.proto import RpcResultBatch
        payloads = [os.urandom(2048), os.urandom(5000), os.urandom(1024)]
        msg = RpcResultBatch([net.RpcResult(i, True, p)
                              for i, p in enumerate(payloads)])
        parts = net._encode_parts(msg, SECRET)
        assert parts is not None
        pool = StagingPool("test")
        base = copy_ledger.ledger().snapshot()["copied"]["staging"]
        got = _parse_one(_flatten(parts), SECRET, staging=pool)
        for r, p in zip(got.results, payloads):
            assert isinstance(r.value, memoryview)   # staged slice
            assert bytes(r.value) == p
        # all three landed with ONE staged copy of the whole sideband
        assert pool.stats["staged_buffers"] == 1
        led = copy_ledger.ledger().snapshot()["copied"]["staging"]
        assert led >= base + sum(len(p) for p in payloads)

    def test_small_payloads_stay_pickled_but_weigh_in_ledger(self):
        """Eligible-but-small values (>= PAYLOAD_MIN, < splice
        threshold) do not lift — the header rewrite would cost more
        than the copy — but their bytes still count as legacy copies,
        so the ratio cannot flatter the small-op path."""
        small = os.urandom(net._SB_SPLICE_MIN - 1)
        msg = net.RpcCall(1, "put", {"data": small}, session="S")
        assert net._encode_parts(msg, SECRET) is None
        base = copy_ledger.ledger().snapshot()["copied"]["pickle"]
        blob = net._encode(msg, SECRET)
        assert bytes(_parse_one(blob, SECRET).args["data"]) == small
        assert copy_ledger.ledger().snapshot()["copied"]["pickle"] \
            >= base + len(small)
        # sub-PAYLOAD_MIN values are invisible to the whole machinery
        tiny = net.RpcCall(2, "put", {"data": os.urandom(8)}, session="S")
        assert net._encode_parts(tiny, SECRET) is None

    def test_kill_switch_gates_encode_side_only(self):
        payload = os.urandom(4096)
        msg = net.RpcCall(9, "put", {"data": payload}, session="S")
        parts = net._encode_parts(msg, SECRET)
        assert parts is not None
        net.set_zero_copy(False)
        try:
            assert net._encode_parts(msg, SECRET) is None
        finally:
            net.set_zero_copy(True)
        # decode accepts sideband frames regardless of the switch:
        # mixed peers interoperate
        net.set_zero_copy(False)
        try:
            got = _parse_one(_flatten(parts), SECRET)
        finally:
            net.set_zero_copy(True)
        assert bytes(got.args["data"]) == payload


# -- the stream parser: chunking, reordering, lifetime -----------------------

class TestStreamParserZeroCopy:
    def _frames(self, seed: int, sizes) -> list[tuple[bytes, bytes]]:
        """(wire_blob, payload) per frame: a mix of sideband and legacy
        encodings of the same call shape."""
        rng = random.Random(seed)
        out = []
        for i, n in enumerate(sizes):
            payload = os.urandom(n)
            msg = net.RpcCall(i, "put", {"data": payload},
                              session=f"S{i}")
            if rng.random() < 0.5:
                parts = net._encode_parts(msg, SECRET)
                blob = _flatten(parts) if parts is not None \
                    else net._encode(msg, SECRET)
            else:
                blob = net._encode(msg, SECRET)
            out.append((blob, payload))
        return out

    @pytest.mark.parametrize("chunk", [1, 7, 4096])
    def test_partial_reads_any_chunking(self, chunk):
        """1-byte and odd-size partial reads across frame boundaries
        decode bitwise-identically to whole-frame feeds — including
        sideband frames whose payload segment spans many feeds."""
        frames = self._frames(chunk, [40, 1024, 9000, 64, 2048])
        stream = b"".join(b for b, _ in frames)
        p = StreamParser(SECRET)
        got = []
        for off in range(0, len(stream), chunk):
            for tag, segs in p.feed(stream[off:off + chunk]):
                got.append(net._decode(tag, segs, authed=True))
        assert [bytes(m.args["data"]) for m in got] \
            == [pl for _, pl in frames]
        assert p.pending() == 0

    def test_reordered_bursts_decode_in_arrival_order(self):
        """Frames delivered in a different burst order (the coalescer
        re-queues under backpressure) decode to exactly the payloads in
        arrival order — no cross-frame buffer state leaks."""
        frames = self._frames(99, [2048, 1024, 70000, 31, 4096])
        order = [2, 0, 4, 1, 3]
        rng = random.Random(7)
        p = StreamParser(SECRET)
        got = []
        for i in order:
            blob = frames[i][0]
            off = 0
            while off < len(blob):      # bursts misaligned with frames
                step = rng.randrange(1, 1 + len(blob) - off)
                for tag, segs in p.feed(blob[off:off + step]):
                    got.append(net._decode(tag, segs, authed=True))
                off += step
        assert [bytes(m.args["data"]) for m in got] \
            == [frames[i][1] for i in order]

    def test_staged_payloads_survive_parser_reuse(self):
        """A staged payload stays intact after the parser buffer that
        produced it is overwritten by later feeds — the staging copy is
        what makes handing views across threads safe."""
        pool = StagingPool("lifetime")
        payload = os.urandom(8192)
        msg = net.RpcCall(1, "put", {"data": payload}, session="S")
        blob = _flatten(net._encode_parts(msg, SECRET))
        p = StreamParser(SECRET)
        (tag, segs), = p.feed(blob)
        got = net._decode(tag, segs, authed=True, staging=pool)
        staged = got.args["data"]
        for i in range(2, 6):           # stomp the parser buffer
            m2 = net.RpcCall(i, "put", {"data": os.urandom(8192)},
                             session="S")
            p.feed(_flatten(net._encode_parts(m2, SECRET)))
        assert bytes(staged) == payload

    def test_retained_view_fallback_counted_and_safe(self):
        """A caller that (wrongly) retains a segment view across feeds
        pins the buffer: the next feed's BufferError fallback rebuilds
        it, COUNTS the copied bytes in the ledger, and the retained
        view still reads the original bytes."""
        p = StreamParser(SECRET)
        m1 = net.RpcCall(1, "put", {"data": os.urandom(2000)},
                         session="S")
        (tag, segs), = p.feed(net._encode(m1, SECRET))
        retained = segs[1]              # memoryview into p's buffer
        header_bytes = bytes(retained)
        base = copy_ledger.ledger().snapshot()["copied"]["fallback"]
        m2 = net.RpcCall(2, "put", {"data": os.urandom(3000)},
                         session="S")
        blob2 = net._encode(m2, SECRET)
        (tag2, segs2), = p.feed(blob2)
        got2 = net._decode(tag2, segs2, authed=True)
        assert bytes(got2.args["data"]) == m2.args["data"]
        assert copy_ledger.ledger().snapshot()["copied"]["fallback"] \
            >= base + len(blob2)
        assert bytes(retained) == header_bytes

    def test_compaction_tail_move_is_counted(self):
        """The amortized head-trim's tail move reports to the ledger:
        park a partial frame behind >64 KiB of consumed stream, then
        let the next feed compact — the moved tail bytes appear under
        ``compaction``."""
        p = StreamParser(SECRET)
        big = net._encode(net.RpcCall(1, "put",
                                      {"data": os.urandom(80000)},
                                      session="S"), SECRET)
        tail_msg = net.RpcCall(2, "put", {"data": os.urandom(4000)},
                               session="S")
        tail = net._encode(tail_msg, SECRET)
        half = len(tail) // 2
        frames = p.feed(big + tail[:half])
        assert len(frames) == 1 and p.pending() == half
        del frames                       # sever the views: buffer free
        base = copy_ledger.ledger().snapshot()["copied"]["compaction"]
        (tag, segs), = p.feed(tail[half:])
        assert bytes(net._decode(tag, segs, authed=True)
                     .args["data"]) == tail_msg.args["data"]
        assert copy_ledger.ledger().snapshot()["copied"]["compaction"] \
            >= base + half


# -- the fused encode + checksum kernel --------------------------------------

class TestFusedChecksum:
    @pytest.mark.parametrize("n", [1, 2, 63, 64, 777, 4096])
    def test_crc32c_rows_matches_host(self, n):
        from ceph_tpu.ops import rs_kernels
        rows = _rng(n).integers(0, 256, size=(5, n), dtype=np.uint8)
        dev = np.asarray(rs_kernels.crc32c_rows(rows))
        host = [ecutil.crc32c(0, bytes(r)) for r in rows]
        assert [int(x) for x in dev] == host

    @pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (6, 3)])
    @pytest.mark.parametrize("n", [64, 1000, 4096])
    def test_encode_with_crc_bitwise(self, k, m, n):
        """The fused dispatch returns the SAME parity as the host
        reference and the SAME seed-free row crcs as a host loop over
        concat(data, parity) — across geometries and non-pow2 widths."""
        from ceph_tpu.ops.codec import RSCodec
        codec = RSCodec(k, m)
        data = _rng(k * 1000 + n).integers(0, 256, size=(k, n),
                                           dtype=np.uint8)
        parity, crcs = codec.encode_with_crc(data)
        ref = codec.encode_host(data)
        assert np.array_equal(parity, ref)
        rows = np.concatenate([data, ref], axis=0)
        assert [int(c) for c in crcs] \
            == [ecutil.crc32c(0, bytes(r)) for r in rows]

    def test_append_crcs_matches_append(self):
        """Chaining device crcs through the crc32_combine identity is
        bitwise-identical to the host running-seed append, across
        multiple uneven-length appends."""
        rng = _rng(17)
        h_ref, h_dev = ecutil.HashInfo(3), ecutil.HashInfo(3)
        old = 0
        for nbytes in (512, 64, 1 << 14, 33):
            chunks = {s: rng.integers(0, 256, size=nbytes,
                                      dtype=np.uint8)
                      for s in range(3)}
            h_ref.append(old, chunks)
            h_dev.append_crcs(
                old, {s: ecutil.crc32c(0, bytes(c))
                      for s, c in chunks.items()}, nbytes)
            old += nbytes
        assert h_ref.cumulative_shard_hashes \
            == h_dev.cumulative_shard_hashes
        assert h_ref.total_chunk_size == h_dev.total_chunk_size

    def test_hinfo_append_device_path_matches_host(self):
        """``hinfo_append`` with a device-codec plugin fuses the shard
        crcs into one kernel call and lands the same running hashes as
        the pure host append."""
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        ec_impl = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {"k": "4", "m": "2", "device": "jax",
                           "technique": "reed_sol_van"})
        assert ec_impl.device_codec(4096 * 6) is not None
        rng = _rng(23)
        h_ref, h_dev = ecutil.HashInfo(6), ecutil.HashInfo(6)
        old = 0
        for nbytes in (4096, 512):
            chunks = {s: rng.integers(0, 256, size=nbytes,
                                      dtype=np.uint8)
                      for s in range(6)}
            h_ref.append(old, chunks)
            ecutil.hinfo_append(h_dev, old, chunks, ec_impl=ec_impl)
            old += nbytes
        assert h_ref.cumulative_shard_hashes \
            == h_dev.cumulative_shard_hashes

    def test_pack_shard_major_matches_reference(self):
        """The single-allocation batched relayout equals per-buffer
        ``_to_shard_major`` + concatenate, for mixed stripe counts."""
        k, c = 4, 32
        rng = _rng(5)
        arrs = [rng.integers(0, 256, size=k * c * s, dtype=np.uint8)
                for s in (1, 3, 2, 7)]
        packed = ecutil._pack_shard_major(arrs, k, c)
        ref = np.concatenate(
            [ecutil._to_shard_major(a, k, c) for a in arrs], axis=1)
        assert np.array_equal(packed, ref)


# -- the whole stack: mux on/off equivalence + the ledger contrast -----------

@pytest.fixture
def served(tmp_path):
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.net import ClusterServer
    c = MiniCluster(n_osds=3, osds_per_host=3, chunk_size=512,
                    data_dir=tmp_path)
    server = ClusterServer(c)
    server.start()
    yield server, tmp_path / "client.admin.keyring"
    server.stop()
    c.shutdown()


class TestEndToEnd:
    def _pings(self, server, keyring, n, size, seed, zero_copy):
        from ceph_tpu.msg import MuxClient
        # the cluster cct IS the process default context, so the mux
        # client's ms_zero_copy observer (adopted at construction) sees
        # the override — net.set_zero_copy alone would be re-adopted
        conf = server.cluster.cct.conf
        saved = conf.get("ms_zero_copy")
        conf.set("ms_zero_copy", zero_copy)
        mux = MuxClient("127.0.0.1", server.port, keyring, n_conns=1)
        rng = _rng(seed)
        try:
            mux.connect()
            s = mux.session()
            for i in range(n):
                payload = bytes(rng.integers(0, 256, size=size,
                                             dtype=np.uint8))
                echoed = s.call("ping", {"payload": payload},
                                timeout=30.0)
                assert bytes(echoed) == payload
        finally:
            mux.close()
            conf.set("ms_zero_copy", saved)

    def test_fused_and_legacy_arms_agree_and_contrast(self, served):
        """Both transport arms echo bulk payloads bitwise; the ledger
        separates them — the fused arm moves each served byte at most
        ~1.5 times, the legacy arm at least ~2.5 (pickle + join +
        unpickle per direction)."""
        server, keyring = served
        led = copy_ledger.ledger()
        led.reset()
        self._pings(server, keyring, 8, 65536, seed=1, zero_copy=True)
        fused = led.snapshot()
        led.reset()
        try:
            self._pings(server, keyring, 8, 65536, seed=2,
                        zero_copy=False)
        finally:
            net.set_zero_copy(True)
        legacy = led.snapshot()
        assert fused["served"] >= 8 * 2 * 65536
        assert legacy["served"] >= 8 * 2 * 65536
        assert fused["copies_per_byte"] <= 1.5, fused
        assert legacy["copies_per_byte"] >= 2.5, legacy
        # the fused arm's copies are the sanctioned landing copies, not
        # codec copies
        sanctioned = fused["copied"]["staging"] \
            + fused["copied"]["materialize"]
        assert sanctioned >= 0.9 * fused["copied_total"], fused
