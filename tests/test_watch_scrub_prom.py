"""watch/notify, scheduled scrub+repair, prometheus exporter.

Reference analogs: librados watch2/notify2 through PrimaryLogPG's
watcher machinery (src/osd/Watch.cc); 'ceph pg deep-scrub' + repair
via background scrub work; the mgr prometheus module's text format
(src/pybind/mgr/prometheus/module.py).
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osd.osd_ops import ObjectOperation


@pytest.fixture
def cluster():
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
    pid = c.create_ec_pool("p", {"k": "2", "m": "1", "device": "numpy"},
                           pg_num=4)
    yield c, pid
    c.shutdown()


class TestWatchNotify:
    def test_watch_notify_roundtrip(self, cluster):
        c, pid = cluster
        c.operate(pid, "w", ObjectOperation().write_full(b"watched"))
        got = []

        def on_notify(notify_id, cookie, payload):
            got.append((cookie, payload))
            return b"ack-from-" + str(cookie).encode()
        c.operate(pid, "w", ObjectOperation().watch(1, on_notify))
        c.operate(pid, "w", ObjectOperation().watch(2, on_notify))
        r = c.operate(pid, "w", ObjectOperation().notify(b"hello"))
        assert got == [(1, b"hello"), (2, b"hello")]
        assert r.outdata(0) == {1: b"ack-from-1", 2: b"ack-from-2"}
        assert c.operate(pid, "w", ObjectOperation()
                         .list_watchers()).outdata(0) == [1, 2]

    def test_unwatch_stops_delivery(self, cluster):
        c, pid = cluster
        c.operate(pid, "u", ObjectOperation().write_full(b"x"))
        got = []
        c.operate(pid, "u", ObjectOperation().watch(
            7, lambda n, ck, p: got.append(p)))
        c.operate(pid, "u", ObjectOperation().unwatch(7))
        c.operate(pid, "u", ObjectOperation().notify(b"gone"))
        assert got == []
        with pytest.raises(IOError):       # unknown cookie
            c.operate(pid, "u", ObjectOperation().unwatch(7))

    def test_broken_watcher_does_not_block_notify(self, cluster):
        c, pid = cluster
        c.operate(pid, "b", ObjectOperation().write_full(b"x"))

        def bad(n, ck, p):
            raise RuntimeError("watcher crashed")
        c.operate(pid, "b", ObjectOperation().watch(1, bad))
        c.operate(pid, "b", ObjectOperation().watch(
            2, lambda n, ck, p: b"ok"))
        r = c.operate(pid, "b", ObjectOperation().notify(b"ping"))
        acks = r.outdata(0)
        assert isinstance(acks[1], RuntimeError)
        assert acks[2] == b"ok"

    def test_delete_discards_watchers(self, cluster):
        c, pid = cluster
        c.operate(pid, "d", ObjectOperation().write_full(b"x"))
        c.operate(pid, "d", ObjectOperation().watch(
            1, lambda n, ck, p: b"a"))
        c.operate(pid, "d", ObjectOperation().remove())
        with pytest.raises(IOError):       # notify on a deleted object
            c.operate(pid, "d", ObjectOperation().notify(b"?"))
        c.operate(pid, "d", ObjectOperation().write_full(b"new"))
        assert c.operate(pid, "d", ObjectOperation()
                         .list_watchers()).outdata(0) == []


class TestScrubScheduling:
    def test_clean_pool_scrubs_clean(self, cluster):
        c, pid = cluster
        for i in range(6):
            c.put(pid, f"s{i}", np.random.default_rng(i).integers(
                0, 256, 1500, np.uint8).tobytes())
        assert c.scrub_pool(pid) == {}

    def test_scrub_detects_and_repairs_corruption(self, cluster):
        from ceph_tpu.backend.memstore import GObject
        c, pid = cluster
        payload = np.random.default_rng(3).integers(
            0, 256, 2000, np.uint8).tobytes()
        c.put(pid, "victim", payload)
        g = c.pg_group(pid, "victim")
        # flip bytes in a NON-primary shard's stored chunk (bitrot)
        shard = g.acting[1]
        store = g.bus.handlers[shard].store
        obj = GObject("victim", shard)
        data = bytearray(store.read(obj))
        data[0] ^= 0xFF
        store.objects[obj].data[:] = data
        report = c.scrub_pool(pid, repair=True)
        assert any("victim" in bad for bad in report.values())
        # repaired: a second scrub is clean and reads are intact
        assert c.scrub_pool(pid) == {}
        assert c.get(pid, "victim", 2000) == payload


class TestPrometheus:
    def test_render_format(self, cluster):
        from ceph_tpu.mgr.prometheus import render
        c, pid = cluster
        c.put(pid, "m", b"metrics" * 100)
        text = render(c.cct)
        assert text.endswith("\n")
        lines = text.splitlines()
        assert any(line.startswith("# TYPE ceph_tpu_") for line in lines)
        # counters carry the collection label and a numeric value
        sample = next(line for line in lines
                      if not line.startswith("#") and "collection=" in line)
        name_and_labels, value = sample.rsplit(" ", 1)
        float(value)
        assert name_and_labels.startswith("ceph_tpu_")
        # time averages render as summary sum/count pairs
        assert any("_sum{" in line for line in lines)
        assert any("_count{" in line for line in lines)
        # HELP precedes TYPE for every family, exactly once per metric
        helps = [line.split(" ", 2)[2].split(" ", 1)[0] for line in lines
                 if line.startswith("# HELP ")]
        types = [line.split(" ", 2)[2].split(" ", 1)[0] for line in lines
                 if line.startswith("# TYPE ")]
        assert helps and helps == types
        assert len(types) == len(set(types)), "duplicate TYPE lines"

    def test_histogram_exposition_pinned(self):
        """Real Prometheus scrapers require the `_sum` series (and HELP)
        for histogram types, with ONE label set across _bucket/_count/
        _sum (regression: _sum was missing and HELP never emitted)."""
        from ceph_tpu.common import Context, PerfCountersBuilder
        from ceph_tpu.mgr.prometheus import render
        cct = Context()
        pc = (PerfCountersBuilder("histo_test")
              .add_histogram("op_lat", [1, 10, 100],
                             "op latency histogram")
              .create_perf_counters())
        cct.perf.add(pc)
        pc.hinc("op_lat", 5)
        pc.hinc("op_lat", 250)         # overflow -> +Inf only
        text = render(cct)
        lines = text.splitlines()
        assert lines.count("# TYPE ceph_tpu_op_lat histogram") == 1
        assert "# HELP ceph_tpu_op_lat op latency histogram" in lines
        # cumulative buckets, +Inf, then _sum and _count — one label set
        assert 'ceph_tpu_op_lat_bucket{collection="histo_test",' \
               'le="1"} 0' in lines
        assert 'ceph_tpu_op_lat_bucket{collection="histo_test",' \
               'le="10"} 1' in lines
        assert 'ceph_tpu_op_lat_bucket{collection="histo_test",' \
               'le="100"} 1' in lines
        assert 'ceph_tpu_op_lat_bucket{collection="histo_test",' \
               'le="+Inf"} 2' in lines
        assert 'ceph_tpu_op_lat_sum{collection="histo_test"} 255.0' in lines
        assert 'ceph_tpu_op_lat_count{collection="histo_test"} 2' in lines

    def test_mclock_queue_depth_gauges_rendered(self):
        """OSD daemon mClock queue depths export as ONE gauge family
        (`ceph_tpu_mclock_queue_depth`, owner/shard/op_class labels) with
        the same HELP-once/TYPE-once invariants as every other family —
        scraped mid-queue, before a drain empties the gauges."""
        from ceph_tpu.common import Context
        from ceph_tpu.mgr.prometheus import render
        from ceph_tpu.osd.mclock import BG_SCRUB
        from ceph_tpu.osd.osd_daemon import OSDDaemon
        d = OSDDaemon(whoami=77, num_shards=1)
        for i in range(3):
            d.queue_background("pg", lambda: None, op_class=BG_SCRUB)
        try:
            text = render(Context())
            lines = text.splitlines()
            assert lines.count(
                "# TYPE ceph_tpu_mclock_queue_depth gauge") == 1
            assert any(line.startswith(
                "# HELP ceph_tpu_mclock_queue_depth ") for line in lines)
            assert 'ceph_tpu_mclock_queue_depth{owner="osd.77",' \
                   'shard="0",op_class="bg_scrub"} 3' in lines
            # HELP/TYPE stay unique across the whole payload
            types = [line.split(" ", 2)[2].split(" ", 1)[0]
                     for line in lines if line.startswith("# TYPE ")]
            assert len(types) == len(set(types)), "duplicate TYPE lines"
        finally:
            d.drain()                     # leave no cross-test gauges

    def test_health_status_gauges_rendered(self):
        """Every REGISTERED check of every live HealthCheckEngine
        exports ONE `ceph_tpu_health_status` gauge (0=ok 1=warn 2=err),
        labelled owner+check, with the HELP/TYPE-once invariants."""
        from ceph_tpu.common import Context
        from ceph_tpu.mgr.health import HEALTH_ERR, HealthCheckEngine
        from ceph_tpu.mgr.prometheus import render
        eng = HealthCheckEngine(name="promtest")
        eng.register("ALWAYS_OK", lambda: None)
        eng.register("ALWAYS_BAD", lambda: "2 things bad",
                     severity=HEALTH_ERR)
        try:
            text = render(Context())
            lines = text.splitlines()
            assert lines.count("# TYPE ceph_tpu_health_status gauge") == 1
            assert any(line.startswith("# HELP ceph_tpu_health_status ")
                       for line in lines)
            assert 'ceph_tpu_health_status{owner="promtest",' \
                   'check="ALWAYS_OK"} 0' in lines
            assert 'ceph_tpu_health_status{owner="promtest",' \
                   'check="ALWAYS_BAD"} 2' in lines
            types = [line.split(" ", 2)[2].split(" ", 1)[0]
                     for line in lines if line.startswith("# TYPE ")]
            assert len(types) == len(set(types)), "duplicate TYPE lines"
        finally:
            eng.close()

    def test_recovery_reserver_gauges_rendered(self):
        """Live RecoveryScheduler reservers export per-OSD queue-depth
        and in-flight gauges (`ceph_tpu_recovery_reserver_queued` /
        `_granted`, owner/kind/osd labels) with the HELP/TYPE-once
        invariants — scraped while a reservation is held."""
        from ceph_tpu.common import Context
        from ceph_tpu.mgr.prometheus import render
        from ceph_tpu.recovery import RecoveryScheduler
        sched = RecoveryScheduler(cct=Context(), name="promrec")
        try:
            sched.local_reserver(3).request_reservation(
                "pgA", lambda: None, prio=180)
            sched.local_reserver(3).request_reservation(
                "pgB", lambda: None, prio=180)
            sched.remote_reserver(5).request_reservation(
                ("pgA", 5), lambda: None, prio=180)
            text = render(Context())
            lines = text.splitlines()
            assert lines.count(
                "# TYPE ceph_tpu_recovery_reserver_queued gauge") == 1
            assert lines.count(
                "# TYPE ceph_tpu_recovery_reserver_granted gauge") == 1
            assert 'ceph_tpu_recovery_reserver_granted{owner="promrec",' \
                   'kind="local",osd="3"} 1' in lines
            assert 'ceph_tpu_recovery_reserver_queued{owner="promrec",' \
                   'kind="local",osd="3"} 1' in lines
            assert 'ceph_tpu_recovery_reserver_granted{owner="promrec",' \
                   'kind="remote",osd="5"} 1' in lines
            types = [line.split(" ", 2)[2].split(" ", 1)[0]
                     for line in lines if line.startswith("# TYPE ")]
            assert len(types) == len(set(types)), "duplicate TYPE lines"
        finally:
            sched.close()

    def test_stats_rate_gauges_rendered(self):
        """Live StatsAggregators export the PGMap-style digest as ONE
        `ceph_tpu_stats_rate` gauge family (owner + stat labels)."""
        from ceph_tpu.common import Context
        from ceph_tpu.mgr.prometheus import render
        from ceph_tpu.mgr.stats import StatsAggregator
        agg = StatsAggregator(cct=Context(), name="promstats")
        try:
            text = render(Context())
            assert text.count("# TYPE ceph_tpu_stats_rate gauge") == 1
            assert 'ceph_tpu_stats_rate{owner="promstats",' \
                   'stat="client_wr_bytes_s"} 0' in text
        finally:
            agg.close()

    def test_wire_counter_families_rendered(self):
        """WireAccounting exports through BOTH surfaces: the ordinary
        collection walk (totals + per-class rollups under
        collection="wire.<name>") and the labelled per-message-type
        family (`ceph_tpu_wire_bytes{owner,msg_type,dir}`), with the
        HELP/TYPE-once invariants."""
        from ceph_tpu.common import Context
        from ceph_tpu.common.wire_accounting import WireAccounting
        from ceph_tpu.mgr.prometheus import render

        class _Ctx:
            op_class = "recovery"
        cct = Context()
        acct = WireAccounting(cct=cct, name="promwire")
        try:
            acct.account_tx("ECSubRead", 100, ctx=_Ctx())
            acct.account_rx("ECSubReadReply", 4096, ctx=_Ctx())
            text = render(cct)
            lines = text.splitlines()
            assert 'ceph_tpu_tx_bytes{collection="wire.promwire"} 100' \
                in lines
            assert 'ceph_tpu_rx_bytes{collection="wire.promwire"} 4096' \
                in lines
            assert 'ceph_tpu_class_bytes_recovery{' \
                   'collection="wire.promwire"} 4196' in lines
            assert 'ceph_tpu_wire_bytes{owner="promwire",' \
                   'msg_type="ECSubRead",dir="tx"} 100' in lines
            assert 'ceph_tpu_wire_bytes{owner="promwire",' \
                   'msg_type="ECSubReadReply",dir="rx"} 4096' in lines
            assert lines.count("# TYPE ceph_tpu_wire_bytes counter") == 1
            types = [line.split(" ", 2)[2].split(" ", 1)[0]
                     for line in lines if line.startswith("# TYPE ")]
            assert len(types) == len(set(types)), "duplicate TYPE lines"
        finally:
            acct.close()

    def test_slo_and_latency_phase_families_rendered(self):
        """ISSUE 10: live SLOTrackers export per-class budget state as
        `ceph_tpu_slo_budget{owner,class,stat}` and live critical-path
        ledgers export cumulative attribution as
        `ceph_tpu_latency_phase_seconds{owner,class,phase}`, with the
        HELP/TYPE-once invariants."""
        from ceph_tpu.common import Context
        from ceph_tpu.common.critpath import CritPathLedger
        from ceph_tpu.mgr.prometheus import render
        from ceph_tpu.mgr.slo import SLOTracker
        cct = Context(overrides={"slo_client_p99_ms": 40.0,
                                 "slo_client_target": 0.9})
        led = CritPathLedger(cct=cct, name="promslo")
        tracker = SLOTracker(led, cct=cct, name="promslo")
        try:
            # the scrape folds the process tracer ring into every live
            # ledger: clear leftovers so the pinned values are exact
            from ceph_tpu.common.tracer import default_tracer
            default_tracer().reset()
            led.ingest("client", 0.010,
                       {"device": 0.008, "wire": 0.002})
            text = render(cct)
            lines = text.splitlines()
            assert 'ceph_tpu_slo_budget{owner="promslo",' \
                   'class="client",stat="objective_p99_ms"} 40.0' \
                in lines
            assert 'ceph_tpu_slo_budget{owner="promslo",' \
                   'class="client",stat="budget_remaining"} 1.0' \
                in lines
            assert 'ceph_tpu_latency_phase_seconds{owner="promslo",' \
                   'class="client",phase="device"} 0.008' in lines
            assert 'ceph_tpu_latency_phase_seconds{owner="promslo",' \
                   'class="client",phase="wire"} 0.002' in lines
            assert lines.count("# TYPE ceph_tpu_slo_budget gauge") == 1
            assert lines.count(
                "# TYPE ceph_tpu_latency_phase_seconds counter") == 1
            types = [line.split(" ", 2)[2].split(" ", 1)[0]
                     for line in lines if line.startswith("# TYPE ")]
            assert len(types) == len(set(types)), "duplicate TYPE lines"
        finally:
            tracker.close()
            led.close()

    def test_device_efficiency_family_rendered(self):
        """The roofline ledger exports through BOTH surfaces: the
        ordinary `device_efficiency` collection walk (aggregate gauges)
        and the labelled per-executable family
        (`ceph_tpu_device_efficiency{executable,stat}`), with the
        HELP/TYPE-once invariants and a deterministic synthetic ledger."""
        from ceph_tpu.common import Context, roofline
        from ceph_tpu.mgr.prometheus import render
        roofline.reset()
        try:
            key = (((4, 8), "uint8"), ((8, 1024), "uint8"))
            roofline.record_compile("enc", key, flops_per_call=512.0,
                                    bytes_per_call=2_000_000.0)
            roofline.record_call("enc", key, 0.001, synced=True)
            text = render(Context())
            lines = text.splitlines()
            assert lines.count(
                "# TYPE ceph_tpu_device_efficiency gauge") == 1
            assert any(line.startswith(
                "# HELP ceph_tpu_device_efficiency ") for line in lines)
            eid = "enc_4x8_uint8_8x1024_uint8_"     # sanitized label
            assert f'ceph_tpu_device_efficiency{{executable="{eid}",' \
                   f'stat="calls"}} 1.0' in lines
            assert f'ceph_tpu_device_efficiency{{executable="{eid}",' \
                   f'stat="achieved_bytes_s"}} 2000000000.0' in lines
            assert f'ceph_tpu_device_efficiency{{executable="{eid}",' \
                   f'stat="memory_bound"}} 1.0' in lines
            # the aggregate rides the ordinary collection walk
            assert any(line.startswith(
                'ceph_tpu_achieved_bytes_s{'
                'collection="device_efficiency"}') for line in lines)
            types = [line.split(" ", 2)[2].split(" ", 1)[0]
                     for line in lines if line.startswith("# TYPE ")]
            assert len(types) == len(set(types)), "duplicate TYPE lines"
        finally:
            roofline.reset()

    def test_heat_gauge_families_rendered(self):
        """Live HeatTrackers export `ceph_tpu_osd_heat{owner,osd,stat}`
        and `ceph_tpu_pg_heat{owner,pg,stat}` — the hot-shard skew
        instrument — with the HELP/TYPE-once invariants."""
        from ceph_tpu.common import Context, PerfCountersBuilder
        from ceph_tpu.mgr.heat import HeatTracker
        from ceph_tpu.mgr.prometheus import render
        from ceph_tpu.mgr.stats import StatsAggregator
        cct = Context()
        pc = (PerfCountersBuilder("ec_backend.ph.pg1.0")
              .add_u64_counter("writes", "client writes committed")
              .add_u64_counter("write_bytes", "client bytes written")
              .create_perf_counters())
        cct.perf.add(pc)
        # fake clock: render() ticks every live aggregator itself, and a
        # real monotonic sample would stretch the 2s window to hours
        t = [2.0]
        agg = StatsAggregator(cct=cct, name="promheat-src",
                              clock=lambda: t[0])
        tracker = HeatTracker(
            agg, lambda: {"1.0": {"primary": 3, "acting": [3, 4, 5]}},
            name="promheat", tag="ph")
        try:
            agg.sample(now=0.0)
            pc.inc("writes", 20)
            pc.inc("write_bytes", 4096)
            agg.sample(now=2.0)
            text = render(cct)
            lines = text.splitlines()
            assert 'ceph_tpu_osd_heat{owner="promheat",osd="3",' \
                   'stat="op_s"} 10.0' in lines
            assert 'ceph_tpu_osd_heat{owner="promheat",osd="4",' \
                   'stat="op_s"} 0.0' in lines
            assert 'ceph_tpu_pg_heat{owner="promheat",pg="1.0",' \
                   'stat="bytes_s"} 2048.0' in lines
            assert lines.count("# TYPE ceph_tpu_osd_heat gauge") == 1
            assert lines.count("# TYPE ceph_tpu_pg_heat gauge") == 1
            types = [line.split(" ", 2)[2].split(" ", 1)[0]
                     for line in lines if line.startswith("# TYPE ")]
            assert len(types) == len(set(types)), "duplicate TYPE lines"
        finally:
            tracker.close()
            agg.close()
            cct.perf.remove(pc.name)

    def test_device_collection_rendered(self):
        """The device-telemetry gauges land in the exposition via the
        ordinary collection walk (refresh happens at render time)."""
        from ceph_tpu.common import Context
        from ceph_tpu.mgr.prometheus import render
        text = render(Context())
        assert 'ceph_tpu_num_devices{collection="device"}' in text
        assert 'ceph_tpu_compile_cache_keys{collection="device"}' in text

    def test_copy_ledger_family_rendered(self):
        """The payload copy ledger exports `ceph_tpu_copy_bytes{source}`
        for the WHOLE closed source vocabulary (zero rows included, so
        dashboards can pin the label set) plus the served/total/ratio
        state gauges — the zero-copy data path's scrape instrument —
        and the same quotient surfaces in the stats digest."""
        from ceph_tpu.common import Context
        from ceph_tpu.common import copy_ledger
        from ceph_tpu.mgr.prometheus import render
        from ceph_tpu.mgr.stats import StatsAggregator
        led = copy_ledger.ledger()
        base = led.snapshot()
        copy_ledger.count_copy("staging", 4096)
        copy_ledger.count_served(4096)
        text = render(Context())
        lines = text.splitlines()
        vals = {}
        for line in lines:
            if line.startswith("ceph_tpu_copy_bytes{"):
                labels, v = line.split("} ")
                vals[labels.split('source="')[1].rstrip('"')] = int(v)
        assert set(vals) == set(copy_ledger.COPY_SOURCES)
        assert vals["staging"] >= base["copied"]["staging"] + 4096
        served = [line for line in lines
                  if 'copy_state{stat="served_bytes"}' in line]
        assert served
        assert float(served[0].split("} ")[1]) >= base["served"] + 4096
        assert any('copy_state{stat="copies_per_byte"}' in line
                   for line in lines)
        assert lines.count("# TYPE ceph_tpu_copy_bytes counter") == 1
        assert lines.count("# TYPE ceph_tpu_copy_state gauge") == 1
        # the same quotient is the digest's serving-side success metric
        t = [0.0]
        agg = StatsAggregator(cct=Context(), name="promcopy-src",
                              clock=lambda: t[0])
        try:
            agg.sample(now=0.0)
            t[0] = 2.0
            agg.sample(now=2.0)
            d = agg.digest()
            quotient = d["serving"]["bytes_copied_per_byte_served"]
            assert quotient == led.copies_per_byte()
            assert agg.digest_flat()["serving_copies_per_byte"] \
                == quotient
        finally:
            agg.close()

    def test_span_latency_histograms_rendered(self):
        """The tracer's per-span-name latency distributions surface as
        prometheus histograms with the full _bucket/_sum/_count set."""
        from ceph_tpu.common import Context
        from ceph_tpu.common.tracer import trace_span
        from ceph_tpu.mgr.prometheus import render
        with trace_span("prom.test.span"):
            pass
        text = render(Context())
        assert "# TYPE ceph_tpu_span_latency_seconds histogram" in text
        assert 'ceph_tpu_span_latency_seconds_bucket{' \
               'span="prom.test.span",le="+Inf"}' in text
        assert 'ceph_tpu_span_latency_seconds_sum{' \
               'span="prom.test.span"}' in text
        assert 'ceph_tpu_span_latency_seconds_count{' \
               'span="prom.test.span"}' in text


class TestWatchAtomicity:
    def test_failed_vector_does_not_register_watch(self, cluster):
        """Watch effects apply only on vector success (regression: they
        applied immediately inside the opcode switch)."""
        c, pid = cluster
        c.operate(pid, "wa", ObjectOperation().write_full(b"x"))
        fired = []
        with pytest.raises(IOError):
            c.operate(pid, "wa", ObjectOperation()
                      .watch(5, lambda n, ck, p: fired.append(p))
                      .getxattr("missing"))       # fails the vector
        c.operate(pid, "wa", ObjectOperation().notify(b"ping"))
        assert fired == []
        assert c.operate(pid, "wa", ObjectOperation()
                         .list_watchers()).outdata(0) == []

    def test_watch_rejected_on_snap_read(self, cluster):
        c, pid = cluster
        c.operate(pid, "ws", ObjectOperation().write_full(b"x"))
        s1 = c.create_pool_snap(pid, "s")
        c.operate(pid, "ws", ObjectOperation().write_full(b"y"))
        with pytest.raises(IOError) as ei:
            c.operate(pid, "ws", ObjectOperation().watch(
                1, lambda n, ck, p: b""), snapid=s1)
        assert ei.value.errno == -22


class TestSnapEdges:
    def test_read_at_removed_snap_is_enoent(self, cluster):
        """A shared clone must not serve reads at a REMOVED snap id."""
        c, pid = cluster
        c.operate(pid, "rm", ObjectOperation().write_full(b"v1" * 300))
        s1 = c.create_pool_snap(pid, "one")
        s2 = c.create_pool_snap(pid, "two")
        c.operate(pid, "rm", ObjectOperation().write_full(b"v2" * 300))
        c.remove_pool_snap(pid, "one")
        with pytest.raises(IOError) as ei:
            c.operate(pid, "rm", ObjectOperation().read(0, 0), snapid=s1)
        assert ei.value.errno == -2
        # the surviving snap still reads v1 through the shared clone
        r = c.operate(pid, "rm", ObjectOperation().read(0, 0), snapid=s2)
        assert r.outdata(0)[:600] == b"v1" * 300

    def test_rollback_to_precreation_snap_deletes_head(self, cluster):
        c, pid = cluster
        s1 = c.create_pool_snap(pid, "early")
        c.operate(pid, "born-late", ObjectOperation().write_full(b"data"))
        c.operate(pid, "born-late", ObjectOperation().rollback(s1))
        with pytest.raises(IOError) as ei:
            c.operate(pid, "born-late", ObjectOperation().stat())
        assert ei.value.errno == -2


def test_failed_vector_does_not_notify(cluster):
    """NOTIFY is a success-only effect (regression: it fired during
    opcode execution even when the vector then failed)."""
    c, pid = cluster
    c.operate(pid, "nf", ObjectOperation().write_full(b"x"))
    got = []
    c.operate(pid, "nf", ObjectOperation().watch(
        1, lambda n, ck, p: got.append(p)))
    with pytest.raises(IOError):
        c.operate(pid, "nf", ObjectOperation()
                  .notify(b"leak").getxattr("missing"))
    assert got == []
    c.operate(pid, "nf", ObjectOperation().notify(b"real"))
    assert got == [b"real"]


def test_scrub_detects_missing_primary_copy(cluster):
    """An object whose PRIMARY shard copy vanished must still be found
    by scrub (regression: the object list came from the primary only)."""
    from ceph_tpu.backend.memstore import GObject
    c, pid = cluster
    payload = np.random.default_rng(7).integers(
        0, 256, 1800, np.uint8).tobytes()
    c.put(pid, "halfgone", payload)
    g = c.pg_group(pid, "halfgone")
    del g.backend.local_shard.store.objects[
        GObject("halfgone", g.backend.whoami)]
    g.backend.hinfo_cache.clear()
    report = c.scrub_pool(pid, repair=True)
    assert any("halfgone" in bad for bad in report.values())
    assert c.get(pid, "halfgone", 1800) == payload     # repaired


class TestParityConsistencyScrub:
    """Silent bitrot on an OVERWRITTEN object (chunk hashes cleared) is
    still detected and located: the code itself is the checksum — m
    parity equations + leave-one-out localisation (regression: scrub
    passed anything whose version matched once hashes were cleared)."""

    def _rot(self, c, pid, oid, chunk_idx):
        from ceph_tpu.backend.memstore import GObject
        from ceph_tpu.backend.pg_backend import shard_store
        g = c.pg_group(pid, oid)
        shard = g.acting[chunk_idx]
        st = shard_store(g.bus, shard)
        st.objects[GObject(oid, shard)].data[3] ^= 0x5A
        return g

    def test_rot_after_overwrite_detected_and_repaired(self):
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        v1 = np.random.default_rng(1).integers(0, 256, 2000,
                                               np.uint8).tobytes()
        v2 = np.random.default_rng(2).integers(0, 256, 1500,
                                               np.uint8).tobytes()
        c.operate(pid, "ow", ObjectOperation().write_full(v1))
        c.operate(pid, "ow", ObjectOperation().write_full(v2))  # clears hash
        g = self._rot(c, pid, "ow", 1)
        report = c.scrub_pool(pid, repair=True)
        assert any("ow" in bad and bad["ow"] == [1]
                   for bad in report.values()), report
        assert c.scrub_pool(pid) == {}
        assert c.operate(pid, "ow", ObjectOperation()
                         .read(0, 0)).outdata(0)[:1500] == v2
        c.shutdown()

    def test_parity_chunk_rot_located_too(self):
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        c.operate(pid, "pw", ObjectOperation().write_full(b"a" * 1800))
        c.operate(pid, "pw", ObjectOperation().write_full(b"b" * 1700))
        self._rot(c, pid, "pw", 3)          # a PARITY shard rots
        report = c.scrub_pool(pid, repair=True)
        assert any(bad.get("pw") == [3] for bad in report.values()), report
        assert c.scrub_pool(pid) == {}
        c.shutdown()

    def test_m1_rot_detected_not_mislocated(self):
        """With m=1 (xor pool) rot is detectable but NOT locatable: scrub
        must flag the whole set rather than guess — a wrong guess would
        'repair' a healthy chunk FROM the rotten one (reproduced
        pre-fix), permanently corrupting the object behind a clean
        scrub."""
        c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
        pid = c.create_ec_pool("p", {"plugin": "xor", "k": "2", "m": "1"},
                               pg_num=4)
        v2 = np.random.default_rng(5).integers(0, 256, 1500,
                                               np.uint8).tobytes()
        c.operate(pid, "x1", ObjectOperation().write_full(b"a" * 1800))
        c.operate(pid, "x1", ObjectOperation().write_full(v2))
        self._rot(c, pid, "x1", 1)
        report = c.scrub_pool(pid, repair=True)
        [bad] = [b["x1"] for b in report.values() if "x1" in b]
        assert bad == [0, 1, 2]          # detected, honestly unlocatable
        # repair did NOT guess: the object still reads (rot is in chunk 1,
        # data reconstructs from 0+parity only if asked; head read shows
        # the rot — but nothing was made WORSE and scrub still reports)
        report2 = c.scrub_pool(pid)
        assert any("x1" in b for b in report2.values())
        c.shutdown()

    def test_degraded_rot_still_detected(self):
        """One shard down + rot on an overwritten object: the spare
        equation still DETECTS (pre-fix: fallback skipped unless every
        chunk was present)."""
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        c.operate(pid, "dg", ObjectOperation().write_full(b"a" * 1800))
        c.operate(pid, "dg", ObjectOperation().write_full(b"b" * 1700))
        g = self._rot(c, pid, "dg", 1)
        down = g.acting[3]
        g.bus.mark_down(down)
        try:
            report = c.scrub_pool(pid, repair=False)
            assert any("dg" in b for b in report.values()), report
        finally:
            g.bus.mark_up(down)
        c.shutdown()


def test_admin_socket_pg_commands(cluster):
    """dump_watchers + peering_history over the admin socket (the
    reference's daemon observability commands)."""
    c, pid = cluster
    c.operate(pid, "aw", ObjectOperation().write_full(b"x"))
    c.operate(pid, "aw", ObjectOperation().watch(3, lambda n, ck, p: b""))
    g = c.pg_group(pid, "aw")
    name = g.backend.instance_name
    ws = c.cct.admin_socket.call(f"dump_watchers.{name}")
    assert ws == {"aw": [3]}
    g.peering.advance_map(epoch=31)
    g.bus.deliver_all()
    hist = c.cct.admin_socket.call(f"peering_history.{name}")
    assert hist["state"].endswith("Active")
    assert hist["last_epoch_started"] == 31
    assert any(s.endswith("GetInfo") for _, s in hist["history"])


class TestDamagedObjects:
    def test_unlocatable_rot_pins_health_until_restore(self):
        """Recovery from inconsistent sources with one spare equation:
        detect-only -> OBJECT_DAMAGED sticks through clean-looking
        scrubs until a WHOLESALE overwrite exonerates (partial
        truncate+write must NOT)."""
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        from ceph_tpu.backend.memstore import GObject
        from ceph_tpu.backend.pg_backend import shard_store
        payload = np.random.default_rng(1).integers(
            0, 256, 2000, np.uint8).tobytes()
        c.operate(pid, "v", ObjectOperation().write_full(b"old"))
        g = c.pg_group(pid, "v")
        victim = g.acting[3]
        g.bus.mark_down(victim)
        c.operate(pid, "v", ObjectOperation().write_full(payload))
        rot = g.acting[1]
        shard_store(g.bus, rot).objects[GObject("v", rot)].data[0] ^= 0xFF
        g.bus.mark_up(victim)
        g.bus.deliver_all()
        assert "v" in g.backend.inconsistent_objects
        assert "OBJECT_DAMAGED" in c.health()["checks"]
        assert any("v" in b for b in c.scrub_pool(pid).values())
        # a PARTIAL truncate+write does not exonerate
        c.operate(pid, "v", ObjectOperation().truncate(512)
                  .write(512, b"tail"))
        assert "v" in g.backend.inconsistent_objects
        # wholesale restore does
        c.operate(pid, "v", ObjectOperation().write_full(payload))
        assert "v" not in g.backend.inconsistent_objects
        assert c.scrub_pool(pid) == {}
        assert c.health()["status"] == "HEALTH_OK"
        c.shutdown()

    def test_verified_repair_preserves_user_xattrs(self):
        """Repairing a LOCATED rotten source replaces the whole shard
        object: the replicated attrs must travel with the push
        (regression: only hinfo was pushed, wiping the xattrs)."""
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        from ceph_tpu.backend.memstore import GObject
        from ceph_tpu.backend.pg_backend import shard_store
        c.operate(pid, "x", ObjectOperation().write_full(b"a" * 1800))
        c.operate(pid, "x", ObjectOperation().write_full(b"b" * 1700)
                  .setxattr("tag", b"keep"))
        g = c.pg_group(pid, "x")
        rot = g.acting[1]
        shard_store(g.bus, rot).objects[GObject("x", rot)].data[0] ^= 0xFF
        assert any("x" in b for b in c.scrub_pool(pid, repair=True).values())
        assert c.scrub_pool(pid) == {}
        # the repaired shard still has the user xattr
        assert shard_store(g.bus, rot).getattr(
            GObject("x", rot), "_tag") == b"keep"
        assert c.operate(pid, "x", ObjectOperation()
                         .getxattr("tag")).outdata(0) == b"keep"
        c.shutdown()

    def test_recovery_crc_verifies_sources(self):
        """With hinfo hashes present, recovery CRC-checks its sources
        and drops+rebuilds a rotten one instead of baking its rot into
        the reconstructed chunk (the reference's recovery-read check)."""
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_ec_pool("p", {"k": "2", "m": "2",
                                     "device": "numpy"}, pg_num=4)
        from ceph_tpu.backend.memstore import GObject
        from ceph_tpu.backend.pg_backend import shard_store
        payload = np.random.default_rng(9).integers(
            0, 256, 1900, np.uint8).tobytes()
        c.put(pid, "cv", payload)          # append-path: hashes PRESENT
        g = c.pg_group(pid, "cv")
        victim = g.acting[3]
        g.bus.mark_down(victim)
        rot = g.acting[0]                  # rot the PRIMARY's data chunk
        shard_store(g.bus, rot).objects[GObject("cv", rot)].data[0] ^= 0xFF
        # force a recovery of the downed shard's chunk
        g.bus.mark_up(victim)
        g.backend.recover_object("cv", {3})
        g.bus.deliver_all()
        # the rotten source was dropped AND healed as an extra target
        assert c.get(pid, "cv", 1900) == payload
        assert c.scrub_pool(pid) == {}
        assert "cv" not in g.backend.inconsistent_objects
        c.shutdown()
