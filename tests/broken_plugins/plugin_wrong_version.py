"""Broken plugin: version mismatch (mirrors the __erasure_code_version checks)."""
def __erasure_code_version__():
    return "0.0.0-not-this"
def __erasure_code_init__(name, directory):
    pass
