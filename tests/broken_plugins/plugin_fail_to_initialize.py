"""Broken plugin: init raises (mirrors ErasureCodePluginFailToInitialize.cc)."""
from ceph_tpu import __version__
def __erasure_code_version__():
    return __version__
def __erasure_code_init__(name, directory):
    raise RuntimeError("-ESRCH: deliberate init failure")
