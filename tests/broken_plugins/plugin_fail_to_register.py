"""Broken plugin: init succeeds but never registers (mirrors ErasureCodePluginFailToRegister.cc)."""
from ceph_tpu import __version__
def __erasure_code_version__():
    return __version__
def __erasure_code_init__(name, directory):
    pass
