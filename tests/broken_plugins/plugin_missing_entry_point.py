"""Broken plugin: no __erasure_code_init__ (mirrors ErasureCodePluginMissingEntryPoint.cc)."""
from ceph_tpu import __version__
def __erasure_code_version__():
    return __version__
