"""Broken plugin: no __erasure_code_version__ (mirrors ErasureCodePluginMissingVersion.cc)."""
def __erasure_code_init__(name, directory):
    pass
