"""Crushmap text format: compile/decompile round trip.

Mirrors the reference's CrushCompiler (reference:
src/crush/CrushCompiler.{h,cc}, the ``crushtool -c``/``-d`` format):
``decompile(compile(x))`` idempotent on normalized text, placements
preserved through a full round trip, and a reference-shaped crushmap text
(the classic two-host example every Ceph deployment starts from) parses
to a working map.
"""
import numpy as np
import pytest

from ceph_tpu.crush import (CRUSH_BUCKET_STRAW2, CRUSH_RULE_CHOOSELEAF_FIRSTN,
                            CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_EMIT,
                            CRUSH_RULE_TAKE, CrushMap, compile_crushmap,
                            crush_do_rule, decompile)

REFERENCE_SHAPED = """\
# begin crush map
tunable choose_local_tries 0
tunable choose_local_fallback_tries 0
tunable choose_total_tries 50
tunable chooseleaf_descend_once 1
tunable chooseleaf_vary_r 1
tunable chooseleaf_stable 1

# devices
device 0 osd.0 class hdd
device 1 osd.1 class hdd
device 2 osd.2 class ssd
device 3 osd.3 class ssd

# types
type 0 osd
type 1 host
type 10 root

# buckets
host node1 {
	id -2		# do not change unnecessarily
	# weight 2.000
	alg straw2
	hash 0	# rjenkins1
	item osd.0 weight 1.000
	item osd.1 weight 1.000
}
host node2 {
	id -3
	# weight 2.000
	alg straw2
	hash 0	# rjenkins1
	item osd.2 weight 1.500
	item osd.3 weight 0.500
}
root default {
	id -1
	# weight 4.000
	alg straw2
	hash 0	# rjenkins1
	item node1 weight 2.000
	item node2 weight 2.000
}

# rules
rule replicated_rule {
	id 0
	type replicated
	min_size 1
	max_size 10
	step take default
	step chooseleaf firstn 0 type host
	step emit
}
rule ec_rule {
	id 1
	type erasure
	min_size 3
	max_size 6
	step set_chooseleaf_tries 5
	step set_choose_tries 100
	step take default
	step chooseleaf indep 4 type host
	step emit
}

# end crush map
"""


class TestCompile:
    def test_reference_shaped_text_parses(self):
        m = compile_crushmap(REFERENCE_SHAPED)
        assert set(m.buckets) == {-1, -2, -3}
        assert m.buckets[-2].items == [0, 1]
        assert m.buckets[-3].item_weights == [0x18000, 0x8000]
        assert m.type_names == {0: "osd", 1: "host", 10: "root"}
        assert m.item_names[-1] == "default"
        assert m.device_classes == {0: "hdd", 1: "hdd", 2: "ssd", 3: "ssd"}
        assert m.tunables["choose_total_tries"] == 50
        assert m.rule_names == {"replicated_rule": 0, "ec_rule": 1}
        r = m.rules[1]
        assert r.type == 3 and r.min_size == 3 and r.max_size == 6
        assert r.steps[0][0] != CRUSH_RULE_TAKE       # set_* steps first
        assert r.steps[2] == (CRUSH_RULE_TAKE, -1, 0)
        assert r.steps[3] == (CRUSH_RULE_CHOOSELEAF_INDEP, 4, 1)
        assert m.max_devices == 4

    def test_compiled_map_places(self):
        m = compile_crushmap(REFERENCE_SHAPED)
        for x in range(16):
            out = crush_do_rule(m, 1, x, 4)
            real = [o for o in out if o != 0x7FFFFFFF]
            assert all(0 <= o < 4 for o in real)

    def test_errors_are_loud(self):
        with pytest.raises(ValueError, match="unknown item"):
            compile_crushmap("type 0 osd\ntype 1 host\n"
                             "host h { id -1 alg straw2 hash 0 "
                             "item nonexistent weight 1.0 }")
        with pytest.raises(ValueError, match="unexpected token"):
            compile_crushmap("bogus syntax here")


class TestRoundTrip:
    def test_decompile_compile_idempotent(self):
        """decompile(compile(x)) is a fixed point: compiling the decompiled
        text and decompiling again reproduces the text byte-for-byte."""
        m1 = compile_crushmap(REFERENCE_SHAPED)
        text1 = decompile(m1)
        m2 = compile_crushmap(text1)
        text2 = decompile(m2)
        assert text1 == text2

    def test_round_trip_preserves_placements(self):
        m1 = compile_crushmap(REFERENCE_SHAPED)
        m2 = compile_crushmap(decompile(m1))
        for ruleno in (0, 1):
            for x in range(32):
                assert crush_do_rule(m1, ruleno, x, 4) == \
                    crush_do_rule(m2, ruleno, x, 4), f"rule {ruleno} x={x}"

    def test_programmatic_map_round_trips(self):
        """A map built through the builder API survives text round trip
        with identical placements (weights at 3-decimal resolution, the
        reference's print_fixedpoint precision)."""
        m = CrushMap()
        m.set_type_name(1, "host")
        m.set_type_name(2, "root")
        hosts = []
        for h in range(3):
            items = list(range(h * 3, h * 3 + 3))
            w = [0x10000, 0x8000, 0x18000]
            b = m.add_bucket(CRUSH_BUCKET_STRAW2, 1, items, w)
            m.set_item_name(b, f"host{h}")
            hosts.append(b)
        root = m.add_bucket(CRUSH_BUCKET_STRAW2, 2, hosts,
                            [sum([0x10000, 0x8000, 0x18000])] * 3)
        m.set_item_name(root, "default")
        m.finalize()
        ruleno = m.add_rule([(CRUSH_RULE_TAKE, root, 0),
                             (CRUSH_RULE_CHOOSELEAF_INDEP, 3, 1),
                             (CRUSH_RULE_EMIT, 0, 0)])
        m.rules[ruleno].type = 3
        m.rule_names["ec"] = ruleno

        m2 = compile_crushmap(decompile(m))
        for x in range(32):
            assert crush_do_rule(m, ruleno, x, 3) == \
                crush_do_rule(m2, ruleno, x, 3)
        assert decompile(m2) == decompile(m)

    def test_choose_args_round_trip(self):
        m = compile_crushmap(REFERENCE_SHAPED)
        m.choose_args[-1] = {
            -2: {"weight_set": [[0x10000, 0xC000], [0x8000, 0x10000]]},
            -3: {"weight_set": [[0x18000, 0x4000]], "ids": [1002, 1003]},
        }
        text = decompile(m)
        assert "# choose_args" in text and "bucket_id -2" in text
        m2 = compile_crushmap(text)
        assert m2.choose_args[-1][-2]["weight_set"] == \
            m.choose_args[-1][-2]["weight_set"]
        assert m2.choose_args[-1][-3]["ids"] == [1002, 1003]
        # and the weight set flows through placement identically
        for x in range(16):
            assert crush_do_rule(m, 1, x, 4,
                                 choose_args=m.choose_args[-1]) == \
                crush_do_rule(m2, 1, x, 4, choose_args=m2.choose_args[-1])
        assert decompile(m2) == text

    def test_uniform_bucket_round_trip(self):
        from ceph_tpu.crush import CRUSH_BUCKET_UNIFORM
        m = CrushMap()
        m.set_type_name(1, "host")
        b = m.add_bucket(CRUSH_BUCKET_UNIFORM, 1, [0, 1, 2],
                         uniform_weight=0x10000)
        m.set_item_name(b, "uni")
        m.finalize()
        m2 = compile_crushmap(decompile(m))
        assert m2.buckets[b].alg == CRUSH_BUCKET_UNIFORM
        assert m2.buckets[b].item_weight == 0x10000
        assert decompile(m2) == decompile(m)
