"""Recovery orchestrator: reserver semantics, scheduler integration,
batch-fused waves, rate caps, stalled-op gating, health surfacing.

Reference analogs: common/AsyncReserver.h (priorities, max_allowed,
preemption), the OSD's local/remote recovery reservations +
osd_max_backfills / osd_recovery_max_active / osd_recovery_sleep
(src/osd/OSD.cc, src/common/options.cc), and the PG recovery priority
ladder (PeeringState::get_recovery_priority).
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.recovery import (AsyncReserver,
                               OSD_RECOVERY_INACTIVE_PRIORITY_BASE,
                               OSD_RECOVERY_PRIORITY_BASE,
                               OSD_RECOVERY_PRIORITY_FORCED)


class TestAsyncReserver:
    def test_fifo_within_priority_and_max_allowed(self):
        r = AsyncReserver("t", max_allowed=2)
        granted = []
        for i in range(4):
            r.request_reservation(f"pg{i}", lambda _i=i: granted.append(_i),
                                  prio=10)
        assert granted == [0, 1]
        assert r.in_flight() == 2 and r.queue_depth() == 2
        r.cancel_reservation("pg0")
        assert granted == [0, 1, 2]          # FIFO promotion
        r.cancel_reservation("pg1")
        assert granted == [0, 1, 2, 3]

    def test_higher_priority_granted_first(self):
        r = AsyncReserver("t", max_allowed=1)
        granted = []
        r.request_reservation("low1", lambda: granted.append("low1"),
                              prio=1)
        r.request_reservation("low2", lambda: granted.append("low2"),
                              prio=1)
        r.request_reservation("high", lambda: granted.append("high"),
                              prio=200)
        r.cancel_reservation("low1")         # the holder releases
        assert granted == ["low1", "high"]
        r.cancel_reservation("high")
        assert granted == ["low1", "high", "low2"]

    def test_preemption_fires_on_preempt_and_regrants(self):
        r = AsyncReserver("t", max_allowed=1)
        events = []
        r.request_reservation("low", lambda: events.append("grant-low"),
                              prio=10,
                              on_preempt=lambda: events.append("preempt"))
        r.request_reservation("high", lambda: events.append("grant-high"),
                              prio=220)
        assert events == ["grant-low", "preempt", "grant-high"]
        assert r.has_reservation("high") and not r.has_reservation("low")
        assert r.stats.preemptions == 1

    def test_non_preemptible_holder_is_never_preempted(self):
        r = AsyncReserver("t", max_allowed=1)
        events = []
        r.request_reservation("holder", lambda: events.append("h"),
                              prio=10)      # no on_preempt: not preemptible
        r.request_reservation("high", lambda: events.append("high"),
                              prio=255)
        assert events == ["h"]
        assert r.has_reservation("holder")
        r.cancel_reservation("holder")
        assert events == ["h", "high"]

    def test_equal_priority_does_not_preempt(self):
        r = AsyncReserver("t", max_allowed=1)
        events = []
        r.request_reservation("a", lambda: events.append("a"), prio=10,
                              on_preempt=lambda: events.append("pre-a"))
        r.request_reservation("b", lambda: events.append("b"), prio=10)
        assert events == ["a"]               # strictly-higher only

    def test_cancel_queued_and_idempotent(self):
        r = AsyncReserver("t", max_allowed=1)
        r.request_reservation("a", lambda: None, prio=1)
        r.request_reservation("b", lambda: None, prio=1)
        assert r.cancel_reservation("b") is True    # still queued
        assert r.cancel_reservation("b") is False   # idempotent
        assert r.queue_depth() == 0

    def test_duplicate_request_rejected(self):
        r = AsyncReserver("t", max_allowed=1)
        r.request_reservation("a", lambda: None, prio=1)
        with pytest.raises(ValueError):
            r.request_reservation("a", lambda: None, prio=2)

    def test_update_priority_reorders_queue(self):
        r = AsyncReserver("t", max_allowed=1)
        granted = []
        r.request_reservation("hold", lambda: granted.append("hold"),
                              prio=10)
        r.request_reservation("x", lambda: granted.append("x"), prio=1)
        r.request_reservation("y", lambda: granted.append("y"), prio=2)
        r.update_priority("x", 100)
        r.cancel_reservation("hold")
        assert granted == ["hold", "x"]

    def test_set_max_grants_backlog(self):
        r = AsyncReserver("t", max_allowed=0)
        granted = []
        r.request_reservation("a", lambda: granted.append("a"), prio=1)
        assert granted == []
        r.set_max(1)
        assert granted == ["a"]
        assert r.stats.peak_in_flight == 1

    def test_reentrant_request_from_grant_callback(self):
        r = AsyncReserver("t", max_allowed=1)
        granted = []

        def grant_a():
            granted.append("a")
            r.request_reservation("b", lambda: granted.append("b"), prio=1)
            r.cancel_reservation("a")
        r.request_reservation("a", grant_a, prio=1)
        assert granted == ["a", "b"]

    def test_dump_shape(self):
        r = AsyncReserver("t", max_allowed=1)
        r.request_reservation("a", lambda: None, prio=5)
        r.request_reservation("b", lambda: None, prio=7)
        d = r.dump()
        assert d["in_progress"] == {"'a'": 5}
        assert d["queues"] == {7: ["'b'"]}
        assert d["stats"]["grants"] == 1


K, M = 2, 2
CHUNK = 512


def _degraded_cluster(n_objects=12, conf=None, pg_num=2):
    """Cluster with a scheduler, one revived-stale shard per PG holding
    ``n_objects`` missed writes — NOT yet delivered, so the caller
    observes the queued/granted states before repair runs.  A FRESH
    Context per cluster: conf knobs must not leak into other tests
    through the process-global default context."""
    from ceph_tpu.common import Context
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=CHUNK,
                    cct=Context())
    for key, value in (conf or {}).items():
        c.cct.conf.set(key, value)
    sched = c.enable_recovery_scheduler()
    pid = c.create_ec_pool(
        "p", {"k": str(K), "m": str(M), "device": "numpy",
              "technique": "reed_sol_van"}, pg_num=pg_num)
    rng = np.random.default_rng(7)
    data = {}
    for i in range(n_objects):
        oid = f"obj{i}"
        data[oid] = rng.integers(0, 256, 3 * CHUNK * K,
                                 np.uint8).tobytes()
        c.put(pid, oid, data[oid])
    victims = {}
    for g in c.pools[pid]["pgs"].values():
        victims[id(g)] = g.acting[1]
        g.bus.mark_down(g.acting[1])
    for oid in list(data):
        data[oid] = rng.integers(0, 256, 3 * CHUNK * K,
                                 np.uint8).tobytes()
        c.put(pid, oid, data[oid])
    for g in c.pools[pid]["pgs"].values():
        g.bus.mark_up(victims[id(g)])
    return c, sched, pid, data


class TestSchedulerCluster:
    def test_revival_recovers_reservation_gated(self):
        c, sched, pid, data = _degraded_cluster()
        try:
            c.deliver_all()
            for g in c.pools[pid]["pgs"].values():
                assert not g.backend.stale
                assert not g.backend.shard_repairs
            for oid, want in data.items():
                assert c.get(pid, oid, len(want)) == want
            # jobs drained, reservations released
            assert sched.jobs == {}
            assert sched.summary()["reservations"]["granted"] == 0
            assert sched.perf.get("jobs_completed") >= 1
            assert sched.perf.get("waves") >= 1
            assert sched.perf.get("wave_objects") >= len(data) // 2
            # the reservation gate was actually enforced
            bound = c.cct.conf.get("osd_max_backfills")
            for table in (sched._local, sched._remote):
                for r in table.values():
                    assert r.stats.peak_in_flight <= bound
        finally:
            c.shutdown()

    def test_batched_waves_fuse_decodes(self):
        """A wave's objects share one decode dispatch per survivor
        signature — far fewer codec calls than objects recovered.
        Chains are pinned OFF: this exercises the centralized wave path
        (with chains on, no primary-side decode runs at all)."""
        conf = {"osd_recovery_max_active": 6,
                "osd_recovery_chain_enable": False}
        c, sched, pid, data = _degraded_cluster(n_objects=12, conf=conf,
                                                pg_num=1)
        try:
            ec = c.pools[pid]["ec"]
            calls = {"n": 0}
            orig = ec.decode

            def counting(want, chunks, chunk_size=0):
                calls["n"] += 1
                return orig(want, chunks, chunk_size)
            ec.decode = counting
            c.deliver_all()
            ec.decode = orig
            recovered = sum(
                g.backend.perf.get("recoveries")
                for g in c.pools[pid]["pgs"].values())
            assert recovered >= 12
            # 12 objects, wave size 6, one survivor signature: ~2 decode
            # dispatches (vs 12 per-object) — allow slack for re-reads
            assert 0 < calls["n"] <= recovered // 2
            for oid, want in data.items():
                assert c.get(pid, oid, len(want)) == want
        finally:
            c.shutdown()

    def test_tight_caps_still_drain(self):
        """osd_recovery_max_active=1 + a byte-rate cap + recovery sleep:
        repair completes (post-paid token bucket guarantees progress)
        and pacing produced one wave per object."""
        conf = {"osd_recovery_max_active": 1,
                "osd_recovery_max_bytes_per_sec": 16 * 1024,
                "osd_recovery_sleep": 0.002}
        c, sched, pid, data = _degraded_cluster(n_objects=8, conf=conf)
        try:
            c.deliver_all()
            for g in c.pools[pid]["pgs"].values():
                assert not g.backend.stale
            for oid, want in data.items():
                assert c.get(pid, oid, len(want)) == want
            assert sched.perf.get("waves") >= 8   # one object per wave
            for oid in data:
                g = c.pg_group(pid, oid)
                rep = g.backend.be_deep_scrub(oid)
                assert all(rep.values()), (oid, rep)
        finally:
            c.shutdown()

    def test_zero_backfills_parks_jobs_and_health_fires(self):
        """osd_max_backfills=0 parks every job (pause background repair);
        PG_RECOVERY_STALLED fires once the stats window shows no
        progress; raising the bound drains the backlog and clears it."""
        conf = {"osd_max_backfills": 0}
        c, sched, pid, data = _degraded_cluster(n_objects=4, conf=conf)
        try:
            c.deliver_all()
            queued, active = sched.job_counts()
            assert queued >= 1 and active == 0
            for g in c.pools[pid]["pgs"].values():
                assert g.backend.stale        # repair never started
            # two samples spanning >= 1s of (injected) time: enough
            # window to judge that nothing progressed
            c.stats.sample(now=100.0)
            c.stats.sample(now=110.0)
            ev = c.health_detail()
            assert "PG_RECOVERY_STALLED" in ev["checks"]
            # unblock live: the conf observer re-bounds every existing
            # reserver (osd_max_backfills is live-tunable)
            c.cct.conf.set("osd_max_backfills", 1)
            c.deliver_all()
            for g in c.pools[pid]["pgs"].values():
                assert not g.backend.stale
            assert "PG_RECOVERY_STALLED" not in c.health_detail()["checks"]
        finally:
            c.shutdown()

    def test_forced_priority_preempts_running_job(self):
        """A forced (prio 255) escalation of a job queued behind another
        PG's remote reservation preempts the holder; the preempted PG
        requeues and both still converge."""
        c, sched, pid, data = _degraded_cluster(n_objects=8, pg_num=2)
        try:
            # both PGs' jobs are mid-acquisition (nothing delivered
            # yet); find a remote reserver where one holds and another
            # queues, and force-escalate the QUEUED one
            contended = next((r for r in sched._remote.values()
                              if r.queue_depth() and r.in_flight()),
                             None)
            assert contended is not None, \
                "expected both PGs to contend for a shared remote slot"
            (job_key, _shard) = next(iter(contended._queued))
            sched.schedule_backend(sched.jobs[job_key].backend,
                                   forced=True)
            assert sched.perf.get("preemptions") >= 1
            c.deliver_all()
            for g in c.pools[pid]["pgs"].values():
                assert not g.backend.stale
            for oid, want in data.items():
                assert c.get(pid, oid, len(want)) == want
            assert sched.jobs == {}
        finally:
            c.shutdown()

    def test_target_merged_mid_batch_restarts_and_drains(self):
        """A shard reviving while another's repair is mid-flight merges
        into the job and RESTARTS the batch (the new shard may be the
        one the in-flight recoveries are waiting on); the aborted repair
        deregisters so the restart starts fresh, and everything drains —
        no shard left stale with the scheduler idle."""
        c, sched, pid, data = _degraded_cluster(n_objects=6, pg_num=1)
        try:
            g = next(iter(c.pools[pid]["pgs"].values()))
            v2 = g.acting[2]
            # partially drive the first victim's repair (log query +
            # some recovery traffic in flight), then revive a SECOND
            # stale shard mid-batch
            g.bus.mark_down(v2)
            for _ in range(6):
                for shard in list(g.bus.queues):
                    g.bus.deliver_one(shard)
            g.bus.mark_up(v2)
            c.deliver_all()
            assert not g.backend.stale
            assert not g.backend.shard_repairs
            assert sched.jobs == {}
            assert sched.summary()["reservations"]["granted"] == 0
            for oid, want in data.items():
                assert c.get(pid, oid, len(want)) == want
        finally:
            c.shutdown()

    def test_sibling_waves_sharing_an_oid_both_drain(self):
        """Two stale shards of ONE PG repair concurrently (one batch)
        and miss the SAME objects: their waves collide on the per-oid
        push slot — the loser must re-drive per object, not drop its
        push replies and wedge the job holding every reservation."""
        from ceph_tpu.common import Context
        c = MiniCluster(n_osds=14, osds_per_host=7, chunk_size=CHUNK,
                        cct=Context())
        sched = c.enable_recovery_scheduler()
        pid = c.create_ec_pool(
            "p", {"k": "4", "m": "3", "device": "numpy",
                  "technique": "reed_sol_van"}, pg_num=1)
        try:
            g = next(iter(c.pools[pid]["pgs"].values()))
            rng = np.random.default_rng(9)
            data = {f"w{i}": rng.integers(0, 256, 2 * CHUNK * 4,
                                          np.uint8).tobytes()
                    for i in range(6)}
            for oid, d in data.items():
                c.put(pid, oid, d)
            v1, v2 = g.acting[1], g.acting[2]
            g.bus.mark_down(v1)
            g.bus.mark_down(v2)
            for oid in data:                # both victims miss these
                data[oid] = rng.integers(0, 256, 2 * CHUNK * 4,
                                         np.uint8).tobytes()
                c.put(pid, oid, data[oid])
            g.bus.mark_up(v1)
            g.bus.mark_up(v2)
            c.deliver_all()
            assert not g.backend.stale
            assert sched.jobs == {}
            assert sched.summary()["reservations"]["granted"] == 0
            for oid, want in data.items():
                assert c.get(pid, oid, len(want)) == want
            rep = c.scrub_pool(pid, repair=False)
            assert rep == {}, rep
        finally:
            c.shutdown()

    def test_stalled_recoveries_requeue_via_scheduler(self):
        """A recovery parked by unrecoverable shard loss re-enters
        through the scheduler's reservation gate on revival — it must
        not bypass it on on_shard_up."""
        c, sched, pid, data = _degraded_cluster(n_objects=2)
        try:
            c.deliver_all()                     # converge first
            oid = sorted(data)[0]
            g = c.pg_group(pid, oid)
            # drop to exactly k current shards, then ask for a recovery
            # of one of the SURVIVORS' chunks: only k-1 sources remain —
            # the op parks
            downed = [s for s in g.acting[2:]][:M]
            for s in downed:
                g.bus.mark_down(s)
            g.backend.recover_object(oid, {1})
            assert g.backend._stalled_recoveries
            before = sched.perf.get("stalled_requeued")
            for s in downed:
                g.bus.mark_up(s)
            c.deliver_all()
            assert sched.perf.get("stalled_requeued") > before
            assert not g.backend._stalled_recoveries
            assert not g.backend.recovery_ops
            assert c.get(pid, oid, len(data[oid])) == data[oid]
        finally:
            c.shutdown()

    def test_preemption_survives_batch_restart(self):
        """A batch restart (new target merged mid-flight) bumps the
        job's wave generation but must NOT stale the local grant's
        preempt callback: a later higher-priority claimant still
        preempts the job (abort + requeue), it does not run alongside
        the intruder past osd_max_backfills."""
        from ceph_tpu.recovery import JobState
        c, sched, pid, data = _degraded_cluster(n_objects=4, pg_num=1)
        try:
            g = next(iter(c.pools[pid]["pgs"].values()))
            job = sched.jobs[g.backend.instance_name]
            assert job.state is JobState.RUNNING
            # merge a second target mid-batch: restarts the batch
            v2 = g.acting[2]
            g.bus.mark_down(v2)
            g.bus.mark_up(v2)
            # a prio-255 claimant takes the local slot: the job must
            # abort cleanly and requeue, releasing its remote holds
            granted = []
            sched.local_reserver(g.backend.whoami).request_reservation(
                "intruder", lambda: granted.append(1), prio=255)
            assert granted == [1]
            assert sched.perf.get("preemptions") >= 1
            assert job.state is JobState.QUEUED
            assert not job.remote_held
            sched.local_reserver(g.backend.whoami).cancel_reservation(
                "intruder")
            c.deliver_all()
            assert not g.backend.stale
            for oid, want in data.items():
                assert c.get(pid, oid, len(want)) == want
        finally:
            c.shutdown()

    def test_priority_ladder(self):
        c, sched, pid, _data = _degraded_cluster(n_objects=2)
        try:
            g = next(iter(c.pools[pid]["pgs"].values()))
            b = g.backend
            prio = sched.pg_priority(b)
            assert prio >= OSD_RECOVERY_PRIORITY_BASE
            assert sched.pg_priority(b, forced=True) == \
                OSD_RECOVERY_PRIORITY_FORCED
            # pool recovery_priority biases within the band (clamped)
            assert sched.pg_priority(b, {"recovery_priority": "5"}) == \
                prio + 5
            assert sched.pg_priority(b, {"recovery_priority": "99"}) == \
                prio + 10
            # drive the PG inactive: priority escalates past every
            # ordinary recovery
            downed = [s for s in g.acting[1:]][:M + 1]
            for s in downed:
                g.bus.mark_down(s)
            assert not b.is_active()
            assert sched.pg_priority(b) >= \
                OSD_RECOVERY_INACTIVE_PRIORITY_BASE
            for s in downed:
                g.bus.mark_up(s)
            c.deliver_all()
        finally:
            c.shutdown()

    def test_status_and_top_render_recovery(self):
        c, sched, pid, _data = _degraded_cluster(n_objects=2)
        try:
            st = c.status()
            assert "recovery" in st["pgmap"]
            assert set(st["pgmap"]["recovery"]) == \
                {"queued_pgs", "active_pgs", "reservations"}
            rates = st["pgmap"]["io_rates"]["recovery"]
            assert "queued_pgs" in rates and "op_s" in rates
            from ceph_tpu.tools.ceph_cli import render_top
            assert "recovery:" in render_top(c)
            c.deliver_all()
        finally:
            c.shutdown()
