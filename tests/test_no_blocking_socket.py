"""Guard: ``ceph_tpu/msg/`` stays readiness-driven (ISSUE 14).

Thin wrapper over the ``blocking-socket`` and ``thread-spawn-site``
rules in :mod:`ceph_tpu.analysis.rules_guards` (ISSUE 15); semantics
unchanged — blocking verbs only inside ``on_*`` readiness callbacks,
``threading.Thread`` only at the three fixed spawn sites.
"""
import ceph_tpu.analysis as A
from ceph_tpu.analysis.rules_guards import (THREAD_SPAWN_ALLOWLIST,
                                            blocking_socket_sites,
                                            msg_thread_spawn_sites)


def test_scan_sees_the_real_sources():
    """The rules must be scanning something real: the known readiness
    callbacks and the three thread sites exist where claimed."""
    idx = A.default_index()
    files = {m.rel.rsplit("/", 1)[-1]
             for m in idx.iter_modules(("ceph_tpu/msg",))}
    for required in ("connection.py", "reactor.py", "server.py",
                     "client.py"):
        assert required in files, f"{required} moved — update the guard"
    sites = blocking_socket_sites(idx)
    assert ("connection.py", "AsyncConnection.on_readable",
            "recv") in sites, \
        "connection.py lost its on_readable recv — guard is stale"


def test_blocking_socket_verbs_only_in_readiness_callbacks():
    offenders = [f.render() for f in A.run_rules(
        A.default_index(), ("blocking-socket",))]
    assert not offenders, (
        "blocking socket verbs outside reactor readiness callbacks "
        "(move the I/O into an on_* handler, or do the blocking work "
        "in net.py outside ceph_tpu/msg/):\n" + "\n".join(offenders))


def test_no_per_connection_thread_spawns():
    offenders = [f.render() for f in A.run_rules(
        A.default_index(), ("thread-spawn-site",))]
    assert not offenders, (
        "threading.Thread outside the fixed spawn sites (the async "
        "messenger must never spawn per connection/request):\n"
        + "\n".join(offenders))
    # and the allowlist itself stays honest: every listed site exists
    spawns = msg_thread_spawn_sites(A.default_index())
    missing = THREAD_SPAWN_ALLOWLIST - spawns
    assert not missing, f"allowlisted spawn sites vanished: {missing}"
