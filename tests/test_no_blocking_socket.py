"""Guard: ``ceph_tpu/msg/`` stays readiness-driven (ISSUE 14).

The async messenger's whole premise is that sockets are only touched
when the reactor says they're ready, and that thread count never scales
with connections.  Both properties are structural, so both are pinned
by AST (the ``test_wire_guard.py`` pattern — discipline as a test):

- blocking socket verbs (``recv``/``recv_into``/``sendall``/``accept``)
  may appear ONLY inside readiness callbacks (``on_*`` methods), where
  the fd is non-blocking and the call returns immediately;
- ``threading.Thread`` may be constructed ONLY at the three fixed
  spawn sites (the reactor loop, the sized dispatch pool, the single
  mux sender) — never per connection, never per request.

The blocking dial + cephx client handshake deliberately live OUTSIDE
this package (``net.dial_and_handshake``), so the guard needs no
escape hatch for them.
"""
import ast
from pathlib import Path

MSG_DIR = Path(__file__).resolve().parent.parent / "ceph_tpu" / "msg"

BLOCKING_SOCKET_VERBS = {"recv", "recv_into", "sendall", "accept"}

# (file, enclosing "Class.function") — the ONLY places a thread may be
# born in the async messenger: one reactor loop, the fixed dispatch
# pool, the single mux sender.  Anything else is the thread-per-
# connection pattern this subsystem exists to remove.
THREAD_SPAWN_ALLOWLIST = {
    ("reactor.py", "Reactor.start"),
    ("server.py", "Dispatcher.start"),
    ("client.py", "MuxClient.__init__"),
}


class _Scan(ast.NodeVisitor):
    def __init__(self):
        self.stack = []                     # class/function name frames
        self.socket_calls = []              # (qualname, verb, lineno)
        self.thread_spawns = []             # (qualname, lineno)

    def _qual(self):
        return ".".join(self.stack) or "<module>"

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def _visit_fn(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr in BLOCKING_SOCKET_VERBS:
                self.socket_calls.append(
                    (self._qual(), f.attr, node.lineno))
            if f.attr == "Thread" and isinstance(f.value, ast.Name) \
                    and f.value.id == "threading":
                self.thread_spawns.append((self._qual(), node.lineno))
        elif isinstance(f, ast.Name) and f.id == "Thread":
            self.thread_spawns.append((self._qual(), node.lineno))
        self.generic_visit(node)


def _scan(path: Path) -> _Scan:
    s = _Scan()
    s.visit(ast.parse(path.read_text(), filename=str(path)))
    return s


def _enclosing_function(qualname: str) -> str:
    return qualname.split(".")[-1]


def test_scan_sees_the_real_sources():
    """The guard must be scanning something real: the known readiness
    callbacks and the three thread sites exist where claimed."""
    files = sorted(p.name for p in MSG_DIR.glob("*.py"))
    for required in ("connection.py", "reactor.py", "server.py",
                     "client.py"):
        assert required in files, f"{required} moved — update the guard"
    conn = _scan(MSG_DIR / "connection.py")
    assert any(v == "recv" and q.endswith("on_readable")
               for q, v, _ in conn.socket_calls), \
        "connection.py lost its on_readable recv — guard is stale"


def test_blocking_socket_verbs_only_in_readiness_callbacks():
    offenders = []
    for path in sorted(MSG_DIR.glob("*.py")):
        for qual, verb, line in _scan(path).socket_calls:
            fn = _enclosing_function(qual)
            if not fn.startswith("on_"):
                offenders.append(
                    f"{path.name}:{line} {qual} calls .{verb}()")
    assert not offenders, (
        "blocking socket verbs outside reactor readiness callbacks "
        "(move the I/O into an on_* handler, or do the blocking work "
        "in net.py outside ceph_tpu/msg/):\n" + "\n".join(offenders))


def test_no_per_connection_thread_spawns():
    spawns = {}
    for path in sorted(MSG_DIR.glob("*.py")):
        for qual, line in _scan(path).thread_spawns:
            spawns[(path.name, qual)] = line
    rogue = {k: v for k, v in spawns.items()
             if k not in THREAD_SPAWN_ALLOWLIST}
    assert not rogue, (
        "threading.Thread outside the fixed spawn sites (the async "
        "messenger must never spawn per connection/request):\n"
        + "\n".join(f"{f}:{line} in {q}" for (f, q), line in
                    rogue.items()))
    # and the allowlist itself stays honest: every listed site exists
    missing = THREAD_SPAWN_ALLOWLIST - set(spawns)
    assert not missing, f"allowlisted spawn sites vanished: {missing}"
