"""Durable store + restart survival.

Covers the reference's durability contract (reference: src/os/ObjectStore.h
transaction semantics; WAL/compaction shape of src/os/bluestore/BlueStore.cc;
boot path OSD::init src/osd/OSD.cc:2719): atomic transactions survive
process restart via WAL replay, checkpoints compact the log, torn WAL tails
are discarded, and a MiniCluster reopened from disk serves every object —
including repairing a shard that restarted stale through the ordinary
PG-log path.
"""
import numpy as np
import pytest

from ceph_tpu.backend.filestore import FileStore
from ceph_tpu.backend.memstore import GObject, Transaction
from ceph_tpu.cluster import MiniCluster


def payload(n, seed=7):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


class TestFileStore:
    def test_reopen_after_close(self, tmp_path):
        fs = FileStore(tmp_path / "s")
        obj = GObject("a", 0)
        fs.queue_transaction(Transaction().write(obj, 0, b"hello")
                             .setattr(obj, "k", {"v": 1})
                             .omap_setkeys(obj, {"ok": b"ov"}))
        fs.close()
        fs2 = FileStore(tmp_path / "s")
        assert fs2.read(obj) == b"hello"
        assert fs2.getattr(obj, "k") == {"v": 1}
        assert fs2.get_omap(obj) == {"ok": b"ov"}

    def test_reopen_without_close_replays_wal(self, tmp_path):
        """Crash model: the process dies without checkpointing — the WAL
        alone must reconstruct the committed state."""
        fs = FileStore(tmp_path / "s")
        obj = GObject("a", 0)
        for i in range(10):
            fs.queue_transaction(
                Transaction().write(obj, i * 4, bytes([i] * 4)))
        fs._wal.flush()                      # crash: no close/checkpoint
        fs2 = FileStore(tmp_path / "s")
        want = b"".join(bytes([i] * 4) for i in range(10))
        assert fs2.read(obj) == want
        assert fs2.committed_seq == 10

    def test_torn_wal_tail_discarded(self, tmp_path):
        fs = FileStore(tmp_path / "s")
        obj = GObject("a", 0)
        fs.queue_transaction(Transaction().write(obj, 0, b"good"))
        fs._wal.flush()
        # simulate a crash mid-append: garbage half-record at the tail
        with open(tmp_path / "s" / "wal.log", "ab") as f:
            f.write(b"\x40\x00\x00\x00\xde\xad\xbe\xefent")
        fs2 = FileStore(tmp_path / "s")
        assert fs2.read(obj) == b"good"      # the good record survived
        assert fs2.committed_seq == 1        # the torn one never committed

    def test_checkpoint_compacts_and_survives(self, tmp_path):
        fs = FileStore(tmp_path / "s", checkpoint_every=4)
        obj = GObject("a", 0)
        for i in range(11):                  # crosses 2 checkpoints
            fs.queue_transaction(Transaction().write(obj, 0, bytes([i] * 8)))
        assert fs._wal_records < 4
        fs2 = FileStore(tmp_path / "s")
        assert fs2.read(obj) == bytes([10] * 8)

    def test_remove_and_truncate_survive(self, tmp_path):
        fs = FileStore(tmp_path / "s")
        a, b = GObject("a", 0), GObject("b", 0)
        fs.queue_transaction(Transaction().write(a, 0, b"xxxx")
                             .write(b, 0, b"yyyyyyyy"))
        fs.queue_transaction(Transaction().remove(a).truncate(b, 3))
        fs.close()
        fs2 = FileStore(tmp_path / "s")
        assert not fs2.exists(a)
        assert fs2.read(b) == b"yyy"


class TestClusterRestart:
    PROFILE = {"plugin": "jax_rs", "k": "4", "m": "2", "device": "numpy",
               "technique": "reed_sol_van"}

    def test_objects_survive_restart(self, tmp_path):
        c1 = MiniCluster(n_osds=12, chunk_size=256, data_dir=tmp_path)
        pid = c1.create_ec_pool("pool", self.PROFILE, pg_num=4)
        want = {f"obj{i}": payload(256 * 4 * 2, seed=i) for i in range(12)}
        for oid, data in want.items():
            c1.put(pid, oid, data)
        c1.shutdown()

        c2 = MiniCluster.load(tmp_path)
        pid2 = c2.pool_ids["pool"]
        for oid, data in sorted(want.items()):
            assert c2.get(pid2, oid, len(data)) == data, \
                f"{oid} lost across restart"

    def test_restart_preserves_pg_log(self, tmp_path):
        c1 = MiniCluster(n_osds=12, chunk_size=256, data_dir=tmp_path)
        pid = c1.create_ec_pool("pool", self.PROFILE, pg_num=2)
        for i in range(6):
            c1.put(pid, f"o{i}", payload(1024, seed=i))
        heads = {ps: g.backend.pg_log.head
                 for ps, g in c1.pools[pid]["pgs"].items()}
        c1.shutdown()
        c2 = MiniCluster.load(tmp_path)
        pid2 = c2.pool_ids["pool"]
        for ps, g in c2.pools[pid2]["pgs"].items():
            assert g.backend.pg_log.head == heads[ps], \
                f"pg {ps} log head diverged across restart"

    def test_stale_shard_repairs_on_boot(self, tmp_path):
        """A shard that 'crashed' (went down) and missed writes restarts
        stale; the boot-time repair pass must catch it up via the PG log
        before it serves."""
        c1 = MiniCluster(n_osds=12, chunk_size=256, data_dir=tmp_path)
        pid = c1.create_ec_pool("pool", self.PROFILE, pg_num=1)
        g = c1.pools[pid]["pgs"][0]
        c1.put(pid, "early", payload(2048, seed=1))
        victim = g.acting[1]
        g.bus.mark_down(victim)              # shard dies...
        c1.put(pid, "late", payload(2048, seed=2))       # ...misses writes
        c1.put(pid, "early", payload(2048, seed=3))      # and an overwrite
        c1.shutdown()                        # whole cluster "restarts"

        c2 = MiniCluster.load(tmp_path)      # boot repair runs here
        pid2 = c2.pool_ids["pool"]
        g2 = c2.pools[pid2]["pgs"][0]
        assert not g2.backend.stale
        assert c2.get(pid2, "early", 2048) == payload(2048, seed=3)
        assert c2.get(pid2, "late", 2048) == payload(2048, seed=2)
        # the repaired shard's chunks are bit-identical: scrub everywhere
        for oid in ("early", "late"):
            report = g2.backend.be_deep_scrub(oid)
            bad = {c for c, ok in report.items() if not ok}
            assert not bad, f"{oid}: dirty chunks {bad} after boot repair"

    def test_deep_scrub_clean_after_restart(self, tmp_path):
        c1 = MiniCluster(n_osds=12, chunk_size=256, data_dir=tmp_path)
        pid = c1.create_ec_pool("pool", self.PROFILE, pg_num=2)
        for i in range(6):
            c1.put(pid, f"o{i}", payload(1024, seed=i))
        c1.shutdown()
        c2 = MiniCluster.load(tmp_path)
        pid2 = c2.pool_ids["pool"]
        for i in range(6):
            g = c2.pg_group(pid2, f"o{i}")
            report = g.backend.be_deep_scrub(f"o{i}")
            assert all(report.values())

    def test_crash_mid_write_rolls_back_on_boot(self, tmp_path):
        """The crash window the two-phase design exists for: a write that
        reached only the primary's own store when the process died.  Boot
        peering must count witnesses, see the write persisted on fewer
        than min_size shards, and roll it back — the acked old data must
        read back intact, not a garbage mix of chunk versions."""
        c1 = MiniCluster(n_osds=12, chunk_size=256, data_dir=tmp_path)
        pid = c1.create_ec_pool("pool", self.PROFILE, pg_num=1)
        g = c1.pools[pid]["pgs"][0]
        old = payload(2048, seed=1)
        c1.put(pid, "x", old)                       # acked everywhere
        new = payload(2048, seed=2)
        g2 = c1.put(pid, "x", new, deliver=False)   # submit, then "crash":
        pr = g2.backend.whoami
        while g2.bus.deliver_one(pr):               # only the primary's own
            pass                                    # sub-write applies
        c1.shutdown()                               # process dies here

        c2 = MiniCluster.load(tmp_path)
        pid2 = c2.pool_ids["pool"]
        got = c2.get(pid2, "x", 2048)
        assert got == old, \
            "crash-recovery mixed chunk versions instead of rolling back"
        gg = c2.pools[pid2]["pgs"][0]
        assert all(gg.backend.be_deep_scrub("x").values())
        # and the PG is writable again afterwards
        c2.put(pid2, "x", new)
        assert c2.get(pid2, "x", 2048) == new

    def test_crash_after_full_commit_rolls_forward(self, tmp_path):
        """Converse case: the write persisted on ALL shards but the
        process died before the roll-forward kick.  Boot peering must keep
        it (witnesses >= min_size) and drop the stale rollback data."""
        c1 = MiniCluster(n_osds=12, chunk_size=256, data_dir=tmp_path)
        pid = c1.create_ec_pool("pool", self.PROFILE, pg_num=1)
        c1.put(pid, "x", payload(2048, seed=1))
        g = c1.pools[pid]["pgs"][0]
        new = payload(2048, seed=2)
        g2 = c1.put(pid, "x", new, deliver=False)
        for osd in g2.acting:                       # all sub-writes apply...
            while g2.bus.deliver_one(osd):
                pass
        c1.shutdown()           # ...but acks/kick die with the process

        c2 = MiniCluster.load(tmp_path)
        pid2 = c2.pool_ids["pool"]
        assert c2.get(pid2, "x", 2048) == new, \
            "fully-persisted write was lost on boot"
        gg = c2.pools[pid2]["pgs"][0]
        from ceph_tpu.backend.ec_backend import OSDShard
        for h in gg.bus.handlers.values():
            shard = h if isinstance(h, OSDShard) else h.local_shard
            assert not shard.pending_rollbacks, \
                "stale rollback data survived boot roll-forward"

    def test_writes_after_restart(self, tmp_path):
        c1 = MiniCluster(n_osds=12, chunk_size=256, data_dir=tmp_path)
        pid = c1.create_ec_pool("pool", self.PROFILE, pg_num=2)
        c1.put(pid, "a", payload(1024, seed=1))
        c1.shutdown()
        c2 = MiniCluster.load(tmp_path)
        pid2 = c2.pool_ids["pool"]
        c2.put(pid2, "b", payload(1024, seed=2))          # new write
        c2.put(pid2, "a", payload(1024, seed=3))          # overwrite
        assert c2.get(pid2, "a", 1024) == payload(1024, seed=3)
        assert c2.get(pid2, "b", 1024) == payload(1024, seed=2)
        c2.shutdown()
        c3 = MiniCluster.load(tmp_path)                   # third generation
        pid3 = c3.pool_ids["pool"]
        assert c3.get(pid3, "a", 1024) == payload(1024, seed=3)
        assert c3.get(pid3, "b", 1024) == payload(1024, seed=2)
