"""Regenerating-code repair (plugins/plugin_pm_regen.py +
recovery/regen.py + the ECRegenRead/ECRegenHelper hop path): bitwise
equivalence against centralized repair across MBR/MSR geometries,
forced fallbacks (insufficient helpers, helper death mid-inner-product,
rotten helper chunks), wire accounting, and the capability surface."""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.common import Context

# every geometry names its own chunk size: MBR needs (k*c) % B == 0 on
# top of the 128-lane alignment (B = k*d - k*(k-1)/2), MSR only lanes
GEOMETRIES = [
    # (mode, k, m, d, chunk)
    ("mbr", 3, 2, 4, 384),       # B=9,  alpha=4, stored 512/chunk 384
    ("mbr", 4, 2, 5, 896),       # B=14, alpha=5, stored 1280/chunk 896
    ("msr", 2, 2, 2, 128),       # alpha=1: the degenerate MSR point
    ("msr", 3, 2, 4, 128),       # alpha=2, d=2k-2
]


def _cluster(k, m, d, mode, chunk, enable=True, conf=None):
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=chunk,
                    cct=Context())
    c.cct.conf.set("osd_recovery_regen_enable", enable)
    for key, value in (conf or {}).items():
        c.cct.conf.set(key, value)
    c.enable_recovery_scheduler()
    prof = {"plugin": "pm_regen", "k": str(k), "m": str(m), "d": str(d),
            "mode": mode, "device": "numpy"}
    pid = c.create_ec_pool("p", prof, pg_num=1)
    g = next(iter(c.pools[pid]["pgs"].values()))
    return c, pid, g


def _write_degrade_revive(c, pid, g, k, chunk, n_objects, victims=1,
                          seed=3):
    """Write, kill ``victims`` shards, overwrite everything they miss,
    revive, drain.  Returns the expected object contents."""
    rng = np.random.default_rng(seed)
    obj_bytes = 3 * chunk * k
    data = {f"o{i}": rng.integers(0, 256, obj_bytes, np.uint8).tobytes()
            for i in range(n_objects)}
    for oid, d in data.items():
        c.put(pid, oid, d)
    vs = [g.acting[i + 1] for i in range(victims)]
    for v in vs:
        g.bus.mark_down(v)
    for oid in list(data):
        data[oid] = rng.integers(0, 256, obj_bytes, np.uint8).tobytes()
        c.put(pid, oid, data[oid])
    for v in vs:
        g.bus.mark_up(v)
    c.deliver_all()
    return data


def _perf(g):
    return {x: g.backend.perf.get(x) for x in
            ("recoveries", "recovery_failures", "regen_repairs",
             "regen_objects", "regen_fallbacks")}


def _shard_state(g, oids):
    """Every shard's stored bytes + hinfo digest dict, for bitwise
    comparison between repair arms."""
    from ceph_tpu.backend.ecutil import HINFO_KEY
    from ceph_tpu.backend.memstore import GObject
    from ceph_tpu.backend.pg_backend import shard_store
    out = {}
    for oid in sorted(oids):
        for s in g.acting:
            st = shard_store(g.backend.bus, s)
            obj = GObject(oid, s)
            out[(oid, s)] = (st.read(obj, 0, None),
                             st.getattr(obj, HINFO_KEY))
    return out


def _run_arm(mode, k, m, d, chunk, enable, n_objects=6):
    c, pid, g = _cluster(k, m, d, mode, chunk, enable=enable)
    try:
        data = _write_degrade_revive(c, pid, g, k, chunk, n_objects)
        assert not g.backend.stale
        perf = _perf(g)
        for oid, want in data.items():
            assert c.get(pid, oid, len(want)) == want
        assert c.scrub_pool(pid, repair=False) == {}
        state = _shard_state(g, data)
    finally:
        c.shutdown()
    return perf, state


class TestRegenBitwiseEquivalence:
    @pytest.mark.parametrize("mode,k,m,d,chunk", GEOMETRIES)
    def test_regen_matches_centralized(self, mode, k, m, d, chunk):
        """Regenerating repair must land byte-identical shard contents
        AND hinfo digests vs the centralized verified wave — MBR
        (non-systematic, expanded stored chunks) and MSR (systematic,
        d = 2k-2) alike."""
        regen_perf, regen_state = _run_arm(mode, k, m, d, chunk, True)
        cent_perf, cent_state = _run_arm(mode, k, m, d, chunk, False)
        assert regen_perf["regen_objects"] == 6
        assert regen_perf["regen_fallbacks"] == 0
        assert regen_perf["recovery_failures"] == 0
        assert cent_perf["regen_objects"] == 0
        assert regen_state == cent_state

    def test_two_sequential_victims_each_regen(self):
        """Two dead shards repair shard-at-a-time; whichever batch
        arrives with a single missing chunk and d current helpers
        regens, the overlap rides the verified per-object path — no
        failures, clean scrub either way."""
        c, pid, g = _cluster(3, 3, 4, "mbr", 384)
        try:
            data = _write_degrade_revive(c, pid, g, 3, 384, 4,
                                         victims=2)
            assert not g.backend.stale
            perf = _perf(g)
            assert perf["recovery_failures"] == 0
            assert perf["regen_objects"] >= 4
            assert perf["regen_fallbacks"] == 0
            assert perf["recoveries"] == 8          # 4 oids x 2 shards
            for oid, want in data.items():
                assert c.get(pid, oid, len(want)) == want
            assert c.scrub_pool(pid, repair=False) == {}
        finally:
            c.shutdown()


class TestRegenFallbacks:
    def test_insufficient_helpers_stays_centralized(self):
        """Fewer than d current helpers: the planner must leave the
        batch to the verified wave (never a short regen), and repair
        still completes once the helper returns."""
        c, pid, g = _cluster(3, 2, 4, "mbr", 384)
        try:
            rng = np.random.default_rng(3)
            obj_bytes = 3 * 384 * 3
            data = {f"o{i}": rng.integers(0, 256, obj_bytes,
                                          np.uint8).tobytes()
                    for i in range(4)}
            for oid, d in data.items():
                c.put(pid, oid, d)
            victim = g.acting[1]
            g.bus.mark_down(victim)
            for oid in list(data):
                data[oid] = rng.integers(0, 256, obj_bytes,
                                         np.uint8).tobytes()
                c.put(pid, oid, data[oid])
            helper = g.acting[2]
            g.bus.mark_down(helper)      # 3 current < d=4
            g.bus.mark_up(victim)
            c.deliver_all()
            perf = _perf(g)
            assert perf["regen_objects"] == 0
            assert perf["recovery_failures"] == 0
            g.bus.mark_up(helper)
            c.deliver_all()
            assert not g.backend.stale
            for oid, want in data.items():
                assert c.get(pid, oid, len(want)) == want
            assert c.scrub_pool(pid, repair=False) == {}
        finally:
            c.shutdown()

    def test_disabled_option_never_plans(self):
        perf, _state = _run_arm("mbr", 3, 2, 4, 384, False, n_objects=4)
        assert perf["regen_repairs"] == 0
        assert perf["regen_objects"] == 0
        assert perf["recovery_failures"] == 0
        assert perf["recoveries"] == 4

    def test_helper_death_mid_inner_product_falls_back(self):
        """Kill a helper the moment its projection leg arrives: no
        stream, no abort — only the bus down event.  The coordinator's
        down listener pops the repair (the helper is in hop_shards) and
        every object re-drives through the verified path — zero
        acked-write loss, fault stamped in the campaign log."""
        from ceph_tpu.failure import FaultInjector, FaultPlan
        c, pid, g = _cluster(3, 2, 4, "mbr", 384)
        inj = FaultInjector(FaultPlan(seed=11))
        try:
            killed = []
            for s in g.acting[1:]:
                h = g.bus.handlers.get(s)
                shard_obj = getattr(h, "local_shard", h)
                orig = shard_obj._regen_helper_leg

                def hook(msg, _o=orig, _s=shard_obj):
                    if not killed:
                        killed.append(_s.shard)
                        inj.record("regen", "helper_blackhole",
                                   target=_s.shard)
                        g.bus.mark_down(_s.shard)
                    else:
                        _o(msg)
                shard_obj._regen_helper_leg = hook
            data = _write_degrade_revive(c, pid, g, 3, 384, 4)
            assert len(killed) == 1
            g.bus.mark_up(killed[0])
            c.deliver_all()
            assert not g.backend.stale
            perf = _perf(g)
            assert perf["regen_fallbacks"] >= 1
            assert perf["recovery_failures"] == 0
            for oid, want in data.items():          # zero acked loss
                assert c.get(pid, oid, len(want)) == want
            assert c.scrub_pool(pid, repair=False) == {}
            assert inj.summary()["planes"]["regen"][
                "helper_blackhole"] == 1
        finally:
            c.shutdown()

    def test_rotten_helper_chunk_aborts_and_heals(self):
        """Corrupt a surviving chunk without touching its hinfo: the
        helper leg's crc-vs-plan-hinfo check must abort the regen
        (never launder rot into an inner product), and the centralized
        fallback both routes around AND rebuilds the rotten source —
        a verifying scrub comes back clean."""
        from ceph_tpu.backend.memstore import GObject, Transaction
        from ceph_tpu.backend.pg_backend import shard_store
        c, pid, g = _cluster(3, 2, 4, "mbr", 384)
        try:
            rng = np.random.default_rng(5)
            obj_bytes = 3 * 384 * 3
            victim = g.acting[1]
            g.bus.mark_down(victim)
            data = {f"o{i}": rng.integers(0, 256, obj_bytes,
                                          np.uint8).tobytes()
                    for i in range(4)}
            for oid, d in data.items():
                c.put(pid, oid, d)
            # with one chunk lost and d = n-1 = 4, EVERY survivor is a
            # helper: any rotten survivor lands in the plan
            s = g.acting[2]
            st = shard_store(g.bus, s)
            obj = GObject("o0", s)
            rot = bytes(b ^ 0xFF for b in st.read(obj, 0, None))
            st.queue_transaction(Transaction().write(obj, 0, rot))
            g.bus.mark_up(victim)
            c.deliver_all()
            assert not g.backend.stale
            perf = _perf(g)
            assert perf["regen_fallbacks"] >= 1
            assert perf["recovery_failures"] == 0
            assert not g.backend.inconsistent_objects
            for oid, want in data.items():
                assert c.get(pid, oid, len(want)) == want
            assert c.scrub_pool(pid, repair=False) == {}
        finally:
            c.shutdown()


class TestRegenWire:
    def test_regen_legs_account_to_recovery_class(self):
        """Every regen leg is charged ONCE, to the recovery op class;
        the helper beta-streams stay near the d*beta floor and the
        class partition invariant survives the new types."""
        c, pid, g = _cluster(3, 2, 4, "mbr", 1536)
        try:
            before_cls = c.wire.class_bytes()["recovery"]
            data = _write_degrade_revive(c, pid, g, 3, 1536, 6)
            assert _perf(g)["regen_objects"] == 6
            per_type = c.wire.per_type()
            assert per_type["ECRegenRead"]["tx_msgs"] >= 5   # 1+d legs
            assert per_type["ECRegenHelper"]["tx_bytes"] > 0
            regen_bytes = sum(per_type[t]["tx_bytes"] for t in
                              ("ECRegenRead", "ECRegenHelper"))
            delta = c.wire.class_bytes()["recovery"] - before_cls
            assert delta >= regen_bytes
            # MBR repairs at ~1.0 B/B: stored chunk is alpha*N bytes,
            # each of d helpers ships N; total wire must stay under the
            # centralized floor of k stored chunks per loss
            ec = g.backend.ec_impl
            stored = ec.get_stored_chunk_size(1536)
            repaired = 3 * stored * len(data)
            assert delta / repaired < 1.5
            totals = c.wire.totals()
            assert sum(c.wire.class_bytes().values()) == \
                totals["tx_bytes"] + totals["rx_bytes"]
        finally:
            c.shutdown()

    def test_helper_sizer_is_payload_proportional(self):
        from ceph_tpu.backend.messages import ECRegenHelper, ECRegenRead
        from ceph_tpu.common.wire_accounting import wire_size
        small = wire_size(ECRegenHelper(0, 1, 0, 2,
                                        streams={"o": b"x" * 64}))
        big = wire_size(ECRegenHelper(0, 1, 0, 2,
                                      streams={"o": b"x" * 4096}))
        assert big - small >= 4096 - 64
        prime = wire_size(ECRegenRead(0, 1, 0, 1, 2, sub_count=4,
                                      combine=b"c" * 16,
                                      helpers=[0, 2, 3, 4],
                                      oids=["o"], lengths=[512],
                                      versions=[1]))
        assert prime > 16


class TestCapabilitySurface:
    def test_non_regenerating_plugins_default_off(self):
        """jax_rs (and anything else not overriding the capability)
        reports no regenerating repair, and minimum_to_repair delegates
        to the cost-aware decode minimum."""
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {"k": "4", "m": "2", "device": "numpy"})
        assert ec.supports_regenerating_repair() is False
        costs = {0: 1, 1: 1, 2: 1, 3: 1, 4: 3, 5: 3}
        got = ec.minimum_to_repair(0, 4, costs)
        assert got == ec.minimum_to_decode_with_cost(
            {0}, {c: v for c, v in costs.items() if c != 0})

    def test_pm_regen_selects_d_cheapest_helpers(self):
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        ec = ErasureCodePluginRegistry.instance().factory(
            "pm_regen", "", {"k": "3", "m": "2", "d": "4",
                             "mode": "mbr", "device": "numpy"})
        assert ec.supports_regenerating_repair() is True
        costs = {1: 1, 2: 3, 3: 1, 4: 1}
        helpers = ec.minimum_to_repair(0, 4, costs)
        assert sorted(helpers) == [1, 2, 3, 4]
        # with a spare survivor, the expensive one is left out
        costs = {1: 1, 2: 3, 3: 1, 4: 1}
        ec5 = ErasureCodePluginRegistry.instance().factory(
            "pm_regen", "", {"k": "2", "m": "2", "d": "2",
                             "mode": "msr", "device": "numpy"})
        helpers = ec5.minimum_to_repair(0, 2, costs)
        assert len(helpers) == 2 and 2 not in helpers
        with pytest.raises(IOError):
            ec.minimum_to_repair(0, 4, {1: 1, 2: 1, 3: 1})

    def test_regen_spans_are_phase_declared(self):
        from ceph_tpu.common import critpath
        for name in ("recovery.regen", "recovery.regen_hop",
                     "mux.batch_send", "mux.batch_reply"):
            assert critpath.is_declared(name), name
        assert critpath.phase_for("mux.batch_send") == critpath.WIRE
        assert critpath.phase_for("recovery.regen") == critpath.DISPATCH


def test_regen_module_is_queue_guard_scanned():
    """Satellite guard coverage: the unbounded-queue AST scan must walk
    recovery/regen.py (it rglobs ceph_tpu/recovery)."""
    import pathlib
    import test_no_unbounded_queue as guard
    scanned = {p.name for p in guard._scan_files()} \
        if hasattr(guard, "_scan_files") else None
    if scanned is None:
        root = pathlib.Path(guard.__file__).resolve().parent.parent
        assert (root / "ceph_tpu" / "recovery" / "regen.py").exists()
    else:
        assert "regen.py" in scanned
