"""OSDMap mapping chain: scalar oracle semantics + bulk-vs-scalar equality.

The scalar chain mirrors the reference (src/osd/OSDMap.cc:2359-2653,
src/osd/osd_types.cc:1640-1656, src/include/rados.h:86-92) on top of the
golden-validated CRUSH interpreter; the bulk mapper (OSDMapMapping analog)
must agree with it PG-for-PG."""
import numpy as np
import pytest

from ceph_tpu.crush import (CRUSH_BUCKET_STRAW2, CRUSH_ITEM_NONE,
                            CRUSH_RULE_CHOOSELEAF_FIRSTN,
                            CRUSH_RULE_CHOOSELEAF_INDEP, CRUSH_RULE_EMIT,
                            CRUSH_RULE_TAKE, CrushMap)
from ceph_tpu.osdmap import (PG, BulkPGMapper, Incremental, OSDMap, Pool,
                             POOL_TYPE_ERASURE, POOL_TYPE_REPLICATED,
                             apply_incremental, ceph_stable_mod, pg_mask)

NONE = CRUSH_ITEM_NONE


def build_cluster(n_racks=3, hosts_per_rack=3, osds_per_host=3, seed=0):
    """racks -> hosts -> osds, all straw2, uniform-ish weights."""
    rng = np.random.default_rng(seed)
    cmap = CrushMap()
    cmap.set_type_name(1, "host")
    cmap.set_type_name(2, "rack")
    cmap.set_type_name(3, "root")
    osd = 0
    racks = []
    for r in range(n_racks):
        hosts = []
        for h in range(hosts_per_rack):
            items = list(range(osd, osd + osds_per_host))
            osd += osds_per_host
            w = [int(rng.integers(1, 4)) * 0x10000 for _ in items]
            hosts.append(cmap.add_bucket(CRUSH_BUCKET_STRAW2, 1, items, w))
        hw = [sum(cmap.buckets[h].item_weights) for h in hosts]
        racks.append(cmap.add_bucket(CRUSH_BUCKET_STRAW2, 2, hosts, hw))
    rw = [sum(cmap.buckets[r].item_weights) for r in racks]
    root = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 3, racks, rw)
    cmap.set_item_name(root, "default")
    cmap.finalize()

    m = OSDMap(crush=cmap)
    for o in range(osd):
        m.create_osd(o)

    rep_rule = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                              (CRUSH_RULE_CHOOSELEAF_FIRSTN, 0, 1),
                              (CRUSH_RULE_EMIT, 0, 0)])
    ec_rule = cmap.add_rule([(CRUSH_RULE_TAKE, root, 0),
                             (CRUSH_RULE_CHOOSELEAF_INDEP, 0, 1),
                             (CRUSH_RULE_EMIT, 0, 0)])
    m.add_pool(Pool(pool_id=1, type=POOL_TYPE_REPLICATED, size=3,
                    pg_num=64, crush_rule=rep_rule, name="rbd"))
    m.add_pool(Pool(pool_id=2, type=POOL_TYPE_ERASURE, size=6,
                    pg_num=48, crush_rule=ec_rule, name="ecpool"))
    return m


def _row(lst, width):
    out = np.full(width, NONE, dtype=np.int64)
    out[:len(lst)] = lst
    return out


def assert_bulk_matches_scalar(m, pool_id):
    pm = BulkPGMapper(m).map_pool(pool_id)
    pool = m.pools[pool_id]
    for ps in range(pool.pg_num):
        up, upp, act, actp = m.pg_to_up_acting_osds(PG(pool_id, ps))
        assert list(pm.up[ps]) == list(_row(up, pool.size)), f"pg {ps} up"
        assert pm.up_primary[ps] == upp, f"pg {ps} up_primary"
        assert list(pm.acting[ps]) == list(_row(act, pool.size)), (
            f"pg {ps} acting")
        assert pm.acting_primary[ps] == actp, f"pg {ps} acting_primary"


# -- stable_mod / pps -------------------------------------------------------

def test_stable_mod_reference_examples():
    # b=12 -> bmask=15 (rados.h:80-85)
    assert pg_mask(12) == 15
    assert pg_mask(123) == 127
    assert pg_mask(1) == 0
    assert pg_mask(16) == 15
    # entries >= b fold into the lower half-range
    for x in range(64):
        got = ceph_stable_mod(x, 12, 15)
        assert 0 <= got < 12
        if (x & 15) < 12:
            assert got == (x & 15)
        else:
            assert got == (x & 7)


def test_pps_distinct_across_pools():
    m = build_cluster()
    a = m.pools[1].raw_pg_to_pps(PG(1, 5))
    b = m.pools[2].raw_pg_to_pps(PG(2, 5))
    assert a != b


# -- scalar chain semantics -------------------------------------------------

def test_replicated_no_holes_ec_positional_holes():
    m = build_cluster()
    for ps in range(16):
        up, upp, *_ = m.pg_to_up_acting_osds(PG(1, ps))
        assert NONE not in up
        assert len(up) == 3
        assert upp == up[0]
    # kill an OSD: replicated shifts, EC leaves a hole
    m2 = m.clone()
    victim = m.pg_to_up_acting_osds(PG(2, 0))[0][2]
    m2.osd_state[victim] &= ~2          # clear UP
    up, _, _, _ = m2.pg_to_up_acting_osds(PG(2, 0))
    assert up[2] == NONE                # positional hole at slot 2
    for ps in range(16):
        upr, *_ = m2.pg_to_up_acting_osds(PG(1, ps))
        assert NONE not in upr          # replicated compacts


def test_out_osd_remapped():
    m = build_cluster()
    up0, *_ = m.pg_to_up_acting_osds(PG(1, 3))
    victim = up0[0]
    m2 = m.clone()
    m2.osd_weight[victim] = 0           # mark out (reweight 0)
    up, *_ = m2.pg_to_up_acting_osds(PG(1, 3))
    assert victim not in up
    assert len(up) == 3                 # refilled by CRUSH retry


def test_pg_temp_overrides_acting_only():
    m = build_cluster()
    pg = PG(1, 7)
    up0, upp0, *_ = m.pg_to_up_acting_osds(pg)
    tmp = [o for o in range(9) if o not in up0][:3]
    m.pg_temp[pg] = tmp
    up, upp, act, actp = m.pg_to_up_acting_osds(pg)
    assert up == up0 and upp == upp0
    assert act == tmp and actp == tmp[0]
    # primary_temp overrides the acting primary only
    m.primary_temp[pg] = tmp[1]
    *_, actp2 = m.pg_to_up_acting_osds(pg)
    assert actp2 == tmp[1]


def test_pg_temp_down_members_filtered():
    m = build_cluster()
    pg = PG(1, 9)
    m.pg_temp[pg] = [0, 1, 2]
    m.osd_state[1] &= ~2                # down
    _, _, act, _ = m.pg_to_up_acting_osds(pg)
    assert act == [0, 2]                # replicated: shifted out
    pg2 = PG(2, 9)
    m.pg_temp[pg2] = [0, 1, 2, 3, 4, 5]
    _, _, act2, _ = m.pg_to_up_acting_osds(pg2)
    assert act2[1] == NONE              # EC: positional hole


def test_upmap_explicit_and_items():
    m = build_cluster()
    pg = PG(1, 11)
    up0, *_ = m.pg_to_up_acting_osds(pg)
    # explicit full mapping
    want = [o for o in range(9) if o not in up0][:3]
    m.pg_upmap[pg] = want
    up, upp, *_ = m.pg_to_up_acting_osds(pg)
    assert up == want and upp == want[0]
    del m.pg_upmap[pg]
    # pairwise swap: replace up0[1] with an unused osd
    repl = [o for o in range(m.max_osd) if o not in up0][0]
    m.pg_upmap_items[pg] = [(up0[1], repl)]
    up, *_ = m.pg_to_up_acting_osds(pg)
    assert up[1] == repl and up[0] == up0[0] and up[2] == up0[2]


def test_upmap_rejected_when_target_out():
    m = build_cluster()
    pg = PG(1, 13)
    up0, *_ = m.pg_to_up_acting_osds(pg)
    repl = [o for o in range(m.max_osd) if o not in up0][0]
    m.osd_weight[repl] = 0              # target marked out
    m.pg_upmap[pg] = [repl] + up0[1:]
    up, *_ = m.pg_to_up_acting_osds(pg)
    assert up == up0                    # explicit mapping ignored
    m.pg_upmap_items[pg] = [(up0[0], repl)]
    up, *_ = m.pg_to_up_acting_osds(pg)
    assert up == up0                    # item swap ignored too


def test_upmap_rejection_skips_items():
    """A rejected pg_upmap returns early, skipping pg_upmap_items too
    (OSDMap.cc:2396-2400)."""
    m = build_cluster()
    pg = PG(1, 14)
    up0, *_ = m.pg_to_up_acting_osds(pg)
    unused = [o for o in range(m.max_osd) if o not in up0]
    out_osd, valid_repl = unused[0], unused[1]
    m.osd_weight[out_osd] = 0
    m.pg_upmap[pg] = [out_osd] + up0[1:]           # rejected (target out)
    m.pg_upmap_items[pg] = [(up0[0], valid_repl)]  # valid on its own
    up, *_ = m.pg_to_up_acting_osds(pg)
    assert up == up0                    # items skipped after rejection


def test_primary_affinity_zero_never_primary():
    m = build_cluster()
    pg_hits = 0
    for ps in range(m.pools[1].pg_num):
        up, upp, *_ = m.pg_to_up_acting_osds(PG(1, ps))
        if up and up[0] == 0:
            pg_hits += 1
    m.set_primary_affinity(0, 0)
    for ps in range(m.pools[1].pg_num):
        up, upp, *_ = m.pg_to_up_acting_osds(PG(1, ps))
        assert not (upp == 0 and any(o != 0 and o != NONE for o in up)), (
            f"osd.0 stayed primary of pg {ps} despite affinity 0")


# -- incrementals -----------------------------------------------------------

def test_incremental_epoch_and_state():
    m = build_cluster()
    inc = Incremental(new_state={4: 2},          # XOR UP -> osd.4 down
                      new_weight={5: 0},
                      new_pg_temp={PG(1, 1): [6, 7, 8]})
    n = apply_incremental(m, inc)
    assert n.epoch == m.epoch + 1
    assert n.is_down(4) and not m.is_down(4)
    assert n.is_out(5)
    assert n.pg_temp[PG(1, 1)] == [6, 7, 8]
    # clearing pg_temp via empty list
    n2 = apply_incremental(n, Incremental(new_pg_temp={PG(1, 1): []}))
    assert PG(1, 1) not in n2.pg_temp


# -- bulk vs scalar ---------------------------------------------------------

@pytest.mark.parametrize("pool_id", [1, 2])
def test_bulk_matches_scalar_clean(pool_id):
    m = build_cluster()
    assert_bulk_matches_scalar(m, pool_id)


@pytest.mark.parametrize("pool_id", [1, 2])
def test_bulk_matches_scalar_degraded(pool_id):
    m = build_cluster(seed=2)
    rng = np.random.default_rng(11)
    downs = rng.choice(m.max_osd, size=4, replace=False)
    for o in downs[:2]:
        m.osd_state[o] &= ~2            # down
    for o in downs[2:]:
        m.osd_weight[o] = 0             # out
    m.osd_weight[int(downs[0])] = 0x8000  # partial reweight on a down osd
    assert_bulk_matches_scalar(m, pool_id)


@pytest.mark.parametrize("pool_id", [1, 2])
def test_bulk_matches_scalar_affinity_and_overrides(pool_id):
    m = build_cluster(seed=4)
    m.set_primary_affinity(0, 0)
    m.set_primary_affinity(3, 0x8000)
    m.set_primary_affinity(7, 0x4000)
    m.pg_temp[PG(pool_id, 2)] = [8, 7, 6] if pool_id == 1 else [8, 7, 6, 5, 4, 3]
    m.primary_temp[PG(pool_id, 4)] = 5
    up0, *_ = m.pg_to_up_acting_osds(PG(pool_id, 5))
    if up0:
        repl = [o for o in range(m.max_osd) if o not in up0][0]
        m.pg_upmap_items[PG(pool_id, 5)] = [(up0[0], repl)]
    assert_bulk_matches_scalar(m, pool_id)


def test_bulk_nonpow2_pg_num():
    m = build_cluster(seed=6)
    m.pools[1].pg_num = 24              # non-power-of-two: stable_mod folds
    m.pools[1].pgp_num = 24
    assert_bulk_matches_scalar(m, 1)
