"""Guard: every perf counter ships a non-empty description.

Thin wrapper over the ``counter-help`` rule in
:mod:`ceph_tpu.analysis.rules_guards` (ISSUE 15); semantics unchanged —
the prometheus exporter renders each counter's description as its
``# HELP`` line, so every ``PerfCountersBuilder`` adder call
(``add_u64``, ``add_u64_counter``, ``add_u64_avg``, ``add_time_avg``,
``add_histogram``) needs a non-empty description, positional or
keyword; a non-constant description expression is accepted.
"""
import ceph_tpu.analysis as A
from ceph_tpu.analysis.rules_guards import count_counter_adders


def test_scan_finds_counter_builders():
    """The guard must actually be scanning something (if the builder API
    is renamed, update the rule rather than silently guarding nothing)."""
    hits = count_counter_adders(A.default_index())
    assert hits >= 20, f"only {hits} adder calls found — guard is stale"


def test_every_counter_has_help_text():
    offenders = [f.render() for f in A.run_rules(
        A.default_index(), ("counter-help",))]
    assert not offenders, (
        "perf counters without descriptions — prometheus # HELP renders "
        "these as the bare metric name:\n" + "\n".join(offenders))


def test_guard_rejects_empty_descriptions():
    """The rule catches all three shapes it documents."""
    bad = ("b = PerfCountersBuilder('x')\n"
           "b.add_u64_counter('no_desc')\n"
           "b.add_u64('empty', '')\n"
           "b.add_histogram('h', [1, 2])\n"
           "b.add_time_avg('ok', 'described')\n"
           "b.add_histogram('h2', [1], 'described')\n"
           "b.add_u64('kw', description='described')\n")
    found = A.run_rule_on_sources("counter-help", {"bad.py": bad})
    assert len(found) == 3
