"""Guard: every perf counter ships a non-empty description.

The prometheus exporter renders each counter's description as its
``# HELP`` line; an empty description exports as a HELP line that just
repeats the metric name — useless at 3am.  This guard walks every
``PerfCountersBuilder`` adder call in the tree by AST (the
``test_no_bare_time.py`` / ``test_no_unbounded_queue.py`` pattern:
discipline as a test) and fails on a missing or empty description.

Checked adders: ``add_u64``, ``add_u64_counter``, ``add_u64_avg``,
``add_time_avg`` (description = 2nd positional or ``description=``) and
``add_histogram`` (3rd positional, after the bucket bounds).  A
non-constant description expression is accepted — the guard cannot
evaluate it, and a dynamic description is at least A description.
"""
import ast
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
SCAN_DIR = ROOT / "ceph_tpu"

# adder -> index of the description positional (after self)
_ADDERS = {"add_u64": 1, "add_u64_counter": 1, "add_u64_avg": 1,
           "add_time_avg": 1, "add_histogram": 2}


def _description_ok(node: ast.Call, pos_index: int) -> bool:
    for kw in node.keywords:
        if kw.arg == "description":
            return not (isinstance(kw.value, ast.Constant)
                        and not kw.value.value)
    if len(node.args) > pos_index:
        arg = node.args[pos_index]
        return not (isinstance(arg, ast.Constant) and not arg.value)
    return False                      # description omitted entirely


def _scan(path: Path) -> list[str]:
    tree = ast.parse(path.read_text(), filename=str(path))
    offenders = []
    rel = path.relative_to(ROOT).as_posix()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute):
            continue
        pos = _ADDERS.get(node.func.attr)
        if pos is not None and not _description_ok(node, pos):
            offenders.append(
                f"{rel}:{node.lineno}: {node.func.attr}(...) without a "
                f"description (prometheus # HELP quality)")
    return offenders


def test_scan_finds_counter_builders():
    """The guard must actually be scanning something (if the builder API
    is renamed, update _ADDERS rather than silently guarding nothing)."""
    hits = 0
    for path in sorted(SCAN_DIR.rglob("*.py")):
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _ADDERS:
                hits += 1
    assert hits >= 20, f"only {hits} adder calls found — guard is stale"


def test_every_counter_has_help_text():
    offenders = []
    for path in sorted(SCAN_DIR.rglob("*.py")):
        offenders.extend(_scan(path))
    assert not offenders, (
        "perf counters without descriptions — prometheus # HELP renders "
        "these as the bare metric name:\n" + "\n".join(offenders))


def test_guard_rejects_empty_descriptions(tmp_path):
    """The guard catches all three shapes it documents."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "b = PerfCountersBuilder('x')\n"
        "b.add_u64_counter('no_desc')\n"
        "b.add_u64('empty', '')\n"
        "b.add_histogram('h', [1, 2])\n"
        "b.add_time_avg('ok', 'described')\n"
        "b.add_histogram('h2', [1], 'described')\n"
        "b.add_u64('kw', description='described')\n")
    tree = ast.parse(bad.read_text())
    found = [n for n in ast.walk(tree)
             if isinstance(n, ast.Call)
             and isinstance(n.func, ast.Attribute)
             and _ADDERS.get(n.func.attr) is not None
             and not _description_ok(n, _ADDERS[n.func.attr])]
    assert len(found) == 3
