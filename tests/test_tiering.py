"""Hit sets + cache tiering (r4 VERDICT missing #6; reference:
src/osd/HitSet.h, src/osd/PrimaryLogPG.h:952-992 hit_set_* + agent_*,
osd_types.h CACHEMODE_WRITEBACK / FLAG_DIRTY)."""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osd.hit_set import BloomHitSet, archive_oid, is_hit_set_oid
from ceph_tpu.osd.osd_ops import ObjectOperation
from ceph_tpu.osd.tiering import DIRTY_ATTR, CacheTier, TieringAgent


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


class TestBloomHitSet:
    def test_membership_and_fpp(self):
        hs = BloomHitSet(target_size=500, fpp=0.01, seed=7)
        members = [f"obj{i}" for i in range(500)]
        for oid in members:
            hs.insert(oid)
        assert all(hs.contains(o) for o in members)   # no false negatives
        fp = sum(hs.contains(f"other{i}") for i in range(2000))
        assert fp < 2000 * 0.05        # ~1% target, generous bound

    def test_serialization_roundtrip(self):
        hs = BloomHitSet(target_size=100, fpp=0.02)
        for i in range(80):
            hs.insert(f"o{i}")
        hs2 = BloomHitSet.from_bytes(hs.to_bytes())
        assert all(hs2.contains(f"o{i}") for i in range(80))
        assert hs2.inserts == 80


@pytest.fixture
def tiered():
    c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512)
    base = c.create_ec_pool("base", {"k": "2", "m": "1",
                                     "device": "numpy"}, pg_num=4)
    cache = c.create_replicated_pool(
        "cache", size=3, pg_num=4,
        params={"hit_set_count": "2", "hit_set_period": "8"})
    yield c, cache, base
    c.shutdown()


class TestHitSets:
    def test_accumulation_and_archive_ring(self, tiered):
        c, cache, _base = tiered
        for i in range(40):             # 40 ops / period 8 = 5 persists
            c.operate(cache, f"o{i % 4}",
                      ObjectOperation().write_full(b"x" * 100))
        g = c.pg_group(cache, "o0")
        archives = g.engine.hit_set_archives()
        assert 1 <= len(archives) <= 2            # ring trims to count=2
        # trimmed archives are GONE from the store
        from ceph_tpu.backend.memstore import GObject
        store = g.backend.local_shard.store
        kept = [n for n in range(g.engine.hit_set_archive_n)
                if store.exists(GObject(archive_oid(n),
                                        g.backend.whoami))]
        assert len(kept) <= 2
        assert min(kept) >= g.engine.hit_set_archive_n - 2

    def test_temperature(self, tiered):
        c, cache, _base = tiered
        for _ in range(3):
            c.operate(cache, "hot", ObjectOperation().write_full(b"h"))
        g = c.pg_group(cache, "hot")
        assert g.engine.object_temperature("hot") >= 1
        assert g.engine.object_temperature("never-seen") == 0

    def test_internal_ops_not_recorded(self, tiered):
        c, cache, _base = tiered
        g = c.pg_group(cache, "ghost")
        c.operate(cache, "ghost", ObjectOperation().write_full(b"x"),
                  internal=True)
        assert g.engine.object_temperature("ghost") == 0


class TestCacheTier:
    def test_writeback_flush_and_promote(self, tiered):
        c, cache, base = tiered
        tier = CacheTier(c, cache, base)
        agent = TieringAgent(c, cache, base)
        payload = _data(3000, 1)
        tier.write("obj", payload)
        assert agent.is_dirty("obj")
        # not yet on the base pool
        with pytest.raises(IOError):
            c.operate(base, "obj", ObjectOperation().stat())
        agent.flush("obj")
        assert not agent.is_dirty("obj")
        r = c.operate(base, "obj", ObjectOperation().read(0, 0))
        assert r.outdata(0)[:len(payload)] == payload
        # evict, then a read MISS promotes from base
        agent.evict("obj")
        with pytest.raises(IOError):
            c.operate(cache, "obj", ObjectOperation().stat())
        assert tier.read("obj") == payload        # promoted
        c.operate(cache, "obj", ObjectOperation().stat())   # in cache now

    def test_agent_flushes_dirty_and_evicts_cold(self, tiered):
        c, cache, base = tiered
        tier = CacheTier(c, cache, base)
        agent = TieringAgent(c, cache, base)
        for i in range(4):
            tier.write(f"cold{i}", _data(500 + i, i))
        tier.write("hotobj", _data(200, 99))
        # agent passes with aging: hit sets are PER-PG and op-count
        # periods never advance on idle PGs, so the agent pass is the
        # clock — the hot object is re-read each period, the cold ones
        # age out of the ring (count=2) and evict
        stats = {}
        for _ in range(4):
            assert tier.read("hotobj") == _data(200, 99)
            stats = agent.agent_work(age=True)
        assert stats["flushes"] >= 5              # everything flushed
        # cold objects evicted; base holds their bytes
        for i in range(4):
            with pytest.raises(IOError):
                c.operate(cache, f"cold{i}", ObjectOperation().stat())
            r = c.operate(base, f"cold{i}", ObjectOperation().read(0, 0))
            assert r.outdata(0)[:500 + i] == _data(500 + i, i)
        # the hot object stays cached
        c.operate(cache, "hotobj", ObjectOperation().stat())
        assert stats["skipped_hot"] >= 1
        # reads after eviction still work through the tier (promote)
        assert tier.read("cold2") == _data(502, 2)

    def test_dirty_flag_survives_user_xattrs(self, tiered):
        c, cache, base = tiered
        tier = CacheTier(c, cache, base)
        agent = TieringAgent(c, cache, base)
        tier.write("x", b"v1")
        c.operate(cache, "x", ObjectOperation().setxattr("user", b"u"))
        agent.flush("x")
        # user xattrs travel to the base copy; the dirty flag does not
        r = c.operate(base, "x", ObjectOperation().getxattr("user"))
        assert r.outdata(0) == b"u"
        with pytest.raises(IOError):
            c.operate(base, "x", ObjectOperation().getxattr(DIRTY_ATTR))


class TestHitSetsSurviveRestart(object):
    def test_archives_reload(self, tmp_path):
        c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                        data_dir=tmp_path)
        cache = c.create_replicated_pool(
            "cache", size=3, pg_num=4,
            params={"hit_set_count": "2", "hit_set_period": "4"})
        for i in range(16):
            c.operate(cache, "obj", ObjectOperation().write_full(b"x"))
        g = c.pg_group(cache, "obj")
        n_before = g.engine.hit_set_archive_n
        assert n_before >= 2
        c.shutdown()
        c2 = MiniCluster.load(tmp_path)
        g2 = c2.pg_group(c2.pool_ids["cache"], "obj")
        # the ring resumes after the persisted archives
        assert g2.engine.hit_set_archive_n == n_before
        assert g2.engine.object_temperature("obj") >= 1
        c2.shutdown()


class TestRemapKeepsHitSets:
    def test_backfill_rearms_hit_sets_and_moves_archives(self):
        """A remapped cache-pool PG must keep accumulating hit sets and
        keep its archive ring (regression: the rebuilt PGGroup had
        hit_set_params=None, so the agent evicted the whole working set
        as 'cold')."""
        from ceph_tpu.common import Context
        cct = Context(overrides={"mon_osd_down_out_interval": 60})
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512,
                        cct=cct)
        cache = c.create_replicated_pool(
            "cache", size=3, pg_num=4,
            params={"hit_set_count": "2", "hit_set_period": "4"})
        mon = c.attach_monitor()
        for _ in range(10):
            c.operate(cache, "obj", ObjectOperation().write_full(b"x"))
        g = c.pg_group(cache, "obj")
        primaries = {gg.backend.whoami
                     for gg in c.pools[cache]["pgs"].values()}
        victim = next(o for o in g.acting if o not in primaries)
        for r in [o for o in range(8) if o != victim][:4]:
            mon.prepare_failure(victim, r, 0.0, 25.0)
        mon.propose_pending(25.0)
        mon.tick(5000.0)                   # auto-out -> remap + backfill
        g2 = c.pg_group(cache, "obj")
        assert list(g2.acting) != list(g.acting)
        assert g2.engine.hit_set_params is not None
        assert g2.engine.object_temperature("obj") >= 1   # archives moved
        c.operate(cache, "obj", ObjectOperation().read(0, 0))
        c.shutdown()
