"""Tier-1 entry point for the static-analysis engine (ISSUE 15).

Three layers:

- the CLI gate — ``python -m tools.ceph_lint --baseline
  .ceph_lint_baseline.json`` must run clean over the repo (in-process
  so the already-imported runtime registries are reused);
- fixture proof for every deep rule — each must flag its seeded-bad
  fixture package (``tests/lint_fixtures/``) and pass the clean twin,
  so the rules are tested against known ground truth, not just
  self-hosted;
- engine internals — index resolution tiers, the baseline round trip,
  and the rule registry the wrapper tests lean on.
"""
from pathlib import Path

import pytest

import ceph_tpu.analysis as A
from tools import ceph_lint

ROOT = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"


def _fixture(name: str) -> str:
    return (FIXTURES / name).read_text()


# -- the CI gate -------------------------------------------------------------

def test_cli_runs_clean_with_baseline():
    rc = ceph_lint.main(["--baseline",
                         str(ROOT / ".ceph_lint_baseline.json")])
    assert rc == 0, "new (non-baselined) lint findings — run " \
        "python -m tools.ceph_lint --baseline .ceph_lint_baseline.json"


def test_cli_fails_without_baseline_iff_findings_exist():
    findings = A.run_rules(A.default_index())
    rc = ceph_lint.main([])
    assert rc == (1 if findings else 0)


def test_cli_list_rules_and_unknown_rule():
    assert ceph_lint.main(["--list-rules"]) == 0
    assert ceph_lint.main(["--rules", "no-such-rule"]) == 2


def test_baseline_entries_all_carry_justifications():
    base = A.load_baseline(ROOT / ".ceph_lint_baseline.json")
    assert all(j and len(j) > 20 for j in base.values()), \
        "every baseline suppression needs a real justification"


def test_lint_summary_block_shape():
    s = ceph_lint.lint_summary(str(ROOT / ".ceph_lint_baseline.json"))
    assert s["new"] == 0
    assert s["total"] == s["baselined"]
    assert s["rules_run"] >= 19
    assert all(isinstance(v, int) for v in s["by_rule"].values())


# -- fixture proof: lock-order ----------------------------------------------

def test_lock_order_rule_flags_seeded_cycle():
    found = A.run_rule_on_sources(
        "lock-order-cycle", {"cycle.py": _fixture("lock_cycle_bad.py")})
    assert len(found) == 1
    assert "Alpha._lock" in found[0].message
    assert "Beta._lock" in found[0].message


def test_lock_order_rule_passes_clean_twin():
    assert A.run_rule_on_sources(
        "lock-order-cycle",
        {"cycle.py": _fixture("lock_cycle_clean.py")}) == []


def test_callback_under_lock_flags_send_and_stored_callback():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self, on_done):\n"
        "        self._lock = threading.Lock()\n"
        "        self.on_done = on_done\n"
        "    def finish(self, conn):\n"
        "        with self._lock:\n"
        "            self.on_done(self)\n"
        "            conn.send(b'x')\n"
        "    def ok(self, conn):\n"
        "        with self._lock:\n"
        "            n = 1\n"
        "        self.on_done(self)\n"
        "        conn.send(b'x')\n")
    found = A.run_rule_on_sources("callback-under-lock",
                                  {"cb.py": src})
    assert len(found) == 2
    kinds = {f.message.split()[0] for f in found}
    assert kinds == {"callback", "send"}


# -- fixture proof: thread contexts ------------------------------------------

def test_cross_thread_rule_flags_unlocked_mutation():
    found = A.run_rule_on_sources(
        "cross-thread-unlocked",
        {"w.py": _fixture("cross_thread_bad.py")})
    assert len(found) == 1
    f = found[0]
    assert "Worker.count" in f.message
    assert "caller" in f.message and "thread:Worker._loop" in f.message


def test_cross_thread_rule_passes_locked_twin():
    assert A.run_rule_on_sources(
        "cross-thread-unlocked",
        {"w.py": _fixture("cross_thread_clean.py")}) == []


# -- fixture proof: hot-path copies ------------------------------------------

def test_hot_path_copy_flags_all_three_shapes():
    found = A.run_rule_on_sources(
        "hot-path-copy", {"relay.py": _fixture("hot_copy_bad.py")})
    msgs = sorted(f.message for f in found)
    assert len(found) == 3, msgs
    assert any("bytes(view)" in m for m in msgs)
    assert any("payload.tobytes()" in m for m in msgs)
    assert any("pickle.dumps" in m for m in msgs)


def test_hot_path_copy_passes_ids_and_boundaries():
    assert A.run_rule_on_sources(
        "hot-path-copy",
        {"relay.py": _fixture("hot_copy_clean.py")}) == []


# -- fixture proof: jax dispatch purity --------------------------------------

def test_jit_host_sync_flags_direct_and_transitive():
    found = A.run_rule_on_sources(
        "jit-host-sync", {"bad.py": _fixture("jit_sync_bad.py")})
    msgs = " | ".join(f.message for f in found)
    assert "device_get" in msgs and "direct_sync" in msgs
    assert "block_until_ready" in msgs and "transitive_sync" in msgs


def test_jit_donated_reuse_flags_read_after_dispatch():
    found = A.run_rule_on_sources(
        "jit-donated-reuse", {"bad.py": _fixture("jit_sync_bad.py")})
    assert len(found) == 1
    assert "'buf'" in found[0].message


def test_jit_rules_pass_clean_twin():
    clean = {"clean.py": _fixture("jit_sync_clean.py")}
    for rid in ("jit-host-sync", "jit-donated-reuse",
                "jit-nonstatic-shape", "jit-traced-control-flow"):
        assert A.run_rule_on_sources(rid, dict(clean)) == [], rid


def test_jit_recompile_rules_flag_nonstatic_params():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "import functools\n"
        "@functools.partial(jax.jit, static_argnames=('k',))\n"
        "def f(x, n, k):\n"
        "    pad = jnp.zeros(n)\n"
        "    if n > 0:\n"
        "        x = x + pad\n"
        "    for _ in range(k):\n"
        "        x = x * 2\n"
        "    return x\n")
    shape = A.run_rule_on_sources("jit-nonstatic-shape",
                                  {"f.py": src})
    assert [f.message for f in shape] and "'n'" in shape[0].message
    flow = A.run_rule_on_sources("jit-traced-control-flow",
                                 {"f.py": src})
    assert len(flow) == 1 and "'n'" in flow[0].message  # k is static


# -- engine internals --------------------------------------------------------

def test_index_resolution_tiers():
    idx = A.default_index()
    conn = idx.modules["ceph_tpu/msg/connection.py"]
    send = conn.functions["AsyncConnection.send"]
    # self-method tier
    import ast as _ast
    calls = [n for n in _ast.walk(send.node)
             if isinstance(n, _ast.Call)
             and isinstance(n.func, _ast.Attribute)
             and n.func.attr == "_account_tx"]
    assert calls
    hit = idx.resolve_call(send, calls[0])
    assert [h.qualname for h in hit] == ["AsyncConnection._account_tx"]
    # callback-binding tier: AsyncConnection.on_message was bound at
    # construction sites to the server/mux handlers
    handlers = idx.callback_bindings.get(("AsyncConnection",
                                         "on_message"), set())
    assert any("_on_message" in r for r in handlers)


def test_baseline_round_trip(tmp_path):
    f = A.Finding("lock-order-cycle", "x.py", 3, "error", "msg")
    p = tmp_path / "base.json"
    A.write_baseline([f], "known benign because reasons", p)
    base = A.load_baseline(p)
    assert base[f.key] == "known benign because reasons"
    new, suppressed, stale = A.split_by_baseline([f], base)
    assert (new, suppressed) == ([], [f])
    assert stale == []
    g = A.Finding("lock-order-cycle", "y.py", 1, "error", "other")
    new2, _, stale2 = A.split_by_baseline([g], base)
    assert new2 == [g] and stale2 == [f.key]


def test_rule_registry_complete():
    rules = A.all_rules()
    for rid in ("lock-order-cycle", "callback-under-lock",
                "cross-thread-unlocked", "jit-host-sync",
                "jit-nonstatic-shape", "jit-traced-control-flow",
                "jit-donated-reuse", "no-host-sync", "unbounded-queue",
                "blocking-socket", "thread-spawn-site", "bounded-retry",
                "span-owner", "span-phase", "profiler-confinement",
                "bare-clock", "counter-help", "percentile-redef",
                "wire-sizer", "hot-path-copy"):
        assert rid in rules, rid
        assert rules[rid].severity in ("error", "warning")
        assert rules[rid].description


def test_findings_render_path_line_severity_rule():
    f = A.Finding("counter-help", "ceph_tpu/x.py", 12, "error", "boom")
    assert f.render() == "ceph_tpu/x.py:12: error [counter-help] boom"


def test_analysis_import_stays_jax_free():
    import subprocess
    import sys
    code = ("import sys; import ceph_tpu.analysis; "
            "sys.exit(1 if 'jax' in sys.modules else 0)")
    proc = subprocess.run([sys.executable, "-c", code], cwd=ROOT)
    assert proc.returncode == 0, "ceph_tpu.analysis must import " \
        "without dragging in jax (rules import registries lazily)"
