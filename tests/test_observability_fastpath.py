"""ISSUE 18: the observability fast path (sampled tracing, sharded
counters, kill-switch, gated overhead).

Pins the correctness surface that lets the instruments get cheap:

- head-based sampling decided once per trace root, atomic across the
  distributed trace (children inherit the decision + weight);
- slow-op promotion: an UNSAMPLED op that crosses the complaint
  threshold still lands in the ring (the acceptance test — slow ops are
  never lost, even at 1% sampling);
- sample-weight de-bias: weighted percentiles equal unweighted ones on
  unit weights and recover population percentiles from a thinned dump;
- the instruments kill-switch no-ops spans/instants/completes and wire
  accounting, and restores cleanly;
- sharded counter cells fold exactly under concurrent mutation, and the
  wire-class partition invariant survives multi-threaded accounting;
- per-thread tracer batching: pending events are visible to every read
  surface (dump/histograms/reset) and auto-flush at FLUSH_BATCH;
- the instrument-under-lock lint rule flags the PR 15 pattern and
  passes its clean twin;
- the perf gate holds observability.overhead_pct to the absolute cap
  and treats instruments-on throughput as a regression metric;
- trace_report/slo_report label sampled artifacts and weight their
  percentile math.
"""
import importlib.util
import json
import pathlib
import threading
import time

import pytest

import ceph_tpu.analysis as A
from ceph_tpu.common import Context
from ceph_tpu.common import instruments
from ceph_tpu.common.perf_counters import PerfCountersBuilder
from ceph_tpu.common.percentile import percentile, weighted_nearest_rank
from ceph_tpu.common.tracer import FLUSH_BATCH, Tracer
from ceph_tpu.common.wire_accounting import WireAccounting

ROOT = pathlib.Path(__file__).resolve().parent.parent


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"{name}_obs_t", ROOT / "tools" / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# -- head-based sampling -----------------------------------------------------

class TestHeadSampling:
    def test_rate_one_samples_everything(self):
        t = Tracer()
        for _ in range(50):
            ctx = t.new_trace("client")
            assert ctx.sampled and ctx.weight == 1.0
        assert t.micro_records() == []

    def test_rate_zero_samples_nothing(self):
        t = Tracer()
        t.sample_rate = 0.0
        for _ in range(50):
            ctx = t.new_trace("client")
            assert not ctx.sampled and ctx.weight == 1.0
        assert len(t.micro_records()) == 50

    def test_quarter_rate_fraction_and_weight(self):
        t = Tracer()
        t.sample_rate = 0.25
        ctxs = [t.new_trace("client") for _ in range(1000)]
        sampled = [c for c in ctxs if c.sampled]
        # Knuth multiplicative hash over sequential ids is
        # low-discrepancy: the hit count stays near rate * n
        assert 200 <= len(sampled) <= 300
        assert all(c.weight == 4.0 for c in sampled)
        assert all(c.weight == 1.0 for c in ctxs if not c.sampled)

    def test_decision_is_per_trace_id_deterministic(self):
        t = Tracer()
        t.sample_rate = 0.25
        ctx = t.new_trace("client")
        assert t._sample(ctx.trace_id) == ctx.sampled
        assert t._sample(ctx.trace_id) == ctx.sampled

    def test_children_inherit_decision_and_weight(self):
        t = Tracer()
        t.sample_rate = 0.25
        ctxs = [t.new_trace("client") for _ in range(64)]
        assert any(c.sampled for c in ctxs)
        assert any(not c.sampled for c in ctxs)
        for ctx in ctxs:
            child = ctx.child_of(17)
            assert child.sampled == ctx.sampled
            assert child.weight == ctx.weight
            assert child.trace_id == ctx.trace_id

    def test_unsampled_instants_are_suppressed(self):
        t = Tracer()
        t.sample_rate = 0.0
        ctx = t.new_trace("client")
        with t.activate(ctx):
            t.instant("tick")
        assert t.dump()["traceEvents"] == []


# -- slow-op promotion (the acceptance pin) ----------------------------------

class TestSlowOpPromotion:
    def test_slow_ops_never_lost_at_one_percent_sampling(self):
        """THE acceptance test: at sample rate 0.01 every op that
        crosses osd_op_complaint_time reaches the ring — sampled ones
        as weighted events, unsampled ones promoted — and no fast
        unsampled op leaks in."""
        t = Tracer()
        t.sample_rate = 0.01
        t.slow_threshold_s = 0.05
        slow, fast = [], []
        for i in range(200):
            ctx = t.new_trace("client")
            name = f"op{i}"
            if i % 10 == 0:
                slow.append((name, ctx))
                dur = 0.2                      # over the complaint time
            else:
                fast.append((name, ctx))
                dur = 0.001
            t.complete(name, time.time() - dur, dur, ctx=ctx)
        ev = {e["name"]: e for e in t.dump()["traceEvents"]}
        for name, ctx in slow:
            assert name in ev, f"slow op {name} lost"
            args = ev[name]["args"]
            if ctx.sampled:
                assert args.get("sample_weight") == 100.0
                assert "promoted" not in args
            else:
                # promoted events represent only themselves: no weight
                assert args.get("promoted") is True
                assert "sample_weight" not in args
        for name, ctx in fast:
            if not ctx.sampled:
                assert name not in ev
        # every root completed: the micro-record table fully drained
        assert t.micro_records() == []

    def test_fast_unsampled_root_drops_micro_without_event(self):
        t = Tracer()
        t.sample_rate = 0.0
        ctx = t.new_trace("client")
        assert len(t.micro_records()) == 1
        t.complete("fast", time.time() - 0.001, 0.001, ctx=ctx)
        assert t.micro_records() == []
        assert t.dump()["traceEvents"] == []

    def test_span_path_promotes_on_threshold(self):
        t = Tracer()
        t.sample_rate = 0.0
        t.slow_threshold_s = 0.0               # everything counts as slow
        ctx = t.new_trace("client")
        with t.activate(ctx):
            with t.span("slow.work"):
                pass
        events = t.dump()["traceEvents"]
        assert len(events) == 1
        assert events[0]["args"].get("promoted") is True
        assert t.micro_records() == []

    def test_span_path_drops_fast_unsampled(self):
        t = Tracer()
        t.sample_rate = 0.0                    # threshold stays 30 s
        ctx = t.new_trace("client")
        with t.activate(ctx):
            with t.span("fast.work"):
                pass
        assert t.dump()["traceEvents"] == []
        assert t.micro_records() == []

    def test_micro_records_expose_inflight_unsampled_ops(self):
        t = Tracer()
        t.sample_rate = 0.0
        ctx = t.new_trace("recovery")
        recs = t.micro_records()
        assert len(recs) == 1
        assert recs[0]["trace_id"] == ctx.trace_id
        assert recs[0]["op_class"] == "recovery"
        assert recs[0]["start_wall"] <= time.time()
        t.reset()
        assert t.micro_records() == []


# -- weighted percentiles ----------------------------------------------------

class TestWeightedPercentiles:
    def test_unit_weights_match_unweighted_definition(self):
        vals = [3.0, 1.0, 4.0, 1.0, 5.0, 9.0, 2.0, 6.0]
        pairs = sorted((v, 1.0) for v in vals)
        for q in (0, 1, 50, 90, 99, 100):
            assert weighted_nearest_rank(pairs, q) == percentile(vals, q)

    def test_thinned_sample_recovers_population_p99(self):
        population = [float(v) for v in range(1, 1001)]
        full_p99 = percentile(population, 99)
        # keep every 4th value, weight 4 — the head sampler's view
        pairs = [(v, 4.0) for v in population if v % 4 == 0]
        est = weighted_nearest_rank(pairs, 99)
        assert abs(est - full_p99) <= 0.012 * full_p99

    def test_heavy_weight_dominates(self):
        # 99 weighted units at 1.0, a single unit at 100.0: p50 is the
        # heavy value, p99.5 reaches the outlier
        pairs = [(1.0, 99.0), (100.0, 1.0)]
        assert weighted_nearest_rank(pairs, 50) == 1.0
        assert weighted_nearest_rank(pairs, 99.5) == 100.0


# -- the instruments kill-switch ---------------------------------------------

class TestKillSwitch:
    def test_tracer_noops_while_disabled_and_restores(self):
        t = Tracer()
        with instruments.disabled():
            assert not instruments.enabled()
            with t.span("gone") as s:
                s.set(note=1)                  # null span absorbs set()
            t.instant("gone.tick")
            t.complete("gone.op", time.time(), 0.01)
        assert instruments.enabled()
        assert t.dump()["traceEvents"] == []
        assert t.histograms() == {}
        with t.span("back"):
            pass
        assert [e["name"] for e in t.dump()["traceEvents"]] == ["back"]

    def test_wire_accounting_noops_while_disabled(self):
        cct = Context()
        acct = WireAccounting(cct=cct, name="ks")
        try:
            with instruments.disabled():
                acct.account_tx("T", 1000)
                acct.account_rx("T", 1000)
                acct.note_queue_depth(7)
                acct.observe_rpc("m", 0.5)
            totals = acct.totals()
            assert totals["tx_bytes"] == 0 and totals["rx_bytes"] == 0
            assert acct.rpc_methods() == {}
            acct.account_tx("T", 10)           # switch back on: counted
            assert acct.totals()["tx_bytes"] == 10
        finally:
            acct.close()

    def test_disabled_is_exception_safe(self):
        with pytest.raises(RuntimeError):
            with instruments.disabled():
                raise RuntimeError("boom")
        assert instruments.enabled()


# -- sharded counter cells ---------------------------------------------------

class TestShardedCounters:
    def _pc(self):
        return (PerfCountersBuilder("shard")
                .add_u64("gauge")
                .add_u64_counter("n")
                .add_u64_avg("bytes")
                .add_time_avg("lat")
                .add_histogram("h", [0.5, 2.0, 8.0])
                .create_perf_counters())

    def test_concurrent_mutation_folds_exactly(self):
        pc = self._pc()
        threads, per = 8, 500

        def work():
            for i in range(per):
                pc.inc("n")
                pc.inc("bytes", 10)
                pc.tinc("lat", 0.001)
                pc.hinc("h", float(i % 10))

        ts = [threading.Thread(target=work) for _ in range(threads)]
        for th in ts:
            th.start()
        for th in ts:
            th.join()
        total = threads * per
        assert pc.get("n") == total
        d = pc.dump()
        assert d["n"] == total
        assert d["bytes"]["avgcount"] == total
        assert d["bytes"]["sum"] == total * 10
        assert d["lat"]["avgcount"] == total
        assert abs(d["lat"]["sum"] - total * 0.001) < 1e-6
        assert d["h"]["count"] == total
        assert sum(d["h"]["buckets"].values()) == total

    def test_gauge_set_dec_keep_read_modify_write_semantics(self):
        pc = self._pc()
        pc.set("gauge", 10)
        pc.inc("gauge", 5)
        pc.dec("gauge", 3)
        assert pc.get("gauge") == 12
        pc.set("gauge", 0)
        assert pc.get("gauge") == 0

    def test_wire_partition_invariant_under_concurrency(self):
        """sum(class_bytes:*) == tx_bytes + rx_bytes even while eight
        threads account concurrently through the sharded cells."""
        cct = Context()
        acct = WireAccounting(cct=cct, name="part")
        classes = ["client", "recovery", "scrub", "rebalance"]

        class _Ctx:
            def __init__(self, op_class):
                self.op_class = op_class

        def work(seed):
            for i in range(400):
                cls = _Ctx(classes[(seed + i) % len(classes)])
                acct.account_tx("T", 10, ctx=cls)
                if i % 3 == 0:
                    acct.account_rx("T", 7, ctx=cls)

        try:
            ts = [threading.Thread(target=work, args=(k,))
                  for k in range(8)]
            for th in ts:
                th.start()
            for th in ts:
                th.join()
            totals = acct.totals()
            assert totals["tx_bytes"] == 8 * 400 * 10
            assert totals["rx_bytes"] == 8 * 134 * 7
            cls_bytes = acct.class_bytes()
            assert sum(cls_bytes.values()) == \
                totals["tx_bytes"] + totals["rx_bytes"]
        finally:
            acct.close()


# -- per-thread batching -----------------------------------------------------

class TestBatchedRingWrites:
    def test_pending_events_visible_to_every_read_surface(self):
        t = Tracer()
        with t.span("pending.a"):
            pass
        t.instant("pending.b")
        # below FLUSH_BATCH: still in the owner buffer, not the ring
        assert len(t._events) == 0
        names = {e["name"] for e in t.dump()["traceEvents"]}
        assert names == {"pending.a", "pending.b"}
        assert t.histograms()["pending.a"]["count"] == 1

    def test_flush_batch_folds_automatically(self):
        t = Tracer()
        for i in range(FLUSH_BATCH):
            t.instant(f"i{i}")
        assert len(t._events) == FLUSH_BATCH

    def test_explicit_flush_is_the_completion_boundary(self):
        t = Tracer()
        with t.span("done"):
            pass
        assert len(t._events) == 0
        t.flush()
        assert len(t._events) == 1

    def test_reset_drains_pending_before_counting(self):
        t = Tracer()
        with t.span("x"):
            pass
        out = t.reset()
        assert out["success"] == "dropped 1 events"
        assert t.dump()["traceEvents"] == []

    def test_cross_thread_pending_drained_by_dump(self):
        t = Tracer()

        def worker():
            with t.span("other.thread"):
                pass

        th = threading.Thread(target=worker)
        th.start()
        th.join()
        names = [e["name"] for e in t.dump()["traceEvents"]]
        assert names == ["other.thread"]


# -- lint rule: instrument-under-lock ----------------------------------------

_LINT_BAD = (
    "import threading\n"
    "class Sender:\n"
    "    def __init__(self, perf, acct):\n"
    "        self._lock = threading.Lock()\n"
    "        self.perf = perf\n"
    "        self.acct = acct\n"
    "        self.queue = []\n"
    "        self._thread = threading.Thread(target=self._loop,\n"
    "                                        daemon=True)\n"
    "    def _loop(self):\n"
    "        with self._lock:\n"
    "            self.queue.append(1)\n"
    "            self.perf.inc('msgs')\n"
    "            self.acct.account_tx('T', 10)\n"
)

_LINT_CLEAN = (
    "import threading\n"
    "class Sender:\n"
    "    def __init__(self, perf, acct):\n"
    "        self._lock = threading.Lock()\n"
    "        self.perf = perf\n"
    "        self.acct = acct\n"
    "        self.queue = []\n"
    "        self._thread = threading.Thread(target=self._loop,\n"
    "                                        daemon=True)\n"
    "    def _loop(self):\n"
    "        with self._lock:\n"
    "            self.queue.append(1)\n"
    "        self.perf.inc('msgs')\n"
    "        self.acct.account_tx('T', 10)\n"
)


class TestInstrumentUnderLockRule:
    def test_flags_instruments_inside_worker_critical_section(self):
        found = A.run_rule_on_sources("instrument-under-lock",
                                      {"sender.py": _LINT_BAD})
        assert len(found) == 2
        msgs = " | ".join(f.message for f in found)
        assert "self.perf.inc()" in msgs
        assert "self.acct.account_tx()" in msgs
        assert "Sender._loop" in msgs
        assert all(f.severity == "warning" for f in found)

    def test_clean_twin_passes(self):
        assert A.run_rule_on_sources("instrument-under-lock",
                                     {"sender.py": _LINT_CLEAN}) == []

    def test_live_tree_has_no_unbaselined_findings(self):
        findings = A.run_rules(A.default_index(),
                               rule_ids=("instrument-under-lock",))
        baseline = A.load_baseline(str(ROOT / ".ceph_lint_baseline.json"))
        new, _old, _stale = A.split_by_baseline(findings, baseline)
        assert new == [], [f.message for f in new]


# -- perf gate ---------------------------------------------------------------

def _obs_line(overhead_pct, ops_s=1000.0):
    return {"device": "cpu",
            "observability": {"device": "cpu",
                              "overhead_pct": overhead_pct,
                              "instruments_on": {"ops_s": ops_s}}}


class TestOverheadGate:
    @pytest.fixture(scope="class")
    def gate(self):
        return _load_tool("perf_gate")

    def test_absolute_cap_fails_over_ten_percent(self, gate):
        out = gate.evaluate(_obs_line(12.0), None)
        assert not out["ok"]
        assert any("observability.overhead_pct" in f and "cap" in f
                   for f in out["failures"])

    def test_absolute_cap_passes_under_budget(self, gate):
        out = gate.evaluate(_obs_line(8.0), None)
        assert out["ok"], out["failures"]

    def test_instruments_on_throughput_gated_against_reference(self, gate):
        ref = _obs_line(5.0, ops_s=1000.0)
        out = gate.evaluate(_obs_line(5.0, ops_s=600.0), ref)
        assert not out["ok"]
        assert any("observability.ops_s" in f for f in out["failures"])
        ok = gate.evaluate(_obs_line(5.0, ops_s=900.0), ref)
        assert ok["ok"], ok["failures"]


# -- device-telemetry refresh TTL --------------------------------------------

class TestDeviceRefreshTTL:
    def test_scrapes_inside_ttl_reuse_the_snapshot(self):
        from ceph_tpu.mgr.prometheus import _device_refresh_due
        cct = Context()
        cct.conf.set("mgr_device_refresh_ttl", 5.0)
        assert _device_refresh_due(cct, 100.0)
        assert not _device_refresh_due(cct, 102.0)
        assert not _device_refresh_due(cct, 104.9)
        assert _device_refresh_due(cct, 105.1)

    def test_ttl_zero_refreshes_every_scrape(self):
        from ceph_tpu.mgr.prometheus import _device_refresh_due
        cct = Context()
        cct.conf.set("mgr_device_refresh_ttl", 0.0)
        assert _device_refresh_due(cct, 100.0)
        assert _device_refresh_due(cct, 100.0)

    def test_stamp_is_per_context(self):
        # one context's scrape must not starve a DIFFERENT context's
        # first scrape of its own device gauges
        from ceph_tpu.mgr.prometheus import _device_refresh_due
        a, b = Context(), Context()
        a.conf.set("mgr_device_refresh_ttl", 5.0)
        b.conf.set("mgr_device_refresh_ttl", 5.0)
        assert _device_refresh_due(a, 100.0)
        assert _device_refresh_due(b, 100.0)


# -- report tools on sampled dumps -------------------------------------------

class TestSampledReportTools:
    def _sampled_dump(self):
        """A dump where every recorded root carries weight 2 (rate 0.5),
        produced through the real tracer so args schemas stay honest."""
        t = Tracer()
        t.sample_rate = 0.5
        durs = []
        n = 0
        while n < 40:
            ctx = t.new_trace("client")
            if not ctx.sampled:
                continue
            dur = 0.001 * (n + 1)
            t.complete("client.op", time.time() - dur, dur, ctx=ctx)
            durs.append(dur)
            n += 1
        return t.dump(), durs

    def test_trace_report_weights_and_labels_sampled_dump(self, tmp_path):
        tr = _load_tool("trace_report")
        dump, durs = self._sampled_dump()
        events = [e for e in dump["traceEvents"] if e.get("ph") == "X"]
        agg = tr.self_times(events)
        assert tr.is_sampled(agg)
        row = agg["client.op"]
        assert row["count"] == 40
        assert row["weight"] == pytest.approx(80.0)
        doc = json.loads(tr.render_json(agg))
        assert doc["sampled"] is True
        assert doc["spans"][0]["est_count"] == pytest.approx(80.0)
        table = tr.render_table(agg)
        assert "sampled trace" in table.splitlines()[0]

    def test_trace_report_unsampled_dump_stays_unlabeled(self):
        tr = _load_tool("trace_report")
        t = Tracer()
        with t.span("plain"):
            pass
        agg = tr.self_times(
            [e for e in t.dump()["traceEvents"] if e.get("ph") == "X"])
        assert not tr.is_sampled(agg)
        assert json.loads(tr.render_json(agg))["sampled"] is False
        assert "sampled trace" not in tr.render_table(agg)

    def test_slo_report_debiases_sampled_trace_dump(self):
        slo = _load_tool("slo_report")
        dump, durs = self._sampled_dump()
        report = slo.build_report(dump)
        assert report["source"] == "trace"
        assert report["sampled"] is True
        cls = report["classes"]["client"]
        assert cls["ops"] == 40
        assert cls["weighted_ops"] == pytest.approx(80.0)
        # weighted p99 over the recorded ops matches the direct
        # computation on (dur, 2.0) pairs
        pairs = sorted((d, 2.0) for d in durs)
        want = weighted_nearest_rank(pairs, 99) * 1e3
        assert cls["p99_ms"] == pytest.approx(want, rel=1e-3)
        assert "head-sampled" in slo.render(report)
