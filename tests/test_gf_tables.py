"""GF(2^8) field axioms and table consistency.

Mirrors the role of the reference's GF unit coverage (the gf-complete
submodule tests); the field itself (poly 0x11D) is pinned by the jerasure
w=8 / isa-l choice (SURVEY.md §7 hard parts: bit-exactness)."""
import numpy as np
import pytest

from ceph_tpu.gf import (EXP_TABLE, LOG_TABLE, MUL_TABLE, gf_mul, gf_div,
                         gf_inv, gf_pow, mul_bitmatrix, expand_bitmatrix)


def test_exp_log_roundtrip():
    for a in range(1, 256):
        assert EXP_TABLE[LOG_TABLE[a]] == a
    # generator is 2: exp[1] == 2
    assert EXP_TABLE[0] == 1
    assert EXP_TABLE[1] == 2
    assert EXP_TABLE[255] == EXP_TABLE[0]


def test_known_products():
    # hand-checked values in GF(2^8)/0x11D
    assert gf_mul(2, 128) == 0x1D          # x * x^7 = x^8 = poly tail
    assert gf_mul(0x80, 0x02) == 0x1D
    assert gf_mul(3, 7) == 9               # (x+1)(x^2+x+1) = x^3+1... carryless
    assert gf_mul(0, 77) == 0 and gf_mul(77, 0) == 0
    assert gf_mul(1, 77) == 77


def test_mul_table_matches_scalar():
    rng = np.random.default_rng(0)
    for _ in range(500):
        a, b = int(rng.integers(256)), int(rng.integers(256))
        assert MUL_TABLE[a, b] == gf_mul(a, b)


def test_field_axioms_sampled():
    rng = np.random.default_rng(1)
    for _ in range(200):
        a, b, c = (int(x) for x in rng.integers(0, 256, 3))
        assert gf_mul(a, b) == gf_mul(b, a)
        assert gf_mul(a, gf_mul(b, c)) == gf_mul(gf_mul(a, b), c)
        assert gf_mul(a, b ^ c) == gf_mul(a, b) ^ gf_mul(a, c)


def test_inverse():
    for a in range(1, 256):
        assert gf_mul(a, gf_inv(a)) == 1
        assert gf_div(1, a) == gf_inv(a)
    with pytest.raises(ZeroDivisionError):
        gf_inv(0)


def test_pow():
    assert gf_pow(2, 0) == 1
    assert gf_pow(2, 8) == 0x1D
    for n in range(1, 20):
        assert gf_pow(3, n) == gf_mul(gf_pow(3, n - 1), 3)


def test_bitmatrix_is_multiplication():
    rng = np.random.default_rng(2)
    for _ in range(100):
        c, d = int(rng.integers(256)), int(rng.integers(256))
        M = mul_bitmatrix(c)
        x = np.array([(d >> i) & 1 for i in range(8)], dtype=np.uint8)
        y = (M @ x) % 2
        got = sum(int(y[i]) << i for i in range(8))
        assert got == gf_mul(c, d)


def test_expand_bitmatrix_shape():
    A = np.array([[1, 2], [3, 4]], dtype=np.uint8)
    B = expand_bitmatrix(A)
    assert B.shape == (16, 16)
    assert set(np.unique(B)) <= {0, 1}
