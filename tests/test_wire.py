"""Wire protocol: framing, integrity modes, handshake, wire-mode bus.

Mirrors the reference's ProtocolV2 frame semantics
(src/msg/async/frames_v2.h, ProtocolV2.cc): preamble-validated lengths,
per-segment crc32c, secure (MAC) mode, banner/hello handshake, and the
rule that corruption is DETECTED, never silently delivered.
"""
import numpy as np
import pytest

from ceph_tpu.backend import wire
from ceph_tpu.backend.messages import (
    ECSubWrite, ECSubWriteReply, FaultConfig, MessageBus, PGActivate,
    PGLogInfo, PGLogQuery, PushOp,
)
from ceph_tpu.backend.memstore import GObject, Transaction


def test_frame_roundtrip_and_incremental_parse():
    segs = [b"header", b"x" * 1000, b"tail"]
    buf = wire.frame_encode(wire.TAG_MESSAGE, segs)
    p = wire.FrameParser()
    # drip-feed byte by byte: nothing yields until the frame completes
    out = []
    for i in range(len(buf)):
        out += p.feed(buf[i:i + 1])
        if i < len(buf) - 1:
            assert out == []
    assert out == [(wire.TAG_MESSAGE, segs)]
    # two frames in one feed
    p2 = wire.FrameParser()
    assert p2.feed(buf + buf) == [(wire.TAG_MESSAGE, segs)] * 2


def test_corruption_detected_everywhere():
    buf = bytearray(wire.frame_encode(wire.TAG_MESSAGE, [b"abc", b"defg"]))
    for pos in range(len(buf)):
        mutated = bytearray(buf)
        mutated[pos] ^= 0x40
        p = wire.FrameParser()
        with pytest.raises(wire.WireError):
            frames = p.feed(bytes(mutated))
            # a flipped bit may land in a length field that makes the
            # frame look longer: starve it and force the verdict
            if not frames:
                raise wire.WireError("incomplete (length corrupted)")
            raise AssertionError(f"byte {pos} corruption undetected")


def test_secure_mode_mac():
    key = b"k" * 32
    buf = wire.frame_encode(wire.TAG_MESSAGE, [b"secret", b"data"],
                            secret=key)
    assert wire.FrameParser(key).feed(buf) == [
        (wire.TAG_MESSAGE, [b"secret", b"data"])]
    with pytest.raises(wire.WireError):
        wire.FrameParser(b"wrong" * 7).feed(buf)
    tampered = bytearray(buf)
    tampered[-20] ^= 1
    with pytest.raises(wire.WireError):
        wire.FrameParser(key).feed(bytes(tampered))


def test_message_codec_all_types():
    t = Transaction().write(GObject("o", 1), 0, b"abc").setattr(
        GObject("o", 1), "k", b"v")
    samples = [
        ECSubWrite(0, 7, t, at_version=3),
        ECSubWriteReply(1, 7),
        PGLogQuery(0, since=2),
        PGLogInfo(2, 9, 1, entries=[]),
        PGActivate(0, 12, head=9),
        PushOp(0, 5, "obj", {1: b"chunk"}),
    ]
    for msg in samples:
        buf = wire.message_encode(msg)
        [(tag, segs)] = wire.FrameParser().feed(buf)
        back = wire.message_decode(tag, segs)
        assert type(back) is type(msg)
        assert getattr(back, "from_shard", None) == \
            getattr(msg, "from_shard", None)


def test_message_decode_rejects_unknown_type():
    frame = wire.frame_encode(wire.TAG_MESSAGE,
                              [b"NotAMessage", b"payload"])
    [(tag, segs)] = wire.FrameParser().feed(frame)
    with pytest.raises(wire.WireError):
        wire.message_decode(tag, segs)


def test_handshake():
    a = wire.FramedConnection("osd.0")
    b = wire.FramedConnection("osd.1")
    assert not a.ready and not b.ready
    a_bytes, b_bytes = bytes(a.out), bytes(b.out)
    a.receive(b_bytes)
    b.receive(a_bytes)
    assert a.ready and a.peer_hello.entity == "osd.1"
    assert b.ready and b.peer_hello.entity == "osd.0"
    a.out.clear()
    a.send(PGLogQuery(0, since=1))
    msgs = b.receive(bytes(a.out))
    assert isinstance(msgs[0], PGLogQuery) and msgs[0].since == 1


def test_handshake_banner_mismatch():
    a = wire.FramedConnection("osd.0")
    with pytest.raises(wire.WireError):
        a.receive(b"ceph v027 legacy banner....." + b"\0" * 32)


def test_send_before_handshake_fails():
    a = wire.FramedConnection("osd.0")
    with pytest.raises(wire.WireError):
        a.send(PGLogQuery(0))


def test_wire_mode_bus_end_to_end():
    """A full MiniCluster over wire-mode buses: every sub-op serializes
    to framed bytes and back; data still roundtrips bit-exact."""
    import ceph_tpu.cluster as cluster_mod
    from ceph_tpu.cluster import MiniCluster
    orig = cluster_mod.MessageBus
    cluster_mod.MessageBus = lambda: MessageBus(wire=True)
    try:
        c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
        pid = c.create_ec_pool("w", {"k": "2", "m": "1", "device": "numpy"},
                               pg_num=4)
        payload = np.random.default_rng(0).integers(
            0, 256, 5000, np.uint8).tobytes()
        c.put(pid, "obj", payload)
        g = c.pg_group(pid, "obj")
        assert g.bus.wire
        # degraded read over the wire too
        victim = next(o for o in g.acting if o != g.backend.whoami)
        g.bus.mark_down(victim)
        assert c.get(pid, "obj", 5000) == payload
        g.bus.mark_up(victim)
        c.shutdown()
    finally:
        cluster_mod.MessageBus = orig


def test_wire_mode_with_faults():
    """Wire framing composes with cross-sender reorder + dup injection."""
    bus = MessageBus(wire=True)
    got = []

    class H:
        def handle_message(self, m):
            got.append(m)
    bus.register(1, H())
    bus.inject_faults(FaultConfig(seed=3, reorder=True, dup_prob=0.5))
    for i in range(10):
        bus.send(1, PGLogQuery(0, since=i))
    bus.deliver_all()
    assert len(got) >= 10
    assert {m.since for m in got} == set(range(10))
    assert all(isinstance(m, PGLogQuery) for m in got)


def test_banner_split_across_reads():
    a = wire.FramedConnection("osd.0")
    b = wire.FramedConnection("osd.1")
    payload = bytes(b.out)
    # drip the peer's banner+hello in 3-byte chunks: must buffer, not fail
    for i in range(0, len(payload), 3):
        a.receive(payload[i:i + 3])
    assert a.ready and a.peer_hello.entity == "osd.1"


def test_cephx_session_key_secures_the_wire():
    """End-to-end auth->transport integration: a cephx mutual-auth
    session yields the service session key, and that key drives the
    bus's SECURE (HMAC) wire mode — the reference's cephx + msgr v2
    secure-mode pairing (ProtocolV2 auth -> crypto_onwire session
    keys)."""
    from ceph_tpu.auth.cephx import (CephxClient, CephxServiceHandler,
                                     KeyServer)
    from ceph_tpu.cluster import MiniCluster
    import ceph_tpu.cluster as cluster_mod

    ks = KeyServer()
    ks.rotate("osd")
    key = ks.create_entity("client.admin")
    client = CephxClient("client.admin", key)
    client.authenticate(ks, now=100.0)
    ticket = client.get_ticket(ks, "osd", now=100.0)
    authz = client.build_authorizer("osd", now=100.0)
    osd_side = CephxServiceHandler("osd", ks)
    entity, reply = osd_side.verify_authorizer(authz, now=100.0)
    assert entity == "client.admin"
    client.verify_reply("osd", reply, authz.nonce)   # mutual auth

    session_key = ticket.session_key
    orig = cluster_mod.MessageBus
    cluster_mod.MessageBus = lambda: MessageBus(wire=True,
                                                wire_secret=session_key)
    try:
        c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
        pid = c.create_ec_pool("sec", {"k": "2", "m": "1",
                                       "device": "numpy"}, pg_num=4)
        payload = np.random.default_rng(1).integers(
            0, 256, 4000, np.uint8).tobytes()
        c.put(pid, "obj", payload)
        assert c.get(pid, "obj", 4000) == payload
        g = c.pg_group(pid, "obj")
        assert g.bus.wire_secret == session_key
        assert g.bus.delivered > 0
        c.shutdown()
    finally:
        cluster_mod.MessageBus = orig


def test_thrash_composes_with_wire_and_faults():
    """The thrasher's kill/revive churn runs over wire-mode buses WITH
    reorder+dup injection: framing, dedup, and recovery compose."""
    import ceph_tpu.cluster as cluster_mod
    from ceph_tpu.backend.messages import FaultConfig
    from ceph_tpu.cluster import MiniCluster

    def bus_factory():
        bus = MessageBus(wire=True)
        bus.inject_faults(FaultConfig(seed=11, reorder=True, dup_prob=0.2))
        return bus
    orig = cluster_mod.MessageBus
    cluster_mod.MessageBus = bus_factory
    try:
        c = MiniCluster(n_osds=8, osds_per_host=2, chunk_size=512)
        pid = c.create_ec_pool("t", {"k": "2", "m": "2", "device": "numpy"},
                               pg_num=4)
        import random
        rng = random.Random(5)
        model = {}
        for i in range(25):
            oid = f"o{rng.randrange(8)}"
            data = np.random.default_rng(i).integers(
                0, 256, 1024, np.uint8).tobytes()
            g = c.pg_group(pid, oid)
            peers = [o for o in g.acting if o != g.backend.whoami]
            if rng.random() < 0.3:
                victim = rng.choice(peers)
                if victim not in g.bus.down:
                    g.bus.mark_down(victim)
            try:
                c.put(pid, oid, data)
                model[oid] = data
            except IOError:
                pass                      # blocked on inactive PG: fine
            if rng.random() < 0.5:
                for o in list(g.bus.down):
                    g.bus.mark_up(o)
                g.bus.deliver_all()
        for g in c.pools[pid]["pgs"].values():
            for o in list(g.bus.down):
                g.bus.mark_up(o)
            g.bus.deliver_all()
        for oid, want in model.items():
            assert c.get(pid, oid, 1024) == want, oid
        c.shutdown()
    finally:
        cluster_mod.MessageBus = orig
