"""Wire protocol: framing, integrity modes, handshake, wire-mode bus.

Mirrors the reference's ProtocolV2 frame semantics
(src/msg/async/frames_v2.h, ProtocolV2.cc): preamble-validated lengths,
per-segment crc32c, secure (MAC) mode, banner/hello handshake, and the
rule that corruption is DETECTED, never silently delivered.
"""
import numpy as np
import pytest

from ceph_tpu.backend import wire
from ceph_tpu.backend.messages import (
    ECSubWrite, ECSubWriteReply, FaultConfig, MessageBus, PGActivate,
    PGLogInfo, PGLogQuery, PushOp,
)
from ceph_tpu.backend.memstore import GObject, Transaction


def test_frame_roundtrip_and_incremental_parse():
    segs = [b"header", b"x" * 1000, b"tail"]
    buf = wire.frame_encode(wire.TAG_MESSAGE, segs)
    p = wire.FrameParser()
    # drip-feed byte by byte: nothing yields until the frame completes
    out = []
    for i in range(len(buf)):
        out += p.feed(buf[i:i + 1])
        if i < len(buf) - 1:
            assert out == []
    assert out == [(wire.TAG_MESSAGE, segs)]
    # two frames in one feed
    p2 = wire.FrameParser()
    assert p2.feed(buf + buf) == [(wire.TAG_MESSAGE, segs)] * 2


def test_corruption_detected_everywhere():
    buf = bytearray(wire.frame_encode(wire.TAG_MESSAGE, [b"abc", b"defg"]))
    for pos in range(len(buf)):
        mutated = bytearray(buf)
        mutated[pos] ^= 0x40
        p = wire.FrameParser()
        with pytest.raises(wire.WireError):
            frames = p.feed(bytes(mutated))
            # a flipped bit may land in a length field that makes the
            # frame look longer: starve it and force the verdict
            if not frames:
                raise wire.WireError("incomplete (length corrupted)")
            raise AssertionError(f"byte {pos} corruption undetected")


def test_secure_mode_mac():
    key = b"k" * 32
    buf = wire.frame_encode(wire.TAG_MESSAGE, [b"secret", b"data"],
                            secret=key)
    assert wire.FrameParser(key).feed(buf) == [
        (wire.TAG_MESSAGE, [b"secret", b"data"])]
    with pytest.raises(wire.WireError):
        wire.FrameParser(b"wrong" * 7).feed(buf)
    tampered = bytearray(buf)
    tampered[-20] ^= 1
    with pytest.raises(wire.WireError):
        wire.FrameParser(key).feed(bytes(tampered))


def test_message_codec_all_types():
    t = Transaction().write(GObject("o", 1), 0, b"abc").setattr(
        GObject("o", 1), "k", b"v")
    samples = [
        ECSubWrite(0, 7, t, at_version=3),
        ECSubWriteReply(1, 7),
        PGLogQuery(0, since=2),
        PGLogInfo(2, 9, 1, entries=[]),
        PGActivate(0, 12, head=9),
        PushOp(0, 5, "obj", {1: b"chunk"}),
    ]
    for msg in samples:
        buf = wire.message_encode(msg)
        [(tag, segs)] = wire.FrameParser().feed(buf)
        back = wire.message_decode(tag, segs)
        assert type(back) is type(msg)
        assert getattr(back, "from_shard", None) == \
            getattr(msg, "from_shard", None)


def test_message_decode_rejects_unknown_type():
    frame = wire.frame_encode(wire.TAG_MESSAGE,
                              [b"NotAMessage", b"payload"])
    [(tag, segs)] = wire.FrameParser().feed(frame)
    with pytest.raises(wire.WireError):
        wire.message_decode(tag, segs)


def test_handshake():
    a = wire.FramedConnection("osd.0")
    b = wire.FramedConnection("osd.1")
    assert not a.ready and not b.ready
    a_bytes, b_bytes = bytes(a.out), bytes(b.out)
    a.receive(b_bytes)
    b.receive(a_bytes)
    assert a.ready and a.peer_hello.entity == "osd.1"
    assert b.ready and b.peer_hello.entity == "osd.0"
    a.out.clear()
    a.send(PGLogQuery(0, since=1))
    msgs = b.receive(bytes(a.out))
    assert isinstance(msgs[0], PGLogQuery) and msgs[0].since == 1


def test_handshake_banner_mismatch():
    a = wire.FramedConnection("osd.0")
    with pytest.raises(wire.WireError):
        a.receive(b"ceph v027 legacy banner....." + b"\0" * 32)


def test_send_before_handshake_fails():
    a = wire.FramedConnection("osd.0")
    with pytest.raises(wire.WireError):
        a.send(PGLogQuery(0))


def test_wire_mode_bus_end_to_end():
    """A full MiniCluster over wire-mode buses: every sub-op serializes
    to framed bytes and back; data still roundtrips bit-exact."""
    import ceph_tpu.cluster as cluster_mod
    from ceph_tpu.cluster import MiniCluster
    orig = cluster_mod.MessageBus
    cluster_mod.MessageBus = lambda: MessageBus(wire=True)
    try:
        c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
        pid = c.create_ec_pool("w", {"k": "2", "m": "1", "device": "numpy"},
                               pg_num=4)
        payload = np.random.default_rng(0).integers(
            0, 256, 5000, np.uint8).tobytes()
        c.put(pid, "obj", payload)
        g = c.pg_group(pid, "obj")
        assert g.bus.wire
        # degraded read over the wire too
        victim = next(o for o in g.acting if o != g.backend.whoami)
        g.bus.mark_down(victim)
        assert c.get(pid, "obj", 5000) == payload
        g.bus.mark_up(victim)
        c.shutdown()
    finally:
        cluster_mod.MessageBus = orig


def test_wire_mode_with_faults():
    """Wire framing composes with cross-sender reorder + dup injection."""
    bus = MessageBus(wire=True)
    got = []

    class H:
        def handle_message(self, m):
            got.append(m)
    bus.register(1, H())
    bus.inject_faults(FaultConfig(seed=3, reorder=True, dup_prob=0.5))
    for i in range(10):
        bus.send(1, PGLogQuery(0, since=i))
    bus.deliver_all()
    assert len(got) >= 10
    assert {m.since for m in got} == set(range(10))
    assert all(isinstance(m, PGLogQuery) for m in got)


def test_banner_split_across_reads():
    a = wire.FramedConnection("osd.0")
    b = wire.FramedConnection("osd.1")
    payload = bytes(b.out)
    # drip the peer's banner+hello in 3-byte chunks: must buffer, not fail
    for i in range(0, len(payload), 3):
        a.receive(payload[i:i + 3])
    assert a.ready and a.peer_hello.entity == "osd.1"
