"""tools/perf_gate.py: verdicts on pass / regress / platform-fallback
artifacts (the ISSUE-6 gate acceptance: nonzero exit on a synthetic 20%
regression and on a TPU->CPU fallback)."""
import importlib.util
import json
from pathlib import Path

import pytest

_PATH = Path(__file__).resolve().parent.parent / "tools" / "perf_gate.py"
spec = importlib.util.spec_from_file_location("perf_gate_t", _PATH)
perf_gate = importlib.util.module_from_spec(spec)
spec.loader.exec_module(perf_gate)


def _line(value=1000.0, device="tpu", serving=500.0, recovery=80.0,
          pipeline=120.0, p99=2.0, wire_per_byte=6.0, wire_per_op=9000.0,
          pct_of_peak=42.0, slo_p99=5.0, budget=1.0):
    return {
        "metric": "rs_k8m4_1MiB_encode_decode_device_resident",
        "value": value, "unit": "MiB/s", "device": device,
        "serving": {"device": device,
                    "batched": {"ops_s": serving, "p99_ms": p99},
                    "wire": {"per_op": wire_per_op}},
        "recovery": {"device": device, "batched": {"mib_s": recovery},
                     "wire": {"per_byte_repaired": wire_per_byte}},
        "pipeline": {"device": device, "async": {"mib_s": pipeline}},
        "efficiency": {"device": device, "pct_of_peak": pct_of_peak},
        "slo": {"device": device,
                "client": {"p99_ms": slo_p99, "ops": 48,
                           "budget_remaining": budget,
                           "phases": {"device": 0.6, "wire": 0.4}}},
    }


class TestEvaluate:
    def test_pass_within_thresholds(self):
        res = perf_gate.evaluate(_line(value=980.0), _line(),
                                 expect_platform="tpu")
        assert res["ok"] and res["verdict"].startswith("PERF GATE: PASS")
        assert len(res["compared"]) == 10

    def test_twenty_percent_regression_fails(self):
        res = perf_gate.evaluate(_line(value=800.0), _line(value=1000.0))
        assert not res["ok"]
        assert any("core.mib_s" in f for f in res["failures"])
        assert res["verdict"].startswith("PERF GATE: FAIL")

    def test_block_regression_fails_independently(self):
        res = perf_gate.evaluate(_line(recovery=50.0), _line())
        assert not res["ok"]
        assert any("recovery.mib_s" in f for f in res["failures"])

    def test_resilience_block_gated(self):
        """ISSUE 9: the `resilience` block participates — a goodput-
        ratio collapse or a breaker-fallback-throughput cliff past the
        (loose, wall-clock-noisy) 30% threshold fails the round; a
        within-threshold wiggle passes."""
        def rline(ratio=0.8, fallback=200.0):
            line = _line()
            line["resilience"] = {
                "device": "tpu", "goodput_ratio": ratio,
                "breaker": {"fallback_mib_s": fallback, "opens": 1}}
            return line
        res = perf_gate.evaluate(rline(), rline())
        assert res["ok"] and len(res["compared"]) == 12
        res = perf_gate.evaluate(rline(ratio=0.4), rline(ratio=0.8))
        assert not res["ok"]
        assert any("resilience.goodput_ratio" in f
                   for f in res["failures"])
        res = perf_gate.evaluate(rline(fallback=100.0),
                                 rline(fallback=200.0))
        assert not res["ok"]
        assert any("resilience.fallback_mib_s" in f
                   for f in res["failures"])
        # 20% off is inside the loose 30% band for this metric
        res = perf_gate.evaluate(rline(ratio=0.65), rline(ratio=0.8))
        assert res["ok"]

    def test_slo_block_gated(self):
        """ISSUE 10: the `slo` block participates — a client-p99 cliff
        (past the loose 50% band: per-op p99 on a shared host is
        tail-of-the-tail noisy) or a budget burn (budget_remaining
        drop past 30%) fails the round; within-band wiggles pass."""
        res = perf_gate.evaluate(_line(slo_p99=20.0),
                                 _line(slo_p99=5.0))
        assert not res["ok"]
        assert any("slo.client_p99_ms" in f for f in res["failures"])
        # a 40% p99 rise is inside the loose band
        res = perf_gate.evaluate(_line(slo_p99=7.0), _line(slo_p99=5.0))
        assert res["ok"]
        # budget burn: remaining budget dropped 50% -> fail
        res = perf_gate.evaluate(_line(budget=0.5), _line(budget=1.0))
        assert not res["ok"]
        assert any("slo.budget_remaining" in f for f in res["failures"])
        res = perf_gate.evaluate(_line(budget=0.9), _line(budget=1.0))
        assert res["ok"]
        # a latency IMPROVEMENT never fails
        res = perf_gate.evaluate(_line(slo_p99=1.0), _line(slo_p99=5.0))
        assert res["ok"]

    def test_wire_efficiency_regression_direction_is_up(self):
        """Wire metrics gate on INCREASE: repair moving more bytes on
        the wire per byte repaired (or serving per op) is the
        regression, even with throughput unchanged."""
        res = perf_gate.evaluate(_line(wire_per_byte=8.0),
                                 _line(wire_per_byte=6.0))
        assert not res["ok"]
        assert any("recovery.wire_per_byte" in f for f in res["failures"])
        res = perf_gate.evaluate(_line(wire_per_op=12000.0),
                                 _line(wire_per_op=9000.0))
        assert any("serving.wire_per_op" in f for f in res["failures"])
        # a wire-efficiency IMPROVEMENT (fewer bytes moved) passes
        res = perf_gate.evaluate(_line(wire_per_byte=2.0,
                                       wire_per_op=5000.0), _line())
        assert res["ok"]

    def test_pct_of_peak_regression_fails_loose_threshold(self):
        """The ISSUE-8 acceptance pin: a synthetic %-of-peak cliff flips
        the verdict to FAIL.  The metric carries a LOOSE default
        threshold (30%: dispatch wall-clock on a shared host is noisy),
        so a 50% drop fails while ordinary jitter passes."""
        res = perf_gate.evaluate(_line(pct_of_peak=20.0),
                                 _line(pct_of_peak=42.0))
        assert not res["ok"]
        assert any("efficiency.pct_of_peak" in f for f in res["failures"])
        # 20% down is inside the loose threshold: jitter, not a cliff
        res = perf_gate.evaluate(_line(pct_of_peak=34.0),
                                 _line(pct_of_peak=42.0))
        assert res["ok"]
        # an explicit --threshold still tightens it
        res = perf_gate.evaluate(
            _line(pct_of_peak=34.0), _line(pct_of_peak=42.0),
            thresholds={"efficiency.pct_of_peak": 0.10})
        assert not res["ok"]

    def test_efficiency_platform_fallback_not_compared(self):
        # a cpu efficiency block never diffs against a tpu reference —
        # and the fallback itself already hard-fails the gate
        res = perf_gate.evaluate(_line(device="cpu", pct_of_peak=90.0),
                                 _line(device="tpu", pct_of_peak=42.0),
                                 expect_platform="tpu")
        assert not res["ok"]
        assert not any("efficiency.pct_of_peak" in c["metric"]
                       for c in res["compared"])
        assert any("platform fallback" in f for f in res["failures"])

    def test_latency_regression_direction_is_up(self):
        res = perf_gate.evaluate(_line(p99=3.0), _line(p99=2.0))
        assert any("serving.p99_ms" in f for f in res["failures"])
        # a latency DROP is an improvement, never a failure
        res = perf_gate.evaluate(_line(p99=1.0), _line(p99=2.0))
        assert res["ok"]

    def test_platform_fallback_hard_fails(self):
        # the r05 failure mode: expected tpu, measured cpu — the numbers
        # themselves look "fine" (cpu vs cpu is not even compared)
        new = _line(value=7500.0, device="cpu")
        res = perf_gate.evaluate(new, _line(), expect_platform="tpu")
        assert not res["ok"]
        assert any("platform fallback" in f for f in res["failures"])

    def test_tpu_reference_cpu_new_fails_per_block(self):
        res = perf_gate.evaluate(_line(device="cpu"), _line(device="tpu"))
        assert not res["ok"]
        assert any("platform fallback" in f for f in res["failures"])

    def test_cpu_vs_cpu_compares_normally(self):
        res = perf_gate.evaluate(_line(device="cpu"),
                                 _line(device="cpu"),
                                 expect_platform="cpu")
        assert res["ok"] and len(res["compared"]) == 10

    def test_custom_threshold(self):
        ref, new = _line(value=1000.0), _line(value=900.0)
        assert perf_gate.evaluate(new, ref)["ok"]          # 10% default
        res = perf_gate.evaluate(new, ref,
                                 thresholds={"core.mib_s": 0.05})
        assert not res["ok"]

    def test_no_reference_checks_platform_only(self):
        res = perf_gate.evaluate(_line(), None, expect_platform="tpu")
        assert res["ok"]
        res = perf_gate.evaluate(_line(device="cpu"), None,
                                 expect_platform="tpu")
        assert not res["ok"]

    def test_bench_wrapper_normalizes(self):
        wrapped = {"n": 7, "rc": 0, "parsed": _line()}
        res = perf_gate.evaluate(wrapped, {"parsed": _line()},
                                 expect_platform="tpu")
        assert res["ok"]

    def test_legacy_tpu_line_infers_platform(self):
        # BENCH_r03's shape: no device field, no error -> tpu success
        legacy = {"metric": "m", "value": 32222.3, "unit": "MiB/s",
                  "vs_baseline": 4.0}
        assert perf_gate.artifact_platform(legacy) == "tpu"
        fallback = dict(legacy, error="tpu unavailable", device="cpu")
        assert perf_gate.artifact_platform(fallback) == "cpu"


class TestMainAndHistory:
    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return p

    def test_cli_exit_codes(self, tmp_path, capsys):
        ref = self._write(tmp_path, "BENCH_r06.json",
                          {"n": 6, "parsed": _line()})
        good = self._write(tmp_path, "good.json", _line(value=990.0))
        bad = self._write(tmp_path, "bad.json", _line(value=700.0))
        cpu = self._write(tmp_path, "cpu.json",
                          _line(value=9000.0, device="cpu"))
        rd = str(tmp_path)
        assert perf_gate.main([str(good), "--repo-dir", rd,
                               "--check"]) == 0
        assert "PERF GATE: PASS" in capsys.readouterr().out
        assert perf_gate.main([str(bad), "--repo-dir", rd,
                               "--check"]) == 1
        assert "PERF GATE: FAIL" in capsys.readouterr().out
        # TPU->CPU fallback: nonzero even though the number is higher
        assert perf_gate.main([str(cpu), "--repo-dir", rd,
                               "--check"]) == 1
        out = capsys.readouterr().out
        assert "platform fallback" in out
        assert ref.exists()

    def test_legacy_reference_still_gates_tpu_regressions(self):
        # a pre-r04 TPU record (no device markers anywhere) must still
        # participate in per-metric comparison via platform inference —
        # not be skipped as device-unknown
        legacy_ref = {"metric": "m", "value": 32000.0, "unit": "MiB/s",
                      "vs_baseline": 4.0}
        res = perf_gate.evaluate(_line(value=16000.0), legacy_ref,
                                 expect_platform="tpu")
        assert not res["ok"]
        assert any("core.mib_s" in f for f in res["failures"])
        res = perf_gate.evaluate(_line(value=31500.0), legacy_ref,
                                 expect_platform="tpu")
        assert res["ok"] and res["compared"]

    def test_find_reference_skips_errored_artifacts(self, tmp_path):
        # the r05 shape (newest round, but an errored cpu fallback) must
        # not become the baseline while a clean round exists
        self._write(tmp_path, "BENCH_r03.json", {"parsed": _line()})
        self._write(tmp_path, "BENCH_r05.json",
                    {"parsed": dict(_line(device="cpu"),
                                    error="tpu unavailable")})
        _doc, path = perf_gate.find_reference(str(tmp_path))
        assert path.endswith("BENCH_r03.json")
        # ...unless EVERY round errored (cpu-only history still compares)
        (tmp_path / "BENCH_r03.json").unlink()
        _doc, path = perf_gate.find_reference(str(tmp_path))
        assert path.endswith("BENCH_r05.json")

    def test_find_reference_picks_newest_round(self, tmp_path):
        self._write(tmp_path, "BENCH_r02.json",
                    {"parsed": _line(value=1.0)})
        self._write(tmp_path, "BENCH_r09.json",
                    {"parsed": _line(value=9.0)})
        self._write(tmp_path, "BENCH_r08.json", {"parsed": _line(8.0)})
        doc, path = perf_gate.find_reference(str(tmp_path))
        assert path.endswith("BENCH_r09.json")
        assert doc["parsed"]["value"] == 9.0

    def test_expected_platform_from_history(self, tmp_path):
        self._write(tmp_path, "BENCH_r01.json",
                    {"parsed": _line(device="cpu")})
        assert perf_gate.expected_platform(str(tmp_path)) is None
        self._write(tmp_path, "BENCH_r02.json", {"parsed": _line()})
        assert perf_gate.expected_platform(str(tmp_path)) == "tpu"

    def test_gate_for_bench_attaches_verdict(self, tmp_path):
        self._write(tmp_path, "BENCH_r03.json", {"parsed": _line()})
        res = perf_gate.gate_for_bench(_line(value=995.0), str(tmp_path))
        assert res["ok"] and res["reference"] == "BENCH_r03.json"
        assert res["expected_platform"] == "tpu"
        res = perf_gate.gate_for_bench(_line(device="cpu"),
                                       str(tmp_path))
        assert not res["ok"]

    def test_repo_history_gates_the_r05_artifact(self):
        """The real repo history: BENCH_r05 (the silent CPU fallback)
        must FAIL the gate against it."""
        repo = Path(__file__).resolve().parent.parent
        if not (repo / "BENCH_r05.json").exists():
            pytest.skip("no BENCH history in this checkout")
        with open(repo / "BENCH_r05.json") as f:
            r05 = json.load(f)
        res = perf_gate.evaluate(
            r05, None, expect_platform=perf_gate.expected_platform(
                str(repo)))
        assert not res["ok"]
        assert any("platform fallback" in x for x in res["failures"])
