"""Fault injection + self-healing (ISSUE 9).

The TCP ports of the bus fault scenarios (resets / black-holes /
truncation under load, healed by reconnect + resend + reqid dedup), the
circuit breaker's open/half-open/close machine, backoff jitter bounds,
mark-down flap damping, store-plane faults, injector determinism, and a
short seeded chaos soak driving ``tools/chaos_run.py`` end to end twice
to pin the same-seed event-digest guarantee.
"""
import threading
import time

import numpy as np
import pytest

from ceph_tpu.common import Context
from ceph_tpu.failure import (CLOSED, HALF_OPEN, OPEN, CircuitBreaker,
                              DeviceFaults, ExponentialBackoff,
                              FaultConfig, FaultInjector, FaultPlan,
                              FaultyStore, MarkDownLimiter,
                              RetriesExhausted, StoreFaults,
                              TransportFaults, live_breakers)


def _data(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, n, dtype=np.uint8).tobytes()


# -- backoff: full-jitter bounds + bounded budgets ---------------------------

class TestBackoff:
    def test_jitter_bounds(self):
        """Every draw for attempt n lies in [0, min(cap, base * 2^n)] —
        the full-jitter envelope."""
        import random
        bo = ExponentialBackoff(base=0.05, cap=2.0, max_attempts=10,
                                rng=random.Random(42))
        for attempt in range(10):
            ceiling = min(2.0, 0.05 * 2 ** attempt)
            for _ in range(200):
                d = bo.delay(attempt)
                assert 0.0 <= d <= ceiling, (attempt, d, ceiling)

    def test_attempt_budget_is_bounded(self):
        slept = []
        bo = ExponentialBackoff(base=0.01, cap=0.02, max_attempts=5,
                                sleep=slept.append)
        attempts = [a for a, _ in bo.delays()]
        assert attempts == [0, 1, 2, 3, 4]
        assert len(slept) == 4          # no sleep before the first try

    def test_run_raises_retries_exhausted(self):
        calls = []
        bo = ExponentialBackoff(base=0.0, cap=0.0, max_attempts=3)

        def always_fails():
            calls.append(1)
            raise ConnectionError("nope")
        with pytest.raises(RetriesExhausted):
            bo.run(always_fails)
        assert len(calls) == 3

    def test_deadline_cuts_schedule_short(self):
        t = {"now": 0.0}

        def clock():
            return t["now"]

        def sleep(d):
            t["now"] += d
        bo = ExponentialBackoff(base=1.0, cap=1.0, max_attempts=50,
                                deadline=2.5, clock=clock, sleep=sleep)
        attempts = [a for a, _ in bo.delays()]
        assert 1 <= len(attempts) < 50


# -- circuit breaker ---------------------------------------------------------

class TestCircuitBreaker:
    def _clocked(self, **kw):
        t = {"now": 0.0}
        b = CircuitBreaker("t.breaker", clock=lambda: t["now"], **kw)
        return b, t

    def test_opens_after_threshold_consecutive_failures(self):
        b, _ = self._clocked(threshold=3, cooldown=10.0)
        b.record_failure()
        b.record_failure()
        assert b.state == CLOSED
        b.record_failure()
        assert b.state == OPEN and b.opens == 1
        assert not b.allow()

    def test_success_resets_consecutive_count(self):
        b, _ = self._clocked(threshold=2, cooldown=10.0)
        b.record_failure()
        b.record_success()
        b.record_failure()
        assert b.state == CLOSED     # never two IN A ROW

    def test_half_open_probe_and_reclose(self):
        b, t = self._clocked(threshold=1, cooldown=5.0)
        b.record_failure()
        assert b.state == OPEN and not b.allow()
        t["now"] = 5.1
        assert b.allow()             # THE probe slot
        assert b.state == HALF_OPEN
        assert not b.allow()         # only one probe at a time
        b.record_success()
        assert b.state == CLOSED and b.allow()

    def test_half_open_probe_failure_reopens(self):
        b, t = self._clocked(threshold=1, cooldown=5.0)
        b.record_failure()
        t["now"] = 5.1
        assert b.allow()
        b.record_failure()
        assert b.state == OPEN and b.opens == 2
        t["now"] = 6.0               # cooldown restarts from the re-open
        assert not b.allow()
        t["now"] = 10.2
        assert b.allow()

    def test_live_registry_and_close(self):
        b, _ = self._clocked(threshold=1, cooldown=1.0)
        assert b in live_breakers()
        b.close()
        assert b not in live_breakers()

    def test_transition_hook_fires(self):
        seen = []
        t = {"now": 0.0}
        b = CircuitBreaker("hooked", threshold=1, cooldown=1.0,
                           clock=lambda: t["now"],
                           on_transition=lambda br, old, new:
                           seen.append((old, new)))
        b.record_failure()
        t["now"] = 1.1
        b.allow()
        b.record_success()
        assert seen == [(CLOSED, OPEN), (OPEN, HALF_OPEN),
                        (HALF_OPEN, CLOSED)]


# -- mark-down limiter (flap damping) ---------------------------------------

class TestMarkDownLimiter:
    def test_damps_after_count_within_window(self):
        lim = MarkDownLimiter(count=3, window=100.0)
        assert not lim.record_down(4, 10.0)
        assert not lim.record_down(4, 20.0)
        assert lim.record_down(4, 30.0)      # tripped
        assert not lim.allow_up(4)
        assert lim.allow_up(5)               # others unaffected

    def test_old_marks_age_out(self):
        lim = MarkDownLimiter(count=3, window=50.0)
        lim.record_down(1, 0.0)
        lim.record_down(1, 10.0)
        assert not lim.record_down(1, 90.0)  # first two aged out
        assert lim.allow_up(1)

    def test_clear_restores_boots(self):
        lim = MarkDownLimiter(count=2, window=100.0)
        lim.record_down(7, 1.0)
        lim.record_down(7, 2.0)
        assert not lim.allow_up(7)
        assert lim.clear(7)
        assert lim.allow_up(7)
        assert lim.dump().get(7) is None


# -- injector: determinism + event log ---------------------------------------

class TestFaultInjector:
    def test_same_seed_same_decisions_and_digest(self):
        plan = FaultPlan(seed=11, transport=TransportFaults(
            reset_prob=0.3, blackhole_prob=0.2))
        runs = []
        for _ in range(2):
            inj = FaultInjector(FaultPlan(**vars(plan)))
            decisions = [(inj.roll("transport", "reset", 0.3, target="x"),
                          inj.roll("transport", "blackhole", 0.2,
                                   target="y"))
                         for _ in range(200)]
            runs.append((decisions, inj.event_digest()))
        assert runs[0] == runs[1]
        assert any(a or b for a, b in runs[0][0])

    def test_streams_independent_per_kind(self):
        """Enabling a second fault kind must not shift the first kind's
        decision stream — the property that keeps soak repros stable."""
        a = FaultInjector(FaultPlan(seed=5))
        only = [a.roll("transport", "reset", 0.5) for _ in range(100)]
        b = FaultInjector(FaultPlan(seed=5))
        mixed = []
        for _ in range(100):
            mixed.append(b.roll("transport", "reset", 0.5))
            b.roll("store", "eio_read", 0.5)
        assert only == mixed

    def test_zero_prob_consumes_nothing(self):
        a = FaultInjector(FaultPlan(seed=9))
        for _ in range(50):
            a.roll("device", "oom", 0.0)
        first_live = a.roll("device", "oom", 1.0)
        b = FaultInjector(FaultPlan(seed=9))
        assert first_live == b.roll("device", "oom", 1.0)

    def test_events_counted_in_perf_collection(self):
        cct = Context()
        inj = FaultInjector(FaultPlan(seed=1), cct=cct, name="t1")
        try:
            inj.roll("store", "eio_read", 1.0, target="osd.0")
            snap = cct.perf.snapshot()["faults.t1"]
            assert snap.get("injected") == 1
            assert snap.get("store_events") == 1
        finally:
            inj.close()

    def test_bus_plane_unified_under_plan_seed(self):
        """MessageBus.inject_faults accepts a whole FaultPlan; its bus
        events land in the injector's log."""
        from ceph_tpu.backend import MessageBus
        plan = FaultPlan(seed=3, bus=FaultConfig(drop_prob=1.0))
        inj = FaultInjector(plan)
        bus = MessageBus()
        bus.register(0, type("S", (), {"handle_message":
                                       lambda self, m: None})())
        bus.inject_faults(plan)
        bus.fault_log = inj.record
        for i in range(5):
            bus.send(0, ("m", i))
        assert bus.dropped == 5
        assert inj.summary()["planes"]["bus"]["drop"] == 5


# -- store plane -------------------------------------------------------------

class TestStoreFaults:
    def _store(self, **faults):
        from ceph_tpu.backend.memstore import MemStore
        inj = FaultInjector(FaultPlan(seed=2,
                                      store=StoreFaults(**faults)))
        return FaultyStore(MemStore(), inj, target="osd.0"), inj

    def test_injected_eio_on_read(self):
        from ceph_tpu.backend.memstore import GObject, Transaction
        st, _ = self._store(eio_read_prob=1.0)
        obj = GObject("o", 0)
        st.queue_transaction(Transaction().write(obj, 0, b"abc"))
        with pytest.raises(IOError) as ei:
            st.read(obj)
        import errno
        assert ei.value.errno == errno.EIO

    def test_injected_eio_on_write_applies_nothing(self):
        from ceph_tpu.backend.memstore import GObject, Transaction
        st, _ = self._store(eio_write_prob=1.0)
        obj = GObject("o", 0)
        with pytest.raises(IOError):
            st.queue_transaction(Transaction().write(obj, 0, b"abc"))
        assert not st.exists(obj)

    def test_torn_write_applies_strict_prefix(self):
        from ceph_tpu.backend.memstore import GObject, Transaction
        st, inj = self._store(torn_write_prob=1.0)
        a, b = GObject("a", 0), GObject("b", 0)
        t = Transaction().write(a, 0, b"AA").write(b, 0, b"BB")
        with pytest.raises(IOError, match="torn"):
            st.queue_transaction(t)
        assert st.exists(a) and not st.exists(b)
        assert inj.summary()["planes"]["store"]["torn_write"] == 1

    def test_slow_read_stalls_then_returns(self):
        from ceph_tpu.backend.memstore import GObject, Transaction
        st, _ = self._store(slow_read_prob=1.0, slow_read_ms=10.0)
        obj = GObject("o", 0)
        st.queue_transaction(Transaction().write(obj, 0, b"xyz"))
        t0 = time.monotonic()
        assert st.read(obj) == b"xyz"
        assert time.monotonic() - t0 >= 0.009

    def test_delegation_and_unwrap(self):
        from ceph_tpu.failure import unwrap
        st, _ = self._store()
        assert st.list_objects() == []
        assert unwrap(st) is st._store


# -- TCP transport: the bus fault scenarios ported to real sockets -----------

def _served_cluster(tmp_path, plan, **overrides):
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.net import ClusterServer
    cct = Context(overrides={
        "ms_rpc_timeout": 4.0, "ms_rpc_retry_attempts": 4,
        "ms_reconnect_backoff_base": 0.01,
        "ms_reconnect_backoff_cap": 0.05, **overrides})
    c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                    cct=cct, data_dir=tmp_path)
    inj = c.inject_faults(plan)
    server = ClusterServer(c)
    server.inject_faults(inj)
    server.start()
    return c, server, inj, cct


class TestTcpTransportFaults:
    PROFILE = {"k": "2", "m": "1", "device": "numpy",
               "technique": "reed_sol_van"}

    def _client(self, server, tmp_path, cct):
        from ceph_tpu.net import TcpRados
        return TcpRados("127.0.0.1", server.port,
                        tmp_path / "client.admin.keyring", cct=cct)

    def test_resets_under_load_zero_acked_loss(self, tmp_path):
        """Connection resets on sends AND receipts: every acked write
        reads back (reconnect + resend + reqid dedup — the drop_prob
        data loss of the bus, healed on the TCP path)."""
        plan = FaultPlan(seed=5, transport=TransportFaults(
            reset_prob=0.15))
        c, server, inj, cct = _served_cluster(tmp_path, plan,
                                              ms_rpc_retry_attempts=8,
                                              ms_rpc_timeout=8.0)
        try:
            r = self._client(server, tmp_path, cct)
            r.mkpool("p", profile=dict(self.PROFILE))
            model = {}
            for i in range(25):
                data = _data(2048, seed=i)
                r.put("p", f"o{i % 8}", data)
                model[f"o{i % 8}"] = data
            for oid, want in sorted(model.items()):
                assert r.get("p", oid) == want, oid
            kinds = inj.summary()["planes"].get("transport", {})
            assert kinds.get("reset", 0) + kinds.get("recv_reset", 0) > 0
            assert r.reconnects > 0
            r.close()
        finally:
            server.stop()
            c.shutdown()

    def test_blackholed_requests_resend_and_dedup(self, tmp_path):
        """A swallowed request (no reply, connection alive) heals via
        the per-RPC deadline -> resend -> server-side reqid dedup: no
        double apply, no lost ack."""
        plan = FaultPlan(seed=9, transport=TransportFaults(
            blackhole_prob=0.12))
        c, server, inj, cct = _served_cluster(tmp_path, plan,
                                              ms_rpc_timeout=2.0)
        try:
            r = self._client(server, tmp_path, cct)
            r.mkpool("p", profile=dict(self.PROFILE))
            model = {}
            for i in range(15):
                data = _data(1536, seed=100 + i)
                r.put("p", f"b{i % 5}", data)
                model[f"b{i % 5}"] = data
            for oid, want in sorted(model.items()):
                assert r.get("p", oid) == want, oid
            assert inj.summary()["planes"][
                "transport"].get("blackhole", 0) > 0
            assert r.resends > 0
            r.close()
        finally:
            server.stop()
            c.shutdown()

    def test_truncated_frames_under_load(self, tmp_path):
        """Partial frames on the wire (mid-frame RST): the client's
        parser dies, reconnect + resend recover every op."""
        plan = FaultPlan(seed=4, transport=TransportFaults(
            truncate_prob=0.10, delay_prob=0.2, delay_ms=1.0))
        c, server, inj, cct = _served_cluster(tmp_path, plan)
        try:
            r = self._client(server, tmp_path, cct)
            r.mkpool("p", profile=dict(self.PROFILE))
            model = {}
            for i in range(20):
                data = _data(1024, seed=200 + i)
                r.put("p", f"t{i % 6}", data)
                model[f"t{i % 6}"] = data
            for oid, want in sorted(model.items()):
                assert r.get("p", oid) == want, oid
            assert inj.summary()["planes"][
                "transport"].get("truncate", 0) > 0
            r.close()
        finally:
            server.stop()
            c.shutdown()

    def test_ms_inject_socket_failures_option_auto_arms(self, tmp_path):
        """The reference's config surface: ms_inject_socket_failures=N
        arms a reset roughly every N post-auth messages with no code —
        and the self-healing client rides them out."""
        from ceph_tpu.cluster import MiniCluster
        from ceph_tpu.net import ClusterServer
        cct = Context(overrides={
            "ms_inject_socket_failures": 6,
            "ms_rpc_retry_attempts": 8, "ms_rpc_timeout": 8.0,
            "ms_reconnect_backoff_base": 0.01,
            "ms_reconnect_backoff_cap": 0.05})
        c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                        cct=cct, data_dir=tmp_path)
        server = ClusterServer(c)
        server.start()
        try:
            assert server.fault_hooks is not None
            r = self._client(server, tmp_path, cct)
            r.mkpool("p", profile=dict(self.PROFILE))
            model = {}
            for i in range(20):
                data = _data(1024, seed=300 + i)
                r.put("p", f"a{i % 5}", data)
                model[f"a{i % 5}"] = data
            for oid, want in sorted(model.items()):
                assert r.get("p", oid) == want, oid
            assert server.fault_hooks.inj.summary()["total"] > 0
            r.close()
        finally:
            server.stop()
            c.shutdown()

    def test_handshake_never_faulted(self, tmp_path):
        """Even at reset_prob 1.0 a fresh client can connect and auth —
        injection arms only post-auth, so reconnects always succeed."""
        plan = FaultPlan(seed=1, transport=TransportFaults(
            reset_prob=1.0))
        c, server, inj, cct = _served_cluster(tmp_path, plan)
        try:
            r = self._client(server, tmp_path, cct)
            assert r.ch.secret is not None
            r.close()
        finally:
            server.stop()
            c.shutdown()


# -- device plane: pipeline breaker integration ------------------------------

class TestPipelineBreaker:
    K, M, CHUNK = 4, 2, 1024

    def _parts(self):
        from ceph_tpu.backend.ecutil import StripeInfo
        from ceph_tpu.plugins.registry import ErasureCodePluginRegistry
        ec = ErasureCodePluginRegistry.instance().factory(
            "jax_rs", "", {"plugin": "jax_rs", "k": str(self.K),
                           "m": str(self.M),
                           "technique": "reed_sol_van", "device": "jax"})
        return ec, StripeInfo(self.K, self.CHUNK)

    def test_injected_dispatch_failures_trip_breaker_and_heal(self):
        from ceph_tpu.backend import ecutil
        from ceph_tpu.mgr.health import device_degraded_check
        from ceph_tpu.ops.pipeline import CodecPipeline
        ec, sinfo = self._parts()
        cct = Context(overrides={"pipeline_breaker_threshold": 2,
                                 "pipeline_breaker_cooldown": 0.05})
        plan = FaultPlan(seed=8, device=DeviceFaults(
            dispatch_fail_prob=1.0))
        inj = FaultInjector(plan)
        pl = CodecPipeline(depth=2, name="chaos.bt", cct=cct)
        try:
            pl.inject_faults(inj)
            bufs = [_data(2 * self.K * self.CHUNK, seed=i)
                    for i in range(5)]
            futs = [ecutil.encode_many_pipelined(sinfo, ec, [b], pl)
                    for b in bufs]
            pl.flush()
            # every batch SUCCEEDS (host fallback), bitwise-identical
            for buf, fut in zip(bufs, futs):
                got = fut.result(30)[0]
                want = ecutil.encode(sinfo, ec, buf)
                assert {c: bytes(v) for c, v in got.items()} == \
                    {c: bytes(v) for c, v in want.items()}
            assert pl.breaker.state == OPEN
            assert pl.perf.get("host_fallbacks") >= 3
            # DEVICE_DEGRADED sees the open breaker...
            res = device_degraded_check()()
            assert res is not None and "degraded" in res.summary
            # ...heal the device; the half-open probe re-closes
            plan.device.dispatch_fail_prob = 0.0
            time.sleep(0.06)
            probe = ecutil.encode_many_pipelined(sinfo, ec, [bufs[0]],
                                                 pl)
            pl.flush()
            probe.result(30)
            assert pl.breaker.state == CLOSED
        finally:
            pl.close()
        assert device_degraded_check()() is None   # closed + unregistered

    def test_completion_failure_heals_via_fallback(self):
        from ceph_tpu.backend import ecutil
        from ceph_tpu.ops.pipeline import CodecPipeline
        ec, sinfo = self._parts()
        cct = Context(overrides={"pipeline_breaker_threshold": 3})
        plan = FaultPlan(seed=6, device=DeviceFaults(
            completion_fail_prob=1.0))
        inj = FaultInjector(plan)
        pl = CodecPipeline(depth=4, name="chaos.ct", cct=cct)
        try:
            pl.inject_faults(inj)
            buf = _data(2 * self.K * self.CHUNK, seed=3)
            fut = ecutil.encode_many_pipelined(sinfo, ec, [buf], pl)
            pl.flush()
            got = fut.result(30)[0]
            want = ecutil.encode(sinfo, ec, buf)
            assert {c: bytes(v) for c, v in got.items()} == \
                {c: bytes(v) for c, v in want.items()}
            assert fut.fallback
        finally:
            pl.close()

    def test_breaker_rejoins_live_registry_on_engine_restart(self):
        """stop() closes the pipeline (breaker leaves the registry);
        start() must bring it BACK, or DEVICE_DEGRADED goes blind after
        any engine restart."""
        from ceph_tpu.exec.engine import ServingEngine
        ec, sinfo = self._parts()
        eng = ServingEngine(ec_impl=ec, sinfo=sinfo, name="restart.brk")
        try:
            b = eng.pipeline.breaker
            assert b is not None and b in live_breakers()
            eng.stop()
            assert b not in live_breakers()
            eng.start()
            assert b in live_breakers()
        finally:
            eng.stop()

    def test_rados_shutdown_releases_objecter(self):
        from ceph_tpu.client.rados import Rados
        from ceph_tpu.cluster import MiniCluster
        c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                        cct=Context())
        try:
            with Rados(c) as r:
                name = r.objecter.perf.name
                assert name in c.cct.perf.snapshot()
            assert name not in c.cct.perf.snapshot()
        finally:
            c.shutdown()

    def test_injected_oom_without_fallback_surfaces(self):
        from ceph_tpu.failure import InjectedOOM
        from ceph_tpu.ops.pipeline import CodecPipeline
        cct = Context(overrides={"pipeline_breaker_threshold": 0})
        plan = FaultPlan(seed=2, device=DeviceFaults(oom_prob=1.0))
        pl = CodecPipeline(depth=2, name="chaos.oom", cct=cct)
        try:
            pl.inject_faults(FaultInjector(plan))
            fut = pl.submit(lambda: np.zeros(8, np.uint8),
                            lambda packed: packed, None)
            assert isinstance(fut.exception(5), InjectedOOM)
        finally:
            pl.close()


# -- mon: flap damping through heartbeats ------------------------------------

class TestFlapDamping:
    def _mon(self, **overrides):
        from ceph_tpu.crush import (CRUSH_BUCKET_STRAW2, CrushMap)
        from ceph_tpu.mon import Monitor
        from ceph_tpu.osdmap import OSDMap
        cmap = CrushMap()
        cmap.set_type_name(1, "host")
        cmap.set_type_name(2, "root")
        hosts = []
        for h0 in range(0, 9, 3):
            hb = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 1,
                                 list(range(h0, h0 + 3)), [0x10000] * 3)
            cmap.set_item_name(hb, f"host{h0 // 3}")
            hosts.append(hb)
        root = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 2, hosts,
                               [0x30000] * len(hosts))
        cmap.set_item_name(root, "default")
        cmap.finalize()
        m = OSDMap(crush=cmap)
        for o in range(9):
            m.create_osd(o)
        cct = Context(overrides={"osd_markdown_count": 3,
                                 "osd_markdown_window": 1000.0,
                                 **overrides})
        return Monitor(m, cct=cct)

    def _flap_once(self, mon, victim, now):
        mon.prepare_failure(victim, 3, failed_since=now - 25.0, now=now)
        mon.prepare_failure(victim, 6, failed_since=now - 25.0, now=now)
        mon.propose_pending(now)
        assert mon.osdmap.is_down(victim)

    def test_boot_refused_after_flapping_and_operator_clear(self):
        from ceph_tpu.common.clusterlog import ClusterLog
        mon = self._mon()
        mon.clog = ClusterLog(cct=mon.cct)
        victim, now = 1, 100.0
        for cycle in range(3):
            now += 30.0
            self._flap_once(mon, victim, now)
            booted = mon.osd_boot(victim, now=now + 1.0)
            mon.propose_pending(now + 1.0)
            if cycle < 2:
                assert booted and mon.osdmap.is_up(victim)
        assert not booted                 # third mark-down tripped damping
        assert mon.osdmap.is_down(victim)
        assert victim in mon.markdown.damped
        # repeated boot attempts stay refused, and log only once
        assert not mon.osd_boot(victim, now=now + 2.0)
        lines = [e["message"] for e in mon.clog.dump()
                 if "boot denied" in e["message"]]
        assert len(lines) == 1
        # operator clear -> boot allowed -> marked up, transitions logged
        assert mon.clear_markdown(victim)
        assert mon.osd_boot(victim, now=now + 3.0)
        mon.propose_pending(now + 3.0)
        assert mon.osdmap.is_up(victim)
        msgs = [e["message"] for e in mon.clog.dump()]
        assert any("marked down" in m for m in msgs)
        assert any("marked up" in m for m in msgs)
        assert any("flapping" in m for m in msgs)
        assert any("cleared by operator" in m for m in msgs)

    def test_heartbeat_reply_boots_downed_peer_with_damping(self):
        """The heartbeat hole: a post-grace reply used to re-mark the
        OSD up unconditionally.  Now the boot routes through the
        limiter: the flapping victim STAYS down."""
        from ceph_tpu.mon.heartbeat import (VirtualClock,
                                            build_heartbeat_mesh)
        mon = self._mon(osd_markdown_count=2, osd_heartbeat_grace=20)
        clock = VirtualClock()
        agents = build_heartbeat_mesh(mon, clock, 9)
        net = agents[0].network
        victim = 4

        def tick():
            clock.advance(6)
            for o, a in agents.items():
                if net.get(o) is not None:
                    a.tick()
            mon.tick(clock.now())

        def kill_until_down():
            net[victim] = None
            for _ in range(8):
                tick()
                if mon.osdmap.is_down(victim):
                    return
            raise AssertionError("victim never marked down")

        for _ in range(3):
            tick()                       # baselines
        # flap cycle 1: die -> down -> revive -> heartbeat boots it up
        kill_until_down()
        net[victim] = agents[victim]
        tick()
        tick()
        assert mon.osdmap.is_up(victim), \
            "heartbeat reply did not boot the revived peer"
        # flap cycle 2: second mark-down trips damping (count=2); the
        # revived peer keeps replying but STAYS down
        kill_until_down()
        net[victim] = agents[victim]
        for _ in range(4):
            tick()
        assert mon.osdmap.is_down(victim), \
            "flapping OSD was re-marked up without damping"
        assert victim in mon.markdown.damped
        # operator clear: the next reply boots it
        mon.clear_markdown(victim)
        tick()
        tick()
        assert mon.osdmap.is_up(victim)

    def test_osd_flapping_health_check(self):
        from ceph_tpu.mgr.health import osd_flapping_check
        mon = self._mon()
        check = osd_flapping_check(lambda: mon.markdown)
        assert check() is None
        now = 100.0
        for _ in range(3):
            now += 30.0
            self._flap_once(mon, 2, now)
            mon.osd_boot(2, now=now + 1.0)
            mon.propose_pending(now + 1.0)
        res = check()
        assert res is not None and "flapping" in res.summary
        mon.clear_markdown(2)
        assert check() is None


class TestRearmAndDisarm:
    def test_rearm_rebinds_store_plane_to_new_injector(self):
        """inject_faults(planB) while planA is armed must swap the store
        wrappers onto planB's injector (stale wrappers kept rolling the
        OLD plan) and release planA's perf collection first."""
        from ceph_tpu.cluster import MiniCluster
        cct = Context()
        c = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                        cct=cct)
        try:
            pid = c.create_ec_pool(
                "p", {"k": "2", "m": "1", "device": "numpy",
                      "technique": "reed_sol_van"}, pg_num=2)
            inj_a = c.inject_faults(FaultPlan(
                seed=1, store=StoreFaults(eio_read_prob=1.0)))
            inj_b = c.inject_faults(FaultPlan(seed=2))   # store clean
            assert c.fault_injector is inj_b
            c.put(pid, "o", _data(1024))
            assert c.get(pid, "o", 1024) == _data(1024)  # no EIO rolls
            assert inj_b.summary()["planes"].get("store") is None
            assert inj_a.perf is None                    # closed
        finally:
            c.shutdown()

    def test_server_disarm_applies_to_live_connections(self, tmp_path):
        """ClusterServer.inject_faults(None) mid-run must stop send-
        plane faults on ALREADY-authenticated connections (the hooks
        are a provider, not a per-connection snapshot)."""
        plan = FaultPlan(seed=3, transport=TransportFaults(
            reset_prob=1.0))
        c, server, inj, cct = _served_cluster(tmp_path, plan,
                                              ms_rpc_retry_attempts=2,
                                              ms_rpc_timeout=2.0)
        try:
            from ceph_tpu.net import TcpRados
            r = TcpRados("127.0.0.1", server.port,
                         tmp_path / "client.admin.keyring", cct=cct)
            server.inject_faults(None)
            r.mkpool("p", profile={"k": "2", "m": "1",
                                   "device": "numpy",
                                   "technique": "reed_sol_van"})
            r.put("p", "o", _data(512))
            assert r.get("p", "o") == _data(512)
            assert r.reconnects == 0     # disarm reached the live conn
            r.close()
        finally:
            server.stop()
            c.shutdown()

    def test_quorum_clear_markdown_clears_every_replica(self):
        """Mark-downs replicate to every quorum member's limiter via
        apply_committed; the operator clear must too, or a leader
        failover resurrects the damping."""
        from ceph_tpu.crush import CRUSH_BUCKET_STRAW2, CrushMap
        from ceph_tpu.mon import MonCluster
        from ceph_tpu.osdmap import OSDMap
        cmap = CrushMap()
        cmap.set_type_name(1, "host")
        cmap.set_type_name(2, "root")
        hb = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 1, [0, 1, 2],
                             [0x10000] * 3)
        cmap.set_item_name(hb, "host0")
        root = cmap.add_bucket(CRUSH_BUCKET_STRAW2, 2, [hb], [0x30000])
        cmap.set_item_name(root, "default")
        cmap.finalize()
        m = OSDMap(crush=cmap)
        for o in range(3):
            m.create_osd(o)
        mc = MonCluster(m, n_mons=3, cct=Context())
        for pm in mc.mons:          # what replicated apply_committed does
            for t in (10.0, 20.0, 30.0, 40.0, 50.0):
                pm.service.markdown.record_down(1, t)
            assert not pm.service.markdown.allow_up(1)
        assert mc.clear_markdown(1)
        for pm in mc.mons:
            assert pm.service.markdown.allow_up(1), \
                "a replica kept the damping after the operator clear"


# -- objecter op timeouts feed SLOW_OPS --------------------------------------

class TestObjecterTimeouts:
    def test_parked_op_flags_slow_and_feeds_slow_ops_check(self):
        from ceph_tpu.client.objecter import Objecter
        from ceph_tpu.cluster import MiniCluster
        cluster = MiniCluster(n_osds=6, osds_per_host=2, chunk_size=512,
                              cct=Context())
        obj = None
        try:
            pid = cluster.create_ec_pool(
                "p", {"k": "2", "m": "1", "device": "numpy",
                      "technique": "reed_sol_van"}, pg_num=4)
            obj = Objecter(cluster)
            oid = "stuck"
            g = cluster.pg_group(pid, oid)
            # drop the PG below min_size: the write PARKS (neither acked
            # nor lost) and sits in the objecter's inflight list
            for shard in g.acting[1:]:
                g.bus.mark_down(shard)
            cluster.status()                      # stats sample #1
            tid = obj.operate(pid, oid,
                              __import__("ceph_tpu.osd.osd_ops",
                                         fromlist=["ObjectOperation"])
                              .ObjectOperation().write_full(b"x" * 512),
                              drain=False)
            assert tid in obj.inflight
            flagged = obj.check_op_timeouts(
                now=time.monotonic() + 10_000.0)
            assert flagged == [tid]
            # idempotent: an op is a slow op once
            assert obj.check_op_timeouts(
                now=time.monotonic() + 20_000.0) == []
            assert obj.perf.get("slow_ops") == 1
            # ...and the cluster-level SLOW_OPS check sees the window
            # delta (the objecter collection feeds the same surface the
            # optracker does)
            cluster.status()                      # stats sample #2
            assert "SLOW_OPS" in cluster.health()["checks"]
            # revive the shards: the parked op completes and drains
            for shard in g.acting[1:]:
                g.bus.mark_up(shard)
            cluster.deliver_all()
            assert tid not in obj.inflight
        finally:
            if obj is not None:
                obj.close()
            cluster.shutdown()


# -- the seeded chaos soak (tools/chaos_run.py), twice ------------------------

class TestChaosSoak:
    def test_campaign_deterministic_and_invariants_hold(self):
        import sys
        from pathlib import Path
        tools = str(Path(__file__).resolve().parent.parent / "tools")
        sys.path.insert(0, tools)
        try:
            from chaos_run import run_campaign
        finally:
            sys.path.remove(tools)
        reports = [run_campaign(seed=13, ops=12) for _ in range(2)]
        for rep in reports:
            assert rep["ok"]
            assert rep["verified"] == rep["acked_writes"] > 0
            assert rep["breaker"]["opens"] >= 1
            assert rep["breaker"]["state"] == "closed"
            assert {"OSD_FLAPPING", "DEVICE_DEGRADED"} <= \
                set(rep["health_seen"])
            assert rep["events"]["total"] > 0
        assert reports[0]["event_digest"] == reports[1]["event_digest"], \
            "same seed produced different injected-event logs"
