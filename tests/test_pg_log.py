"""PG log + log-based shard recovery.

Mirrors the reference's PGLog semantics (reference: src/osd/PGLog.{h,cc};
EC log-entry flow described in
doc/dev/osd_internals/erasure_coding/ecbackend.rst:8-26): bounded per-PG
entry window, divergence detection, catch-up of a stale shard by
replaying exactly its missed entries, and backfill only past the log
horizon.  The cost assertions (push counts, zero deep scrubs) are the
point: boot repair must be O(missed writes), not O(all objects).
"""
import numpy as np
import pytest

from ceph_tpu.backend import MessageBus, PGTransaction, make_cluster
from ceph_tpu.backend.ec_backend import RepairState
from ceph_tpu.backend.messages import ECSubWrite, PushOp
from ceph_tpu.osd.pg_log import (OP_DELETE, OP_MODIFY, PGLog, PGLogEntry,
                                 dedup_latest)
from ceph_tpu.plugins.registry import ErasureCodePluginRegistry

K, M = 4, 2
CHUNK = 128
STRIPE = K * CHUNK


@pytest.fixture(scope="module")
def ec_impl():
    return ErasureCodePluginRegistry.instance().factory(
        "jax_rs", "", {"k": str(K), "m": str(M), "device": "numpy",
                       "technique": "reed_sol_van"})


@pytest.fixture()
def cluster(ec_impl):
    return make_cluster(ec_impl, chunk_size=CHUNK)


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


def _write(backend, bus, oid, data):
    backend.submit_transaction(PGTransaction().write(oid, 0, data))
    bus.deliver_all()


def _read(backend, bus, oid, length):
    out = {}
    backend.objects_read_and_reconstruct(
        {oid: [(0, length)]},
        lambda result, errors: out.update(result=result, errors=errors))
    bus.deliver_all()
    assert not out.get("errors"), out["errors"]
    return out["result"][oid][0][2][:length]


class CountingBus(MessageBus):
    """Counts messages by type so tests can assert I/O proportionality."""

    def __init__(self):
        super().__init__()
        self.sent: dict[type, int] = {}

    def send(self, to_shard, msg):
        self.sent[type(msg)] = self.sent.get(type(msg), 0) + 1
        super().send(to_shard, msg)


# -- unit: the log structure -------------------------------------------------

class TestPGLogUnit:
    def test_append_monotonic_and_prior(self):
        log = PGLog()
        a = log.append("a")
        b = log.append("b")
        a2 = log.append("a")
        assert (a.version, b.version, a2.version) == (1, 2, 3)
        assert a.prior_version == 0 and a2.prior_version == 1
        assert log.head == 3 and log.tail == 0

    def test_trim_moves_tail_and_horizon(self):
        log = PGLog(max_entries=3)
        for i in range(6):
            log.append(f"o{i}")
        log.maybe_trim()
        assert log.tail == 3 and log.head == 6
        assert [e.version for e in log.entries] == [4, 5, 6]
        assert log.entries_after(3) is not None
        assert log.entries_after(2) is None          # past the horizon

    def test_catch_up_plans(self):
        log = PGLog(max_entries=10)
        for i in range(5):
            log.append(f"o{i % 2}")                  # o0,o1 alternating
        assert log.catch_up_plan(5) == ("clean", [])
        plan, entries = log.catch_up_plan(2)
        assert plan == "log"
        # versions 3,4,5 touch o0(3), o1(4), o0(5): dedup keeps 4 and 5
        assert [(e.version, e.oid) for e in entries] == [(4, "o1"),
                                                         (5, "o0")]
        log.trim(3)
        assert log.catch_up_plan(2) == ("backfill", [])

    def test_dedup_latest_keeps_final_state(self):
        es = [PGLogEntry(1, "a", OP_MODIFY), PGLogEntry(2, "a", OP_DELETE),
              PGLogEntry(3, "b", OP_MODIFY)]
        assert [(e.oid, e.op) for e in dedup_latest(es)] == [
            ("a", OP_DELETE), ("b", OP_MODIFY)]

    def test_divergent_oids(self):
        log = PGLog()
        for o in ("a", "b"):
            log.append(o)
        # follower entry beyond head, and one disagreeing at version 2
        div, rewind = log.divergent_oids([PGLogEntry(2, "x"),
                                          PGLogEntry(3, "c")])
        assert div == {"x", "c"}
        assert rewind == 1          # last consistent shared version
        div, rewind = log.divergent_oids(list(log.entries))
        assert div == set() and rewind == log.head


# -- integration: log rides the write path -----------------------------------

class TestLogOnWritePath:
    def test_entries_reach_every_shard(self, cluster):
        backend, bus = cluster
        _write(backend, bus, "obj", payload(STRIPE))
        _write(backend, bus, "obj2", payload(STRIPE, 1))
        assert backend.pg_log.head == 2
        for shard in backend.acting:
            h = bus.handlers[shard]
            # the primary's LOCAL shard log is separate from the authority
            # log and advances via self-delivery like any replica's
            log = h.pg_log if shard != backend.whoami else \
                backend.local_shard.pg_log
            assert log.head == 2, f"shard {shard} log behind"
            assert [e.oid for e in log.entries] == ["obj", "obj2"]

    def test_delete_logs_delete(self, cluster):
        backend, bus = cluster
        _write(backend, bus, "obj", payload(STRIPE))
        backend.submit_transaction(PGTransaction().delete("obj"))
        bus.deliver_all()
        assert backend.pg_log.entries[-1].op == OP_DELETE

    def test_down_shard_log_stays_behind(self, cluster):
        backend, bus = cluster
        _write(backend, bus, "obj", payload(STRIPE))
        bus.mark_down(3)
        _write(backend, bus, "obj2", payload(STRIPE, 1))
        assert backend.pg_log.head == 2
        assert bus.handlers[3].pg_log.head == 1


# -- integration: log-based repair -------------------------------------------

def make_counting_cluster(ec_impl):
    from ceph_tpu.backend.ec_backend import ECBackend, OSDShard
    from ceph_tpu.backend import StripeInfo
    bus = CountingBus()
    backend = ECBackend(ec_impl, StripeInfo(K, CHUNK), bus,
                        acting=list(range(K + M)), whoami=0)
    for s in range(1, K + M):
        OSDShard(s, bus)
    return backend, bus


class TestLogRepair:
    def test_clean_shard_repair_is_free(self, ec_impl):
        backend, bus = make_counting_cluster(ec_impl)
        for i in range(5):
            _write(backend, bus, f"o{i}", payload(STRIPE, i))
        before = bus.sent.get(PushOp, 0)
        rop = backend.start_shard_repair(3)
        bus.deliver_all()
        assert rop.state == RepairState.COMPLETE
        assert rop.plan == "clean"
        assert bus.sent.get(PushOp, 0) == before         # zero data moved
        assert backend.perf.get("log_repairs_clean") == 1

    def test_missed_n_writes_replays_exactly_n(self, ec_impl):
        """The VERDICT's acceptance test: a shard missing N writes
        recovers by replaying exactly N entries — push count == N, and
        untouched objects see no I/O."""
        backend, bus = make_counting_cluster(ec_impl)
        for i in range(10):
            _write(backend, bus, f"base{i}", payload(STRIPE, i))
        bus.mark_down(4)
        n_missed = 3
        for i in range(n_missed):
            _write(backend, bus, f"missed{i}", payload(STRIPE, 100 + i))
        bus.mark_up(4)
        pushes_before = bus.sent.get(PushOp, 0)
        reads_before = bus.sent.get(ECSubWrite, 0)
        rop = backend.start_shard_repair(4)
        bus.deliver_all()
        assert rop.state == RepairState.COMPLETE
        assert rop.plan == "log"
        assert rop.objects_repaired == n_missed
        # exactly one push per missed object, all to the stale shard
        assert bus.sent.get(PushOp, 0) - pushes_before == n_missed
        # no client-write traffic was generated (no deletes needed)
        assert bus.sent.get(ECSubWrite, 0) == reads_before
        assert backend.perf.get("log_repair_objects") == n_missed
        # the shard's chunk content is now current
        for i in range(n_missed):
            from ceph_tpu.backend import GObject
            data = bus.handlers[4].store.read(GObject(f"missed{i}", 4))
            assert len(data) == CHUNK
        # and its log matches the primary's
        assert bus.handlers[4].pg_log.head == backend.pg_log.head

    def test_repeated_same_object_writes_replay_once(self, ec_impl):
        backend, bus = make_counting_cluster(ec_impl)
        _write(backend, bus, "obj", payload(STRIPE))
        bus.mark_down(4)
        for i in range(5):                       # 5 writes, ONE object
            _write(backend, bus, "obj", payload(STRIPE, i + 1))
        bus.mark_up(4)
        before = bus.sent.get(PushOp, 0)
        rop = backend.start_shard_repair(4)
        bus.deliver_all()
        assert rop.state == RepairState.COMPLETE
        assert rop.objects_repaired == 1
        assert bus.sent.get(PushOp, 0) - before == 1

    def test_missed_delete_replays_delete(self, ec_impl):
        from ceph_tpu.backend import GObject
        backend, bus = make_counting_cluster(ec_impl)
        _write(backend, bus, "obj", payload(STRIPE))
        bus.mark_down(4)
        backend.submit_transaction(PGTransaction().delete("obj"))
        bus.deliver_all()
        bus.mark_up(4)
        assert GObject("obj", 4) in bus.handlers[4].store.objects
        rop = backend.start_shard_repair(4)
        bus.deliver_all()
        assert rop.state == RepairState.COMPLETE
        assert GObject("obj", 4) not in bus.handlers[4].store.objects

    def test_past_horizon_falls_back_to_backfill(self, ec_impl):
        backend, bus = make_counting_cluster(ec_impl)
        backend.pg_log.max_entries = 4
        for i in range(3):
            _write(backend, bus, f"keep{i}", payload(STRIPE, i))
        bus.mark_down(4)
        for i in range(8):                       # trims past shard's head
            _write(backend, bus, f"new{i}", payload(STRIPE, 50 + i))
        assert backend.pg_log.tail > bus.handlers[4].pg_log.head
        bus.mark_up(4)
        rop = backend.start_shard_repair(4)
        bus.deliver_all()
        assert rop.state == RepairState.COMPLETE
        assert rop.plan == "backfill"
        assert backend.perf.get("shard_backfills") == 1
        # backfill touches every object the primary has (3 + 8 = 11)
        assert rop.objects_repaired == 11
        assert bus.handlers[4].pg_log.head == backend.pg_log.head
        # a second repair is now clean
        rop2 = backend.start_shard_repair(4)
        bus.deliver_all()
        assert rop2.plan == "clean"

    def test_divergent_shard_rewound_to_authority(self, ec_impl):
        from ceph_tpu.backend import GObject
        backend, bus = make_counting_cluster(ec_impl)
        _write(backend, bus, "obj", payload(STRIPE))
        shard = bus.handlers[4]
        # fabricate a write the primary never committed: entry past head
        # plus garbage chunk content (the divergent-op aftermath)
        shard.pg_log.record(PGLogEntry(99, "ghost", OP_MODIFY))
        from ceph_tpu.backend import Transaction
        shard.store.queue_transaction(
            Transaction().write(GObject("ghost", 4), 0, b"x" * CHUNK))
        rop = backend.start_shard_repair(4)
        bus.deliver_all()
        assert rop.state == RepairState.COMPLETE
        # the ghost object is gone, the log matches the authority
        assert GObject("ghost", 4) not in shard.store.objects
        assert shard.pg_log.head == backend.pg_log.head
        assert [e.oid for e in shard.pg_log.entries] == \
               [e.oid for e in backend.pg_log.entries]

    def test_revived_primary_repairs_its_own_store(self, ec_impl):
        """The primary's local shard goes stale while it is down (writes
        commit on the other shards); its local log lags the authority log,
        and start_shard_repair(whoami) replays the misses onto itself."""
        from ceph_tpu.backend import GObject
        backend, bus = make_counting_cluster(ec_impl)
        _write(backend, bus, "pre", payload(STRIPE))
        bus.mark_down(0)                     # the primary's own shard
        _write(backend, bus, "missed", payload(STRIPE, 7))
        assert backend.local_shard.pg_log.head == 1 < backend.pg_log.head
        assert GObject("missed", 0) not in backend.local_shard.store.objects
        bus.mark_up(0)
        rop = backend.start_shard_repair(0)
        bus.deliver_all()
        assert rop.state == RepairState.COMPLETE
        assert rop.plan == "log" and rop.objects_repaired == 1
        assert GObject("missed", 0) in backend.local_shard.store.objects
        assert backend.local_shard.pg_log.head == backend.pg_log.head
        # healthy-path read now uses the repaired primary chunk
        assert _read(backend, bus, "missed", STRIPE) == payload(STRIPE, 7)

    def test_revived_primary_backfills_past_horizon(self, ec_impl):
        from ceph_tpu.backend import GObject
        backend, bus = make_counting_cluster(ec_impl)
        backend.pg_log.max_entries = 3
        _write(backend, bus, "pre", payload(STRIPE))
        bus.mark_down(0)
        for i in range(6):
            _write(backend, bus, f"n{i}", payload(STRIPE, i))
        bus.mark_up(0)
        rop = backend.start_shard_repair(0)
        bus.deliver_all()
        assert rop.state == RepairState.COMPLETE
        assert rop.plan == "backfill"
        for i in range(6):
            assert GObject(f"n{i}", 0) in backend.local_shard.store.objects

    def test_repair_survives_interleaved_writes(self, ec_impl):
        """Writes landing between query and completion do not corrupt the
        repair; a follow-up repair converges."""
        backend, bus = make_counting_cluster(ec_impl)
        for i in range(4):
            _write(backend, bus, f"o{i}", payload(STRIPE, i))
        bus.mark_down(4)
        _write(backend, bus, "missed", payload(STRIPE, 9))
        bus.mark_up(4)
        rop = backend.start_shard_repair(4)
        # new write while the repair query is still queued
        backend.submit_transaction(
            PGTransaction().write("concurrent", 0, payload(STRIPE, 10)))
        bus.deliver_all()
        assert rop.state == RepairState.COMPLETE
        rop2 = backend.start_shard_repair(4)
        bus.deliver_all()
        assert rop2.state == RepairState.COMPLETE
        assert bus.handlers[4].pg_log.head == backend.pg_log.head


# -- cluster-level: boot repair is log-driven --------------------------------

class TestClusterBootRepair:
    def test_boot_repair_cost_is_o_missed_writes(self):
        """MiniCluster revival path: objects written while a shard was
        down are repaired by log replay; the prior objects see no
        recovery I/O and no deep scrubs."""
        from ceph_tpu.cluster import MiniCluster
        mc = MiniCluster(n_osds=12, osds_per_host=3, chunk_size=CHUNK)
        pid = mc.create_ec_pool("p", {"plugin": "jax_rs", "k": "4",
                                      "m": "2", "device": "numpy"},
                                pg_num=1)
        mon = mc.attach_monitor()
        for i in range(6):
            mc.put(pid, f"pre{i}", payload(2 * STRIPE, i))
        g = mc.pools[pid]["pgs"][0]
        victim = next(s for s in g.acting if s != g.backend.whoami)
        # quorum of reporters ages past grace -> down-mark commits
        reporters = [o for o in range(12) if o != victim][:4]
        for r in reporters:
            mon.prepare_failure(victim, r, failed_since=0.0, now=30.0)
        assert mon.propose_pending(30.0) is not None
        assert not mc.osdmap.is_up(victim)
        missed = ["pre0", "pre1"]
        for o in missed:
            mc.put(pid, o, payload(2 * STRIPE, 42))
        scrubs = 0
        orig = g.backend.be_deep_scrub

        def counting_scrub(oid):
            nonlocal scrubs
            scrubs += 1
            return orig(oid)
        g.backend.be_deep_scrub = counting_scrub
        mon.osd_boot(victim)
        assert mon.propose_pending(31.0) is not None
        assert g.backend.perf.get("log_repair_objects") == len(missed)
        assert scrubs == 0, "boot repair fell back to deep scrubbing"
        for i in range(6):
            want = payload(2 * STRIPE, 42 if f"pre{i}" in missed else i)
            assert mc.get(pid, f"pre{i}", 2 * STRIPE) == want
