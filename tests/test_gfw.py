"""GF(2^16)/GF(2^32) wide-word codes (jerasure w in {16, 32}).

The reference accepts w in {8, 16, 32} for the scalar jerasure
techniques (ErasureCodeJerasure.cc:191-197); these tests pin the wide
fields' arithmetic, the MDS property of the constructions, and the
plugin path that runs them as GF(2) bitmatrices over w packets.
"""
import itertools

import numpy as np
import pytest

from ceph_tpu.gf import bitmatrix as bm
from ceph_tpu.gf.gfw import GFW
from ceph_tpu.plugins import ErasureCodePluginRegistry


@pytest.fixture
def registry():
    return ErasureCodePluginRegistry()


@pytest.mark.parametrize("w", [16, 32])
class TestFieldArithmetic:
    def test_field_axioms(self, w):
        gf = GFW(w)
        rng = np.random.default_rng(w)
        xs = [int(x) for x in rng.integers(1, 1 << min(w, 31), 20)]
        for a in xs[:5]:
            assert gf.mul(a, 1) == a
            assert gf.mul(a, 0) == 0
            assert gf.mul(a, gf.inv(a)) == 1
        for a, b in zip(xs[:8], xs[8:16]):
            assert gf.mul(a, b) == gf.mul(b, a)
        a, b, c = xs[0], xs[1], xs[2]
        assert gf.mul(a, gf.mul(b, c)) == gf.mul(gf.mul(a, b), c)
        assert gf.mul(a, b ^ c) == gf.mul(a, b) ^ gf.mul(a, c)

    def test_generator_order(self, w):
        gf = GFW(w)
        # x generates the multiplicative group: x^(2^w-1) == 1, and for
        # a primitive poly no smaller power of a few sampled divisors is 1
        assert gf.pow(2, (1 << w) - 1) == 1
        assert gf.pow(2, 1) == 2

    def test_mul_bitmatrix_matches_mul(self, w):
        gf = GFW(w)
        rng = np.random.default_rng(w + 1)
        for _ in range(5):
            a = int(rng.integers(1, 1 << min(w, 31)))
            d = int(rng.integers(1, 1 << min(w, 31)))
            M = gf.mul_bitmatrix(a)
            bits = np.array([(d >> i) & 1 for i in range(w)], dtype=np.uint8)
            out_bits = (M.astype(np.int64) @ bits) % 2
            got = sum(int(b) << i for i, b in enumerate(out_bits))
            assert got == gf.mul(a, d)


@pytest.mark.parametrize("w", [16, 32])
@pytest.mark.parametrize("technique", ["reed_sol_van", "cauchy"])
def test_constructions_are_mds(w, technique):
    """Every erasure pattern of size <= m decodes: the generator rows of
    any k survivors are invertible over GF(2) after bit expansion."""
    gf = GFW(w)
    k, m = 4, 2
    mat = gf.vandermonde(k, m) if technique == "reed_sol_van" \
        else gf.cauchy(k, m)
    coding = gf.expand_bitmatrix(mat)
    ps = 4
    rng = np.random.default_rng(3)
    data = rng.integers(0, 256, (k, w * ps), dtype=np.uint8)
    packets = bm.to_packets(data, w, ps)
    parity = bm.from_packets(bm.xor_apply_host(coding, packets), w, ps)
    chunks = np.concatenate([data, parity], axis=0)
    n = k + m
    pats = [(e,) for e in range(n)] + \
        list(itertools.combinations(range(n), 2))
    for erasures in pats:
        avail = [i for i in range(n) if i not in erasures]
        D, src = bm.decode_bitmatrix(coding, k, w, list(erasures), avail)
        rec = bm.from_packets(
            bm.xor_apply_host(D, bm.to_packets(chunks[src], w, ps)), w, ps)
        for row, e in enumerate(sorted(erasures)):
            assert np.array_equal(rec[row], chunks[e]), (erasures, e)


@pytest.mark.parametrize("w", ["16", "32"])
def test_plugin_wide_roundtrip(registry, w):
    ec = registry.factory("jerasure", "",
                          {"technique": "reed_sol_van", "k": "4", "m": "3",
                           "w": w, "packetsize": "8", "device": "numpy"})
    assert ec.get_chunk_count() == 7
    data = np.random.default_rng(9).integers(
        0, 256, 40000, dtype=np.uint8).tobytes()
    encoded = ec.encode(set(range(7)), data)
    assert len(encoded[0]) % (int(w) * 8) == 0     # packet-group aligned
    avail = {i: encoded[i] for i in range(7) if i not in (0, 2, 6)}
    assert ec.decode_concat(avail)[:40000] == data


def test_plugin_wide_cauchy_and_w8_still_byte_codec(registry):
    wide = registry.factory("jerasure", "",
                            {"technique": "cauchy_good", "k": "3",
                             "m": "2", "w": "16", "packetsize": "4",
                             "device": "numpy"})
    data = np.random.default_rng(10).integers(
        0, 256, 9000, dtype=np.uint8).tobytes()
    enc = wide.encode(set(range(5)), data)
    avail = {i: enc[i] for i in (1, 2, 4)}
    assert wide.decode_concat(avail)[:9000] == data
    # w=8 keeps the byte-codec fast path (RSCodec, not bitmatrix)
    from ceph_tpu.plugins.plugin_jerasure import ErasureCodeJerasureCompat
    w8 = registry.factory("jerasure", "", {"technique": "reed_sol_van",
                                           "k": "4", "m": "2",
                                           "device": "numpy"})
    assert isinstance(w8, ErasureCodeJerasureCompat)


def test_plugin_rejects_unsupported_w(registry):
    with pytest.raises(ValueError):
        registry.factory("jerasure", "", {"technique": "reed_sol_van",
                                          "k": "4", "m": "2", "w": "12"})


def test_plugin_wide_r6(registry):
    """reed_sol_r6_op at w=16 is a reference-valid profile (m forced 2)."""
    ec = registry.factory("jerasure", "",
                          {"technique": "reed_sol_r6_op", "k": "4",
                           "m": "5", "w": "16", "packetsize": "8",
                           "device": "numpy"})
    assert ec.get_coding_chunk_count() == 2
    data = np.random.default_rng(11).integers(
        0, 256, 20000, dtype=np.uint8).tobytes()
    enc = ec.encode(set(range(6)), data)
    avail = {i: enc[i] for i in range(6) if i not in (1, 5)}
    assert ec.decode_concat(avail)[:20000] == data


def test_wide_codec_as_pool_codec():
    """A w=16 jerasure profile drives a full MiniCluster pool: the
    bitmatrix codec runs under the EC backend's stripe pipeline,
    degraded reads reconstruct, snapshots COW — the whole stack over
    the wide field."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.osd.osd_ops import ObjectOperation
    c = MiniCluster(n_osds=9, osds_per_host=3, chunk_size=512)
    pid = c.create_ec_pool("wide", {"plugin": "jerasure",
                                    "technique": "reed_sol_van",
                                    "k": "2", "m": "1", "w": "16",
                                    "packetsize": "8", "device": "numpy"},
                           pg_num=4)
    payload = np.random.default_rng(20).integers(
        0, 256, 5000, np.uint8).tobytes()
    c.operate(pid, "obj", ObjectOperation().write_full(payload))
    s1 = c.create_pool_snap(pid, "s")
    c.operate(pid, "obj", ObjectOperation().write_full(b"new" * 200))
    g = c.pg_group(pid, "obj")
    victim = next(o for o in g.acting if o != g.backend.whoami)
    g.bus.mark_down(victim)
    r = c.operate(pid, "obj", ObjectOperation().read(0, 0), snapid=s1)
    assert r.outdata(0)[:5000] == payload      # degraded wide snap read
    g.bus.mark_up(victim)
    g.bus.deliver_all()
    assert c.scrub_pool(pid) == {}
    c.shutdown()
