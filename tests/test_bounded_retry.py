"""Guard: every retry/reconnect loop in ``net.py``, ``client/`` and
``failure/`` carries a bounded attempt count or deadline.

Thin wrapper over the ``bounded-retry`` rule in
:mod:`ceph_tpu.analysis.rules_guards` (ISSUE 15); semantics unchanged:
a constant-true ``while`` that swallows a retryable exception with no
bounded-budget name in sight is the live-lock shape.
"""
import ceph_tpu.analysis as A


def test_scanned_files_exist():
    idx = A.default_index()
    assert idx.iter_modules(("ceph_tpu/net.py",))
    assert idx.iter_modules(("ceph_tpu/failure",)), \
        "failure/ package missing from the scan set"


def test_every_retry_loop_is_bounded():
    offenders = [f.render() for f in A.run_rules(
        A.default_index(), ("bounded-retry",))]
    assert not offenders, (
        "unbounded retry loops in the self-healing layer:\n"
        + "\n".join(offenders))


def test_guard_catches_the_documented_shapes():
    """Flag the classic unbounded-retry shape; pass the bounded and
    event-loop shapes."""
    bad = ("import time\n"
           "def forever(sock):\n"
           "    while True:\n"
           "        try:\n"
           "            sock.connect()\n"
           "            break\n"
           "        except ConnectionError:\n"
           "            time.sleep(1)\n")
    assert len(A.run_rule_on_sources("bounded-retry",
                                     {"bad.py": bad})) == 1
    ok = ("def bounded(sock, max_attempts):\n"
          "    for attempt in range(max_attempts):\n"
          "        try:\n"
          "            return sock.connect()\n"
          "        except ConnectionError:\n"
          "            pass\n"
          "    raise ConnectionError\n"
          "def reader(ch):\n"
          "    while True:\n"
          "        msg = ch.recv()\n"     # failures propagate out: fine
          "        handle(msg)\n"
          "def deadline_loop(clock, deadline):\n"
          "    while True:\n"
          "        try:\n"
          "            return poll()\n"
          "        except TimeoutError:\n"
          "            if clock() >= deadline:\n"
          "                raise\n")
    assert A.run_rule_on_sources("bounded-retry", {"ok.py": ok}) == []
