"""Guard: every retry/reconnect loop in ``net.py``, ``client/`` and
``failure/`` carries a bounded attempt count or deadline.

Sibling of ``test_no_unbounded_queue.py``: the self-healing layer
(ISSUE 9) retries by design — reconnect-with-backoff, resend-on-reset,
half-open probes — and an UNBOUNDED retry loop smuggled into it turns a
dead server into a live-locked client spinning forever.  The discipline:
retry loops are ``for`` loops over a bounded schedule
(``range(attempts)``, ``ExponentialBackoff.delays()``), never bare
``while True`` spins that swallow connection errors.

What the scan flags (by AST, so multiline code and aliases are caught):
a ``while`` loop with a CONSTANT-TRUE test whose body contains a
``try``/``except`` handler that CATCHES a retryable exception
(ConnectionError / OSError / TimeoutError / socket.timeout / Exception)
and SWALLOWS it (no ``raise``/``return`` in the handler — the
retry-and-go-around shape), while nothing in the loop references a
bounded-budget name (attempt/deadline/retries/tries/remaining/max/
budget).  Event loops (reader threads, accept loops) pass: they either
have a real loop condition or let failures propagate out of the loop.
"""
import ast
import re
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
SCAN = [ROOT / "ceph_tpu" / "net.py",
        *sorted((ROOT / "ceph_tpu" / "client").rglob("*.py")),
        *sorted((ROOT / "ceph_tpu" / "failure").rglob("*.py"))]

_RETRYABLE = {"ConnectionError", "OSError", "TimeoutError",
              "ConnectionResetError", "BrokenPipeError", "timeout",
              "Exception", "BaseException", "IOError", "error"}

_BOUND_NAME = re.compile(
    r"attempt|deadline|retries|tries|remaining|max|budget|stop",
    re.IGNORECASE)


def _const_true(test: ast.expr) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def _handler_names(handler: ast.ExceptHandler) -> set[str]:
    t = handler.type
    if t is None:
        return {"BaseException"}
    parts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = set()
    for p in parts:
        if isinstance(p, ast.Name):
            out.add(p.id)
        elif isinstance(p, ast.Attribute):
            out.add(p.attr)
    return out


def _walk_same_scope(node):
    """ast.walk, but WITHOUT descending into nested function/class
    definitions: an except handler inside a callback defined in the loop
    body is that callback's control flow, not the loop's go-around."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        sub = stack.pop()
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda, ast.ClassDef)):
            continue
        yield sub
        stack.extend(ast.iter_child_nodes(sub))


def _swallows_retryable(node: ast.While) -> bool:
    """True when the loop body contains an except handler that catches a
    retryable exception and neither raises nor returns (the go-around)."""
    for sub in _walk_same_scope(node):
        if not isinstance(sub, ast.Try):
            continue
        for h in sub.handlers:
            if not (_handler_names(h) & _RETRYABLE):
                continue
            if not any(isinstance(n, (ast.Raise, ast.Return))
                       for body in h.body for n in ast.walk(body)):
                return True
    return False


def _has_bound_reference(node: ast.While) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and _BOUND_NAME.search(sub.id):
            return True
        if isinstance(sub, ast.Attribute) and \
                _BOUND_NAME.search(sub.attr):
            return True
    return False


def _scan(path: Path, rel: str) -> list[str]:
    tree = ast.parse(path.read_text(), filename=str(path))
    offenders = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.While):
            continue
        if not _const_true(node.test):
            continue
        if _swallows_retryable(node) and not _has_bound_reference(node):
            offenders.append(
                f"{rel}:{node.lineno}: unbounded 'while True' retry "
                f"loop swallowing connection errors — bound it with an "
                f"attempt count or deadline "
                f"(failure/backoff.ExponentialBackoff)")
    return offenders


def test_scanned_files_exist():
    assert SCAN and all(p.exists() for p in SCAN), \
        "scan targets vanished — update or remove this guard"
    assert any("failure" in str(p) for p in SCAN), \
        "failure/ package missing from the scan set"


def test_every_retry_loop_is_bounded():
    offenders = []
    for path in SCAN:
        offenders.extend(_scan(path, path.relative_to(ROOT).as_posix()))
    assert not offenders, (
        "unbounded retry loops in the self-healing layer:\n"
        + "\n".join(offenders))


def test_guard_catches_the_documented_shapes(tmp_path):
    """The guard must flag the classic unbounded-retry shape and pass
    the bounded and event-loop shapes."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import time\n"
        "def forever(sock):\n"
        "    while True:\n"
        "        try:\n"
        "            sock.connect()\n"
        "            break\n"
        "        except ConnectionError:\n"
        "            time.sleep(1)\n")
    assert len(_scan(bad, "bad.py")) == 1
    ok = tmp_path / "ok.py"
    ok.write_text(
        "def bounded(sock, max_attempts):\n"
        "    for attempt in range(max_attempts):\n"
        "        try:\n"
        "            return sock.connect()\n"
        "        except ConnectionError:\n"
        "            pass\n"
        "    raise ConnectionError\n"
        "def reader(ch):\n"
        "    while True:\n"
        "        msg = ch.recv()\n"     # failures propagate out: fine
        "        handle(msg)\n"
        "def deadline_loop(clock, deadline):\n"
        "    while True:\n"
        "        try:\n"
        "            return poll()\n"
        "        except TimeoutError:\n"
        "            if clock() >= deadline:\n"
        "                raise\n")
    assert _scan(ok, "ok.py") == []
