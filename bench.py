"""Driver benchmark: north-star metric as ONE JSON line.

Metric (BASELINE.json): encode+decode MiB/s at k=8, m=4, 1 MiB stripes.
Measured with device-resident buffers (the sidecar keeps persistent device
buffers; host<->device transfer over the dev tunnel is not representative
of a production PCIe/DMA path and is reported separately on stderr).

vs_baseline: ratio against the in-process CPU reference codec (numpy,
table-based — the stand-in for the reference's CPU plugins; the repository
publishes no absolute ISA numbers, BASELINE.md).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def measure(fn, iters: int = 10, warmup: int = 2) -> float:
    for _ in range(warmup):
        fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def main() -> int:
    import jax
    import jax.numpy as jnp
    from ceph_tpu.ops import RSCodec

    k, m = 8, 4
    stripe_bytes = 1024 * 1024
    n = stripe_bytes // k                      # 128 KiB chunks
    batch = 64                                 # stripes per dispatch
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(k, batch * n), dtype=np.uint8)

    codec = RSCodec(k, m, technique="cauchy", device="jax")
    dev = jax.device_put(jnp.asarray(data))

    # encode: [k, B*N] -> [m, B*N]
    enc_t = measure(lambda: codec.encode_device(dev).block_until_ready())
    enc_mibs = batch * (stripe_bytes / 2**20) / enc_t

    # decode: 2 erasures (1 data + 1 parity), device-resident
    parity = codec.encode_device(dev)
    full = jnp.concatenate([dev, parity], axis=0)
    erasures = [0, 9]
    D, src = codec.decode_matrix(erasures)
    survivors = full[np.asarray(src)]
    dmat = jnp.asarray(D)
    from ceph_tpu.ops import rs_kernels
    dec_t = measure(
        lambda: rs_kernels.gf_apply(dmat, survivors).block_until_ready())
    dec_mibs = batch * (stripe_bytes / 2**20) / dec_t

    combined = 2.0 / (1.0 / enc_mibs + 1.0 / dec_mibs)

    # CPU baseline: same work through the exact numpy codec, 1 stripe
    cpu = RSCodec(k, m, technique="cauchy", device="numpy")
    cdata = data[:, :n]
    cpu_enc_t = measure(lambda: cpu.encode(cdata), iters=3, warmup=1)
    cpu_enc = (stripe_bytes / 2**20) / cpu_enc_t
    cfull = np.concatenate([cdata, cpu.encode(cdata)], axis=0)
    csurv = cfull[src]
    from ceph_tpu.gf import ref
    cpu_dec_t = measure(lambda: ref.apply_matrix(D, csurv), iters=3, warmup=1)
    cpu_dec = (stripe_bytes / 2**20) / cpu_dec_t
    cpu_combined = 2.0 / (1.0 / cpu_enc + 1.0 / cpu_dec)

    print(f"# encode {enc_mibs:.0f} MiB/s, decode {dec_mibs:.0f} MiB/s, "
          f"cpu-ref encode {cpu_enc:.0f} decode {cpu_dec:.0f} MiB/s "
          f"(device={jax.devices()[0].platform})", file=sys.stderr)
    print(json.dumps({
        "metric": "rs_k8m4_1MiB_encode_decode_device_resident",
        "value": round(combined, 1),
        "unit": "MiB/s",
        "vs_baseline": round(combined / cpu_combined, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
